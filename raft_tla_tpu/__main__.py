"""``python -m raft_tla_tpu`` — alias for ``raft_tla_tpu.check``."""

import sys

from raft_tla_tpu.check import main

sys.exit(main())
