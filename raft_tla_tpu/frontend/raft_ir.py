"""Raft transcribed into the action IR — the compiler's first client.

Every family of ``ops/kernels.py`` re-expressed as an
:class:`~raft_tla_tpu.frontend.expr.ActionDef`; compiled through
``frontend/actions.compile_kernels`` and plugged into ``build_step``'s
``family_kernels`` seam, the generated step must be *bit-identical* to
the hand-written one (states, fingerprints, traces — pinned by
tests/test_frontend_ir.py), and ``widthgen.transfer_of`` over the same
defs must reproduce the hand-written speclint twins exactly.  Guard and
update structure below mirrors the kernel bodies line for line — the
``raft.tla`` line references live on the kernels; this file only cites
the kernel each def transcribes.

Parity mode only: the faithful-mode history fields (``vLog``,
``allLogs``, election records) stay on the hand-written kernels —
:func:`family_kernels` refuses history bounds rather than silently
dropping proof-state writes.
"""

from __future__ import annotations

from raft_tla_tpu.analysis import intervals as iv
from raft_tla_tpu.frontend import expr as E
from raft_tla_tpu.frontend import raft_schema as SP

# -- shorthand ---------------------------------------------------------------

I, J, V, SLOT = E.Param("i"), E.Param("j"), E.Param("v"), E.Param("slot")
N, LCAP = E.Dim("n_servers"), E.Dim("log_cap")


def lit(v):
    return E.Lit(v)


def g(field, *idx):
    return E.Get(field, tuple(idx))


def add(a, b):
    return E.Bin("+", a, b)


def sub(a, b):
    return E.Bin("-", a, b)


def eq(a, b):
    return E.Bin("==", a, b)


def ne(a, b):
    return E.Bin("!=", a, b)


def lt(a, b):
    return E.Bin("<", a, b)


def le(a, b):
    return E.Bin("<=", a, b)


def gt(a, b):
    return E.Bin(">", a, b)


def ge(a, b):
    return E.Bin(">=", a, b)


def and_(a, b):
    return E.Bin("and", a, b)


def or_(a, b):
    return E.Bin("or", a, b)


def clip_log(a):
    """clip(a, 0, log_cap-1) — the guarded log-index idiom."""
    return E.Clip(a, lit(0), sub(LCAP, lit(1)))


def _srv(b):
    return iv.Interval(0, max(b.n_servers - 1, 0))


def _val_iv(b):
    return iv.Interval(1, b.n_values)


def _slot_iv(b):
    return iv.Interval(0, max(b.msg_cap - 1, 0))


_IJ = (("i", _srv), ("j", _srv))
_I = (("i", _srv),)
_SLOT = (("slot", _slot_iv),)

# -- local actions -----------------------------------------------------------

# kernels.k_restart
RESTART = E.ActionDef(SP.RESTART, ("i",), lit(True), (E.Branch(updates=(
    E.Set1("role", I, lit(SP.FOLLOWER)),
    E.Set1("vResp", I, lit(0)),
    E.Set1("vGrant", I, lit(0)),
    E.SetRow("nextIndex", I, lit(1)),
    E.SetRow("matchIndex", I, lit(0)),
    E.Set1("commitIndex", I, lit(0)),
)),), param_iv=_I)

# kernels.k_timeout
TIMEOUT = E.ActionDef(
    SP.TIMEOUT, ("i",),
    or_(eq(g("role", I), lit(SP.FOLLOWER)),
        eq(g("role", I), lit(SP.CANDIDATE))),
    (E.Branch(updates=(
        E.Set1("role", I, lit(SP.CANDIDATE)),
        E.Set1("term", I, add(g("term", I), lit(1))),
        E.Set1("votedFor", I, lit(SP.NIL)),
        E.Set1("vResp", I, lit(0)),
        E.Set1("vGrant", I, lit(0)),
    )),), param_iv=_I)

# kernels.k_request_vote
REQUESTVOTE = E.ActionDef(
    SP.REQUESTVOTE, ("i", "j"),
    and_(eq(g("role", I), lit(SP.CANDIDATE)),
         eq(E.Bin("band", E.Bin(">>", g("vResp", I), J), lit(1)), lit(0))),
    (E.Branch(ops=(E.BagAdd(E.PackMsg(SP.M_RVREQ, (
        ("mterm", g("term", I)),
        ("a", E.LastTerm(I)),
        ("b", g("logLen", I)),
        ("src", I),
        ("dst", J),
    ))),)),), param_iv=_IJ)

# kernels.k_become_leader
BECOMELEADER = E.ActionDef(
    SP.BECOMELEADER, ("i",),
    and_(eq(g("role", I), lit(SP.CANDIDATE)),
         gt(E.Bin("*", lit(2), E.Popcount(g("vGrant", I))), N)),
    (E.Branch(updates=(
        E.Set1("role", I, lit(SP.LEADER)),
        E.SetRow("nextIndex", I, add(g("logLen", I), lit(1))),
        E.SetRow("matchIndex", I, lit(0)),
    )),), param_iv=_I)

# kernels.k_client_request
CLIENTREQUEST = E.ActionDef(
    SP.CLIENTREQUEST, ("i", "v"),
    eq(g("role", I), lit(SP.LEADER)),
    (E.Branch(updates=(
        E.Set2("logTerm", I, g("logLen", I), g("term", I)),
        E.Set2("logVal", I, g("logLen", I), V),
        E.Set1("logLen", I, add(g("logLen", I), lit(1))),
    ), overflow=ge(g("logLen", I), LCAP)),),
    param_iv=(("i", _srv), ("v", _val_iv)))


def _quorum_commit(bounds, s, params, xp):
    """kernels.k_advance_commit's quorum aggregation, verbatim: the
    largest index a majority matches at the leader's current term."""
    import jax.numpy as jnp
    i = params["i"]
    n, Lcap = bounds.n_servers, s["logTerm"].shape[1]
    idxs = jnp.arange(1, Lcap + 1)
    others = s["matchIndex"][i][None, :] >= idxs[:, None]
    in_set = others | (jnp.arange(n)[None, :] == i)
    agree_cnt = jnp.sum(in_set.astype(jnp.int32), axis=1)
    agree_ok = (2 * agree_cnt > n) & (idxs <= s["logLen"][i])
    max_agree = jnp.max(jnp.where(agree_ok, idxs, 0))
    t_at = s["logTerm"][i, jnp.clip(max_agree - 1, 0, Lcap - 1)]
    return jnp.where((max_agree > 0) & (t_at == s["term"][i]),
                     max_agree, s["commitIndex"][i])


# kernels.k_advance_commit — the quorum-max is an Intrinsic (a scalar
# aggregation over the match matrix, outside the IR's expression
# language) with the hand twin's declared transfer.
ADVANCECOMMIT = E.ActionDef(
    SP.ADVANCECOMMIT, ("i",),
    eq(g("role", I), lit(SP.LEADER)),
    (E.Branch(updates=(E.Set1("commitIndex", I, E.Intrinsic(
        "quorum_commit", _quorum_commit,
        lambda bounds, env: iv.Interval(0, env["logLen"].hi)
        .join(env["commitIndex"]))),)),),
    param_iv=_I)

# kernels.k_append_entries
_NI = g("nextIndex", I, J)
_PREV_IDX = sub(_NI, lit(1))
_LAST_ENTRY = E.MinE(g("logLen", I), _NI)
_HAS_ENT = le(_NI, _LAST_ENTRY)
_EIDX = clip_log(sub(_NI, lit(1)))
APPENDENTRIES = E.ActionDef(
    SP.APPENDENTRIES, ("i", "j"),
    and_(ne(I, J), eq(g("role", I), lit(SP.LEADER))),
    (E.Branch(ops=(E.BagAdd(E.PackMsg(SP.M_AEREQ, (
        ("mterm", g("term", I)),
        ("a", _PREV_IDX),
        ("b", E.Where(gt(_PREV_IDX, lit(0)),
                      g("logTerm", I, clip_log(sub(_PREV_IDX, lit(1)))),
                      lit(0))),
        ("c", _HAS_ENT),
        ("d", E.Where(_HAS_ENT, g("logTerm", I, _EIDX), lit(0))),
        ("e", E.Where(_HAS_ENT, g("logVal", I, _EIDX), lit(0))),
        ("f", E.MinE(g("commitIndex", I), _LAST_ENTRY)),
        ("src", I),
        ("dst", J),
    ), facts=(("a+c", lambda bounds, env, menv:
               (env["nextIndex"] - 1).join(iv.Interval(1, env["logLen"].hi))
               if env["logLen"].hi >= 1 else env["nextIndex"] - 1),))),)),),
    param_iv=_IJ)

# kernels.k_receive — eleven exclusive branches over the slot's message.
_MT, _MTY = E.MsgField("mterm"), E.MsgField("mtype")
_DST, _SRC = E.MsgField("dst"), E.MsgField("src")
_CT = g("term", _DST)
_ROLE_I = g("role", _DST)
_LEN_I = g("logLen", _DST)
_NOT_UPD = le(_MT, _CT)

_LAST_I = E.LastTerm(_DST)
_LOG_OK_RV = or_(gt(E.MsgField("a"), _LAST_I),
                 and_(eq(E.MsgField("a"), _LAST_I),
                      ge(E.MsgField("b"), _LEN_I)))
_GRANT = and_(and_(eq(_MT, _CT), _LOG_OK_RV),
              or_(eq(g("votedFor", _DST), lit(SP.NIL)),
                  eq(g("votedFor", _DST), add(_SRC, lit(1)))))

_AE_PREV = E.MsgField("a")
_AE_NENT = E.MsgField("c")
_LOG_OK_AE = or_(eq(_AE_PREV, lit(0)),
                 and_(and_(gt(_AE_PREV, lit(0)), le(_AE_PREV, _LEN_I)),
                      eq(E.MsgField("b"),
                         g("logTerm", _DST,
                           clip_log(sub(_AE_PREV, lit(1)))))))
_IS_AE = and_(_NOT_UPD, eq(_MTY, lit(SP.M_AEREQ)))
_ACCEPT = and_(and_(_IS_AE, eq(_MT, _CT)),
               and_(eq(_ROLE_I, lit(SP.FOLLOWER)), _LOG_OK_AE))
_INDEX = add(_AE_PREV, lit(1))
_T_AT_INDEX = g("logTerm", _DST, clip_log(sub(_INDEX, lit(1))))
_AE_SUCC = gt(E.MsgField("a"), lit(0))

RECEIVE = E.ActionDef(
    SP.RECEIVE, ("slot",),
    gt(g("msgCount", SLOT), lit(0)),
    (
        # UpdateTerm (any message with a newer term)
        E.Branch(gt(_MT, _CT), updates=(
            E.Set1("term", _DST, _MT),
            E.Set1("role", _DST, lit(SP.FOLLOWER)),
            E.Set1("votedFor", _DST, lit(SP.NIL)),
        )),
        # HandleRequestVoteRequest
        E.Branch(and_(_NOT_UPD, eq(_MTY, lit(SP.M_RVREQ))), updates=(
            E.Set1("votedFor", _DST, add(_SRC, lit(1)), cond=_GRANT),
        ), ops=(E.Reply(E.PackMsg(SP.M_RVRESP, (
            ("mterm", _CT),
            ("a", _GRANT),
            ("src", _DST),
            ("dst", _SRC),
        ))),), mtype=SP.M_RVREQ),
        # DropStaleResponse (RequestVote)
        E.Branch(and_(and_(_NOT_UPD, eq(_MTY, lit(SP.M_RVRESP))),
                      lt(_MT, _CT)),
                 ops=(E.BagRemove(),), mtype=SP.M_RVRESP),
        # HandleRequestVoteResponse
        E.Branch(and_(and_(_NOT_UPD, eq(_MTY, lit(SP.M_RVRESP))),
                      eq(_MT, _CT)), updates=(
            E.Set1("vResp", _DST,
                   E.Bin("bor", g("vResp", _DST),
                         E.Bin("<<", lit(1), _SRC))),
            E.Set1("vGrant", _DST,
                   E.Bin("bor", g("vGrant", _DST),
                         E.Bin("<<", lit(1), _SRC)),
                   cond=gt(E.MsgField("a"), lit(0))),
        ), ops=(E.BagRemove(),), mtype=SP.M_RVRESP),
        # AppendEntries: reject (stale term, or follower with a log
        # mismatch)
        E.Branch(and_(_IS_AE,
                      or_(lt(_MT, _CT),
                          and_(and_(eq(_MT, _CT),
                                    eq(_ROLE_I, lit(SP.FOLLOWER))),
                               E.Not(_LOG_OK_AE)))),
                 ops=(E.Reply(E.PackMsg(SP.M_AERESP, (
                     ("mterm", _CT),
                     ("src", _DST),
                     ("dst", _SRC),
                 ))),), mtype=SP.M_AEREQ),
        # AppendEntries: candidate steps down (message kept)
        E.Branch(and_(and_(_IS_AE, eq(_MT, _CT)),
                      eq(_ROLE_I, lit(SP.CANDIDATE))),
                 updates=(E.Set1("role", _DST, lit(SP.FOLLOWER)),),
                 mtype=SP.M_AEREQ),
        # AppendEntries: done (heartbeat or already-matching entry)
        E.Branch(and_(_ACCEPT,
                      or_(eq(_AE_NENT, lit(0)),
                          and_(ge(_LEN_I, _INDEX),
                               eq(_T_AT_INDEX, E.MsgField("d"))))),
                 updates=(E.Set1("commitIndex", _DST, E.MsgField("f")),),
                 ops=(E.Reply(E.PackMsg(SP.M_AERESP, (
                     ("mterm", _CT),
                     ("a", lit(1)),
                     ("b", add(_AE_PREV, _AE_NENT)),
                     ("src", _DST),
                     ("dst", _SRC),
                 ), overrides=(("b", "a+c"),))),), mtype=SP.M_AEREQ),
        # AppendEntries: conflict — truncate the last entry (msg kept)
        E.Branch(and_(and_(_ACCEPT, gt(_AE_NENT, lit(0))),
                      and_(ge(_LEN_I, _INDEX),
                           ne(_T_AT_INDEX, E.MsgField("d")))),
                 updates=(
                     E.Set2("logTerm", _DST, sub(_LEN_I, lit(1)), lit(0)),
                     E.Set2("logVal", _DST, sub(_LEN_I, lit(1)), lit(0)),
                     E.Set1("logLen", _DST, sub(_LEN_I, lit(1))),
                 ), mtype=SP.M_AEREQ,
                 refines=(("logLen", 1, 1 << 40),)),
        # AppendEntries: append the entry (msg kept)
        E.Branch(and_(and_(_ACCEPT, gt(_AE_NENT, lit(0))),
                      eq(_LEN_I, _AE_PREV)),
                 updates=(
                     E.Set2("logTerm", _DST, _LEN_I, E.MsgField("d")),
                     E.Set2("logVal", _DST, _LEN_I, E.MsgField("e")),
                     E.Set1("logLen", _DST, add(_LEN_I, lit(1))),
                 ), overflow=ge(_LEN_I, LCAP), mtype=SP.M_AEREQ),
        # DropStaleResponse (AppendEntries)
        E.Branch(and_(and_(_NOT_UPD, eq(_MTY, lit(SP.M_AERESP))),
                      lt(_MT, _CT)),
                 ops=(E.BagRemove(),), mtype=SP.M_AERESP),
        # HandleAppendEntriesResponse
        E.Branch(and_(and_(_NOT_UPD, eq(_MTY, lit(SP.M_AERESP))),
                      eq(_MT, _CT)), updates=(
            E.Set2("nextIndex", _DST, _SRC,
                   E.Where(_AE_SUCC, add(E.MsgField("b"), lit(1)),
                           E.MaxE(sub(g("nextIndex", _DST, _SRC), lit(1)),
                                  lit(1)))),
            E.Set2("matchIndex", _DST, _SRC, E.MsgField("b"),
                   cond=_AE_SUCC),
        ), ops=(E.BagRemove(),), mtype=SP.M_AERESP),
    ),
    param_iv=_SLOT, any_guard_valid=True)

# kernels.k_duplicate
DUPLICATE = E.ActionDef(
    SP.DUPLICATE, ("slot",),
    gt(g("msgCount", SLOT), lit(0)),
    (E.Branch(updates=(
        E.Set1("msgCount", SLOT, add(g("msgCount", SLOT), lit(1))),
    )),), param_iv=_SLOT)

# kernels.k_drop
DROP = E.ActionDef(
    SP.DROP, ("slot",),
    gt(g("msgCount", SLOT), lit(0)),
    (E.Branch(ops=(E.BagRemove(),)),), param_iv=_SLOT)

ACTIONS = (RESTART, TIMEOUT, REQUESTVOTE, BECOMELEADER, CLIENTREQUEST,
           ADVANCECOMMIT, APPENDENTRIES, RECEIVE, DUPLICATE, DROP)


def family_kernels(bounds):
    """The IR-compiled kernel table for ``build_step(...,
    family_kernels=)``.  Parity mode only — the faithful history fields
    are hand-written (module docstring)."""
    if bounds.history:
        raise ValueError(
            "the Raft IR transcription covers parity mode only; faithful "
            "(history) bounds keep the hand-written kernels")
    from raft_tla_tpu.frontend.actions import compile_kernels
    return compile_kernels(ACTIONS)


def transfers():
    """Generated speclint Pass-1 twins, ``{family: transfer}`` — the
    drop-in for ``widthcheck.check_widths(transfers=...)``, cross-checked
    against the hand twins by tests/test_frontend_ir.py."""
    from raft_tla_tpu.frontend.widthgen import transfer_of
    return {adef.family: transfer_of(adef) for adef in ACTIONS}
