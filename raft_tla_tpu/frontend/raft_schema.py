"""Raft as frontend data: encodings, action-instance table, state schema.

This is ``models/spec.py``'s content relocated behind the frontend seam
(spec.py re-exports everything, so no import site changes): the integer
encodings for the spec's model values, the static successor fan-out, and
— new here — the Raft *state schema* as a declared
:class:`~raft_tla_tpu.frontend.schema.Schema` instance.  The schema
twin of ``ops/state.STATE_FIELDS`` (field names, order, shapes, declared
value ranges = ``analysis/intervals.envelope``) is what lets the generic
frontend paths (predicate compilation, schema linting) treat Raft like
any other loaded spec.  This module stays a leaf: it imports only
``config`` and ``frontend/schema``, never the kernels.

Encodings
---------
Roles (``CONSTANTS Follower, Candidate, Leader``, ``raft.tla:17``):
0/1/2.  ``Nil`` (``raft.tla:20``) is 0 in ``votedFor``; servers are 1..n
there, and 0..n-1 everywhere else.  Message types (``raft.tla:23-24``)
are 1..4, with 0 meaning "empty slot".

Action families — the ``Next`` disjuncts (``raft.tla:454-463``)
---------------------------------------------------------------
==============  ===========================  ==================
family          TLA action                   instances
==============  ===========================  ==================
RESTART         Restart(i)        :167-175   n
TIMEOUT         Timeout(i)        :178-187   n
REQUESTVOTE     RequestVote(i,j)  :190-199   n*n   (j may = i)
BECOMELEADER    BecomeLeader(i)   :229-243   n
CLIENTREQUEST   ClientRequest(i,v):246-253   n*V
ADVANCECOMMIT   AdvanceCommitIndex(i):259-276  n
APPENDENTRIES   AppendEntries(i,j):204-226   n*(n-1)  (i /= j)
RECEIVE         Receive(m)        :421-436   msg_cap slots
DUPLICATE       DuplicateMessage(m):443-445  msg_cap slots
DROP            DropMessage(m)    :448-450   msg_cap slots
==============  ===========================  ==================

``Receive``/``Duplicate``/``Drop`` quantify over ``DOMAIN messages``
(``raft.tla:461-463``); in the tensor encoding that is "occupied message
slot", and because slots are kept canonically sorted, slot index k
denotes the same message on both the interpreter and kernel sides.

Sub-specs ("model families", BASELINE.md measurement matrix):
``full`` is the whole ``Next``; ``election`` keeps Timeout + RequestVote
+ Receive + BecomeLeader (BASELINE config #2); ``replication`` keeps
ClientRequest + AppendEntries + Receive + AdvanceCommitIndex from a
preset single-leader initial state (BASELINE config #3).
"""

from __future__ import annotations

import dataclasses

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.frontend.schema import Field, Schema

# Roles (raft.tla:17)
FOLLOWER, CANDIDATE, LEADER = 0, 1, 2
ROLE_NAMES = ("Follower", "Candidate", "Leader")

# votedFor: 0 = Nil (raft.tla:20), 1..n = server id + 1
NIL = 0

# Message types (raft.tla:23-24); 0 = empty slot
M_NONE = 0
M_RVREQ = 1   # RequestVoteRequest
M_RVRESP = 2  # RequestVoteResponse
M_AEREQ = 3   # AppendEntriesRequest
M_AERESP = 4  # AppendEntriesResponse
MTYPE_NAMES = ("None", "RequestVoteRequest", "RequestVoteResponse",
               "AppendEntriesRequest", "AppendEntriesResponse")

# Action families, in enumeration order.
RESTART = "Restart"
TIMEOUT = "Timeout"
REQUESTVOTE = "RequestVote"
BECOMELEADER = "BecomeLeader"
CLIENTREQUEST = "ClientRequest"
ADVANCECOMMIT = "AdvanceCommitIndex"
APPENDENTRIES = "AppendEntries"
RECEIVE = "Receive"
DUPLICATE = "DuplicateMessage"
DROP = "DropMessage"

ALL_FAMILIES = (RESTART, TIMEOUT, REQUESTVOTE, BECOMELEADER, CLIENTREQUEST,
                ADVANCECOMMIT, APPENDENTRIES, RECEIVE, DUPLICATE, DROP)

SPECS = {
    # The full Next relation (raft.tla:454-463).
    "full": frozenset(ALL_FAMILIES),
    # Election-only sub-spec (BASELINE config #2).
    "election": frozenset({TIMEOUT, REQUESTVOTE, RECEIVE, BECOMELEADER}),
    # Log-replication sub-spec from a preset leader (BASELINE config #3).
    "replication": frozenset({CLIENTREQUEST, APPENDENTRIES, RECEIVE,
                              ADVANCECOMMIT}),
}

# The parity-mode state schema — ops/state.STATE_FIELDS as a frontend
# declaration: same field order, same resolved shapes, value ranges from
# the claimed inductive envelope (analysis/intervals.envelope; the
# packed msgHi/msgLo words are checked per-subfield there, so the whole-
# word ranges here are the packed spans).  tests assert layout/width
# agreement with ops/state.Layout so the twin cannot drift.
RAFT_SCHEMA = Schema("raft", (
    Field("role", ("n",), 0, 2),
    Field("term", ("n",), 1, "term_cap", init=1),
    Field("votedFor", ("n",), 0, "n_servers"),
    Field("commitIndex", ("n",), 0, "log_cap"),
    Field("logLen", ("n",), 0, "log_cap"),
    Field("logTerm", ("n", "L"), 0, "term_cap"),
    Field("logVal", ("n", "L"), 0, "n_values"),
    Field("vResp", ("n",), 0, lambda b: (1 << b.n_servers) - 1),
    Field("vGrant", ("n",), 0, lambda b: (1 << b.n_servers) - 1),
    Field("nextIndex", ("n", "n"), 1, lambda b: b.log_cap + 1, init=1),
    Field("matchIndex", ("n", "n"), 0, "log_cap"),
    Field("msgHi", ("S",), 0, lambda b: (1 << 29) - 1),
    Field("msgLo", ("S",), 0,
          lambda b: (1 << (31 if b.history else 17)) - 1),
    Field("msgCount", ("S",), 0, "dup_cap"),
))


@dataclasses.dataclass(frozen=True)
class ActionInstance:
    """One successor lane: a family plus its bound parameters.

    ``i``/``j`` are server ids, ``v`` a value id (1..V), ``slot`` a message
    slot index — mirroring the existential quantifiers of ``raft.tla:454-463``.
    """
    family: str
    i: int = -1
    j: int = -1
    v: int = -1
    slot: int = -1

    def label(self) -> str:
        if self.family == RESTART:
            return f"Restart(s{self.i + 1})"
        if self.family == TIMEOUT:
            return f"Timeout(s{self.i + 1})"
        if self.family == REQUESTVOTE:
            return f"RequestVote(s{self.i + 1}, s{self.j + 1})"
        if self.family == BECOMELEADER:
            return f"BecomeLeader(s{self.i + 1})"
        if self.family == CLIENTREQUEST:
            return f"ClientRequest(s{self.i + 1}, v{self.v})"
        if self.family == ADVANCECOMMIT:
            return f"AdvanceCommitIndex(s{self.i + 1})"
        if self.family == APPENDENTRIES:
            return f"AppendEntries(s{self.i + 1}, s{self.j + 1})"
        return f"{self.family}(slot {self.slot})"


def action_table(bounds: Bounds, spec: str = "full") -> list[ActionInstance]:
    """The static, ordered successor fan-out for one state.

    Enumeration order mirrors the disjunct order of ``Next``
    (``raft.tla:454-463``).  Size A = 4n + n^2 + nV + n(n-1) + 3*msg_cap for
    the full spec.
    """
    fams = SPECS[spec]
    n, V, S = bounds.n_servers, bounds.n_values, bounds.msg_cap
    table: list[ActionInstance] = []
    if RESTART in fams:
        table += [ActionInstance(RESTART, i=i) for i in range(n)]
    if TIMEOUT in fams:
        table += [ActionInstance(TIMEOUT, i=i) for i in range(n)]
    if REQUESTVOTE in fams:
        table += [ActionInstance(REQUESTVOTE, i=i, j=j)
                  for i in range(n) for j in range(n)]
    if BECOMELEADER in fams:
        table += [ActionInstance(BECOMELEADER, i=i) for i in range(n)]
    if CLIENTREQUEST in fams:
        table += [ActionInstance(CLIENTREQUEST, i=i, v=v)
                  for i in range(n) for v in range(1, V + 1)]
    if ADVANCECOMMIT in fams:
        table += [ActionInstance(ADVANCECOMMIT, i=i) for i in range(n)]
    if APPENDENTRIES in fams:
        table += [ActionInstance(APPENDENTRIES, i=i, j=j)
                  for i in range(n) for j in range(n) if i != j]
    if RECEIVE in fams:
        table += [ActionInstance(RECEIVE, slot=s) for s in range(S)]
    if DUPLICATE in fams:
        table += [ActionInstance(DUPLICATE, slot=s) for s in range(S)]
    if DROP in fams:
        table += [ActionInstance(DROP, slot=s) for s in range(S)]
    return table
