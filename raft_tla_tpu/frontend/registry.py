"""``resolve_model(spec)`` — one spec name, one model adapter.

The engines, serve lanes, and CLI never hard-code a protocol; they ask
the registry for a *model adapter* and go through its uniform surface:

- ``layout(bounds)`` / ``action_table(bounds)`` / ``build_step(config)``
  — the compiled step (same fused contract for every model);
- ``init_py`` / ``to_vec`` / ``from_vec`` / ``init_fingerprint`` /
  ``constraint_ok`` / ``py_invariant`` — the host-side half of the BFS
  (roots, trace decoding, frontier invariant probes);
- ``build_sim_expand`` / ``sim_codec`` / ``jnp_invariants`` /
  ``jnp_constraint`` / ``host_apply`` — the simulation surface (present
  when ``"simulate" in engines``): the per-state action fan-out the
  walker engines sample from, the struct<->vec codec, traced invariant /
  constraint probes, and the host interpreter one lane at a time for
  exact violation replay;
- ``render_state`` / ``render_trace`` — violation reporting;
- ``check_widths(bounds)`` — the admission-time width/validity gate;
- ``resolve_check_config(cfg, opts, path)`` — cfg-file -> CheckConfig
  for models that own their cfg mapping (non-Raft specs).

Raft resolves to :class:`RaftModel` (pure delegation to the existing
modules — zero behavior change), with ``ir-full`` / ``ir-election`` /
``ir-replication`` the same model stepped through
``frontend/raft_ir``-compiled kernels instead of the hand-written ones
(pinned bit-identical by tests).  ``twophase`` resolves to the bundled
two-phase-commit spec, compiled entirely from frontend declarations.

Everything heavy imports inside methods: this module sits under
``frontend/__init__`` which ``models/spec.py``'s re-export pulls in, so
module level must stay light to avoid import cycles.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from raft_tla_tpu.config import Bounds, CheckConfig


@dataclasses.dataclass(frozen=True)
class RaftModel:
    """The built-in Raft model; ``sub`` is the Next-subset family table
    name (``full``/``election``/``replication``), ``use_ir`` swaps the
    hand-written family kernels for the IR-compiled ones."""

    name: str
    sub: str
    use_ir: bool = False
    is_raft: bool = True
    engines: tuple = ("device", "host", "ref", "simulate")

    def layout(self, bounds):
        from raft_tla_tpu.ops import state as st
        return st.Layout.of(bounds)

    def action_table(self, bounds):
        from raft_tla_tpu.models import spec as S
        return S.action_table(bounds, self.sub)

    def build_step(self, config: CheckConfig):
        from raft_tla_tpu.ops import kernels
        fk = None
        if self.use_ir:
            from raft_tla_tpu.frontend import raft_ir
            fk = raft_ir.family_kernels(config.bounds)
        return kernels.build_step(
            config.bounds, self.sub, tuple(config.invariants),
            tuple(config.symmetry), view=config.view, family_kernels=fk)

    def init_py(self, bounds):
        from raft_tla_tpu.models import interp
        return interp.init_state(bounds)

    def to_vec(self, py, bounds):
        from raft_tla_tpu.models import interp
        return interp.to_vec(py, bounds)

    def from_vec(self, vec, bounds):
        from raft_tla_tpu.models import interp
        from raft_tla_tpu.ops import state as st
        return interp.from_struct(
            st.unpack(vec, st.Layout.of(bounds), np), bounds)

    def init_fingerprint(self, config, init_py, init_vec):
        from raft_tla_tpu.ops import symmetry as sym_mod
        return sym_mod.init_fingerprint(config, init_py, init_vec)

    def constraint_ok(self, py, bounds) -> bool:
        from raft_tla_tpu.models import interp
        return bool(interp.constraint_ok(py, bounds))

    def py_invariant(self, name):
        from raft_tla_tpu.models import invariants as inv_mod
        return inv_mod.py_invariant(name)

    def render_state(self, py, bounds, indent="    "):
        from raft_tla_tpu.utils import render
        return render.render_state(py, bounds, indent)

    def render_trace(self, violation, bounds):
        from raft_tla_tpu.utils import render
        return render.render_trace(violation, bounds)

    def check_widths(self, bounds):
        from raft_tla_tpu.analysis import widthcheck
        return widthcheck.check_widths(bounds, self.sub)

    # -- simulation surface (walker engines) --------------------------------

    def build_sim_expand(self, config: CheckConfig):
        from raft_tla_tpu.ops import kernels
        fk = None
        if self.use_ir:
            from raft_tla_tpu.frontend import raft_ir
            fk = raft_ir.family_kernels(config.bounds)
        return kernels.build_expand(config.bounds, self.sub,
                                    family_kernels=fk)

    def sim_codec(self, bounds):
        import jax.numpy as jnp
        from raft_tla_tpu.ops import state as st
        lay = st.Layout.of(bounds)
        return (lay.width,
                lambda t: st.pack(t, jnp),
                lambda v: st.unpack(v, lay, jnp))

    def jnp_invariants(self, config: CheckConfig):
        from raft_tla_tpu.models import invariants as inv_mod
        return tuple(inv_mod.jnp_invariant(nm, config.bounds)
                     for nm in config.invariants)

    def jnp_constraint(self, bounds):
        import jax.numpy as jnp
        from raft_tla_tpu.ops import state as st
        return lambda t: st.constraint_ok(t, bounds, jnp)

    def host_apply(self, py, inst, bounds):
        from raft_tla_tpu.models import interp
        return interp.apply_action(py, inst, bounds)


class TwoPhaseModel:
    """Bounded two-phase commit, compiled from frontend declarations
    (``frontend/twophase``): schema layout, IR-built step, predicate
    invariants.  ``bounds.n_servers`` is the RM count; the other bound
    knobs are inert for this state space."""

    name = "twophase"
    sub = "twophase"
    is_raft = False
    use_ir = True
    engines = ("host", "simulate")

    def _mod(self):
        from raft_tla_tpu.frontend import twophase
        return twophase

    def _predicate(self, name: str):
        from raft_tla_tpu.frontend.predicate import (compile_predicate,
                                                     is_expression)
        tp = self._mod()
        text = tp.INVARIANTS.get(name)
        if text is None:
            if not is_expression(name):
                raise ValueError(
                    f"unknown twophase invariant {name!r} (known: "
                    f"{', '.join(sorted(tp.INVARIANTS))}; or write a "
                    "predicate expression over the state fields)")
            text = name
        return compile_predicate(text, fields=tp.SCHEMA.field_names)

    def layout(self, bounds):
        return self._mod().SCHEMA.layout(bounds)

    def action_table(self, bounds):
        return self._mod().action_table(bounds)

    def build_step(self, config: CheckConfig):
        from raft_tla_tpu.frontend import actions
        tp = self._mod()
        preds = tuple(self._predicate(nm) for nm in config.invariants)
        return actions.build_schema_step(
            tp.SCHEMA, tp.ACTIONS, tp.action_table(config.bounds),
            config.bounds, predicates=preds)

    def init_py(self, bounds):
        return self._mod().init_state(bounds)

    def to_vec(self, py, bounds):
        return self._mod().to_vec(py, bounds)

    def from_vec(self, vec, bounds):
        return self._mod().from_vec(vec, bounds)

    def init_fingerprint(self, config, init_py, init_vec):
        # symmetry/view are rejected at config time, so this always takes
        # the generic lane-constants branch — the same fingerprint the
        # compiled schema step computes on device.
        from raft_tla_tpu.ops import symmetry as sym_mod
        return sym_mod.init_fingerprint(config, init_py, init_vec)

    def constraint_ok(self, py, bounds) -> bool:
        return True      # the state space is finite with no constraint

    def py_invariant(self, name):
        tp = self._mod()
        pred = self._predicate(name)

        def check(py, bounds) -> bool:
            lay = tp.SCHEMA.layout(bounds)
            struct = lay.unpack(tp.to_vec(py, bounds), np)
            return bool(pred.ev(struct, np))

        return check

    def render_state(self, py, bounds, indent="    "):
        return self._mod().render_state(py, bounds, indent)

    def render_trace(self, violation, bounds):
        return self._mod().render_trace(violation, bounds)

    def check_widths(self, bounds):
        from raft_tla_tpu.frontend.schema import check_schema
        return check_schema(self._mod().SCHEMA, bounds)

    # -- simulation surface (walker engines) --------------------------------

    def build_sim_expand(self, config: CheckConfig):
        from raft_tla_tpu.frontend import actions
        tp = self._mod()
        return actions.build_schema_expand(
            tp.SCHEMA, tp.ACTIONS, tp.action_table(config.bounds),
            config.bounds)

    def sim_codec(self, bounds):
        import jax.numpy as jnp
        lay = self._mod().SCHEMA.layout(bounds)
        return (lay.width,
                lambda t: lay.pack(t, jnp),
                lambda v: lay.unpack(v, jnp))

    def jnp_invariants(self, config: CheckConfig):
        import jax.numpy as jnp
        preds = tuple(self._predicate(nm) for nm in config.invariants)
        return tuple((lambda t, p=p: p.ev(t, jnp)) for p in preds)

    def jnp_constraint(self, bounds):
        import jax.numpy as jnp
        return lambda t: jnp.bool_(True)   # finite space, no constraint

    def host_apply(self, py, inst, bounds):
        return self._mod().apply_instance(py, inst, bounds)

    def emit_tla(self, out_dir, bounds, invariants=()):
        return self._mod().emit_tla(out_dir, bounds, invariants)

    def resolve_check_config(self, cfg, opts, path=None):
        """TLC cfg -> (CheckConfig, properties) for the twophase model —
        the non-Raft face of ``serve/jobs.resolve_check_config``."""
        tp = self._mod()
        where = path or "cfg"
        if cfg.specification not in (None, "Spec"):
            raise ValueError(
                f"{where}: twophase checks SPECIFICATION Spec only "
                f"(got {cfg.specification!r})")
        if cfg.init not in (None, "Init") or cfg.next not in (None, "Next"):
            raise ValueError(
                f"{where}: twophase supports INIT Init / NEXT Next only")
        if cfg.properties:
            raise ValueError(
                f"{where}: temporal properties are not supported for "
                "twophase")
        if cfg.constraints:
            raise ValueError(
                f"{where}: twophase is finite; CONSTRAINT is not supported")
        if cfg.symmetry or opts.symmetry:
            raise ValueError("symmetry reduction is not supported for "
                             "twophase")
        if cfg.view or opts.view:
            raise ValueError("views are not supported for twophase")
        if opts.faithful:
            raise ValueError("faithful mode (history variables) is "
                             "Raft-specific")
        rms = cfg.constants.get("RM", cfg.constants.get("Server"))
        if not isinstance(rms, list) or not rms:
            raise ValueError(
                f"{where}: twophase needs CONSTANT RM = {{r1, ...}} "
                "(a nonempty finite set)")
        invariants = tuple(cfg.invariants) or (tp.DEFAULT_INVARIANT,)
        for nm in invariants:        # parse/validate now, fail loudly here
            self._predicate(nm)
        bounds = Bounds(n_servers=len(rms), n_values=1)
        config = CheckConfig(
            bounds=bounds, spec="twophase", invariants=invariants,
            symmetry=(), chunk=opts.chunk, check_deadlock=opts.deadlock,
            view=None)
        return config, ()


_RAFT_SUBS = ("full", "election", "replication")


def known_specs() -> tuple:
    return _RAFT_SUBS + tuple(f"ir-{s}" for s in _RAFT_SUBS) + (
        "raft", "twophase")


def resolve_model(spec: str):
    """Spec name -> model adapter.  Unknown names raise with a
    did-you-mean, mirroring the cfg-name diagnostics."""
    if spec in _RAFT_SUBS:
        return RaftModel(spec, spec)
    if spec == "raft":
        return RaftModel("raft", "full")
    if spec.startswith("ir-") and spec[3:] in _RAFT_SUBS:
        return RaftModel(spec, spec[3:], use_ir=True)
    if spec == "twophase":
        return TwoPhaseModel()
    from raft_tla_tpu.utils import cfgparse
    hints = cfgparse.suggest(spec, known_specs())
    hint_txt = f" (did you mean: {', '.join(hints)}?)" if hints else ""
    raise ValueError(
        f"unknown spec {spec!r}{hint_txt}; known: "
        f"{', '.join(sorted(known_specs()))}")
