"""Quantifier-free boolean predicate compiler over a state schema.

Any loaded spec's INVARIANT stanza may name a registered invariant OR
write an expression directly; expressions compile here into the same
dual py/jnp probe shape the hand-written Raft invariants use (a scalar-
bool function of the struct-of-arrays state), so they ride the existing
vmapped invariant stack unchanged.

Grammar (TLA+ ASCII operators, loosest to tightest):

    expr   :=  impl
    impl   :=  or  ("=>" or)*                  -- right-associative
    or     :=  and ("\\/" and)*
    and    :=  not ("/\\" not)*
    not    :=  "~" not | cmp
    cmp    :=  sum (("=" | "/=" | "<=" | ">=" | "<" | ">") sum)?
    sum    :=  term (("+" | "-") term)*
    term   :=  unary ("*" unary)*
    unary  :=  "-" unary | atom
    atom   :=  INT | TRUE | FALSE | NAME | NAME "[" expr "]"
            |  ("any" | "all" | "count" | "min" | "max") "(" expr ")"
            |  "(" expr ")"

NAME reads a schema field elementwise; comparisons and arithmetic
broadcast; a non-scalar boolean result is implicitly universally
quantified (``xp.all``) at the top — the quantifier-free reading of
TLA+'s ``\\A i \\in Server: P(i)``.  ``count`` sums a boolean array.

Everything is statically typed (BOOL vs INT) so malformed invariants
fail at admission with a position-carrying ValueError, never inside a
jit trace.
"""

from __future__ import annotations

import dataclasses
import re

BOOL, INT = "bool", "int"

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<int>\d+)
    | (?P<name>[A-Za-z_]\w*)
    | (?P<op>=>|\\/|/\\|/=|<=|>=|[~=<>+\-*()\[\]])
    )""", re.VERBOSE)

_REDUCERS = ("any", "all", "count", "min", "max")
_CMP = {"=", "/=", "<", "<=", ">", ">="}

_IDENT = re.compile(r"[A-Za-z_]\w*\Z")


def is_expression(text: str) -> bool:
    """A bare identifier is a registered-invariant NAME; anything else
    (operators, brackets, digits-leading, ...) is an expression for this
    compiler.  One definition shared by cfgparse, cfglint, invariants,
    and serve admission so they can never disagree."""
    return _IDENT.match(text.strip()) is None


def _tokenize(text: str):
    toks, pos = [], 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == m.start():
            rest = text[pos:].lstrip()
            if not rest:
                break
            raise ValueError(
                f"predicate syntax error at column {pos + 1}: "
                f"unexpected {rest[:10]!r}")
        if m.lastgroup is not None:
            toks.append((m.lastgroup, m.group(m.lastgroup), m.start()))
        pos = m.end()
    toks.append(("end", "", len(text)))
    return toks


# ---------------------------------------------------------------------------
# AST — each node evaluates against a struct of arrays with xp in
# {numpy, jax.numpy} and reports its static type and field reads.

@dataclasses.dataclass(frozen=True)
class Lit:
    v: int
    kind: str = INT

    def ev(self, struct, xp):
        return self.v

    def reads(self):
        return frozenset()


@dataclasses.dataclass(frozen=True)
class Name:
    field: str
    kind: str = INT

    def ev(self, struct, xp):
        return struct[self.field]

    def reads(self):
        return frozenset((self.field,))


@dataclasses.dataclass(frozen=True)
class Index:
    field: str
    idx: object
    kind: str = INT

    def ev(self, struct, xp):
        return struct[self.field][..., self.idx.ev(struct, xp)]

    def reads(self):
        return frozenset((self.field,)) | self.idx.reads()


@dataclasses.dataclass(frozen=True)
class Neg:
    a: object
    kind: str = INT

    def ev(self, struct, xp):
        return -self.a.ev(struct, xp)

    def reads(self):
        return self.a.reads()


@dataclasses.dataclass(frozen=True)
class Not:
    a: object
    kind: str = BOOL

    def ev(self, struct, xp):
        return xp.logical_not(self.a.ev(struct, xp))

    def reads(self):
        return self.a.reads()


_BIN_EV = {
    "+": lambda a, b, xp: a + b,
    "-": lambda a, b, xp: a - b,
    "*": lambda a, b, xp: a * b,
    "=": lambda a, b, xp: a == b,
    "/=": lambda a, b, xp: a != b,
    "<": lambda a, b, xp: a < b,
    "<=": lambda a, b, xp: a <= b,
    ">": lambda a, b, xp: a > b,
    ">=": lambda a, b, xp: a >= b,
    "/\\": lambda a, b, xp: xp.logical_and(a, b),
    "\\/": lambda a, b, xp: xp.logical_or(a, b),
    "=>": lambda a, b, xp: xp.logical_or(xp.logical_not(a), b),
}


@dataclasses.dataclass(frozen=True)
class Bin:
    op: str
    a: object
    b: object
    kind: str = INT

    def ev(self, struct, xp):
        return _BIN_EV[self.op](self.a.ev(struct, xp),
                                self.b.ev(struct, xp), xp)

    def reads(self):
        return self.a.reads() | self.b.reads()


@dataclasses.dataclass(frozen=True)
class Reduce:
    fn: str
    a: object
    kind: str = INT

    def ev(self, struct, xp):
        v = self.a.ev(struct, xp)
        if self.fn == "any":
            return xp.any(v)
        if self.fn == "all":
            return xp.all(v)
        if self.fn == "count":
            # sum of a boolean array; int32 keeps it on the state dtype
            return xp.sum(xp.asarray(v, dtype="int32"))
        if self.fn == "min":
            return xp.min(v)
        return xp.max(v)

    def reads(self):
        return self.a.reads()


class _Parser:
    def __init__(self, text: str, fields=None):
        self.text = text
        self.toks = _tokenize(text)
        self.i = 0
        self.fields = None if fields is None else tuple(fields)

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def err(self, msg, tok=None):
        tok = tok or self.peek()
        return ValueError(f"predicate syntax error at column "
                          f"{tok[2] + 1}: {msg} (in {self.text!r})")

    def expect(self, op):
        t = self.next()
        if t[0] != "op" or t[1] != op:
            raise self.err(f"expected {op!r}, got {t[1] or 'end'!r}", t)

    def want_bool(self, node, ctx):
        if node.kind != BOOL:
            raise self.err(f"{ctx} needs a boolean operand")
        return node

    def want_int(self, node, ctx):
        if node.kind != INT:
            raise self.err(f"{ctx} needs an integer operand")
        return node

    def parse(self):
        node = self.impl()
        t = self.peek()
        if t[0] != "end":
            raise self.err(f"trailing input {t[1]!r}")
        return node

    def impl(self):
        left = self.or_()
        if self.peek()[:2] == ("op", "=>"):
            self.next()
            right = self.impl()                     # right-associative
            return Bin("=>", self.want_bool(left, "'=>'"),
                       self.want_bool(right, "'=>'"), BOOL)
        return left

    def or_(self):
        node = self.and_()
        while self.peek()[:2] == ("op", "\\/"):
            self.next()
            rhs = self.and_()
            node = Bin("\\/", self.want_bool(node, "'\\/'"),
                       self.want_bool(rhs, "'\\/'"), BOOL)
        return node

    def and_(self):
        node = self.not_()
        while self.peek()[:2] == ("op", "/\\"):
            self.next()
            rhs = self.not_()
            node = Bin("/\\", self.want_bool(node, "'/\\'"),
                       self.want_bool(rhs, "'/\\'"), BOOL)
        return node

    def not_(self):
        if self.peek()[:2] == ("op", "~"):
            self.next()
            return Not(self.want_bool(self.not_(), "'~'"))
        return self.cmp()

    def cmp(self):
        left = self.sum()
        t = self.peek()
        if t[0] == "op" and t[1] in _CMP:
            self.next()
            right = self.sum()
            return Bin(t[1], self.want_int(left, f"{t[1]!r}"),
                       self.want_int(right, f"{t[1]!r}"), BOOL)
        return left

    def sum(self):
        node = self.term()
        while self.peek()[0] == "op" and self.peek()[1] in ("+", "-"):
            op = self.next()[1]
            rhs = self.term()
            node = Bin(op, self.want_int(node, f"{op!r}"),
                       self.want_int(rhs, f"{op!r}"), INT)
        return node

    def term(self):
        node = self.unary()
        while self.peek()[:2] == ("op", "*"):
            self.next()
            rhs = self.unary()
            node = Bin("*", self.want_int(node, "'*'"),
                       self.want_int(rhs, "'*'"), INT)
        return node

    def unary(self):
        if self.peek()[:2] == ("op", "-"):
            self.next()
            return Neg(self.want_int(self.unary(), "unary '-'"))
        return self.atom()

    def atom(self):
        t = self.next()
        if t[0] == "int":
            return Lit(int(t[1]))
        if t[0] == "name":
            name = t[1]
            if name == "TRUE":
                return Lit(True, BOOL)
            if name == "FALSE":
                return Lit(False, BOOL)
            if name in _REDUCERS:
                self.expect("(")
                arg = self.impl()
                self.expect(")")
                if name in ("any", "all"):
                    return Reduce(name, self.want_bool(arg, name), BOOL)
                if name == "count":
                    return Reduce(name, self.want_bool(arg, name), INT)
                return Reduce(name, self.want_int(arg, name), INT)
            if self.fields is not None and name not in self.fields:
                raise self.err(
                    f"unknown field {name!r}; schema fields: "
                    f"{', '.join(self.fields)}", t)
            if self.peek()[:2] == ("op", "["):
                self.next()
                idx = self.sum()
                self.expect("]")
                return Index(name, self.want_int(idx, "index"))
            return Name(name)
        if t[:2] == ("op", "("):
            node = self.impl()
            self.expect(")")
            return node
        raise self.err(f"unexpected {t[1] or 'end of input'!r}", t)


@dataclasses.dataclass(frozen=True)
class Predicate:
    """A compiled predicate: ``ev(struct, xp)`` -> scalar bool (numpy or
    traced jnp), ``reads`` for the vacuity pass, ``text`` for display."""
    text: str
    node: object
    reads: frozenset

    def ev(self, struct, xp):
        v = self.node.ev(struct, xp)
        # implicit universal quantification over any residual axes
        return xp.all(v)


def parse(text: str, fields=None):
    """Parse to an AST; ``fields`` (optional) enables unknown-field
    errors at compile time instead of KeyErrors at probe time."""
    return _Parser(text, fields).parse()


def compile_predicate(text: str, fields=None) -> Predicate:
    node = parse(text, fields)
    if node.kind != BOOL:
        raise ValueError(
            f"predicate {text!r} is arithmetic, not boolean — an "
            "invariant must evaluate to TRUE/FALSE (wrap it in a "
            "comparison)")
    return Predicate(text, node, frozenset(node.reads()))
