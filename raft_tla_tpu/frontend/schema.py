"""Declared tensor state schemas — the frontend's model-independent core.

A :class:`Schema` is the declaration a spec makes about its state: a
tuple of small-int tensor fields with symbolic shapes and value ranges.
Resolving it against a :class:`~raft_tla_tpu.config.Bounds` yields a
:class:`SchemaLayout`, which duck-types ``ops/state.Layout`` (``shapes``
/ ``fields`` / ``width``) and carries the generic pack/unpack between
the struct-of-arrays form the kernels use and the flat ``[W]`` int32
vector the engines dedup and store.

The declared ranges are what upgrade speclint from a Raft artifact into
a compiler property: :func:`envelope` hands the width analyzer an
interval per field straight from the declaration, and
:func:`check_schema` is the admission-time validity gate for non-Raft
specs (shape sanity, range sanity, int32 headroom).
"""

from __future__ import annotations

import dataclasses

import numpy as np

I32 = np.int32

# Symbolic dimension / bound names resolve against Bounds attributes;
# the short forms mirror the letters ops/state.Layout uses.
_DIM_ALIASES = {"n": "n_servers", "L": "log_cap", "S": "msg_cap",
                "E": "elections_cap", "V": "n_values"}


def _resolve(sym, bounds) -> int:
    """An int stands for itself; a string names a Bounds attribute
    (aliases above); a callable is evaluated on bounds."""
    if isinstance(sym, int):
        return sym
    if callable(sym):
        return int(sym(bounds))
    return int(getattr(bounds, _DIM_ALIASES.get(sym, sym)))


@dataclasses.dataclass(frozen=True)
class Field:
    """One state variable: a small-int tensor with a declared shape and
    value range.

    ``shape`` entries are ints or symbolic dimension names (``"n"`` =
    ``n_servers``, ``"L"`` = ``log_cap``, ``"S"`` = ``msg_cap``); an
    empty shape is a scalar carried as one vector word.  ``lo``/``hi``
    declare the inclusive value range (``hi`` may be symbolic), and
    ``init`` is the uniform initial value.
    """
    name: str
    shape: tuple = ()
    lo: int = 0
    hi: object = 0
    init: int = 0


@dataclasses.dataclass(frozen=True)
class Schema:
    """A named tuple of fields; the unit the frontend compiles against."""
    name: str
    fields: tuple

    def __post_init__(self):
        seen = set()
        for f in self.fields:
            if f.name in seen:
                raise ValueError(
                    f"schema {self.name!r}: duplicate field {f.name!r}")
            seen.add(f.name)

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(f"schema {self.name!r} has no field {name!r}")

    @property
    def field_names(self) -> tuple:
        return tuple(f.name for f in self.fields)

    def layout(self, bounds) -> "SchemaLayout":
        return SchemaLayout(self, bounds)


class SchemaLayout:
    """Schema resolved against concrete bounds.

    Duck-types ``ops/state.Layout`` where the engines need it: a
    ``shapes`` dict (field -> concrete shape, declaration order), a
    ``fields`` tuple, and the flat vector ``width``.
    """

    def __init__(self, schema: Schema, bounds):
        self.schema = schema
        self.bounds = bounds
        self.shapes = {f.name: tuple(_resolve(d, bounds) for d in f.shape)
                       for f in schema.fields}

    @property
    def fields(self) -> tuple:
        return tuple(self.shapes)

    @property
    def width(self) -> int:
        return sum(int(np.prod(s, dtype=np.int64)) if s else 1
                   for s in self.shapes.values())

    def init_struct(self, xp=np):
        """The (single) initial state as a struct of arrays."""
        out = {}
        for f in self.schema.fields:
            shp = self.shapes[f.name]
            out[f.name] = (xp.full(shp, f.init, dtype=I32) if shp
                           else xp.asarray(f.init, dtype=I32))
        return out

    def pack(self, struct, xp):
        """Struct of arrays -> flat int32 vector(s).  Arrays may carry
        arbitrary leading batch dims; trailing dims must match the
        declared shapes (scalars get one word)."""
        parts = []
        for name, shp in self.shapes.items():
            a = xp.asarray(struct[name])
            k = int(np.prod(shp, dtype=np.int64)) if shp else 1
            lead = a.shape[:len(a.shape) - len(shp)]
            parts.append(xp.reshape(a, lead + (k,)))
        return xp.concatenate(parts, axis=-1).astype(I32)

    def unpack(self, vec, xp):
        """Flat int32 vector(s) -> struct of arrays (leading batch dims
        preserved) — the inverse of :meth:`pack`."""
        out, off = {}, 0
        for name, shp in self.shapes.items():
            k = int(np.prod(shp, dtype=np.int64)) if shp else 1
            sl = vec[..., off:off + k]
            out[name] = xp.reshape(sl, vec.shape[:-1] + shp) if shp \
                else xp.reshape(sl, vec.shape[:-1])
            off += k
        return out


def envelope(schema: Schema, bounds) -> dict:
    """Field -> declared value interval — the width analyzer's input for
    schema-declared specs (the analog of ``intervals.envelope`` for
    Raft's hand-declared table)."""
    from raft_tla_tpu.analysis.intervals import Interval
    return {f.name: Interval(f.lo, _resolve(f.hi, bounds))
            for f in schema.fields}


def check_schema(schema: Schema, bounds) -> list:
    """Admission-time validity findings for a schema at these bounds
    (lint-style: a list of ``analysis.report.Finding``, empty = clean).

    Checks shape positivity, range sanity, and int32 headroom — the
    declared analog of the Raft packed-width proof: a declared range the
    vector words cannot carry is rejected before any device time.
    """
    from raft_tla_tpu.analysis import report
    findings = []
    lay = schema.layout(bounds)
    for f in schema.fields:
        shp = lay.shapes[f.name]
        if any(d <= 0 for d in shp):
            findings.append(report.Finding(
                report.WIDTH, report.ERROR, "schema-empty-dim",
                f"field {f.name!r} resolves to shape {shp} at these "
                f"bounds", field=f.name))
        hi = _resolve(f.hi, bounds)
        if hi < f.lo:
            findings.append(report.Finding(
                report.WIDTH, report.ERROR, "schema-empty-range",
                f"field {f.name!r} declares empty range "
                f"[{f.lo}, {hi}]", field=f.name))
        if f.lo < -(1 << 31) or hi > (1 << 31) - 1:
            findings.append(report.Finding(
                report.WIDTH, report.ERROR, "schema-i32-overflow",
                f"field {f.name!r} range [{f.lo}, {hi}] exceeds the "
                f"int32 state words", field=f.name))
        if not (f.lo <= f.init <= hi):
            findings.append(report.Finding(
                report.WIDTH, report.ERROR, "schema-init-range",
                f"field {f.name!r} init {f.init} outside declared "
                f"range [{f.lo}, {hi}]", field=f.name))
    return findings
