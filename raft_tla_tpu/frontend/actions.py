"""The IR compiler: ActionDefs -> fused per-family kernels.

:func:`compile_kernels` lowers a spec's :class:`~raft_tla_tpu.frontend.
expr.ActionDef` table to kernels with the exact
``(bounds, s, *params) -> (out, valid, ovf)`` contract that
``ops/kernels.grouped_dispatch`` vmaps — so an IR-defined spec (or Raft
itself, via ``frontend/raft_ir``) rides the existing fused
expand→canonicalize→dedup step untouched.  The lowering deliberately
calls the hand-written helper layer (``_set1``/``_set2``/``bag_add``/
``reply``/``_tree_select``) rather than re-deriving it: equal IR
semantics then produce *bit-identical* lanes, which is what the Raft
parity tests pin down.

:func:`build_schema_step` is the generic step builder for specs declared
purely as a schema + IR (no hand kernels at all): same step-dict
contract as ``kernels.build_step`` — plain lane fingerprints, vmapped
predicate invariants, identity canonicalization unless the spec
declares one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from raft_tla_tpu.frontend import expr as E
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import kernels as K

I32 = jnp.int32


def _as_array_bool(v):
    """Python bools (a Lit(True) validity) become traced scalars so the
    dispatch loop can broadcast them like the hand kernels'
    ``jnp.bool_(True)``."""
    return jnp.bool_(v) if isinstance(v, bool) else v


def _apply_update(ctx, out, u):
    """One field write on the branch struct; values read the pre-state
    through ``ctx`` (the hand kernels' functional idiom)."""
    arr = out[u.field]
    if isinstance(u, E.Set1):
        written = K._set1(arr, u.i.ev(ctx), u.val.ev(ctx))
    elif isinstance(u, E.SetRow):
        return K._set_row(arr, u.i.ev(ctx), u.val.ev(ctx))
    elif isinstance(u, E.Set2):
        written = K._set2(arr, u.i.ev(ctx), u.j.ev(ctx), u.val.ev(ctx))
    else:
        raise TypeError(f"unknown update node {type(u).__name__}")
    cond = getattr(u, "cond", None)
    if cond is None:
        return written
    return jnp.where(cond.ev(ctx), written, arr)


def _pack_words(ctx, msg):
    """Evaluate a PackMsg into the (hi, lo) packed int32 words —
    value-identical to the ``ops/msgbits`` constructors (same shifts,
    OR-composition of non-negative subfields)."""
    from raft_tla_tpu.ops import msgbits as mb
    vals = {"mtype": msg.mtype}
    for name, e in msg.fields:
        v = e.ev(ctx)
        if hasattr(v, "dtype") and v.dtype == jnp.bool_:
            v = v.astype(I32)
        vals[name] = v
    words = []
    for table in (mb.HI_FIELDS, mb.LO_FIELDS):
        w = None
        for name, (shift, _width) in table.items():
            v = vals.get(name)
            if v is None:
                continue
            t = (v << shift) if shift else v
            w = t if w is None else (w | t)
        words.append(jnp.int32(0) if w is None else w)
    return words[0], words[1]


def _branch_effects(ctx, s, br):
    """Apply one branch: field updates, then bag ops in order.  Returns
    (out_struct, ovf_or_None)."""
    out = dict(s)
    for u in br.updates:
        out[u.field] = _apply_update(ctx, out, u)
    ovf = None
    for op in br.ops:
        if isinstance(op, E.BagAdd):
            hi, lo = _pack_words(ctx, op.msg)
            out, o = K.bag_add(out, hi, lo)
        elif isinstance(op, E.BagRemove):
            mhi, mlo = ctx.msg_words()
            out = K.bag_remove(out, mhi, mlo)
            continue
        elif isinstance(op, E.Reply):
            hi, lo = _pack_words(ctx, op.msg)
            mhi, mlo = ctx.msg_words()
            out, o = K.reply(out, hi, lo, mhi, mlo)
        else:
            raise TypeError(f"unknown bag op {type(op).__name__}")
        ovf = o if ovf is None else (ovf | o)
    if br.overflow is not None:
        o = br.overflow.ev(ctx)
        ovf = o if ovf is None else (ovf | o)
    return out, ovf


def _compile_action(adef):
    """ActionDef -> kernel(bounds, s, *params) with the grouped_dispatch
    contract."""

    def kern(bounds, s, *args):
        ctx = E.Ctx(bounds, s, dict(zip(adef.params, args)), jnp)
        valid = _as_array_bool(adef.valid.ev(ctx))
        if len(adef.branches) == 1 and adef.branches[0].guard is None:
            out, contrib = _branch_effects(ctx, s, adef.branches[0])
            total = contrib
        else:
            pairs, guards, total = [], [], None
            for br in adef.branches:
                g = br.guard.ev(ctx)
                b_out, contrib = _branch_effects(ctx, s, br)
                pairs.append((g, b_out))
                guards.append(g)
                if contrib is not None:
                    t = g & contrib
                    total = t if total is None else (total | t)
            out = K._tree_select(pairs, s)
            if adef.any_guard_valid:
                valid = valid & functools.reduce(jnp.logical_or, guards)
        ovf = jnp.bool_(False) if total is None else (valid & total)
        return out, valid, ovf

    kern.__name__ = f"ir_{adef.family.lower()}"
    return kern


def compile_kernels(defs):
    """IR table -> ``{family: (kernel, params)}``, the shape
    ``grouped_dispatch(..., family_kernels=...)`` consumes."""
    return {adef.family: (_compile_action(adef), adef.params)
            for adef in defs}


def build_schema_expand(schema, defs, table, bounds):
    """The expand half of :func:`build_schema_step` on its own:
    ``expand(struct) -> (succs[A, ...], valid[A], ovf[A])`` in
    action_table order — the same contract as ``kernels.build_expand``,
    which is what the simulation engines vmap per walker (they sample
    one lane per step instead of fingerprinting the whole fan-out)."""
    fam_kernels = compile_kernels(defs)
    groups = K.group_instances(table)

    def expand(s):
        succs, valids, ovfs = K.grouped_dispatch(
            bounds, s, groups, family_kernels=fam_kernels)
        all_succs = jax.tree.map(
            lambda *xs: jnp.concatenate(xs, axis=0), *succs)
        return (all_succs,
                jnp.concatenate(valids, axis=0),
                jnp.concatenate(ovfs, axis=0))

    return expand


def build_schema_step(schema, defs, table, bounds, predicates=()):
    """Generic fused step for a schema-declared spec.

    ``table`` is the action-instance list (objects with ``.family`` and
    the per-family param attributes), ``predicates`` the compiled
    invariant :class:`~raft_tla_tpu.frontend.predicate.Predicate` probes
    (order = CheckConfig.invariants).  Returns ``step(vecs[B, W]) ->
    dict`` with the exact key set/shapes ``kernels.build_step``
    produces: svecs, valid, overflow, fp_hi/fp_lo (uint32 lanes),
    inv_ok, con_ok.  Canonicalization is the identity (a schema spec
    declares no bag-slot permutation) and ``con_ok`` is all-true; both
    are points where a future schema hook can slot in.
    """
    lay = schema.layout(bounds)
    consts = jnp.asarray(fpr.lane_constants(lay.width))
    expand = build_schema_expand(schema, defs, table, bounds)

    def step(vecs):
        structs = jax.vmap(lambda v: lay.unpack(v, jnp))(vecs)
        succs, valid, ovf = jax.vmap(expand)(structs)
        svecs = jax.vmap(jax.vmap(lambda t: lay.pack(t, jnp)))(succs)
        fp_hi, fp_lo = fpr.fingerprint(svecs, consts, jnp)
        if predicates:
            inv_ok = jnp.stack(
                [jax.vmap(jax.vmap(lambda t, p=p: p.ev(t, jnp)))(succs)
                 for p in predicates], axis=-1)
        else:
            inv_ok = jnp.ones(valid.shape + (0,), dtype=bool)
        return {"svecs": svecs, "valid": valid, "overflow": ovf,
                "fp_hi": fp_hi, "fp_lo": fp_lo, "inv_ok": inv_ok,
                "con_ok": jnp.ones_like(valid)}

    return step
