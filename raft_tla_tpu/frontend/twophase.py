"""Bounded two-phase commit — the second bundled spec.

The frontend's existence proof that "one checker, many protocols" is
real: Lamport's ``TwoPhase.tla`` (the TM/RM transaction-commit protocol
from the TLA+ hyperbook, itself a refinement of ``TCommit``) declared
purely as frontend data — a :class:`~raft_tla_tpu.frontend.schema.
Schema` plus an :class:`~raft_tla_tpu.frontend.expr.ActionDef` table —
and compiled by ``frontend/actions.build_schema_step`` into the same
fused step contract every engine consumes.  Not one line of kernel code
is specific to this protocol.

Encoding
--------
Messages in ``TwoPhase.tla`` live in a *set* (never removed), so each
possible message is one monotone flag: ``msgPrepared[rm]`` for
``[type |-> "Prepared", rm |-> rm]``, and scalar ``msgCommit`` /
``msgAbort`` flags for the TM's broadcast decisions.  State is
``3n + 3`` lanes for ``n`` RMs; the state space is finite with no
``--max-*`` bound needed.  ``rmState`` values: 0 working, 1 prepared,
2 committed, 3 aborted; ``tmState``: 0 init, 1 committed, 2 aborted.

The module also carries everything a model adapter needs end-to-end:
a hashable Python state + vec codec (trace rendering), an *independent*
pure-Python BFS oracle (:func:`reference_check` — hand-transcribed
guards, no IR, the NumPy reference the engine counts are validated
against), a TLC-style state renderer, and :func:`emit_tla` for a
stock-TLC parity run of the identical bounded model.

The canonical invariant is ``TCConsistent`` (``TCommit.tla``): no RM
has committed while another has aborted — expressed in the frontend
predicate language, so it exercises the same compiled-predicate path
any user-written INVARIANT expression rides.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.frontend import expr as E
from raft_tla_tpu.frontend.schema import Field, Schema

# rmState values (TCommit.tla: RM states)
WORKING, PREPARED, COMMITTED, ABORTED = 0, 1, 2, 3
RM_STATE_NAMES = ("working", "prepared", "committed", "aborted")
# tmState values (TwoPhase.tla: TM states)
TM_INIT, TM_COMMITTED, TM_ABORTED = 0, 1, 2
TM_STATE_NAMES = ("init", "committed", "aborted")

SCHEMA = Schema("twophase", (
    Field("rmState", ("n",), 0, 3),
    Field("tmState", (1,), 0, 2),
    Field("tmPrepared", ("n",), 0, 1),
    Field("msgPrepared", ("n",), 0, 1),
    Field("msgCommit", (1,), 0, 1),
    Field("msgAbort", (1,), 0, 1),
))

# Action families, in Next-disjunct order (TwoPhase.tla: TPNext).
TM_RCV_PREPARED = "TMRcvPrepared"
TM_COMMIT = "TMCommit"
TM_ABORT = "TMAbort"
RM_PREPARE = "RMPrepare"
RM_CHOOSE_ABORT = "RMChooseToAbort"
RM_RCV_COMMIT = "RMRcvCommitMsg"
RM_RCV_ABORT = "RMRcvAbortMsg"

ALL_FAMILIES = (TM_RCV_PREPARED, TM_COMMIT, TM_ABORT, RM_PREPARE,
                RM_CHOOSE_ABORT, RM_RCV_COMMIT, RM_RCV_ABORT)

# TCommit.tla's TCConsistent, in the frontend predicate grammar: no two
# RMs ever disagree committed-vs-aborted.  Registered names resolve to
# these texts; whole-line INVARIANT expressions compile directly.
INVARIANTS = {
    "TCConsistent": "~(any(rmState = 3) /\\ any(rmState = 2))",
}
DEFAULT_INVARIANT = "TCConsistent"


# -- the IR action table ------------------------------------------------------

def _lit(v):
    return E.Lit(v)


def _g(field, *idx):
    return E.Get(field, tuple(idx))


def _eq(a, b):
    return E.Bin("==", a, b)


def _and(a, b):
    return E.Bin("and", a, b)


_I = E.Param("i")
_Z = _lit(0)
_TM = _g("tmState", _Z)

# TMCommit's \A rm: tmPrepared[rm] guard is a reduction over the RM
# axis — an Intrinsic, like Raft's quorum scan (entries are 0/1, so
# "all prepared" is min > 0).
_ALL_PREPARED = E.Intrinsic(
    "all_prepared",
    lambda bounds, s, params, xp: xp.min(s["tmPrepared"]) > 0,
    lambda bounds, env: __import__(
        "raft_tla_tpu.analysis.intervals", fromlist=["BOOL"]).BOOL)


def _set1(field, i, val):
    return E.Set1(field, i, _lit(val))


ACTIONS = (
    # TMRcvPrepared(rm): the TM records rm's Prepared message.
    E.ActionDef(
        TM_RCV_PREPARED, ("i",),
        _and(_eq(_TM, _lit(TM_INIT)), _eq(_g("msgPrepared", _I), _lit(1))),
        (E.Branch(updates=(_set1("tmPrepared", _I, 1),)),)),
    # TMCommit: every RM prepared -> commit and broadcast.
    E.ActionDef(
        TM_COMMIT, ("i",),
        _and(_eq(_TM, _lit(TM_INIT)), _ALL_PREPARED),
        (E.Branch(updates=(_set1("tmState", _Z, TM_COMMITTED),
                           _set1("msgCommit", _Z, 1))),)),
    # TMAbort: the TM may spontaneously abort while undecided.
    E.ActionDef(
        TM_ABORT, ("i",),
        _eq(_TM, _lit(TM_INIT)),
        (E.Branch(updates=(_set1("tmState", _Z, TM_ABORTED),
                           _set1("msgAbort", _Z, 1))),)),
    # RMPrepare(rm): a working RM prepares and tells the TM.
    E.ActionDef(
        RM_PREPARE, ("i",),
        _eq(_g("rmState", _I), _lit(WORKING)),
        (E.Branch(updates=(_set1("rmState", _I, PREPARED),
                           _set1("msgPrepared", _I, 1))),)),
    # RMChooseToAbort(rm): a working RM unilaterally aborts.
    E.ActionDef(
        RM_CHOOSE_ABORT, ("i",),
        _eq(_g("rmState", _I), _lit(WORKING)),
        (E.Branch(updates=(_set1("rmState", _I, ABORTED),)),)),
    # RMRcvCommitMsg(rm): any RM that sees the Commit message commits.
    E.ActionDef(
        RM_RCV_COMMIT, ("i",),
        _eq(_g("msgCommit", _Z), _lit(1)),
        (E.Branch(updates=(_set1("rmState", _I, COMMITTED),)),)),
    # RMRcvAbortMsg(rm): any RM that sees the Abort message aborts.
    E.ActionDef(
        RM_RCV_ABORT, ("i",),
        _eq(_g("msgAbort", _Z), _lit(1)),
        (E.Branch(updates=(_set1("rmState", _I, ABORTED),)),)),
)


@dataclasses.dataclass(frozen=True)
class TPInstance:
    """One successor lane: family + bound RM index.  The TM-only actions
    carry a single dummy instance (``i`` unread) so the grouped vmapped
    dispatch keeps its one mapped axis."""

    family: str
    i: int = 0

    def label(self) -> str:
        if self.family in (TM_COMMIT, TM_ABORT):
            return self.family
        return f"{self.family}(r{self.i + 1})"


def action_table(bounds: Bounds) -> list:
    """The static successor fan-out, in Next-disjunct order: A = 5n + 2."""
    n = bounds.n_servers
    table = [TPInstance(TM_RCV_PREPARED, i) for i in range(n)]
    table += [TPInstance(TM_COMMIT), TPInstance(TM_ABORT)]
    for fam in (RM_PREPARE, RM_CHOOSE_ABORT, RM_RCV_COMMIT, RM_RCV_ABORT):
        table += [TPInstance(fam, i) for i in range(n)]
    return table


# -- Python state + codec -----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TPState:
    """One state, hashable — the twophase analog of ``interp.PyState``."""

    rmState: tuple
    tmState: int
    tmPrepared: tuple
    msgPrepared: tuple
    msgCommit: int
    msgAbort: int

    def _replace(self, **kw) -> "TPState":
        return dataclasses.replace(self, **kw)


def init_state(bounds: Bounds) -> TPState:
    """TPInit: every RM working, TM undecided, no messages."""
    n = bounds.n_servers
    return TPState((WORKING,) * n, TM_INIT, (0,) * n, (0,) * n, 0, 0)


def to_vec(s: TPState, bounds: Bounds) -> np.ndarray:
    """Pack in schema declaration order — must agree with
    ``SCHEMA.layout(bounds).pack`` (pinned by tests)."""
    return np.asarray([*s.rmState, s.tmState, *s.tmPrepared,
                       *s.msgPrepared, s.msgCommit, s.msgAbort],
                      dtype=np.int32)


def from_vec(vec, bounds: Bounds) -> TPState:
    n = bounds.n_servers
    v = [int(x) for x in np.asarray(vec).reshape(-1)]
    return TPState(tuple(v[0:n]), v[n], tuple(v[n + 1:2 * n + 1]),
                   tuple(v[2 * n + 1:3 * n + 1]), v[3 * n + 1], v[3 * n + 2])


# -- the independent NumPy/pure-Python reference oracle -----------------------

def _py_successors(s: TPState, n: int):
    """Enabled (label, successor) pairs in action_table order — a direct
    hand transcription of the TwoPhase.tla guards, deliberately NOT via
    the IR (it is the oracle the compiled step is validated against)."""
    out = []
    for rm in range(n):
        if s.tmState == TM_INIT and s.msgPrepared[rm]:
            tp = list(s.tmPrepared)
            tp[rm] = 1
            out.append((f"TMRcvPrepared(r{rm + 1})",
                        s._replace(tmPrepared=tuple(tp))))
    if s.tmState == TM_INIT and all(s.tmPrepared):
        out.append(("TMCommit",
                    s._replace(tmState=TM_COMMITTED, msgCommit=1)))
    if s.tmState == TM_INIT:
        out.append(("TMAbort", s._replace(tmState=TM_ABORTED, msgAbort=1)))
    for rm in range(n):
        if s.rmState[rm] == WORKING:
            rs, mp = list(s.rmState), list(s.msgPrepared)
            rs[rm], mp[rm] = PREPARED, 1
            out.append((f"RMPrepare(r{rm + 1})",
                        s._replace(rmState=tuple(rs),
                                   msgPrepared=tuple(mp))))
    for rm in range(n):
        if s.rmState[rm] == WORKING:
            rs = list(s.rmState)
            rs[rm] = ABORTED
            out.append((f"RMChooseToAbort(r{rm + 1})",
                        s._replace(rmState=tuple(rs))))
    for rm in range(n):
        if s.msgCommit:
            rs = list(s.rmState)
            rs[rm] = COMMITTED
            out.append((f"RMRcvCommitMsg(r{rm + 1})",
                        s._replace(rmState=tuple(rs))))
    for rm in range(n):
        if s.msgAbort:
            rs = list(s.rmState)
            rs[rm] = ABORTED
            out.append((f"RMRcvAbortMsg(r{rm + 1})",
                        s._replace(rmState=tuple(rs))))
    return out


def apply_instance(s: TPState, inst: TPInstance,
                   bounds: Bounds) -> TPState | None:
    """Host interpreter for one action lane (simulation replay): the
    successor for ``inst`` if its guard holds, else None — the same
    hand-transcribed guards as :func:`_py_successors`, addressed by
    lane instead of enumerated, so a recorded walk replays exactly."""
    rm, fam = inst.i, inst.family
    if fam == TM_RCV_PREPARED:
        if s.tmState == TM_INIT and s.msgPrepared[rm]:
            tp = list(s.tmPrepared)
            tp[rm] = 1
            return s._replace(tmPrepared=tuple(tp))
        return None
    if fam == TM_COMMIT:
        if s.tmState == TM_INIT and all(s.tmPrepared):
            return s._replace(tmState=TM_COMMITTED, msgCommit=1)
        return None
    if fam == TM_ABORT:
        if s.tmState == TM_INIT:
            return s._replace(tmState=TM_ABORTED, msgAbort=1)
        return None
    if fam == RM_PREPARE:
        if s.rmState[rm] == WORKING:
            rs, mp = list(s.rmState), list(s.msgPrepared)
            rs[rm], mp[rm] = PREPARED, 1
            return s._replace(rmState=tuple(rs), msgPrepared=tuple(mp))
        return None
    if fam == RM_CHOOSE_ABORT:
        if s.rmState[rm] == WORKING:
            rs = list(s.rmState)
            rs[rm] = ABORTED
            return s._replace(rmState=tuple(rs))
        return None
    if fam == RM_RCV_COMMIT:
        if s.msgCommit:
            rs = list(s.rmState)
            rs[rm] = COMMITTED
            return s._replace(rmState=tuple(rs))
        return None
    if fam == RM_RCV_ABORT:
        if s.msgAbort:
            rs = list(s.rmState)
            rs[rm] = ABORTED
            return s._replace(rmState=tuple(rs))
        return None
    raise ValueError(f"unknown twophase action family {fam!r}")


def py_tc_consistent(s: TPState) -> bool:
    """TCConsistent, hand-written (the oracle face of the predicate)."""
    return not (any(r == ABORTED for r in s.rmState)
                and any(r == COMMITTED for r in s.rmState))


@dataclasses.dataclass
class ReferenceResult:
    n_states: int
    diameter: int
    n_transitions: int
    levels: list
    consistent: bool          # TCConsistent held on every reachable state


def reference_check(n: int) -> ReferenceResult:
    """Exhaustive BFS over hashable states: the count/diameter oracle the
    engine and serve paths are pinned against at small n."""
    bounds = Bounds(n_servers=n)
    init = init_state(bounds)
    seen = {init}
    frontier = [init]
    levels = [1]
    n_transitions = 0
    consistent = py_tc_consistent(init)
    while frontier:
        nxt = []
        for s in frontier:
            succs = _py_successors(s, n)
            n_transitions += len(succs)
            for _label, t in succs:
                if t in seen:
                    continue
                seen.add(t)
                consistent = consistent and py_tc_consistent(t)
                nxt.append(t)
        if nxt:
            levels.append(len(nxt))
        frontier = nxt
    return ReferenceResult(n_states=len(seen), diameter=len(levels) - 1,
                           n_transitions=n_transitions, levels=levels,
                           consistent=consistent)


# -- rendering ----------------------------------------------------------------

def _rm(i: int) -> str:
    return f"r{i + 1}"


def render_state(s: TPState, bounds: Bounds, indent: str = "    ") -> str:
    """TLC-style conjunction, message flags rendered back as the
    TwoPhase.tla message *set*."""
    n = bounds.n_servers
    msgs = [f'[type |-> "Prepared", rm |-> {_rm(i)}]'
            for i in range(n) if s.msgPrepared[i]]
    if s.msgCommit:
        msgs.append('[type |-> "Commit"]')
    if s.msgAbort:
        msgs.append('[type |-> "Abort"]')
    lines = [
        "/\\ rmState = (" + " @@ ".join(
            f'{_rm(i)} :> "{RM_STATE_NAMES[s.rmState[i]]}"'
            for i in range(n)) + ")",
        f'/\\ tmState = "{TM_STATE_NAMES[s.tmState]}"',
        "/\\ tmPrepared = {" + ", ".join(
            _rm(i) for i in range(n) if s.tmPrepared[i]) + "}",
        "/\\ msgs = {" + ", ".join(msgs) + "}",
    ]
    return "\n".join(indent + ln for ln in lines)


def render_trace(violation, bounds: Bounds) -> str:
    from raft_tla_tpu.utils import render
    return render.render_trace(violation, bounds,
                               state_renderer=render_state)


# -- TLC parity emission ------------------------------------------------------

_TLA_TEMPLATE = """---------------------------- MODULE MC2pc ----------------------------
\\* Bounded two-phase commit — emitted by raft_tla_tpu for a stock-TLC
\\* parity run of the exact model the TPU checker explored (the message
\\* set is total: TwoPhase.tla messages are never removed).
EXTENDS Naturals

CONSTANT RM                  \\* the set of resource managers

VARIABLES rmState, tmState, tmPrepared, msgs
vars == <<rmState, tmState, tmPrepared, msgs>>

Messages == [type : {{"Prepared"}}, rm : RM] \\cup [type : {{"Commit", "Abort"}}]

TPTypeOK ==
  /\\ rmState \\in [RM -> {{"working", "prepared", "committed", "aborted"}}]
  /\\ tmState \\in {{"init", "committed", "aborted"}}
  /\\ tmPrepared \\subseteq RM
  /\\ msgs \\subseteq Messages

Init ==
  /\\ rmState = [rm \\in RM |-> "working"]
  /\\ tmState = "init"
  /\\ tmPrepared = {{}}
  /\\ msgs = {{}}

TMRcvPrepared(rm) ==
  /\\ tmState = "init"
  /\\ [type |-> "Prepared", rm |-> rm] \\in msgs
  /\\ tmPrepared' = tmPrepared \\cup {{rm}}
  /\\ UNCHANGED <<rmState, tmState, msgs>>

TMCommit ==
  /\\ tmState = "init"
  /\\ tmPrepared = RM
  /\\ tmState' = "committed"
  /\\ msgs' = msgs \\cup {{[type |-> "Commit"]}}
  /\\ UNCHANGED <<rmState, tmPrepared>>

TMAbort ==
  /\\ tmState = "init"
  /\\ tmState' = "aborted"
  /\\ msgs' = msgs \\cup {{[type |-> "Abort"]}}
  /\\ UNCHANGED <<rmState, tmPrepared>>

RMPrepare(rm) ==
  /\\ rmState[rm] = "working"
  /\\ rmState' = [rmState EXCEPT ![rm] = "prepared"]
  /\\ msgs' = msgs \\cup {{[type |-> "Prepared", rm |-> rm]}}
  /\\ UNCHANGED <<tmState, tmPrepared>>

RMChooseToAbort(rm) ==
  /\\ rmState[rm] = "working"
  /\\ rmState' = [rmState EXCEPT ![rm] = "aborted"]
  /\\ UNCHANGED <<tmState, tmPrepared, msgs>>

RMRcvCommitMsg(rm) ==
  /\\ [type |-> "Commit"] \\in msgs
  /\\ rmState' = [rmState EXCEPT ![rm] = "committed"]
  /\\ UNCHANGED <<tmState, tmPrepared, msgs>>

RMRcvAbortMsg(rm) ==
  /\\ [type |-> "Abort"] \\in msgs
  /\\ rmState' = [rmState EXCEPT ![rm] = "aborted"]
  /\\ UNCHANGED <<tmState, tmPrepared, msgs>>

Next ==
  \\/ TMCommit \\/ TMAbort
  \\/ \\E rm \\in RM :
       TMRcvPrepared(rm) \\/ RMPrepare(rm) \\/ RMChooseToAbort(rm)
         \\/ RMRcvCommitMsg(rm) \\/ RMRcvAbortMsg(rm)

Spec == Init /\\ [][Next]_vars

TCConsistent ==
  \\A rm1, rm2 \\in RM :
    ~(rmState[rm1] = "aborted" /\\ rmState[rm2] = "committed")
=======================================================================
"""


def emit_tla(out_dir: str, bounds: Bounds, invariants=()) -> tuple:
    """Write ``MC2pc.tla``/``MC2pc.cfg`` — the stock-TLC twin of this
    bounded model.  Only registered (named) invariants can be emitted;
    a whole-line expression has no TLA+ operator name to reference."""
    names = []
    for nm in invariants:
        if nm not in INVARIANTS:
            raise ValueError(
                f"cannot emit invariant expression {nm!r} to TLC: only "
                f"the registered names ({', '.join(sorted(INVARIANTS))}) "
                "have TLA+ operator definitions")
        names.append(nm)
    os.makedirs(out_dir, exist_ok=True)
    tla = os.path.join(out_dir, "MC2pc.tla")
    cfgp = os.path.join(out_dir, "MC2pc.cfg")
    with open(tla, "w", encoding="utf-8") as f:
        f.write(_TLA_TEMPLATE.format())
    rms = ", ".join(_rm(i) for i in range(bounds.n_servers))
    lines = ["SPECIFICATION Spec",
             f"CONSTANT RM = {{{rms}}}"]
    for nm in names:
        lines.append(f"INVARIANT {nm}")
    with open(cfgp, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    return tla, cfgp
