"""Generated speclint Pass-1 transfer twins — widthcheck from the IR.

``analysis/widthcheck.TRANSFERS`` is a hand-written abstract twin per
kernel family: the interval effect of one transition on every written
field, plus the message records it creates.  :func:`transfer_of`
*derives* that twin from the same :class:`~raft_tla_tpu.frontend.expr.
ActionDef` the runtime kernel is compiled from, by evaluating the def
over the interval domain:

- update values evaluate via each node's ``iv`` rule (``Where`` -> join,
  comparisons -> BOOL, ``bor`` -> ``Interval.or_``, reads -> envelope);
  conditional writes contribute the *written* value only, matching the
  hand twins' "interval of newly written values" convention;
- a branch is skipped when it is infeasible under the current message
  envelope (its ``mtype`` has no creation site, a scoped subfield is
  absent) or its declared guard ``refines`` meet is empty — the
  structural analog of the hand twins' ``if rec is not None`` /
  capacity-gated blocks;
- record creation sites become ``MsgRecord``s over the full subfield
  tables (missing subfields pack as 0 -> ``const(0)``), with declared
  relational ``facts`` and ``overrides`` passed through;
- bag effects reuse ``widthcheck._send_writes`` verbatim (packed-word
  arithmetic has ONE definition), and any remove op contributes the
  emptied-slot joins.

tests/test_frontend_ir.py pins ``transfer_of(adef) == TRANSFERS[fam]``
output-for-output across bounds, so the hand twins and the kernels can
only drift together — which is the point: speclint's width proof becomes
a property of the compiler, not of one hand-maintained table.
"""

from __future__ import annotations

from raft_tla_tpu.analysis import intervals as iv
from raft_tla_tpu.frontend import expr as E


def _record(msg, ictx):
    """A PackMsg site as a widthcheck MsgRecord under ``ictx``."""
    from raft_tla_tpu.analysis.widthcheck import MsgRecord
    from raft_tla_tpu.ops import msgbits as mb
    declared = dict(msg.fields)
    overrides = dict(msg.overrides)
    fields = {}
    for name in (*mb.HI_FIELDS, *mb.LO_FIELDS):
        if name == "mtype":
            fields[name] = iv.const(msg.mtype)
        elif name in overrides:
            # the subfield echoes a relational fact of the consumed
            # record (the done-reply's b = a+c of the request)
            rec = ictx.menv.get(ictx.mtype)
            if rec is None or overrides[name] not in rec:
                raise E.Infeasible(overrides[name])
            fields[name] = rec[overrides[name]]
        else:
            e = declared.get(name)
            fields[name] = iv.const(0) if e is None else e.iv(ictx)
    for fname, fn in msg.facts:
        fields[fname] = fn(ictx.bounds, ictx.env, ictx.menv)
    return MsgRecord(msg.mtype, fields)


def transfer_of(adef):
    """ActionDef -> ``transfer(bounds, env, menv) -> TransferResult``,
    the exact callable shape ``widthcheck.TRANSFERS`` holds (and
    ``check_widths(transfers=...)`` injects)."""

    def transfer(bounds, env, menv):
        from raft_tla_tpu.analysis.widthcheck import (TransferResult,
                                                      _send_writes)
        param_iv = {name: fn(bounds) for name, fn in adef.param_iv}
        writes: dict = {}

        def join_write(field, interval):
            cur = writes.get(field)
            writes[field] = interval if cur is None else cur.join(interval)

        sends = []
        # Structural, not envelope-gated: a spec whose action CAN remove
        # a message must always account for the emptied slot (the hand
        # t_receive/t_drop join these unconditionally).
        has_remove = any(isinstance(op, (E.BagRemove, E.Reply))
                         for br in adef.branches for op in br.ops)
        for br in adef.branches:
            try:
                benv = env
                if br.refines:
                    benv = dict(env)
                    for field, lo, hi in br.refines:
                        # empty meet (ValueError) = branch infeasible at
                        # these bounds, e.g. truncation with log_cap 0
                        benv[field] = benv[field].meet(iv.Interval(lo, hi))
                ictx = E.IvCtx(bounds, benv, menv, param_iv, br.mtype)
                if br.mtype is not None and br.mtype not in menv:
                    raise E.Infeasible(f"mtype {br.mtype} has no record")
                branch_writes = [(u.field, u.val.iv(ictx))
                                 for u in br.updates]
                branch_sends = [_record(op.msg, ictx) for op in br.ops
                                if isinstance(op, (E.BagAdd, E.Reply))]
            except (E.Infeasible, ValueError):
                continue
            for field, interval in branch_writes:
                join_write(field, interval)
            sends.extend(branch_sends)
        if sends:
            for field, interval in _send_writes(env, tuple(sends)).items():
                join_write(field, interval)
        if has_remove:
            join_write("msgHi", iv.const(0))
            join_write("msgLo", iv.const(0))
            join_write("msgCount", iv.Interval(0, env["msgCount"].hi))
        return TransferResult(writes, tuple(sends))

    return transfer
