"""Spec-generic frontend — compile bounded TLA+ subsets, not just Raft.

The engines, dedup stores, symmetry, views, liveness, obs, and serve
layers are model-agnostic in shape; only ``models/spec.py`` +
``ops/kernels.py`` were Raft-specific.  This package is the seam that
makes "one checker, many protocols" real (ROADMAP item 7):

- ``schema``     — declared tensor state schemas (fields, shapes, ranges)
- ``predicate``  — quantifier-free boolean predicate compiler accepted in
                   INVARIANT stanzas of any loaded spec (dual numpy/jnp)
- ``expr``       — the action-definition IR: guards, per-field updates,
                   bag/message ops over a schema
- ``actions``    — the IR compiler: IR -> fused per-family kernels with
                   the exact ``(bounds, s, *params) -> (out, valid, ovf)``
                   contract ``ops/kernels.grouped_dispatch`` expects, plus
                   a generic ``build_step`` for non-Raft schemas
- ``widthgen``   — speclint Pass-1 transfer twins *generated from the IR*
                   (cross-checked bit-for-bit against the hand twins)
- ``raft_schema``— the Raft field table + action table as a schema
                   instance (``models/spec.py`` re-exports it)
- ``raft_ir``    — Raft transcribed into the IR: the first client of the
                   compiler, bit-identical to the hand-written kernels
- ``twophase``   — the second bundled spec: bounded two-phase commit,
                   checked end-to-end with a NumPy reference oracle
- ``registry``   — ``resolve_model(spec)``: one name -> model adapter
"""

from raft_tla_tpu.frontend.predicate import compile_predicate, is_expression


def resolve_model(spec: str):
    """Lazy re-export of :func:`raft_tla_tpu.frontend.registry.
    resolve_model` — deferred because the registry pulls in the kernel
    layer, which itself imports ``models/spec`` (a re-export of
    ``frontend/raft_schema``); an eager import here would cycle."""
    from raft_tla_tpu.frontend.registry import resolve_model as _resolve
    return _resolve(spec)


__all__ = ["compile_predicate", "is_expression", "resolve_model"]
