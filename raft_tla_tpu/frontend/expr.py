"""The action-definition IR — guards, per-field updates, bag ops.

An :class:`ActionDef` describes one action family of a spec as data:
scalar guard/value expressions over the state struct, one-hot field
updates, and message-bag operations.  Two independent consumers compile
it:

- ``frontend/actions.py`` lowers it to a batched kernel with the exact
  ``(bounds, s, *params) -> (out, valid, ovf)`` contract
  ``ops/kernels.grouped_dispatch`` expects.  The lowering calls the SAME
  helper functions the hand-written kernels use (``_set1``/``_set2``/
  ``bag_add``/``_tree_select``/msgbits accessors), so equal IR semantics
  produce bit-identical lanes — the Raft parity guarantee is structural,
  not coincidental.
- ``frontend/widthgen.py`` abstract-interprets the same tree over the
  interval domain (``analysis/intervals``) to *generate* speclint's
  Pass-1 transfer twins, cross-checked against the hand-written ones.

Expression values are scalars (per-action-instance); array effects live
in the Update/Bag nodes.  Every node carries both a concrete evaluator
(``ev``) and an interval transfer (``iv``); :class:`Intrinsic` is the
escape hatch for aggregations the scalar language cannot express (e.g.
Raft's quorum-max-agree) — a compiler builtin with a declared transfer,
exactly like the relational ``facts`` a :class:`PackMsg` may declare.
"""

from __future__ import annotations

import dataclasses

from raft_tla_tpu.analysis import intervals as iv

BOOL, INT = "bool", "int"


class Infeasible(Exception):
    """Raised during interval evaluation when a branch cannot execute
    under the current message envelope / guard refinement (e.g. a
    MsgField read of an mtype no creation site produces).  widthgen
    skips the branch — mirroring the hand twins' ``if rec is not None``
    structure."""


class Ctx:
    """Concrete evaluation context: one action instance on one state."""

    __slots__ = ("bounds", "s", "params", "xp", "_msg")

    def __init__(self, bounds, s, params, xp):
        self.bounds, self.s, self.params, self.xp = bounds, s, params, xp
        self._msg = None

    def msg_words(self):
        """(msgHi[slot], msgLo[slot]) of the instance's ``slot`` param."""
        if self._msg is None:
            slot = self.params["slot"]
            self._msg = (self.s["msgHi"][slot], self.s["msgLo"][slot])
        return self._msg


class IvCtx:
    """Abstract evaluation context for widthgen: the expansion envelope,
    the message envelope, per-param declared intervals, and the active
    branch's mtype scope for MsgField reads."""

    __slots__ = ("bounds", "env", "menv", "param_iv", "mtype")

    def __init__(self, bounds, env, menv, param_iv, mtype=None):
        self.bounds = bounds
        self.env = env
        self.menv = menv
        self.param_iv = param_iv
        self.mtype = mtype


# ---------------------------------------------------------------------------
# Scalar expressions


@dataclasses.dataclass(frozen=True)
class Lit:
    v: object                     # int or bool

    def ev(self, ctx):
        return self.v

    def iv(self, ictx):
        if isinstance(self.v, bool):
            return iv.BOOL if self.v else iv.const(0)
        return iv.const(self.v)


@dataclasses.dataclass(frozen=True)
class Dim:
    """A bounds-derived static integer (``n_servers``, ``log_cap``, ...);
    evaluates to a Python int so it can parameterize shapes/clips."""
    name: str

    def ev(self, ctx):
        return int(getattr(ctx.bounds, self.name))

    def iv(self, ictx):
        return iv.const(int(getattr(ictx.bounds, self.name)))


@dataclasses.dataclass(frozen=True)
class Param:
    name: str

    def ev(self, ctx):
        return ctx.params[self.name]

    def iv(self, ictx):
        return ictx.param_iv[self.name]


@dataclasses.dataclass(frozen=True)
class Get:
    """State read ``s[field][idx...]`` (0, 1 or 2 scalar indices)."""
    field: str
    idx: tuple = ()

    def ev(self, ctx):
        a = ctx.s[self.field]
        if not self.idx:
            return a
        if len(self.idx) == 1:
            return a[self.idx[0].ev(ctx)]
        return a[tuple(e.ev(ctx) for e in self.idx)]

    def iv(self, ictx):
        return ictx.env[self.field]


# evaluator / interval-transfer tables per op code; "and"/"or" are the
# logical forms (BOOL), "band"/"bor" the bitwise forms (value intervals)
_EV = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "and": lambda a, b: a & b,
    "or": lambda a, b: a | b,
    "band": lambda a, b: a & b,
    "bor": lambda a, b: a | b,
    "<<": lambda a, b: a << b,
    ">>": lambda a, b: a >> b,
}

_IV = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: iv.Interval(a.lo * b.lo, a.hi * b.hi),
    "band": lambda a, b: iv.Interval(0, min(a.hi, b.hi)),
    "bor": lambda a, b: a.or_(b),
    "<<": lambda a, b: iv.Interval(a.lo << b.lo, a.hi << b.hi),
    ">>": lambda a, b: iv.Interval(a.lo >> b.hi, a.hi >> b.lo),
}


@dataclasses.dataclass(frozen=True)
class Bin:
    op: str
    a: object
    b: object

    def ev(self, ctx):
        return _EV[self.op](self.a.ev(ctx), self.b.ev(ctx))

    def iv(self, ictx):
        if self.op in ("==", "!=", "<", "<=", ">", ">=", "and", "or"):
            return iv.BOOL
        return _IV[self.op](self.a.iv(ictx), self.b.iv(ictx))


@dataclasses.dataclass(frozen=True)
class Not:
    a: object

    def ev(self, ctx):
        return ~self.a.ev(ctx)

    def iv(self, ictx):
        return iv.BOOL


@dataclasses.dataclass(frozen=True)
class Where:
    c: object
    a: object
    b: object

    def ev(self, ctx):
        return ctx.xp.where(self.c.ev(ctx), self.a.ev(ctx), self.b.ev(ctx))

    def iv(self, ictx):
        return self.a.iv(ictx).join(self.b.iv(ictx))


@dataclasses.dataclass(frozen=True)
class Clip:
    a: object
    lo: object
    hi: object

    def ev(self, ctx):
        return ctx.xp.clip(self.a.ev(ctx), self.lo.ev(ctx), self.hi.ev(ctx))

    def iv(self, ictx):
        a = self.a.iv(ictx)
        lo, hi = self.lo.iv(ictx), self.hi.iv(ictx)
        return iv.Interval(max(a.lo, lo.lo), min(a.hi, hi.hi))


@dataclasses.dataclass(frozen=True)
class MinE:
    a: object
    b: object

    def ev(self, ctx):
        return ctx.xp.minimum(self.a.ev(ctx), self.b.ev(ctx))

    def iv(self, ictx):
        return self.a.iv(ictx).min_(self.b.iv(ictx))


@dataclasses.dataclass(frozen=True)
class MaxE:
    a: object
    b: object

    def ev(self, ctx):
        return ctx.xp.maximum(self.a.ev(ctx), self.b.ev(ctx))

    def iv(self, ictx):
        return self.a.iv(ictx).max_(self.b.iv(ictx))


@dataclasses.dataclass(frozen=True)
class Popcount:
    a: object

    def ev(self, ctx):
        from raft_tla_tpu.ops.kernels import _popcount
        return _popcount(self.a.ev(ctx))

    def iv(self, ictx):
        return iv.Interval(0, max(self.a.iv(ictx).hi.bit_length(), 1))


@dataclasses.dataclass(frozen=True)
class LastTerm:
    """``LastTerm(log[i])`` (raft.tla:102) — a builtin: 0 on an empty
    log, else the stored tail term."""
    i: object

    def ev(self, ctx):
        from raft_tla_tpu.ops.kernels import _last_term
        return _last_term(ctx.s, self.i.ev(ctx))

    def iv(self, ictx):
        return ictx.env["logTerm"].join(0)


@dataclasses.dataclass(frozen=True)
class MsgField:
    """Subfield read of the current ``slot``'s packed message words.

    Abstractly this reads the message envelope: scoped to the enclosing
    branch's ``mtype`` when set, else joined across every record that
    carries the subfield (the UpdateTerm shape).  No carrying record =>
    the branch is infeasible under this envelope."""
    name: str

    def ev(self, ctx):
        from raft_tla_tpu.ops import msgbits as mb
        hi, lo = ctx.msg_words()
        acc = {"mtype": (mb.mtype, 0), "mterm": (mb.mterm, 0),
               "a": (mb.fa, 0), "b": (mb.fb, 0), "src": (mb.src, 0),
               "dst": (mb.dst, 0), "c": (mb.fc, 1), "d": (mb.fd, 1),
               "e": (mb.fe, 1), "f": (mb.ff, 1), "g": (mb.fg, 1)}
        fn, word = acc[self.name]
        return fn(lo if word else hi)

    def iv(self, ictx):
        if ictx.mtype is not None:
            rec = ictx.menv.get(ictx.mtype)
            if rec is None or self.name not in rec:
                raise Infeasible(self.name)
            return rec[self.name]
        vals = [rec[self.name] for rec in ictx.menv.values()
                if self.name in rec]
        if not vals:
            raise Infeasible(self.name)
        out = vals[0]
        for v in vals[1:]:
            out = out.join(v)
        return out


@dataclasses.dataclass(frozen=True)
class Intrinsic:
    """Compiler builtin: an aggregation the scalar IR cannot express,
    with a declared interval transfer (the IR analog of a declared
    relational fact — widthgen uses ``ivfn(bounds, env)`` verbatim)."""
    name: str
    fn: object        # (bounds, s, params, xp) -> value
    ivfn: object      # (bounds, env) -> Interval

    def ev(self, ctx):
        return self.fn(ctx.bounds, ctx.s, ctx.params, ctx.xp)

    def iv(self, ictx):
        return self.ivfn(ictx.bounds, ictx.env)


# ---------------------------------------------------------------------------
# Field updates (array effects; values read the PRE-state, like the
# functional hand kernels)


@dataclasses.dataclass(frozen=True)
class Set1:
    """``field[i] := val`` (optionally only when ``cond``); the hand
    kernels' ``_set1``/conditional-``_set1`` idiom."""
    field: str
    i: object
    val: object
    cond: object = None


@dataclasses.dataclass(frozen=True)
class SetRow:
    """``field[i][*] := val`` — whole row to a scalar (``_set_row``)."""
    field: str
    i: object
    val: object


@dataclasses.dataclass(frozen=True)
class Set2:
    """``field[i][j] := val`` (optionally only when ``cond``) — one cell
    of a 2-D field (``_set2``; the log writes use j = a log index)."""
    field: str
    i: object
    j: object
    val: object
    cond: object = None


# ---------------------------------------------------------------------------
# Bag / message ops (applied after the field updates, in order)


@dataclasses.dataclass(frozen=True)
class PackMsg:
    """One packed-record creation site.  ``fields`` maps msgbits
    subfield names to scalar exprs (missing names pack as 0); ``facts``
    declares relational facts ((name, (bounds, env, menv) -> Interval))
    that join into the message envelope but are not packed — e.g. the
    AppendEntriesRequest ``a+c`` bound; ``overrides`` replaces a
    subfield's *derived* interval with an envelope fact by name (the
    done-reply's ``b`` echoes ``a+c``)."""
    mtype: int
    fields: tuple                 # ((name, Expr), ...)
    facts: tuple = ()             # ((name, fn), ...)
    overrides: tuple = ()         # ((field, fact_name_in_menv), ...)


@dataclasses.dataclass(frozen=True)
class BagAdd:
    msg: PackMsg


@dataclasses.dataclass(frozen=True)
class BagRemove:
    """Remove the current ``slot``'s message (WithoutMessage)."""


@dataclasses.dataclass(frozen=True)
class Reply:
    """Remove the current ``slot``'s message, add the response
    (``kernels.reply``: remove-first, overflow on the final bag)."""
    msg: PackMsg


# ---------------------------------------------------------------------------
# Branches and actions


@dataclasses.dataclass(frozen=True)
class Branch:
    """One guarded alternative.  ``guard=None`` only in single-branch
    actions (updates apply unconditionally; validity masks downstream).
    ``mtype`` scopes MsgField reads for widthgen; ``refines`` declares
    guard-implied envelope refinements ((field, lo, hi) meets — an
    empty meet marks the branch infeasible); ``overflow`` is an extra
    overflow condition OR'd with the branch's bag overflows."""
    guard: object = None
    updates: tuple = ()
    ops: tuple = ()
    overflow: object = None
    mtype: object = None
    refines: tuple = ()


@dataclasses.dataclass(frozen=True)
class ActionDef:
    """One action family: parameter names (kernel argument order),
    validity, and ordered branches (``_tree_select`` order — guards must
    be exclusive).  ``any_guard_valid`` AND-joins ``valid`` with "some
    branch fired" (the Receive shape).  ``param_iv`` declares per-param
    intervals for widthgen ((name, fn(bounds) -> Interval))."""
    family: str
    params: tuple
    valid: object
    branches: tuple
    param_iv: tuple = ()
    any_guard_valid: bool = False

    def __post_init__(self):
        if len(self.branches) > 1:
            for br in self.branches:
                if br.guard is None:
                    raise ValueError(
                        f"{self.family}: multi-branch actions need a "
                        "guard on every branch")
