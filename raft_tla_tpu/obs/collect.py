"""Merge per-process event logs into one clock-aligned timeline.

A traced run leaves a *set* of JSONL logs behind: one per tenant engine
(``{job_id}.events``), one per scheduler process (``sched-{pid}.events``),
plus the supervision logs (``pool.events`` / ``supervisor.events``).
Each process stamped a wall/monotonic anchor pair into its ``run_start``
(obs/trace.clock_anchor), and each span carries a *monotonic* ``t0``
valid only in its own process.  This module is the one place that knows
how to put them all on a single wall-clock axis:

    abs_ts = anchor.wall + (t0 - anchor.mono)

with the alignment error bounded by the recorded ``anchor.err_s`` (the
width of the anchor's wall read).  Logs written without an anchor (pre-v8
producers, or tracing layered onto an untraced resume) degrade to the
span event's own append timestamp: ``abs_ts = ts - dur`` — correct to
within the EventLog queue latency, and flagged in the collection so the
report can say which processes are on the degraded clock.

The collection is a plain dict (processes / spans / instants / counters)
consumed by obs/perfetto.py (Chrome ``trace_event`` export) and by
:func:`report` (wall attribution, device-idle gaps, per-level critical
path) — and by ``raft-tla-monitor``'s directory mode, which reuses
:func:`find_logs` to sweep a fleet.
"""

from __future__ import annotations

import json
import os

# Events rendered as instants on the merged timeline: the lifecycle
# marks worth seeing against the span tracks.
_INSTANTS = frozenset({
    "violation", "stop_requested", "checkpoint", "preempt",
    "resume_attempt", "worker_spawn", "worker_lost", "job_retry",
    "quarantine", "run_end",
})


def find_logs(root: str) -> list:
    """Every ``*.events`` file under ``root`` (sorted; recursive), or
    ``[root]`` itself when it is a file — the fleet sweep used by both
    the trace collector and the monitor's directory mode."""
    if os.path.isfile(root):
        return [root]
    found = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            if fn.endswith(".events"):
                found.append(os.path.join(dirpath, fn))
    return sorted(found)


class LogTail:
    """Incremental JSONL tailer: byte-offset resume, partial-line safe
    (a half-written line stays buffered until its newline lands), and
    truncation-aware — a log rewritten/rotated underneath us (file
    shrank below our offset) resets the tail to the start of the new
    content instead of reading from a stale offset forever.

    Grew up as ``campaign/supervisor._LogTail`` (the health watch);
    now shared with the serve supervision tails and the metrics
    aggregator's per-log reducers (obs/metrics.py), which is why it
    lives here next to :func:`find_logs`.
    """

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._buf = ""

    def seek_end(self) -> None:
        try:
            self._pos = os.path.getsize(self.path)
        except OSError:
            self._pos = 0
        self._buf = ""

    def poll(self) -> list:
        try:
            if os.path.getsize(self.path) < self._pos:
                self._pos = 0            # truncated under us: re-anchor
                self._buf = ""
            with open(self.path, "r", encoding="utf-8") as f:
                f.seek(self._pos)
                chunk = f.read()
                self._pos = f.tell()
        except OSError:
            return []
        if not chunk:
            return []
        self._buf += chunk
        out = []
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue                 # torn line: a crash mid-append
            if isinstance(d, dict):
                out.append(d)
        return out


def _read_events(path: str) -> tuple:
    """(events, n_invalid): parsed JSONL rows with an ``event`` field.

    Validation here is deliberately shallow (is it JSON, is it an event
    dict) — the collector must merge logs from MIXED schema versions
    (a v2 pool.events next to v8 tenant logs), so the strict per-version
    gate of ``validate_event`` is the producer's contract, not the
    reader's.
    """
    events, invalid = [], 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                invalid += 1
                continue
            if not isinstance(d, dict) or "event" not in d:
                invalid += 1
                continue
            events.append(d)
    return events, invalid


def collect(paths: list) -> dict:
    """Merge event logs into one clock-aligned collection.

    Returns::

        {"processes": [{"pid", "os_pid", "label", "log", "engine",
                        "anchored", "skew_bound_s",
                        "threads": [...]}, ...],
         "spans":     [{"pid", "thread", "name", "ts", "dur",
                        "span_id", "parent_id", "args"}, ...],
         "instants":  [{"pid", "name", "ts", "args"}, ...],
         "counters":  [{"pid", "name", "ts", "value"}, ...],
         "levels":    [{"pid", "level", "ts", "n_states"}, ...],
         "t_min", "t_max", "skew_bound_s", "n_invalid", "n_logs"}

    ``ts`` everywhere is absolute wall seconds; ``skew_bound_s`` is the
    worst recorded anchor error across anchored processes (cross-process
    ordering tighter than this is not meaningful).

    Each LOG becomes one timeline row: ``pid`` is a synthetic 1-based
    display id (unique per log — span/parent ids are per-producer, so
    two logs written by the same OS process must not share a rendered
    track space), and ``os_pid`` is the pid the log recorded (None for
    pre-v8 logs).  A serve worker therefore shows as two rows — its
    scheduler (``sched sched-1234.events``) and each tenant engine —
    sharing an ``os_pid``, which the label carries for correlation.
    """
    processes: list = []
    spans: list = []
    instants: list = []
    counters: list = []
    levels: list = []
    n_invalid = 0

    for path in paths:
        events, bad = _read_events(path)
        n_invalid += bad
        if not events:
            continue

        starts = [e for e in events if e["event"] == "run_start"]
        anchor = None
        engine = "?"
        os_pid = None
        for s in starts:
            engine = s.get("engine", engine)
            if s.get("pid") is not None:
                os_pid = int(s["pid"])
            if isinstance(s.get("anchor"), dict):
                anchor = s["anchor"]
        pid = len(processes) + 1
        label = f"{engine} {os.path.basename(path)}"
        if os_pid is not None:
            label += f" (os pid {os_pid})"
        proc = {"pid": pid, "os_pid": os_pid, "label": label,
                "log": path, "engine": engine,
                "anchored": anchor is not None,
                "skew_bound_s": (float(anchor["err_s"])
                                 if anchor else None),
                "threads": []}
        processes.append(proc)
        threads = proc["threads"]

        for e in events:
            ev = e["event"]
            if ev == "span":
                dur = float(e["dur"])
                if anchor is not None:
                    ts = (float(anchor["wall"])
                          + (float(e["t0"]) - float(anchor["mono"])))
                elif e.get("ts") is not None:
                    # degraded clock: the append stamp minus duration
                    ts = float(e["ts"]) - dur
                else:
                    continue  # unplaceable: no anchor, no append stamp
                thread = e.get("thread", "main")
                if thread not in threads:
                    threads.append(thread)
                spans.append({"pid": pid, "thread": thread,
                              "name": e["name"], "ts": ts, "dur": dur,
                              "span_id": e.get("span_id"),
                              "parent_id": e.get("parent_id"),
                              "args": e.get("args") or {}})
            elif ev in _INSTANTS and e.get("ts") is not None:
                args = {k: v for k, v in e.items()
                        if k not in ("v", "event", "ts")}
                instants.append({"pid": pid, "name": ev,
                                 "ts": float(e["ts"]), "args": args})
            elif ev == "segment" and e.get("ts") is not None:
                if e.get("inc_states_per_sec") is not None:
                    counters.append(
                        {"pid": pid, "name": "inc_states_per_sec",
                         "ts": float(e["ts"]),
                         "value": float(e["inc_states_per_sec"])})
            elif ev == "level_end" and e.get("ts") is not None:
                levels.append({"pid": pid, "level": int(e["level"]),
                               "ts": float(e["ts"]),
                               "n_states": int(e["n_states"])})

    stamps = ([s["ts"] for s in spans]
              + [s["ts"] + s["dur"] for s in spans]
              + [i["ts"] for i in instants])
    skews = [p["skew_bound_s"] for p in processes
             if p["skew_bound_s"] is not None]
    return {"processes": processes, "spans": spans,
            "instants": instants, "counters": counters,
            "levels": levels,
            "t_min": min(stamps) if stamps else 0.0,
            "t_max": max(stamps) if stamps else 0.0,
            "skew_bound_s": max(skews) if skews else None,
            "n_invalid": n_invalid, "n_logs": len(paths)}


# --------------------------------------------------------------------------
# analysis (``raft-tla-trace report``)


def _merge_intervals(ivals: list) -> list:
    """Coalesce overlapping (start, end) intervals — overlap-safe wall
    attribution (pipelined dispatch spans may interleave)."""
    out: list = []
    for s, e in sorted(ivals):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out


def _thread_report(tspans: list) -> dict:
    """Attribution for one (process, thread) track.

    Top-level spans (no parent) carve the track's wall into named work
    and the gaps between them; nested spans refine but never double-
    count.  ``attributed_frac`` is the acceptance metric: the share of
    the track's span wall (first start to last end) covered by named
    top-level spans, the remainder being reported as gaps — so
    attributed + gaps == 1.0 by construction, and the interesting
    number is how much of the wall the *named* side claims.
    """
    top = [s for s in tspans if s["parent_id"] is None]
    if not top:
        top = tspans  # manual-span tracks (tickets/workers) have no stack
    t0 = min(s["ts"] for s in top)
    t1 = max(s["ts"] + s["dur"] for s in top)
    wall = max(1e-9, t1 - t0)
    merged = _merge_intervals([[s["ts"], s["ts"] + s["dur"]] for s in top])
    covered = sum(e - s for s, e in merged)
    gaps = []
    prev = t0
    for s, e in merged:
        if s - prev > 0:
            gaps.append({"ts": prev, "dur": s - prev})
        prev = max(prev, e)
    phases: dict = {}
    counts: dict = {}
    for s in top:
        phases[s["name"]] = phases.get(s["name"], 0.0) + s["dur"]
        counts[s["name"]] = counts.get(s["name"], 0) + 1
    return {"wall_s": wall, "t0": t0, "t1": t1,
            "attributed_s": covered,
            "attributed_frac": covered / wall,
            "phases": {k: {"total_s": v, "n": counts[k],
                           "frac": v / wall}
                       for k, v in sorted(phases.items(),
                                          key=lambda kv: -kv[1])},
            "gap_s": wall - covered,
            "gap_frac": (wall - covered) / wall,
            "largest_gaps": sorted(gaps, key=lambda g: -g["dur"])[:5]}


def _level_critical_path(col: dict, proc: dict, threads: dict) -> list:
    """Per-level summary for one process: each level's wall (between
    consecutive ``level_end`` stamps) and its dominant main-track phase
    — the critical-path row the report prints per level."""
    marks = sorted((lv for lv in col["levels"]
                    if lv["pid"] == proc["pid"]),
                   key=lambda lv: lv["ts"])
    if not marks:
        return []
    main = threads.get("MainThread") or threads.get("main")
    tspans = main or []
    out = []
    prev_ts = min((s["ts"] for s in tspans), default=marks[0]["ts"])
    prev_n = 0
    for m in marks:
        window = [s for s in tspans
                  if prev_ts <= s["ts"] < m["ts"]
                  and s["parent_id"] is None]
        acc: dict = {}
        for s in window:
            acc[s["name"]] = acc.get(s["name"], 0.0) + s["dur"]
        dom = max(acc.items(), key=lambda kv: kv[1]) if acc else None
        out.append({"level": m["level"],
                    "wall_s": m["ts"] - prev_ts,
                    "new_states": m["n_states"] - prev_n,
                    "dominant_phase": dom[0] if dom else None,
                    "dominant_s": dom[1] if dom else 0.0})
        prev_ts, prev_n = m["ts"], m["n_states"]
    return out


def report(col: dict) -> dict:
    """Wall attribution over a collection: per process, per thread —
    named-phase totals, idle gaps, and the per-level critical path."""
    by_track: dict = {}
    for s in col["spans"]:
        by_track.setdefault(s["pid"], {}).setdefault(
            s["thread"], []).append(s)
    procs = []
    for proc in col["processes"]:
        threads = by_track.get(proc["pid"], {})
        procs.append({
            "pid": proc["pid"], "os_pid": proc["os_pid"],
            "label": proc["label"],
            "anchored": proc["anchored"],
            "skew_bound_s": proc["skew_bound_s"],
            "threads": {name: _thread_report(tspans)
                        for name, tspans in sorted(threads.items())},
            "levels": _level_critical_path(col, proc, threads),
        })
    return {"processes": procs,
            "t_min": col["t_min"], "t_max": col["t_max"],
            "wall_s": col["t_max"] - col["t_min"],
            "skew_bound_s": col["skew_bound_s"],
            "n_invalid": col["n_invalid"], "n_logs": col["n_logs"]}


def render_report(rep: dict) -> str:
    """The human rendering of :func:`report` (the CLI's default)."""
    lines = [f"trace: {rep['n_logs']} log(s), "
             f"wall {rep['wall_s']:.3f}s"
             + (f", cross-process skew bound "
                f"{rep['skew_bound_s'] * 1e6:.0f}us"
                if rep["skew_bound_s"] is not None else "")
             + (f"  [{rep['n_invalid']} invalid lines]"
                if rep["n_invalid"] else "")]
    for proc in rep["processes"]:
        clock = "" if proc["anchored"] else "  [degraded clock: no anchor]"
        lines.append(f"\n{proc['label']}{clock}")
        for tname, tr in proc["threads"].items():
            lines.append(
                f"  {tname}: {tr['wall_s']:.3f}s wall, "
                f"{100 * tr['attributed_frac']:.1f}% attributed, "
                f"{100 * tr['gap_frac']:.1f}% gaps")
            for pname, ph in tr["phases"].items():
                lines.append(
                    f"    {pname:<14} {ph['total_s']:8.3f}s "
                    f"{100 * ph['frac']:5.1f}%  x{ph['n']}")
            for g in tr["largest_gaps"][:3]:
                lines.append(f"    (gap)          {g['dur']:8.3f}s "
                             f"at +{g['ts'] - rep['t_min']:.3f}s")
        for lv in proc["levels"]:
            dom = (f"{lv['dominant_phase']} {lv['dominant_s']:.3f}s"
                   if lv["dominant_phase"] else "-")
            lines.append(f"  L{lv['level']}: {lv['wall_s']:.3f}s, "
                         f"+{lv['new_states']:,} states, "
                         f"critical: {dom}")
    return "\n".join(lines)
