"""``python -m raft_tla_tpu.obs`` — external event emission + monitor.

``emit`` appends one schema-validated event to a run log from outside
the engine process — campaign_stop.sh stamps ``stop_requested`` this way
before signaling, so the monitor can attribute a clean stop vs a crash
vs a raw SIGINT.  ``monitor`` is an alias for ``raft-tla-monitor``.
"""

from __future__ import annotations

import argparse
import sys

from raft_tla_tpu.obs import events as _events


def _parse_field(kv: str):
    """k=v extra fields; values parse as JSON when possible, else str."""
    import json
    if "=" not in kv:
        raise argparse.ArgumentTypeError(f"expected k=v, got {kv!r}")
    k, v = kv.split("=", 1)
    try:
        return k, json.loads(v)
    except ValueError:
        return k, v


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m raft_tla_tpu.obs")
    sub = p.add_subparsers(dest="cmd", required=True)

    pe = sub.add_parser("emit", help="append one validated event to a log")
    pe.add_argument("path")
    pe.add_argument("event", help="event type (e.g. stop_requested)")
    pe.add_argument("--reason", help="stop_requested reason")
    pe.add_argument("--source", help="who emitted (e.g. campaign_stop.sh)")
    pe.add_argument("--pid", type=int, help="target process id")
    pe.add_argument("--field", action="append", type=_parse_field,
                    default=[], metavar="K=V",
                    help="extra schema field (JSON-parsed when possible)")

    pm = sub.add_parser("monitor", help="alias for raft-tla-monitor")
    pm.add_argument("rest", nargs=argparse.REMAINDER)

    args = p.parse_args(argv)
    if args.cmd == "monitor":
        from raft_tla_tpu.obs import monitor
        return monitor.main(args.rest)

    fields = dict(args.field)
    for k in ("reason", "source", "pid"):
        v = getattr(args, k)
        if v is not None:
            fields[k] = v
    try:
        _events.append_event(args.path, args.event, **fields)
    except ValueError as e:
        print(f"obs emit: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
