"""Cross-process trace spans — the schema-v8 profiling layer.

The obs event logs (events.py) record *what happened* per process; this
module records *where the wall went*: nested, thread-attributed spans
emitted as schema-v8 ``span`` events through the same JSONL sinks, so a
supervised pool run (supervisor + N serve children + per-process
background threads) collects into one merged timeline (obs/collect.py)
that Perfetto can render (obs/perfetto.py) and ``raft-tla-trace report``
can attribute.

Design points, in the order they matter:

- **Off by default, off path unmeasurable.**  Tracing is gated by
  ``--trace`` / ``RAFT_TLA_TRACE``.  Disabled, every instrumentation
  site touches :data:`NULL_TRACER`, whose ``span()`` returns one shared
  stateless handle — no allocation, no clock read, nothing enqueued —
  the same discipline as ``PhaseTimers``'s null handle (A/B'd by the
  ``runs/obs_overhead_ab.py`` protocol; ``bench.py`` pins the per-call
  cost as the ``trace_emit_overhead_us`` fiducial).
- **Monotonic timestamps + a wall anchor.**  Span ``t0`` is
  ``time.monotonic()`` in the emitting process (immune to NTP steps
  mid-run); each process stamps one wall/monotonic :func:`clock_anchor`
  pair into its ``run_start`` so the collector can place every process's
  spans on one wall-clock axis, with the alignment error bounded by the
  recorded ``err_s`` (the width of the anchor's wall read).
- **Thread-aware context.**  Every span records the emitting thread's
  name, and parenthood nests per thread via a thread-local stack — a
  flush running on ``raft-tla-flush`` is attributed to that track, never
  folded into the main thread's phase (the PhaseTimers bug this PR
  fixes).  :meth:`SpanTracer.emit_span` additionally places *manual*
  spans on synthetic tracks (``thread="tickets"``/``"workers"``) for
  lifetimes that start and end in different stack frames (dispatch
  tickets, pool worker lifetimes).
- **One sink, no new I/O machinery.**  Spans ride the existing
  non-blocking ``EventLog`` (engines: ``tracer = SpanTracer(log.emit)``)
  or the synchronous validated ``append_event`` (supervisors, low rate),
  so `tel.active`'s no-listener fast path and the crash-attribution
  contract (log without ``run_end`` = death) are untouched.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

ENV_TRACE = "RAFT_TLA_TRACE"


def trace_enabled(env: str | None = None) -> bool:
    """The ``--trace`` / ``RAFT_TLA_TRACE`` gate (default: off)."""
    v = (env if env is not None
         else os.environ.get(ENV_TRACE, "")).strip().lower()
    return v in ("1", "on", "true", "yes")


def clock_anchor() -> dict:
    """One wall/monotonic pair: ``wall`` was read between two monotonic
    reads whose spread is ``err_s`` — the bound on how precisely this
    process's monotonic span timestamps can be placed on the wall axis
    (plus whatever NTP skew separates the hosts, which no process can
    observe alone)."""
    m1 = time.monotonic()
    wall = time.time()
    m2 = time.monotonic()
    return {"wall": round(wall, 6), "mono": round((m1 + m2) / 2.0, 6),
            "err_s": round(m2 - m1, 6)}


def host_context() -> dict:
    """Best-effort host identity for cross-session trace comparison:
    nproc always; jax version/backend only if jax is already imported
    (never force the import — obs stays light)."""
    import sys
    ctx: dict = {"nproc": os.cpu_count() or 1}
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            ctx["jax"] = str(jax.__version__)
            ctx["backend"] = str(jax.default_backend())
        except Exception:
            pass
    return ctx


class _NullSpan:
    """The disabled-path handle: a shared singleton that does nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args):
        return self


_NULL_SPAN = _NullSpan()


class NullTracer:
    """The disabled tracer: every method is a no-op returning the shared
    null span, so instrumentation sites need no ``if`` guards."""

    __slots__ = ()
    enabled = False

    def span(self, name: str, **args):
        return _NULL_SPAN

    def emit_span(self, name: str, t0: float, dur: float,
                  thread: str | None = None, **args) -> None:
        pass

    def current_id(self):
        return None


NULL_TRACER = NullTracer()


class _Span:
    """An open traced region; emitted as one ``span`` event at exit."""

    __slots__ = ("_tr", "_name", "_args", "_id", "_parent", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict):
        self._tr = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        tr = self._tr
        stack = tr._stack()
        self._parent = stack[-1] if stack else None
        self._id = next(tr._ids)
        stack.append(self._id)
        self._t0 = time.monotonic()
        return self

    def set(self, **args):
        """Attach result attributes discovered inside the region (row
        counts, hit/miss) — lands in the event's ``args`` dict."""
        self._args.update(args)
        return self

    def __exit__(self, *exc):
        dur = time.monotonic() - self._t0
        stack = self._tr._stack()
        if stack and stack[-1] == self._id:
            stack.pop()
        fields = {"name": self._name, "span_id": self._id,
                  "t0": round(self._t0, 6), "dur": round(dur, 6),
                  "thread": threading.current_thread().name}
        if self._parent is not None:
            fields["parent_id"] = self._parent
        if self._args:
            fields["args"] = self._args
        self._tr._emit("span", **fields)
        return False


class SpanTracer:
    """Emit nested, thread-attributed ``span`` events through ``emit``.

    ``emit`` is any ``(event_type, **fields) -> ...`` callable — an
    ``EventLog.emit`` bound method (non-blocking; engines) or a
    ``functools.partial(append_event, path)`` (synchronous + validated;
    supervisors).  Span ids are unique per tracer; parenthood nests via
    a per-thread stack, so concurrent threads trace independently.
    """

    enabled = True

    def __init__(self, emit):
        self._emit = emit
        self._ids = itertools.count(1)   # CPython-atomic __next__
        self._tls = threading.local()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def span(self, name: str, **args) -> _Span:
        """Context manager for a region on the current thread."""
        return _Span(self, name, args)

    def emit_span(self, name: str, t0: float, dur: float,
                  thread: str | None = None, **args) -> None:
        """Manual span for lifetimes that open and close in different
        stack frames (dispatch tickets, worker lifetimes).  ``thread``
        names the track — pass a synthetic one (``"tickets"``) when the
        span overlaps the emitting thread's nested spans, so renderers
        that require proper nesting per track stay happy."""
        fields = {"name": name, "span_id": next(self._ids),
                  "t0": round(t0, 6), "dur": round(max(0.0, dur), 6),
                  "thread": thread or threading.current_thread().name}
        if args:
            fields["args"] = args
        self._emit("span", **fields)

    def current_id(self):
        """Id of the innermost open span on this thread (or None)."""
        st = self._stack()
        return st[-1] if st else None


def tracer_for(log_path: str) -> SpanTracer:
    """A tracer whose spans append synchronously (validated) to
    ``log_path`` — the supervisor-side sink (low event rate)."""
    import functools

    from raft_tla_tpu.obs.events import append_event
    return SpanTracer(functools.partial(append_event, log_path))


def anchored_run_start(log_path: str, engine: str) -> dict:
    """Append the minimal ``run_start`` that makes a supervisor-side log
    (pool.events / supervisor.events / sched-*.events) alignable: the
    clock anchor, host context and pid.  Engine logs get theirs through
    ``RunTelemetry.run_start`` instead."""
    from raft_tla_tpu.obs.events import append_event
    return append_event(log_path, "run_start", engine=engine,
                        universe={}, spec="", invariants=[],
                        resumed=False, pid=os.getpid(),
                        anchor=clock_anchor(), host=host_context())
