"""Cross-run fiducial history + the one drift policy.

The measurement record (RESULTS.md, ``BENCH_r0*.json``, the
``runs/*_ab.py`` harnesses) is the repo's honesty mechanism, but until
now drift detection was manual — a human re-deriving each table — and
the *only* automated comparison lived private to the campaign
supervisor's health watch.  This module makes both into one subsystem:

- :func:`fiducial_drift` — the supervisor's bracketing-fiducial
  comparison, factored out verbatim (one-sided ``current / baseline >
  drift_max`` on the sorted shared keys, :data:`_DRIFT_EXEMPT`
  honored).  ``campaign.supervisor.HealthMonitor`` is now a client.
- :func:`drift_report` — the regress CLI's richer form: every shared
  numeric key, with *rate-type* keys (states/s, orbits/s, warm rates)
  compared inverted (``baseline / current`` — slower is the
  regression) so one tolerance covers both walls and rates.
- :class:`HistoryStore` — an append-only JSONL store of run records
  keyed by a config digest + host context, with per-field median
  baselines.  Records carry the same ``parsed`` payload shape as the
  ``BENCH_r0*.json`` drivers, so the existing bench artifacts are
  ingestible as seed history.

Gate: ``--history`` / ``RAFT_TLA_HISTORY`` (resolved once, in
:func:`history_path`); unset means producers (bench.py) skip the write
— evidence channel, never the verdict.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

ENV_HISTORY = "RAFT_TLA_HISTORY"

# Fiducials excluded from the drift verdict: sub-microsecond timing
# pins (the trace off-path cost) are too noisy for a ratio test — a
# scheduler hiccup would read as 3x "drift" on a number measured in
# tenths of a microsecond.  They are pinned for the A/B record, not as
# a health signal.  (Moved here from campaign/supervisor so the
# supervisor and the regress CLI can never disagree about exemptions.)
_DRIFT_EXEMPT = frozenset({"trace_emit_overhead_us"})

# Keys whose value is a *rate* (bigger is better): the drift ratio is
# inverted so a regression reads > 1 for walls and rates alike.
_RATE_HINTS = ("per_sec", "_rate", "hit_rate")
_RATE_KEYS = frozenset({"value", "vs_baseline"})


def history_path(explicit: str | None = None) -> str | None:
    """The one resolution point for the HISTORY gate: an explicit path
    wins, else ``RAFT_TLA_HISTORY``, else None (no history store)."""
    return explicit or os.environ.get(ENV_HISTORY) or None


def fiducial_drift(baseline: dict, current: dict, drift_max: float,
                   exempt: frozenset = _DRIFT_EXEMPT) -> tuple | None:
    """First offending ``(key, ratio)`` in sorted key order, or None.

    Exactly the supervisor's health-watch semantics: one-sided —
    ``current / baseline > drift_max`` on keys both sides carry, with
    the exempt set removed.  Timing fiducials grow when the machine
    degrades, so only growth is drift here; the regress CLI's
    :func:`drift_report` adds the rate-direction handling.
    """
    if not drift_max or not baseline or not current:
        return None
    for key in sorted(set(baseline) & set(current) - exempt):
        a, b = baseline[key], current[key]
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                and a > 0 and b / a > drift_max:
            return key, b / a
    return None


def _is_rate_key(key: str) -> bool:
    return key in _RATE_KEYS or any(h in key for h in _RATE_HINTS)


def drift_report(baseline: dict, current: dict, drift_max: float,
                 exempt: frozenset = _DRIFT_EXEMPT) -> dict:
    """Every shared numeric key compared against tolerance.

    Returns ``{"ok", "worst": (key, ratio) | None, "keys": {key:
    {"baseline", "current", "ratio", "rate", "drift"}}}`` where
    ``ratio`` is oriented so > 1 is a regression: ``current /
    baseline`` for walls and costs, ``baseline / current`` for
    rate-type keys (:data:`_RATE_HINTS`)."""
    keys: dict = {}
    worst = None
    for key in sorted(set(baseline) & set(current) - exempt):
        a, b = baseline.get(key), current.get(key)
        if not isinstance(a, (int, float)) or isinstance(a, bool) \
                or not isinstance(b, (int, float)) or isinstance(b, bool):
            continue
        rate = _is_rate_key(key)
        num, den = (a, b) if rate else (b, a)
        if den <= 0 or num <= 0:
            continue
        ratio = num / den
        keys[key] = {"baseline": a, "current": b,
                     "ratio": round(ratio, 4), "rate": rate,
                     "drift": bool(drift_max) and ratio > drift_max}
        if worst is None or ratio > worst[1]:
            worst = (key, round(ratio, 4))
    return {"ok": not any(k["drift"] for k in keys.values()),
            "worst": worst, "keys": keys}


# --------------------------------------------------------------------------
# record construction / ingest


def _digest(obj) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True).encode("utf-8")).hexdigest()[:12]


def _numeric(d: dict) -> dict:
    return {k: v for k, v in d.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)}


def bench_record(parsed: dict, meta: dict | None = None,
                 ts: float | None = None) -> dict | None:
    """One history record from a bench ``parsed`` block (the exact
    payload shape bench.py emits and the ``BENCH_r0*.json`` drivers
    recorded).  Keyed by the metric identity (name + unit), so runs of
    a renamed flagship metric never silently compare."""
    if not _numeric(parsed):
        return None
    ident = {"metric": parsed.get("metric"), "unit": parsed.get("unit")}
    return {"kind": "bench", "key": "bench:" + _digest(ident),
            "ts": round(ts if ts is not None else time.time(), 3),
            "parsed": dict(parsed), "meta": dict(meta or {})}


def run_record(events: list, source: str = "") -> dict | None:
    """One history record from a parsed event log: the ``run_start``
    config identity (engine / universe / bounds / spec / invariants /
    symmetry / view / chunk) is the key, the fiducials plus ``run_end``
    summary are the payload."""
    start = next((e for e in events if e.get("event") == "run_start"),
                 None)
    if start is None:
        return None
    ident = {k: start.get(k) for k in
             ("engine", "universe", "bounds", "spec", "invariants",
              "symmetry", "view", "chunk")}
    parsed: dict = {}
    fid = start.get("fiducials")
    if isinstance(fid, dict):
        parsed.update(_numeric(fid))
    end = next((e for e in reversed(events)
                if e.get("event") == "run_end"), None)
    if end is not None:
        for k in ("n_states", "n_transitions", "wall_s"):
            v = end.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                parsed[k] = v
        wall = end.get("wall_s")
        if isinstance(wall, (int, float)) and wall and wall > 0:
            parsed["states_per_sec"] = round(end["n_states"] / wall, 1)
    if not parsed:
        return None
    host = start.get("host") if isinstance(start.get("host"), dict) \
        else None
    ts = end.get("ts") if end is not None else start.get("ts")
    return {"kind": "run", "key": "run:" + _digest(ident),
            "ts": round(float(ts), 3) if isinstance(ts, (int, float))
            else round(time.time(), 3),
            "parsed": parsed,
            "meta": {"source": source, "engine": start.get("engine"),
                     **({"host": host} if host else {})}}


def ingest_file(path: str) -> list:
    """Records from one artifact: a ``BENCH_*.json`` driver file, a raw
    bench ``parsed`` JSON, an ``*.events`` log, or a JSONL of history
    records (re-ingest).  Unknown shapes yield []."""
    records: list = []
    base = os.path.basename(path)
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        mtime = None
    if base.endswith(".events"):
        events = []
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if isinstance(d, dict):
                    events.append(d)
        rec = run_record(events, source=base)
        return [rec] if rec else []
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        if isinstance(doc.get("kind"), str) and "parsed" in doc:
            return [doc]  # already a history record
        if "parsed" in doc:
            # driver shape (BENCH_r0*.json): the payload is "parsed";
            # a null/empty one (a failed round) yields no record
            parsed = doc["parsed"] if isinstance(doc["parsed"], dict) \
                else {}
        else:
            parsed = doc  # raw bench payload (bench.py's stdout line)
        meta = {"source": base}
        for k in ("n", "cmd", "rc"):
            if k in doc:
                meta[k] = doc[k]
        rec = bench_record(parsed, meta=meta, ts=mtime)
        return [rec] if rec else []
    # JSONL of history records
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and isinstance(d.get("kind"), str) \
                and "parsed" in d:
            records.append(d)
    return records


# --------------------------------------------------------------------------
# the store


def _median(xs: list) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


class HistoryStore:
    """Append-only JSONL of history records (one object per line).

    The baseline for a key is the per-field **median** over every
    stored record with that key — robust to one bad run poisoning the
    reference, and exactly the statistic the A/B harnesses report."""

    def __init__(self, path: str):
        self.path = path

    def append(self, record: dict) -> None:
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(record, sort_keys=True) + "\n")

    def load(self) -> list:
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if isinstance(d, dict) and "parsed" in d:
                    out.append(d)
        return out

    def records(self, key: str) -> list:
        return [r for r in self.load() if r.get("key") == key]

    def baseline(self, key: str) -> dict | None:
        """Per-field median over the stored records for ``key``."""
        cols: dict = {}
        for r in self.records(key):
            for k, v in _numeric(r.get("parsed") or {}).items():
                cols.setdefault(k, []).append(v)
        if not cols:
            return None
        return {k: _median(vs) for k, vs in sorted(cols.items())}


def append_bench(parsed: dict, meta: dict | None = None,
                 history: str | None = None) -> str | None:
    """bench.py's hook: write the fiducial block into the history store
    when the HISTORY gate is set; a no-op (returns None) otherwise."""
    path = history_path(history)
    if path is None:
        return None
    rec = bench_record(parsed, meta=meta)
    if rec is None:
        return None
    HistoryStore(path).append(rec)
    return path
