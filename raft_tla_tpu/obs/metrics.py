"""Live metrics layer: streaming reducers over the v-schema event logs.

The repo's acceptance bar for serving (ROADMAP item 7) is *bounded
per-tenant p99 admission-to-result latency* — a percentile, which
nothing in the per-run monitor or the post-hoc trace pipeline computes.
This module closes that gap without touching the engines: everything
here is a **reader** of the event logs the engines already write, so
the check loop's off-path cost is exactly what it was (the
``tel.active`` discipline; A/B'd by ``runs/obs_overhead_ab.py``'s
``events+metrics`` arm).

Three pieces:

- :class:`LogHistogram` — a mergeable log-bucketed histogram (base
  ``2**(1/4)``): bucket ``i`` holds values in ``[g**i, g**(i+1))``, so
  any quantile is answerable from counts alone with relative error
  bounded by ``sqrt(g) - 1`` (~9%), and merging two histograms is
  bucket-count addition — exactly associative, so per-process
  histograms roll up into fleet histograms with no resampling.
- :class:`MetricsRegistry` — a lock-guarded bag of counters, gauges and
  histograms keyed by (name, sorted label pairs), with a flat
  Prometheus-style ``snapshot()`` used both by the OpenMetrics endpoint
  (obs/openmetrics.py) and by the replayable schema-v10
  ``metrics_snapshot`` event.
- :class:`MetricsAggregator` — the streaming reducer: sweeps a
  directory with :func:`obs.collect.find_logs`, tails every log with
  the byte-offset :class:`obs.collect.LogTail` (each ``poll`` reads
  only new bytes), and folds events into the registry —
  ``inc_states_per_sec`` / ``flush_backlog`` / ``upload_wait_ms`` /
  dedup hit rates / per-bin inflight gauges, pool lifecycle counters,
  and the per-tenant admission(``run_start``)→terminal(``run_end``)
  latency histogram behind the p50/p95/p99 summaries.

Gate: ``--metrics-port`` / ``RAFT_TLA_METRICS`` (resolved once, in
:func:`metrics_port`).  Off means none of this is even constructed.
"""

from __future__ import annotations

import math
import os
import threading

from raft_tla_tpu.obs.collect import LogTail, find_logs

ENV_METRICS = "RAFT_TLA_METRICS"

# Bucket base: 2**(1/4).  Quantiles read from geometric bucket midpoints
# are within sqrt(g) - 1 ~ 9.05% of the exact sample quantile.
_GAMMA = 2.0 ** 0.25
_LOG_GAMMA = math.log(_GAMMA)


def metrics_port(explicit: int | None = None) -> int | None:
    """The one resolution point for the METRICS gate: an explicit port
    wins (0 = bind an ephemeral port), else ``RAFT_TLA_METRICS`` parsed
    as a port number, else None (metrics off).  Every consumer (serve,
    campaign) goes through here so the precedence can never fork."""
    if explicit is not None:
        return explicit
    raw = os.environ.get(ENV_METRICS)
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


# --------------------------------------------------------------------------
# mergeable log-bucketed histogram


class LogHistogram:
    """Counts per geometric bucket ``i = floor(log(v) / log(g))``.

    Non-positive observations clamp into the smallest representable
    bucket (latencies of identical timestamps round to 0.0); the exact
    running min/max clamp quantile answers so the edges are exact, and
    a one-sample histogram answers every quantile exactly.
    """

    __slots__ = ("counts", "n", "total", "vmin", "vmax")

    def __init__(self):
        self.counts: dict = {}
        self.n = 0
        self.total = 0.0
        self.vmin: float | None = None
        self.vmax: float | None = None

    def add(self, value: float) -> None:
        v = float(value)
        self.n += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)
        idx = (int(math.floor(math.log(v) / _LOG_GAMMA))
               if v > 0.0 else -4096)
        self.counts[idx] = self.counts.get(idx, 0) + 1

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Bucket-count addition — exactly associative and commutative
        (dict-sum), so fleet roll-ups are order-independent."""
        out = LogHistogram()
        out.n = self.n + other.n
        out.total = self.total + other.total
        mins = [m for m in (self.vmin, other.vmin) if m is not None]
        maxs = [m for m in (self.vmax, other.vmax) if m is not None]
        out.vmin = min(mins) if mins else None
        out.vmax = max(maxs) if maxs else None
        out.counts = dict(self.counts)
        for idx, c in other.counts.items():
            out.counts[idx] = out.counts.get(idx, 0) + c
        return out

    def quantile(self, q: float) -> float | None:
        """The geometric midpoint of the bucket holding the rank-
        ``ceil(q * n)`` observation, clamped to the exact [min, max]."""
        if self.n == 0:
            return None
        rank = max(1, math.ceil(q * self.n))
        seen = 0
        for idx in sorted(self.counts):
            seen += self.counts[idx]
            if seen >= rank:
                mid = _GAMMA ** (idx + 0.5)
                return min(self.vmax, max(self.vmin, mid))
        return self.vmax  # unreachable: counts sum to n

    def to_dict(self) -> dict:
        return {"n": self.n, "sum": self.total,
                "min": self.vmin, "max": self.vmax,
                "counts": {str(i): c for i, c in self.counts.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "LogHistogram":
        h = cls()
        h.n = int(d["n"])
        h.total = float(d["sum"])
        h.vmin = d["min"]
        h.vmax = d["max"]
        h.counts = {int(i): int(c) for i, c in d["counts"].items()}
        return h


# --------------------------------------------------------------------------
# registry


def _labelkey(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _promname(name: str, labels: tuple, extra: tuple = ()) -> str:
    pairs = labels + extra
    if not pairs:
        return name
    body = ",".join(f'{k}="{v}"' for k, v in pairs)
    return f"{name}{{{body}}}"


_QUANTILES = (0.5, 0.95, 0.99)


class MetricsRegistry:
    """Counters, gauges and histograms keyed (name, label pairs).

    Every mutator and reader takes the internal lock — the aggregator
    feeds it from whichever thread polls (the HTTP handler or the
    snapshot loop), and the exposition reads concurrently.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._hists: dict = {}

    def inc(self, name: str, value: float = 1, **labels) -> None:
        key = (name, _labelkey(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        key = (name, _labelkey(labels))
        with self._lock:
            self._gauges[key] = value

    def observe(self, name: str, value: float, **labels) -> None:
        key = (name, _labelkey(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = LogHistogram()
            h.add(value)

    def series(self) -> tuple:
        """(counters, gauges, histograms) — consistent copies for the
        exposition renderer (histograms merged into fresh objects so
        the renderer never races an ``add``)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: LogHistogram().merge(h)
                     for k, h in self._hists.items()}
        return counters, gauges, hists

    def snapshot(self) -> dict:
        """Flat ``{prometheus_series_name: number}`` — the replayable
        payload of the schema-v10 ``metrics_snapshot`` event (summary
        quantiles expanded, exactly what the endpoint exposes)."""
        counters, gauges, hists = self.series()
        out: dict = {}
        for (name, labels), v in sorted(counters.items()):
            out[_promname(name + "_total", labels)] = v
        for (name, labels), v in sorted(gauges.items()):
            out[_promname(name, labels)] = v
        for (name, labels), h in sorted(hists.items()):
            for q in _QUANTILES:
                qv = h.quantile(q)
                if qv is not None:
                    out[_promname(name, labels,
                                  (("quantile", f"{q:g}"),))] = round(qv, 6)
            out[_promname(name + "_count", labels)] = h.n
            out[_promname(name + "_sum", labels)] = round(h.total, 6)
        return out


# --------------------------------------------------------------------------
# streaming reducer over event logs


class MetricsAggregator:
    """Tail every ``*.events`` log under ``root`` and fold new events
    into a :class:`MetricsRegistry`.

    Pull-based: nothing runs between ``poll()`` calls, and each poll
    reads only the bytes appended since the last one (``LogTail``).
    The tenant label is the log's basename (the serve convention:
    ``{job_id}.events``); supervision logs (``pool.events``) feed the
    worker-lifecycle counters under the same rule.
    """

    def __init__(self, root: str, registry: MetricsRegistry | None = None,
                 extra_labels: dict | None = None):
        self.root = root
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._extra = dict(extra_labels or {})
        self._lock = threading.Lock()
        self._tails: dict = {}
        self._admit: dict = {}      # tenant -> run_start ts
        self._live: dict = {}       # tenant -> True while un-ended
        self._workers = 0           # spawned minus lost (pool events)

    def poll(self) -> None:
        with self._lock:
            for path in find_logs(self.root):
                if path not in self._tails:
                    self._tails[path] = LogTail(path)
            for path, tail in self._tails.items():
                tenant = os.path.basename(path)
                if tenant.endswith(".events"):
                    tenant = tenant[:-len(".events")]
                for e in tail.poll():
                    if isinstance(e.get("event"), str):
                        self._reduce(tenant, e)
            reg = self.registry
            depth = sum(1 for live in self._live.values() if live)
            reg.set_gauge("raft_tla_queue_depth", depth, **self._extra)

    # -- one event -> registry mutations ------------------------------------

    def _reduce(self, tenant: str, e: dict) -> None:
        ev = e["event"]
        reg = self.registry
        lbl = dict(self._extra, tenant=tenant)
        reg.inc("raft_tla_events", 1, event=ev, **self._extra)
        if ev == "run_start":
            ts = e.get("ts")
            if isinstance(ts, (int, float)):
                self._admit[tenant] = float(ts)
            self._live[tenant] = True
            reg.inc("raft_tla_runs_started", 1, **lbl)
        elif ev == "segment":
            for field, metric in (
                    ("inc_states_per_sec", "raft_tla_inc_states_per_sec"),
                    ("dedup_hit_rate", "raft_tla_dedup_hit_rate"),
                    ("flush_backlog", "raft_tla_flush_backlog"),
                    ("upload_wait_ms", "raft_tla_upload_wait_ms"),
                    ("n_states", "raft_tla_states")):
                v = e.get(field)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    reg.set_gauge(metric, v, **lbl)
            if isinstance(e.get("inflight"), int):
                reg.set_gauge("raft_tla_inflight", e["inflight"],
                              bin=e.get("bin") or "-", **lbl)
        elif ev == "run_end":
            self._live[tenant] = False
            reg.inc("raft_tla_runs_ended", 1,
                    outcome=str(e.get("outcome", "?")), **lbl)
            ts, t0 = e.get("ts"), self._admit.get(tenant)
            if isinstance(ts, (int, float)) and t0 is not None:
                lat = max(0.0, float(ts) - t0)
                reg.observe("raft_tla_latency_seconds", lat, **lbl)
                reg.observe("raft_tla_latency_seconds", lat, **self._extra)
        elif ev == "worker_spawn":
            self._workers += 1
            reg.inc("raft_tla_workers_spawned", 1, **self._extra)
            reg.set_gauge("raft_tla_workers_live", self._workers,
                          **self._extra)
        elif ev == "worker_lost":
            self._workers -= 1
            reg.inc("raft_tla_workers_lost", 1,
                    kind=str(e.get("kind", "?")), **self._extra)
            reg.set_gauge("raft_tla_workers_live", self._workers,
                          **self._extra)
        elif ev == "job_retry":
            reg.inc("raft_tla_job_retries", 1, **self._extra)
        elif ev == "quarantine":
            reg.inc("raft_tla_quarantines", 1, **self._extra)
        # metrics_snapshot events are deliberately NOT reduced: the
        # aggregator may be tailing its own snapshot log (same root),
        # and folding snapshots back in would be a feedback loop.
