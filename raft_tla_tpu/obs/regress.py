"""``raft-tla-regress`` — the cross-run regression sentinel CLI.

Three subcommands over :mod:`raft_tla_tpu.obs.history`:

``ingest FILES... --history PATH``
    Seed/extend the history store from recorded artifacts:
    ``BENCH_r0*.json`` driver files, raw bench ``parsed`` JSON,
    ``*.events`` logs (``run_start`` fiducials + ``run_end`` summary),
    or a JSONL of history records.

``check FILE --history PATH [--drift-max R]``
    Compare one artifact against the per-field **median** baseline of
    its config key and emit a machine-readable verdict line.  Exit
    codes are the CI contract: 0 within tolerance, 3 no baseline for
    this key, 4 drift.

``ab FILE [--gate R]``
    Verdict an ``runs/*_ab.out`` harness summary directly: every
    ``*_over_off`` key (wall ratio — drift when > gate) and every
    ``on_vs_off_*`` key (rate ratio — drift when < 1/gate) found in
    the file's JSON lines, so the recorded RESULTS.md verdicts (e.g.
    the devdedup 0.44x warm-rate refutation) reproduce mechanically.

The drift policy is the shared one (:func:`obs.history.drift_report`,
``_DRIFT_EXEMPT`` honored) — the same comparison the campaign
supervisor's health watch runs live.
"""

from __future__ import annotations

import argparse
import json
import sys

from raft_tla_tpu.obs.history import (HistoryStore, drift_report,
                                      history_path, ingest_file)

EXIT_OK = 0
EXIT_USAGE = 2
EXIT_NO_BASELINE = 3
EXIT_DRIFT = 4


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="raft-tla-regress",
        description="compare runs against recorded fiducial history")
    sub = p.add_subparsers(dest="cmd", required=True)

    pi = sub.add_parser("ingest", help="seed history from artifacts")
    pi.add_argument("files", nargs="+",
                    help="BENCH_*.json / *.events / record JSONL")
    pi.add_argument("--history", default=None,
                    help="history store path (default: RAFT_TLA_HISTORY)")

    pc = sub.add_parser("check", help="verdict one run vs baseline")
    pc.add_argument("file", help="artifact to check (not appended)")
    pc.add_argument("--history", default=None,
                    help="history store path (default: RAFT_TLA_HISTORY)")
    pc.add_argument("--drift-max", type=float, default=1.5,
                    help="tolerated regression ratio (default 1.5)")
    pc.add_argument("--json", action="store_true",
                    help="include the full per-key table in the verdict")

    pa = sub.add_parser("ab", help="verdict an A/B harness summary")
    pa.add_argument("file", help="runs/*_ab.out summary file")
    pa.add_argument("--gate", type=float, default=1.5,
                    help="tolerated ratio: wall keys drift above this, "
                         "rate keys below its inverse (default 1.5)")
    return p


def _emit(verdict: dict) -> None:
    sys.stdout.write(json.dumps(verdict, sort_keys=True) + "\n")


def _cmd_ingest(args) -> int:
    path = history_path(args.history)
    if path is None:
        sys.stderr.write("regress ingest: no history store "
                         "(--history or RAFT_TLA_HISTORY)\n")
        return EXIT_USAGE
    store = HistoryStore(path)
    n = 0
    for f in args.files:
        for rec in ingest_file(f):
            store.append(rec)
            n += 1
    _emit({"verdict": "ingested", "records": n,
           "files": len(args.files), "history": path})
    return EXIT_OK


def _cmd_check(args) -> int:
    path = history_path(args.history)
    if path is None:
        sys.stderr.write("regress check: no history store "
                         "(--history or RAFT_TLA_HISTORY)\n")
        return EXIT_USAGE
    recs = ingest_file(args.file)
    if not recs:
        sys.stderr.write(f"regress check: no record parseable from "
                         f"{args.file}\n")
        return EXIT_USAGE
    rec = recs[0]
    base = HistoryStore(path).baseline(rec["key"])
    if base is None:
        _emit({"verdict": "no-baseline", "key": rec["key"],
               "file": args.file, "history": path})
        return EXIT_NO_BASELINE
    rep = drift_report(base, rec.get("parsed") or {}, args.drift_max)
    verdict = {"verdict": "ok" if rep["ok"] else "drift",
               "key": rec["key"], "file": args.file,
               "drift_max": args.drift_max, "worst": rep["worst"],
               "n_keys": len(rep["keys"]),
               "drifted": sorted(k for k, v in rep["keys"].items()
                                 if v["drift"])}
    if args.json:
        verdict["keys"] = rep["keys"]
    _emit(verdict)
    return EXIT_OK if rep["ok"] else EXIT_DRIFT


def _walk_ratios(node, path: str, out: dict) -> None:
    if isinstance(node, dict):
        for k, v in node.items():
            sub = f"{path}.{k}" if path else str(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                if k.endswith("_over_off"):
                    out[sub] = ("wall", float(v))
                elif "on_vs_off" in k:
                    out[sub] = ("rate", float(v))
            else:
                _walk_ratios(v, sub, out)
    elif isinstance(node, list):
        for i, v in enumerate(node):
            _walk_ratios(v, f"{path}[{i}]", out)


def _cmd_ab(args) -> int:
    ratios: dict = {}
    with open(args.file, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue
            _walk_ratios(d, "", ratios)
    if not ratios:
        sys.stderr.write(f"regress ab: no *_over_off / on_vs_off_* "
                         f"ratio keys in {args.file}\n")
        return EXIT_USAGE
    keys = {}
    for k, (kind, v) in sorted(ratios.items()):
        # Orient so > 1 is a regression, same convention as check.
        oriented = v if kind == "wall" else (1.0 / v if v > 0
                                             else float("inf"))
        keys[k] = {"kind": kind, "ratio": v,
                   "oriented": round(oriented, 4),
                   "drift": oriented > args.gate}
    drifted = sorted(k for k, v in keys.items() if v["drift"])
    worst = max(keys.items(), key=lambda kv: kv[1]["oriented"])
    _emit({"verdict": "drift" if drifted else "ok", "file": args.file,
           "gate": args.gate, "n_keys": len(keys), "drifted": drifted,
           "worst": [worst[0], worst[1]["oriented"]], "keys": keys})
    return EXIT_DRIFT if drifted else EXIT_OK


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    if args.cmd == "ingest":
        return _cmd_ingest(args)
    if args.cmd == "check":
        return _cmd_check(args)
    return _cmd_ab(args)


def entry() -> None:
    raise SystemExit(main())


if __name__ == "__main__":
    raise SystemExit(main())
