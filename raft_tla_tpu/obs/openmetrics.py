"""OpenMetrics/Prometheus text exposition for the live metrics layer.

Stdlib only: a :class:`~http.server.ThreadingHTTPServer` on a daemon
thread serves ``GET /metrics`` by polling the directory's
:class:`~raft_tla_tpu.obs.metrics.MetricsAggregator` (each scrape reads
only the event-log bytes appended since the previous scrape) and
rendering the registry in the Prometheus text format — ``# TYPE``
headers, ``_total`` counters, plain gauges, and summary series with
``quantile`` labels plus ``_count``/``_sum``.

A second daemon thread (only when ``snapshot_path`` is given) appends a
validated schema-v10 ``metrics_snapshot`` event on a fixed cadence, so
the scrape record is replayable from the event log alone — the fleet
monitor's latency/queue rows come from these snapshots, no endpoint
required.

Nothing here runs inside an engine process's check loop: the server
binds 127.0.0.1 in the *supervising* process (serve daemon, pool,
campaign CLI), and when the ``--metrics-port`` / ``RAFT_TLA_METRICS``
gate is off the server is never constructed at all.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from raft_tla_tpu.obs.events import append_event
from raft_tla_tpu.obs.metrics import (_QUANTILES, MetricsAggregator,
                                      MetricsRegistry, _promname)

_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v) -> str:
    if isinstance(v, float):
        return repr(v)
    return str(v)


def render(registry: MetricsRegistry) -> str:
    """The registry in Prometheus text format, one family at a time:
    counters (``_total`` suffix), gauges, then histogram-backed
    summaries (p50/p95/p99 + ``_count``/``_sum``)."""
    counters, gauges, hists = registry.series()
    lines: list = []

    def series(name, labels, value, extra=()):
        esc = tuple((k, _escape(v)) for k, v in labels + tuple(extra))
        lines.append(f"{_promname(name, esc)} {_fmt(value)}")

    by_name: dict = {}
    for (name, labels), v in sorted(counters.items()):
        by_name.setdefault(name, []).append((labels, v))
    for name, rows in by_name.items():
        lines.append(f"# TYPE {name}_total counter")
        for labels, v in rows:
            series(name + "_total", labels, v)
    by_name = {}
    for (name, labels), v in sorted(gauges.items()):
        by_name.setdefault(name, []).append((labels, v))
    for name, rows in by_name.items():
        lines.append(f"# TYPE {name} gauge")
        for labels, v in rows:
            series(name, labels, v)
    by_name = {}
    for (name, labels), h in sorted(hists.items()):
        by_name.setdefault(name, []).append((labels, h))
    for name, rows in by_name.items():
        lines.append(f"# TYPE {name} summary")
        for labels, h in rows:
            for q in _QUANTILES:
                qv = h.quantile(q)
                if qv is not None:
                    series(name, labels, round(qv, 6),
                           extra=(("quantile", f"{q:g}"),))
            series(name + "_count", labels, h.n)
            series(name + "_sum", labels, round(h.total, 6))
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Bind the endpoint, start the scrape + snapshot threads, expose
    :attr:`port` (the real bound port — pass 0 for an ephemeral one).

    Thread discipline: every shared object (aggregator, registry, the
    stop event) is constructed and published *before* either thread
    starts, and all cross-thread mutation goes through the registry /
    aggregator locks.  ``close`` is idempotent: it stops the snapshot
    loop, takes one final poll + snapshot (so short runs still leave a
    replayable record), and shuts the HTTP server down.
    """

    def __init__(self, root: str, port: int = 0,
                 snapshot_path: str | None = None,
                 interval_s: float = 10.0,
                 labels: dict | None = None):
        self.root = root
        self.snapshot_path = snapshot_path
        self.interval_s = interval_s
        self.aggregator = MetricsAggregator(root, extra_labels=labels)
        self.registry = self.aggregator.registry
        self._stop = threading.Event()
        self._closed = False

        agg = self.aggregator

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                agg.poll()
                body = render(agg.registry).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", _CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes are not stderr news
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-metrics-http",
            daemon=True)
        self._snap_thread = (
            threading.Thread(target=self._snapshot_loop,
                             name="obs-metrics-snapshot", daemon=True)
            if snapshot_path else None)
        self._http_thread.start()
        if self._snap_thread is not None:
            self._snap_thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/metrics"

    def _snapshot_once(self) -> None:
        self.aggregator.poll()
        snap = self.registry.snapshot()
        if not snap:
            return  # nothing observed yet: an empty snapshot says less
        try:
            append_event(self.snapshot_path, "metrics_snapshot",
                         metrics=snap, port=self.port, root=self.root)
        except (OSError, ValueError):
            pass  # evidence channel, never the verdict

    def _snapshot_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._snapshot_once()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self.snapshot_path:
            self._snapshot_once()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._http_thread.join(timeout=10.0)
        if self._snap_thread is not None:
            self._snap_thread.join(timeout=10.0)
