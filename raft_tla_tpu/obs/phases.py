"""Device-sync-aware phase timers — the measurement layer for the
export-anatomy / post-filter-anatomy chip jobs (ROADMAP item 3).

Timing an async-dispatch JAX program phase-by-phase requires a
``block_until_ready`` at each phase edge, which *serialises* the very
pipelining the engines rely on (the ddd engines dispatch segment k+1
before harvesting segment k).  So the timers are **off by default** and
the off path is engineered to be unmeasurable:

- ``phase(name)`` returns one shared, stateless no-op handle when
  disabled — no allocation, no clock read, no sync, nothing for jit to
  see.  An A/B with chip-state fiducials backs this (RESULTS.md).
- enabled (``--phase-timers`` / ``RAFT_TLA_PHASE_TIMERS=1``), each
  ``with timers.phase("expand") as ph: ... ph.sync(out)`` blocks on the
  value handed to ``sync`` before stamping, so the phase wall is honest
  device time, not dispatch time.  Enabling timers trades pipelining for
  attribution — per-phase numbers are for anatomy runs, not records.

Accumulated walls are drained into each ``segment`` event's ``phase_s``
field by :meth:`PhaseTimers.snapshot`.

Phase vocabulary (shared so logs compare across engines): ``upload``
(host->device frontier/block staging), ``expand`` (the jit segment),
``export`` (device->host harvest / pageout), ``dedup`` (host-side exact
dedup flush run inline, ddd only), ``snapshot`` (checkpoint save).
With background host dedup (``RAFT_TLA_HOSTDEDUP``) the ddd engine
splits ``dedup`` into ``dedup_submit`` (sealing + handing the batch to
the depth-1 worker — blocks only while the *previous* flush is still
running, so a nonzero wall here means the device outran the host dedup)
and ``dedup_wait`` (drain at a block/checkpoint/level/stop boundary —
the part of the flush that did NOT overlap device compute), so the
overlap is attributable, not inferred.  With upload prefetch
(``RAFT_TLA_PREFETCH``) the per-block ``dedup_wait`` drain disappears
entirely — block reads rely on the stores' disjoint-range concurrency
contract instead — so ``dedup_wait`` fires only at
checkpoint/level/stop drains (the on/off asymmetry is the gate's
phase-timer signature), and ``upload`` becomes the wait for an
already-staged buffer (a prefetch *hit* costs a swap; a *miss* pays
the old read+pad+h2d inline).  With device dedup
(``RAFT_TLA_DEVDEDUP``) a ``devdedup`` phase covers the per-segment
export-filter dispatch (ops/devdedup) — the on-device set membership
pass that shrinks the subsequent ``export`` wall.

**Thread attribution** (schema v8): phases recorded on a thread other
than the one that built the ``PhaseTimers`` accumulate under
``{name}@{thread-name}`` — a background flush shows up as
``dedup@raft-tla-flush``, never silently folded into (or racing with)
the main thread's bucket.  Accumulation is lock-protected so background
workers (flushq, prefetch) can time their own work.

**Span integration**: when a :class:`~raft_tla_tpu.obs.trace.SpanTracer`
is attached (``timers.tracer``, wired by ``RunTelemetry``), every
enabled phase handle also emits one v8 ``span`` event at exit — the same
named region lands in both the per-segment ``phase_s`` aggregate and the
merged trace timeline.  With tracing on but timers off the handle skips
``sync`` (no ``block_until_ready``), so spans record honest *host-side*
walls — dispatch time, not device time — and the engine pipelining the
timers would serialise stays intact.

This module is host-path orchestration only — nothing here runs under
jit (the no-op handle is what jit-adjacent code touches).
"""

from __future__ import annotations

import os
import threading
import time

ENV_PHASE_TIMERS = "RAFT_TLA_PHASE_TIMERS"


class _NullPhase:
    """The disabled-path handle: a shared singleton that does nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def sync(self, value=None):
        return value


_NULL = _NullPhase()


class _Phase:
    """An enabled timed region; ``sync(x)`` marks x to block on at exit."""

    __slots__ = ("_timers", "_name", "_t0", "_pending", "_span")

    def __init__(self, timers: "PhaseTimers", name: str):
        self._timers = timers
        self._name = name
        self._pending = None
        self._span = None

    def __enter__(self):
        tr = self._timers.tracer
        if tr is not None and tr.enabled:
            self._span = tr.span(self._name).__enter__()
        self._t0 = time.monotonic()
        return self

    def sync(self, value=None):
        self._pending = value
        return value

    def __exit__(self, *exc):
        timers = self._timers
        if timers.enabled and self._pending is not None:
            import jax  # host path; deferred so obs imports stay light
            jax.block_until_ready(self._pending)
        self._pending = None
        if timers.enabled:
            dur = time.monotonic() - self._t0
            name = self._name
            if threading.get_ident() != timers._owner:
                # Explicit background-thread attribution: never race
                # with (or masquerade as) the owning thread's bucket.
                name = f"{name}@{threading.current_thread().name}"
            with timers._lock:
                acc = timers._acc
                acc[name] = acc.get(name, 0.0) + dur
        if self._span is not None:
            # Close after the sync so a timed phase's span covers the
            # same (device-honest) wall the phase_s bucket records.
            self._span.__exit__()
        return False


class PhaseTimers:
    """Per-phase wall-time accumulator; disabled unless asked for.

    ``tracer`` (attached by ``RunTelemetry``) piggybacks v8 trace spans
    on the same phase sites: the handle is live when *either* layer is
    on, but syncs (and accumulates) only when the timers are.
    """

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.tracer = None               # SpanTracer | None (NULL ok)
        self._acc: dict = {}
        self._lock = threading.Lock()
        self._owner = threading.get_ident()

    @classmethod
    def from_env(cls) -> "PhaseTimers":
        return cls(os.environ.get(ENV_PHASE_TIMERS, "").lower()
                   in ("1", "on", "true", "yes"))

    def phase(self, name: str):
        if not self.enabled:
            tr = self.tracer
            if tr is None or not tr.enabled:
                return _NULL
        return _Phase(self, name)

    def snapshot(self, reset: bool = True) -> dict:
        """Drain accumulated per-phase walls (rounded; {} when disabled)."""
        with self._lock:
            if not self._acc:
                return {}
            out = {k: round(v, 4) for k, v in sorted(self._acc.items())}
            if reset:
                self._acc = {}
        return out
