"""Device-sync-aware phase timers — the measurement layer for the
export-anatomy / post-filter-anatomy chip jobs (ROADMAP item 3).

Timing an async-dispatch JAX program phase-by-phase requires a
``block_until_ready`` at each phase edge, which *serialises* the very
pipelining the engines rely on (the ddd engines dispatch segment k+1
before harvesting segment k).  So the timers are **off by default** and
the off path is engineered to be unmeasurable:

- ``phase(name)`` returns one shared, stateless no-op handle when
  disabled — no allocation, no clock read, no sync, nothing for jit to
  see.  An A/B with chip-state fiducials backs this (RESULTS.md).
- enabled (``--phase-timers`` / ``RAFT_TLA_PHASE_TIMERS=1``), each
  ``with timers.phase("expand") as ph: ... ph.sync(out)`` blocks on the
  value handed to ``sync`` before stamping, so the phase wall is honest
  device time, not dispatch time.  Enabling timers trades pipelining for
  attribution — per-phase numbers are for anatomy runs, not records.

Accumulated walls are drained into each ``segment`` event's ``phase_s``
field by :meth:`PhaseTimers.snapshot`.

Phase vocabulary (shared so logs compare across engines): ``upload``
(host->device frontier/block staging), ``expand`` (the jit segment),
``export`` (device->host harvest / pageout), ``dedup`` (host-side exact
dedup flush run inline, ddd only), ``snapshot`` (checkpoint save).
With background host dedup (``RAFT_TLA_HOSTDEDUP``) the ddd engine
splits ``dedup`` into ``dedup_submit`` (sealing + handing the batch to
the depth-1 worker — blocks only while the *previous* flush is still
running, so a nonzero wall here means the device outran the host dedup)
and ``dedup_wait`` (drain at a block/checkpoint/level/stop boundary —
the part of the flush that did NOT overlap device compute), so the
overlap is attributable, not inferred.  With upload prefetch
(``RAFT_TLA_PREFETCH``) the per-block ``dedup_wait`` drain disappears
entirely — block reads rely on the stores' disjoint-range concurrency
contract instead — so ``dedup_wait`` fires only at
checkpoint/level/stop drains (the on/off asymmetry is the gate's
phase-timer signature), and ``upload`` becomes the wait for an
already-staged buffer (a prefetch *hit* costs a swap; a *miss* pays
the old read+pad+h2d inline).

This module is host-path orchestration only — nothing here is ever
traced (the no-op handle is what jit-adjacent code touches).
"""

from __future__ import annotations

import os
import time

ENV_PHASE_TIMERS = "RAFT_TLA_PHASE_TIMERS"


class _NullPhase:
    """The disabled-path handle: a shared singleton that does nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def sync(self, value=None):
        return value


_NULL = _NullPhase()


class _Phase:
    """An enabled timed region; ``sync(x)`` marks x to block on at exit."""

    __slots__ = ("_timers", "_name", "_t0", "_pending")

    def __init__(self, timers: "PhaseTimers", name: str):
        self._timers = timers
        self._name = name
        self._pending = None

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def sync(self, value=None):
        self._pending = value
        return value

    def __exit__(self, *exc):
        if self._pending is not None:
            import jax  # host path; deferred so obs imports stay light
            jax.block_until_ready(self._pending)
            self._pending = None
        acc = self._timers._acc
        acc[self._name] = acc.get(self._name, 0.0) + (
            time.monotonic() - self._t0)
        return False


class PhaseTimers:
    """Per-phase wall-time accumulator; disabled unless asked for."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._acc: dict = {}

    @classmethod
    def from_env(cls) -> "PhaseTimers":
        return cls(os.environ.get(ENV_PHASE_TIMERS, "").lower()
                   in ("1", "on", "true", "yes"))

    def phase(self, name: str):
        if not self.enabled:
            return _NULL
        return _Phase(self, name)

    def snapshot(self, reset: bool = True) -> dict:
        """Drain accumulated per-phase walls (rounded; {} when disabled)."""
        if not self._acc:
            return {}
        out = {k: round(v, 4) for k, v in sorted(self._acc.items())}
        if reset:
            self._acc = {}
        return out
