"""``raft-tla-trace`` — merge, export, and analyze trace collections.

Three subcommands over the logs a ``--trace`` run leaves behind:

- ``collect PATH...`` — merge the logs (files or directories, swept
  recursively for ``*.events``) and print the collection summary: which
  processes were found, whether each is anchored, span/instant counts,
  the cross-process skew bound.
- ``export PATH... -o trace.json`` — the same merge, written as Chrome
  ``trace_event`` JSON for ui.perfetto.dev / chrome://tracing.
- ``report PATH...`` — wall attribution: per process and thread, named-
  phase totals and idle gaps; per level, the critical-path summary.
  ``--json`` prints the machine form.

Typical flow after a traced pool run::

    raft-tla-serve --manifest m.json --pool --workers 2 --trace \\
        --out-dir runs/pool1
    raft-tla-trace export runs/pool1 -o trace.json
    raft-tla-trace report runs/pool1
"""

from __future__ import annotations

import argparse
import json
import sys

from raft_tla_tpu.obs import collect as _collect
from raft_tla_tpu.obs import perfetto as _perfetto


def _gather(paths: list) -> list:
    logs: list = []
    for p in paths:
        logs.extend(_collect.find_logs(p))
    # dedupe, keep order: a dir arg plus an explicit file inside it
    seen: set = set()
    out = []
    for p in logs:
        if p not in seen:
            seen.add(p)
            out.append(p)
    return out


def _summary(col: dict) -> str:
    lines = [f"collected {col['n_logs']} log(s): "
             f"{len(col['spans'])} spans, "
             f"{len(col['instants'])} instants, "
             f"{len(col['counters'])} counter samples"
             + (f", skew bound {col['skew_bound_s'] * 1e6:.0f}us"
                if col["skew_bound_s"] is not None else "")
             + (f"  [{col['n_invalid']} invalid lines]"
                if col["n_invalid"] else "")]
    for proc in col["processes"]:
        n = sum(1 for s in col["spans"] if s["pid"] == proc["pid"])
        clock = "anchored" if proc["anchored"] else "NO ANCHOR"
        lines.append(f"  {proc['label']} ({clock}): {n} spans on "
                     f"{len(proc['threads'])} thread track(s)")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="raft-tla-trace",
        description="Merge --trace event logs into one clock-aligned "
                    "timeline; export to Perfetto or attribute the "
                    "wall.")
    sub = p.add_subparsers(dest="cmd", required=True)

    pc = sub.add_parser("collect",
                        help="merge logs; print the collection summary")
    pc.add_argument("paths", nargs="+", metavar="PATH",
                    help="event logs or directories (swept for "
                         "*.events)")
    pc.add_argument("--json", action="store_true",
                    help="print the full collection as JSON")

    px = sub.add_parser("export",
                        help="write Chrome trace_event JSON "
                             "(ui.perfetto.dev)")
    px.add_argument("paths", nargs="+", metavar="PATH")
    px.add_argument("-o", "--out", default="trace.json",
                    help="output path (default trace.json)")

    pr = sub.add_parser("report",
                        help="wall attribution: phases, gaps, per-level "
                             "critical path")
    pr.add_argument("paths", nargs="+", metavar="PATH")
    pr.add_argument("--json", action="store_true",
                    help="print the machine-readable report")

    args = p.parse_args(argv)
    logs = _gather(args.paths)
    if not logs:
        print("raft-tla-trace: no *.events logs found", file=sys.stderr)
        return 1
    col = _collect.collect(logs)

    if args.cmd == "collect":
        if args.json:
            print(json.dumps(col))
        else:
            print(_summary(col))
        return 0
    if args.cmd == "export":
        n = _perfetto.export(col, args.out)
        print(f"wrote {args.out}: {n} trace events from "
              f"{col['n_logs']} log(s)")
        return 0
    rep = _collect.report(col)
    if args.json:
        print(json.dumps(rep))
    else:
        print(_collect.render_report(rep))
    return 0


def entry() -> None:
    sys.exit(main())


if __name__ == "__main__":
    entry()
