"""obs/ — unified run-event telemetry for every engine family.

One schema, five engine families, three layers:

- ``events``  — the versioned JSONL run-event log (``run_start`` /
  ``segment`` / ``level_end`` / ``checkpoint`` / ``violation`` /
  ``stop_requested`` / ``run_end``), the shared ``ProgressRecord``
  payload that replaced the divergent per-engine ``on_progress`` dicts,
  and the ``RunTelemetry`` facade the engines drive.
- ``phases``  — device-sync-aware phase timers (off by default so the
  engines' async-dispatch pipelining is untouched).
- ``monitor`` — log reader + one-line campaign heartbeat
  (``raft-tla-monitor``); imported lazily so engine processes never pay
  for it.
"""

from raft_tla_tpu.obs.events import (  # noqa: F401
    SCHEMA_VERSION,
    EventLog,
    ProgressRecord,
    ProgressTracker,
    RunTelemetry,
    append_event,
    validate_event,
)
from raft_tla_tpu.obs.phases import PhaseTimers  # noqa: F401
