"""Chrome ``trace_event`` JSON export of a merged trace collection.

The output loads in Perfetto (ui.perfetto.dev) and legacy
``chrome://tracing``: one process row per event log, one track per
recorded thread (engine main thread, ``raft-tla-flush``,
``raft-tla-prefetch``, and the synthetic ``tickets`` / ``workers`` /
``children`` tracks), complete (``X``) events for spans, instant
(``i``) events for lifecycle marks, and counter (``C``) rows for the
per-tenant incremental state rate.

Timestamps are microseconds rebased to the collection's ``t_min`` —
Perfetto renders absolute epoch-µs fine, but rebasing keeps the
numbers readable and the JSON compact.  Thread *names* become stable
synthetic tids (per process, in first-seen order, main-ish tracks
first) because the format wants integers; the ``thread_name`` metadata
rows carry the real names.
"""

from __future__ import annotations

import json


def _us(ts: float, t_min: float) -> float:
    return round((ts - t_min) * 1e6, 1)


def _tid_map(threads: list) -> dict:
    """Thread name -> synthetic tid.  Main thread first (tid 1), then
    the rest in recorded order — stable across exports of one run."""
    names = sorted(threads,
                   key=lambda n: (n not in ("MainThread", "main"),
                                  threads.index(n)))
    return {name: i + 1 for i, name in enumerate(names)}


def to_trace_events(col: dict) -> list:
    """The ``traceEvents`` list for a collection (see module doc)."""
    t_min = col["t_min"]
    out: list = []
    tids: dict = {}
    for proc in col["processes"]:
        pid = proc["pid"]
        out.append({"ph": "M", "name": "process_name", "pid": pid,
                    "args": {"name": proc["label"]}})
        tmap = _tid_map(proc["threads"])
        tids[pid] = tmap
        for tname, tid in sorted(tmap.items(), key=lambda kv: kv[1]):
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "args": {"name": tname}})
    for s in col["spans"]:
        tid = tids.get(s["pid"], {}).get(s["thread"], 0)
        ev = {"ph": "X", "name": s["name"], "pid": s["pid"],
              "tid": tid, "ts": _us(s["ts"], t_min),
              "dur": round(s["dur"] * 1e6, 1), "cat": "span"}
        if s["args"]:
            ev["args"] = s["args"]
        out.append(ev)
    for i in col["instants"]:
        out.append({"ph": "i", "name": i["name"], "pid": i["pid"],
                    "tid": 0, "ts": _us(i["ts"], t_min), "s": "p",
                    "cat": "lifecycle", "args": i["args"]})
    for c in col["counters"]:
        out.append({"ph": "C", "name": c["name"], "pid": c["pid"],
                    "tid": 0, "ts": _us(c["ts"], t_min),
                    "args": {c["name"]: c["value"]}})
    return out


def export(col: dict, path: str) -> int:
    """Write the collection as Chrome trace JSON; returns the event
    count.  ``displayTimeUnit: ms`` suits model-checker span scales."""
    events = to_trace_events(col)
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w") as fh:
        json.dump(doc, fh, separators=(",", ":"))
        fh.write("\n")
    return len(events)
