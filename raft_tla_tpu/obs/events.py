"""Versioned JSONL run-event telemetry — the one progress schema every
engine family emits.

Before this module each engine family grew its own ad-hoc ``on_progress``
dict (device/paged's ``_progress_stats``, streamed's method of the same
name, the shard engines' ``n_devices`` variant, and the ddd engines'
``progress()`` closures with their incremental-rate anchors).  Campaign
state then lived in hand-rolled ``runs/*.stats`` streams plus an
undocumented ``.telemetry`` column format, and a resumed run's cumulative
``states_per_sec`` silently inflated (prior-process states / this-process
wall).  This module replaces all of that with:

- :class:`ProgressRecord` — one dataclass carrying cumulative counters
  *and* incremental (honest-rate) counters, plus the dedup-hit-rate and
  route-peak fields the ddd engines already computed.
- :class:`ProgressTracker` — the rate/anchor arithmetic in one place:
  ``inc_states_per_sec`` is primary (delta since the last record, immune
  to resume inflation); cumulative fields are tagged ``since_resume``
  (False = the counters span prior processes, so cumulative rates mix
  prior-process states with this-process wall and are NOT trustworthy).
- :class:`EventLog` — a non-blocking buffered JSONL writer (background
  thread; ``emit`` never blocks the check loop).
- :class:`RunTelemetry` — the facade engines drive: ``run_start`` /
  ``segment`` / ``checkpoint`` / ``stop_requested`` / ``run_end``, with
  ``level_end`` derived automatically from level transitions and
  ``violation`` derived from the final :class:`~raft_tla_tpu.engine.EngineResult`.

Event grammar (``SCHEMA_VERSION`` = 10; earlier-version lines remain
valid) —
every line is one JSON object with base fields ``v`` (schema version),
``event`` (type) and ``ts`` (unix epoch seconds):

``run_start``      engine, universe, spec, invariants, resumed
                   [+ bounds, symmetry, view, chunk, caps, n_states,
                      n_devices, git_sha, fiducials, pid]
``segment``        the ProgressRecord fields (below)
``level_end``      level, n_states           (as observed at a boundary)
``checkpoint``     path [+ n_states]
``violation``      invariant [+ kind]
``stop_requested`` reason [+ source, pid]    (clean stop vs crash vs abort)
``run_end``        n_states, n_transitions, complete, outcome
                   [+ diameter, levels, wall_s]

Version 2 adds the campaign-supervisor lifecycle (emitted by
``raft_tla_tpu/campaign``, never by the engines themselves):

``preempt``        reason [+ detail, pid, stale_s, drift]
                   (the supervisor declared the child unhealthy / got a
                    preemption signal and is driving the lossless stop)
``reshard``        ndev_src, ndev_dst [+ n_states, path, block]
``resume_attempt`` attempt [+ path, ndev, backoff_s, quarantined]

Version 3 adds the statistical-checking (walker fleet) fields — both
optional, both invalid on a ``"v" < 3`` line:

``segment.device_rates``   per-device walker states/s for the segment
                           (fleet runs; list of numbers, mesh order)
``run_end.sim``            confidence summary for simulation runs:
                           behaviors / sampled_transitions / max_depth /
                           walkers / n_devices / coverage_entropy /
                           steer_tau / per_invariant states-checked —
                           what a statistical run actually established,
                           next to the exhaustive engines' proofs

Version 4 adds the serve-scheduler attribution fields — both optional,
both invalid on a ``"v" < 4`` line:

``segment.bin``            the step-signature bin tag of a serve lane's
                           dispatch stream, so the monitor can attribute
                           device time per compiled bin
``segment.inflight``       async-scheduler dispatches in flight when the
                           segment boundary was observed (0 = the lane
                           ran synchronously)

Version 5 adds the ddd background host-dedup attribution field —
optional, invalid on a ``"v" < 5`` line:

``segment.flush_backlog``  sealed dedup flushes pending/in flight on the
                           background worker when the segment boundary
                           was observed (0/1 — the worker is depth-1
                           ordered; absent = synchronous host dedup)

Version 6 adds the ddd upload-prefetch attribution fields — both
optional, both invalid on a ``"v" < 6`` line:

``segment.upload_wait_ms`` cumulative main-thread wall spent waiting in
                           the upload phase for a staged block (hits)
                           or loading one inline (misses); absent =
                           prefetch gate off
``segment.prefetch_hits``  block uploads served from an already-staged
                           buffer since the run started (misses =
                           blocks - hits; the in-engine warm rate
                           runs/prefetch_ab.py reports)

Version 7 adds the serve worker-pool supervision lifecycle (emitted by
``raft_tla_tpu/serve/pool``, never by the engines themselves) — all four
event types invalid on a ``"v" < 7`` line:

``worker_spawn``   worker, pid [+ jobs, bins, chunk, respawn, attempt]
                   (a pool worker child came up, with its job assignment
                    and the dispatch width it was granted)
``worker_lost``    worker, kind [+ pid, exit_code, jobs, detail]
                   (the pool reaped a dead/preempted worker; ``kind`` is
                    the death classification: killed / segfault / oom /
                    signal / crashed / heartbeat-stale / session-wall)
``job_retry``      job_id, attempt [+ worker, backoff_s, reason]
                   (a surviving job was requeued to a fresh worker)
``quarantine``     job_id, reason [+ deaths, worker, detail]
                   (poison verdict: the job killed its worker K times
                    and will never be executed again)

Version 8 adds the cross-process tracing layer (obs/trace.py — gated by
``--trace`` / ``RAFT_TLA_TRACE``, never on by default):

``span``           name, span_id, t0, dur, thread [+ parent_id, args]
                   (one completed traced region: ``t0`` is
                    ``time.monotonic()`` in the emitting process and
                    ``dur`` seconds; ``thread`` the emitting thread's
                    name or a synthetic track like ``"tickets"``;
                    ``parent_id`` nests spans per thread)
``run_start.anchor``  wall/mono/err_s clock-anchor pair — the emitting
                   process's ``time.time()`` read bracketed by two
                   ``time.monotonic()`` reads, so the trace collector
                   (obs/collect.py) can place monotonic span timestamps
                   from many processes on one wall axis with a recorded
                   error bound
``run_start.host`` host context for cross-session comparison (nproc,
                   jax version, backend)

Version 9 adds ddd device-dedup attribution (ops/devdedup — gated by
``--device-dedup`` / ``RAFT_TLA_DEVDEDUP``): segment ``export_rows``
(cumulative rows actually exported d2h, post-filter; emitted by the DDD
engines regardless of the gate so A/B off arms stay comparable) and
``dev_dedup_hits`` (cumulative rows the device set dropped pre-export;
only present when the gate is on).

Version 10 adds the live metrics layer (obs/metrics.py — gated by
``--metrics-port`` / ``RAFT_TLA_METRICS``, never on by default):

``metrics_snapshot``  metrics [+ port, root]
                   (one periodic snapshot of the streaming-reducer
                    registry: a flat ``{prometheus_name: value}`` dict
                    — counters, gauges, and the per-tenant latency
                    histogram quantiles the OpenMetrics endpoint
                    exposes — so the scrape record is replayable from
                    the event log alone; ``port`` the bound endpoint
                    port, ``root`` the swept log directory)

A run log with no ``run_end`` means the process died — crash attribution
for free.  The schema is strict: unknown fields fail validation and the
v2/v7/v8/v10-only event types (resp. v3/v4/v5/v6/v8/v9-only fields) are
invalid on a ``"v" < 2`` / ``"v" < 7`` / ``"v" < 8`` / ``"v" < 10``
(resp. ``"v" < 3`` / ``"v" < 4`` / ``"v" < 5`` / ``"v" < 6`` /
``"v" < 8`` / ``"v" < 9``) line, so any addition requires a version
bump (versioning policy in README.md).
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import subprocess
import threading
import time

SCHEMA_VERSION = 10
_VERSIONS = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10)  # versions validate_event accepts

# Environment knobs (set by check.py --events/--phase-timers; inherited by
# liveness re-runs and bench children the same way RAFT_TLA_SIGPRUNE is).
ENV_EVENTS = "RAFT_TLA_EVENTS"


def events_path(explicit: str | None = None) -> str | None:
    """The one resolution point for the EVENTS gate: an explicit path
    wins, else ``RAFT_TLA_EVENTS``, else None (telemetry off).  Every
    consumer (RunTelemetry, check.py's --trace validation) goes through
    here so the precedence can never fork."""
    return explicit or os.environ.get(ENV_EVENTS) or None


_DEADLOCK_NAME = "Deadlock"  # engine.DEADLOCK's invariant name (avoid import)


# --------------------------------------------------------------------------
# schema validation


def _is(value, spec) -> bool:
    """Type check where bool is NOT an int (JSON booleans are not counts)."""
    if spec is int:
        return type(value) is int
    if spec is _NUM:
        return type(value) in (int, float)
    if isinstance(spec, tuple):
        return any(_is(value, s) for s in spec)
    return isinstance(value, spec)


class _NUM:  # sentinel: int or float, not bool
    pass


_BASE = {"v": int, "event": str, "ts": _NUM}

_SEGMENT_REQUIRED = {
    "wall_s": _NUM,
    "n_states": int,
    "level": int,
    "n_transitions": int,
    "dedup_hit_rate": _NUM,
    "states_per_sec": _NUM,
    "inc_states_per_sec": _NUM,
    "since_resume": bool,
}

_REQUIRED = {
    "run_start": {"engine": str, "universe": dict, "spec": str,
                  "invariants": list, "resumed": bool},
    "segment": _SEGMENT_REQUIRED,
    "level_end": {"level": int, "n_states": int},
    "checkpoint": {"path": str},
    "violation": {"invariant": str},
    "stop_requested": {"reason": str},
    "run_end": {"n_states": int, "n_transitions": int, "complete": bool,
                "outcome": str},
    "preempt": {"reason": str},
    "reshard": {"ndev_src": int, "ndev_dst": int},
    "resume_attempt": {"attempt": int},
    "worker_spawn": {"worker": str, "pid": int},
    "worker_lost": {"worker": str, "kind": str},
    "job_retry": {"job_id": str, "attempt": int},
    "quarantine": {"job_id": str, "reason": str},
    "span": {"name": str, "span_id": int, "t0": _NUM, "dur": _NUM,
             "thread": str},
    "metrics_snapshot": {"metrics": dict},
}

# Event types that only exist from schema version 2 on (the campaign
# supervisor lifecycle) — invalid on a "v": 1 line.
_V2_EVENTS = frozenset({"preempt", "reshard", "resume_attempt"})

# Event types that only exist from schema version 7 on (the serve
# worker-pool supervision lifecycle) — invalid on a "v" < 7 line.
_V7_EVENTS = frozenset({"worker_spawn", "worker_lost", "job_retry",
                        "quarantine"})

# Event types that only exist from schema version 8 on (the cross-process
# tracing layer, obs/trace.py) — invalid on a "v" < 8 line.
_V8_EVENTS = frozenset({"span"})

# Event types that only exist from schema version 10 on (the live
# metrics layer, obs/metrics.py) — invalid on a "v" < 10 line.
_V10_EVENTS = frozenset({"metrics_snapshot"})

# Fields that only exist from schema version 3 on (walker-fleet
# statistical checking) — invalid on a "v" < 3 line.
_V3_FIELDS = {"segment": frozenset({"device_rates"}),
              "run_end": frozenset({"sim"})}

# Fields that only exist from schema version 4 on (serve async-scheduler
# per-bin attribution) — invalid on a "v" < 4 line.
_V4_FIELDS = {"segment": frozenset({"bin", "inflight"})}

# Fields that only exist from schema version 5 on (ddd background
# host-dedup attribution) — invalid on a "v" < 5 line.
_V5_FIELDS = {"segment": frozenset({"flush_backlog"})}

# Fields that only exist from schema version 6 on (ddd upload-prefetch
# attribution) — invalid on a "v" < 6 line.
_V6_FIELDS = {"segment": frozenset({"upload_wait_ms", "prefetch_hits"})}

# Fields that only exist from schema version 8 on (trace clock anchors
# and host context) — invalid on a "v" < 8 line.
_V8_FIELDS = {"run_start": frozenset({"anchor", "host"})}

# Fields that only exist from schema version 9 on (ddd device-dedup
# attribution) — invalid on a "v" < 9 line.
_V9_FIELDS = {"segment": frozenset({"export_rows", "dev_dedup_hits"})}

_OPTIONAL = {
    "run_start": {"bounds": dict, "symmetry": list, "view": str,
                  "chunk": int, "caps": str, "n_states": int,
                  "n_devices": int, "git_sha": str, "fiducials": dict,
                  "pid": int, "anchor": dict, "host": dict},
    "segment": {"coverage": dict, "route_peak": int, "n_devices": int,
                "inv_evals": dict, "phase_s": dict, "device_rates": list,
                "bin": str, "inflight": int, "flush_backlog": int,
                "upload_wait_ms": _NUM, "prefetch_hits": int,
                "export_rows": int, "dev_dedup_hits": int},
    "level_end": {},
    "checkpoint": {"n_states": int},
    "violation": {"kind": str},
    "stop_requested": {"source": str, "pid": int},
    "run_end": {"diameter": int, "levels": list, "wall_s": _NUM,
                "sim": dict},
    "preempt": {"detail": str, "pid": int, "stale_s": _NUM,
                "drift": dict},
    "reshard": {"n_states": int, "path": str, "block": int},
    "resume_attempt": {"path": str, "ndev": int, "backoff_s": _NUM,
                       "quarantined": str},
    "worker_spawn": {"jobs": list, "bins": int, "chunk": int,
                     "respawn": bool, "attempt": int},
    "worker_lost": {"pid": int, "exit_code": int, "jobs": list,
                    "detail": str},
    "job_retry": {"worker": str, "backoff_s": _NUM, "reason": str},
    "quarantine": {"deaths": int, "worker": str, "detail": str},
    "span": {"parent_id": int, "args": dict},
    "metrics_snapshot": {"port": int, "root": str},
}


def validate_event(d: dict) -> list:
    """Return the list of schema violations in ``d`` ([] = valid).

    Strict by design: unknown event types and unknown fields are errors,
    so schema drift between engines is caught by the conformance test
    instead of accumulating silently (the pre-obs failure mode).
    """
    errs = []
    if not isinstance(d, dict):
        return [f"not an object: {type(d).__name__}"]
    for k, spec in _BASE.items():
        if k not in d:
            errs.append(f"missing base field {k!r}")
        elif not _is(d[k], spec):
            errs.append(f"base field {k!r} has wrong type")
    if errs:
        return errs
    if d["v"] not in _VERSIONS:
        errs.append(f"schema version {d['v']} not in {list(_VERSIONS)}")
    ev = d["event"]
    if ev not in _REQUIRED:
        return errs + [f"unknown event type {ev!r}"]
    if ev in _V2_EVENTS and d["v"] in _VERSIONS and d["v"] < 2:
        errs.append(f"{ev}: event type requires schema version >= 2")
    if ev in _V7_EVENTS and d["v"] in _VERSIONS and d["v"] < 7:
        errs.append(f"{ev}: event type requires schema version >= 7")
    if ev in _V8_EVENTS and d["v"] in _VERSIONS and d["v"] < 8:
        errs.append(f"{ev}: event type requires schema version >= 8")
    if ev in _V10_EVENTS and d["v"] in _VERSIONS and d["v"] < 10:
        errs.append(f"{ev}: event type requires schema version >= 10")
    req, opt = _REQUIRED[ev], _OPTIONAL[ev]
    for k, spec in req.items():
        if k not in d:
            errs.append(f"{ev}: missing required field {k!r}")
        elif not _is(d[k], spec):
            errs.append(f"{ev}: field {k!r} has wrong type")
    v3_only = _V3_FIELDS.get(ev, frozenset())
    v4_only = _V4_FIELDS.get(ev, frozenset())
    v5_only = _V5_FIELDS.get(ev, frozenset())
    v6_only = _V6_FIELDS.get(ev, frozenset())
    v8_only = _V8_FIELDS.get(ev, frozenset())
    v9_only = _V9_FIELDS.get(ev, frozenset())
    for k, val in d.items():
        if k in _BASE or k in req:
            continue
        if k not in opt:
            errs.append(f"{ev}: unknown field {k!r} (schema is strict; "
                        "additions need a version bump)")
        elif not _is(val, opt[k]):
            errs.append(f"{ev}: field {k!r} has wrong type")
        elif k in v3_only and d["v"] in _VERSIONS and d["v"] < 3:
            errs.append(f"{ev}: field {k!r} requires schema version >= 3")
        elif k in v4_only and d["v"] in _VERSIONS and d["v"] < 4:
            errs.append(f"{ev}: field {k!r} requires schema version >= 4")
        elif k in v5_only and d["v"] in _VERSIONS and d["v"] < 5:
            errs.append(f"{ev}: field {k!r} requires schema version >= 5")
        elif k in v6_only and d["v"] in _VERSIONS and d["v"] < 6:
            errs.append(f"{ev}: field {k!r} requires schema version >= 6")
        elif k in v8_only and d["v"] in _VERSIONS and d["v"] < 8:
            errs.append(f"{ev}: field {k!r} requires schema version >= 8")
        elif k in v9_only and d["v"] in _VERSIONS and d["v"] < 9:
            errs.append(f"{ev}: field {k!r} requires schema version >= 9")
    return errs


# --------------------------------------------------------------------------
# progress schema


@dataclasses.dataclass
class ProgressRecord:
    """The shared ``segment`` payload — what every engine's ``on_progress``
    callback now receives (as a plain dict, via :meth:`to_dict`).

    ``inc_states_per_sec`` is the primary rate: states discovered since
    the previous record over wall time since the previous record.  It is
    immune to the resume-inflation wart (ddd campaigns resume with the
    prior process's ``n_states`` but a fresh wall clock).  The cumulative
    ``states_per_sec`` is kept for quick glances and tagged by
    ``since_resume``: True means the counters were accumulated entirely
    by this process and the cumulative rate is honest; False means they
    span prior processes and only the incremental rate is trustworthy.
    """

    wall_s: float
    n_states: int
    level: int
    n_transitions: int
    dedup_hit_rate: float
    states_per_sec: float
    inc_states_per_sec: float
    since_resume: bool
    coverage: dict | None = None      # per-action discovery counts
    route_peak: int | None = None     # ddd: peak per-bucket route occupancy
    n_devices: int | None = None      # shard engines: mesh size
    inv_evals: dict | None = None     # per-invariant evaluation counts
    phase_s: dict | None = None       # per-phase wall since last record
    device_rates: list | None = None  # fleet: per-device walker states/s
    bin: str | None = None            # serve: step-signature bin tag
    inflight: int | None = None       # serve: dispatches in flight
    flush_backlog: int | None = None  # ddd: background flushes pending
    upload_wait_ms: float | None = None  # ddd: cumulative upload wait
    prefetch_hits: int | None = None  # ddd: staged-buffer block uploads
    export_rows: int | None = None    # ddd: cumulative d2h export rows
    dev_dedup_hits: int | None = None  # ddd: device-set pre-export drops

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}


class ProgressTracker:
    """Rate arithmetic shared by every engine (formerly five copies).

    ``n0`` is the state count already present when this process started:
    1 for a fresh run, the checkpoint's count for a ddd resume, or None
    when the baseline is unknown until the first device fetch (table
    engines resuming a donated carry) — the first record then just
    anchors and reports a zero incremental rate rather than a fabricated
    one.

    ``record(n_incl=...)`` takes the *inclusive* count (states + pending
    keys awaiting host dedup) the ddd engines report; the anchor is
    ``max`` -monotone across checkpoint-rollback resumes so incremental
    rates never go negative — the logic that used to live in
    ddd_engine's ``prev`` dict.
    """

    def __init__(self, t0: float, n0: int | None = 1,
                 invariants: tuple = (), resumed: bool = False,
                 n_devices: int | None = None):
        self.t0 = t0
        self._prev_wall = 0.0
        self._prev_n = n0
        self.invariants = tuple(invariants)
        self.since_resume = not resumed
        self.n_devices = n_devices

    def anchor(self, n_states: int) -> None:
        """Set the incremental-rate baseline (a resume's restored count),
        so the first post-resume record's rate covers only new states."""
        self._prev_n = max(self._prev_n or 0, int(n_states))

    def record(self, n_states: int, level: int, n_transitions: int,
               coverage: dict | None = None, route_peak: int | None = None,
               n_incl: int | None = None,
               phase_s: dict | None = None,
               device_rates: list | None = None,
               bin: str | None = None,
               inflight: int | None = None,
               flush_backlog: int | None = None,
               upload_wait_ms: float | None = None,
               prefetch_hits: int | None = None,
               export_rows: int | None = None,
               dev_dedup_hits: int | None = None) -> ProgressRecord:
        wall = time.monotonic() - self.t0
        reported = n_states if n_incl is None else max(n_states, n_incl)
        if self._prev_n is None:  # unknown baseline: anchor, rate 0
            self._prev_n = reported
        dn = max(0, reported - self._prev_n)
        dt = wall - self._prev_wall
        inc = dn / dt if dt > 0 else 0.0
        self._prev_wall = wall
        self._prev_n = max(self._prev_n, reported)
        # Dedup hit rate uses the *exact* count: candidates generated vs
        # distinct states actually admitted.
        hit = 1.0 - n_states / max(1, n_transitions)
        inv_evals = ({nm: n_transitions for nm in self.invariants}
                     if self.invariants else None)
        return ProgressRecord(
            wall_s=round(wall, 3),
            n_states=reported,
            level=level,
            n_transitions=n_transitions,
            dedup_hit_rate=round(hit, 4),
            states_per_sec=round(reported / max(wall, 1e-9), 1),
            inc_states_per_sec=round(inc, 1),
            since_resume=self.since_resume,
            coverage=coverage,
            route_peak=route_peak,
            n_devices=self.n_devices,
            inv_evals=inv_evals,
            phase_s=phase_s or None,
            device_rates=device_rates,
            bin=bin,
            inflight=inflight,
            flush_backlog=flush_backlog,
            upload_wait_ms=upload_wait_ms,
            prefetch_hits=prefetch_hits,
            export_rows=export_rows,
            dev_dedup_hits=dev_dedup_hits,
        )


# --------------------------------------------------------------------------
# JSONL writer


_CLOSE = object()  # writer-thread sentinel


class EventLog:
    """Append-only JSONL event sink with a background writer thread.

    ``emit`` serialises on the caller (cheap: small dicts) and enqueues;
    file I/O happens on the daemon thread so a slow disk never stalls a
    segment boundary.  ``close`` drains the queue and joins.  The file is
    opened in append mode line-at-a-time-ish, so external one-shot
    emitters (``python -m raft_tla_tpu.obs emit`` from campaign_stop.sh)
    can interleave whole lines with a live run.
    """

    def __init__(self, path: str):
        self.path = path
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._writer, name="obs-eventlog", daemon=True)
        self._closed = False
        self._thread.start()

    def emit(self, event: str, **fields) -> dict:
        d = {"v": SCHEMA_VERSION, "event": event,
             "ts": round(time.time(), 3), **fields}
        if not self._closed:
            self._q.put(json.dumps(d, sort_keys=False) + "\n")
        return d

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(_CLOSE)
        self._thread.join(timeout=10.0)

    def _writer(self) -> None:
        with open(self.path, "a") as fh:
            while True:
                item = self._q.get()
                if item is _CLOSE:
                    break
                lines = [item]
                try:  # batch whatever queued up behind it
                    while True:
                        nxt = self._q.get_nowait()
                        if nxt is _CLOSE:
                            fh.writelines(lines)
                            return
                        lines.append(nxt)
                except queue.Empty:
                    pass
                fh.writelines(lines)
                fh.flush()


def append_event(log_path: str, event: str, **fields) -> dict:
    """Synchronously validate + append one event (external emitters:
    bench.py's fiducial ``run_start``, the ``obs emit`` CLI).

    First parameter named ``log_path`` so ``checkpoint`` events can pass
    their ``path`` field as a keyword.
    """
    d = {"v": SCHEMA_VERSION, "event": event,
         "ts": round(time.time(), 3), **fields}
    errs = validate_event(d)
    if errs:
        raise ValueError(f"invalid {event!r} event: " + "; ".join(errs))
    with open(log_path, "a") as fh:
        fh.write(json.dumps(d) + "\n")
    return d


_GIT_SHA_CACHE: list = []


def git_sha() -> str | None:
    """Short commit sha of the checkout (best-effort, cached)."""
    if not _GIT_SHA_CACHE:
        try:
            out = subprocess.run(
                ["git", "rev-parse", "--short=12", "HEAD"],
                cwd=os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))),
                capture_output=True, text=True, timeout=5)
            sha = out.stdout.strip() if out.returncode == 0 else ""
            _GIT_SHA_CACHE.append(sha or None)
        except Exception:
            _GIT_SHA_CACHE.append(None)
    return _GIT_SHA_CACHE[0]


# --------------------------------------------------------------------------
# engine facade


class RunTelemetry:
    """What an engine's check loop drives instead of hand-rolled dicts.

    Resolution: an explicit ``events`` path wins, else ``RAFT_TLA_EVENTS``
    (the check.py / bench.py wiring), else no log — and with neither a log
    nor an ``on_progress`` callback, :attr:`active` is False so engines
    skip the per-segment device fetches entirely (the pre-obs fast path).

    ``segment`` emits the shared record to both sinks and derives
    ``level_end`` events from observed level transitions; ``run_end``
    derives the ``violation`` event from the result.  ``close`` is
    idempotent and safe under exceptions — a log ending without
    ``run_end`` is the crash signature the monitor reports.
    """

    def __init__(self, engine: str, config=None, caps=None,
                 on_progress=None, events: str | None = None,
                 resumed: bool = False, n0: int | None = 1,
                 n_devices: int | None = None, t0: float | None = None):
        from raft_tla_tpu.obs.phases import PhaseTimers
        from raft_tla_tpu.obs.trace import (NULL_TRACER, SpanTracer,
                                            trace_enabled)
        self.engine = engine
        self.config = config
        self.caps = caps
        self.on_progress = on_progress
        self.resumed = resumed
        path = events_path(events)
        self.log = EventLog(path) if path else None
        # Spans need a sink: tracing stays NULL (the off path) without a
        # log even when the gate is on, preserving `active`'s contract.
        self.trace = (SpanTracer(self.log.emit)
                      if self.log is not None and trace_enabled()
                      else NULL_TRACER)
        self.phases = PhaseTimers.from_env()
        self.phases.tracer = self.trace
        inv = tuple(config.invariants) if config is not None else ()
        self.tracker = ProgressTracker(
            t0 if t0 is not None else time.monotonic(),
            n0=n0, invariants=inv, resumed=resumed, n_devices=n_devices)
        self._n_devices = n_devices
        self._last_level: int | None = None
        self._ended = False

    @property
    def active(self) -> bool:
        """True when someone is listening (else skip the stats fetches)."""
        return self.on_progress is not None or self.log is not None

    # -- lifecycle events ---------------------------------------------------

    def run_start(self, n_states: int | None = None,
                  fiducials: dict | None = None) -> None:
        if n_states is not None:
            self.tracker.anchor(n_states)
        if self.log is None:
            return
        cfg = self.config
        fields: dict = {"engine": self.engine, "resumed": self.resumed}
        if cfg is not None:
            b = cfg.bounds
            fields["universe"] = {"servers": b.n_servers, "values": b.n_values}
            fields["bounds"] = {
                "max_term": b.max_term, "max_log": b.max_log,
                "max_msgs": b.max_msgs, "max_dup": b.max_dup,
                "history": b.history}
            fields["spec"] = cfg.spec
            fields["invariants"] = list(cfg.invariants)
            if cfg.symmetry:
                fields["symmetry"] = list(cfg.symmetry)
            if cfg.view is not None:
                fields["view"] = cfg.view
            fields["chunk"] = cfg.chunk
        else:
            fields["universe"] = {}
            fields["spec"] = ""
            fields["invariants"] = []
        if self.caps is not None:
            fields["caps"] = repr(self.caps)
        if n_states is not None:
            fields["n_states"] = int(n_states)
        if self._n_devices is not None:
            fields["n_devices"] = self._n_devices
        sha = git_sha()
        if sha:
            fields["git_sha"] = sha
        if fiducials:
            fields["fiducials"] = fiducials
        fields["pid"] = os.getpid()
        # The v8 clock anchor: always stamped (cheap, three clock reads)
        # so any log joins a merged trace timeline; host context rides
        # along only when tracing, where cross-host comparison matters.
        from raft_tla_tpu.obs.trace import clock_anchor, host_context
        fields["anchor"] = clock_anchor()
        if self.trace.enabled:
            fields["host"] = host_context()
        self.log.emit("run_start", **fields)

    def segment(self, n_states: int, level: int, n_transitions: int,
                coverage: dict | None = None, route_peak: int | None = None,
                n_incl: int | None = None,
                device_rates: list | None = None,
                bin: str | None = None,
                inflight: int | None = None,
                flush_backlog: int | None = None,
                upload_wait_ms: float | None = None,
                prefetch_hits: int | None = None,
                export_rows: int | None = None,
                dev_dedup_hits: int | None = None) -> ProgressRecord:
        rec = self.tracker.record(
            n_states, level, n_transitions, coverage=coverage,
            route_peak=route_peak, n_incl=n_incl,
            phase_s=self.phases.snapshot(),
            device_rates=device_rates,
            bin=bin, inflight=inflight,
            flush_backlog=flush_backlog,
            upload_wait_ms=upload_wait_ms,
            prefetch_hits=prefetch_hits,
            export_rows=export_rows,
            dev_dedup_hits=dev_dedup_hits)
        if self.log is not None:
            if self._last_level is not None and level > self._last_level:
                # The boundary count is the count as observed at the first
                # segment of the new level (exact for engines that call
                # segment() at each boundary, best-known otherwise).
                self.log.emit("level_end", level=level - 1,
                              n_states=rec.n_states)
            self.log.emit("segment", **rec.to_dict())
        self._last_level = level
        if self.on_progress is not None:
            self.on_progress(rec.to_dict())
        return rec

    def checkpoint(self, path: str, n_states: int | None = None) -> None:
        if self.log is None:
            return
        extra = {} if n_states is None else {"n_states": int(n_states)}
        self.log.emit("checkpoint", path=str(path), **extra)

    def stop_requested(self, reason: str, source: str = "engine") -> None:
        if self.log is None:
            return
        self.log.emit("stop_requested", reason=reason, source=source,
                      pid=os.getpid())

    def violation(self, invariant: str, kind: str = "invariant") -> None:
        if self.log is None:
            return
        self.log.emit("violation", invariant=invariant, kind=kind)

    def run_end(self, result) -> None:
        if self.log is None or self._ended:
            return
        self._ended = True
        outcome = "ok" if result.complete else "stopped"
        if result.violation is not None:
            inv = result.violation.invariant
            kind = "deadlock" if inv == _DEADLOCK_NAME else "invariant"
            self.violation(inv, kind=kind)
            outcome = "violation"
        self.log.emit(
            "run_end", n_states=int(result.n_states),
            n_transitions=int(result.n_transitions),
            complete=bool(result.complete), outcome=outcome,
            diameter=int(result.diameter), levels=list(result.levels),
            wall_s=round(float(result.wall_s), 3))

    def run_end_sim(self, *, n_states: int, n_behaviors: int,
                    max_depth: int, wall_s: float, complete: bool,
                    violation=None, sim: dict | None = None) -> None:
        """``run_end`` for statistical (simulation) runs: honest per-field
        semantics instead of shoehorning walker counters into the
        exhaustive-result shape.  ``n_transitions`` is the sampled
        transition count (== states generated along walks), ``diameter``
        the deepest walk observed, and the v3 ``sim`` dict carries the
        confidence summary (behaviors, per-invariant states-checked,
        coverage entropy, fleet geometry).
        """
        if self.log is None or self._ended:
            return
        self._ended = True
        outcome = "ok" if complete else "stopped"
        if violation is not None:
            inv = violation.invariant
            kind = "deadlock" if inv == _DEADLOCK_NAME else "invariant"
            self.violation(inv, kind=kind)
            outcome = "violation"
        fields = dict(
            n_states=int(n_states), n_transitions=int(n_states),
            complete=bool(complete), outcome=outcome,
            diameter=int(max_depth), levels=[],
            wall_s=round(float(wall_s), 3))
        if sim is not None:
            fields["sim"] = dict(sim, behaviors=int(n_behaviors))
        self.log.emit("run_end", **fields)

    def close(self) -> None:
        if self.log is not None:
            self.log.close()
