"""Campaign monitor — read a run-event log and render a one-line
heartbeat (``raft-tla-monitor``).  Point it at a DIRECTORY instead and
it renders the combined fleet view: one heartbeat per ``*.events`` log
found (obs/collect.find_logs does the sweep) plus the aggregate row —
summed incremental rate over live tenants, live/ended/crashed counts,
and the merged pool worker attribution.

The reader is the ONE place that knows how to turn an on-disk stream
into a clean timeline; ``runs/campaign_projection.py`` is a thin client
of :func:`load_stream` instead of carrying its own parsing.  Two stream
dialects are accepted:

- v1 event logs (obs/events.py): JSONL with ``event`` fields.
- legacy ``runs/*.stats`` streams (bare ``on_progress`` dicts, one JSON
  object per line, pre-obs campaigns): lifted to synthetic ``segment``
  events so recorded artifacts like ``elect5ddd_r4_final.stats`` keep
  working.  (The third historical dialect, the space-separated
  ``.telemetry`` columns, is retired — see README.)

Timeline normalisation (formerly campaign_projection.load):

- **wall rebasing** — each process restart resets ``wall_s`` to ~0; a
  drop in ``wall_s`` advances a cumulative offset so ``cum_wall_s`` is a
  single monotone clock across every resume in the file.
- **rollback dropping** — a checkpoint-rollback resume replays counts
  the surviving timeline already passed (r4 has one at L30); segments
  whose reported count sits below the running maximum are dropped from
  the ``segments`` timeline (kept in ``events``).

The heartbeat shows: level, states, incremental rate (trailing window),
ETA (to ``--target``, else to end-of-level from the frontier trend),
phase breakdown (when ``--phase-timers`` ran), fiducial drift vs the
first ``run_start``, heartbeat staleness (time since the last event vs
the run's own segment cadence), and the end-state attribution: a
``run_end`` outcome; else "live" when events are still arriving on
cadence; else "presumed-crashed" when the log has gone stale without a
``run_end`` (the crash signature); else "live?" when the stream carries
no timestamps to judge by (legacy .stats).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from raft_tla_tpu.obs.events import validate_event


# --------------------------------------------------------------------------
# stream reading


def load_stream(path: str, drop_rollbacks: bool = True) -> dict:
    """Parse an event log (or legacy .stats stream) into a clean timeline.

    Returns ``{"events", "segments", "invalid", "legacy"}``: all valid
    events in file order; the normalised segment timeline (each dict
    gains ``cum_wall_s``, the resume-rebased cumulative clock); a list of
    ``(lineno, errors)`` for lines that failed validation; and whether
    any legacy (bare-dict) lines were lifted.
    """
    events: list = []
    invalid: list = []
    legacy = False
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError as e:
                invalid.append((lineno, [f"not JSON: {e}"]))
                continue
            if isinstance(d, dict) and "event" not in d:
                # legacy stats line: lift to a synthetic segment event
                if "n_states" in d and "wall_s" in d:
                    legacy = True
                    d = {"v": 0, "event": "segment", "ts": None, **d}
                else:
                    invalid.append((lineno, ["unrecognised legacy line"]))
                    continue
            else:
                errs = validate_event(d)
                if errs:
                    invalid.append((lineno, errs))
                    continue
            events.append(d)

    # wall rebasing: one cumulative clock across in-file resumes
    offset = prev = 0.0
    segments = []
    for e in events:
        if e["event"] != "segment":
            continue
        w = float(e["wall_s"])
        if w < prev:
            offset += prev
        prev = w
        seg = dict(e, cum_wall_s=w + offset)
        segments.append(seg)

    if drop_rollbacks:
        n_max, kept = -1, []
        for s in segments:
            if s["n_states"] >= n_max:
                kept.append(s)
                n_max = s["n_states"]
        segments = kept

    return {"events": events, "segments": segments,
            "invalid": invalid, "legacy": legacy}


# --------------------------------------------------------------------------
# summarising


def _trailing_rate(segments: list, window_s: float) -> float:
    """Incremental rate over the trailing window of the timeline."""
    if not segments:
        return 0.0
    w = segments[-1]["cum_wall_s"]
    tail = [s for s in segments if s["cum_wall_s"] >= w - window_s]
    if len(tail) >= 2:
        dt = tail[-1]["cum_wall_s"] - tail[0]["cum_wall_s"]
        if dt > 0:
            return (tail[-1]["n_states"] - tail[0]["n_states"]) / dt
    return float(segments[-1].get("inc_states_per_sec", 0.0))


def _level_sizes(events: list, segments: list) -> dict:
    """Per-level state-count increments from level boundaries.

    v1 logs carry explicit ``level_end`` events; legacy streams only
    have the level column, so boundaries are inferred from the first
    segment of each level.
    """
    boundary = {}  # level -> cumulative count at its end
    for e in events:
        if e["event"] == "level_end":
            boundary[e["level"]] = e["n_states"]
    if not boundary:
        seen_level = None
        for s in segments:
            if seen_level is not None and s["level"] > seen_level:
                boundary[s["level"] - 1] = s["n_states"]
            seen_level = s["level"]
    sizes = {}
    ks = sorted(boundary)
    for i, k in enumerate(ks):
        lo = boundary[ks[i - 1]] if i else 0
        sizes[k] = boundary[k] - lo
    return sizes


def _eta_s(summary: dict) -> float | None:
    """Seconds to the target count, else to end-of-level projected from
    the frontier trend (ratio of the last two completed level sizes)."""
    inc = summary["inc_states_per_sec"]
    if inc <= 0:
        return None
    if summary.get("target"):
        return max(0.0, summary["target"] - summary["n_states"]) / inc
    sizes = summary["level_sizes"]
    ks = sorted(sizes)
    if len(ks) < 2 or sizes[ks[-2]] <= 0:
        return None
    ratio = sizes[ks[-1]] / sizes[ks[-2]]
    projected = sizes[ks[-1]] * ratio        # expected size of current level
    boundary_n = sum(sizes[k] for k in ks)   # count at last boundary
    done_in_level = summary["n_states"] - boundary_n
    return max(0.0, projected - done_in_level) / inc


def _staleness(events: list, now: float,
               stale_after_s: float | None) -> tuple:
    """(last_event_age_s, segment_cadence_s, stale) for a timeline.

    ``stale`` is a tri-state: True/False when the stream carries wall
    timestamps to judge by, None when it does not (legacy .stats lines
    have ``ts: None`` — no basis for a verdict).  The threshold is
    ``stale_after_s`` when given, else derived from the run's OWN recent
    segment cadence (10x the median inter-segment gap, clamped to
    [30s, 1h]) so a slow deep level is not misread as a hang, falling
    back to 300s when fewer than two timestamped segments exist.
    """
    stamped = [e["ts"] for e in events if e.get("ts") is not None]
    if not stamped:
        return None, None, None
    age = max(0.0, now - stamped[-1])
    seg_ts = [e["ts"] for e in events
              if e["event"] == "segment" and e.get("ts") is not None]
    tail = seg_ts[-9:]
    gaps = sorted(g for g in
                  (b - a for a, b in zip(tail, tail[1:])) if g >= 0)
    cadence = gaps[len(gaps) // 2] if gaps else None
    if stale_after_s is None:
        stale_after_s = (min(3600.0, max(30.0, 10.0 * cadence))
                         if cadence is not None else 300.0)
    return age, cadence, age > stale_after_s


def _pool_counts(events: list) -> dict | None:
    """Aggregate the v7 worker-pool supervision lifecycle (pool.events):
    spawn/loss/retry/quarantine counters plus the last loss kind and the
    quarantined job ids — the pool's end-state attribution row."""
    spawns = [e for e in events if e["event"] == "worker_spawn"]
    losses = [e for e in events if e["event"] == "worker_lost"]
    retries = [e for e in events if e["event"] == "job_retry"]
    quar = [e for e in events if e["event"] == "quarantine"]
    if not (spawns or losses or retries or quar):
        return None
    pool = {"spawns": len(spawns), "losses": len(losses),
            "retries": len(retries),
            "quarantined": [e["job_id"] for e in quar]}
    if losses:
        pool["last_loss_kind"] = losses[-1]["kind"]
    return pool


def summarize(stream: dict, window_s: float = 600.0,
              target: int | None = None, now: float | None = None,
              stale_after_s: float | None = None) -> dict | None:
    """Distil a loaded stream into the heartbeat fields (None = no data)."""
    segments = stream["segments"]
    events = stream["events"]
    pool = _pool_counts(events)
    if not segments:
        if pool is not None:
            # A pure supervision log (serve pool.events) has no segment
            # timeline; the pool lifecycle IS the heartbeat.
            return {"pool": pool, "pool_only": True,
                    "n_invalid": len(stream["invalid"])}
        snaps = [e for e in events if e["event"] == "metrics_snapshot"]
        if snaps:
            # A metrics log (OUT/metrics.events, schema v10): the last
            # snapshot carries the endpoint's whole registry — latency
            # quantiles, queue depth — replayable without the endpoint.
            last = snaps[-1]
            return {"metrics": dict(last.get("metrics") or {}),
                    "metrics_ts": last.get("ts"),
                    "metrics_only": True,
                    "n_invalid": len(stream["invalid"])}
        return None
    cur = segments[-1]
    summary = {
        "level": cur["level"],
        "n_states": cur["n_states"],
        "cum_wall_s": cur["cum_wall_s"],
        "inc_states_per_sec": _trailing_rate(segments, window_s),
        "since_resume": cur.get("since_resume"),
        "route_peak": cur.get("route_peak"),
        "bin": cur.get("bin"),
        "inflight": cur.get("inflight"),
        "flush_backlog": cur.get("flush_backlog"),
        "dev_dedup_hits": cur.get("dev_dedup_hits"),
        "level_sizes": _level_sizes(events, segments),
        "target": target,
        "legacy": stream["legacy"],
        "n_invalid": len(stream["invalid"]),
        "pool": pool,
    }
    summary["eta_s"] = _eta_s(summary)

    # phase breakdown: aggregate phase_s across the trailing window
    acc: dict = {}
    w = cur["cum_wall_s"]
    for s in segments:
        if s["cum_wall_s"] >= w - window_s:
            for k, v in (s.get("phase_s") or {}).items():
                acc[k] = acc.get(k, 0.0) + v
    total = sum(acc.values())
    summary["phase_pct"] = (
        {k: 100.0 * v / total for k, v in acc.items()} if total > 0 else {})

    # fiducial drift: latest run_start's fiducials vs the first's
    fids = [e["fiducials"] for e in events
            if e["event"] == "run_start" and e.get("fiducials")]
    drift = {}
    if len(fids) >= 1:
        first, last = fids[0], fids[-1]
        for key in ("synthetic_step_ms", "copy_512mb_ms"):
            if first.get(key) and last.get(key):
                drift[key] = last[key] / first[key]
    summary["fiducial_drift"] = drift

    # heartbeat staleness: time since the last event vs segment cadence
    age, cadence, stale = _staleness(
        events, time.time() if now is None else now, stale_after_s)
    summary["last_event_age_s"] = age
    summary["segment_cadence_s"] = cadence
    summary["stale"] = stale

    # end-state attribution
    status = "live?"  # no run_end and no timestamps: can't judge
    for e in events:
        if e["event"] == "stop_requested":
            status = f"stop requested ({e['reason']})"
    for e in events:
        if e["event"] == "violation":
            status = f"VIOLATION {e['invariant']}"
    ended = any(e["event"] == "run_end" for e in events)
    if ended:
        status = [e for e in events if e["event"] == "run_end"][-1]["outcome"]
    elif stale:
        # the crash signature: the log went quiet without a run_end
        cad = f", cadence ~{cadence:.0f}s" if cadence is not None else ""
        status = f"presumed-crashed (last event {age:.0f}s ago{cad})"
    elif stale is False:
        status = f"live ({status})" if status != "live?" else "live"
    summary["status"] = status
    return summary


def _fmt_eta(s: float) -> str:
    if s < 90:
        return f"{s:.0f}s"
    if s < 5400:
        return f"{s / 60:.0f}m"
    return f"{s / 3600:.1f}h"


def _fmt_pool(pool: dict) -> str:
    tag = (f"pool: {pool['spawns']} spawn(s), {pool['losses']} lost, "
           f"{pool['retries']} retried")
    if pool.get("last_loss_kind"):
        tag += f" (last loss: {pool['last_loss_kind']})"
    if pool["quarantined"]:
        tag += f", QUARANTINED {','.join(pool['quarantined'])}"
    return tag


def _parse_series(key: str) -> tuple:
    """``name{k="v",...}`` -> (name, labels) for metrics_snapshot keys
    (the flat Prometheus-style names obs/metrics.py snapshots)."""
    if "{" not in key:
        return key, {}
    name, _, body = key.partition("{")
    labels = {}
    for part in body.rstrip("}").split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v.strip('"')
    return name, labels


def _metrics_rows(snap: dict, age_s: float | None) -> list:
    """The fleet metrics rows, one unit per row: per-tenant p99
    admission-to-result latency (ms), queue depth (jobs), and endpoint
    liveness (seconds since the last snapshot)."""
    p99: dict = {}
    depth = None
    for key, val in snap.items():
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            continue
        name, labels = _parse_series(key)
        if name == "raft_tla_latency_seconds" \
                and labels.get("quantile") == "0.99":
            p99[labels.get("tenant", "(all)")] = val
        elif name == "raft_tla_queue_depth":
            depth = val
    rows = [f"p99 latency {tenant}: {p99[tenant] * 1000.0:,.0f} ms"
            for tenant in sorted(p99)]
    if depth is not None:
        rows.append(f"queue depth: {depth:.0f} jobs")
    if age_s is not None:
        state = "live" if age_s <= 120.0 else "stale"
        rows.append(f"metrics endpoint: {state} "
                    f"(last snapshot {age_s:.0f} s ago)")
    return rows


def heartbeat(summary: dict | None) -> str:
    """Render the one-line heartbeat."""
    if summary is None:
        return "obs: no segments yet"
    if summary.get("pool_only"):
        line = _fmt_pool(summary["pool"])
        if summary["n_invalid"]:
            line += f"  [{summary['n_invalid']} invalid lines]"
        return line
    if summary.get("metrics_only"):
        age = None
        if isinstance(summary.get("metrics_ts"), (int, float)):
            age = max(0.0, time.time() - summary["metrics_ts"])
        rows = _metrics_rows(summary.get("metrics") or {}, age)
        line = " | ".join(rows) if rows else "metrics: empty snapshot"
        if summary["n_invalid"]:
            line += f"  [{summary['n_invalid']} invalid lines]"
        return line
    parts = [
        f"L{summary['level']}",
        f"{summary['n_states']:,} st",
        f"inc {summary['inc_states_per_sec']:,.0f}/s",
        f"wall {summary['cum_wall_s']:,.0f}s",
    ]
    if summary["eta_s"] is not None:
        tag = "target" if summary.get("target") else "level"
        parts.append(f"ETA {tag} ~{_fmt_eta(summary['eta_s'])}")
    if summary["phase_pct"]:
        parts.append(" ".join(
            f"{k} {v:.0f}%" for k, v in
            sorted(summary["phase_pct"].items(), key=lambda kv: -kv[1])))
    for key, short in (("synthetic_step_ms", "step"),
                       ("copy_512mb_ms", "copy")):
        if key in summary["fiducial_drift"]:
            parts.append(f"{short} drift {summary['fiducial_drift'][key]:.2f}x")
    if summary.get("route_peak") is not None:
        parts.append(f"route_peak {summary['route_peak']}")
    if summary.get("bin") is not None:
        # serve lanes: which compiled step signature this tenant rode, and
        # how deep the async scheduler's dispatch pipeline ran
        tag = f"bin {summary['bin']}"
        if summary.get("inflight") is not None:
            tag += f" (inflight {summary['inflight']})"
        parts.append(tag)
    if summary.get("flush_backlog") is not None:
        # ddd background host dedup: 1 = a sealed flush was overlapping
        # device compute at the segment boundary (depth-1 worker)
        parts.append(f"flush backlog {summary['flush_backlog']}")
    if summary.get("dev_dedup_hits") is not None:
        # ddd device dedup: rows the HBM-resident within-level set kept
        # off the d2h export path in this segment (schema v9)
        parts.append(f"dev dedup {summary['dev_dedup_hits']:,}")
    if summary.get("pool"):
        parts.append(_fmt_pool(summary["pool"]))
    if summary.get("last_event_age_s") is not None:
        parts.append(f"last ev {summary['last_event_age_s']:.0f}s ago")
    parts.append(summary["status"])
    line = " | ".join(parts)
    if summary["n_invalid"]:
        line += f"  [{summary['n_invalid']} invalid lines]"
    return line


# --------------------------------------------------------------------------
# fleet view (directory mode)


def fleet_view(root: str, window_s: float = 600.0,
               stale_after_s: float | None = None) -> tuple:
    """Summarize every ``*.events`` log under ``root`` (the collector's
    sweep — obs/collect.find_logs).  Returns ``(rows, totals)``: one
    ``(relpath, summary)`` per readable log, and the fleet aggregate —
    summed incremental rate and state count over live tenant timelines,
    live/ended/crashed attribution counts, and the merged pool counters
    (spawns/losses/retries/quarantines across supervision logs)."""
    import os

    from raft_tla_tpu.obs.collect import find_logs

    rows = []
    for path in find_logs(root):
        try:
            stream = load_stream(path)
        except OSError:
            continue
        rows.append((os.path.relpath(path, root),
                     summarize(stream, window_s=window_s,
                               stale_after_s=stale_after_s)))
    totals = {"n_logs": len(rows), "inc_states_per_sec": 0.0,
              "n_states": 0, "live": 0, "ended": 0, "crashed": 0,
              "pool": {"spawns": 0, "losses": 0, "retries": 0,
                       "quarantined": []}}
    pooled = False
    metrics_summary = None
    for _name, s in rows:
        if s is None:
            continue
        if s.get("metrics_only"):
            # newest snapshot wins: one endpoint per fleet directory
            if metrics_summary is None or \
                    (s.get("metrics_ts") or 0) > \
                    (metrics_summary.get("metrics_ts") or 0):
                metrics_summary = s
            continue
        if s.get("pool"):
            pooled = True
            for k in ("spawns", "losses", "retries"):
                totals["pool"][k] += s["pool"][k]
            totals["pool"]["quarantined"].extend(s["pool"]["quarantined"])
        if s.get("pool_only"):
            continue
        totals["n_states"] += s["n_states"]
        status = s["status"]
        if status.startswith("live"):
            totals["live"] += 1
            totals["inc_states_per_sec"] += s["inc_states_per_sec"]
        elif status.startswith("presumed-crashed"):
            totals["crashed"] += 1
        else:
            totals["ended"] += 1
    if not pooled:
        totals["pool"] = None
    totals["metrics"] = metrics_summary
    return rows, totals


def _fleet_lines(rows: list, totals: dict) -> str:
    width = max((len(n) for n, _s in rows), default=0)
    lines = [f"{name:<{width}}  {heartbeat(s)}" for name, s in rows]
    agg = [f"fleet: {totals['n_logs']} log(s)",
           f"{totals['n_states']:,} st",
           f"inc {totals['inc_states_per_sec']:,.0f}/s",
           f"{totals['live']} live / {totals['ended']} ended / "
           f"{totals['crashed']} presumed-crashed"]
    if totals["pool"]:
        agg.append(_fmt_pool(totals["pool"]))
    lines.append(" | ".join(agg))
    ms = totals.get("metrics")
    if ms:
        age = None
        if isinstance(ms.get("metrics_ts"), (int, float)):
            age = max(0.0, time.time() - ms["metrics_ts"])
        for row in _metrics_rows(ms.get("metrics") or {}, age):
            lines.append(f"  {row}")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# CLI


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="raft-tla-monitor",
        description="One-line heartbeat over a run-event log "
                    "(or legacy .stats stream).")
    p.add_argument("path",
                   help="event log (JSONL) to read — or a DIRECTORY, "
                        "which is swept recursively for *.events and "
                        "rendered as a combined fleet view (one "
                        "heartbeat per log + the summed incremental "
                        "rate and pool attribution)")
    p.add_argument("--follow", action="store_true",
                   help="re-read and re-print every --interval seconds")
    p.add_argument("--interval", type=float, default=10.0)
    p.add_argument("--window", type=float, default=600.0,
                   help="trailing window for the incremental rate (s)")
    p.add_argument("--target", type=int, default=None,
                   help="ETA to this state count instead of end-of-level")
    p.add_argument("--stale-after", type=float, default=None,
                   help="flag the run presumed-crashed when the last "
                        "event is older than this many seconds and no "
                        "run_end was written (default: 10x the run's "
                        "own segment cadence)")
    p.add_argument("--json", action="store_true",
                   help="print the full summary as JSON instead")
    args = p.parse_args(argv)

    import os
    while True:
        if os.path.isdir(args.path):
            rows, totals = fleet_view(args.path, window_s=args.window,
                                      stale_after_s=args.stale_after)
            if args.json:
                print(json.dumps({"logs": dict(rows), "fleet": totals},
                                 default=str), flush=True)
            elif not rows:
                print(f"obs: no *.events under {args.path}", flush=True)
            else:
                print(_fleet_lines(rows, totals), flush=True)
            if not args.follow:
                return 0 if rows else 1
            time.sleep(args.interval)
            continue
        try:
            stream = load_stream(args.path)
        except FileNotFoundError:
            print(f"obs: waiting for {args.path}", flush=True)
            stream = None
        if stream is not None:
            summary = summarize(stream, window_s=args.window,
                                target=args.target,
                                stale_after_s=args.stale_after)
            if args.json:
                print(json.dumps(summary, default=str), flush=True)
            else:
                print(heartbeat(summary), flush=True)
        if not args.follow:
            return 0 if stream is not None else 1
        time.sleep(args.interval)


def entry() -> None:
    sys.exit(main())


if __name__ == "__main__":
    entry()
