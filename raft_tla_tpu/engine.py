"""Single-chip BFS engine — the L4 checker runtime (SURVEY §7.1 step 5).

Plays the role TLC plays for the reference (SURVEY §0): level-synchronous
breadth-first exploration from ``Init`` (``raft.tla:155-160``) of the
transition graph of ``Next`` (``raft.tla:454-465``), deduplicating states by
64-bit fingerprint, checking invariants on every distinct state, gating
expansion on the StateConstraint (violating states are counted and
invariant-checked but never expanded — TLC CONSTRAINT semantics), and
reconstructing a counterexample trace on violation.

TPU-native structure:

- The hot loop is one fused, jitted computation per frontier chunk
  (``ops/kernels.build_step``): unpack → batched guarded transitions for the
  whole action table → canonicalize → pack → fingerprint → invariant +
  constraint predicates.  One device round-trip per chunk.
- Fixed chunk size ⇒ exactly one compiled executable; the last chunk is
  padded (XLA static shapes, SURVEY §7.2.4).
- Dedup v1 is a host-side fingerprint set: only the (small) fingerprint /
  mask lanes come back per chunk; the (wide) successor vectors are gathered
  on device for *new* states only before transfer.  The device-resident
  hash-table dedup is layered on in ``parallel/`` — this module is the
  correctness anchor it is differentially tested against.

Discovery order is byte-identical to the oracle's (``models/refbfs.py``):
frontier states in insertion order × action lanes in ``spec.action_table``
order.  That makes state counts, per-level counts, coverage counters, and
the *first* invariant violation all exactly comparable.

Fingerprint collisions merge states (probabilistically negligible, the same
regime TLC's FP64 operates in — SURVEY §2.8); the oracle-parity tests run on
spaces small enough that a collision would be detected as a count mismatch.
"""

from __future__ import annotations

import dataclasses
import time
from collections import Counter
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import fingerprint as fpr


from raft_tla_tpu.models.refbfs import DEADLOCK  # noqa: E402  (sentinel)


@dataclasses.dataclass
class Violation:
    invariant: str          # registry name, or refbfs.DEADLOCK
    state: interp.PyState
    # Trace from Init: [(action_label | None, PyState)]; replayable by interp.
    trace: list


@dataclasses.dataclass
class EngineResult:
    n_states: int          # distinct states found (incl. constraint-violating)
    diameter: int          # BFS levels past Init that produced new states
    n_transitions: int     # enabled (state, action) pairs explored
    coverage: Counter      # action family -> distinct new states produced
    violation: Optional[Violation]
    levels: list           # new-state count per level (levels[0] = 1)
    wall_s: float
    # False only for deadline-bounded partial runs (PagedEngine.check
    # deadline_s — the bench's time-boxed north-star workload); every
    # exhaustive verdict above requires complete=True.
    complete: bool = True

    @property
    def states_per_sec(self) -> float:
        return self.n_states / self.wall_s if self.wall_s > 0 else float("inf")


class _VecStore:
    """Append-only host store of packed state vectors, random-access by index.

    Plays the role of TLC's ``states/`` directory (``.gitignore:2``) for trace
    reconstruction: every accepted state's vector is kept, addressed by its
    global discovery index.  Chunked append keeps inserts O(1) amortized.
    """

    def __init__(self, width: int):
        self._chunks: list[np.ndarray] = []
        self._offsets = [0]
        self._width = width

    def append(self, rows: np.ndarray) -> None:
        if rows.size:
            self._chunks.append(np.ascontiguousarray(rows, dtype=np.int32))
            self._offsets.append(self._offsets[-1] + rows.shape[0])

    def __len__(self) -> int:
        return self._offsets[-1]

    def get(self, idx: int) -> np.ndarray:
        import bisect
        c = bisect.bisect_right(self._offsets, idx) - 1
        return self._chunks[c][idx - self._offsets[c]]


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()


class Engine:
    """Compiled checker for one :class:`CheckConfig`. Reusable across runs."""

    def __init__(self, config: CheckConfig, model=None):
        from raft_tla_tpu.frontend import resolve_model
        self.config = config
        self.bounds = config.bounds
        self.model = model if model is not None \
            else resolve_model(config.spec)
        self.lay = self.model.layout(self.bounds)
        self.table = self.model.action_table(self.bounds)
        self.A = len(self.table)
        self.chunk = config.chunk
        self._step = jax.jit(self.model.build_step(config))

    # -- public API ----------------------------------------------------------

    def check(self, max_states: int | None = None,
              init_override: interp.PyState | None = None,
              progress=None) -> EngineResult:
        """Exhaustively explore; stop at the first invariant violation.

        ``init_override`` mirrors the oracle's hook (``refbfs.check``).
        ``progress`` is an optional callback ``(level, n_states, frontier)``.
        """
        t0 = time.monotonic()
        cfg, bounds, lay = self.config, self.bounds, self.lay
        B, A, W = self.chunk, self.A, self.lay.width
        inv_names = list(cfg.invariants)

        init_py = init_override if init_override is not None \
            else self.model.init_py(bounds)
        init_vec = self.model.to_vec(init_py, bounds)
        hi0, lo0 = self.model.init_fingerprint(self.config, init_py,
                                               init_vec)
        init_key = int(fpr.to_u64(hi0, lo0))

        seen: set[int] = {init_key}
        store = _VecStore(W)
        store.append(init_vec[None, :])
        parents: list = [None]               # global idx -> (parent, lane) | None
        con_flags = [self.model.constraint_ok(init_py, bounds)]
        coverage: Counter = Counter()
        levels = [1]
        n_transitions = 0
        violation: Optional[Violation] = None

        for nm in inv_names:
            if not self.model.py_invariant(nm)(init_py, bounds):
                violation = self._make_violation(nm, 0, store, parents)
                break

        # frontier: list of global indices of states to expand this level
        frontier = [0] if violation is None and con_flags[0] else []

        while frontier and violation is None:
            new_this_level = 0
            next_frontier: list[int] = []
            for c0 in range(0, len(frontier), B):
                gidx = frontier[c0:c0 + B]
                nb = len(gidx)
                vecs = np.stack([store.get(g) for g in gidx])
                if nb < B:   # pad to the static chunk shape
                    pad = np.broadcast_to(vecs[0], (B - nb, W))
                    vecs = np.concatenate([vecs, pad], axis=0)
                out = self._step(jnp.asarray(vecs))

                valid = np.asarray(out["valid"])[:nb]          # [nb, A]
                ovf = np.asarray(out["overflow"])[:nb]
                keys = fpr.to_u64(np.asarray(out["fp_hi"])[:nb],
                                  np.asarray(out["fp_lo"])[:nb])
                inv_ok = np.asarray(out["inv_ok"])[:nb]        # [nb, A, nI]
                con_ok = np.asarray(out["con_ok"])[:nb]

                if ovf.any():
                    b, a = np.argwhere(ovf)[0]
                    raise RuntimeError(
                        "state-capacity overflow at "
                        f"{self.table[int(a)].label()} — bounds reasoning "
                        "violated (config.py capacity scheme)")
                # TLC's default deadlock check: an expanded state with no
                # successor (stuttering excluded).  Successors of earlier
                # rows in the chunk are recorded first — refbfs order.
                dead_limit = None
                if cfg.check_deadlock:
                    dead = ~valid.any(axis=1)
                    if dead.any():
                        fb = int(np.argmax(dead))
                        dead_limit = fb * A

                # Dedup in discovery order: flat index = b * A + a.
                flat_keys = keys.reshape(-1)
                flat_valid = valid.reshape(-1)
                if dead_limit is not None:
                    flat_valid = flat_valid.copy()
                    flat_valid[dead_limit:] = False
                # Count transitions AFTER the dead-state truncation so the
                # stats stay refbfs-exact on deadlock counterexamples (the
                # oracle stops counting at the first dead state).
                n_transitions += int(flat_valid.sum())
                cand = np.nonzero(flat_valid)[0]
                new_flat: list[int] = []
                for fi in cand:
                    kk = int(flat_keys[fi])
                    if kk in seen:
                        continue
                    seen.add(kk)
                    new_flat.append(int(fi))
                # Truncate at the first violating new state so stats match
                # refbfs exactly: the oracle stops recording the instant it
                # sees a violation, mid-chunk included.
                for t, fi in enumerate(new_flat):
                    b, a = divmod(fi, A)
                    if not inv_ok[b, a].all():
                        new_flat = new_flat[:t + 1]
                        break
                if not new_flat:
                    if dead_limit is not None:
                        violation = self._make_violation(
                            DEADLOCK, gidx[dead_limit // A], store, parents)
                        break
                    continue

                nf = np.asarray(new_flat, dtype=np.int64)
                # Device-side gather of just the new rows (padded to a pow2
                # bucket so the eager gather compiles O(log) distinct shapes).
                cap = _next_pow2(max(len(nf), 1))
                sel = np.concatenate(
                    [nf, np.zeros(cap - len(nf), dtype=np.int64)])
                rows = np.asarray(out["svecs"].reshape(B * A, W)
                                  [jnp.asarray(sel)])[:len(nf)]

                base = len(store)
                store.append(rows)
                for t, fi in enumerate(new_flat):
                    b, a = divmod(fi, A)
                    g = base + t
                    parents.append((gidx[b], int(a)))
                    coverage[self.table[int(a)].family] += 1
                    new_this_level += 1
                    c_ok = bool(con_ok[b, a])
                    con_flags.append(c_ok)
                    bad = np.nonzero(~inv_ok[b, a])[0]
                    if bad.size:
                        violation = self._make_violation(
                            inv_names[int(bad[0])], g, store, parents)
                        break
                    if c_ok:
                        next_frontier.append(g)
                if violation is None and dead_limit is not None:
                    violation = self._make_violation(
                        DEADLOCK, gidx[dead_limit // A], store, parents)
                if violation is not None:
                    break
            if violation is not None:
                break
            if max_states is not None and len(store) > max_states:
                raise RuntimeError(f"state count exceeded {max_states}")
            if new_this_level:
                levels.append(new_this_level)
            if progress is not None:
                progress(len(levels) - 1, len(store), len(next_frontier))
            frontier = next_frontier

        return EngineResult(
            n_states=len(store),
            diameter=len(levels) - 1,
            n_transitions=n_transitions,
            coverage=coverage,
            violation=violation,
            levels=levels,
            wall_s=time.monotonic() - t0,
        )

    # -- internals -----------------------------------------------------------

    def _make_violation(self, inv_name: str, gidx: int, store: _VecStore,
                        parents: list) -> Violation:
        """Walk the parent chain back to Init (TLC's counterexample trace)."""
        chain = []
        cur: Optional[int] = gidx
        while cur is not None:
            py = self.model.from_vec(store.get(cur), self.bounds)
            entry = parents[cur]
            label = self.table[entry[1]].label() if entry else None
            chain.append((label, py))
            cur = entry[0] if entry else None
        chain.reverse()
        return Violation(invariant=inv_name, state=chain[-1][1], trace=chain)


def check(config: CheckConfig, **kw) -> EngineResult:
    """One-shot convenience: build the engine and run it."""
    return Engine(config).check(**kw)
