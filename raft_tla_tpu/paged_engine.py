"""Host-paged BFS engine — HBM ring + native host store (SURVEY §2.8).

The device-resident engine (device_engine.py) keeps every discovered state in
HBM: at ~240 B/state plus the <2 GiB single-buffer limit, that caps a run at
~8M states — far below the bounded full-``Next`` spaces (the 3-server/2-value
model exceeds that by level 18).  This engine removes the ceiling the way TLC
does with its disk-backed ``states/`` queue (reference ``.gitignore:2``):

- **Only the active BFS window lives in HBM** — a ring of the current level
  (being expanded) and the next (being appended).  A state's ring row is its
  discovery index mod ``ring``; level-synchronous BFS guarantees the live
  window ``[lvl_start, n_states)`` is contiguous, so ring reuse is safe while
  the window fits (checked loudly: FAIL_RING).
- **Every new state pages out to the C++ host store** (utils/native.py)
  after each watchdog segment, with its (parent, lane) trace links, via a
  single fixed-shape gather (mid-run XLA compiles wedge the deployment
  tunnel).  Host RAM (then disk) is the capacity bound, not HBM.
- **Only the fingerprint table scales with the full space** on device:
  8 B/slot at load ≤ 0.5 → ~16 B/state, an order of magnitude less than
  storing states.  ~64M states fit in ~1 GiB of table.
- Violation traces reconstruct entirely host-side: ``store_trace_chain``
  walks the native link log; the device is never consulted.

Shares the fingerprint table protocol, failure bitmask, segment/watchdog
machinery and Carry layout with device_engine.py; discovery order — and
therefore counts, levels, coverage, and first-violation — is byte-identical
to the oracle's, which the parity tests assert with rings small enough to
wrap many times per run.
"""

from __future__ import annotations

import dataclasses
import time
from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.device_engine import (
    _EMPTY, _dedup_insert, BUCKET, Carry, FAIL_INDEX,
    FAIL_LEVEL, FAIL_PROBE, FAIL_RING, FAIL_WIDTH, decode_fail, _carry_done,
    _acc64_add, _acc64_zero, acc64_int, aggregate_coverage,
    widen_legacy_n_trans)
from raft_tla_tpu.engine import DEADLOCK, EngineResult, Violation
from raft_tla_tpu.obs import RunTelemetry
from raft_tla_tpu.models import interp, invariants as inv_mod, spec as S
from raft_tla_tpu.ops import bitpack
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym_mod
from raft_tla_tpu.utils import ckpt
from raft_tla_tpu.utils import native
from raft_tla_tpu.utils import pacing

I32 = jnp.int32
U32 = jnp.uint32


@dataclasses.dataclass(frozen=True)
class PagedCapacities:
    """Static shapes of one compiled paged search.

    ``ring`` bounds the *live window* (current + next BFS level), not the
    total space; ``table`` bounds total distinct states at ~2 slots/state.
    """

    ring: int = 1 << 20          # HBM rows for the active window
    table: int = 1 << 24         # fingerprint slots (power of two)
    levels: int = 1 << 10

    def __post_init__(self):
        if self.ring & (self.ring - 1) or self.table & (self.table - 1):
            raise ValueError("ring and table must be powers of two")


def _build_segment(config: CheckConfig, caps: PagedCapacities, A: int,
                   W: int, schema: bitpack.BitSchema):
    """Ring variant of device_engine._build_segment (same Carry, same loop
    structure; store/parent/lane/conflag are rings indexed by discovery
    index mod ``ring``).  Ring rows are bit-packed (ops/bitpack.py) —
    ~4-8x more frontier per HBM byte; rows unpack only for the chunk
    being expanded."""
    B = config.chunk
    n_inv = len(config.invariants)
    step = kernels.build_step(config.bounds, config.spec,
                              tuple(config.invariants), config.symmetry,
                              view=config.view)
    Rcap, Lcap = caps.ring, caps.levels
    rmask = Rcap - 1
    BIG = jnp.int32(np.iinfo(np.int32).max)
    IDX_CEIL = jnp.int32(np.iinfo(np.int32).max - 2 * B * A)

    def chunk_body(carry: Carry) -> Carry:
        (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
         lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail,
         levels, lvl, c) = carry
        start = lvl_start + c * B
        rows_g = start + jnp.arange(B, dtype=I32)
        row_act = rows_g < lvl_end
        ridx = rows_g & rmask
        vecs = schema.unpack(store[ridx], jnp)
        out = step(vecs)
        valid = out["valid"] & row_act[:, None] & conflag[ridx][:, None]
        n_trans = _acc64_add(n_trans, jnp.sum(valid.astype(I32)))
        fail = fail | jnp.any(valid & out["overflow"]) * FAIL_WIDTH

        fhi = out["fp_hi"].reshape(-1)
        flo = out["fp_lo"].reshape(-1)
        fvalid = valid.reshape(-1)
        tbl_hi, tbl_lo, is_new, pfail = _dedup_insert(
            tbl_hi, tbl_lo, fhi, flo, fvalid)
        fail = fail | jnp.any(pfail) * FAIL_PROBE

        # Append new states into the ring at (discovery index mod Rcap).
        pos = n_states + jnp.cumsum(is_new.astype(I32)) - 1
        n_new = jnp.sum(is_new.astype(I32))
        # Live window must fit the ring: appending past lvl_start + Rcap
        # would overwrite the frontier still being expanded.
        fail = fail | (n_states + n_new - lvl_start > Rcap) * FAIL_RING
        # The paged engine is host-RAM-bounded, so (unlike the HBM-bounded
        # engines) its int32 discovery index could genuinely reach 2^31 —
        # fail loudly with a chunk's worth of headroom left.
        fail = fail | (n_states > IDX_CEIL) * FAIL_INDEX
        ok = is_new & (pos - lvl_start < Rcap)
        sl = jnp.where(ok, pos & rmask, Rcap)
        svecs = schema.pack(out["svecs"].reshape(B * A, W), jnp)
        store = store.at[sl].set(svecs, mode="drop")
        flat_b = jnp.arange(B * A, dtype=I32) // A
        flat_a = jnp.arange(B * A, dtype=I32) % A
        parent = parent.at[sl].set(start + flat_b, mode="drop")
        lane = lane.at[sl].set(flat_a, mode="drop")
        conflag = conflag.at[sl].set(out["con_ok"].reshape(-1), mode="drop")
        cov = cov.at[jnp.where(is_new, flat_a, A)].add(1, mode="drop")
        n_states = n_states + n_new

        inv_bad = is_new & jnp.any(
            ~out["inv_ok"].reshape(B * A, n_inv), axis=-1) if n_inv \
            else jnp.zeros((B * A,), bool)
        first = jnp.min(jnp.where(inv_bad, jnp.arange(B * A, dtype=I32), BIG))
        bad_inv = jnp.argmax(
            ~out["inv_ok"].reshape(B * A, n_inv)
            [jnp.minimum(first, B * A - 1)]) if n_inv else jnp.int32(0)
        g_target = pos[jnp.minimum(first, B * A - 1)]
        if config.check_deadlock:
            # TLC's default deadlock check (see device_engine.chunk_body).
            dead = row_act & conflag[ridx] & ~jnp.any(out["valid"], axis=1)
            drow = jnp.min(jnp.where(dead, jnp.arange(B, dtype=I32), BIG))
            dpos = jnp.where(drow < BIG // A, drow * A, BIG)
            use_dead = dpos < first
            first = jnp.minimum(first, dpos)
            g_target = jnp.where(use_dead,
                                 start + jnp.minimum(drow, B - 1), g_target)
            bad_inv = jnp.where(use_dead, jnp.int32(n_inv), bad_inv)
        has_viol = first < BIG
        new_viol = has_viol & (viol_g < 0)
        viol_g = jnp.where(new_viol, g_target, viol_g)
        viol_i = jnp.where(new_viol, bad_inv, viol_i)
        return Carry(store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
                     lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail,
                     levels, lvl, c + 1)

    def outer_body(sc):
        steps, carry = sc
        n_chunks = (carry.lvl_end - carry.lvl_start + B - 1) // B

        def ccond(cc):
            s, inner = cc
            return ((inner.c < n_chunks) & (inner.viol_g < 0) &
                    (inner.fail == 0) & (s < budget) &
                    (inner.n_states < pause))    # host must page out first

        def cbody(cc):
            s, inner = cc
            return s + 1, chunk_body(inner)

        steps, carry = jax.lax.while_loop(ccond, cbody, (steps, carry))
        (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
         lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail,
         levels, lvl, c) = carry
        adv = (c >= n_chunks) & (viol_g < 0) & (fail == 0)
        n_new = n_states - lvl_end
        levels = levels.at[jnp.where(adv, jnp.minimum(lvl, Lcap - 1),
                                     Lcap)].set(n_new, mode="drop")
        fail = fail | (adv & (lvl >= Lcap - 1) & (n_new > 0)) * FAIL_LEVEL
        lvl_start = jnp.where(adv, lvl_end, lvl_start)
        lvl_end = jnp.where(adv, n_states, lvl_end)
        lvl = jnp.where(adv, lvl + 1, lvl)
        c = jnp.where(adv, 0, c)
        return steps, Carry(store, parent, lane, conflag, tbl_hi, tbl_lo,
                            n_states, lvl_start, lvl_end, viol_g, viol_i,
                            n_trans, cov, fail, levels, lvl, c)

    def outer_cond(sc):
        steps, carry = sc
        return (steps < budget) & ~_carry_done(carry)

    def segment(carry, budget_, pause_at):
        # ``pause_at``: also return control once n_states crosses this mark,
        # so the host can page out before the ring laps itself.
        nonlocal budget, pause
        budget, pause = budget_, pause_at
        steps, carry = jax.lax.while_loop(
            lambda sc: outer_cond(sc) & (sc[1].n_states < pause),
            lambda sc: outer_body(sc), (jnp.int32(0), carry))
        # Executed chunk count: paged segments routinely end mid-budget
        # (the pause_at pageout yield), so the host's per-chunk cost
        # estimate must divide by THIS, not the requested budget —
        # otherwise the watchdog clamp projects oversized segments.
        return carry, _carry_done(carry), steps

    budget = pause = None
    return segment


def _build_init(caps: PagedCapacities, A: int, P: int):
    Rcap, Lcap, Tcap = caps.ring, caps.levels, caps.table
    TB = Tcap // BUCKET

    def init(init_vec_packed, init_key_hi, init_key_lo, init_con):
        store = jnp.zeros((Rcap, P), I32).at[0].set(init_vec_packed)
        parent = jnp.full((Rcap,), -1, I32)
        lane = jnp.full((Rcap,), -1, I32)
        conflag = jnp.zeros((Rcap,), bool).at[0].set(init_con)
        b0 = (init_key_lo & jnp.uint32(TB - 1)).astype(I32)
        tbl_hi = jnp.full((TB, BUCKET), _EMPTY, U32).at[b0, 0].set(
            init_key_hi)
        tbl_lo = jnp.full((TB, BUCKET), _EMPTY, U32).at[b0, 0].set(
            init_key_lo)
        levels = jnp.zeros((Lcap,), I32)
        return Carry(store, parent, lane, conflag, tbl_hi, tbl_lo,
                     jnp.int32(1), jnp.int32(0), jnp.int32(1),
                     jnp.int32(-1), jnp.int32(0), _acc64_zero(),
                     jnp.zeros((A,), I32), jnp.int32(0),
                     levels, jnp.int32(1), jnp.int32(0))

    return init


class PagedEngine:
    """Exhaustive checker bounded by host RAM, not HBM."""

    SEG_TARGET_S = 8.0
    SEG_CLAMP_S = 25.0       # see DeviceEngine: watchdog-overshoot guard
    SEG_MIN, SEG_MAX = 16, 1 << 16

    def __init__(self, config: CheckConfig, caps: PagedCapacities | None =
                 None, seg_chunks: int = 64):
        self.config = config
        self.bounds = config.bounds
        self.lay = st.Layout.of(self.bounds)
        self.table = S.action_table(self.bounds, config.spec)
        self.A = len(self.table)
        self.caps = caps or PagedCapacities()
        # One chunk appends up to chunk*A rows past the pause mark (the
        # pause check runs between chunks); ring//2 headroom must absorb it
        # so unpaged rows are never overwritten.
        if self.caps.ring < 2 * config.chunk * self.A:
            raise ValueError(
                f"PagedCapacities.ring={self.caps.ring} must be >= "
                f"2 * chunk * A = {2 * config.chunk * self.A}")
        self.seg_chunks = seg_chunks
        self.schema = bitpack.BitSchema(self.bounds)
        self._init = jax.jit(_build_init(self.caps, self.A, self.schema.P))
        self._segment = jax.jit(
            _build_segment(config, self.caps, self.A, self.lay.width,
                           self.schema),
            donate_argnums=(0,))
        self._gather = jax.jit(
            lambda carry, ridx: (carry.store[ridx], carry.parent[ridx],
                                 carry.lane[ridx]))

    # Fixed pageout gather width: ONE compiled gather shape for the whole
    # run.  A size ladder would trigger a fresh XLA compile the first time
    # a segment's new-state count crossed each bucket — and on the
    # deployment tunnel a mid-run compile against a busy device wedges the
    # worker (observed repeatedly ~13 min into large runs).  Padding waste
    # is bounded at PAGE_ROWS rows (~2 MB packed) per segment.
    PAGE_ROWS = 1 << 16

    def _pageout(self, carry, host, paged: int, n_states: int) -> int:
        """Copy rows [paged, n_states) from the device ring to the host
        store, PAGE_ROWS at a time."""
        iota = np.arange(self.PAGE_ROWS, dtype=np.int32)
        while paged < n_states:
            n = min(n_states - paged, self.PAGE_ROWS)
            gidx = np.minimum(paged + iota, n_states - 1)   # pad w/ last row
            ridx = jnp.asarray(gidx & (self.caps.ring - 1))
            rows, par, lan = jax.device_get(self._gather(carry, ridx))
            host.append(rows[:n])
            host.append_links(par[:n], lan[:n])
            paged += n
        return paged

    # -- checkpoint / resume --------------------------------------------
    # A paged checkpoint is the device carry plus the host store's row and
    # link logs; resume is bit-exact (the search is a pure function of
    # both).  Needed in anger: the deployment tunnel's chip can be
    # preempted mid-run (the worker dies silently, the client hangs), so
    # long exhaustive runs are driven as checkpoint → rerun → resume.

    def save_checkpoint(self, path: str, carry: Carry, host, paged: int,
                        init_key: tuple) -> None:
        """Snapshot carry + host store.  The store's row/link logs stream
        to ``path + ".rows"``/``".links"`` in bounded blocks (never a
        second full copy in RAM); the metadata npz with the ``paged``
        counter is written LAST, so a crash between files leaves an older
        counter next to longer streams — safe, because the store is
        append-only and prefixes are stable (utils/ckpt.py)."""
        ckpt.stream_rows_out(path + ".rows", host.read, paged,
                             self.schema.P)

        def links_reader(start, n):
            par, lan = host.read_links(start, n)
            return np.stack([par, lan], axis=1)

        ckpt.stream_rows_out(path + ".links", links_reader, paged, 2)
        arrs = jax.device_get(carry)
        ckpt.atomic_savez(
            path,
            **{f"c{i}": np.asarray(x) for i, x in enumerate(arrs)},
            paged=np.int64(paged),
            config_digest=np.uint64(
                ckpt.config_digest(self.config, self.caps, init_key)))

    def load_checkpoint(self, path: str, init_key: tuple):
        """Returns ``(carry, host, paged)`` restored from ``path``."""
        with ckpt.load_npz_checked(
                path, ckpt.config_digest(self.config, self.caps,
                                         init_key)) as z:
            arrs = [z[f"c{i}"] for i in range(len(Carry._fields))]
            carry = Carry(*(jnp.asarray(a) for a in
                            widen_legacy_n_trans(arrs, Carry._fields)))
            paged = int(z["paged"])
        host = native.make_store(self.schema.P)
        ckpt.stream_rows_in(path + ".rows", host.append, paged,
                            expect_width=self.schema.P)
        ckpt.stream_rows_in(
            path + ".links",
            lambda blk: host.append_links(blk[:, 0], blk[:, 1]), paged,
            expect_width=2)
        return carry, host, paged

    def check(self, init_override: interp.PyState | None = None,
              on_progress=None, checkpoint: str | None = None,
              checkpoint_every_s: float = 300.0,
              resume: str | None = None,
              deadline_s: float | None = None,
              events: str | None = None) -> EngineResult:
        """``on_progress``/``events`` as in DeviceEngine.check: the shared
        per-segment ProgressRecord + run-event log (SURVEY §5).
        ``checkpoint``/``resume`` as in DeviceEngine, additionally
        snapshotting the host store.

        ``deadline_s`` time-boxes the search: segments stop once that many
        seconds have passed AFTER the first (compile-carrying) segment, and
        the result comes back with ``complete=False`` and the counts found
        so far — the bench's north-star-shaped throughput probe."""
        t0 = time.monotonic()
        tel = RunTelemetry(
            "paged", config=self.config, caps=self.caps,
            on_progress=on_progress, events=events,
            resumed=resume is not None,
            n0=1 if resume is None else None, t0=t0)
        try:
            return self._check_impl(tel, t0, init_override, checkpoint,
                                    checkpoint_every_s, resume, deadline_s)
        finally:
            tel.close()

    def _check_impl(self, tel, t0, init_override, checkpoint,
                    checkpoint_every_s, resume, deadline_s) -> EngineResult:
        bounds = self.bounds
        init_py = init_override if init_override is not None \
            else interp.init_state(bounds)
        init_vec = interp.to_vec(init_py, bounds)
        hi0, lo0 = sym_mod.init_fingerprint(self.config, init_py,
                                            init_vec)

        tel.run_start()
        for nm in self.config.invariants:
            if not inv_mod.py_invariant(nm)(init_py, bounds):
                res = EngineResult(
                    n_states=1, diameter=0, n_transitions=0,
                    coverage=Counter(),
                    violation=Violation(nm, init_py, [(None, init_py)]),
                    levels=[1], wall_s=time.monotonic() - t0)
                tel.run_end(res)
                return res

        if resume:
            carry, host, paged = self.load_checkpoint(resume, (hi0, lo0))
        else:
            host = native.make_store(self.schema.P)
            init_packed = self.schema.pack(init_vec.astype(np.int32), np)
            carry = self._init(
                jnp.asarray(init_packed, I32), jnp.uint32(hi0),
                jnp.uint32(lo0),
                jnp.bool_(interp.constraint_ok(init_py, bounds)))
            paged = 0
        pacer = pacing.SegmentPacer(self.seg_chunks, self.SEG_MIN,
                                    self.SEG_MAX, self.SEG_TARGET_S,
                                    self.SEG_CLAMP_S)
        budget = pacer.budget
        complete = True
        t_warm = None
        last_ckpt = time.monotonic()
        while True:
            if (deadline_s is not None and t_warm is not None
                    and time.monotonic() - t_warm > deadline_s):
                complete = False
                tel.stop_requested("deadline")
                break
            # Pause the device loop before unpaged rows could be overwritten:
            # rows < pause_at are safe while n_states - lvl_start <= ring.
            pause_at = paged + self.caps.ring // 2
            t_seg = time.monotonic()
            with tel.phases.phase("expand") as ph:
                carry, done, steps_d = self._segment(carry, jnp.int32(budget),
                                                     jnp.int32(pause_at))
                n_states = int(carry.n_states)
            with tel.phases.phase("export"):
                paged = self._pageout(carry, host, paged, n_states)
            if tel.active:
                lvl, n_trans, cov = jax.device_get(
                    (carry.lvl, carry.n_trans, carry.cov))
                tel.segment(
                    n_states=n_states, level=int(lvl),
                    n_transitions=acc64_int(n_trans),
                    coverage=dict(aggregate_coverage(self.table, cov)))
            if bool(done):
                break
            dt = time.monotonic() - t_seg
            # dt includes the pageout above — attributing it to chunk cost
            # overestimates, which is the safe direction for the watchdog.
            executed = max(1, int(steps_d))
            if checkpoint and (time.monotonic() - last_ckpt
                               >= checkpoint_every_s):
                with tel.phases.phase("snapshot"):
                    self.save_checkpoint(checkpoint, carry, host, paged,
                                         (hi0, lo0))
                tel.checkpoint(checkpoint, n_states)
                last_ckpt = time.monotonic()
            if t_warm is None:
                t_warm = time.monotonic()   # deadline starts post-compile
            budget = pacer.update(dt, executed)
            self.seg_chunks = budget

        (viol_g, viol_i, n_trans, fail, n_levels, levels_dev,
         cov_arr) = jax.device_get((
             carry.viol_g, carry.viol_i, carry.n_trans, carry.fail,
             carry.lvl, carry.levels, carry.cov))
        viol_g, fail = int(viol_g), int(fail)
        if fail:
            raise RuntimeError(
                f"paged search aborted: {decode_fail(fail)} "
                f"(caps={self.caps}) — grow PagedCapacities and rerun")
        levels_arr = [1] + [int(x) for x in levels_dev[:int(n_levels)]
                            if int(x) > 0]
        coverage: Counter = Counter()
        for a, inst in enumerate(self.table):
            if cov_arr[a]:
                coverage[inst.family] += int(cov_arr[a])

        violation = None
        if viol_g >= 0:
            chain_idx = host.trace_chain(viol_g)
            chain = []
            for k, g in enumerate(chain_idx):
                row = self.schema.unpack(host.read(int(g), 1)[0], np)
                _, lane_g = host.read_links(int(g), 1)
                py = interp.from_struct(st.unpack(row, self.lay, np),
                                        self.bounds)
                label = self.table[int(lane_g[0])].label() if k > 0 else None
                chain.append((label, py))
            violation = Violation(
                invariant=DEADLOCK
                if int(viol_i) == len(self.config.invariants)
                else self.config.invariants[int(viol_i)],
                state=chain[-1][1], trace=chain)
        host.close()

        result = EngineResult(
            n_states=n_states, diameter=len(levels_arr) - 1,
            n_transitions=acc64_int(n_trans), coverage=coverage,
            violation=violation, levels=levels_arr,
            wall_s=time.monotonic() - t0, complete=complete)
        tel.run_end(result)
        return result


def check(config: CheckConfig, caps: PagedCapacities | None = None,
          **kw) -> EngineResult:
    return PagedEngine(config, caps).check(**kw)
