"""Host-streamed frontier engine — no live-window ceiling (paged v2).

The host-paged engine (paged_engine.py) must hold the live BFS window
(current + next level) in an HBM ring.  The deployment target's 2 GiB
single-buffer limit caps that ring at 2^25 bit-packed rows — and the
5-server election space's level pairs outgrow ANY legal ring from level
~24 (measured: FAIL_RING at 53.8M orbits, runs/elect5v2.stats).  This
engine removes the ceiling by inverting the data flow:

- **The frontier streams host→device in fixed blocks.**  Every discovered
  state already lives in the host store (utils/native.py); each block of
  the current level is uploaded into a device frontier buffer, expanded in
  watchdog-safe segments, and replaced by the next block.  HBM never holds
  more than one block of frontier.
- **The ring only buffers appends** between pageouts.  New states append
  at (discovery index mod ring) and page out to the host store when the
  ring is half full — the loud-guard invariant is simply
  ``n_states - paged <= ring``, independent of level widths.
- **Level bookkeeping moves to the host** (it knows every level boundary:
  the discovery index at each advance).  The device segment is simpler
  than the paged engine's: expand chunks of the block, dedup, append.
- Only the fingerprint table still scales with the full space on device
  (~8 B/slot; the 2 GiB buffer limit caps it at 2^28 slots ≈ 134M states
  at load 0.5 — the next capacity frontier, which FAIL_PROBE guards
  loudly).

Streaming cost: one host→device upload of each level (bit-packed rows, so
~44 B/state at 5 servers) — measured single-digit seconds per 10M-row
level on the deployment tunnel, amortized over minutes of expansion.

Discovery order — and therefore counts, levels, coverage attribution and
first-violation — is byte-identical to the oracle and the other
single-chip engines (the parity tests assert it with blocks and rings
small enough to cycle many times per run).  Checkpoint/resume as in the
paged engine: host-store streams + device carry snapshot, digest-guarded.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import Counter
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.device_engine import (
    _EMPTY, _dedup_insert, BUCKET, FAIL_INDEX, FAIL_LEVEL, FAIL_PROBE,
    FAIL_RING, FAIL_WIDTH, aggregate_coverage, decode_fail, _acc64_add,
    _acc64_zero, acc64_int)
from raft_tla_tpu.engine import DEADLOCK, EngineResult, Violation
from raft_tla_tpu.models import interp, invariants as inv_mod, spec as S
from raft_tla_tpu.obs import RunTelemetry
from raft_tla_tpu.ops import bitpack
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym_mod
from raft_tla_tpu.utils import ckpt
from raft_tla_tpu.utils import native
from raft_tla_tpu.utils import pacing

I32 = jnp.int32
U32 = jnp.uint32


@dataclasses.dataclass(frozen=True)
class StreamedCapacities:
    """Static shapes.  ``block`` is the frontier upload granularity;
    ``ring`` buffers appends between pageouts (both independent of level
    widths); ``table`` bounds total distinct states at ~2 slots/state."""

    block: int = 1 << 20
    ring: int = 1 << 22
    table: int = 1 << 26
    levels: int = 1 << 12        # host-side level-count bound (bookkeeping)

    def __post_init__(self):
        for nm in ("block", "ring", "table"):
            v = getattr(self, nm)
            if v & (v - 1):
                raise ValueError(f"{nm}={v} must be a power of two")


class SCarry(NamedTuple):
    """Device carry between segments (the frontier block is an argument,
    not a carry member — the host replaces it per block)."""

    store: jax.Array     # [Rcap, P] append ring, bit-packed
    parent: jax.Array    # [Rcap] parent discovery index
    lane: jax.Array      # [Rcap]
    conflag: jax.Array   # [Rcap]
    tbl_hi: jax.Array    # [TB, BUCKET]
    tbl_lo: jax.Array    # [TB, BUCKET]
    n_states: jax.Array  # discovery count
    viol_g: jax.Array    # discovery index of first violation, -1
    viol_i: jax.Array
    n_trans: jax.Array   # [2] uint32 limbs
    cov: jax.Array       # [A]
    fail: jax.Array
    c: jax.Array         # chunk cursor within the current block


def _build_segment(config: CheckConfig, caps: StreamedCapacities, A: int,
                   W: int, schema: bitpack.BitSchema):
    B = config.chunk
    n_inv = len(config.invariants)
    # Orbit-scan variants (prescan, sig-prune) resolve from their env
    # gates at build time — the segment must be rebuilt to change them.
    step = kernels.build_step(config.bounds, config.spec,
                              tuple(config.invariants), config.symmetry,
                              view=config.view)
    Rcap = caps.ring
    rmask = Rcap - 1
    BIG = jnp.int32(np.iinfo(np.int32).max)
    IDX_CEIL = jnp.int32(np.iinfo(np.int32).max - 2 * B * A)

    def chunk_body(carry: SCarry) -> SCarry:
        (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
         viol_g, viol_i, n_trans, cov, fail, c) = carry
        # rows of the CURRENT BLOCK (fbuf/fcon are segment closures)
        r0 = c * B
        rows_b = r0 + jnp.arange(B, dtype=I32)       # block-local
        row_act = rows_b < block_rows
        bidx = jnp.minimum(rows_b, caps.block - 1)
        vecs = schema.unpack(fbuf[bidx], jnp)
        out = step(vecs)
        valid = out["valid"] & row_act[:, None] & fcon[bidx][:, None]
        n_trans = _acc64_add(n_trans, jnp.sum(valid.astype(I32)))
        fail = fail | jnp.any(valid & out["overflow"]) * FAIL_WIDTH

        fhi = out["fp_hi"].reshape(-1)
        flo = out["fp_lo"].reshape(-1)
        fvalid = valid.reshape(-1)
        tbl_hi, tbl_lo, is_new, pfail = _dedup_insert(
            tbl_hi, tbl_lo, fhi, flo, fvalid)
        fail = fail | jnp.any(pfail) * FAIL_PROBE

        pos = n_states + jnp.cumsum(is_new.astype(I32)) - 1
        n_new = jnp.sum(is_new.astype(I32))
        # appends must not lap rows not yet paged to the host — the ONLY
        # ring invariant in this engine (no level-window term)
        fail = fail | (n_states + n_new - paged_wm > Rcap) * FAIL_RING
        fail = fail | (n_states > IDX_CEIL) * FAIL_INDEX
        ok = is_new & (pos - paged_wm < Rcap)
        sl = jnp.where(ok, pos & rmask, Rcap)
        svecs = schema.pack(out["svecs"].reshape(B * A, W), jnp)
        store = store.at[sl].set(svecs, mode="drop")
        flat_b = jnp.arange(B * A, dtype=I32) // A
        flat_a = jnp.arange(B * A, dtype=I32) % A
        parent = parent.at[sl].set(block_start + r0 + flat_b, mode="drop")
        lane = lane.at[sl].set(flat_a, mode="drop")
        conflag = conflag.at[sl].set(out["con_ok"].reshape(-1), mode="drop")
        cov = cov.at[jnp.where(is_new, flat_a, A)].add(1, mode="drop")
        n_states = n_states + n_new

        inv_bad = is_new & jnp.any(
            ~out["inv_ok"].reshape(B * A, n_inv), axis=-1) if n_inv \
            else jnp.zeros((B * A,), bool)
        first = jnp.min(jnp.where(inv_bad, jnp.arange(B * A, dtype=I32),
                                  BIG))
        bad_inv = jnp.argmax(
            ~out["inv_ok"].reshape(B * A, n_inv)
            [jnp.minimum(first, B * A - 1)]) if n_inv else jnp.int32(0)
        g_target = pos[jnp.minimum(first, B * A - 1)]
        if config.check_deadlock:
            dead = row_act & fcon[bidx] & ~jnp.any(out["valid"], axis=1)
            drow = jnp.min(jnp.where(dead, jnp.arange(B, dtype=I32), BIG))
            dpos = jnp.where(drow < BIG // A, drow * A, BIG)
            use_dead = dpos < first
            first = jnp.minimum(first, dpos)
            g_target = jnp.where(
                use_dead, block_start + r0 + jnp.minimum(drow, B - 1),
                g_target)
            bad_inv = jnp.where(use_dead, jnp.int32(n_inv), bad_inv)
        has_viol = first < BIG
        new_viol = has_viol & (viol_g < 0)
        viol_g = jnp.where(new_viol, g_target, viol_g)
        viol_i = jnp.where(new_viol, bad_inv, viol_i)
        return SCarry(store, parent, lane, conflag, tbl_hi, tbl_lo,
                      n_states, viol_g, viol_i, n_trans, cov, fail, c + 1)

    def cond(sc):
        s, carry = sc
        n_chunks = (block_rows + B - 1) // B
        return ((carry.c < n_chunks) & (carry.viol_g < 0)
                & (carry.fail == 0) & (s < budget)
                & (carry.n_states < pause))

    def body(sc):
        s, carry = sc
        return s + 1, chunk_body(carry)

    def segment(carry, fbuf_, fcon_, budget_, paged_, block_start_,
                block_rows_):
        nonlocal fbuf, fcon, budget, pause, paged_wm, block_start, \
            block_rows
        fbuf, fcon = fbuf_, fcon_
        budget = budget_
        paged_wm = paged_
        pause = paged_ + Rcap // 2
        block_start, block_rows = block_start_, block_rows_
        steps, carry = jax.lax.while_loop(cond, body,
                                          (jnp.int32(0), carry))
        n_chunks = (block_rows + B - 1) // B
        return steps, carry.c >= n_chunks, carry

    fbuf = fcon = budget = pause = block_start = block_rows = None
    paged_wm = None
    return segment


class StreamedEngine:
    """Exhaustive checker with no live-window ceiling (host-RAM-bounded
    frontier AND store; only the fingerprint table scales on device)."""

    SEG_TARGET_S = 8.0
    SEG_CLAMP_S = 25.0
    SEG_MIN, SEG_MAX = 16, 1 << 16
    PAGE_ROWS = 1 << 16

    def __init__(self, config: CheckConfig,
                 caps: StreamedCapacities | None = None,
                 seg_chunks: int = 64):
        self.config = config
        self.bounds = config.bounds
        self.lay = st.Layout.of(self.bounds)
        self.table = S.action_table(self.bounds, config.spec)
        self.A = len(self.table)
        self.caps = caps or StreamedCapacities()
        if self.caps.ring < 2 * config.chunk * self.A:
            raise ValueError(
                f"ring={self.caps.ring} must be >= 2 * chunk * A = "
                f"{2 * config.chunk * self.A} (pageout headroom)")
        if self.caps.block < config.chunk:
            raise ValueError("block must be >= chunk")
        self.seg_chunks = seg_chunks
        self.schema = bitpack.BitSchema(self.bounds)
        self._segment = jax.jit(
            _build_segment(config, self.caps, self.A, self.lay.width,
                           self.schema),
            donate_argnums=(0,))
        self._gather = jax.jit(
            lambda carry, ridx: (carry.store[ridx], carry.parent[ridx],
                                 carry.lane[ridx], carry.conflag[ridx]))

    def _init_carry(self, hi0, lo0) -> SCarry:
        Rcap, TB = self.caps.ring, self.caps.table // BUCKET
        b0 = int(np.uint32(lo0) & np.uint32(TB - 1))
        tbl_hi = np.full((TB, BUCKET), _EMPTY, np.uint32)
        tbl_lo = np.full((TB, BUCKET), _EMPTY, np.uint32)
        tbl_hi[b0, 0] = hi0
        tbl_lo[b0, 0] = lo0
        return SCarry(
            store=jnp.zeros((Rcap, self.schema.P), I32),
            parent=jnp.full((Rcap,), -1, I32),
            lane=jnp.full((Rcap,), -1, I32),
            conflag=jnp.zeros((Rcap,), bool),
            tbl_hi=jnp.asarray(tbl_hi), tbl_lo=jnp.asarray(tbl_lo),
            n_states=jnp.int32(1), viol_g=jnp.int32(-1),
            viol_i=jnp.int32(0), n_trans=_acc64_zero(),
            cov=jnp.zeros((self.A,), I32), fail=jnp.int32(0),
            c=jnp.int32(0))

    def _pageout(self, carry, host, constore, paged: int,
                 n_states: int) -> int:
        """``constore`` is a width-1 host store of CONSTRAINT flags — the
        frontier re-upload needs them (expansion gates on conflag)."""
        rmask = self.caps.ring - 1
        iota = np.arange(self.PAGE_ROWS, dtype=np.int32)
        while paged < n_states:
            n = min(n_states - paged, self.PAGE_ROWS)
            gidx = np.minimum(paged + iota, n_states - 1)
            ridx = jnp.asarray(gidx & rmask)
            rows, par, lan, con = jax.device_get(
                self._gather(carry, ridx))
            host.append(rows[:n])
            host.append_links(par[:n], lan[:n])
            constore.append(con[:n].astype(np.int32)[:, None])
            paged += n
        return paged

    # -- checkpoint / resume --------------------------------------------

    def save_checkpoint(self, path: str, carry: SCarry, host, constore,
                        paged: int, level_ends: list, blocks_done: int,
                        init_key) -> None:
        """Snapshots are taken at BLOCK boundaries only (the host loop's
        invariant): re-expansion on resume would double-count transition/
        coverage counters, so the resume point must be exactly a completed
        block.  ``blocks_done`` = completed blocks of the frontier level.

        Streams extend INCREMENTALLY (ckpt.stream_rows_append): the host
        stores are append-only, so each snapshot writes only the suffix
        since the previous one — full rewrites cost ~10 idle-device
        minutes each at 10^8-orbit scale."""
        ckpt.stream_rows_append(path + ".rows", host.read, paged,
                                self.schema.P)

        def links_reader(start, n):
            par, lan = host.read_links(start, n)
            return np.stack([par, lan], axis=1)

        ckpt.stream_rows_append(path + ".links", links_reader, paged, 2)
        ckpt.stream_rows_append(path + ".con", constore.read, paged, 1)
        arrs = jax.device_get(carry)
        ckpt.atomic_savez(
            path,
            **{f"c{i}": np.asarray(x) for i, x in enumerate(arrs)},
            paged=np.int64(paged),
            level_ends=np.asarray(level_ends, np.int64),
            blocks_done=np.int64(blocks_done),
            config_digest=np.uint64(
                ckpt.config_digest(self.config, self.caps, init_key)))

    def load_checkpoint(self, path: str, init_key):
        with ckpt.load_npz_checked(
                path, ckpt.config_digest(self.config, self.caps,
                                         init_key)) as z:
            carry = SCarry(*(jnp.asarray(z[f"c{i}"])
                             for i in range(len(SCarry._fields))))
            paged = int(z["paged"])
            level_ends = [int(x) for x in z["level_ends"]]
            blocks_done = int(z["blocks_done"])
        host = native.make_store(self.schema.P)
        constore = native.make_store(1)
        ckpt.stream_rows_in(path + ".rows", host.append, paged,
                            expect_width=self.schema.P)
        ckpt.stream_rows_in(
            path + ".links",
            lambda blk: host.append_links(blk[:, 0], blk[:, 1]), paged,
            expect_width=2)
        ckpt.stream_rows_in(path + ".con", constore.append, paged,
                            expect_width=1)
        return carry, host, constore, paged, level_ends, blocks_done

    # -- main loop ------------------------------------------------------

    def check(self, init_override: interp.PyState | None = None,
              on_progress=None, checkpoint: str | None = None,
              checkpoint_every_s: float = 600.0,
              resume: str | None = None,
              deadline_s: float | None = None,
              events: str | None = None) -> EngineResult:
        t0 = time.monotonic()
        tel = RunTelemetry(
            "streamed", config=self.config, caps=self.caps,
            on_progress=on_progress, events=events,
            resumed=resume is not None,
            n0=1 if resume is None else None, t0=t0)
        try:
            return self._check_impl(tel, t0, init_override, checkpoint,
                                    checkpoint_every_s, resume, deadline_s)
        finally:
            tel.close()

    def _check_impl(self, tel, t0, init_override, checkpoint,
                    checkpoint_every_s, resume, deadline_s) -> EngineResult:
        bounds = self.bounds
        init_py = init_override if init_override is not None \
            else interp.init_state(bounds)
        init_vec = interp.to_vec(init_py, bounds)
        hi0, lo0 = sym_mod.init_fingerprint(self.config, init_py, init_vec)
        tel.run_start()

        for nm in self.config.invariants:
            if not inv_mod.py_invariant(nm)(init_py, bounds):
                res = EngineResult(
                    n_states=1, diameter=0, n_transitions=0,
                    coverage=Counter(),
                    violation=Violation(nm, init_py, [(None, init_py)]),
                    levels=[1], wall_s=time.monotonic() - t0)
                tel.run_end(res)
                return res

        B = self.config.chunk
        # Incremental snapshots (save_checkpoint) extend the checkpoint
        # path's stream files in place, trusting their existing prefix.
        # That trust is only valid for rows THIS run verified:
        # - fresh run: any streams at the checkpoint path are leftovers
        #   of some other run — delete them (a digest on the npz alone
        #   would not protect them);
        # - resume from the same path: rows beyond the npz's ``paged``
        #   were written by a later, superseded snapshot of a previous
        #   process — trim to ``paged`` so they are re-written from this
        #   run's own store, not assumed bit-identical.
        if checkpoint:
            if resume and os.path.abspath(resume) == \
                    os.path.abspath(checkpoint):
                pass                      # trimmed after load, below
            else:
                for suf in (".rows", ".links", ".con"):
                    try:
                        os.remove(checkpoint + suf)
                    except FileNotFoundError:
                        pass
        if resume:
            (carry, host, constore, paged, level_ends,
             blocks_done) = self.load_checkpoint(resume, (hi0, lo0))
            if checkpoint and os.path.abspath(resume) == \
                    os.path.abspath(checkpoint):
                ckpt.trim_stream(checkpoint + ".rows", paged,
                                 self.schema.P)
                ckpt.trim_stream(checkpoint + ".links", paged, 2)
                ckpt.trim_stream(checkpoint + ".con", paged, 1)
        else:
            carry = self._init_carry(np.uint32(hi0), np.uint32(lo0))
            host = native.make_store(self.schema.P)
            constore = native.make_store(1)
            init_packed = self.schema.pack(
                np.asarray(init_vec, np.int32), np)
            host.append(init_packed[None, :])
            host.append_links(np.asarray([-1], np.int32),
                              np.asarray([-1], np.int32))
            constore.append(np.asarray(
                [[interp.constraint_ok(init_py, bounds)]], np.int32))
            paged = 1
            # level_ends[k] = discovery index just past level k
            level_ends = [1]
            blocks_done = 0              # completed blocks, frontier level

        pacer = pacing.SegmentPacer(self.seg_chunks, self.SEG_MIN,
                                    self.SEG_MAX, self.SEG_TARGET_S,
                                    self.SEG_CLAMP_S)
        budget = pacer.budget
        complete = True
        t_warm = None
        last_ckpt = time.monotonic()
        Fcap = self.caps.block
        stopped = False

        while not stopped:
            lvl_lo = level_ends[-2] if len(level_ends) > 1 else 0
            lvl_hi = level_ends[-1]
            for b_start in range(lvl_lo + blocks_done * Fcap, lvl_hi,
                                 Fcap):
                b_rows = min(Fcap, lvl_hi - b_start)
                with tel.phases.phase("upload") as ph:
                    blk = host.read(b_start, b_rows)
                    con = constore.read(b_start, b_rows)[:, 0].astype(bool)
                    if b_rows < Fcap:
                        blk = np.concatenate([blk, np.zeros(
                            (Fcap - b_rows, self.schema.P), np.int32)])
                        con = np.concatenate(
                            [con, np.zeros((Fcap - b_rows,), bool)])
                    fbuf, fcon = ph.sync((jnp.asarray(blk),
                                          jnp.asarray(con)))
                carry = carry._replace(c=jnp.int32(0))
                block_done = False
                while not block_done:
                    if (deadline_s is not None and t_warm is not None
                            and time.monotonic() - t_warm > deadline_s):
                        complete = False
                        stopped = True
                        tel.stop_requested("deadline")
                        break
                    t_seg = time.monotonic()
                    with tel.phases.phase("expand"):
                        steps_d, done_d, carry = self._segment(
                            carry, fbuf, fcon, jnp.int32(budget),
                            jnp.int32(paged), jnp.int32(b_start),
                            jnp.int32(b_rows))
                        n_states, fail_v, viol_v = map(int, jax.device_get(
                            (carry.n_states, carry.fail, carry.viol_g)))
                    with tel.phases.phase("export"):
                        paged = self._pageout(carry, host, constore, paged,
                                              n_states)
                    if tel.active:
                        n_trans, cov = jax.device_get(
                            (carry.n_trans, carry.cov))
                        tel.segment(
                            n_states=n_states, level=len(level_ends),
                            n_transitions=acc64_int(n_trans),
                            coverage=dict(aggregate_coverage(
                                self.table, cov)))
                    if fail_v or viol_v >= 0:
                        stopped = True
                        break
                    dt = time.monotonic() - t_seg
                    if t_warm is None:
                        t_warm = time.monotonic()
                    budget = pacer.update(dt, max(1, int(steps_d)))
                    self.seg_chunks = budget
                    block_done = bool(done_d)
                if stopped:
                    break
                blocks_done += 1
                # snapshots land exactly at block boundaries (see
                # save_checkpoint: resume must never re-expand rows)
                if checkpoint and (time.monotonic() - last_ckpt
                                   >= checkpoint_every_s):
                    with tel.phases.phase("snapshot"):
                        self.save_checkpoint(checkpoint, carry, host,
                                             constore, paged, level_ends,
                                             blocks_done, (hi0, lo0))
                    tel.checkpoint(checkpoint)
                    last_ckpt = time.monotonic()
            if stopped:
                break
            blocks_done = 0
            n_now = int(carry.n_states)
            if n_now == level_ends[-1]:          # no new states: done
                break
            level_ends.append(n_now)
            if len(level_ends) > self.caps.levels:
                # host-side condition, same loud-fail contract/wording as
                # the device-side FAIL_* path
                raise RuntimeError(
                    f"streamed search aborted: {decode_fail(FAIL_LEVEL)} "
                    f"(caps={self.caps}) — grow StreamedCapacities and "
                    "rerun")

        (viol_g, viol_i, n_trans, fail, cov_arr) = jax.device_get((
            carry.viol_g, carry.viol_i, carry.n_trans, carry.fail,
            carry.cov))
        viol_g, fail = int(viol_g), int(fail)
        if fail:
            raise RuntimeError(
                f"streamed search aborted: {decode_fail(fail)} "
                f"(caps={self.caps}) — grow StreamedCapacities and rerun")
        n_states = int(carry.n_states)
        levels_arr = [level_ends[0]] + [
            level_ends[k] - level_ends[k - 1]
            for k in range(1, len(level_ends))]
        coverage = aggregate_coverage(self.table, cov_arr)

        violation = None
        if viol_g >= 0:
            chain_idx = host.trace_chain(viol_g)
            chain = []
            for k, g in enumerate(chain_idx):
                row = self.schema.unpack(host.read(int(g), 1)[0], np)
                _, lane_g = host.read_links(int(g), 1)
                py = interp.from_struct(st.unpack(row, self.lay, np),
                                        self.bounds)
                label = self.table[int(lane_g[0])].label() if k > 0 \
                    else None
                chain.append((label, py))
            violation = Violation(
                invariant=DEADLOCK
                if int(viol_i) == len(self.config.invariants)
                else self.config.invariants[int(viol_i)],
                state=chain[-1][1], trace=chain)
        host.close()
        constore.close()

        result = EngineResult(
            n_states=n_states, diameter=len(levels_arr) - 1,
            n_transitions=acc64_int(n_trans), coverage=coverage,
            violation=violation, levels=levels_arr,
            wall_s=time.monotonic() - t0, complete=complete)
        tel.run_end(result)
        return result


def check(config: CheckConfig, caps: StreamedCapacities | None = None,
          **kw) -> EngineResult:
    return StreamedEngine(config, caps).check(**kw)
