"""Fixed-width tensor state schema — the L1 layer (SURVEY §7.0.1).

The spec's 13 non-history variables (``raft.tla:50-86`` plus ``messages``,
``raft.tla:32``) map to a struct of small int32 arrays; a whole state also
round-trips to a flat ``int32[W]`` vector (the frontier storage / fingerprint
form).  History variables (``elections`` ``raft.tla:39``, ``allLogs``
``raft.tla:44``, ``voterLog`` ``raft.tla:77``) are proof-only — read by no
guard — and are stripped in parity mode (SURVEY §7.0.3).

Struct fields (n = servers, L = log capacity, S = message slots):

==============  ========  =====================================================
field           shape     spec variable
==============  ========  =====================================================
role            (n,)      ``state``        (raft.tla:52)  0/1/2 = F/C/L
term            (n,)      ``currentTerm``  (raft.tla:50)
votedFor        (n,)      ``votedFor``     (raft.tla:55)  0 = Nil, else id+1
commitIndex     (n,)      ``commitIndex``  (raft.tla:63)
logLen          (n,)      ``Len(log[i])``  (raft.tla:61)
logTerm         (n, L)    ``log[i][k].term``  (1-based k -> column k-1)
logVal          (n, L)    ``log[i][k].value``  (values 1..V; 0 = no entry)
vResp           (n,)      ``votesResponded`` (raft.tla:69) as bitmask
vGrant          (n,)      ``votesGranted``   (raft.tla:72) as bitmask
nextIndex       (n, n)    ``nextIndex``    (raft.tla:82)
matchIndex      (n, n)    ``matchIndex``   (raft.tla:85)
msgHi/Lo/Count  (S,)      the ``messages`` bag (raft.tla:32), ops/msgbits.py
==============  ========  =====================================================

Canonical form (required before fingerprinting — the bag is unordered, and
sequences are padded):

- message slots sorted by (occupied-first, hi, lo); empty slots are all-zero;
- log columns >= logLen[i] are zero;
- everything else is canonical by construction (bitmask sets, dense arrays).

All transition kernels preserve canonical zero-padding functionally, and
:func:`canonicalize` restores slot order after bag mutations.

The module is dual-backend: every function takes the array namespace ``xp``
(``numpy`` or ``jax.numpy``) so the host oracle and the device kernels share
one implementation, bit for bit.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models.spec import FOLLOWER, NIL

STATE_FIELDS = ("role", "term", "votedFor", "commitIndex", "logLen",
                "logTerm", "logVal", "vResp", "vGrant",
                "nextIndex", "matchIndex", "msgHi", "msgLo", "msgCount")

# Faithful-mode extras (SURVEY §7.0.3b), appended after the parity fields so
# parity-mode vectors are untouched.  Log-valued data is stored as ranks in
# the bounded log universe (ops/loguniv.py):
#   allLogs  (Wa,)   U-bit bitmask of log ranks        (raft.tla:44)
#   vLog     (n, n)  voterLog[i][j] as rank+1, 0 = absent (raft.tla:77)
#   eTerm    (E,)    elections slots (raft.tla:39); 0 = empty slot
#   eLeader  (E,)    eleader (server id; 0 when slot empty)
#   eLog     (E,)    elog as rank
#   eVotes   (E,)    evotes as a server bitmask
#   eVLog    (E, n)  evoterLog[j] as rank+1, 0 = absent
HISTORY_FIELDS = ("allLogs", "vLog", "eTerm", "eLeader", "eLog",
                  "eVotes", "eVLog")


@dataclasses.dataclass(frozen=True)
class Layout:
    """Shapes and flat-vector offsets for a bounds instance."""

    n: int
    L: int
    S: int
    E: int = 0       # elections slots (faithful mode; 0 = parity mode)
    Wa: int = 0      # allLogs bitmask words

    @classmethod
    def of(cls, bounds: Bounds) -> "Layout":
        if not bounds.history:
            return cls(n=bounds.n_servers, L=bounds.log_cap,
                       S=bounds.msg_cap)
        from raft_tla_tpu.ops.loguniv import LogUniverse
        return cls(n=bounds.n_servers, L=bounds.log_cap, S=bounds.msg_cap,
                   E=bounds.max_elections,
                   Wa=LogUniverse.of(bounds).mask_words)

    @property
    def history(self) -> bool:
        return self.E > 0

    @property
    def shapes(self) -> dict:
        n, L, S, E = self.n, self.L, self.S, self.E
        out = {
            "role": (n,), "term": (n,), "votedFor": (n,),
            "commitIndex": (n,), "logLen": (n,),
            "logTerm": (n, L), "logVal": (n, L),
            "vResp": (n,), "vGrant": (n,),
            "nextIndex": (n, n), "matchIndex": (n, n),
            "msgHi": (S,), "msgLo": (S,), "msgCount": (S,),
        }
        if self.history:
            out.update({
                "allLogs": (self.Wa,), "vLog": (n, n),
                "eTerm": (E,), "eLeader": (E,), "eLog": (E,),
                "eVotes": (E,), "eVLog": (E, n),
            })
        return out

    @property
    def fields(self) -> tuple:
        return STATE_FIELDS + (HISTORY_FIELDS if self.history else ())

    @property
    def width(self) -> int:
        return sum(int(np.prod(s)) for s in self.shapes.values())


def init_struct(bounds: Bounds, xp):
    """The unique initial state (``Init``, ``raft.tla:155-160``).

    currentTerm = 1, state = Follower, votedFor = Nil (``raft.tla:143-145``);
    empty vote sets (``raft.tla:146-147``); nextIndex = 1, matchIndex = 0
    (``raft.tla:151-152``); empty logs, commitIndex = 0 (``raft.tla:153-154``);
    empty message bag (``raft.tla:155``).
    """
    lay = Layout.of(bounds)
    n, L, S = lay.n, lay.L, lay.S
    i32 = xp.int32
    out = {
        "role": xp.full((n,), FOLLOWER, dtype=i32),
        "term": xp.ones((n,), dtype=i32),
        "votedFor": xp.full((n,), NIL, dtype=i32),
        "commitIndex": xp.zeros((n,), dtype=i32),
        "logLen": xp.zeros((n,), dtype=i32),
        "logTerm": xp.zeros((n, L), dtype=i32),
        "logVal": xp.zeros((n, L), dtype=i32),
        "vResp": xp.zeros((n,), dtype=i32),
        "vGrant": xp.zeros((n,), dtype=i32),
        "nextIndex": xp.ones((n, n), dtype=i32),
        "matchIndex": xp.zeros((n, n), dtype=i32),
        "msgHi": xp.zeros((S,), dtype=i32),
        "msgLo": xp.zeros((S,), dtype=i32),
        "msgCount": xp.zeros((S,), dtype=i32),
    }
    if lay.history:
        # InitHistoryVars (raft.tla:140-142): elections = {}, allLogs = {},
        # voterLog = per-server empty map.
        n, E, Wa = lay.n, lay.E, lay.Wa
        out.update({
            "allLogs": xp.zeros((Wa,), dtype=i32),
            "vLog": xp.zeros((n, n), dtype=i32),
            "eTerm": xp.zeros((E,), dtype=i32),
            "eLeader": xp.zeros((E,), dtype=i32),
            "eLog": xp.zeros((E,), dtype=i32),
            "eVotes": xp.zeros((E,), dtype=i32),
            "eVLog": xp.zeros((E, n), dtype=i32),
        })
    return out


def pack(struct, xp):
    """Struct -> flat int32[W] vector (field order = parity then history)."""
    fields = STATE_FIELDS + (HISTORY_FIELDS if "allLogs" in struct else ())
    return xp.concatenate([xp.reshape(struct[f], (-1,)) for f in fields])


def unpack(vec, lay: Layout, xp):
    """int32[..., W] vector(s) -> struct (leading batch dims pass
    through: [W] -> per-field ``shape``, [C, W] -> ``(C,) + shape``)."""
    out, off = {}, 0
    batch = tuple(vec.shape[:-1])
    for f, shape in lay.shapes.items():
        size = int(np.prod(shape))
        out[f] = xp.reshape(vec[..., off:off + size],
                            batch + tuple(shape)).astype(xp.int32)
        off += size
    return out


def _oddeven_pairs(m: int) -> tuple:
    """Odd-even transposition sorting-network comparator pairs for ``m``
    slots — a data-independent sort: m rounds of adjacent compare-swaps."""
    return tuple((i, i + 1) for r in range(m)
                 for i in range(r % 2, m - 1, 2))


def _network_sort(keys: list, vals: list, m: int, xp):
    """Sort ``m`` slots by the lexicographic key tuple via a branchless
    comparator network; returns the reordered ``vals``.

    Bit-identical to a lexsort-based gather: the key tuples are either
    strictly ordered (occupied slots always differ, see callers) or the
    full rows are identical (empty slots), so every correct sort yields
    the same sequence.  A network of selects is what the orbit pass needs:
    under ``lax.scan`` over the permutation group, a vmapped ``lexsort``
    in the loop body was ~90% of the whole symmetry cost (measured on
    TPU, round 2), while compare-swaps fuse into the surrounding
    elementwise work.
    """
    # Keys and vals overlap (e.g. hi/lo are both); swap each distinct
    # array once per comparator, not once per appearance.
    arrs: list = []
    pos: dict = {}
    for a in list(keys) + list(vals):
        if id(a) not in pos:
            pos[id(a)] = len(arrs)
            arrs.append(a)
    key_ix = [pos[id(k)] for k in keys]
    val_ix = [pos[id(v)] for v in vals]
    for i, j in _oddeven_pairs(m):
        le = None       # key[i] <= key[j], built least-significant first
        for kx in reversed(key_ix):
            k = arrs[kx]
            if le is None:
                le = k[..., i] <= k[..., j]
            else:
                le = (k[..., i] < k[..., j]) | ((k[..., i] == k[..., j]) & le)
        for a_i, a in enumerate(arrs):
            ai, aj = a[..., i], a[..., j]
            arrs[a_i] = a.at[..., i].set(xp.where(le, ai, aj)) \
                .at[..., j].set(xp.where(le, aj, ai)) \
                if xp is not np else _np_swap(a, i, j, le)
    return [arrs[ix] for ix in val_ix]


def _np_swap(a, i: int, j: int, le):
    out = a.copy()
    out[..., i] = np.where(le, a[..., i], a[..., j])
    out[..., j] = np.where(le, a[..., j], a[..., i])
    return out


def canonicalize(struct, xp):
    """Sort message slots into canonical order: occupied first, then (hi, lo).

    The bag is an unordered function (``raft.tla:32``); slot order is an
    encoding artifact and must not influence the fingerprint.  Distinct
    occupied slots always differ in (hi, lo) — the bag merges equal messages
    into one multiplicity (``WithMessage``, ``raft.tla:106-110``) — so the
    sort is a total order and canonicalization is unique (the comparator
    network in :func:`_network_sort` therefore reproduces the historical
    lexsort bit-for-bit).
    """
    occupied = struct["msgCount"] > 0
    # Enforce, not just assume, the all-zero empty-slot form: a kernel that
    # decrements a count to 0 may leave stale content words behind, which
    # would split fingerprints of identical bags.
    hi = xp.where(occupied, struct["msgHi"], 0)
    lo = xp.where(occupied, struct["msgLo"], 0)
    ct = xp.where(occupied, struct["msgCount"], 0)
    M = int(struct["msgHi"].shape[-1])
    occ_key = (~occupied).astype(xp.int32)
    out = dict(struct)
    out["msgHi"], out["msgLo"], out["msgCount"] = _network_sort(
        [occ_key, hi, lo], [hi, lo, ct], M, xp)
    if "eTerm" in struct:
        # elections is a set (raft.tla:39); slot order is an encoding
        # artifact, canonicalized exactly like the message bag.  eTerm > 0
        # marks occupancy (election terms start at 1, raft.tla:143).
        eocc_key = (~(struct["eTerm"] > 0)).astype(xp.int32)
        E = int(struct["eTerm"].shape[-1])
        evl_cols = [struct["eVLog"][..., c]
                    for c in range(struct["eVLog"].shape[-1])]
        keys = [eocc_key, struct["eTerm"], struct["eLeader"],
                struct["eLog"], struct["eVotes"]] + evl_cols
        sorted_vals = _network_sort(
            keys, [struct["eTerm"], struct["eLeader"], struct["eLog"],
                   struct["eVotes"]] + evl_cols, E, xp)
        out["eTerm"], out["eLeader"], out["eLog"], out["eVotes"] = \
            sorted_vals[:4]
        out["eVLog"] = xp.stack(sorted_vals[4:], axis=-1)
    return out


def occupied_slots(struct, xp):
    """Mask of slots holding a bag element (``m \\in DOMAIN messages``)."""
    return struct["msgCount"] > 0


def constraint_ok(struct, bounds: Bounds, xp):
    """The StateConstraint (SURVEY §0 defect 2): scalar bool.

    ``/\\ \\A i : currentTerm[i] <= MaxTerm /\\ Len(log[i]) <= MaxLogLen
    /\\ Cardinality(DOMAIN messages) <= MaxMsgs /\\ \\A m : messages[m] <= MaxDup``

    States violating it are counted and invariant-checked but not expanded —
    TLC CONSTRAINT semantics.
    """
    return (xp.all(struct["term"] <= bounds.max_term)
            & xp.all(struct["logLen"] <= bounds.max_log)
            & (xp.sum((struct["msgCount"] > 0).astype(xp.int32))
               <= bounds.max_msgs)
            & xp.all(struct["msgCount"] <= bounds.max_dup))
