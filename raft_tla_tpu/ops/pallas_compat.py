"""Shared platform probe + execution-mode switch for the Pallas kernels.

Both hand-scheduled kernels in the tree (the standalone fingerprint,
ops/pallas_fp.py, and the fused-step megakernel, ops/pallas_step.py)
need the same decision made the same way: compile for Mosaic when a TPU
backend is present, run the kernel under the Pallas interpreter when the
caller is testing on CPU, and — where a bit-identical jnp twin exists —
fall back to it off-TPU rather than paying interpreter overhead in
production paths.  One definition site so the two kernels can never
disagree about what "off-TPU" means.

Modes (returned by :func:`resolve`):

- ``MOSAIC``    — real ``pl.pallas_call`` compile; requires a TPU.
- ``INTERPRET`` — ``pallas_call(interpret=True)``: the kernel body runs
  as ordinary traced JAX under the grid emulator.  Bit-identical to the
  Mosaic build by Pallas's contract; this is how every CPU parity test
  executes the kernels.
- ``JNP``       — skip Pallas entirely and use the caller's portable
  jnp twin (only offered when the caller HAS one; the megakernel's twin
  is the XLA step itself, selected a level above by the gate).
"""

from __future__ import annotations

import jax

MOSAIC = "mosaic"
INTERPRET = "interpret"
JNP = "jnp"


def on_tpu() -> bool:
    """True when the default JAX backend is a TPU."""
    return jax.default_backend() == "tpu"


def resolve(interpret: bool | None, *, jnp_fallback: bool) -> str:
    """Pick the execution mode for a Pallas kernel call.

    ``interpret=True`` forces the interpreter (CPU tests assert parity
    through this path); ``interpret=False`` forces a real Mosaic build
    (loud failure off-TPU beats silently testing nothing); ``None``
    means auto: Mosaic on TPU, otherwise the jnp twin when the caller
    has one (``jnp_fallback=True``), else the interpreter.
    """
    if interpret:
        return INTERPRET
    if on_tpu():
        return MOSAIC
    if interpret is None:
        return JNP if jnp_fallback else INTERPRET
    return MOSAIC                    # interpret=False off-TPU: fail loudly
