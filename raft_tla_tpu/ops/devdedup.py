"""Device-resident exact within-level fingerprint dedup for the DDD
engines (``RAFT_TLA_DEVDEDUP`` / ``--device-dedup``).

The DDD loop's remaining structural host dependency (ROADMAP item 5):
every candidate fingerprint — including within-level duplicates the
lossy filter evicted and re-sighted — crosses the d2h tunnel to the
master keyset.  This module is the hot tier of a two-tier dedup: an
HBM-resident **exact** set of the fingerprints already streamed *this
level*, applied to each segment's output buffers before export, so only
first-occurrence-this-level rows are compacted and transferred.  The
host LSM keyset (utils/keyset) stays the cold tier and the sole
correctness authority.

**Why dropping is sound (the widening argument, inverted).**  The set
only ever contains keys that were *kept* — i.e. already exported to the
host earlier this level.  A candidate is dropped iff its exact (hi, lo)
key is present, so every dropped row is one ``master.dedup`` would have
rejected as a duplicate; first occurrences always survive, in stream
order, because compaction preserves relative order.  Therefore
n_states, n_transitions (counted in-segment, pre-filter), parent
choice (first discoverer), level boundaries, checkpoints, and
violation/deadlock traces are byte-identical on vs off.  Every lossy
path in the set itself — probe overflow, capacity truncation, the
all-ones sentinel — resolves to *streaming* the candidate, never to
dropping it: uncertainty widens the stream and the host dedups exactly,
the same one-sided contract ``ddd_engine._filter_insert`` documents.

Two interchangeable backends behind one ``(state, keys, n) -> (state,
keep, idx, new_n, hits)`` interface:

- ``"hash"``: a bucketized open-addressing (hi, lo) table driven by
  ``device_engine._dedup_insert`` — the table engines' proven exact
  insert-if-absent protocol (hashed claim domain, scatter-min first-
  discoverer resolution, duplicate-free scatters).  A lane whose probe
  is unresolved at ``_MAX_PROBE`` (table too full) simply streams and
  is not inserted.
- ``"sort"``: a portable sorted-array set — one stable
  ``jax.lax.sort`` over (set ++ batch) keyed on (hi, lo) generalizes
  ``ddd_engine._filter_insert``'s two-sort first-occurrence pass from
  lossy filter to exact set: stability puts set entries before equal
  batch lanes and batch lanes in stream order, so ``same_as_prev``
  marks exactly the duplicates.  The union's first-occurrence keys
  (smallest ``capacity`` of them on overflow) become the next set.
  This arm has no while_loop and no claim protocol — the CPU /
  interpret-mode arm and the parity oracle for ``"hash"``.

Keys equal to the table sentinel (both words all-ones) are never
inserted and always stream in BOTH backends — a real all-ones
fingerprint would alias the hash table's empty slot and the sort
backend's padding, so it is excluded identically (widening-safe), and
backend keep-decisions stay equivalent.

The set is **within-level** by construction: the engine resets it at
every level boundary (and resume starts it empty — mid-level resumes
just re-stream, which the master dedups).  The gate is resolved once at
engine construction like sig-prune/hostdedup/prefetch and is
deliberately NOT part of the checkpoint digest: snapshots resume across
either gate setting in both directions.

Auto policy: measured by ``runs/devdedup_ab.py`` per the sig-prune /
hostdedup protocol (bracketing fiducials, interleaved reps, per-level
export-row parity) — see ``_auto_backend`` below and RESULTS.md
"Device dedup A/B".
"""

from __future__ import annotations

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.device_engine import BUCKET, _EMPTY, _dedup_insert

I32 = jnp.int32

ENV_DEVDEDUP = "RAFT_TLA_DEVDEDUP"

# The sort backend re-sorts (capacity + seg_rows) keys every segment, so
# its set is clamped: beyond this the O((S+O) log(S+O)) pass dominates a
# segment and overflowed keys just re-stream (widening-safe).
_SORT_CAP = 1 << 17


def _auto_backend() -> str | None:
    """The ``auto`` verdict (runs/devdedup_ab.py, RESULTS.md "Device
    dedup A/B"): on this 1-core CPU container the filter pass and the
    harvest loop time-slice one core and d2h is a memcpy, so the
    export-row reduction (measured exact — off rows == on rows + hits
    held at all 74 parity segments — but only ~0.1% of rows at the
    flagship shape, whose 2^22-slot filter leaks few within-level
    re-sights) cost 0.43-0.44x warm rate instead of buying wall time —
    the sig-prune precedent, honest refutation -> auto=OFF, with the
    on-chip re-A/B queued under ROADMAP item 2 (PCIe d2h is where the
    dropped rows are real bandwidth, and the eviction-heavy elect5
    capacity regime is where the duplicate rate is not 0.1%)."""
    return None


def devdedup_backend(env: str | None = None) -> str | None:
    """Resolve the device-dedup gate: None (off), ``"hash"`` or
    ``"sort"``.  ``on`` forces the hash backend (the TPU-native arm);
    ``hash``/``sort`` force a specific backend; ``auto`` (or unset)
    applies the measured policy."""
    v = (os.environ.get(ENV_DEVDEDUP, "") if env is None else env)
    v = v.strip().lower()
    if v in ("", "auto"):
        return _auto_backend()
    if v in ("0", "off", "false", "no"):
        return None
    if v in ("1", "on", "true", "yes", "hash"):
        return "hash"
    if v == "sort":
        return "sort"
    raise ValueError(
        f"{ENV_DEVDEDUP}={v!r}: expected auto, on, off, hash or sort")


class DevSet(NamedTuple):
    """The device set between segments (serial state, donated).

    hash: ``hi``/``lo`` are the ``[capacity // BUCKET, BUCKET]`` table
    words (``_EMPTY`` = free slot), ``n`` unused (0).  sort: ``hi``/
    ``lo`` are the ``[capacity]`` first-occurrence key array padded with
    ``_EMPTY``, ``n`` the live entry count."""

    hi: jax.Array
    lo: jax.Array
    n: jax.Array


def init_set(capacity: int, backend: str) -> DevSet:
    """Empty per-level set state as host numpy (callers device_put it —
    the shard engine with a per-shard NamedSharding)."""
    if backend == "hash":
        if capacity & (capacity - 1):
            raise ValueError(f"devdedup capacity {capacity} must be a "
                             "power of two (bucket-mask probe)")
        tb = max(capacity // BUCKET, 1)
        return DevSet(hi=np.full((tb, BUCKET), _EMPTY, np.uint32),
                      lo=np.full((tb, BUCKET), _EMPTY, np.uint32),
                      n=np.int32(0))
    if backend == "sort":
        cap = min(capacity, _SORT_CAP)
        return DevSet(hi=np.full((cap,), _EMPTY, np.uint32),
                      lo=np.full((cap,), _EMPTY, np.uint32),
                      n=np.int32(0))
    raise ValueError(f"unknown devdedup backend {backend!r}")


def _compact(keep, lane):
    """Stream-order compaction gather: ``idx[:new_n]`` are the kept
    lanes in original order (tail positions never read — the harvest
    slices ``[:new_n]`` and the next segment rewrites from cursor 0)."""
    OC = keep.shape[0]
    kpos = jnp.cumsum(keep.astype(I32)) - 1
    idx = jnp.zeros((OC,), I32).at[
        jnp.where(keep, kpos, OC)].set(lane, mode="drop")
    return idx, jnp.sum(keep.astype(I32))


def _hash_filter(state: DevSet, key_hi, key_lo, n):
    OC = key_hi.shape[0]
    lane = jnp.arange(OC, dtype=I32)
    valid = lane < n
    sent = (key_hi == _EMPTY) & (key_lo == _EMPTY)
    act = valid & ~sent
    thi, tlo, is_new, unres = _dedup_insert(
        state.hi, state.lo, key_hi, key_lo, act)
    # keep = first-occurrence-this-level (inserted), sentinel, or probe-
    # unresolved (not inserted — streams now and again if re-sighted);
    # drop only lanes RESOLVED as exact duplicates.
    keep = valid & (sent | is_new | unres)
    hits = jnp.sum((valid & ~keep).astype(I32))
    idx, new_n = _compact(keep, lane)
    return DevSet(thi, tlo, state.n), keep, idx, new_n, hits


def _sort_filter(state: DevSet, key_hi, key_lo, n):
    OC = key_hi.shape[0]
    S = state.hi.shape[0]
    lane = jnp.arange(OC, dtype=I32)
    valid = lane < n
    sent = (key_hi == _EMPTY) & (key_lo == _EMPTY)
    act = valid & ~sent
    # Masked lanes sort into the all-ones padding run at the back; their
    # dup flags are overridden by ``valid``/``sent`` below and the
    # padding key is excluded from the rebuilt set.
    chi = jnp.concatenate([state.hi, jnp.where(act, key_hi, _EMPTY)])
    clo = jnp.concatenate([state.lo, jnp.where(act, key_lo, _EMPTY)])
    src = jnp.concatenate([jnp.full((S,), -1, I32), lane])
    shi, slo, ssrc = jax.lax.sort((chi, clo, src), num_keys=2,
                                  is_stable=True)
    # Stability: equal keys keep operand order — set entry first, then
    # batch lanes in stream order — so same_as_prev marks exactly the
    # non-first occurrences (the _filter_insert pass, made exact).
    same = jnp.concatenate([
        jnp.zeros((1,), bool),
        (shi[1:] == shi[:-1]) & (slo[1:] == slo[:-1])])
    dup = jnp.zeros((OC,), bool).at[
        jnp.where(ssrc >= 0, ssrc, OC)].set(same, mode="drop")
    keep = valid & (sent | ~dup)
    hits = jnp.sum((valid & ~keep).astype(I32))
    # Rebuild the set as the union's first-occurrence keys; on capacity
    # overflow the largest keys fall out and simply re-stream later.
    pad = (shi == _EMPTY) & (slo == _EMPTY)
    uniq = ~same & ~pad
    upos = jnp.cumsum(uniq.astype(I32)) - 1
    tgt = jnp.where(uniq & (upos < S), upos, S)
    nhi = jnp.full((S,), _EMPTY, jnp.uint32).at[tgt].set(shi, mode="drop")
    nlo = jnp.full((S,), _EMPTY, jnp.uint32).at[tgt].set(slo, mode="drop")
    nn = jnp.minimum(jnp.sum(uniq.astype(I32)), S)
    idx, new_n = _compact(keep, lane)
    return DevSet(nhi, nlo, nn), keep, idx, new_n, hits


def make_filter(backend: str):
    """The segment-output filter for ``backend``: ``filter_fn(state,
    key_hi, key_lo, n) -> (state, keep, idx, new_n, hits)`` — pure and
    jit/shard_map-safe.  ``n`` is the segment cursor (lanes >= n are
    stale buffer contents and pass through masked); ``keep[lane]`` says
    lane survives; ``idx``/``new_n`` are the order-preserving compaction
    gather; ``hits`` counts dropped (already-streamed-this-level)
    rows.  Shapes come from the arguments, so one filter serves any
    (capacity, seg_rows) pairing."""
    if backend == "hash":
        return _hash_filter
    if backend == "sort":
        return _sort_filter
    raise ValueError(f"unknown devdedup backend {backend!r}")
