"""Bit-packed state rows — the canonical pack kernel (SURVEY §2.8).

The flat ``int32[W]`` state vector (ops/state.py) spends a full 32-bit word
on every field element, though no field needs more than 29 bits and most
need 2-6: the 3-server/2-value flagship layout is 60 words (240 B) carrying
~390 useful bits (~49 B).  HBM capacity and host↔device pageout bandwidth
are the checker's scaling limits (the full 3s/2v run died when a BFS level
pair outgrew the ring), so the paged engine stores rows *bit-packed* at
~4-5x density and unpacks only the chunk being expanded.

The packing is a static bitstream: field element w occupies bits
``[start[w], start[w] + bits[w])`` of the row, where ``bits[w]`` is derived
from :class:`~raft_tla_tpu.config.Bounds` capacities (one step past each
bound, config.py) and ``start`` is the running sum.  Everything is computed
at trace time from static widths, so pack/unpack lower to a fixed sequence
of shifts and ors that XLA fuses into the surrounding kernel — no gathers,
no loops.

Dual-backend (``xp`` = numpy | jax.numpy), like ops/state.py: the host
store holds the same packed bytes the device ring holds, and the trace
decoder unpacks with the identical code path.
"""

from __future__ import annotations

import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops.msgbits import _HI_FIELDS, _LO_FIELDS


def _bits(max_value: int) -> int:
    """Bits to represent values 0..max_value."""
    return max(1, int(max_value).bit_length())


# Fields whose packed width is a RAW bit block, not a value range: the
# allLogs mask words carry 32 bits of set-membership data (the int32 sign
# bit is data, uint32 semantics).  The analyzer exempts these from the
# "width <= 31 so int32 stays non-negative" flat-vector rule.
RAW_FIELDS = ("allLogs",)


def width_table(bounds: Bounds) -> dict:
    """The full width contract for one Bounds instance — the table the
    static analyzer (analysis/widthcheck) proves the kernels against.

    Returns ``{"bits": field -> width, "raw": RAW_FIELDS subset present,
    "total_bits": packed row bits, "packed_words": P, "flat_words": W}``.
    """
    schema = BitSchema(bounds)
    fb = field_bits(bounds)
    return {
        "bits": fb,
        "raw": tuple(f for f in RAW_FIELDS if f in fb),
        "total_bits": schema.total_bits,
        "packed_words": schema.P,
        "flat_words": schema.W,
    }


def field_bits(bounds: Bounds) -> dict:
    """Per-element bit width for every Layout field (pack() order)."""
    n = bounds.n_servers
    hi_bits = max(sh + w for sh, w in _HI_FIELDS.values())
    # Parity mode never sets the mlog field 'g' (always 0): pack only the
    # bits below it, so parity rows don't widen with the faithful schema.
    lo_fields = _LO_FIELDS if bounds.history else \
        {k: v for k, v in _LO_FIELDS.items() if k != "g"}
    lo_bits = max(sh + w for sh, w in lo_fields.values())
    out = {
        "role": _bits(2),
        "term": _bits(bounds.term_cap),
        "votedFor": _bits(n),                    # 0 = Nil, else id+1
        "commitIndex": _bits(bounds.log_cap),
        "logLen": _bits(bounds.log_cap),
        "logTerm": _bits(bounds.term_cap),
        "logVal": _bits(bounds.n_values),
        "vResp": n,                              # bitmask over servers
        "vGrant": n,
        "nextIndex": _bits(bounds.log_cap + 1),  # 1..Len(log)+1
        "matchIndex": _bits(bounds.log_cap),
        "msgHi": hi_bits,                        # 29: the packed record word
        "msgLo": lo_bits,                        # the packed record word
        "msgCount": _bits(bounds.dup_cap),
    }
    if bounds.history:
        from raft_tla_tpu.ops.loguniv import LogUniverse
        uni = LogUniverse.of(bounds)
        out.update({
            "allLogs": 32,                       # raw bitmask words
            "vLog": uni.id_bits,                 # rank+1, 0 = absent
            "eTerm": _bits(bounds.term_cap),
            "eLeader": _bits(max(n - 1, 1)),
            "eLog": uni.id_bits,
            "eVotes": n,                         # evotes server bitmask
            "eVLog": uni.id_bits,                # rank+1, 0 = absent
        })
    return out


class BitSchema:
    """Static pack plan: per-position widths, offsets, packed width."""

    def __init__(self, bounds: Bounds):
        lay = st.Layout.of(bounds)
        fb = field_bits(bounds)
        bits = []
        for f in lay.fields:
            bits += [fb[f]] * int(np.prod(lay.shapes[f]))
        self.bits = np.asarray(bits, np.int64)          # [W]
        self.start = np.concatenate(([0], np.cumsum(self.bits)[:-1]))
        self.total_bits = int(self.bits.sum())
        self.W = lay.width
        self.P = (self.total_bits + 31) // 32           # packed words

    def pack(self, vec, xp):
        """``int32[..., W] -> int32[..., P]`` (uint32 bitstream in int32)."""
        u = vec.astype(xp.uint32)
        words = [None] * self.P
        for w in range(self.W):
            b, s = int(self.bits[w]), int(self.start[w])
            v = u[..., w] & xp.uint32((1 << b) - 1)
            o, sh = s // 32, s % 32
            lowpart = (v << xp.uint32(sh)) if sh else v
            words[o] = lowpart if words[o] is None else words[o] | lowpart
            if sh + b > 32:                      # straddles two words
                spill = v >> xp.uint32(32 - sh)
                words[o + 1] = spill if words[o + 1] is None \
                    else words[o + 1] | spill
        zero = xp.zeros_like(u[..., 0])
        cols = [zero if c is None else c for c in words]
        return xp.stack(cols, axis=-1).astype(xp.int32)

    def unpack(self, packed, xp):
        """``int32[..., P] -> int32[..., W]``."""
        u = packed.astype(xp.uint32)
        cols = []
        for w in range(self.W):
            b, s = int(self.bits[w]), int(self.start[w])
            o, sh = s // 32, s % 32
            v = u[..., o] >> xp.uint32(sh) if sh else u[..., o]
            if sh + b > 32:
                v = v | (u[..., o + 1] << xp.uint32(32 - sh))
            cols.append(v & xp.uint32((1 << b) - 1))
        return xp.stack(cols, axis=-1).astype(xp.int32)
