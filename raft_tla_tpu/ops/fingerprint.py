"""64-bit state fingerprints — the dedup key (TLC's FP64 analog, SURVEY §2.8).

TLC deduplicates states by a 64-bit fingerprint of the canonicalized value
(probabilistically exact, with a reported collision bound).  This module plays
that role for the tensor encoding: a canonical ``int32[W]`` state vector hashes
to two independent 32-bit lanes, combined host-side into one ``uint64``.

Scheme: two-lane *multilinear* hash + murmur3 finalizer.  Lane k computes
``fmix32(seed_k + sum_w c_k[w] * state[w] mod 2^32)`` with per-position odd
random constants ``c_k``.  The multilinear family is pairwise almost-universal
(collision probability ~2^-32 per lane per pair); two independent lanes give
~2^-64 per pair — the same regime TLC operates in.  The linear part is one
elementwise multiply + reduction (TPU-friendly: no sequential dependency over
W, unlike a rolling hash), and the fmix32 avalanche decorrelates lanes from
the raw linear structure for use as a hash-table index.

Bit-identical across backends: all arithmetic is uint32 wraparound, explicit
dtypes everywhere, same constants (fixed PRNG seed) — NumPy host, jnp device,
the Pallas kernel (ops/pallas_fp.py), and the C++ host store (native/) must
all agree, because sharding routes states by fingerprint (SURVEY §2.8).
"""

from __future__ import annotations

import numpy as np

_SEED = 0x5AF7_0001
_LANE_SEEDS = (np.uint32(0x9E3779B9), np.uint32(0x85EBCA77))


def lane_constants(width: int) -> np.ndarray:
    """Per-position odd uint32 multipliers, shape (2, width). Deterministic."""
    rng = np.random.Generator(np.random.PCG64(_SEED))
    c = rng.integers(0, 2**32, size=(2, width), dtype=np.uint32)
    return c | np.uint32(1)  # odd => multiplication is invertible mod 2^32


def _fmix32(h, xp):
    """murmur3 32-bit finalizer (public domain avalanche function)."""
    u = xp.uint32
    h = h ^ (h >> u(16))
    h = h * u(0x85EBCA6B)
    h = h ^ (h >> u(13))
    h = h * u(0xC2B2AE35)
    h = h ^ (h >> u(16))
    return h


def fingerprint(vec, consts, xp):
    """Canonical int32[..., W] -> (hi, lo) uint32 lanes, shape [...]."""
    # uint32 wraparound is the *point* of the arithmetic; silence NumPy's
    # scalar-overflow warning (no-op under jnp, which never warns).
    with np.errstate(over="ignore"):
        w = vec.astype(xp.uint32)
        c1 = consts[0].astype(xp.uint32)
        c2 = consts[1].astype(xp.uint32)
        s1 = xp.sum(w * c1, axis=-1, dtype=xp.uint32)
        s2 = xp.sum(w * c2, axis=-1, dtype=xp.uint32)
        h1 = _fmix32(s1 + _LANE_SEEDS[0], xp)
        h2 = _fmix32(s2 + _LANE_SEEDS[1], xp)
    return h1, h2


def to_u64(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Host-side combine: two uint32 lanes -> one uint64 key."""
    return (np.asarray(hi, dtype=np.uint64) << np.uint64(32)) | np.asarray(
        lo, dtype=np.uint64)
