"""Bounded-log universe enumeration — the keystone of faithful mode.

Faithful mode (SURVEY §7.0.3b) carries the spec's proof-only history
variables — ``elections`` (raft.tla:39), ``allLogs`` (raft.tla:44),
``voterLog`` (raft.tla:77) and the ``mlog`` message fields
(raft.tla:220-222, 297-299) — as real, fingerprinted state.  All of them
are *log-valued*: sets of logs, maps to logs, logs inside messages.  Under
the StateConstraint every log is drawn from the finite universe

    U = { <<e_1..e_k>> : 0 <= k <= L, e_i in [1..T] x [1..V] }

(L = ``Bounds.log_cap``, T = ``Bounds.term_cap``, V = ``n_values``), so a
log is representable as its *rank* in a fixed enumeration — one small
integer instead of a 2L-word sequence.  That turns

- ``allLogs``      into a U-bit bitmask (set of ranks),
- ``voterLog``     into an n x n table of rank+1 (0 = absent),
- ``elections``    into slots holding ranks for elog/evoterLog,
- ``mlog``         into one extra packed message field (ops/msgbits.py),

each updated with a handful of integer ops inside the fused transition
kernel — no variable-length data anywhere, XLA-static throughout.

Enumeration: logs ordered by length, then lexicographically by entry codes.
An entry (t, v) has code ``c = (t-1)*V + (v-1)`` in radix ``R = T*V``; a
log of length k has ``id = offset[k] + sum_i c_i * R^(k-1-i)`` where
``offset[k] = (R^k - 1) / (R - 1)`` counts all shorter logs.  Properties
used downstream:

- ``id = 0``  iff the log is empty (``offset[0] = 0``);
- dropping the last entry is ``prefix_id(id) = offset[k-1] + (id - offset[k]) // R``
  — a closed form, so the AllLogsPrefixClosed invariant needs no tables;
- appending entry c is ``offset[k+1] + (id - offset[k]) * R + c``.

Dual-backend like ops/state.py: every function takes ``xp`` (numpy |
jax.numpy) and works element-wise on arrays, so the interpreter, the
invariants and the fused kernels share one implementation bit for bit.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from raft_tla_tpu.config import Bounds


@dataclasses.dataclass(frozen=True)
class LogUniverse:
    """Static enumeration tables for one Bounds instance."""

    T: int          # entry terms 1..T (term_cap: one past MaxTerm, config.py)
    V: int          # entry values 1..V
    L: int          # lengths 0..L (log_cap)
    R: int          # entry radix T*V
    offsets: tuple  # offsets[k] = first id of length-k logs; len L+2
    size: int       # |U| = offsets[L+1]

    @classmethod
    def of(cls, bounds: Bounds) -> "LogUniverse":
        T, V, L = bounds.term_cap, bounds.n_values, bounds.log_cap
        R = T * V
        offs = [0]
        for _k in range(L + 1):
            offs.append(offs[-1] * R + 1)
        # offs[k] = (R^k - 1)/(R - 1) by Horner; offs[L+1] = |U|
        return cls(T=T, V=V, L=L, R=R, offsets=tuple(offs), size=offs[-1])

    @property
    def id_bits(self) -> int:
        """Bits for a rank+1 value (0 reserved for 'absent')."""
        return max(1, int(self.size).bit_length())

    @property
    def mask_words(self) -> int:
        """int32 words of a U-bit set-of-logs bitmask (allLogs)."""
        return (self.size + 31) // 32

    # -- rank arithmetic (xp-generic, element-wise) --------------------------

    def log_id(self, log_term, log_val, log_len, xp):
        """Rank of the log held in padded rows (ops/state.py log encoding).

        ``log_term``/``log_val`` are ``[..., L]`` padded arrays, ``log_len``
        the matching lengths; columns >= len are ignored (they are zero in
        canonical states, but this does not rely on that).
        """
        L, R, V = self.L, self.R, self.V
        offs = xp.asarray(self.offsets, dtype=xp.int32)
        k = xp.arange(L, dtype=xp.int32)
        ln = xp.asarray(log_len, dtype=xp.int32)[..., None]
        code = (xp.asarray(log_term, xp.int32) - 1) * V \
            + (xp.asarray(log_val, xp.int32) - 1)
        # weight of column k is R^(len-1-k) for k < len, else 0
        expo = xp.clip(ln - 1 - k, 0, max(L - 1, 0))
        powR = xp.asarray([R ** e for e in range(max(L, 1))], dtype=xp.int32)
        w = xp.where(k < ln, powR[expo], 0)
        return offs[xp.asarray(log_len, xp.int32)] \
            + xp.sum(code * w, axis=-1).astype(xp.int32)

    def log_len_of(self, ids, xp):
        """Length of the log with the given rank."""
        ids = xp.asarray(ids, xp.int32)
        ln = xp.zeros_like(ids)
        for k in range(1, self.L + 1):
            ln = xp.where(ids >= self.offsets[k], k, ln)
        return ln

    def prefix_id(self, ids, xp):
        """Rank of the log minus its last entry (undefined-at-0 maps to 0)."""
        ids = xp.asarray(ids, xp.int32)
        ln = self.log_len_of(ids, xp)
        offs = xp.asarray(self.offsets, dtype=xp.int32)
        kk = xp.clip(ln, 1, self.L)
        return xp.where(
            ln > 0, offs[kk - 1] + (ids - offs[kk]) // self.R, 0)

    def decode(self, ids, xp):
        """Rank -> padded (log_term [...,L], log_val [...,L], log_len).

        Static L-step digit extraction (big-endian: entry 0 is the most
        significant digit), vectorized over any leading shape.
        """
        L, R, V = self.L, self.R, self.V
        ids = xp.asarray(ids, xp.int32)
        ln = self.log_len_of(ids, xp)
        offs = xp.asarray(self.offsets, dtype=xp.int32)
        rem = ids - offs[ln]
        terms, vals = [], []
        for k in range(L):
            # digit k has weight R^(len-1-k); extract by repeated divmod
            # from the most significant side: divide by R^(len-1-k).
            expo = xp.clip(ln - 1 - k, 0, max(L - 1, 0))
            powR = xp.asarray([R ** e for e in range(max(L, 1))],
                              dtype=xp.int32)
            w = powR[expo]
            digit = xp.where(k < ln, rem // w, 0)
            rem = xp.where(k < ln, rem - digit * w, rem)
            terms.append(xp.where(k < ln, digit // V + 1, 0))
            vals.append(xp.where(k < ln, digit % V + 1, 0))
        if L == 0:
            z = xp.zeros(ids.shape + (0,), xp.int32)
            return z, z, ln
        return (xp.stack(terms, axis=-1).astype(xp.int32),
                xp.stack(vals, axis=-1).astype(xp.int32), ln)

    # -- host-side conveniences ----------------------------------------------

    def id_of_tuple(self, log: tuple) -> int:
        """Rank of a ((term, value), ...) tuple (interpreter form)."""
        k = len(log)
        if k > self.L:
            raise OverflowError(f"log of length {k} exceeds universe L={self.L}")
        rid = self.offsets[k]
        for pos, (t, v) in enumerate(log):
            if not (1 <= t <= self.T and 1 <= v <= self.V):
                raise OverflowError(f"entry ({t},{v}) outside universe "
                                    f"T={self.T} V={self.V}")
            rid += ((t - 1) * self.V + (v - 1)) * self.R ** (k - 1 - pos)
        return rid

    def tuple_of_id(self, rid: int) -> tuple:
        """Inverse of :meth:`id_of_tuple`."""
        lt, lv, ln = self.decode(np.asarray(rid), np)
        ln = int(ln)
        return tuple((int(lt[..., k]), int(lv[..., k])) for k in range(ln))
