"""Batched transition kernels — the L2 layer as branchless tensor ops.

Each of the spec's 10 action families (``Next`` disjuncts, ``raft.tla:454-463``)
and 7 message handlers (``raft.tla:284-418``) becomes a guarded functional
update on the tensor struct (ops/state.py).  :func:`build_expand` assembles
them into one jittable ``state -> (successors, valid, overflow)`` function with
the static fan-out of models/spec.py's action table; the engine vmaps it over
the frontier.

Design rules (SURVEY §7):

- **No data-dependent control flow.**  Every disjunct/branch computes its
  guard as a boolean and its effect unconditionally; ``jnp.where`` selects.
  Handler guards partition on ``mterm`` vs ``currentTerm`` (SURVEY §3.3), so
  the per-message dispatch is a branchless select over mutually exclusive
  masks.
- **Effects are functional one-hot updates** (``x.at[]`` is avoided in favor
  of mask arithmetic so the same code vmaps over action parameters).
- **Messages survive or die exactly as in the spec**: UpdateTerm, candidate
  step-down, conflict-truncate and append all *keep* the request in the bag
  (``raft.tla:411-412, 350, 382, 388``) — the multi-step convergence loop must
  not be fused (SURVEY §2.6).
- **Capacity overflow is loud**: ``bag_add`` reports when no slot is free;
  the engine asserts the flag never fires for states it expands (the +1
  capacity scheme of config.py makes that a theorem, the flag checks it).

The differential test (tests/test_kernels.py) compares every successor lane
against the reference interpreter on random bounded states and on reachable
prefixes.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import spec as SP
from raft_tla_tpu.ops import loguniv
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import fingerprint as fpr

I32 = jnp.int32


def _log_rank(bounds, s, i):
    """Rank of ``log[i]`` in the bounded log universe (faithful mode)."""
    uni = loguniv.LogUniverse.of(bounds)
    return uni.log_id(s["logTerm"][i], s["logVal"][i], s["logLen"][i], jnp)


def _popcount(x):
    """Branchless 32-bit popcount (Quorum test, ``raft.tla:99``)."""
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    x = (x + (x >> 4)) & 0x0F0F0F0F
    return (x * 0x01010101) >> 24


def _onehot(i, n):
    return jnp.arange(n) == i


def _set1(arr, i, val):
    """arr with arr[i] = val (one-hot form, vmappable over traced i)."""
    return jnp.where(_onehot(i, arr.shape[0]), val, arr)


def _set_row(mat, i, val):
    """mat with row i set to the scalar val."""
    return jnp.where(_onehot(i, mat.shape[0])[:, None], val, mat)


def _set2(mat, i, j, val):
    mask = _onehot(i, mat.shape[0])[:, None] & _onehot(j, mat.shape[1])[None, :]
    return jnp.where(mask, val, mat)


def _last_term(s, i):
    """``LastTerm(log[i])`` (raft.tla:102)."""
    ln = s["logLen"][i]
    idx = jnp.clip(ln - 1, 0, s["logTerm"].shape[1] - 1)
    return jnp.where(ln > 0, s["logTerm"][i, idx], 0)


# -- bag operations (raft.tla:106-130) ---------------------------------------

def _slot_insert(match, empty):
    """Fixed-shape set/bag insert plan over slot arrays.

    Given exclusive masks for "element already in a slot" and "slot free",
    returns ``(ins, exists, overflow)``: the one-hot first-free-slot mask to
    write into (all-False when the element exists or nothing is free),
    whether it already exists, and whether insertion was impossible.
    Shared by the message bag and the faithful-mode elections set so the
    soundness-sensitive idiom has one definition site.
    """
    exists = jnp.any(match)
    has_empty = jnp.any(empty)
    ins = (~exists) & has_empty & _onehot(jnp.argmax(empty),
                                          empty.shape[0]) & empty
    return ins, exists, (~exists) & (~has_empty)


def bag_add(s, hi, lo):
    """``WithMessage`` (raft.tla:106-110). Returns (struct', overflow)."""
    H, L, C = s["msgHi"], s["msgLo"], s["msgCount"]
    match = (H == hi) & (L == lo) & (C > 0)
    ins, _exists, ovf = _slot_insert(match, C == 0)
    out = dict(s)
    out["msgHi"] = jnp.where(ins, hi, H).astype(I32)
    out["msgLo"] = jnp.where(ins, lo, L).astype(I32)
    out["msgCount"] = (C + match.astype(I32) + ins.astype(I32)).astype(I32)
    return out, ovf


def bag_remove(s, hi, lo):
    """``WithoutMessage`` (raft.tla:114-119); no-op when absent."""
    H, L, C = s["msgHi"], s["msgLo"], s["msgCount"]
    match = (H == hi) & (L == lo) & (C > 0)
    c2 = C - match.astype(I32)
    emptied = match & (c2 == 0)
    out = dict(s)
    out["msgHi"] = jnp.where(emptied, 0, H).astype(I32)
    out["msgLo"] = jnp.where(emptied, 0, L).astype(I32)
    out["msgCount"] = c2.astype(I32)
    return out


def reply(s, resp_hi, resp_lo, req_hi, req_lo):
    """``Reply`` (raft.tla:129-130): WithoutMessage(request, WithMessage(resp)).

    Evaluated remove-first: request and response always differ (mtype), so
    the two bag edits commute, and removing first avoids claiming a transient
    extra slot — overflow then fires iff the *final* bag exceeds capacity.
    """
    out = bag_remove(s, req_hi, req_lo)
    out, ovf = bag_add(out, resp_hi, resp_lo)
    return out, ovf


def _tree_select(branches, default):
    """Select among (guard, struct) branches; guards must be exclusive."""
    out = default
    for g, s in branches:
        out = jax.tree.map(lambda a, b: jnp.where(g, b, a), out, s)
    return out


# -- action families ---------------------------------------------------------

def k_restart(bounds, s, i):
    """``Restart(i)`` (raft.tla:167-175): always enabled."""
    out = dict(s)
    out["role"] = _set1(s["role"], i, SP.FOLLOWER)
    out["vResp"] = _set1(s["vResp"], i, 0)
    out["vGrant"] = _set1(s["vGrant"], i, 0)
    out["nextIndex"] = _set_row(s["nextIndex"], i, 1)
    out["matchIndex"] = _set_row(s["matchIndex"], i, 0)
    out["commitIndex"] = _set1(s["commitIndex"], i, 0)
    if "vLog" in s:   # voterLog[i] := empty map (raft.tla:171)
        out["vLog"] = _set_row(s["vLog"], i, 0)
    return out, jnp.bool_(True), jnp.bool_(False)


def k_timeout(bounds, s, i):
    """``Timeout(i)`` (raft.tla:178-187)."""
    valid = (s["role"][i] == SP.FOLLOWER) | (s["role"][i] == SP.CANDIDATE)
    out = dict(s)
    out["role"] = _set1(s["role"], i, SP.CANDIDATE)
    out["term"] = _set1(s["term"], i, s["term"][i] + 1)
    out["votedFor"] = _set1(s["votedFor"], i, SP.NIL)
    out["vResp"] = _set1(s["vResp"], i, 0)
    out["vGrant"] = _set1(s["vGrant"], i, 0)
    if "vLog" in s:   # voterLog[i] := empty map (raft.tla:186)
        out["vLog"] = _set_row(s["vLog"], i, 0)
    return out, valid, jnp.bool_(False)


def k_request_vote(bounds, s, i, j):
    """``RequestVote(i, j)`` (raft.tla:190-199); j may equal i."""
    valid = (s["role"][i] == SP.CANDIDATE) & (((s["vResp"][i] >> j) & 1) == 0)
    hi, lo = mb.rv_request(s["term"][i], _last_term(s, i), s["logLen"][i], i, j)
    out, ovf = bag_add(s, hi, lo)
    return out, valid, valid & ovf


def k_append_entries(bounds, s, i, j):
    """``AppendEntries(i, j)`` (raft.tla:204-226): <=1 entry, heartbeats incl."""
    Lcap = s["logTerm"].shape[1]
    valid = (i != j) & (s["role"][i] == SP.LEADER)
    ni = s["nextIndex"][i, j]
    prev_idx = ni - 1
    prev_term = jnp.where(
        prev_idx > 0, s["logTerm"][i, jnp.clip(prev_idx - 1, 0, Lcap - 1)], 0)
    last_entry = jnp.minimum(s["logLen"][i], ni)        # raft.tla:213
    has_ent = ni <= last_entry
    eidx = jnp.clip(ni - 1, 0, Lcap - 1)
    ent_term = jnp.where(has_ent, s["logTerm"][i, eidx], 0)
    ent_val = jnp.where(has_ent, s["logVal"][i, eidx], 0)
    mlog = _log_rank(bounds, s, i) if "allLogs" in s else 0  # raft.tla:220-222
    hi, lo = mb.ae_request(
        s["term"][i], prev_idx, prev_term, has_ent.astype(I32), ent_term,
        ent_val, jnp.minimum(s["commitIndex"][i], last_entry), i, j, mlog)
    out, ovf = bag_add(s, hi, lo)
    return out, valid, valid & ovf


def k_become_leader(bounds, s, i):
    """``BecomeLeader(i)`` (raft.tla:229-243); Quorum as popcount.

    In faithful mode also inserts [eterm, eleader, elog, evotes, evoterLog]
    into the ``elections`` slot set (raft.tla:237-242) — a set insert like
    ``bag_add``, minus multiplicities; slot exhaustion is a loud overflow.
    """
    n = bounds.n_servers
    valid = ((s["role"][i] == SP.CANDIDATE)
             & (2 * _popcount(s["vGrant"][i]) > n))
    out = dict(s)
    out["role"] = _set1(s["role"], i, SP.LEADER)
    out["nextIndex"] = _set_row(s["nextIndex"], i, s["logLen"][i] + 1)
    out["matchIndex"] = _set_row(s["matchIndex"], i, 0)
    ovf = jnp.bool_(False)
    if "eTerm" in s:
        lid = _log_rank(bounds, s, i)
        vrow = s["vLog"][i]
        occ = s["eTerm"] > 0
        match = (occ & (s["eTerm"] == s["term"][i]) & (s["eLeader"] == i)
                 & (s["eLog"] == lid) & (s["eVotes"] == s["vGrant"][i])
                 & jnp.all(s["eVLog"] == vrow[None, :], axis=1))
        ins, _exists, ovf = _slot_insert(match, ~occ)
        out["eTerm"] = jnp.where(ins, s["term"][i], s["eTerm"]).astype(I32)
        out["eLeader"] = jnp.where(ins, i, s["eLeader"]).astype(I32)
        out["eLog"] = jnp.where(ins, lid, s["eLog"]).astype(I32)
        out["eVotes"] = jnp.where(ins, s["vGrant"][i],
                                  s["eVotes"]).astype(I32)
        out["eVLog"] = jnp.where(ins[:, None], vrow[None, :],
                                 s["eVLog"]).astype(I32)
    return out, valid, valid & ovf


def k_client_request(bounds, s, i, v):
    """``ClientRequest(i, v)`` (raft.tla:246-253)."""
    Lcap = s["logTerm"].shape[1]
    ln = s["logLen"][i]
    valid = s["role"][i] == SP.LEADER
    row = _onehot(i, bounds.n_servers)[:, None]
    col = (jnp.arange(Lcap) == ln)[None, :]
    out = dict(s)
    out["logTerm"] = jnp.where(row & col, s["term"][i], s["logTerm"]).astype(I32)
    out["logVal"] = jnp.where(row & col, v, s["logVal"]).astype(I32)
    out["logLen"] = _set1(s["logLen"], i, ln + 1)
    # ln == Lcap would silently drop the entry; the capacity scheme makes it
    # unreachable from constraint-satisfying states — flag, don't clamp.
    return out, valid, valid & (ln >= Lcap)


def k_advance_commit(bounds, s, i):
    """``AdvanceCommitIndex(i)`` (raft.tla:259-276).

    ``Agree(index) == {i} \\cup {k : matchIndex[i][k] >= index}``; commits
    ``Max(agreeIndexes)`` only if that entry's term is current
    (raft.tla:268-270).
    """
    n, Lcap = bounds.n_servers, s["logTerm"].shape[1]
    valid = s["role"][i] == SP.LEADER
    idxs = jnp.arange(1, Lcap + 1)                                   # [L]
    others = s["matchIndex"][i][None, :] >= idxs[:, None]            # [L, n]
    in_set = others | (jnp.arange(n)[None, :] == i)                  # {i} ∪ ...
    agree_cnt = jnp.sum(in_set.astype(I32), axis=1)
    agree_ok = (2 * agree_cnt > n) & (idxs <= s["logLen"][i])
    max_agree = jnp.max(jnp.where(agree_ok, idxs, 0))
    t_at = s["logTerm"][i, jnp.clip(max_agree - 1, 0, Lcap - 1)]
    commit = jnp.where((max_agree > 0) & (t_at == s["term"][i]),
                       max_agree, s["commitIndex"][i])
    out = dict(s)
    out["commitIndex"] = _set1(s["commitIndex"], i, commit)
    return out, valid, jnp.bool_(False)


# -- Receive(m): deterministic dispatch over one slot (raft.tla:421-436) -----

def k_receive(bounds, s, slot):
    n, Lcap = bounds.n_servers, s["logTerm"].shape[1]
    occupied = s["msgCount"][slot] > 0
    hi, lo = s["msgHi"][slot], s["msgLo"][slot]
    i, j = mb.dst(hi), mb.src(hi)
    mt, mty = mb.mterm(hi), mb.mtype(hi)
    ct = s["term"][i]
    role_i = s["role"][i]
    len_i = s["logLen"][i]
    ovf = jnp.bool_(False)

    # UpdateTerm (raft.tla:406-412): any type, message kept.
    g_upd = mt > ct
    s_upd = dict(s)
    s_upd["term"] = _set1(s["term"], i, mt)
    s_upd["role"] = _set1(s["role"], i, SP.FOLLOWER)
    s_upd["votedFor"] = _set1(s["votedFor"], i, SP.NIL)

    not_upd = ~g_upd  # below here mterm <= currentTerm[i]

    # HandleRequestVoteRequest (raft.tla:284-303)
    g_rvreq = not_upd & (mty == SP.M_RVREQ)
    log_ok_rv = ((mb.fa(hi) > _last_term(s, i))
                 | ((mb.fa(hi) == _last_term(s, i))
                    & (mb.fb(hi) >= len_i)))                  # raft.tla:285-287
    grant = ((mt == ct) & log_ok_rv
             & ((s["votedFor"][i] == SP.NIL)
                | (s["votedFor"][i] == j + 1)))               # raft.tla:288-290
    my_mlog = _log_rank(bounds, s, i) if "allLogs" in s else 0  # :297-299
    resp_hi, resp_lo = mb.rv_response(ct, grant.astype(I32), i, j, my_mlog)
    s_rvreq = dict(s)
    s_rvreq["votedFor"] = jnp.where(
        grant, _set1(s["votedFor"], i, j + 1), s["votedFor"])  # raft.tla:292
    s_rvreq, ovf_rv = reply(s_rvreq, resp_hi, resp_lo, hi, lo)
    ovf |= g_rvreq & ovf_rv

    # RequestVoteResponse: DropStaleResponse | HandleRequestVoteResponse
    g_rvresp_drop = not_upd & (mty == SP.M_RVRESP) & (mt < ct)   # raft.tla:415-418
    g_rvresp = not_upd & (mty == SP.M_RVRESP) & (mt == ct)       # raft.tla:307-321
    s_drop = bag_remove(s, hi, lo)
    s_rvresp = dict(s)
    s_rvresp["vResp"] = _set1(s["vResp"], i, s["vResp"][i] | (1 << j))
    s_rvresp["vGrant"] = jnp.where(
        mb.fa(hi) > 0,
        _set1(s["vGrant"], i, s["vGrant"][i] | (1 << j)), s["vGrant"])
    if "vLog" in s:
        # voterLog[i] @@ (j :> m.mlog): existing entry wins (raft.tla:316-317)
        cur = s["vLog"][i, j]
        newv = jnp.where((mb.fa(hi) > 0) & (cur == 0), mb.fg(lo) + 1, cur)
        s_rvresp["vLog"] = _set2(s["vLog"], i, j, newv)
    s_rvresp = bag_remove(s_rvresp, hi, lo)

    # HandleAppendEntriesRequest (raft.tla:327-389)
    prev_idx, prev_term = mb.fa(hi), mb.fb(hi)
    n_ent, ent_term, ent_val = mb.fc(lo), mb.fd(lo), mb.fe(lo)
    log_ok_ae = ((prev_idx == 0)
                 | ((prev_idx > 0) & (prev_idx <= len_i)
                    & (prev_term == s["logTerm"][
                        i, jnp.clip(prev_idx - 1, 0, Lcap - 1)])))  # :328-331
    is_ae = not_upd & (mty == SP.M_AEREQ)
    g_ae_reject = is_ae & ((mt < ct)
                           | ((mt == ct) & (role_i == SP.FOLLOWER)
                              & ~log_ok_ae))                        # :333-337
    rej_hi, rej_lo = mb.ae_response(ct, 0, 0, i, j)                 # :338-344
    s_ae_reject, ovf_rej = reply(s, rej_hi, rej_lo, hi, lo)
    ovf |= g_ae_reject & ovf_rej

    g_ae_step = is_ae & (mt == ct) & (role_i == SP.CANDIDATE)       # :346-350
    s_ae_step = dict(s)
    s_ae_step["role"] = _set1(s["role"], i, SP.FOLLOWER)            # msg kept

    accept = is_ae & (mt == ct) & (role_i == SP.FOLLOWER) & log_ok_ae
    index = prev_idx + 1
    t_at_index = s["logTerm"][i, jnp.clip(index - 1, 0, Lcap - 1)]
    g_ae_done = accept & ((n_ent == 0)
                          | ((len_i >= index) & (t_at_index == ent_term)))
    # already done (raft.tla:356-374): commitIndex := mcommitIndex (may
    # decrease, :361-363), Reply success.
    done_hi, done_lo = mb.ae_response(ct, 1, prev_idx + n_ent, i, j)
    s_ae_done = dict(s)
    s_ae_done["commitIndex"] = _set1(s["commitIndex"], i, mb.ff(lo))
    s_ae_done, ovf_done = reply(s_ae_done, done_hi, done_lo, hi, lo)
    ovf |= g_ae_done & ovf_done

    g_ae_conflict = accept & (n_ent > 0) & (len_i >= index) \
        & (t_at_index != ent_term)                                  # :375-382
    # conflict: drop exactly one entry off the TAIL; message kept.
    row = _onehot(i, n)[:, None]
    tail = (jnp.arange(Lcap) == (len_i - 1))[None, :]
    s_ae_conflict = dict(s)
    s_ae_conflict["logTerm"] = jnp.where(row & tail, 0, s["logTerm"]).astype(I32)
    s_ae_conflict["logVal"] = jnp.where(row & tail, 0, s["logVal"]).astype(I32)
    s_ae_conflict["logLen"] = _set1(s["logLen"], i, len_i - 1)

    g_ae_append = accept & (n_ent > 0) & (len_i == prev_idx)        # :383-388
    newcol = (jnp.arange(Lcap) == len_i)[None, :]
    s_ae_append = dict(s)
    s_ae_append["logTerm"] = jnp.where(row & newcol, ent_term,
                                       s["logTerm"]).astype(I32)
    s_ae_append["logVal"] = jnp.where(row & newcol, ent_val,
                                      s["logVal"]).astype(I32)
    s_ae_append["logLen"] = _set1(s["logLen"], i, len_i + 1)
    ovf |= g_ae_append & (len_i >= Lcap)

    # AppendEntriesResponse: DropStaleResponse | Handle (raft.tla:393-403)
    g_aeresp_drop = not_upd & (mty == SP.M_AERESP) & (mt < ct)
    g_aeresp = not_upd & (mty == SP.M_AERESP) & (mt == ct)
    succ_flag = mb.fa(hi) > 0
    match = mb.fb(hi)
    ni_new = jnp.where(succ_flag, match + 1,
                       jnp.maximum(s["nextIndex"][i, j] - 1, 1))
    s_aeresp = dict(s)
    s_aeresp["nextIndex"] = _set2(s["nextIndex"], i, j, ni_new)
    s_aeresp["matchIndex"] = jnp.where(
        succ_flag, _set2(s["matchIndex"], i, j, match), s["matchIndex"])
    s_aeresp = bag_remove(s_aeresp, hi, lo)

    branches = [
        (g_upd, s_upd),
        (g_rvreq, s_rvreq),
        (g_rvresp_drop, s_drop),
        (g_rvresp, s_rvresp),
        (g_ae_reject, s_ae_reject),
        (g_ae_step, s_ae_step),
        (g_ae_done, s_ae_done),
        (g_ae_conflict, s_ae_conflict),
        (g_ae_append, s_ae_append),
        (g_aeresp_drop, s_drop),
        (g_aeresp, s_aeresp),
    ]
    any_branch = functools.reduce(jnp.logical_or, (g for g, _ in branches))
    out = _tree_select(branches, s)
    valid = occupied & any_branch
    return out, valid, valid & ovf


def k_duplicate(bounds, s, slot):
    """``DuplicateMessage(m)`` (raft.tla:443-445)."""
    occupied = s["msgCount"][slot] > 0
    out = dict(s)
    out["msgCount"] = (s["msgCount"]
                       + (jnp.arange(s["msgCount"].shape[0]) == slot)
                       .astype(I32))
    return out, occupied, jnp.bool_(False)


def k_drop(bounds, s, slot):
    """``DropMessage(m)`` (raft.tla:448-450)."""
    occupied = s["msgCount"][slot] > 0
    out = bag_remove(s, s["msgHi"][slot], s["msgLo"][slot])
    return out, occupied, jnp.bool_(False)


# -- assembly ----------------------------------------------------------------

_FAMILY_KERNELS = {
    SP.RESTART: (k_restart, ("i",)),
    SP.TIMEOUT: (k_timeout, ("i",)),
    SP.REQUESTVOTE: (k_request_vote, ("i", "j")),
    SP.BECOMELEADER: (k_become_leader, ("i",)),
    SP.CLIENTREQUEST: (k_client_request, ("i", "v")),
    SP.ADVANCECOMMIT: (k_advance_commit, ("i",)),
    SP.APPENDENTRIES: (k_append_entries, ("i", "j")),
    SP.RECEIVE: (k_receive, ("slot",)),
    SP.DUPLICATE: (k_duplicate, ("slot",)),
    SP.DROP: (k_drop, ("slot",)),
}

# Which struct fields each family's kernel can write (beyond copying the
# input).  This is the kernel side of the width-safety contract: the
# static analyzer (analysis/widthcheck) keeps an abstract transfer twin
# per family and cross-checks the two write-sets, so a kernel growing a
# new write without the twin being re-proved fails the lint loudly.
# History-only fields are listed unconditionally; the analyzer filters
# by mode.  Keep in sync with the k_* bodies above.
TRANSFER_WRITES = {
    SP.RESTART: ("role", "vResp", "vGrant", "nextIndex", "matchIndex",
                 "commitIndex", "vLog"),
    SP.TIMEOUT: ("role", "term", "votedFor", "vResp", "vGrant", "vLog"),
    SP.REQUESTVOTE: ("msgHi", "msgLo", "msgCount"),
    SP.BECOMELEADER: ("role", "nextIndex", "matchIndex",
                      "eTerm", "eLeader", "eLog", "eVotes", "eVLog"),
    SP.CLIENTREQUEST: ("logTerm", "logVal", "logLen"),
    SP.ADVANCECOMMIT: ("commitIndex",),
    SP.APPENDENTRIES: ("msgHi", "msgLo", "msgCount"),
    SP.RECEIVE: ("term", "role", "votedFor", "vResp", "vGrant", "vLog",
                 "commitIndex", "logTerm", "logVal", "logLen",
                 "nextIndex", "matchIndex", "msgHi", "msgLo", "msgCount"),
    SP.DUPLICATE: ("msgCount",),
    SP.DROP: ("msgHi", "msgLo", "msgCount"),
}

# finish_expand's shared postlude writes (outside any single family):
# the faithful-mode allLogs union — raw 32-bit mask words, or-only.
POSTLUDE_WRITES = ("allLogs",)


def transfer_metadata() -> dict:
    """Per-family metadata for the static analyzer: parameter names and
    declared write-sets.  Raises KeyError (loudly, at lint time) if the
    two tables ever drift apart."""
    out = {}
    for fam, (_kern, params) in _FAMILY_KERNELS.items():
        out[fam] = {"params": params, "writes": TRANSFER_WRITES[fam]}
    for fam in TRANSFER_WRITES:
        if fam not in _FAMILY_KERNELS:
            raise KeyError(f"TRANSFER_WRITES names unknown family {fam}")
    return out


def group_instances(table):
    """Group contiguous instances of the same family for vectorized
    dispatch (shared by the dense and CP-sharded expansions)."""
    groups: list[tuple[str, list[SP.ActionInstance]]] = []
    for a in table:
        if groups and groups[-1][0] == a.family:
            groups[-1][1].append(a)
        else:
            groups.append((a.family, [a]))
    return groups


def grouped_dispatch(bounds, s, groups, family_kernels=None):
    """Evaluate the family kernels over grouped static instances:
    ``-> (succs list, valids list, ovfs list)`` of per-group arrays.

    ``family_kernels`` overrides the hand-written kernel table with one
    of the same shape (``{family: (kernel, params)}``) — the seam the
    frontend IR compiler plugs into (frontend/actions.compile_kernels);
    the dispatch, vmapping and broadcast semantics stay this one
    definition either way."""
    table = _FAMILY_KERNELS if family_kernels is None else family_kernels
    succs, valids, ovfs = [], [], []
    for fam, instances in groups:
        kern, params = table[fam]
        args = [jnp.asarray([getattr(a, p) for a in instances], dtype=I32)
                for p in params]
        fn = functools.partial(kern, bounds)
        batched = jax.vmap(fn, in_axes=(None,) + (0,) * len(args))
        out, valid, ovf = batched(s, *args)
        succs.append(out)
        valids.append(jnp.broadcast_to(valid, (len(instances),)))
        ovfs.append(jnp.broadcast_to(ovf, (len(instances),)))
    return succs, valids, ovfs


def finish_expand(bounds, s, succs, valids, ovfs):
    """Concatenate per-group lanes, apply the shared allLogs union
    (faithful mode), canonicalize every successor — the one definition
    of an expansion's postlude (dense and CP twins both end here)."""
    all_succs = jax.tree.map(
        lambda *xs: jnp.concatenate(xs, axis=0), *succs)
    if "allLogs" in s:
        all_succs["allLogs"] = _alllogs_update(
            bounds, s, all_succs["allLogs"].shape[0])
    all_succs = jax.vmap(lambda t: st.canonicalize(t, jnp))(all_succs)
    return all_succs, jnp.concatenate(valids), jnp.concatenate(ovfs)


def build_expand(bounds: Bounds, spec: str = "full", family_kernels=None):
    """Build ``expand(struct) -> (succs[A,...], valid[A], overflow[A])``.

    The A successor lanes follow models/spec.action_table order exactly;
    every successor is canonicalized (message slots sorted).  Pure function
    of a single state struct — vmap/jit at the call site.
    ``family_kernels`` swaps in an alternative kernel table (the IR
    compiler's output) under the same table order and postlude.
    """
    groups = group_instances(SP.action_table(bounds, spec))

    def expand(s):
        succs, valids, ovfs = grouped_dispatch(
            bounds, s, groups, family_kernels=family_kernels)
        return finish_expand(bounds, s, succs, valids, ovfs)

    return expand


def _alllogs_update(bounds, s, n_lanes):
    """``allLogs' = allLogs \\cup {log[i] : i \\in Server}``, conjoined
    with the UNPRIMED logs onto every disjunct (raft.tla:464-465) — one
    shared update broadcast across all ``n_lanes`` successor lanes."""
    uni = loguniv.LogUniverse.of(bounds)
    Wa = s["allLogs"].shape[0]
    ids = uni.log_id(s["logTerm"], s["logVal"], s["logLen"], jnp)
    word, bit = ids // 32, ids % 32
    shift = jnp.left_shift(jnp.int32(1), bit)           # [n]
    masks = jnp.where(jnp.arange(Wa)[None, :] == word[:, None],
                      shift[:, None], 0)                # [n, Wa]
    delta = masks[0]
    for t in range(1, masks.shape[0]):
        delta = delta | masks[t]
    new_all = (s["allLogs"] | delta).astype(I32)
    return jnp.broadcast_to(new_all, (n_lanes, Wa))


def _step_stages(bounds: Bounds, spec: str, invariants: tuple,
                 symmetry: tuple, view: str | None = None,
                 family_kernels=None):
    """The shared builder prologue of the dense and EP-routed steps:
    layout, fingerprint constants, the expansion, the invariant
    predicates, the orbit-fingerprint pipeline, and the dedup-key view.
    One definition site so the step variants can never disagree on key
    arithmetic (the parity and checkpoint-compatibility guarantees rest
    on bit-identical fingerprints)."""
    from raft_tla_tpu.models import invariants as inv_mod
    from raft_tla_tpu.ops import symmetry as sym

    lay = st.Layout.of(bounds)
    consts = jnp.asarray(fpr.lane_constants(lay.width))
    expand = build_expand(bounds, spec, family_kernels=family_kernels)
    inv_fns = [inv_mod.jnp_invariant(nm, bounds) for nm in invariants]
    # Scan-compiled orbit pass: ONE copy of the permute/canonicalize/pack/
    # fingerprint pipeline iterated over the n!*V! group, not n!*V!
    # unrolled copies (ops/symmetry.build_orbit_fp) — bit-identical keys.
    # The sig-prune gate selects the coset-pruned variant of the SAME
    # scan (still bit-identical; ops/symmetry._SIGPRUNE_RUNGS comment);
    # every engine's step builder flows through here, so one gate covers
    # ddd/device/streamed and the parallel shard family alike.
    orbit_fp = sym.build_orbit_fp(bounds, symmetry, consts,
                                  "allLogs" in lay.shapes,
                                  prune=_sigprune_enabled(bounds, symmetry)) \
        if symmetry else None
    # The lax.scan orbit pass above is the PERMANENT design (VERDICT r3
    # next #9, decided round 4): a VMEM-resident Pallas orbit kernel was
    # built in round 2, measured at speed parity (0.7-1.15x) where
    # Mosaic compiled it (P <= 6 unrolled perms), failed Mosaic
    # compilation at P=24 (kernel stack scales with the unrolled group;
    # 73 MB at P=120 vs the 16 MB scoped-vmem limit, and the P=24
    # remote-compile returned HTTP 500 — runs/pallas_orbit_p24.out),
    # and was deleted: XLA's scan fusion already keeps one copy of the
    # permute/canonicalize/pack/fingerprint pipeline resident, which is
    # all the kernel could offer.  Mosaic findings preserved in
    # RESULTS.md "Pallas orbit kernel" and runs/pallas_orbit_p24.out.
    # (Distinct bet, different scope: the WHOLE-step Pallas megakernel,
    # ops/pallas_step.py, stages this very program into one kernel to
    # eliminate the HBM round-trips BETWEEN the stage fusions — gated
    # RAFT_TLA_MEGAKERNEL, auto=OFF; see _megakernel_enabled.)
    # The view folds into the DEDUP KEY only: stored rows, invariants and
    # the constraint all see the full successor (TLC VIEW semantics).
    viewer = None
    if view:
        from raft_tla_tpu.models import views as views_mod
        viewer = views_mod.jnp_view(view, bounds)
    return lay, consts, expand, inv_fns, orbit_fp, viewer


def build_step(bounds: Bounds, spec: str = "full", invariants: tuple = (),
               symmetry: tuple = (), view: str | None = None,
               megakernel: bool | None = None, family_kernels=None):
    """One fused frontier step: packed vecs -> everything the engine needs.

    ``step(vecs[B, W]) -> dict`` with packed successors ``svecs [B, A, W]``,
    ``valid``/``overflow`` ``[B, A]``, fingerprint lanes ``fp_hi/fp_lo``
    ``[B, A]`` (uint32), per-invariant truth ``inv_ok [B, A, n_inv]``, and
    StateConstraint satisfaction ``con_ok [B, A]``.  Everything downstream of
    the expansion fuses into one XLA computation — one device round-trip per
    frontier chunk.

    With ``symmetry=("Server",)`` the fingerprint lanes become the
    orbit-minimal fingerprint over all server permutations
    (ops/symmetry.py) — the dedup key that quotients the state space the
    way TLC's SYMMETRY stanza does.

    ``megakernel`` selects the Pallas megakernel build of the SAME step
    (ops/pallas_step.py: one kernel, candidates VMEM-resident across all
    stages, bit-identical lane for lane); ``None`` defers to the
    ``RAFT_TLA_MEGAKERNEL`` gate (:func:`_megakernel_enabled`) so every
    engine family inherits one process-wide decision at construction
    time, exactly like sig-prune.  The compile signature — everything
    this builder specializes on, gates included — is
    :func:`step_signature`; keep serving-side bin keys on that helper.
    """
    if megakernel is None:
        megakernel = _megakernel_enabled(bounds, symmetry)
    if megakernel:
        if family_kernels is not None:
            # The megakernel stages the HAND kernel bodies; an IR kernel
            # table has no fused build.  Refuse loudly rather than
            # silently dropping the override.
            raise ValueError(
                "RAFT_TLA_MEGAKERNEL=on does not compose with a "
                "family_kernels override (IR-compiled specs); leave the "
                "megakernel gate auto/off")
        from raft_tla_tpu.ops import pallas_step
        return pallas_step.build_step_megakernel(
            bounds, spec, invariants, symmetry, view)
    stages = _step_stages(bounds, spec, invariants, symmetry, view,
                          family_kernels=family_kernels)
    lay = stages[0]
    expand = stages[2]

    def step(vecs):
        structs = jax.vmap(lambda v: st.unpack(v, lay, jnp))(vecs)
        succs, valid, ovf = jax.vmap(expand)(structs)
        svecs = jax.vmap(jax.vmap(lambda t: st.pack(t, jnp)))(succs)
        # (EP-routed twin: build_step_routed compacts the valid lanes
        # before these per-candidate stages — same values, K-shaped.)
        fp_hi, fp_lo, inv_ok, con_ok = apply_stages(
            bounds, stages, symmetry, succs, svecs, valid)
        return {"svecs": svecs, "valid": valid, "overflow": ovf,
                "fp_hi": fp_hi, "fp_lo": fp_lo, "inv_ok": inv_ok,
                "con_ok": con_ok}

    return step


# Pre-orbit dedup compaction ladder: the orbit scan runs on the
# smallest static slot count the chunk's raw-unique candidates fit —
# N/4, then N/2, then the full N lanes.  Justification (measured,
# runs/step_anatomy.out "distinct-row measurement"): on 4,096 DISTINCT
# depth-9 flagship rows the valid share is 0.419 and the raw in-chunk
# duplicate share 0.450, so unique candidates (+1 sentinel group for
# every invalid lane) are 23.0% of N — the N/4 rung; the elect5
# campaign's deeper regime (valid share to 0.63) lands on N/2.
# Measured effect at that shape: 815.9 -> 367.4 ms/chunk on an idle
# CPU core (2.22x; the .out records both runs).  Raw-identical
# successors are the SAME state, so the group representative's
# canonical fingerprint is bit-identical to every member's — counts,
# discovery order and checkpoints are unchanged on every rung.
_PRESCAN_RUNGS = (4, 2)      # divisors of N, tried in order


def _prescan_enabled(bounds, symmetry):
    """Platform/shape gate for the prescan ladder.  The lexsort is a
    fixed per-chunk cost while the saving scales with |G| (the scan
    iterations skipped per deduplicated lane), and TPU sorts are slow:
    measured on-chip (runs/prescan_ab.py, sync-timed medians), the
    ladder is a 1.44x LOSS at |G|=6 (flagship, 117.5 vs 81.5 ms/chunk)
    but a 1.25x win at |G|=120 (elect5, 201.7 vs 251.5 ms/chunk).  On
    CPU it wins already at |G|=6 (2.22x, runs/step_anatomy.out)."""
    if not _PRESCAN_RUNGS or not symmetry:
        return False
    import os
    force = os.environ.get("RAFT_TLA_PRESCAN", "auto")
    if force == "on":            # measurement override (runs/prescan_ab,
        return True              # in-engine bench A/B) — not for prod
    if force == "off":
        return False
    if jax.default_backend() == "cpu":
        return True
    g = 1
    if "Server" in symmetry:
        g *= math.factorial(bounds.n_servers)
    if "Value" in symmetry:
        g *= math.factorial(bounds.n_values)
    return g >= 120


def _sigprune_enabled(bounds, symmetry):
    """Platform/shape gate for signature-refinement orbit pruning
    (ops/symmetry.build_orbit_fp ``prune=``; the _SIGPRUNE_RUNGS comment
    has the soundness argument).  Env override ``RAFT_TLA_SIGPRUNE``
    {auto, on, off} mirrors RAFT_TLA_PRESCAN; ``check.py --sig-prune``
    sets it process-wide so every engine inherits one decision.

    Auto policy: OFF.  Measured (runs/sigprune_ab.py, sync-timed
    medians on reachable chunks; runs/bench_sigprune_inengine_ab.out):
    the kept scan only shortens when EVERY state in the chunk has a
    non-trivial verified stabilizer, and reachable mid-depth chunks are
    dominated by fully-asymmetric states (avg orbit size ~= |G| — the
    flagship's 94.4M orbits over ~6x raw states), so the probe overhead
    buys no rung and the A/B lands at loss-to-parity on CPU: mid-depth
    0.80-0.98x, shallow 0.74-1.02x, in-engine exhaustive 0.94x — the
    best case (|G|=120 shallow) only reaches parity, so even the
    symmetric-rich regime does not pay here.  The pruned path stays
    available via the override for on-chip
    re-measurement (the probe/min-scan trade is bandwidth-vs-flops and
    may invert on the VPU); composition with the prescan ladder is free
    because the prescan calls orbit_fp on its compacted rows."""
    if not symmetry:
        return False
    import os
    force = os.environ.get("RAFT_TLA_SIGPRUNE", "auto")
    if force == "on":            # measurement override (runs/sigprune_ab,
        return True              # in-engine bench A/B) and symmetric-rich
    if force == "off":           # workloads — not the default
        return False
    return False


def _megakernel_enabled(bounds, symmetry):
    """Platform gate for the Pallas megakernel build of the fused step
    (ops/pallas_step.py: the whole expand->canonicalize->orbit->filter
    pipeline in ONE kernel, candidates VMEM-resident across stages).
    Env override ``RAFT_TLA_MEGAKERNEL`` {auto, on, off} mirrors
    RAFT_TLA_SIGPRUNE; ``check.py --megakernel`` sets it process-wide so
    every engine inherits one decision at step-construction time.

    Auto policy: OFF.  Measured on CPU (runs/megakernel_ab.py: sync-timed
    per-chunk medians, in-engine northstar probe with per-phase
    attribution, chip-state fiducials bracketing): in-engine the gate-on
    arm is a 0.82x warm-rate LOSS (7,384 vs 9,006 orbits/s; the whole
    delta is the expand phase, 135.4 s vs 112.6 s) even though the
    block-sliced program wins 2-5% on pinned-gate step timings — under
    the production auto policy the prescan ladder makes the XLA step
    >2x faster, and the staged ladder is BLOCK-LOCAL (its signature
    grouping sees one 128-row block instead of the whole chunk), so the
    blocking that helps the pinned program costs the production one
    (RESULTS.md "Megakernel A/B" attributes the loss entirely to the
    expand phase).  On-chip
    the bet is HBM-round-trip elimination between the stage fusions vs
    Mosaic's appetite for the gather/sort-heavy canonicalize+prescan
    stages (the round-2 hand orbit kernel died there — RESULTS.md
    "Pallas orbit kernel"); the on-chip A/B is queued, and the gate
    stays available for it via the override."""
    import os
    force = os.environ.get("RAFT_TLA_MEGAKERNEL", "auto")
    if force == "on":            # measurement override (runs/megakernel_ab)
        return True              # and the on-chip re-A/B — not the default
    if force == "off":
        return False
    return False


def step_signature(bounds, spec, invariants, symmetry, view):
    """Everything :func:`build_step` specializes the compiled step on —
    universe bounds, spec subset, invariant set, symmetry axes, the
    dedup-key view, and the construction-time gate resolutions
    (megakernel / prescan / sig-prune).  THE definition of step-compile
    identity: serve/batch.bin_key delegates here, so two jobs share a
    lane-packed bin (and a compile) iff this tuple matches — bins can
    never mix step variants when a gate flips between admissions.

    Gates resolve per call (env + backend), so compute the signature at
    the same time you build the step it stands for."""
    # call-time import: devdedup imports device_engine, which imports
    # this module — a top-level import would cycle
    from raft_tla_tpu.ops import devdedup
    return (bounds, spec, tuple(invariants), tuple(symmetry), view,
            ("megakernel", _megakernel_enabled(bounds, symmetry)),
            ("prescan", _prescan_enabled(bounds, symmetry)),
            ("sigprune", _sigprune_enabled(bounds, symmetry)),
            ("devdedup", devdedup.devdedup_backend()))


def _orbit_fp_prescan(orbit_fp, flat, raw_hi, raw_lo, N):
    """Orbit-scan only the first occurrence of each raw key, gather the
    canonical fingerprints back through the group map (see the
    _PRESCAN_RUNGS comment; runs/step_anatomy.out has the measured
    justification).  Keys are (hi, lo) uint32 pairs — x64 is disabled,
    a u64 fuse would silently truncate."""
    idx = jnp.lexsort((raw_lo, raw_hi))
    sh, sl = raw_hi[idx], raw_lo[idx]
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (sh[1:] != sh[:-1]) | (sl[1:] != sl[:-1])])
    gid_sorted = jnp.cumsum(first.astype(jnp.int32)) - 1
    gid = jnp.zeros((N,), jnp.int32).at[idx].set(gid_sorted)
    n_uniq = gid_sorted[-1] + 1

    def compact_at(K):
        def compact(_):
            # rep[g] = original index of group g's first sorted member
            # (built INSIDE the branch: untaken rungs must cost nothing)
            rep = jnp.zeros((K,), jnp.int32).at[
                jnp.where(first, gid_sorted, K)].set(
                idx.astype(jnp.int32), mode="drop")
            flat_k = jax.tree.map(lambda a: a[rep], flat)
            fh_k, fl_k = orbit_fp(flat_k)
            return fh_k[gid], fl_k[gid]

        return compact

    def full(_):
        return orbit_fp(flat)

    # build the elif chain inside-out: largest K wraps full first, so
    # the final test order is smallest-K-first (tightest rung wins)
    out = full
    for div in sorted(_PRESCAN_RUNGS):
        K = max(1, N // div)
        out = (lambda _, _c=compact_at(K), _o=out, _K=K:
               jax.lax.cond(n_uniq <= _K, _c, _o, None))
    return out(None)


def apply_stages(bounds, stages, symmetry, succs, svecs, valid):
    """The per-candidate stage block on ``[B, A]``-shaped successors —
    view, orbit/plain fingerprints, invariants, StateConstraint.  One
    definition shared by the dense step and the CP-sharded step (the
    EP-routed step runs the same stages on its compacted ``[K]`` axis)."""
    lay, consts, _expand, inv_fns, orbit_fp, viewer = stages
    ksuccs, ksvecs = succs, svecs          # dedup-key inputs
    if viewer is not None:
        ksuccs = jax.vmap(jax.vmap(viewer))(succs)
        if not symmetry:
            ksvecs = jax.vmap(jax.vmap(
                lambda t: st.pack(t, jnp)))(ksuccs)
    if symmetry:
        flat = jax.tree.map(
            lambda a: a.reshape((-1,) + a.shape[2:]), ksuccs)
        N = valid.size
        vmask = valid.reshape(-1)
        if _prescan_enabled(bounds, symmetry):
            # raw keys hash the ALREADY-PACKED UN-VIEWED rows —
            # deliberate: zero extra pack cost, and raw grouping only
            # needs to REFINE canonical equality (under a view,
            # view-equal successors that differ in view-excluded fields
            # just occupy separate slots — less compaction, never
            # wrong).  In-chunk raw collisions are strictly inside the
            # globally-accepted fp-collision class; invalid lanes
            # collapse into one all-ones sentinel group
            rh, rl = fpr.fingerprint(svecs.reshape(N, -1), consts, jnp)
            rh = jnp.where(vmask, rh, ~jnp.uint32(0))
            rl = jnp.where(vmask, rl, ~jnp.uint32(0))
            fh, fl = _orbit_fp_prescan(orbit_fp, flat, rh, rl, N)
        else:
            fh, fl = orbit_fp(flat)
        # invalid lanes: ZERO, not whichever garbage the sentinel
        # group's rep produced — deterministic across step variants
        # (the CP per-lane parity test compares every lane)
        fh = jnp.where(vmask, fh, 0)
        fl = jnp.where(vmask, fl, 0)
        fp_hi = fh.reshape(svecs.shape[:2])
        fp_lo = fl.reshape(svecs.shape[:2])
    else:
        fp_hi, fp_lo = fpr.fingerprint(ksvecs, consts, jnp)
    if inv_fns:
        inv_ok = jnp.stack(
            [jax.vmap(jax.vmap(f))(succs) for f in inv_fns], axis=-1)
    else:
        inv_ok = jnp.ones(valid.shape + (0,), dtype=bool)
    con_ok = jax.vmap(jax.vmap(
        lambda t: st.constraint_ok(t, bounds, jnp)))(succs)
    return fp_hi, fp_lo, inv_ok, con_ok


def build_step_routed(bounds: Bounds, spec: str = "full",
                      invariants: tuple = (), symmetry: tuple = (),
                      k_rows: int = 0, view: str | None = None,
                      megakernel: bool | None = None, family_kernels=None):
    """EP-style routed frontier step (SURVEY §2.9, EP row): compact the
    enabled lanes, then run the expensive per-candidate stages densely.

    The dense :func:`build_step` evaluates pack/fingerprint/orbit/
    invariant/constraint on ALL ``B*A`` successor lanes, but measured
    transition density is ~6-10% of the fan-out (258.1M transitions over
    94.4M x 42 lanes on the flagship; RESULTS.md) — ~90% of the dominant
    orbit pass (|G| = n!*V! permutations, runs/xla_profile/SUMMARY.md) is
    spent on guard-disabled lanes.  This is the MoE-routing analog: the
    cheap elementwise expansion plays the router, a stable-order
    compaction (cumsum positions + scatter/gather, no sort) routes the
    enabled (state, action) pairs into ``k_rows`` dense slots, and the
    orbit/fingerprint/invariant "experts" see only live work.

    ``step(vecs[B, W], row_ok[B]) -> dict`` with the dense ``valid``/
    ``overflow`` ``[B, A]`` (the engine's deadlock/truncation logic reads
    these; NOT masked by ``row_ok``) plus the compacted candidate stream,
    ordered by flat lane index ``b*A + a`` — byte-identical discovery
    order to the dense step.  ``row_ok`` marks the chunk rows that are
    live (inside the block, constraint-satisfying): only their lanes
    consume routing slots — without it, the stale padded rows of a
    partial chunk would eat the budget and could trigger spurious
    ``route_ovf`` aborts.  Pass ``None`` when every row is live.

    - ``cidx [K] int32``: flat source index of each routed lane
      (``N = B*A`` for padding slots), strictly increasing on the live
      prefix;
    - ``cvalid [K]``: slot holds a routed lane;
    - ``csvecs [K, W]``, ``cfp_hi/cfp_lo [K]``, ``cinv_ok [K, n_inv]``,
      ``ccon_ok [K]``: exactly the dense step's values at ``cidx``;
    - ``route_ovf``: scalar bool — more than ``k_rows`` enabled lanes
      (the caller must abort loudly: candidates would be LOST, and
      "exhaustive" may not silently mean "sampled", SURVEY §4.5).

    Sizing: worst case is ``k_rows = B*A`` (full density — no saving, no
    loss); the measured regime makes ``B*A // 4`` a >=2.5x-headroom
    default.  Correct for parity AND faithful mode (the expansion twin
    carries the allLogs update; history fields ride the same gather).
    """
    if megakernel is None:
        megakernel = _megakernel_enabled(bounds, symmetry)
    if megakernel:
        # The routed step's stable-order compaction is an XLA scatter
        # BETWEEN the expand and stage phases — there is no fused-kernel
        # build of it.  Refusing loudly at construction beats silently
        # ignoring the gate (check.py rejects --megakernel on + --route
        # up front; direct env users land here).
        raise ValueError(
            "RAFT_TLA_MEGAKERNEL=on does not compose with the EP-routed "
            "step (build_step_routed); use the dense step (--route 0) or "
            "leave the megakernel gate auto/off")
    (lay, consts, expand, inv_fns, orbit_fp,
     viewer) = _step_stages(bounds, spec, invariants, symmetry, view,
                            family_kernels=family_kernels)
    if k_rows <= 0:
        raise ValueError(f"k_rows={k_rows} must be positive")
    K = int(k_rows)

    def step(vecs, row_ok=None):
        B = vecs.shape[0]
        structs = jax.vmap(lambda v: st.unpack(v, lay, jnp))(vecs)
        succs, valid, ovf = jax.vmap(expand)(structs)
        A = valid.shape[1]
        N = B * A
        live = valid if row_ok is None else valid & row_ok[:, None]
        fvalid = live.reshape(-1)
        # Stable compaction: slot k <- k-th enabled flat lane.  cumsum
        # preserves flat order, so the compacted stream IS the dense
        # stream with the dead lanes deleted — discovery order (hence
        # counts, coverage, traces, checkpoints) is engine-identical.
        pos = jnp.cumsum(fvalid.astype(I32)) - 1
        n_en = jnp.where(N > 0, pos[-1] + 1, 0)
        route_ovf = n_en > K
        slot = jnp.where(fvalid & (pos < K), pos, K)
        cidx = jnp.full((K,), N, dtype=I32).at[slot].set(
            jnp.arange(N, dtype=I32), mode="drop")
        cvalid = cidx < N
        gidx = jnp.minimum(cidx, N - 1)
        flat = jax.tree.map(lambda a: a.reshape((N,) + a.shape[2:]), succs)
        csucc = jax.tree.map(lambda a: a[gidx], flat)
        csvecs = jax.vmap(lambda t: st.pack(t, jnp))(csucc)
        ksucc, ksvecs = csucc, csvecs      # dedup-key inputs
        if viewer is not None:
            ksucc = jax.vmap(viewer)(csucc)
            if not symmetry:
                ksvecs = jax.vmap(lambda t: st.pack(t, jnp))(ksucc)
        if symmetry:
            cfp_hi, cfp_lo = orbit_fp(ksucc)
        else:
            cfp_hi, cfp_lo = fpr.fingerprint(ksvecs, consts, jnp)
        if inv_fns:
            cinv_ok = jnp.stack([jax.vmap(f)(csucc) for f in inv_fns],
                                axis=-1)
        else:
            cinv_ok = jnp.ones((K, 0), dtype=bool)
        ccon_ok = jax.vmap(
            lambda t: st.constraint_ok(t, bounds, jnp))(csucc)
        return {"valid": valid, "overflow": ovf, "cidx": cidx,
                "cvalid": cvalid, "csvecs": csvecs, "cfp_hi": cfp_hi,
                "cfp_lo": cfp_lo, "cinv_ok": cinv_ok, "ccon_ok": ccon_ok,
                "route_ovf": route_ovf, "n_en": n_en}

    return step
