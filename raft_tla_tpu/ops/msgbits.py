"""Bit-packed message encoding — the tensor form of the spec's message records.

The reference's ``messages`` variable is a bag of heterogeneous records
(``raft.tla:32``, schemas built at ``raft.tla:193-198`` (RequestVoteRequest),
``raft.tla:294-301`` (RequestVoteResponse), ``raft.tla:215-225``
(AppendEntriesRequest), ``raft.tla:338-343,366-372`` (AppendEntriesResponse)).
Each distinct message maps to one slot of three int32s: two *content words*
``(hi, lo)`` and a multiplicity ``count`` (the bag value, ``raft.tla:106-119``).

Content is unioned into generic fields ``a..f`` so every record type fits one
layout (field meanings per type are in the table below).  Two messages are the
same bag element iff their ``(hi, lo)`` words are equal, and canonical state
ordering sorts slots by ``(hi, lo)`` — so packing *is* the equality and order
structure of the bag.

The ``mlog`` fields (``raft.tla:220-222`` and ``raft.tla:297-299``) are
proof-only history data: in parity mode they are stripped (field ``g`` = 0),
exactly as they are stripped from the derived history-free spec that the TLC
oracle runs (models/tla_export.py, SURVEY §7.0.3); in faithful mode they are
carried as log-universe ranks (ops/loguniv.py) and join message identity, as
in stock TLC on the unmodified spec.

=========  =============================  =====================================
field      bits (word@shift)              meaning by mtype
=========  =============================  =====================================
mtype      3  (hi@0)                      1=RVReq 2=RVResp 3=AEReq 4=AEResp
mterm      6  (hi@3)                      all types (raft.tla:194,295,216,339)
a          6  (hi@9)                      RVReq: mlastLogTerm (:195)
                                          RVResp: mvoteGranted (:296)
                                          AEReq: mprevLogIndex (:217)
                                          AEResp: msuccess (:340)
b          6  (hi@15)                     RVReq: mlastLogIndex (:196)
                                          AEReq: mprevLogTerm (:218)
                                          AEResp: mmatchIndex (:341)
src        4  (hi@21)                     msource (all)
dst        4  (hi@25)                     mdest (all)
c          1  (lo@0)                      AEReq: Len(mentries), 0|1 (:212-214)
d          6  (lo@1)                      AEReq: mentries[1].term
e          4  (lo@7)                      AEReq: mentries[1].value
f          6  (lo@11)                     AEReq: mcommitIndex (:223)
g          14 (lo@17)                     faithful mode only: ``mlog`` as a
                                          log-universe rank (ops/loguniv.py)
                                          AEReq :220-222, RVResp :297-299;
                                          0 in parity mode (stripped)
=========  =============================  =====================================

All helpers are plain shift/mask arithmetic, so they work identically on
Python ints, NumPy arrays, and JAX arrays (the np/jnp fingerprint and the
interpreter share this module — one source of truth for the encoding).
"""

from __future__ import annotations

# (shift, width) per field — THE packed-record encoding.  Public: the
# static analyzer (analysis/widthcheck) validates the tables (no overlap,
# no spill past bit 31 — the int32 sign bit stays clear) and proves every
# record-creation site writes subfields that fit them.  Mutating a width
# here without re-deriving the proof is exactly the silent-truncation bug
# class the analyzer exists to catch (tests/test_lint_mutations.py).
HI_FIELDS = {"mtype": (0, 3), "mterm": (3, 6), "a": (9, 6), "b": (15, 6),
             "src": (21, 4), "dst": (25, 4)}
LO_FIELDS = {"c": (0, 1), "d": (1, 6), "e": (7, 4), "f": (11, 6),
             "g": (17, 14)}
# Historical private aliases (bitpack and older call sites).
_HI_FIELDS = HI_FIELDS
_LO_FIELDS = LO_FIELDS


def pack_hi(mtype, mterm, a, b, src, dst):
    return (mtype | (mterm << 3) | (a << 9) | (b << 15)
            | (src << 21) | (dst << 25))


def pack_lo(c, d, e, f, g=0):
    return c | (d << 1) | (e << 7) | (f << 11) | (g << 17)


def _get(word, shift, width):
    return (word >> shift) & ((1 << width) - 1)


def mtype(hi):
    return _get(hi, *_HI_FIELDS["mtype"])


def mterm(hi):
    return _get(hi, *_HI_FIELDS["mterm"])


def fa(hi):
    return _get(hi, *_HI_FIELDS["a"])


def fb(hi):
    return _get(hi, *_HI_FIELDS["b"])


def src(hi):
    return _get(hi, *_HI_FIELDS["src"])


def dst(hi):
    return _get(hi, *_HI_FIELDS["dst"])


def fc(lo):
    return _get(lo, *_LO_FIELDS["c"])


def fd(lo):
    return _get(lo, *_LO_FIELDS["d"])


def fe(lo):
    return _get(lo, *_LO_FIELDS["e"])


def ff(lo):
    return _get(lo, *_LO_FIELDS["f"])


def fg(lo):
    """``mlog`` as a log-universe rank (faithful mode only; 0 in parity)."""
    return _get(lo, *_LO_FIELDS["g"])


# -- typed constructors (field meanings per record schema, see module doc) ---

def rv_request(term, last_log_term, last_log_index, i, j):
    """RequestVoteRequest record (raft.tla:193-198)."""
    return pack_hi(1, term, last_log_term, last_log_index, i, j), pack_lo(0, 0, 0, 0)


def rv_response(term, granted, i, j, mlog=0):
    """RequestVoteResponse record (raft.tla:294-301).

    ``mlog`` — the voter's log as a universe rank (raft.tla:297-299) — is
    carried only in faithful mode; parity mode passes 0 (stripped).
    """
    return pack_hi(2, term, granted, 0, i, j), pack_lo(0, 0, 0, 0, mlog)


def ae_request(term, prev_idx, prev_term, n_entries, ent_term, ent_val,
               commit, i, j, mlog=0):
    """AppendEntriesRequest record (raft.tla:215-225).

    ``mlog`` — the leader's log as a universe rank (raft.tla:220-222) — is
    carried only in faithful mode; parity mode passes 0 (stripped).
    """
    return (pack_hi(3, term, prev_idx, prev_term, i, j),
            pack_lo(n_entries, ent_term, ent_val, commit, mlog))


def ae_response(term, success, match_idx, i, j):
    """AppendEntriesResponse record (raft.tla:338-343, 366-372)."""
    return pack_hi(4, term, success, match_idx, i, j), pack_lo(0, 0, 0, 0)
