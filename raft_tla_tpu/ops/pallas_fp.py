"""Pallas TPU kernel for the two-lane multilinear fingerprint.

The fingerprint (ops/fingerprint.py) is a per-row multiply-accumulate over
uint32 lanes plus a murmur3 finalizer — exactly the shape the VPU wants:
one [rows, lanes] elementwise product, a lane reduction, and a handful of
shifts.  XLA already fuses the jnp version into the surrounding step
kernel, so this Pallas twin exists for the cases where the fingerprint
runs *standalone* over large row blocks (host-store audits, re-hashing a
paged store after a bounds change, the sharded engine's routing prefix)
and as the reference pattern for hand-scheduled kernels in this codebase:
explicit VMEM blocking over a 1-D grid, broadcast constants, lane-padded
inputs.

Bit-identical to the NumPy/jnp implementations (asserted in tests): same
constants, same uint32 wraparound, same finalizer.  Falls back to the jnp
path off-TPU (Pallas interpret mode is used by the CPU tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import pallas_compat as pc

_BLOCK_ROWS = 1024
_LANES = 128          # TPU lane width; W pads up to a multiple


def i32_const(u) -> int:
    """uint32 constant as the same-bits PYTHON int32 literal (shared by
    the Pallas kernels: they may not close over traced array constants,
    and plain ints fold into the program)."""
    return int(np.uint32(u).astype(np.int32))


def _i32(u) -> jnp.int32:
    """Reinterpret a uint32 constant as int32 (same bits)."""
    return jnp.int32(np.uint32(u).astype(np.int32))


def fmix_i32(h):
    """murmur3 finalizer in two's-complement int32 — bit-identical to
    the uint32 reference (ops/fingerprint._fmix32); right shifts are
    explicitly logical.  Shared by both Pallas kernels."""
    srl = jax.lax.shift_right_logical
    h = h ^ srl(h, 16)
    h = h * i32_const(0x85EBCA6B)
    h = h ^ srl(h, 13)
    h = h * i32_const(0xC2B2AE35)
    h = h ^ srl(h, 16)
    return h


def _fp_kernel(vec_ref, c1_ref, c2_ref, hi_ref, lo_ref):
    # Mosaic has no unsigned reductions; two's-complement int32 add/mul/xor
    # are bit-identical to uint32 mod 2^32, and the finalizer's right
    # shifts are made explicitly logical.
    srl = jax.lax.shift_right_logical
    w = vec_ref[...]
    s1 = jnp.sum(w * c1_ref[...], axis=1, dtype=jnp.int32)
    s2 = jnp.sum(w * c2_ref[...], axis=1, dtype=jnp.int32)

    def fmix(h):
        h = h ^ srl(h, jnp.int32(16))
        h = h * _i32(0x85EBCA6B)
        h = h ^ srl(h, jnp.int32(13))
        h = h * _i32(0xC2B2AE35)
        h = h ^ srl(h, jnp.int32(16))
        return h

    hi_ref[...] = fmix(s1 + _i32(fpr._LANE_SEEDS[0]))
    lo_ref[...] = fmix(s2 + _i32(fpr._LANE_SEEDS[1]))


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fp_call(vecs, c1, c2, interpret=False):
    from jax.experimental import pallas as pl

    B, Wp = vecs.shape
    grid = (B // _BLOCK_ROWS,)
    return pl.pallas_call(
        _fp_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, Wp), lambda i: (i, 0)),
            pl.BlockSpec((1, Wp), lambda i: (0, 0)),
            pl.BlockSpec((1, Wp), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_ROWS,), lambda i: (i,)),
            pl.BlockSpec((_BLOCK_ROWS,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B,), jnp.int32),
            jax.ShapeDtypeStruct((B,), jnp.int32),
        ],
        interpret=interpret,
    )(vecs, c1, c2)


@functools.lru_cache(maxsize=None)
def _padded_constants(W: int, Wp: int):
    """Lane-padded int32 views of the multipliers, built once per width
    (callers loop over row blocks of a fixed W)."""
    ci = np.asarray(fpr.lane_constants(W)).astype(np.int32)  # same bits
    c1 = jnp.zeros((1, Wp), jnp.int32).at[0, :W].set(ci[0])
    c2 = jnp.zeros((1, Wp), jnp.int32).at[0, :W].set(ci[1])
    return c1, c2


def fingerprint_rows(vecs, interpret: bool | None = None):
    """``int32[B, W] -> (hi, lo) uint32[B]`` via the Pallas kernel.

    Rows pad to the block multiple and lanes to 128 (zero pads contribute
    zero to the multilinear sum, so padding never changes a fingerprint).
    Execution mode is resolved by ``ops.pallas_compat``: ``interpret=True``
    runs the kernel under the Pallas interpreter (CPU testing), ``None``
    auto-selects — Mosaic on TPU, else the bit-identical portable jnp
    path (``ops.fingerprint.fingerprint``) — and ``False`` forces a real
    Mosaic build (loud failure off-TPU).
    """
    vecs = jnp.asarray(vecs, jnp.int32)
    B, W = vecs.shape
    if pc.resolve(interpret, jnp_fallback=True) == pc.JNP:
        # the portable jnp path (XLA-fused; bit-identical by construction)
        return fpr.fingerprint(vecs, jnp.asarray(fpr.lane_constants(W)),
                               jnp)
    Wp = ((W + _LANES - 1) // _LANES) * _LANES
    Bp = ((B + _BLOCK_ROWS - 1) // _BLOCK_ROWS) * _BLOCK_ROWS
    vp = jnp.zeros((Bp, Wp), jnp.int32).at[:B, :W].set(vecs)
    c1, c2 = _padded_constants(W, Wp)
    hi, lo = _fp_call(vp, c1, c2,
                      interpret=pc.resolve(interpret,
                                           jnp_fallback=True) == pc.INTERPRET)
    return hi[:B].astype(jnp.uint32), lo[:B].astype(jnp.uint32)
