"""Pallas TPU kernel for the orbit-minimal fingerprint (Server symmetry).

The scan-compiled orbit pass (ops/symmetry.build_orbit_fp) is the hot
stage of every symmetric search: at 5 servers it iterates the permute →
canonicalize → pack → fingerprint pipeline 120 times per candidate
block, and under ``lax.scan`` XLA materializes the intermediate struct in
HBM on EVERY iteration — measured ~123 ms of a 134 ms chunk at
chunk 2048, ~100x off both the VPU and HBM rooflines (RESULTS.md
round-2 profile).  This kernel keeps a row block resident in VMEM and
unrolls the whole permutation group over it, so HBM sees each candidate
exactly once: read [R, W] lanes, write [R] (hi, lo).

The key algebraic move: a permutation only REORDERS most lanes, and the
fingerprint is a dot product — so instead of gathering the data, the
kernel dots the ORIGINAL lanes against **permutation-permuted constants**
(``sum_l v[g[l]]*c[l] == sum_m v[m]*c[ginv[m]]``), baked per group
element into one ``[P, 2, W]`` operand.  Only the three value-rewriting
fields (votedFor relabel, vote-bitmask bit moves, message src/dst
relabel + slot re-sort) are computed explicitly, with static integer
slices and short one-hot sums — no tables, no dynamic gathers, no
captured constants.

Scope: **parity mode** (no history variables), **Server axis only** —
the shape of every large campaign (the flagship, elect5, config #4).
Value symmetry / faithful mode fall back to the scan path in
kernels.build_step.

Bit-identity with the scan path (asserted lane-for-lane in
tests/test_pallas_orbit.py):

- canonicalize re-sorts the S message slots with the same odd-even
  comparator network as ``state._network_sort`` (the sorted result is
  unique, see its docstring); hi/lo stay below 2^31 (ops/msgbits field
  widths), so int32 comparisons equal the reference's;
- the fingerprint runs in two's-complement int32, bit-identical to
  uint32 mod 2^32 (the ops/pallas_fp.py argument), with explicitly
  logical right shifts in the finalizer;
- the (hi, lo) running min uses sign-bias-corrected comparisons, since
  the reference minimizes in uint32.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.ops.pallas_fp import fmix_i32, i32_const
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym

_BLOCK_ROWS = 128    # per-block VMEM stack scales with R; 256 overflowed
#                      the 16M scoped-vmem limit on a real v5e

# fields whose VALUES change under a server relabeling (everything else
# only moves between lanes, which the permuted-constants trick absorbs)
_REWRITTEN = ("votedFor", "vResp", "vGrant", "msgHi", "msgLo", "msgCount")


def _offsets(lay: st.Layout) -> dict:
    out, off = {}, 0
    for f, shape in lay.shapes.items():
        out[f] = (off, shape)
        off += int(np.prod(shape))
    return out


def _perm_gather_index(lay: st.Layout, p: tuple) -> np.ndarray:
    """Static lane map for one permutation: output lane w reads input
    lane ``gidx[w]`` — the pure-reorder part of ``permute_struct``
    (``rows(a) = take(a, inv, axis=1)`` with ``inv[k] = p.index(k)``)."""
    n, L = lay.n, lay.L
    inv = [p.index(k) for k in range(n)]
    offs = _offsets(lay)
    gidx = np.arange(lay.width, dtype=np.int32)
    for f in ("role", "term", "votedFor", "commitIndex", "logLen",
              "vResp", "vGrant"):
        b = offs[f][0]
        for k in range(n):
            gidx[b + k] = b + inv[k]
    for f in ("logTerm", "logVal"):
        b = offs[f][0]
        for k in range(n):
            for l in range(L):
                gidx[b + k * L + l] = b + inv[k] * L + l
    for f in ("nextIndex", "matchIndex"):
        b = offs[f][0]
        for k in range(n):
            for l in range(n):
                gidx[b + k * n + l] = b + inv[k] * n + inv[l]
    return gidx


def _perm_consts(lay: st.Layout, consts: np.ndarray,
                 perms: tuple) -> np.ndarray:
    """``cp[pi, t, m] = consts[t, ginv_pi[m]]`` on reorder-only lanes,
    0 on value-rewritten lanes (their contributions are added explicitly
    in the kernel)."""
    offs = _offsets(lay)
    rewritten = np.zeros(lay.width, bool)
    for f in _REWRITTEN:
        b, shape = offs[f]
        rewritten[b:b + int(np.prod(shape))] = True
    ci = consts.astype(np.uint32).view(np.int32)
    cp = np.zeros((len(perms), 2, lay.width), np.int32)
    for pi, p in enumerate(perms):
        ginv = np.argsort(_perm_gather_index(lay, p))
        for t in range(2):
            row = ci[t][ginv].copy()
            row[rewritten] = 0
            cp[pi, t] = row
    return cp


def _build_kernel(bounds: Bounds):
    lay = st.Layout.of(bounds)
    n, S = lay.n, lay.S
    offs = _offsets(lay)
    perms = sym.permutations(bounds)
    pairs = st._oddeven_pairs(S)
    s_sh, s_w = mb._HI_FIELDS["src"]
    d_sh, d_w = mb._HI_FIELDS["dst"]
    hi_keep = int(~np.int32(((1 << s_w) - 1) << s_sh
                            | ((1 << d_w) - 1) << d_sh))
    b_vf = offs["votedFor"][0]
    b_vr = offs["vResp"][0]
    b_vg = offs["vGrant"][0]
    b_mh = offs["msgHi"][0]
    b_ml = offs["msgLo"][0]
    b_mc = offs["msgCount"][0]
    SIGN = i32_const(0x80000000)

    def kernel(vec_ref, cp_ref, cr_ref, hi_ref, lo_ref):
        w0 = vec_ref[...]                       # [R, W] VMEM-resident
        R = w0.shape[0]
        best_hi = jnp.full((R,), -1, jnp.int32)     # 0xFFFFFFFF
        best_lo = jnp.full((R,), -1, jnp.int32)
        for pi, p in enumerate(perms):
            inv = [p.index(k) for k in range(n)]
            # reorder-only lanes: dot against permuted constants
            s1 = jnp.sum(w0 * cp_ref[pi, 0][None, :], axis=1,
                         dtype=jnp.int32)
            s2 = jnp.sum(w0 * cp_ref[pi, 1][None, :], axis=1,
                         dtype=jnp.int32)

            def add(s1, s2, col, lane):
                return (s1 + col * cr_ref[0, lane],
                        s2 + col * cr_ref[1, lane])

            # votedFor: column k comes from old column inv[k]; values
            # relabel 0 (Nil) fixed, j+1 -> p[j]+1
            for k in range(n):
                col = w0[:, b_vf + inv[k]]
                col2 = jnp.zeros_like(col)
                for j in range(n):
                    col2 = col2 + jnp.where(col == j + 1,
                                            jnp.int32(p[j] + 1), 0)
                s1, s2 = add(s1, s2, col2, b_vf + k)
            # vote bitmasks: bit j moves to bit p[j]
            for base in (b_vr, b_vg):
                for k in range(n):
                    col = w0[:, base + inv[k]]
                    col2 = jnp.zeros_like(col)
                    for j in range(n):
                        col2 = col2 | (((col >> j) & 1) << p[j])
                    s1, s2 = add(s1, s2, col2, base + k)
            # message slots: src/dst relabel on occupied slots, zero the
            # unoccupied, then the canonical odd-even slot sort
            ks, hs, ls, cs = [], [], [], []
            for s in range(S):
                hi = w0[:, b_mh + s]
                lo = w0[:, b_ml + s]
                ct = w0[:, b_mc + s]
                src = (hi >> s_sh) & ((1 << s_w) - 1)
                dst = (hi >> d_sh) & ((1 << d_w) - 1)
                src2 = jnp.zeros_like(src)
                dst2 = jnp.zeros_like(dst)
                for j in range(n):
                    src2 = src2 + jnp.where(src == j, jnp.int32(p[j]), 0)
                    dst2 = dst2 + jnp.where(dst == j, jnp.int32(p[j]), 0)
                occ = ct > 0
                hi = jnp.where(occ, (hi & hi_keep) | (src2 << s_sh)
                               | (dst2 << d_sh), 0)
                lo = jnp.where(occ, lo, 0)
                ct = jnp.where(occ, ct, 0)
                # int32 select, NOT a bool cast: Mosaic folds
                # (~occ).astype(int32) back to an i1 vector, and the
                # sort network's == on i1 fails to legalize on real
                # TPUs ('arith.cmpi' on vector<8x128xi1>)
                ks.append(jnp.where(occ, jnp.int32(0), jnp.int32(1)))
                hs.append(hi)
                ls.append(lo)
                cs.append(ct)
            for i, j in pairs:
                le = ls[i] <= ls[j]
                le = (hs[i] < hs[j]) | ((hs[i] == hs[j]) & le)
                le = (ks[i] < ks[j]) | ((ks[i] == ks[j]) & le)
                for arr in (ks, hs, ls, cs):
                    a, b = arr[i], arr[j]
                    arr[i] = jnp.where(le, a, b)
                    arr[j] = jnp.where(le, b, a)
            for s in range(S):
                s1, s2 = add(s1, s2, hs[s], b_mh + s)
                s1, s2 = add(s1, s2, ls[s], b_ml + s)
                s1, s2 = add(s1, s2, cs[s], b_mc + s)

            fhi = fmix_i32(s1 + i32_const(int(fpr._LANE_SEEDS[0])))
            flo = fmix_i32(s2 + i32_const(int(fpr._LANE_SEEDS[1])))
            # unsigned (hi, lo) lexicographic min via sign bias
            bh, bl = best_hi ^ SIGN, best_lo ^ SIGN
            fh, fl = fhi ^ SIGN, flo ^ SIGN
            take = (fh < bh) | ((fh == bh) & (fl < bl))
            best_hi = jnp.where(take, fhi, best_hi)
            best_lo = jnp.where(take, flo, best_lo)
        hi_ref[...] = best_hi[:, None]
        lo_ref[...] = best_lo[:, None]

    return kernel, lay.width, perms


def supported(bounds: Bounds, axes: tuple, faithful: bool) -> bool:
    return tuple(axes) == ("Server",) and not faithful


@functools.partial(jax.jit, static_argnames=("bounds", "interpret"))
def _orbit_call(vecs, bounds, interpret=False):
    kernel, W, perms = _build_kernel(bounds)
    consts = fpr.lane_constants(W)
    lay = st.Layout.of(bounds)
    cp = jnp.asarray(_perm_consts(lay, consts, perms))
    cr = jnp.asarray(consts.astype(np.uint32).view(np.int32))
    N = vecs.shape[0]
    R = _BLOCK_ROWS
    npad = (-N) % R
    v = jnp.pad(vecs, ((0, npad), (0, 0)))
    grid = (v.shape[0] // R,)
    P = len(perms)
    hi, lo = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((R, W), lambda i: (i, 0)),
                  pl.BlockSpec((P, 2, W), lambda i: (0, 0, 0)),
                  pl.BlockSpec((2, W), lambda i: (0, 0))],
        # outputs are column vectors [Npad, 1] with (R, 1) blocks: 1-D
        # s32 outputs carry XLA layout tiling T(1024), which Mosaic
        # rejects for R != 1024 blocks — and R = 1024 overflows the
        # scoped-vmem stack; the 2-D column form tiles (8, 128) with a
        # lane dim equal to the array's, which both sides accept
        out_specs=[pl.BlockSpec((R, 1), lambda i: (i, 0)),
                   pl.BlockSpec((R, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((v.shape[0], 1), jnp.int32),
                   jax.ShapeDtypeStruct((v.shape[0], 1), jnp.int32)],
        interpret=interpret,
    )(v.astype(jnp.int32), cp, cr)
    return (hi.reshape(-1)[:N].astype(jnp.uint32),
            lo.reshape(-1)[:N].astype(jnp.uint32))


# Mosaic's scoped-vmem kernel stack grows with the unrolled permutation
# count: measured 73.4M at P = 120 (5 servers) against the 16M limit on a
# real v5e, while P = 6 (3 servers) compiles and runs bit-identically.
# Beyond this bound the builder declines and callers use the scan path.
# MEASURED boundary (round 3, runs/pallas_orbit_p24.py on the real
# chip): P=24 (4-server group) fails Mosaic compilation outright
# (remote_compile HTTP 500, tpu_compile_helper exit 1), so the earlier
# extrapolated gate of 24 was too generous — only the measured-good
# P=6 (3 servers) compiles.  The round-2 advisor predicted exactly
# this; the gate now sits at the largest value ever seen to work.
_MAX_COMPILED_PERMS = 6


def build_orbit_fp(bounds: Bounds, axes: tuple, faithful: bool,
                   interpret: bool | None = None):
    """Packed-vec orbit fingerprints ``vecs[N, W] -> (hi, lo)[N]``, or
    ``None`` when this kernel does not cover the configuration."""
    if not supported(bounds, axes, faithful):
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not interpret and math.factorial(bounds.n_servers) \
            > _MAX_COMPILED_PERMS:
        return None

    def orbit_fp(vecs):
        return _orbit_call(vecs, bounds, interpret)

    return orbit_fp
