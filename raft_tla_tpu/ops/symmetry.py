"""Symmetry reduction over the Server model values (TLC SYMMETRY stanza).

The reference binds ``Server`` to model values (``raft.cfg:6``), which TLC
can quotient by permutation symmetry (its classic state-space reduction —
the spec never distinguishes individual servers).  This module implements
the same reduction for the tensor checker, the TPU way:

The dedup key of a state becomes its **orbit-minimal fingerprint**:
``min over all permutations π of fp(canonicalize(π(s)))``, where ``π(s)``
renumbers every server-indexed axis and server-valued field.  The min is
orbit-invariant, so two states equal up to server renaming share one key
and one store row — the reachable count becomes the orbit count, exactly
TLC's SYMMETRY semantics (including its property: the stored witness per
orbit is whichever member was discovered first).  On device this is |π|
static transforms batched over the candidate block — pure gathers, bit
arithmetic, and the existing canonicalize/pack/fingerprint pipeline, fused
by XLA; no extra passes over HBM.

Permuting one state under ``p`` (new index of old server j is ``p[j]``):

- per-server axes (role, term, votedFor, commitIndex, logLen, log*,
  vResp, vGrant): rows reordered by the inverse permutation;
- server-valued *contents*: ``votedFor`` ids map through ``p`` (0 = Nil
  fixed); vote bitmasks move bit j to bit ``p[j]``;
- ``nextIndex``/``matchIndex`` reorder both axes;
- message records rewrite their ``src``/``dst`` fields through ``p``
  (occupied slots only — empty slots stay all-zero), then the bag
  re-canonicalizes (sort order may change under renaming).

``Value`` symmetry (TLC's ``Permutations(Value)``) composes: values have no
distinguished elements in the spec (they only enter through ``ClientRequest``
and flow inertly through logs and ``mentries``), so the orbit key may also
minimize over value permutations.  Permuting values remaps ``logVal``
contents, the message entry-value field, and — in faithful mode — every
log-universe rank (``ops/loguniv.py``) through a precomputed static
rank-permutation table (allLogs bitmasks permute bitwise).  The full orbit
pass is then ``n! * V!`` static transforms.
"""

from __future__ import annotations

import functools
import itertools
import math

import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.ops import state as st

MAX_SYM_SERVERS = 6      # 720 permutations; beyond this the orbit pass dwarfs the step


def permutations(bounds: Bounds) -> tuple:
    if bounds.n_servers > MAX_SYM_SERVERS:
        raise ValueError(
            f"Server symmetry supports at most {MAX_SYM_SERVERS} servers "
            f"(got {bounds.n_servers}: {math.factorial(bounds.n_servers)}"
            " permutations)")
    return tuple(itertools.permutations(range(bounds.n_servers)))


MAX_SYM_VALUES = 5       # 120 value permutations


def value_permutations(bounds: Bounds) -> tuple:
    if bounds.n_values > MAX_SYM_VALUES:
        raise ValueError(
            f"Value symmetry supports at most {MAX_SYM_VALUES} values "
            f"(got {bounds.n_values})")
    return tuple(itertools.permutations(range(bounds.n_values)))


@functools.lru_cache(maxsize=None)
def _rank_maps(bounds: Bounds) -> tuple:
    """Per value-permutation q: int32[U] mapping each log rank to the rank
    of the value-permuted log (faithful mode; identity-permutation first)."""
    from raft_tla_tpu.ops.loguniv import LogUniverse
    uni = LogUniverse.of(bounds)
    maps = []
    for q in value_permutations(bounds):
        m = np.empty((uni.size,), np.int32)
        for r in range(uni.size):
            log = uni.tuple_of_id(r)
            m[r] = uni.id_of_tuple(tuple((t, q[v - 1] + 1) for t, v in log))
        maps.append(m)
    return tuple(maps)


def permute_values(struct: dict, qi: int, bounds: Bounds, xp) -> dict:
    """Apply the ``qi``-th value permutation to one state struct.

    Remaps ``logVal`` contents (0 = padding fixed), the message entry-value
    field ``e`` (zero for every non-AppendEntriesRequest record, and the
    LUT fixes 0), and in faithful mode every log rank through the static
    rank table — ``allLogs`` permutes bitwise.
    """
    q = value_permutations(bounds)[qi]
    V = bounds.n_values
    vlut = xp.asarray((0,) + tuple(q[v - 1] + 1 for v in range(1, V + 1)))
    out = dict(struct)
    out["logVal"] = vlut[struct["logVal"]]
    e_sh, e_w = mb._LO_FIELDS["e"]
    lo = struct["msgLo"]
    e_lut = xp.asarray((0,) + tuple(q[v - 1] + 1 for v in range(1, V + 1))
                       + tuple(0 for _ in range((1 << e_w) - V - 1)))
    new_lo = (lo & ~(((1 << e_w) - 1) << e_sh)) \
        | (e_lut[(lo >> e_sh) & ((1 << e_w) - 1)] << e_sh)
    if "allLogs" in struct:
        rmap = xp.asarray(_rank_maps(bounds)[qi])
        U = int(rmap.shape[0])
        rlut1 = xp.concatenate([xp.zeros((1,), xp.int32),
                                rmap.astype(xp.int32) + 1])  # rank+1 form
        out["vLog"] = rlut1[struct["vLog"]]
        out["eLog"] = rmap[struct["eLog"]]
        out["eVLog"] = rlut1[struct["eVLog"]]
        # mlog rank rides the g field of the lo word
        g_sh, g_w = mb._LO_FIELDS["g"]
        g_lut = xp.concatenate(
            [rmap.astype(xp.int32),
             xp.zeros(((1 << g_w) - U,), xp.int32)])
        new_lo = (new_lo & ~(((1 << g_w) - 1) << g_sh)) \
            | (g_lut[(new_lo >> g_sh) & ((1 << g_w) - 1)] << g_sh)
        # allLogs: bit r of the old mask becomes bit rmap[r] of the new
        # one.  Contributions within a word are distinct bit positions, so
        # an integer sum IS the bitwise OR.  Bits 0..30 sum safely in
        # int32; the sign bit is OR'd in separately (no x64 under jit).
        rs = xp.arange(U)
        bits = ((struct["allLogs"][rs // 32] >> (rs % 32)) & 1)
        Wa = struct["allLogs"].shape[0]
        in_word = (rmap[None, :] // 32) == xp.arange(Wa)[:, None]  # [Wa, U]
        tb = rmap[None, :] % 32
        low = xp.where(in_word & (tb < 31) & (bits[None, :] > 0),
                       xp.asarray(1, xp.int32) << tb, 0).sum(axis=1)
        top = (in_word & (tb == 31) & (bits[None, :] > 0)).any(axis=1)
        out["allLogs"] = (low.astype(xp.int32)
                          | xp.where(top, xp.asarray(-2**31, xp.int32), 0))
    occupied = struct["msgCount"] > 0
    out["msgLo"] = xp.where(occupied, new_lo, struct["msgLo"])
    return out


def permute_struct(struct: dict, p: tuple, bounds: Bounds, xp) -> dict:
    """Apply server permutation ``p`` to one state struct (then the caller
    must re-canonicalize the message bag)."""
    n = bounds.n_servers
    inv = tuple(p.index(k) for k in range(n))      # new row k = old row inv[k]
    inv_idx = xp.asarray(inv)
    # votedFor lookup: 0 stays Nil, id j+1 -> p[j]+1
    vf_map = xp.asarray((0,) + tuple(p[j] + 1 for j in range(n)))

    def rows(a):
        return a[inv_idx, ...]

    def bitperm(mask):
        out = xp.zeros_like(mask)
        for j in range(n):
            out = out | (((mask >> j) & 1) << p[j])
        return out

    # src/dst fields of occupied message slots, via the packed hi word
    s_sh, s_w = mb._HI_FIELDS["src"]
    d_sh, d_w = mb._HI_FIELDS["dst"]
    keep = ~(((1 << s_w) - 1) << s_sh | ((1 << d_w) - 1) << d_sh)
    hi = struct["msgHi"]
    occupied = struct["msgCount"] > 0
    p_lut = xp.asarray(p + tuple(0 for _ in range(16 - n)))  # 4-bit fields
    new_hi = (hi & keep) | (p_lut[(hi >> s_sh) & ((1 << s_w) - 1)] << s_sh) \
        | (p_lut[(hi >> d_sh) & ((1 << d_w) - 1)] << d_sh)
    new_hi = xp.where(occupied, new_hi, hi)

    out = {
        "role": rows(struct["role"]),
        "term": rows(struct["term"]),
        "votedFor": vf_map[rows(struct["votedFor"])],
        "commitIndex": rows(struct["commitIndex"]),
        "logLen": rows(struct["logLen"]),
        "logTerm": rows(struct["logTerm"]),
        "logVal": rows(struct["logVal"]),
        "vResp": bitperm(rows(struct["vResp"])),
        "vGrant": bitperm(rows(struct["vGrant"])),
        "nextIndex": struct["nextIndex"][inv_idx, :][:, inv_idx],
        "matchIndex": struct["matchIndex"][inv_idx, :][:, inv_idx],
        "msgHi": new_hi,
        "msgLo": struct["msgLo"],
        "msgCount": struct["msgCount"],
    }
    if "eTerm" in struct:
        # Faithful-mode history (ops/state.py HISTORY_FIELDS).  Log ranks
        # contain no server ids, so allLogs/eLog/mlog are fixed points;
        # voterLog permutes both axes like nextIndex, election records
        # remap eleader/evotes/evoterLog (slot re-sort happens in the
        # caller's canonicalize, like the message bag).
        eocc = struct["eTerm"] > 0
        lead_lut = xp.asarray(p)
        out.update({
            "allLogs": struct["allLogs"],
            "vLog": struct["vLog"][inv_idx, :][:, inv_idx],
            "eTerm": struct["eTerm"],
            "eLeader": xp.where(eocc, lead_lut[struct["eLeader"]],
                                struct["eLeader"]),
            "eLog": struct["eLog"],
            "eVotes": xp.where(eocc, bitperm(struct["eVotes"]),
                               struct["eVotes"]),
            "eVLog": struct["eVLog"][:, inv_idx],
        })
    return out


def _server_luts(bounds: Bounds) -> tuple:
    """Stacked lookup tables for every server permutation — the data that
    lets ONE compiled transform apply any group element (build_orbit_fp):
    ``inv_idx [P, n]`` row gathers, ``vf_map [P, n+1]`` votedFor relabel,
    ``bit_lut [P, 2^n]`` vote-bitmask permutation, ``p_lut [P, 16]``
    message src/dst relabel (4-bit fields)."""
    ps = permutations(bounds)
    n = bounds.n_servers
    P = len(ps)
    inv_idx = np.empty((P, n), np.int32)
    vf_map = np.empty((P, n + 1), np.int32)
    bit_lut = np.empty((P, 1 << n), np.int32)
    p_lut = np.zeros((P, 16), np.int32)
    masks = np.arange(1 << n, dtype=np.int64)
    for i, p in enumerate(ps):
        inv_idx[i] = [p.index(k) for k in range(n)]
        vf_map[i] = (0,) + tuple(p[j] + 1 for j in range(n))
        bm = np.zeros((1 << n,), np.int64)
        for j in range(n):
            bm |= ((masks >> j) & 1) << p[j]
        bit_lut[i] = bm
        p_lut[i, :n] = p
    return inv_idx, vf_map, bit_lut, p_lut


def _value_luts(bounds: Bounds, faithful: bool) -> dict:
    """Stacked lookup tables per value permutation (build_orbit_fp):
    ``vlut [Q, V+1]`` logVal relabel, ``e_lut [Q, 2^e_w]`` message
    entry-value field, and in faithful mode the log-rank maps."""
    qs = value_permutations(bounds)
    V = bounds.n_values
    e_sh, e_w = mb._LO_FIELDS["e"]
    vlut = np.zeros((len(qs), V + 1), np.int32)
    e_lut = np.zeros((len(qs), 1 << e_w), np.int32)
    for i, q in enumerate(qs):
        vlut[i] = (0,) + tuple(q[v - 1] + 1 for v in range(1, V + 1))
        e_lut[i, :V + 1] = vlut[i]
    out = {"vlut": vlut, "e_lut": e_lut}
    if faithful:
        rmaps = np.stack(_rank_maps(bounds))             # [Q, U]
        U = rmaps.shape[1]
        g_sh, g_w = mb._LO_FIELDS["g"]
        out["rmap"] = rmaps
        out["rlut1"] = np.concatenate(
            [np.zeros((len(qs), 1), np.int32), rmaps + 1], axis=1)
        out["g_lut"] = np.concatenate(
            [rmaps, np.zeros((len(qs), (1 << g_w) - U), np.int32)], axis=1)
    return out


def _permute_struct_batch(struct: dict, inv, vf_map, bit_lut, p_lut, xp):
    """``permute_struct`` over a leading batch axis, with the permutation
    given as traced LUT rows (same arithmetic, same bits — the gathers
    read precomputed tables instead of Python-side tuples)."""
    def rows(a):
        return xp.take(a, inv, axis=1)

    s_sh, s_w = mb._HI_FIELDS["src"]
    d_sh, d_w = mb._HI_FIELDS["dst"]
    keep = ~(((1 << s_w) - 1) << s_sh | ((1 << d_w) - 1) << d_sh)
    hi = struct["msgHi"]
    occupied = struct["msgCount"] > 0
    new_hi = (hi & keep) \
        | (p_lut[(hi >> s_sh) & ((1 << s_w) - 1)] << s_sh) \
        | (p_lut[(hi >> d_sh) & ((1 << d_w) - 1)] << d_sh)
    new_hi = xp.where(occupied, new_hi, hi)

    out = {
        "role": rows(struct["role"]),
        "term": rows(struct["term"]),
        "votedFor": vf_map[rows(struct["votedFor"])],
        "commitIndex": rows(struct["commitIndex"]),
        "logLen": rows(struct["logLen"]),
        "logTerm": rows(struct["logTerm"]),
        "logVal": rows(struct["logVal"]),
        "vResp": bit_lut[rows(struct["vResp"])],
        "vGrant": bit_lut[rows(struct["vGrant"])],
        "nextIndex": xp.take(rows(struct["nextIndex"]), inv, axis=2),
        "matchIndex": xp.take(rows(struct["matchIndex"]), inv, axis=2),
        "msgHi": new_hi,
        "msgLo": struct["msgLo"],
        "msgCount": struct["msgCount"],
    }
    if "eTerm" in struct:
        eocc = struct["eTerm"] > 0
        out.update({
            "allLogs": struct["allLogs"],
            "vLog": xp.take(rows(struct["vLog"]), inv, axis=2),
            "eTerm": struct["eTerm"],
            "eLeader": xp.where(eocc, p_lut[struct["eLeader"]],
                                struct["eLeader"]),
            "eLog": struct["eLog"],
            "eVotes": xp.where(eocc, bit_lut[struct["eVotes"]],
                               struct["eVotes"]),
            "eVLog": xp.take(struct["eVLog"], inv, axis=2),
        })
    return out


def _permute_values_batch(struct: dict, luts: dict, qi, bounds: Bounds, xp):
    """``permute_values`` over a leading batch axis with traced LUT rows."""
    vlut = luts["vlut"][qi]
    e_lut = luts["e_lut"][qi]
    e_sh, e_w = mb._LO_FIELDS["e"]
    lo = struct["msgLo"]
    out = dict(struct)
    out["logVal"] = vlut[struct["logVal"]]
    new_lo = (lo & ~(((1 << e_w) - 1) << e_sh)) \
        | (e_lut[(lo >> e_sh) & ((1 << e_w) - 1)] << e_sh)
    if "allLogs" in struct:
        rmap = luts["rmap"][qi]
        rlut1 = luts["rlut1"][qi]
        g_lut = luts["g_lut"][qi]
        U = int(rmap.shape[0])
        out["vLog"] = rlut1[struct["vLog"]]
        out["eLog"] = rmap[struct["eLog"]]
        out["eVLog"] = rlut1[struct["eVLog"]]
        g_sh, g_w = mb._LO_FIELDS["g"]
        new_lo = (new_lo & ~(((1 << g_w) - 1) << g_sh)) \
            | (g_lut[(new_lo >> g_sh) & ((1 << g_w) - 1)] << g_sh)
        # allLogs bit-permute, batched (same sum-as-OR trick as
        # permute_values; sign bit handled separately — no x64 under jit)
        rs = np.arange(U)
        Wa = struct["allLogs"].shape[1]
        bits = (struct["allLogs"][:, rs // 32] >> (rs % 32)) & 1   # [N, U]
        in_word = (rmap[None, :] // 32) == xp.arange(Wa)[:, None]  # [Wa, U]
        tb = rmap % 32                                             # [U]
        low = xp.where(
            in_word[None] & (tb < 31)[None, None] & (bits[:, None, :] > 0),
            xp.asarray(1, xp.int32) << tb, 0).sum(axis=2)
        top = (in_word[None] & (tb == 31)[None, None]
               & (bits[:, None, :] > 0)).any(axis=2)
        out["allLogs"] = (low.astype(xp.int32)
                          | xp.where(top, xp.asarray(-2**31, xp.int32), 0))
    occupied = struct["msgCount"] > 0
    out["msgLo"] = xp.where(occupied, new_lo, struct["msgLo"])
    return out


def build_orbit_fp(bounds: Bounds, axes: tuple, consts, faithful: bool):
    """Batched orbit-minimal fingerprints: ``struct[N, ...] -> (hi, lo)[N]``.

    Bit-identical to :func:`orbit_fingerprint` (same permute/canonicalize/
    pack/fingerprint arithmetic; the (hi, lo) lexicographic min is
    order-independent) but compiled as ONE transform iterated by
    ``lax.scan`` over the |G| = n!·V! group elements, instead of |G|
    unrolled copies of the pipeline.  The round-1 unrolled graph at five
    servers (120 copies) crashed compiles at chunk 2048 and capped the
    elect5 run at ~3k orbits/s; the scan keeps the program size constant
    in |G| so large chunks compile and the VPU sees one tight loop.
    """
    import jax
    import jax.numpy as jnp

    sluts = tuple(jnp.asarray(a) for a in _server_luts(bounds)) \
        if "Server" in axes else None
    vluts = {k: jnp.asarray(v)
             for k, v in _value_luts(bounds, faithful).items()} \
        if "Value" in axes else None
    P = len(permutations(bounds)) if "Server" in axes else 1
    Q = len(value_permutations(bounds)) if "Value" in axes else 1

    def orbit_fp(struct):
        N = struct["role"].shape[0]

        def body(best, k):
            pi, qi = k // Q, k % Q
            t = struct
            if sluts is not None:
                inv_idx, vf_map, bit_lut, p_lut = sluts
                t = _permute_struct_batch(t, inv_idx[pi], vf_map[pi],
                                          bit_lut[pi], p_lut[pi], jnp)
            if vluts is not None:
                t = _permute_values_batch(t, vluts, qi, bounds, jnp)
            packed = jax.vmap(
                lambda s: st.pack(st.canonicalize(s, jnp), jnp))(t)
            hi, lo = fpr.fingerprint(packed, consts, jnp)
            bh, bl = best
            take = (hi < bh) | ((hi == bh) & (lo < bl))
            return (jnp.where(take, hi, bh), jnp.where(take, lo, bl)), None

        # derive the +inf init from the input so it inherits the input's
        # varying manual axes — a constant-built carry breaks the scan
        # type match when this runs inside shard_map (CP lane sharding)
        top = jnp.zeros_like(struct["role"][:, 0]).astype(jnp.uint32) \
            | jnp.uint32(0xFFFFFFFF)
        init = (top, top)
        (bh, bl), _ = jax.lax.scan(body, init,
                                   jnp.arange(P * Q, dtype=jnp.int32))
        return bh, bl

    return orbit_fp


def orbit_fingerprint(struct: dict, bounds: Bounds, consts, xp,
                      axes: tuple = ("Server",)):
    """Orbit-minimal (hi, lo) fingerprint of one canonical state struct,
    minimized over the permutation group of the named ``axes``."""
    sperms = permutations(bounds) if "Server" in axes \
        else (tuple(range(bounds.n_servers)),)
    vqs = range(len(value_permutations(bounds))) if "Value" in axes else (0,)
    best_hi = best_lo = None
    for p in sperms:
        ps = permute_struct(struct, p, bounds, xp)
        for qi in vqs:
            t = permute_values(ps, qi, bounds, xp) if "Value" in axes else ps
            t = st.canonicalize(t, xp)
            hi, lo = fpr.fingerprint(st.pack(t, xp), consts, xp)
            if best_hi is None:
                best_hi, best_lo = hi, lo
            else:
                take = (hi < best_hi) | ((hi == best_hi) & (lo < best_lo))
                best_hi = xp.where(take, hi, best_hi)
                best_lo = xp.where(take, lo, best_lo)
    return best_hi, best_lo


@functools.lru_cache(maxsize=None)
def _host_consts(width: int) -> np.ndarray:
    # one PCG64 spin-up per width, not per call (refbfs keys every
    # transition through here under symmetry)
    return fpr.lane_constants(width)


def py_orbit_fingerprint(s, bounds: Bounds,
                         axes: tuple = ("Server",)) -> tuple:
    """Oracle-side orbit key of a PyState — same arithmetic, NumPy."""
    from raft_tla_tpu.models import interp

    lay = st.Layout.of(bounds)
    struct = st.unpack(interp.to_vec(s, bounds), lay, np)
    hi, lo = orbit_fingerprint(struct, bounds, _host_consts(lay.width), np,
                               axes)
    return int(hi), int(lo)


def init_fingerprint(config, init_py, init_vec) -> tuple:
    """The dedup key of the initial state, view-folded and orbit-reduced
    per the config — one definition for every engine's table seeding."""
    if getattr(config, "view", None):
        from raft_tla_tpu.models import interp, views

        viewed = views.py_view(config.view)(init_py, config.bounds)
        if viewed is not init_py:
            init_py = viewed
            init_vec = interp.to_vec(viewed, config.bounds)
    if config.symmetry:
        return py_orbit_fingerprint(init_py, config.bounds, config.symmetry)
    consts = _host_consts(init_vec.shape[-1])
    hi, lo = fpr.fingerprint(init_vec.astype(np.int32), consts, np)
    return int(hi), int(lo)
