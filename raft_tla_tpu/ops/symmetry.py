"""Symmetry reduction over the Server model values (TLC SYMMETRY stanza).

The reference binds ``Server`` to model values (``raft.cfg:6``), which TLC
can quotient by permutation symmetry (its classic state-space reduction —
the spec never distinguishes individual servers).  This module implements
the same reduction for the tensor checker, the TPU way:

The dedup key of a state becomes its **orbit-minimal fingerprint**:
``min over all permutations π of fp(canonicalize(π(s)))``, where ``π(s)``
renumbers every server-indexed axis and server-valued field.  The min is
orbit-invariant, so two states equal up to server renaming share one key
and one store row — the reachable count becomes the orbit count, exactly
TLC's SYMMETRY semantics (including its property: the stored witness per
orbit is whichever member was discovered first).  On device this is |π|
static transforms batched over the candidate block — pure gathers, bit
arithmetic, and the existing canonicalize/pack/fingerprint pipeline, fused
by XLA; no extra passes over HBM.

Permuting one state under ``p`` (new index of old server j is ``p[j]``):

- per-server axes (role, term, votedFor, commitIndex, logLen, log*,
  vResp, vGrant): rows reordered by the inverse permutation;
- server-valued *contents*: ``votedFor`` ids map through ``p`` (0 = Nil
  fixed); vote bitmasks move bit j to bit ``p[j]``;
- ``nextIndex``/``matchIndex`` reorder both axes;
- message records rewrite their ``src``/``dst`` fields through ``p``
  (occupied slots only — empty slots stay all-zero), then the bag
  re-canonicalizes (sort order may change under renaming).

``Value`` symmetry (TLC's ``Permutations(Value)``) composes: values have no
distinguished elements in the spec (they only enter through ``ClientRequest``
and flow inertly through logs and ``mentries``), so the orbit key may also
minimize over value permutations.  Permuting values remaps ``logVal``
contents, the message entry-value field, and — in faithful mode — every
log-universe rank (``ops/loguniv.py``) through a precomputed static
rank-permutation table (allLogs bitmasks permute bitwise).  The full orbit
pass is then ``n! * V!`` static transforms.
"""

from __future__ import annotations

import functools
import itertools
import math

import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.ops import state as st

MAX_SYM_SERVERS = 6      # 720 permutations; beyond this the orbit pass dwarfs the step


def permutations(bounds: Bounds) -> tuple:
    if bounds.n_servers > MAX_SYM_SERVERS:
        raise ValueError(
            f"Server symmetry supports at most {MAX_SYM_SERVERS} servers "
            f"(got {bounds.n_servers}: {math.factorial(bounds.n_servers)}"
            " permutations)")
    return tuple(itertools.permutations(range(bounds.n_servers)))


MAX_SYM_VALUES = 5       # 120 value permutations


def value_permutations(bounds: Bounds) -> tuple:
    if bounds.n_values > MAX_SYM_VALUES:
        raise ValueError(
            f"Value symmetry supports at most {MAX_SYM_VALUES} values "
            f"(got {bounds.n_values})")
    return tuple(itertools.permutations(range(bounds.n_values)))


@functools.lru_cache(maxsize=None)
def _rank_maps(bounds: Bounds) -> tuple:
    """Per value-permutation q: int32[U] mapping each log rank to the rank
    of the value-permuted log (faithful mode; identity-permutation first)."""
    from raft_tla_tpu.ops.loguniv import LogUniverse
    uni = LogUniverse.of(bounds)
    maps = []
    for q in value_permutations(bounds):
        m = np.empty((uni.size,), np.int32)
        for r in range(uni.size):
            log = uni.tuple_of_id(r)
            m[r] = uni.id_of_tuple(tuple((t, q[v - 1] + 1) for t, v in log))
        maps.append(m)
    return tuple(maps)


def permute_values(struct: dict, qi: int, bounds: Bounds, xp) -> dict:
    """Apply the ``qi``-th value permutation to one state struct.

    Remaps ``logVal`` contents (0 = padding fixed), the message entry-value
    field ``e`` (zero for every non-AppendEntriesRequest record, and the
    LUT fixes 0), and in faithful mode every log rank through the static
    rank table — ``allLogs`` permutes bitwise.
    """
    q = value_permutations(bounds)[qi]
    V = bounds.n_values
    vlut = xp.asarray((0,) + tuple(q[v - 1] + 1 for v in range(1, V + 1)))
    out = dict(struct)
    out["logVal"] = vlut[struct["logVal"]]
    e_sh, e_w = mb._LO_FIELDS["e"]
    lo = struct["msgLo"]
    e_lut = xp.asarray((0,) + tuple(q[v - 1] + 1 for v in range(1, V + 1))
                       + tuple(0 for _ in range((1 << e_w) - V - 1)))
    new_lo = (lo & ~(((1 << e_w) - 1) << e_sh)) \
        | (e_lut[(lo >> e_sh) & ((1 << e_w) - 1)] << e_sh)
    if "allLogs" in struct:
        rmap = xp.asarray(_rank_maps(bounds)[qi])
        U = int(rmap.shape[0])
        rlut1 = xp.concatenate([xp.zeros((1,), xp.int32),
                                rmap.astype(xp.int32) + 1])  # rank+1 form
        out["vLog"] = rlut1[struct["vLog"]]
        out["eLog"] = rmap[struct["eLog"]]
        out["eVLog"] = rlut1[struct["eVLog"]]
        # mlog rank rides the g field of the lo word
        g_sh, g_w = mb._LO_FIELDS["g"]
        g_lut = xp.concatenate(
            [rmap.astype(xp.int32),
             xp.zeros(((1 << g_w) - U,), xp.int32)])
        new_lo = (new_lo & ~(((1 << g_w) - 1) << g_sh)) \
            | (g_lut[(new_lo >> g_sh) & ((1 << g_w) - 1)] << g_sh)
        # allLogs: bit r of the old mask becomes bit rmap[r] of the new
        # one.  Contributions within a word are distinct bit positions, so
        # an integer sum IS the bitwise OR.  Bits 0..30 sum safely in
        # int32; the sign bit is OR'd in separately (no x64 under jit).
        rs = xp.arange(U)
        bits = ((struct["allLogs"][rs // 32] >> (rs % 32)) & 1)
        Wa = struct["allLogs"].shape[0]
        in_word = (rmap[None, :] // 32) == xp.arange(Wa)[:, None]  # [Wa, U]
        tb = rmap[None, :] % 32
        low = xp.where(in_word & (tb < 31) & (bits[None, :] > 0),
                       xp.asarray(1, xp.int32) << tb, 0).sum(axis=1)
        top = (in_word & (tb == 31) & (bits[None, :] > 0)).any(axis=1)
        out["allLogs"] = (low.astype(xp.int32)
                          | xp.where(top, xp.asarray(-2**31, xp.int32), 0))
    occupied = struct["msgCount"] > 0
    out["msgLo"] = xp.where(occupied, new_lo, struct["msgLo"])
    return out


def permute_struct(struct: dict, p: tuple, bounds: Bounds, xp) -> dict:
    """Apply server permutation ``p`` to one state struct (then the caller
    must re-canonicalize the message bag)."""
    n = bounds.n_servers
    inv = tuple(p.index(k) for k in range(n))      # new row k = old row inv[k]
    inv_idx = xp.asarray(inv)
    # votedFor lookup: 0 stays Nil, id j+1 -> p[j]+1
    vf_map = xp.asarray((0,) + tuple(p[j] + 1 for j in range(n)))

    def rows(a):
        return a[inv_idx, ...]

    def bitperm(mask):
        out = xp.zeros_like(mask)
        for j in range(n):
            out = out | (((mask >> j) & 1) << p[j])
        return out

    # src/dst fields of occupied message slots, via the packed hi word
    s_sh, s_w = mb._HI_FIELDS["src"]
    d_sh, d_w = mb._HI_FIELDS["dst"]
    keep = ~(((1 << s_w) - 1) << s_sh | ((1 << d_w) - 1) << d_sh)
    hi = struct["msgHi"]
    occupied = struct["msgCount"] > 0
    p_lut = xp.asarray(p + tuple(0 for _ in range(16 - n)))  # 4-bit fields
    new_hi = (hi & keep) | (p_lut[(hi >> s_sh) & ((1 << s_w) - 1)] << s_sh) \
        | (p_lut[(hi >> d_sh) & ((1 << d_w) - 1)] << d_sh)
    new_hi = xp.where(occupied, new_hi, hi)

    out = {
        "role": rows(struct["role"]),
        "term": rows(struct["term"]),
        "votedFor": vf_map[rows(struct["votedFor"])],
        "commitIndex": rows(struct["commitIndex"]),
        "logLen": rows(struct["logLen"]),
        "logTerm": rows(struct["logTerm"]),
        "logVal": rows(struct["logVal"]),
        "vResp": bitperm(rows(struct["vResp"])),
        "vGrant": bitperm(rows(struct["vGrant"])),
        "nextIndex": struct["nextIndex"][inv_idx, :][:, inv_idx],
        "matchIndex": struct["matchIndex"][inv_idx, :][:, inv_idx],
        "msgHi": new_hi,
        "msgLo": struct["msgLo"],
        "msgCount": struct["msgCount"],
    }
    if "eTerm" in struct:
        # Faithful-mode history (ops/state.py HISTORY_FIELDS).  Log ranks
        # contain no server ids, so allLogs/eLog/mlog are fixed points;
        # voterLog permutes both axes like nextIndex, election records
        # remap eleader/evotes/evoterLog (slot re-sort happens in the
        # caller's canonicalize, like the message bag).
        eocc = struct["eTerm"] > 0
        lead_lut = xp.asarray(p)
        out.update({
            "allLogs": struct["allLogs"],
            "vLog": struct["vLog"][inv_idx, :][:, inv_idx],
            "eTerm": struct["eTerm"],
            "eLeader": xp.where(eocc, lead_lut[struct["eLeader"]],
                                struct["eLeader"]),
            "eLog": struct["eLog"],
            "eVotes": xp.where(eocc, bitperm(struct["eVotes"]),
                               struct["eVotes"]),
            "eVLog": struct["eVLog"][:, inv_idx],
        })
    return out


def _server_luts(bounds: Bounds) -> tuple:
    """Stacked lookup tables for every server permutation — the data that
    lets ONE compiled transform apply any group element (build_orbit_fp):
    ``inv_idx [P, n]`` row gathers, ``vf_map [P, n+1]`` votedFor relabel,
    ``bit_lut [P, 2^n]`` vote-bitmask permutation, ``p_lut [P, 16]``
    message src/dst relabel (4-bit fields)."""
    ps = permutations(bounds)
    n = bounds.n_servers
    P = len(ps)
    inv_idx = np.empty((P, n), np.int32)
    vf_map = np.empty((P, n + 1), np.int32)
    bit_lut = np.empty((P, 1 << n), np.int32)
    p_lut = np.zeros((P, 16), np.int32)
    masks = np.arange(1 << n, dtype=np.int64)
    for i, p in enumerate(ps):
        inv_idx[i] = [p.index(k) for k in range(n)]
        vf_map[i] = (0,) + tuple(p[j] + 1 for j in range(n))
        bm = np.zeros((1 << n,), np.int64)
        for j in range(n):
            bm |= ((masks >> j) & 1) << p[j]
        bit_lut[i] = bm
        p_lut[i, :n] = p
    return inv_idx, vf_map, bit_lut, p_lut


def _value_luts(bounds: Bounds, faithful: bool) -> dict:
    """Stacked lookup tables per value permutation (build_orbit_fp):
    ``vlut [Q, V+1]`` logVal relabel, ``e_lut [Q, 2^e_w]`` message
    entry-value field, and in faithful mode the log-rank maps."""
    qs = value_permutations(bounds)
    V = bounds.n_values
    e_sh, e_w = mb._LO_FIELDS["e"]
    vlut = np.zeros((len(qs), V + 1), np.int32)
    e_lut = np.zeros((len(qs), 1 << e_w), np.int32)
    for i, q in enumerate(qs):
        vlut[i] = (0,) + tuple(q[v - 1] + 1 for v in range(1, V + 1))
        e_lut[i, :V + 1] = vlut[i]
    out = {"vlut": vlut, "e_lut": e_lut}
    if faithful:
        rmaps = np.stack(_rank_maps(bounds))             # [Q, U]
        U = rmaps.shape[1]
        g_sh, g_w = mb._LO_FIELDS["g"]
        out["rmap"] = rmaps
        out["rlut1"] = np.concatenate(
            [np.zeros((len(qs), 1), np.int32), rmaps + 1], axis=1)
        out["g_lut"] = np.concatenate(
            [rmaps, np.zeros((len(qs), (1 << g_w) - U), np.int32)], axis=1)
    return out


# -- signature-refinement pruning (sig-prune) --------------------------------
#
# The orbit scan pays |G| = n!*V! pipeline iterations per state even when
# the state has symmetry left over — e.g. two followers with equal terms,
# logs and relations, which every checker's initial states and election
# churn produce in bulk.  For such states many group elements map the
# state to the SAME orbit member, and recomputing a duplicate member's
# fingerprint cannot change the min.  Sig-prune removes exactly those
# provable duplicates and nothing else, so the min — the dedup key every
# checkpoint and parity guarantee rests on — is bit-identical:
#
# 1. **Exact interchangeability classes.**  Servers a, b are
#    interchangeable iff the transposition (a b) maps the state to itself
#    (compared as packed canonical rows — exact equality, no hashing).
#    Stab(s) is a group, so the relation is transitive and partitions the
#    servers; the generated subgroup H = ∏ Sym(class) stabilizes s.
# 2. **Coset representatives.**  π and π∘σ produce the same permuted
#    state for σ ∈ H, so the scan only needs one element per left coset
#    πH: keep π iff π is increasing on every class (exactly one member
#    per coset satisfies this).  Every distinct orbit member is still
#    scanned — the pruned min is the full min, bit for bit.  Value
#    permutations factor the same way; kept(π, q) = kept_s(π) & kept_v(q).
# 3. **Signature prefilter.**  A cheap per-server invariant signature
#    (role, term class, log-content hash, votedFor class, vote popcounts)
#    is a NECESSARY condition for interchangeability, so a chunk whose
#    states nowhere repeat a signature skips the exact transposition
#    probes wholesale (lax.cond, jit-stable shapes).
# 4. **Static-slot cond ladder.**  The kept count is data-dependent; like
#    the prescan rungs, the kept scan runs at the smallest static slot
#    count |G|/d (d in _SIGPRUNE_RUNGS) that fits the chunk's max kept
#    count, falling back to the unpruned scan (shared-LUT body) when any
#    state in the chunk keeps the whole group.  Pad slots re-scan the
#    identity element (always kept) — a real orbit member, harmless to
#    the min.
#
# Note the one-sided failure mode this construction rules out: pruning by
# signature classes ALONE (keep only partition-preserving permutations)
# is unsound — for a state with all-distinct signatures it would scan
# only the identity and miss every other orbit member.  The exact probe
# step is what makes the mask a duplicate-eliminator instead of an
# orbit-truncator; tests/test_sigprune.py asserts both directions.
_SIGPRUNE_RUNGS = (8, 4, 2)      # divisors of |G|, tried smallest-slot-first


@functools.lru_cache(maxsize=None)
def _transposition_pairs(bounds: Bounds) -> tuple:
    """Static probe table: ``(a, b, perm_index)`` for every server pair
    a < b, where ``perm_index`` locates the transposition (a b) in
    :func:`permutations` order."""
    ps = permutations(bounds)
    n = bounds.n_servers
    out = []
    for a in range(n):
        for b in range(a + 1, n):
            t = list(range(n))
            t[a], t[b] = b, a
            out.append((a, b, ps.index(tuple(t))))
    return tuple(out)


@functools.lru_cache(maxsize=None)
def _value_transposition_pairs(bounds: Bounds) -> tuple:
    """Value-axis analog of :func:`_transposition_pairs`."""
    qs = value_permutations(bounds)
    V = bounds.n_values
    out = []
    for a in range(V):
        for b in range(a + 1, V):
            t = list(range(V))
            t[a], t[b] = b, a
            out.append((a, b, qs.index(tuple(t))))
    return tuple(out)


def _pair_less_lut(perms: tuple, pairs: tuple) -> np.ndarray:
    """bool[P, n_pairs]: permutation p is increasing on pair (a, b),
    i.e. ``p[a] < p[b]`` — the coset-representative condition per pair."""
    arr = np.asarray(perms, np.int32)
    return np.stack([arr[:, a] < arr[:, b] for (a, b, _) in pairs], axis=1)


def _server_sig(struct: dict, xp):
    """Cheap per-server invariant signature ``[..., n] uint32``.

    Equal signatures are NECESSARY for two servers to be exactly
    interchangeable (every hashed field moves with its server under a
    transposition; popcounts and the votedFor nil/self/other class are
    renaming-invariant), so distinct signatures let the sig-prune path
    skip the exact probe for that pair chunk-wide.  Not sufficient —
    relational fields (nextIndex columns, vote bit positions, message
    endpoints) are deliberately out; the exact probe certifies those."""
    u = xp.uint32

    def mix(h, x):
        return (h ^ x.astype(xp.uint32)) * u(0x9E3779B1)

    def popcount(x):
        x = x - ((x >> 1) & 0x55555555)
        x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
        x = (x + (x >> 4)) & 0x0F0F0F0F
        return (x * 0x01010101) >> 24

    n = struct["role"].shape[-1]
    vf = struct["votedFor"]
    self_id = xp.arange(n) + 1
    vf_cls = xp.where(vf == 0, 0, xp.where(vf == self_id, 1, 2))
    h = xp.zeros_like(struct["role"]).astype(xp.uint32) + u(0x811C9DC5)
    for f in ("role", "term", "commitIndex", "logLen"):
        h = mix(h, struct[f])
    h = mix(h, vf_cls)
    h = mix(h, popcount(struct["vResp"]))
    h = mix(h, popcount(struct["vGrant"]))
    lt, lv = struct["logTerm"], struct["logVal"]
    for c in range(lt.shape[-1]):
        h = mix(h, lt[..., c])
        h = mix(h, lv[..., c])
    return h


def _permute_struct_traced(struct: dict, inv, vf_map, bit_lut, p_lut, xp):
    """``permute_struct`` for ONE state with the permutation as traced LUT
    rows — the sig-prune kept scan vmaps this over per-state permutation
    indices (each state walks its own kept-coset list, so the LUT row
    varies along the batch axis)."""
    def rows(a):
        return xp.take(a, inv, axis=0)

    s_sh, s_w = mb._HI_FIELDS["src"]
    d_sh, d_w = mb._HI_FIELDS["dst"]
    keep = ~(((1 << s_w) - 1) << s_sh | ((1 << d_w) - 1) << d_sh)
    hi = struct["msgHi"]
    occupied = struct["msgCount"] > 0
    new_hi = (hi & keep) \
        | (p_lut[(hi >> s_sh) & ((1 << s_w) - 1)] << s_sh) \
        | (p_lut[(hi >> d_sh) & ((1 << d_w) - 1)] << d_sh)
    new_hi = xp.where(occupied, new_hi, hi)

    out = {
        "role": rows(struct["role"]),
        "term": rows(struct["term"]),
        "votedFor": vf_map[rows(struct["votedFor"])],
        "commitIndex": rows(struct["commitIndex"]),
        "logLen": rows(struct["logLen"]),
        "logTerm": rows(struct["logTerm"]),
        "logVal": rows(struct["logVal"]),
        "vResp": bit_lut[rows(struct["vResp"])],
        "vGrant": bit_lut[rows(struct["vGrant"])],
        "nextIndex": xp.take(rows(struct["nextIndex"]), inv, axis=1),
        "matchIndex": xp.take(rows(struct["matchIndex"]), inv, axis=1),
        "msgHi": new_hi,
        "msgLo": struct["msgLo"],
        "msgCount": struct["msgCount"],
    }
    if "eTerm" in struct:
        eocc = struct["eTerm"] > 0
        out.update({
            "allLogs": struct["allLogs"],
            "vLog": xp.take(rows(struct["vLog"]), inv, axis=1),
            "eTerm": struct["eTerm"],
            "eLeader": xp.where(eocc, p_lut[struct["eLeader"]],
                                struct["eLeader"]),
            "eLog": struct["eLog"],
            "eVotes": xp.where(eocc, bit_lut[struct["eVotes"]],
                               struct["eVotes"]),
            "eVLog": xp.take(struct["eVLog"], inv, axis=1),
        })
    return out


def _permute_values_traced(struct: dict, luts: dict, bounds: Bounds, xp):
    """``permute_values`` for ONE state with the value permutation as
    traced LUT rows (sig-prune kept scan; see _permute_struct_traced)."""
    vlut = luts["vlut"]
    e_lut = luts["e_lut"]
    e_sh, e_w = mb._LO_FIELDS["e"]
    lo = struct["msgLo"]
    out = dict(struct)
    out["logVal"] = vlut[struct["logVal"]]
    new_lo = (lo & ~(((1 << e_w) - 1) << e_sh)) \
        | (e_lut[(lo >> e_sh) & ((1 << e_w) - 1)] << e_sh)
    if "allLogs" in struct:
        rmap = luts["rmap"]
        rlut1 = luts["rlut1"]
        g_lut = luts["g_lut"]
        U = int(rmap.shape[0])
        out["vLog"] = rlut1[struct["vLog"]]
        out["eLog"] = rmap[struct["eLog"]]
        out["eVLog"] = rlut1[struct["eVLog"]]
        g_sh, g_w = mb._LO_FIELDS["g"]
        new_lo = (new_lo & ~(((1 << g_w) - 1) << g_sh)) \
            | (g_lut[(new_lo >> g_sh) & ((1 << g_w) - 1)] << g_sh)
        rs = xp.arange(U)
        bits = ((struct["allLogs"][rs // 32] >> (rs % 32)) & 1)
        Wa = struct["allLogs"].shape[0]
        in_word = (rmap[None, :] // 32) == xp.arange(Wa)[:, None]  # [Wa, U]
        tb = rmap[None, :] % 32
        low = xp.where(in_word & (tb < 31) & (bits[None, :] > 0),
                       xp.asarray(1, xp.int32) << tb, 0).sum(axis=1)
        top = (in_word & (tb == 31) & (bits[None, :] > 0)).any(axis=1)
        out["allLogs"] = (low.astype(xp.int32)
                          | xp.where(top, xp.asarray(-2**31, xp.int32), 0))
    occupied = struct["msgCount"] > 0
    out["msgLo"] = xp.where(occupied, new_lo, struct["msgLo"])
    return out


def _permute_struct_batch(struct: dict, inv, vf_map, bit_lut, p_lut, xp):
    """``permute_struct`` over a leading batch axis, with the permutation
    given as traced LUT rows (same arithmetic, same bits — the gathers
    read precomputed tables instead of Python-side tuples)."""
    def rows(a):
        return xp.take(a, inv, axis=1)

    s_sh, s_w = mb._HI_FIELDS["src"]
    d_sh, d_w = mb._HI_FIELDS["dst"]
    keep = ~(((1 << s_w) - 1) << s_sh | ((1 << d_w) - 1) << d_sh)
    hi = struct["msgHi"]
    occupied = struct["msgCount"] > 0
    new_hi = (hi & keep) \
        | (p_lut[(hi >> s_sh) & ((1 << s_w) - 1)] << s_sh) \
        | (p_lut[(hi >> d_sh) & ((1 << d_w) - 1)] << d_sh)
    new_hi = xp.where(occupied, new_hi, hi)

    out = {
        "role": rows(struct["role"]),
        "term": rows(struct["term"]),
        "votedFor": vf_map[rows(struct["votedFor"])],
        "commitIndex": rows(struct["commitIndex"]),
        "logLen": rows(struct["logLen"]),
        "logTerm": rows(struct["logTerm"]),
        "logVal": rows(struct["logVal"]),
        "vResp": bit_lut[rows(struct["vResp"])],
        "vGrant": bit_lut[rows(struct["vGrant"])],
        "nextIndex": xp.take(rows(struct["nextIndex"]), inv, axis=2),
        "matchIndex": xp.take(rows(struct["matchIndex"]), inv, axis=2),
        "msgHi": new_hi,
        "msgLo": struct["msgLo"],
        "msgCount": struct["msgCount"],
    }
    if "eTerm" in struct:
        eocc = struct["eTerm"] > 0
        out.update({
            "allLogs": struct["allLogs"],
            "vLog": xp.take(rows(struct["vLog"]), inv, axis=2),
            "eTerm": struct["eTerm"],
            "eLeader": xp.where(eocc, p_lut[struct["eLeader"]],
                                struct["eLeader"]),
            "eLog": struct["eLog"],
            "eVotes": xp.where(eocc, bit_lut[struct["eVotes"]],
                               struct["eVotes"]),
            "eVLog": xp.take(struct["eVLog"], inv, axis=2),
        })
    return out


def _permute_values_batch(struct: dict, luts: dict, qi, bounds: Bounds, xp):
    """``permute_values`` over a leading batch axis with traced LUT rows."""
    vlut = luts["vlut"][qi]
    e_lut = luts["e_lut"][qi]
    e_sh, e_w = mb._LO_FIELDS["e"]
    lo = struct["msgLo"]
    out = dict(struct)
    out["logVal"] = vlut[struct["logVal"]]
    new_lo = (lo & ~(((1 << e_w) - 1) << e_sh)) \
        | (e_lut[(lo >> e_sh) & ((1 << e_w) - 1)] << e_sh)
    if "allLogs" in struct:
        rmap = luts["rmap"][qi]
        rlut1 = luts["rlut1"][qi]
        g_lut = luts["g_lut"][qi]
        U = int(rmap.shape[0])
        out["vLog"] = rlut1[struct["vLog"]]
        out["eLog"] = rmap[struct["eLog"]]
        out["eVLog"] = rlut1[struct["eVLog"]]
        g_sh, g_w = mb._LO_FIELDS["g"]
        new_lo = (new_lo & ~(((1 << g_w) - 1) << g_sh)) \
            | (g_lut[(new_lo >> g_sh) & ((1 << g_w) - 1)] << g_sh)
        # allLogs bit-permute, batched (same sum-as-OR trick as
        # permute_values; sign bit handled separately — no x64 under jit)
        rs = np.arange(U)
        Wa = struct["allLogs"].shape[1]
        bits = (struct["allLogs"][:, rs // 32] >> (rs % 32)) & 1   # [N, U]
        in_word = (rmap[None, :] // 32) == xp.arange(Wa)[:, None]  # [Wa, U]
        tb = rmap % 32                                             # [U]
        low = xp.where(
            in_word[None] & (tb < 31)[None, None] & (bits[:, None, :] > 0),
            xp.asarray(1, xp.int32) << tb, 0).sum(axis=2)
        top = (in_word[None] & (tb == 31)[None, None]
               & (bits[:, None, :] > 0)).any(axis=2)
        out["allLogs"] = (low.astype(xp.int32)
                          | xp.where(top, xp.asarray(-2**31, xp.int32), 0))
    occupied = struct["msgCount"] > 0
    out["msgLo"] = xp.where(occupied, new_lo, struct["msgLo"])
    return out


def build_orbit_fp(bounds: Bounds, axes: tuple, consts, faithful: bool,
                   prune: bool = False):
    """Batched orbit-minimal fingerprints: ``struct[N, ...] -> (hi, lo)[N]``.

    Bit-identical to :func:`orbit_fingerprint` (same permute/canonicalize/
    pack/fingerprint arithmetic; the (hi, lo) lexicographic min is
    order-independent) but compiled as ONE transform iterated by
    ``lax.scan`` over the |G| = n!·V! group elements, instead of |G|
    unrolled copies of the pipeline.  The round-1 unrolled graph at five
    servers (120 copies) crashed compiles at chunk 2048 and capped the
    elect5 run at ~3k orbits/s; the scan keeps the program size constant
    in |G| so large chunks compile and the VPU sees one tight loop.

    With ``prune=True`` the scan runs the signature-refinement pruned
    path (see the _SIGPRUNE_RUNGS comment): exact interchangeability
    classes from transposition probes, then a min over one permutation
    per stabilizer coset — still bit-identical, by construction, because
    only provable duplicate orbit members are skipped.  Gated at the
    call sites (ops/kernels._sigprune_enabled); default off.
    """
    import jax
    import jax.numpy as jnp

    sluts = tuple(jnp.asarray(a) for a in _server_luts(bounds)) \
        if "Server" in axes else None
    vluts = {k: jnp.asarray(v)
             for k, v in _value_luts(bounds, faithful).items()} \
        if "Value" in axes else None
    P = len(permutations(bounds)) if "Server" in axes else 1
    Q = len(value_permutations(bounds)) if "Value" in axes else 1

    def orbit_fp(struct):
        N = struct["role"].shape[0]

        def body(best, k):
            pi, qi = k // Q, k % Q
            t = struct
            if sluts is not None:
                inv_idx, vf_map, bit_lut, p_lut = sluts
                t = _permute_struct_batch(t, inv_idx[pi], vf_map[pi],
                                          bit_lut[pi], p_lut[pi], jnp)
            if vluts is not None:
                t = _permute_values_batch(t, vluts, qi, bounds, jnp)
            packed = jax.vmap(
                lambda s: st.pack(st.canonicalize(s, jnp), jnp))(t)
            hi, lo = fpr.fingerprint(packed, consts, jnp)
            bh, bl = best
            take = (hi < bh) | ((hi == bh) & (lo < bl))
            return (jnp.where(take, hi, bh), jnp.where(take, lo, bl)), None

        # derive the +inf init from the input so it inherits the input's
        # varying manual axes — a constant-built carry breaks the scan
        # type match when this runs inside shard_map (CP lane sharding)
        top = jnp.zeros_like(struct["role"][:, 0]).astype(jnp.uint32) \
            | jnp.uint32(0xFFFFFFFF)
        init = (top, top)
        (bh, bl), _ = jax.lax.scan(body, init,
                                   jnp.arange(P * Q, dtype=jnp.int32))
        return bh, bl

    spairs = _transposition_pairs(bounds) if "Server" in axes else ()
    vpairs = _value_transposition_pairs(bounds) if "Value" in axes else ()
    if not prune or (not spairs and not vpairs):
        return orbit_fp

    less_s = jnp.asarray(_pair_less_lut(permutations(bounds), spairs)) \
        if spairs else None                                   # [P, Ps]
    less_v = jnp.asarray(_pair_less_lut(value_permutations(bounds), vpairs)) \
        if vpairs else None                                   # [Q, Pv]
    sprobes = jnp.asarray([(pi, a, b) for (a, b, pi) in spairs], jnp.int32)
    vprobes = jnp.asarray([pi for (_a, _b, pi) in vpairs], jnp.int32)

    def pruned_orbit_fp(struct):
        N = struct["role"].shape[0]
        if sluts is not None:
            inv_idx, vf_map, bit_lut, p_lut = sluts
        canon_pack = jax.vmap(lambda s: st.pack(st.canonicalize(s, jnp), jnp))
        id_row = canon_pack(struct)                           # [N, W]

        def keep_from(eq, less):
            # keep[s, p] <=> no verified-equal pair (a, b) with p[a] > p[b]
            # — small exact-int matmul (counts <= n_pairs, exact in f32)
            bad = jnp.matmul(eq.astype(jnp.float32),
                             (~less).astype(jnp.float32).T)
            return bad < 0.5                                  # [N, P]

        if spairs:
            sig = _server_sig(struct, jnp)                    # [N, n]

            def sbody(carry, row):
                pidx, a, b = row[0], row[1], row[2]

                def probe(_):
                    t = _permute_struct_batch(
                        struct, inv_idx[pidx], vf_map[pidx],
                        bit_lut[pidx], p_lut[pidx], jnp)
                    return jnp.all(canon_pack(t) == id_row, axis=1)

                # signature prefilter: equal sigs are necessary for the
                # exact probe to fire anywhere in the chunk
                cand = jnp.any(jnp.take(sig, a, axis=1)
                               == jnp.take(sig, b, axis=1))
                eq = jax.lax.cond(cand, probe,
                                  lambda _: jnp.zeros((N,), bool), None)
                return carry, eq

            _, eq_sT = jax.lax.scan(sbody, None, sprobes)     # [Ps, N]
            keep_s = keep_from(eq_sT.T, less_s)               # [N, P]
        else:
            keep_s = jnp.ones((N, P), bool)

        if vpairs:
            def vbody(carry, qidx):
                t = _permute_values_batch(struct, vluts, qidx, bounds, jnp)
                return carry, jnp.all(canon_pack(t) == id_row, axis=1)

            _, eq_vT = jax.lax.scan(vbody, None, vprobes)     # [Pv, N]
            keep_v = keep_from(eq_vT.T, less_v)               # [N, Q]
        else:
            keep_v = jnp.ones((N, Q), bool)

        keptf = (keep_s[:, :, None] & keep_v[:, None, :]).reshape(N, P * Q)
        n_kept = jnp.sum(keptf.astype(jnp.int32), axis=1)
        max_kept = jnp.max(n_kept)

        top = jnp.zeros_like(struct["role"][:, 0]).astype(jnp.uint32) \
            | jnp.uint32(0xFFFFFFFF)

        def scan_kept_at(K):
            def run(_):
                # compact each state's kept group-element indices into K
                # static slots (built INSIDE the rung branch: untaken
                # rungs must cost nothing); pad slots stay 0 = identity,
                # which is always kept — re-scanning it is harmless
                pos = jnp.cumsum(keptf.astype(jnp.int32), axis=1) - 1
                slot = jnp.where(keptf & (pos < K), pos, K)
                kidx = jnp.zeros((N, K), jnp.int32).at[
                    jnp.arange(N)[:, None], slot].set(
                    jnp.arange(P * Q, dtype=jnp.int32)[None, :],
                    mode="drop")

                def one(s, pi, qi):
                    t = s
                    if sluts is not None:
                        t = _permute_struct_traced(
                            t, inv_idx[pi], vf_map[pi], bit_lut[pi],
                            p_lut[pi], jnp)
                    if vluts is not None:
                        t = _permute_values_traced(
                            t, {kk: vv[qi] for kk, vv in vluts.items()},
                            bounds, jnp)
                    return st.pack(st.canonicalize(t, jnp), jnp)

                def body(best, j):
                    k = kidx[:, j]
                    pi, qi = k // Q, k % Q
                    packed = jax.vmap(one)(struct, pi, qi)
                    hi, lo = fpr.fingerprint(packed, consts, jnp)
                    bh, bl = best
                    take = (hi < bh) | ((hi == bh) & (lo < bl))
                    return (jnp.where(take, hi, bh),
                            jnp.where(take, lo, bl)), None

                (bh, bl), _ = jax.lax.scan(
                    body, (top, top), jnp.arange(K, dtype=jnp.int32))
                return bh, bl

            return run

        # elif chain inside-out like _orbit_fp_prescan: smallest rung
        # tested first; chunks with any fully-asymmetric state fall back
        # to the unpruned shared-LUT scan (same arithmetic, zero overlay)
        out = lambda _: orbit_fp(struct)
        for div in sorted(_SIGPRUNE_RUNGS):
            K = max(1, (P * Q) // div)
            if K >= P * Q:
                continue
            out = (lambda _, _r=scan_kept_at(K), _o=out, _K=K:
                   jax.lax.cond(max_kept <= _K, _r, _o, None))
        return out(None)

    return pruned_orbit_fp


def orbit_fingerprint(struct: dict, bounds: Bounds, consts, xp,
                      axes: tuple = ("Server",)):
    """Orbit-minimal (hi, lo) fingerprint of one canonical state struct,
    minimized over the permutation group of the named ``axes``."""
    sperms = permutations(bounds) if "Server" in axes \
        else (tuple(range(bounds.n_servers)),)
    vqs = range(len(value_permutations(bounds))) if "Value" in axes else (0,)
    best_hi = best_lo = None
    for p in sperms:
        ps = permute_struct(struct, p, bounds, xp)
        for qi in vqs:
            t = permute_values(ps, qi, bounds, xp) if "Value" in axes else ps
            t = st.canonicalize(t, xp)
            hi, lo = fpr.fingerprint(st.pack(t, xp), consts, xp)
            if best_hi is None:
                best_hi, best_lo = hi, lo
            else:
                take = (hi < best_hi) | ((hi == best_hi) & (lo < best_lo))
                best_hi = xp.where(take, hi, best_hi)
                best_lo = xp.where(take, lo, best_lo)
    return best_hi, best_lo


@functools.lru_cache(maxsize=None)
def _host_consts(width: int) -> np.ndarray:
    # one PCG64 spin-up per width, not per call (refbfs keys every
    # transition through here under symmetry)
    return fpr.lane_constants(width)


def py_orbit_fingerprint(s, bounds: Bounds,
                         axes: tuple = ("Server",)) -> tuple:
    """Oracle-side orbit key of a PyState — same arithmetic, NumPy."""
    from raft_tla_tpu.models import interp

    lay = st.Layout.of(bounds)
    struct = st.unpack(interp.to_vec(s, bounds), lay, np)
    hi, lo = orbit_fingerprint(struct, bounds, _host_consts(lay.width), np,
                               axes)
    return int(hi), int(lo)


def init_fingerprint(config, init_py, init_vec) -> tuple:
    """The dedup key of the initial state, view-folded and orbit-reduced
    per the config — one definition for every engine's table seeding."""
    if getattr(config, "view", None):
        from raft_tla_tpu.models import interp, views

        viewed = views.py_view(config.view)(init_py, config.bounds)
        if viewed is not init_py:
            init_py = viewed
            init_vec = interp.to_vec(viewed, config.bounds)
    if config.symmetry:
        return py_orbit_fingerprint(init_py, config.bounds, config.symmetry)
    consts = _host_consts(init_vec.shape[-1])
    hi, lo = fpr.fingerprint(init_vec.astype(np.int32), consts, np)
    return int(hi), int(lo)
