"""Symmetry reduction over the Server model values (TLC SYMMETRY stanza).

The reference binds ``Server`` to model values (``raft.cfg:6``), which TLC
can quotient by permutation symmetry (its classic state-space reduction —
the spec never distinguishes individual servers).  This module implements
the same reduction for the tensor checker, the TPU way:

The dedup key of a state becomes its **orbit-minimal fingerprint**:
``min over all permutations π of fp(canonicalize(π(s)))``, where ``π(s)``
renumbers every server-indexed axis and server-valued field.  The min is
orbit-invariant, so two states equal up to server renaming share one key
and one store row — the reachable count becomes the orbit count, exactly
TLC's SYMMETRY semantics (including its property: the stored witness per
orbit is whichever member was discovered first).  On device this is |π|
static transforms batched over the candidate block — pure gathers, bit
arithmetic, and the existing canonicalize/pack/fingerprint pipeline, fused
by XLA; no extra passes over HBM.

Permuting one state under ``p`` (new index of old server j is ``p[j]``):

- per-server axes (role, term, votedFor, commitIndex, logLen, log*,
  vResp, vGrant): rows reordered by the inverse permutation;
- server-valued *contents*: ``votedFor`` ids map through ``p`` (0 = Nil
  fixed); vote bitmasks move bit j to bit ``p[j]``;
- ``nextIndex``/``matchIndex`` reorder both axes;
- message records rewrite their ``src``/``dst`` fields through ``p``
  (occupied slots only — empty slots stay all-zero), then the bag
  re-canonicalizes (sort order may change under renaming).

``Value`` symmetry is not implemented this round (the reference cfg names
no SYMMETRY at all; Server is the axis the state space actually explodes
in).
"""

from __future__ import annotations

import functools
import itertools
import math

import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.ops import state as st

MAX_SYM_SERVERS = 6      # 720 permutations; beyond this the orbit pass dwarfs the step


def permutations(bounds: Bounds) -> tuple:
    if bounds.n_servers > MAX_SYM_SERVERS:
        raise ValueError(
            f"Server symmetry supports at most {MAX_SYM_SERVERS} servers "
            f"(got {bounds.n_servers}: {math.factorial(bounds.n_servers)}"
            " permutations)")
    return tuple(itertools.permutations(range(bounds.n_servers)))


def permute_struct(struct: dict, p: tuple, bounds: Bounds, xp) -> dict:
    """Apply server permutation ``p`` to one state struct (then the caller
    must re-canonicalize the message bag)."""
    n = bounds.n_servers
    inv = tuple(p.index(k) for k in range(n))      # new row k = old row inv[k]
    inv_idx = xp.asarray(inv)
    # votedFor lookup: 0 stays Nil, id j+1 -> p[j]+1
    vf_map = xp.asarray((0,) + tuple(p[j] + 1 for j in range(n)))

    def rows(a):
        return a[inv_idx, ...]

    def bitperm(mask):
        out = xp.zeros_like(mask)
        for j in range(n):
            out = out | (((mask >> j) & 1) << p[j])
        return out

    # src/dst fields of occupied message slots, via the packed hi word
    s_sh, s_w = mb._HI_FIELDS["src"]
    d_sh, d_w = mb._HI_FIELDS["dst"]
    keep = ~(((1 << s_w) - 1) << s_sh | ((1 << d_w) - 1) << d_sh)
    hi = struct["msgHi"]
    occupied = struct["msgCount"] > 0
    p_lut = xp.asarray(p + tuple(0 for _ in range(16 - n)))  # 4-bit fields
    new_hi = (hi & keep) | (p_lut[(hi >> s_sh) & ((1 << s_w) - 1)] << s_sh) \
        | (p_lut[(hi >> d_sh) & ((1 << d_w) - 1)] << d_sh)
    new_hi = xp.where(occupied, new_hi, hi)

    out = {
        "role": rows(struct["role"]),
        "term": rows(struct["term"]),
        "votedFor": vf_map[rows(struct["votedFor"])],
        "commitIndex": rows(struct["commitIndex"]),
        "logLen": rows(struct["logLen"]),
        "logTerm": rows(struct["logTerm"]),
        "logVal": rows(struct["logVal"]),
        "vResp": bitperm(rows(struct["vResp"])),
        "vGrant": bitperm(rows(struct["vGrant"])),
        "nextIndex": struct["nextIndex"][inv_idx, :][:, inv_idx],
        "matchIndex": struct["matchIndex"][inv_idx, :][:, inv_idx],
        "msgHi": new_hi,
        "msgLo": struct["msgLo"],
        "msgCount": struct["msgCount"],
    }
    if "eTerm" in struct:
        # Faithful-mode history (ops/state.py HISTORY_FIELDS).  Log ranks
        # contain no server ids, so allLogs/eLog/mlog are fixed points;
        # voterLog permutes both axes like nextIndex, election records
        # remap eleader/evotes/evoterLog (slot re-sort happens in the
        # caller's canonicalize, like the message bag).
        eocc = struct["eTerm"] > 0
        lead_lut = xp.asarray(p)
        out.update({
            "allLogs": struct["allLogs"],
            "vLog": struct["vLog"][inv_idx, :][:, inv_idx],
            "eTerm": struct["eTerm"],
            "eLeader": xp.where(eocc, lead_lut[struct["eLeader"]],
                                struct["eLeader"]),
            "eLog": struct["eLog"],
            "eVotes": xp.where(eocc, bitperm(struct["eVotes"]),
                               struct["eVotes"]),
            "eVLog": struct["eVLog"][:, inv_idx],
        })
    return out


def orbit_fingerprint(struct: dict, bounds: Bounds, consts, xp):
    """Orbit-minimal (hi, lo) fingerprint of one canonical state struct."""
    best_hi = best_lo = None
    for p in permutations(bounds):
        t = st.canonicalize(permute_struct(struct, p, bounds, xp), xp)
        hi, lo = fpr.fingerprint(st.pack(t, xp), consts, xp)
        if best_hi is None:
            best_hi, best_lo = hi, lo
        else:
            take = (hi < best_hi) | ((hi == best_hi) & (lo < best_lo))
            best_hi = xp.where(take, hi, best_hi)
            best_lo = xp.where(take, lo, best_lo)
    return best_hi, best_lo


@functools.lru_cache(maxsize=None)
def _host_consts(width: int) -> np.ndarray:
    # one PCG64 spin-up per width, not per call (refbfs keys every
    # transition through here under symmetry)
    return fpr.lane_constants(width)


def py_orbit_fingerprint(s, bounds: Bounds) -> tuple:
    """Oracle-side orbit key of a PyState — same arithmetic, NumPy."""
    from raft_tla_tpu.models import interp

    lay = st.Layout.of(bounds)
    struct = st.unpack(interp.to_vec(s, bounds), lay, np)
    hi, lo = orbit_fingerprint(struct, bounds, _host_consts(lay.width), np)
    return int(hi), int(lo)


def init_fingerprint(config, init_py, init_vec) -> tuple:
    """The dedup key of the initial state, orbit-reduced when the run has
    SYMMETRY — one definition for every engine's table seeding."""
    if config.symmetry:
        return py_orbit_fingerprint(init_py, config.bounds)
    consts = _host_consts(init_vec.shape[-1])
    hi, lo = fpr.fingerprint(init_vec.astype(np.int32), consts, np)
    return int(hi), int(lo)
