"""Pallas megakernel for the fused frontier step.

One ``pl.pallas_call`` runs the ENTIRE per-chunk pipeline — unpack,
successor expansion, canonicalize, orbit-minimal fingerprint, invariant
probes, StateConstraint — over a VMEM-resident block of candidate rows,
emitting only the per-lane ``(fp_hi, fp_lo)`` key lanes, the
``valid``/``overflow``/``inv_ok``/``con_ok`` masks and the packed
survivor vectors.  The XLA step (ops/kernels.build_step) lowers the same
stages as separate fusions with the ``[B, A, W]`` candidate block
round-tripping HBM between them; here a 1-D grid walks row blocks of the
chunk and each block's candidates stay on-core across all stages.

Construction — staged, not re-derived
-------------------------------------
The kernel body does not reimplement the step: it *stages the XLA step's
own jaxpr* (``jax.make_jaxpr`` over one row block) into the Pallas call,
re-evaluating it inside the kernel via ``jax.core.eval_jaxpr``.  Two
consequences, both load-bearing:

- **Bit-identity by construction.**  The kernel evaluates literally the
  same program the XLA path runs (same orbit scan, same prescan ladder
  and sig-prune gates resolved at build time, same invalid-lane
  zeroing), so the parity suite (tests/test_pallas_step.py) is a check
  on the staging machinery, not on a hand-kept twin that could drift.
  All three orbit-scan variants — full scan, prescan-grouped, sig-prune
  — ride along for free, selected by the same construction-time gates
  as the XLA step (the prescan's in-block grouping compacts per row
  block here; its outputs are bit-identical at any grouping scope by
  the rung argument in ops/kernels._PRESCAN_RUNGS).
- **Constants become kernel inputs.**  Pallas kernels may not close
  over array constants (ops/pallas_fp.i32_const), so the jaxpr's consts
  — permutation LUTs, fingerprint lane multipliers, action-parameter
  tables — are passed as broadcast inputs (whole-array BlockSpecs,
  index map pinned to the origin), normalized to int32 on the way in
  (Mosaic has no unsigned ops; same-bits reinterpret both ways).

VMEM blocking scheme
--------------------
Grid = ``(ceil(B / block_rows),)`` with ``block_rows`` = 128 by default:
per grid step the resident set is one ``[block, W]`` input slab, the
``[block, A, W]`` candidate block plus its masks/keys, and the LUT
inputs — ~``block * W * (A + 1) * 4`` bytes plus stage temporaries.  At
the flagship shape (3s/2v: W = 60, A = 42) a 128-row block is ~1.3 MB
of named slabs against the ~16 MB/core VMEM budget, leaving Mosaic
headroom for the scan carries; rows pad up to the block multiple with
zero rows (sliced off the outputs, so padding never changes a lane).

Mosaic status: off-TPU this module runs under the Pallas interpreter
(ops/pallas_compat; that is also the CPU A/B + parity-test path).  A
real Mosaic build of the staged step must contend with the gather/sort
heavy canonicalize + prescan stages — the round-2 hand-scheduled orbit
kernel failed Mosaic past P=6 on scoped-vmem (RESULTS.md "Pallas orbit
kernel") — so the gate ships auto=OFF until an on-chip session measures
a win (RESULTS.md "Megakernel A/B"; ops/kernels._megakernel_enabled).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.ops import pallas_compat as pc
from raft_tla_tpu.ops import state as st

_BLOCK_ROWS = 128          # grid-block rows; chunks pad up to a multiple

I32 = jnp.int32

# The megakernel's whole-step write surface per spec subset — the fused
# analog of the per-family ops/kernels.TRANSFER_WRITES contract, for the
# width-safety analyzer: the fused kernel must not be able to write a
# packed field the per-family transfer twins never proved.  The analyzer
# (analysis/widthcheck.check_fused_coverage) cross-checks each tuple
# against the union of the families' declared write-sets plus the
# expansion postlude, so a family growing a new write — or a spec subset
# gaining a family — fails the lint loudly until this table is re-kept.
# History-only fields are listed unconditionally; the analyzer filters
# by mode.  Hand-maintained: do NOT derive from TRANSFER_WRITES (that
# would make the cross-check vacuous).
FUSED_WRITES = {
    "full": (
        "allLogs", "commitIndex", "eLeader", "eLog", "eTerm", "eVLog",
        "eVotes", "logLen", "logTerm", "logVal", "matchIndex", "msgCount",
        "msgHi", "msgLo", "nextIndex", "role", "term", "vGrant", "vLog",
        "vResp", "votedFor",
    ),
    # Receive alone already writes most of the schema, so the election
    # subset's union coincides with full.
    "election": (
        "allLogs", "commitIndex", "eLeader", "eLog", "eTerm", "eVLog",
        "eVotes", "logLen", "logTerm", "logVal", "matchIndex", "msgCount",
        "msgHi", "msgLo", "nextIndex", "role", "term", "vGrant", "vLog",
        "vResp", "votedFor",
    ),
    # No BecomeLeader in the replication subset: the election-history
    # fields are out of the fused write surface.
    "replication": (
        "allLogs", "commitIndex", "logLen", "logTerm", "logVal",
        "matchIndex", "msgCount", "msgHi", "msgLo", "nextIndex", "role",
        "term", "vGrant", "vLog", "vResp", "votedFor",
    ),
}


def _normalize(c):
    """Constants cross the Pallas boundary as int32 (same bits)."""
    if c.dtype in (jnp.uint32, jnp.bool_):
        return c.astype(I32)
    return c


def _restore(x, dtype):
    if dtype == jnp.uint32:
        return x.astype(jnp.uint32)
    if dtype == jnp.bool_:
        return x != 0
    return x


def _origin_map(ndim):
    return lambda i: (0,) * ndim


def _row_map(ndim):
    return lambda i: (i,) + (0,) * (ndim - 1)


def build_step_megakernel(bounds: Bounds, spec: str = "full",
                          invariants: tuple = (), symmetry: tuple = (),
                          view: str | None = None, *,
                          block_rows: int | None = None,
                          interpret: bool | None = None):
    """The megakernel twin of ops/kernels.build_step — same contract.

    ``step(vecs[B, W]) -> dict`` with exactly the dense step's keys and
    dtypes (``svecs``/``valid``/``overflow``/``fp_hi``/``fp_lo``/
    ``inv_ok``/``con_ok``), bit-identical lane for lane.  ``interpret``
    follows ops/pallas_compat: ``None`` auto-selects Mosaic on TPU and
    the interpreter elsewhere (there is no silent jnp fallback here —
    the jnp path IS the gate-off default a level above, in
    ``build_step``).
    """
    from raft_tla_tpu.ops import kernels

    block = int(block_rows or _BLOCK_ROWS)
    lay = st.Layout.of(bounds)
    W = lay.width
    n_inv = len(invariants)
    # The staged program: the XLA step itself (megakernel=False — this
    # builder IS the gate-on branch of build_step) over one row block,
    # masks/keys normalized to int32 for the kernel boundary.
    xla_step = kernels.build_step(bounds, spec, invariants, symmetry,
                                  view, megakernel=False)

    def _stage(vecs):
        out = xla_step(vecs)
        outs = (out["svecs"], out["valid"].astype(I32),
                out["overflow"].astype(I32), out["fp_hi"].astype(I32),
                out["fp_lo"].astype(I32))
        if n_inv:                   # zero-lane outputs can't cross Pallas
            outs += (out["inv_ok"].astype(I32),)
        return outs + (out["con_ok"].astype(I32),)

    closed = jax.make_jaxpr(_stage)(jnp.zeros((block, W), I32))
    consts = [jnp.asarray(c) for c in closed.consts]
    const_dtypes = [c.dtype for c in consts]
    out_avals = [v.aval for v in closed.jaxpr.outvars]
    A = out_avals[0].shape[1]
    n_c = len(consts)
    mode = pc.resolve(interpret, jnp_fallback=False)

    def kernel(*refs):
        c_refs, vec_ref = refs[:n_c], refs[n_c]
        out_refs = refs[n_c + 1:]
        cs = [_restore(r[...], dt) for r, dt in zip(c_refs, const_dtypes)]
        outs = jax.core.eval_jaxpr(closed.jaxpr, cs, vec_ref[...])
        for r, o in zip(out_refs, outs):
            r[...] = o

    @functools.partial(jax.jit, static_argnames=("Bp",))
    def _call(Bp, *args):
        from jax.experimental import pallas as pl

        in_specs = [pl.BlockSpec(c.shape, _origin_map(c.ndim))
                    for c in consts]
        in_specs.append(pl.BlockSpec((block, W), _row_map(2)))
        out_specs = [pl.BlockSpec((block,) + a.shape[1:],
                                  _row_map(a.ndim)) for a in out_avals]
        out_shape = [jax.ShapeDtypeStruct((Bp,) + a.shape[1:], a.dtype)
                     for a in out_avals]
        return pl.pallas_call(
            kernel, grid=(Bp // block,), in_specs=in_specs,
            out_specs=out_specs, out_shape=out_shape,
            interpret=mode == pc.INTERPRET)(*args)

    norm_consts = [_normalize(c) for c in consts]

    def step(vecs):
        B = vecs.shape[0]
        Bp = -(-B // block) * block
        vp = vecs if Bp == B else \
            jnp.zeros((Bp, W), I32).at[:B].set(vecs)
        outs = _call(Bp, *norm_consts, vp)
        outs = [o[:B] for o in outs]
        if n_inv:
            (svecs, valid, ovf, fp_hi, fp_lo, inv_ok, con_ok) = outs
            inv_ok = inv_ok != 0
        else:
            (svecs, valid, ovf, fp_hi, fp_lo, con_ok) = outs
            inv_ok = jnp.ones((B, A, 0), dtype=bool)
        return {"svecs": svecs, "valid": valid != 0, "overflow": ovf != 0,
                "fp_hi": fp_hi.astype(jnp.uint32),
                "fp_lo": fp_lo.astype(jnp.uint32),
                "inv_ok": inv_ok, "con_ok": con_ok != 0}

    return step
