"""Bounds & check configuration — the L0/L5 layer of the checker.

The reference config (``raft.cfg:1-15``) binds ``Server = {s1,s2,s3}`` and
``Value = {v1,v2}`` but contains **no CONSTRAINT**, while the raw spec has an
infinite reachable state space: ``Timeout`` increments ``currentTerm`` without
bound (``raft.tla:180``), ``ClientRequest`` grows logs without bound
(``raft.tla:250``), and ``DuplicateMessage`` grows message multiplicities
without bound (``raft.tla:443-445``).  Exhaustive checking is therefore only
meaningful relative to a state constraint.  :class:`Bounds` is that constraint,
made first-class.

Capacity scheme (why ``*_cap = bound + 1``)
-------------------------------------------
TLC's CONSTRAINT semantics: a state that *violates* the constraint is still
generated, counted, and invariant-checked, but its successors are never
explored.  The tensor encoding must therefore be able to *represent* states one
step past each bound, because every expanded state satisfies the constraint and
each action moves a bound by at most one:

- ``Timeout`` bumps a term by exactly 1 (``raft.tla:180``); messages carry
  terms of senders that satisfied the constraint when they sent, so no value
  ever needs more than ``max_term + 1``.
- ``ClientRequest``/append grow a log by exactly 1 entry (``raft.tla:250``,
  ``raft.tla:383-388``).
- One action adds at most one *distinct* message to the bag (``Send``
  ``raft.tla:122``; ``Reply`` ``raft.tla:129-130`` removes one and adds one).
- ``DuplicateMessage`` bumps one multiplicity by 1 (``raft.tla:443-445``).

Any state that would exceed a *capacity* (not just a bound) indicates a bug in
this reasoning and must fail loudly — never clamp (SURVEY §4.5).
"""

from __future__ import annotations

import dataclasses

# Bit widths of packed message fields (ops/msgbits.py).  Caps must fit.
_MAX_TERM_CAP = 63      # 6-bit term fields
_MAX_INDEX_CAP = 62     # 6-bit index fields; nextIndex can reach log_cap + 1
_MAX_SERVERS = 14       # 4-bit src/dst fields; votedFor uses n+1 symbols
_MAX_VALUES = 15        # 4-bit value field; values are 1..V (0 = none)
# Multiplicities live in full int32 slots (never bit-packed); this cap only
# keeps counts sane for host-side displays and catches runaway configs.
_MAX_DUP_CAP = 1 << 20
# Faithful mode: log ranks+1 must fit the 14-bit mlog field and the allLogs
# bitmask must stay small (<= 32 int32 words).
_MAX_LOG_UNIVERSE = 1024


@dataclasses.dataclass(frozen=True)
class Bounds:
    """The model universe (``raft.cfg:5-15``) plus the state constraint.

    ``n_servers``/``n_values`` bind the CONSTANTS ``Server``/``Value``
    (``raft.tla:11,14``); the ``max_*`` fields are the StateConstraint the
    reference's cfg is missing (SURVEY §0 defect 2).
    """

    n_servers: int = 3
    n_values: int = 2
    max_term: int = 3      # constraint: \A i : currentTerm[i] <= max_term
    max_log: int = 2       # constraint: \A i : Len(log[i]) <= max_log
    max_msgs: int = 4      # constraint: Cardinality(DOMAIN messages) <= max_msgs
    max_dup: int = 1       # constraint: \A m : messages[m] <= max_dup
    # Faithful mode (SURVEY §7.0.3b): carry the proof-only history variables
    # (elections raft.tla:39, allLogs raft.tla:44, voterLog raft.tla:77, and
    # the mlog message fields raft.tla:220-222/297-299) as real fingerprinted
    # state, exactly as stock TLC does on the unmodified spec.  Off (parity
    # mode) they are stripped on both sides of every TLC comparison.
    history: bool = False
    # Capacity of the `elections` slot encoding.  The spec puts no bound on
    # the set (it is derived-finite under the constraint); exceeding the
    # capacity is a loud engine failure, never a clamp (SURVEY §4.5).
    max_elections: int = 6

    def __post_init__(self) -> None:
        if not (1 <= self.n_servers <= _MAX_SERVERS):
            raise ValueError(f"n_servers must be in [1,{_MAX_SERVERS}], got {self.n_servers}")
        if not (1 <= self.n_values <= _MAX_VALUES):
            raise ValueError(f"n_values must be in [1,{_MAX_VALUES}], got {self.n_values}")
        if self.max_term < 1 or self.term_cap > _MAX_TERM_CAP:
            raise ValueError(f"max_term out of range: {self.max_term}")
        if self.max_log < 0 or self.log_cap + 1 > _MAX_INDEX_CAP:
            raise ValueError(f"max_log out of range: {self.max_log}")
        if self.max_msgs < 1:
            raise ValueError(f"max_msgs must be >= 1, got {self.max_msgs}")
        if self.max_dup < 1 or self.dup_cap > _MAX_DUP_CAP:
            raise ValueError(f"max_dup out of range: {self.max_dup}")
        if self.history:
            if not (1 <= self.max_elections <= 64):
                raise ValueError(
                    f"max_elections must be in [1,64], got {self.max_elections}")
            # Log-universe size gates the history encodings: ranks+1 must fit
            # the 14-bit mlog message field (ops/msgbits.py) and the allLogs
            # bitmask must stay a few dozen words (ops/loguniv.py).
            from raft_tla_tpu.ops.loguniv import LogUniverse
            uni = LogUniverse.of(self)
            if uni.size > _MAX_LOG_UNIVERSE:
                raise ValueError(
                    f"faithful mode needs a log universe <= "
                    f"{_MAX_LOG_UNIVERSE} (got {uni.size}: term_cap="
                    f"{self.term_cap} x {self.n_values} values, lengths 0.."
                    f"{self.log_cap}); shrink max_term/max_log/n_values")

    # -- capacities (representable range = one step past each bound) --------
    @property
    def term_cap(self) -> int:
        return self.max_term + 1

    @property
    def log_cap(self) -> int:
        return self.max_log + 1

    @property
    def msg_cap(self) -> int:
        """Number of message slots in the tensor encoding."""
        return self.max_msgs + 1

    @property
    def dup_cap(self) -> int:
        return self.max_dup + 1


@dataclasses.dataclass(frozen=True)
class CheckConfig:
    """A full checking run: universe + bounds + spec subset + invariants.

    ``spec`` selects the ``Next`` disjunct subset (models/spec.py); the
    reference's full ``Next`` is ``raft.tla:454-465``.  ``invariants`` are
    names resolved against the invariant registry (``models/invariants.py``);
    the reference cfg's ``INVARIANT NoTwoLeaders`` (``raft.cfg:3``) is
    *undefined in raft.tla* and is resolved to Election Safety by default
    (SURVEY §0 defect 1).
    """

    bounds: Bounds = dataclasses.field(default_factory=Bounds)
    spec: str = "full"                     # full | election | replication
    invariants: tuple = ("NoTwoLeaders",)  # registry names
    symmetry: tuple = ()                   # () or ("Server",): TLC SYMMETRY
    chunk: int = 1024                      # frontier states expanded per jit call
    check_deadlock: bool = False           # TLC -deadlock analog (off: Restart is always enabled anyway)
    view: str | None = None                # TLC VIEW analog: a registered
    #   exact view (models/views.py) folded into every dedup key; None =
    #   identity.  Joins the checkpoint digest when set.

    def __post_init__(self) -> None:
        if not self.bounds.history:
            from raft_tla_tpu.models.invariants import HISTORY_REGISTRY
            hist = [nm for nm in self.invariants if nm in HISTORY_REGISTRY]
            if hist:
                raise ValueError(
                    f"invariant(s) {hist} read the history variables; they "
                    "require faithful mode (Bounds.history / --faithful)")
        if self.view is not None:
            from raft_tla_tpu.models.views import REGISTRY
            if self.view not in REGISTRY:
                raise ValueError(
                    f"unknown view {self.view!r} "
                    f"(known: {sorted(REGISTRY)})")
