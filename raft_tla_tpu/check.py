"""The checker CLI — the L6 layer (SURVEY §1), ``python -m raft_tla_tpu.check``.

Drives a full checking run from a stock TLC model config (the reference's
``raft.cfg:1-15`` parses unchanged), mirroring the TLC invocation surface the
reference relies on (``.vscode/settings.json:3-4``): spec + cfg in,
pass/violation + trace out, per-action coverage (TLC's ``-coverage``), and
exit codes distinguishing success, violation, and error (TLC's own
convention: 0 ok, 12 safety violation).

The model universe (``Server``/``Value``) comes from the cfg; the state
constraint — which stock TLC leaves to the missing ``CONSTRAINT`` stanza
(SURVEY §0 defect 2) — comes from ``--max-*`` flags.  ``--emit-tlc DIR``
writes the matching ``MCraft.tla``/``MCraft.cfg`` pair so the identical
bounded model can be run under stock TLC on a JVM host (oracle parity,
SURVEY §4.3).

Engines (``--engine``): ``device`` (default; full search resident on the
accelerator), ``shard`` (multi-device mesh over ICI), ``host`` (per-chunk
jit, host dedup), ``ref`` (pure-Python oracle BFS).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

EXIT_OK = 0
EXIT_DEADLOCK = 11       # TLC's exit code for deadlock
EXIT_VIOLATION = 12      # TLC's exit code for safety-property violations
EXIT_LIVENESS = 13       # TLC's exit code for liveness-property violations
EXIT_ERROR = 1
EXIT_STOPPED = 14        # ours: stopped before exhaustion (resumable) —
#                          no verdict; the campaign supervisor keys on it


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m raft_tla_tpu.check",
        description="TPU-native exhaustive checker for the Raft TLA+ spec")
    p.add_argument("cfg", help="TLC model config (e.g. the reference "
                               "raft.cfg); binds Server/Value/INVARIANT")
    p.add_argument("--spec", default="full",
                   choices=("full", "election", "replication", "twophase"),
                   help="loaded spec: a Raft Next-disjunct subset (default: "
                        "full raft.tla:454-465) or the bundled twophase "
                        "(two-phase commit, frontend-compiled; --engine "
                        "host; cfg binds CONSTANT RM)")
    p.add_argument("--engine", default="device",
                   choices=("device", "paged", "streamed", "ddd", "shard",
                            "pagedshard", "ddd-shard", "host", "ref"),
                   help="device: search resident in HBM; paged: HBM ring + "
                        "native host store (capacity bounded by host RAM); "
                        "streamed: host-streamed frontier (no live-window "
                        "ceiling — for spaces whose BFS levels outgrow any "
                        "ring); ddd: delayed duplicate detection — exact "
                        "dedup on the host, no device fingerprint-table "
                        "ceiling (for spaces past ~2^28 distinct states); "
                        "shard: multi-chip mesh; pagedshard: mesh "
                        "whose per-device stores page to host RAM; "
                        "ddd-shard: mesh-sharded DDD — host-exact dedup "
                        "partitioned over the fingerprint-owner map (the "
                        "scale engine's multi-chip composition); host: "
                        "per-chunk jit; ref: pure-Python oracle")
    p.add_argument("--max-term", type=int, default=3,
                   help="CONSTRAINT: currentTerm[i] <= N (default 3)")
    p.add_argument("--max-log", type=int, default=2,
                   help="CONSTRAINT: Len(log[i]) <= N (default 2)")
    p.add_argument("--max-msgs", type=int, default=4,
                   help="CONSTRAINT: Cardinality(DOMAIN messages) <= N")
    p.add_argument("--max-dup", type=int, default=1,
                   help="CONSTRAINT: messages[m] <= N")
    p.add_argument("--deadlock", action="store_true",
                   help="check for deadlocks (a reachable state with no "
                        "successor) like stock TLC does by default; exit "
                        "code 11 on one. Off by default: the full Next "
                        "cannot deadlock (Restart is always enabled, "
                        "raft.tla:167-175), only sub-specs can")
    p.add_argument("--faithful", action="store_true",
                   help="carry the proof-only history variables (elections/"
                        "allLogs/voterLog/mlog, raft.tla:39,44,77) as real "
                        "fingerprinted state, as stock TLC does on the "
                        "unmodified spec; enables the *Hist invariants "
                        "(default: parity mode, history stripped)")
    p.add_argument("--max-elections", type=int, default=6,
                   help="elections-history slot capacity (--faithful only); "
                        "exceeding it aborts loudly")
    p.add_argument("--chunk", type=int, default=1024,
                   help="frontier states expanded per device step")
    p.add_argument("--cap", type=int, default=1 << 20,
                   help="expected distinct-state capacity: store rows for "
                        "device/shard; fingerprint-table sizing (2 slots "
                        "per state) for paged, whose store itself is host-"
                        "RAM-bounded")
    p.add_argument("--levels", type=int, default=256,
                   help="max BFS depth (device/shard engines)")
    p.add_argument("--ring", type=int, default=None,
                   help="HBM ring rows for --engine paged (power of two; "
                        "must hold the widest current+next BFS level pair; "
                        "default: derived from --cap, at most 4M)")
    p.add_argument("--devices", type=int, default=None,
                   help="mesh size for --engine shard (default: all)")
    p.add_argument("--seg-chunks", type=int, default=256,
                   help="initial chunk expansions per device dispatch for "
                        "--engine shard (the adaptive pacer tunes it from "
                        "there; small values force frequent segment "
                        "boundaries, hence more checkpoint opportunities)")
    p.add_argument("--route", type=int, default=0, metavar="K",
                   help="--engine ddd only: EP-routed step with K "
                        "compacted candidate slots per chunk (the "
                        "expensive orbit/invariant stages then run on K "
                        "rows instead of chunk*A; size from the "
                        "route_peak stat of a dense run; overflow aborts "
                        "loudly; 0 = dense step)")
    p.add_argument("--reshard-to", type=int, default=None, metavar="NDEV",
                   help="shard/ddd/ddd-shard: instead of searching, "
                        "rewrite the --resume checkpoint for an "
                        "NDEV-device mesh, save it to the --checkpoint "
                        "path, print a summary, and exit (a pod-size "
                        "change no longer discards a run; --engine ddd "
                        "migrates a single-chip DDD campaign onto a "
                        "ddd-shard mesh)")
    p.add_argument("--reshard-cap", type=int, default=None, metavar="N",
                   help="with --reshard-to (shard engine): grow the "
                        "destination per-device store to N rows (rescues "
                        "a run near FAIL_STORE/FAIL_PROBE; default: keep "
                        "the source capacities)")
    p.add_argument("--block", type=int, default=None, metavar="ROWS",
                   help="ddd/ddd-shard: frontier window rows per shard "
                        "(default: 2^20 for ddd, the smallest chunk-"
                        "multiple >= 2^18 for ddd-shard; must match the "
                        "source run when resuming or resharding — the "
                        "reshard summary prints the value to resume "
                        "with)")
    p.add_argument("--retention", default="full",
                   choices=("full", "frontier"),
                   help="--engine ddd / ddd-shard: 'frontier' keeps "
                        "master keys "
                        "in RAM and only the current+next BFS level of "
                        "rows in disk-backed level files, with NO trace "
                        "links (violations report the state, not a path "
                        "— TLC -noTrace).  ~16 B/state instead of ~76: "
                        "the campaign mode for 10^9+-state spaces")
    p.add_argument("--keep-levels", action="store_true",
                   help="--retention frontier: retain ALL level files "
                        "(TLC's states/ disk regime) so a violation "
                        "reconstructs a full trace by backward "
                        "re-search; costs the rows-stream disk "
                        "footprint")
    p.add_argument("--cp-lanes", action="store_true",
                   help="--engine ddd-shard only: CP mode — shard the "
                        "bag-scan ACTION lanes across the mesh instead "
                        "of the frontier rows (window replicated; see "
                        "RESULTS.md 'CP measured' before choosing it)")
    from raft_tla_tpu.models.views import REGISTRY as _view_registry
    p.add_argument("--view", default=None,
                   choices=tuple(sorted(_view_registry)),
                   help="TLC VIEW analog: fold a registered EXACT view "
                        "into every dedup key (models/views.py carries "
                        "the soundness argument; deadvotes: zero "
                        "votesResponded/votesGranted of non-Candidates — "
                        "collapses dead vote-set freight, same verdicts)")
    p.add_argument("--slices", type=int, default=None,
                   help="multi-slice scale-out for shard/pagedshard: build "
                        "a 2-D (dcn, ici) mesh of N slices x (devices/N) "
                        "chips with the hierarchical dedup exchange "
                        "(default: single-slice 1-D mesh)")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (virtual devices for shard)")
    p.add_argument("--emit-tlc", metavar="DIR",
                   help="also write MCraft.tla/MCraft.cfg for a stock-TLC "
                        "parity run, then continue")
    p.add_argument("--property", action="append", default=[],
                   metavar="NAME_OR_FORMULA",
                   help="temporal property to check under weak fairness: "
                        "a registered name (models/liveness.PROPERTIES) "
                        "or a formula '<>P', '[]<>P', 'P ~> Q' over "
                        "registered predicates (models/liveness."
                        "PREDICATES). Also read from the cfg's PROPERTY "
                        "stanza")
    p.add_argument("--wf", default="Next",
                   help="comma-separated action families assumed weakly "
                        "fair for --property (default: Next = the whole "
                        "relation; 'none' = no fairness, the reference "
                        "spec's actual Spec, raft.tla:469)")
    p.add_argument("--checkpoint", metavar="PATH",
                   help="periodically snapshot the search (device/paged/"
                        "shard engines); resume later with --resume")
    p.add_argument("--checkpoint-every", type=float, default=120.0,
                   metavar="SECONDS")
    p.add_argument("--resume", metavar="PATH",
                   help="resume a --checkpoint snapshot (device/paged/"
                        "shard engines)")
    p.add_argument("--deadline", type=float, default=None,
                   metavar="SECONDS",
                   help="stop losslessly at the first segment boundary "
                        "past this wall budget (exit 14, snapshot "
                        "flushed; --engine ddd only) — the campaign "
                        "supervisor's session-wall policy knob")
    p.add_argument("--no-trace", action="store_true",
                   help="suppress the counterexample trace on violation")
    p.add_argument("--coverage", action="store_true",
                   help="print per-action coverage (TLC -coverage analog)")
    p.add_argument("--symmetry", action="store_true",
                   help="quotient the state space by Server permutation "
                        "symmetry (TLC SYMMETRY analog; also enabled by a "
                        "cfg SYMMETRY stanza)")
    p.add_argument("--prescan", default=None,
                   choices=("auto", "on", "off"),
                   help="device-side duplicate prescan of candidate blocks "
                        "before the host sees them (ops/kernels."
                        "_prescan_enabled). Sets RAFT_TLA_PRESCAN "
                        "process-wide so every engine inherits one "
                        "decision; default: leave the env/auto policy "
                        "alone")
    p.add_argument("--sig-prune", default=None,
                   choices=("auto", "on", "off"),
                   help="signature-refinement orbit-scan pruning: scan one "
                        "permutation per coset of the verified per-state "
                        "stabilizer instead of the whole group (bit-"
                        "identical keys; ops/symmetry.py has the soundness "
                        "argument). Sets RAFT_TLA_SIGPRUNE process-wide so "
                        "every engine inherits one decision; default: "
                        "leave the env/auto policy alone (auto is "
                        "currently OFF — RESULTS.md 'sig-prune A/B')")
    p.add_argument("--megakernel", default=None,
                   choices=("auto", "on", "off"),
                   help="Pallas megakernel build of the fused step: the "
                        "whole expand/canonicalize/orbit/filter pipeline "
                        "in ONE kernel with candidates VMEM-resident "
                        "across stages (ops/pallas_step.py; bit-identical "
                        "lane for lane). Sets RAFT_TLA_MEGAKERNEL "
                        "process-wide so every engine inherits one "
                        "decision; default: leave the env/auto policy "
                        "alone (auto is currently OFF — RESULTS.md "
                        "'Megakernel A/B')")
    p.add_argument("--host-dedup", default=None,
                   choices=("auto", "on", "off"),
                   help="partitioned + background host dedup for the ddd "
                        "engines: the master key set splits into 2^k "
                        "high-bit partitions with budgeted compaction (no "
                        "O(N) merge spike in any single flush) and the "
                        "flush runs on a depth-1 ordered worker thread "
                        "that overlaps device compute — discovery stays "
                        "byte-identical (utils/keyset.py has the ordering "
                        "argument). Sets RAFT_TLA_HOSTDEDUP process-wide; "
                        "default: leave the env/auto policy alone (auto "
                        "= on iff nproc >= 2 — RESULTS.md 'Host dedup "
                        "A/B')")
    p.add_argument("--prefetch", default=None,
                   choices=("auto", "on", "off"),
                   help="double-buffered upload prefetch for the ddd "
                        "engines: a background thread reads block k+1's "
                        "rows + constraint column and stages them onto "
                        "the device while block k expands, so block "
                        "boundaries swap to a resident buffer instead of "
                        "paying drain+read+pad+h2d (utils/prefetch.py; "
                        "relies on the host stores' disjoint-range "
                        "append+read contract, utils/native.py) — "
                        "discovery stays byte-identical, hit or miss. "
                        "Sets RAFT_TLA_PREFETCH process-wide; default: "
                        "leave the env/auto policy alone (auto = on iff "
                        "nproc >= 2 — RESULTS.md 'Upload prefetch A/B')")
    p.add_argument("--device-dedup", default=None,
                   choices=("auto", "on", "off", "hash", "sort"),
                   help="device-resident exact within-level fingerprint "
                        "dedup for the ddd engines (ops/devdedup.py): "
                        "each segment's output buffers are filtered "
                        "against an HBM set of the keys already streamed "
                        "this level, so within-level duplicates never "
                        "cross d2h — the host LSM keyset stays the exact "
                        "cold tier and discovery stays byte-identical. "
                        "'on'/'hash' uses the open-addressing table "
                        "(device_engine's insert-if-absent protocol), "
                        "'sort' the portable sorted-set arm. Sets "
                        "RAFT_TLA_DEVDEDUP process-wide; default: leave "
                        "the env/auto policy alone (auto is currently "
                        "OFF — RESULTS.md 'Device dedup A/B')")
    p.add_argument("--lint", default="warn", choices=("warn", "strict"),
                   help="static width-safety pass (analysis/widthcheck) "
                        "before any step build: prove no transition can "
                        "overflow a packed field for these bounds. 'warn' "
                        "(default) prints findings and proceeds; 'strict' "
                        "makes any finding fatal. The full three-pass "
                        "analyzer is `python -m raft_tla_tpu.lint`")
    p.add_argument("--no-lint", action="store_true",
                   help="skip the static width-safety pass")
    p.add_argument("--stats", action="store_true",
                   help="emit one JSON line of run stats per search segment "
                        "on stderr (device/paged/shard engines)")
    p.add_argument("--events", metavar="PATH",
                   help="append the versioned JSONL run-event log "
                        "(run_start/segment/level_end/checkpoint/"
                        "violation/run_end — obs/events.py) to PATH; "
                        "tail it live with raft-tla-monitor. Sets "
                        "RAFT_TLA_EVENTS process-wide so liveness "
                        "re-runs inherit the same log")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent JAX compilation-cache directory "
                        "(also via RAFT_TLA_COMPILE_CACHE): repeated "
                        "runs of the same bounds skip XLA compilation "
                        "entirely — the serve daemon's warm-start knob, "
                        "useful for single checks too")
    p.add_argument("--trace", action="store_true",
                   help="emit schema-v8 span events (trace spans with "
                        "nesting and thread attribution) into the "
                        "--events log; merge and export with "
                        "raft-tla-trace. Unlike --phase-timers this adds "
                        "no device syncs — spans record host-side "
                        "dispatch walls. Distinct from --no-trace, which "
                        "suppresses counterexample trace RENDERING. Also "
                        "RAFT_TLA_TRACE=1")
    p.add_argument("--phase-timers", action="store_true",
                   help="attribute wall time to search phases (upload/"
                        "expand/export/dedup/snapshot, plus dedup_submit/"
                        "dedup_wait when background host dedup is on) in "
                        "each segment "
                        "event, at the cost of a device sync per phase — "
                        "the ddd engines lose their two-deep dispatch "
                        "overlap while this is on. Off by default so jit "
                        "pipelining is untouched; also RAFT_TLA_"
                        "PHASE_TIMERS=1")
    p.add_argument("--simulate", type=int, metavar="N", default=None,
                   help="TLC -simulate analog: instead of exhaustive "
                        "search, sample N random behaviors (batched "
                        "walkers on device), invariants checked on every "
                        "generated state")
    p.add_argument("--depth", type=int, default=100,
                   help="--simulate: maximum behavior length (TLC's "
                        "-depth; default 100)")
    p.add_argument("--walkers", type=int, default=1024,
                   help="--simulate: parallel walkers per device step "
                        "(with --fleet: the GLOBAL fleet size, split "
                        "evenly over the mesh)")
    p.add_argument("--seed", type=int, default=0,
                   help="--simulate: PRNG seed (same seed = same walks; "
                        "with --fleet, the same walks at any device "
                        "count)")
    p.add_argument("--fleet", action="store_true",
                   help="--simulate: shard the walker fleet over the "
                        "device mesh (--devices; statistical checking "
                        "at serving scale, bit-reproducible across "
                        "mesh shapes)")
    p.add_argument("--steer", type=float, default=0.0, metavar="TAU",
                   help="--fleet: coverage-steering temperature — bias "
                        "lane sampling against over-visited actions by "
                        "TAU * log1p(visits/mean) (default 0 = off; "
                        "exact replay preserved)")
    p.add_argument("--fault-weights", default=None, metavar="F=W,...",
                   help="--fleet: per-action-family sampling weights, "
                        "e.g. 'Restart=2,DropMessage=0.5' (sampling "
                        "policy only; enabledness untouched)")
    return p


def _resolve_config(args):
    # One code path with the serve/ admission gate: the CLI flags become a
    # JobOptions and the shared builder does every validation.
    from raft_tla_tpu.serve.jobs import JobOptions, resolve_check_config
    from raft_tla_tpu.utils.cfgparse import load_cfg

    opts = JobOptions(
        spec=args.spec, max_term=args.max_term, max_log=args.max_log,
        max_msgs=args.max_msgs, max_dup=args.max_dup,
        faithful=args.faithful, max_elections=args.max_elections,
        chunk=args.chunk, symmetry=args.symmetry, view=args.view,
        deadlock=args.deadlock, properties=tuple(args.property))
    return resolve_check_config(load_cfg(args.cfg), opts, path=args.cfg)


def _stats_cb(args):
    if not args.stats:
        return None
    import json

    def cb(stats):
        print(json.dumps(stats), file=sys.stderr)
    return cb


def _parse_fault_weights(text):
    """``Fam=W,Fam=W`` -> dict; raises ValueError on malformed cells
    (family-name validity is checked by the fleet engine, which knows
    the spec's action table)."""
    if not text:
        return None
    out = {}
    for cell in text.split(","):
        fam, eq, w = cell.partition("=")
        if not eq or not fam.strip():
            raise ValueError(f"bad --fault-weights cell {cell!r} "
                             "(want Family=Weight,...)")
        out[fam.strip()] = float(w)
    return out


def _simulate(args, config):
    """TLC -simulate analog; returns a TLC-compatible exit code."""
    from raft_tla_tpu.engine import DEADLOCK
    if args.fleet:
        from raft_tla_tpu.fleet import FleetSimulator
        from raft_tla_tpu.parallel.shard_engine import make_mesh
        sim = FleetSimulator(config, mesh=make_mesh(args.devices),
                             walkers=args.walkers, depth=args.depth,
                             seed=args.seed, steer_tau=args.steer,
                             fault_weights=_parse_fault_weights(
                                 args.fault_weights))
    else:
        from raft_tla_tpu.simulate import Simulator
        sim = Simulator(config, walkers=args.walkers, depth=args.depth,
                        seed=args.seed)
    # --stats/--events flow through the same RunTelemetry facade as the
    # exhaustive engines (the events path rides the env set in main()).
    res = sim.run(args.simulate, on_progress=_stats_cb(args))
    print(f"{res.n_behaviors} behaviors generated ({res.n_states} states, "
          f"deepest {res.max_depth_seen}), {res.wall_s:.2f}s "
          f"({res.states_per_sec:,.0f} states/s).")
    if args.fleet:
        print(f"Fleet: {res.n_devices} devices x "
              f"{res.walkers // res.n_devices} walkers"
              + (f", steer tau={res.steer_tau:g}" if res.steer_tau
                 else "")
              + f"; action-coverage entropy {res.coverage_entropy:.3f}")
    if res.violation is None:
        print("Model checking completed. No error has been found.")
        print(f"  (simulation: {args.simulate} behaviors of depth "
              f"<= {args.depth}; not exhaustive)")
        if args.fleet:
            conf = res.confidence(config.invariants)
            per = conf["per_invariant"]
            for nm in config.invariants:
                print(f"  {nm}: held on {per[nm]:,} sampled states")
        return EXIT_OK
    is_deadlock = res.violation.invariant == DEADLOCK
    if args.no_trace:
        print("Error: Deadlock reached." if is_deadlock else
              f"Error: Invariant {res.violation.invariant} is violated.")
    else:
        from raft_tla_tpu.frontend import resolve_model
        model = resolve_model(config.spec)
        print(model.render_trace(res.violation, config.bounds))
    return EXIT_DEADLOCK if is_deadlock else EXIT_VIOLATION



def _ddd_shard_block(chunk: int) -> int:
    """Smallest chunk-multiple >= 2^18: the default ddd-shard window
    slice (block needs chunk alignment, not a power of two)."""
    return chunk * max(1, -(-(1 << 18) // chunk))


def _make_cli_mesh(args):
    """1-D mesh, or the 2-D (dcn, ici) slice mesh when --slices is given."""
    import jax

    from raft_tla_tpu.parallel.shard_engine import make_mesh, make_slice_mesh
    if args.slices is None:
        return make_mesh(args.devices)
    nd = args.devices if args.devices is not None else len(jax.devices())
    if nd % args.slices:
        raise SystemExit(
            f"--devices {nd} not divisible by --slices {args.slices}")
    return make_slice_mesh(args.slices, nd // args.slices)


def _force_cpu(args):
    """Honor ``--cpu`` (one definition for every CLI path): switch the
    backend, or warn when backends are already initialized — never
    silently run on the accelerator."""
    if not args.cpu:
        return
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
        if args.devices:
            try:
                jax.config.update("jax_num_cpu_devices", args.devices)
            except AttributeError:
                # older jax: no jax_num_cpu_devices knob — the XLA flag
                # does the same job as long as no backend is live yet
                # (same caveat the RuntimeError arm below covers)
                flags = [f for f in os.environ.get("XLA_FLAGS",
                                                   "").split()
                         if "host_platform_device_count" not in f]
                flags.append("--xla_force_host_platform_device_count="
                             f"{args.devices}")
                os.environ["XLA_FLAGS"] = " ".join(flags)
    except RuntimeError:
        if jax.default_backend() != "cpu":
            print("Warning: --cpu requested but JAX backends are "
                  f"already initialized on {jax.default_backend()!r}; "
                  "proceeding there", file=sys.stderr)


def _run(args, config):
    _force_cpu(args)
    if args.engine == "ref":
        from raft_tla_tpu.models import refbfs
        return refbfs.check(config)
    if args.engine == "host":
        from raft_tla_tpu import engine
        return engine.check(config)
    if args.engine == "paged":
        from raft_tla_tpu.models import spec as S
        from raft_tla_tpu.paged_engine import PagedCapacities, PagedEngine
        A = len(S.action_table(config.bounds, config.spec))
        table = 1 << max(1, (2 * args.cap - 1).bit_length())
        if args.ring is not None:
            # Explicit ring: pass through untouched — PagedEngine rejects
            # undersized rings loudly (never silently resize, SURVEY §4.5).
            ring = args.ring
        else:
            ring = max(1 << min(22, max(12, (args.cap // 4).bit_length())),
                       1 << (2 * args.chunk * A - 1).bit_length())
        eng = PagedEngine(config, PagedCapacities(
            ring=ring, table=table, levels=args.levels))
        return eng.check(on_progress=_stats_cb(args),
                         checkpoint=args.checkpoint,
                         checkpoint_every_s=args.checkpoint_every,
                         resume=args.resume)
    if args.engine == "streamed":
        from raft_tla_tpu.streamed_engine import (StreamedCapacities,
                                                  StreamedEngine)
        table = 1 << max(1, (2 * args.cap - 1).bit_length())
        ring = args.ring if args.ring is not None else 1 << 22
        eng = StreamedEngine(config, StreamedCapacities(
            block=1 << 20, ring=ring, table=table, levels=args.levels))
        return eng.check(on_progress=_stats_cb(args),
                         checkpoint=args.checkpoint,
                         checkpoint_every_s=args.checkpoint_every,
                         resume=args.resume)
    if args.engine == "ddd":
        from raft_tla_tpu.models import spec as S
        from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine
        # the filter table is a traffic optimization, not a capacity
        # bound — size it to the expected state count, capped at the
        # 2 GiB-buffer limit the exact tables live under
        table = 1 << max(10, min(28, (2 * args.cap - 1).bit_length()))
        # segment output buffers must hold at least one chunk's worst-case
        # candidate stream (chunk * action fan-out)
        A = len(S.action_table(config.bounds, config.spec))
        seg_rows = max(1 << 19, 2 * args.chunk * A)
        if args.route and args.route > seg_rows:
            seg_rows = args.route
        eng = DDDEngine(config, DDDCapacities(
            block=args.block or 1 << 20, table=table, seg_rows=seg_rows,
            levels=args.levels, route_rows=args.route,
            retention=args.retention, keep_levels=args.keep_levels))
        return eng.check(on_progress=_stats_cb(args),
                         checkpoint=args.checkpoint,
                         checkpoint_every_s=args.checkpoint_every,
                         resume=args.resume,
                         deadline_s=args.deadline)
    if args.engine == "ddd-shard":
        from raft_tla_tpu.models import spec as S
        from raft_tla_tpu.parallel.ddd_shard_engine import (
            DDDShardCapacities, DDDShardEngine)
        mesh = _make_cli_mesh(args)
        nd = mesh.devices.size
        # per-shard filter share of the expected state count (traffic
        # only); per-shard output buffers must hold one chunk's
        # worst-case post-exchange stream (ndev * chunk * fan-out)
        A = len(S.action_table(config.bounds, config.spec))
        table = 1 << max(10, min(26, ((2 * args.cap + nd - 1) // nd - 1)
                                 .bit_length()))
        seg_rows = max(1 << 19, 2 * nd * args.chunk * A)
        blk = args.block or _ddd_shard_block(args.chunk)
        eng = DDDShardEngine(config, mesh, DDDShardCapacities(
            block=blk, table=table, seg_rows=seg_rows,
            levels=args.levels, cp=args.cp_lanes,
            retention=args.retention, keep_levels=args.keep_levels))
        return eng.check(on_progress=_stats_cb(args),
                         checkpoint=args.checkpoint,
                         checkpoint_every_s=args.checkpoint_every,
                         resume=args.resume)
    if args.engine == "shard":
        from raft_tla_tpu.parallel.shard_engine import (
            ShardCapacities, ShardEngine)
        mesh = _make_cli_mesh(args)
        eng = ShardEngine(config, mesh,
                          ShardCapacities(n_states=args.cap,
                                          levels=args.levels),
                          seg_chunks=args.seg_chunks)
        return eng.check(checkpoint=args.checkpoint,
                         checkpoint_every_s=args.checkpoint_every,
                         resume=args.resume, on_progress=_stats_cb(args))
    if args.engine == "pagedshard":
        from raft_tla_tpu.models import spec as S
        from raft_tla_tpu.parallel.paged_shard_engine import (
            PagedShardCapacities, PagedShardEngine)
        A = len(S.action_table(config.bounds, config.spec))
        # --cap is the expected distinct-state total across the mesh;
        # tables shard it, rings hold each device's live window share
        table = 1 << max(1, (2 * args.cap - 1).bit_length())
        mesh = _make_cli_mesh(args)
        nd = mesh.devices.size
        ring = args.ring if args.ring is not None else max(
            1 << min(22, max(12, (args.cap // (4 * nd)).bit_length())),
            1 << (2 * args.chunk * A - 1).bit_length())
        # per-device table share, rounded up to a power of two (the
        # bucket mask is bitwise)
        tbl_d = 1 << max(10, ((table + nd - 1) // nd - 1).bit_length())
        eng = PagedShardEngine(config, mesh, PagedShardCapacities(
            ring=ring, table=tbl_d, levels=args.levels))
        return eng.check(checkpoint=args.checkpoint,
                         checkpoint_every_s=args.checkpoint_every,
                         resume=args.resume, on_progress=_stats_cb(args))
    from raft_tla_tpu.device_engine import Capacities, DeviceEngine
    eng = DeviceEngine(config, Capacities(n_states=args.cap,
                                          levels=args.levels))
    return eng.check(checkpoint=args.checkpoint,
                     checkpoint_every_s=args.checkpoint_every,
                     resume=args.resume, on_progress=_stats_cb(args))


def _finish_run(args, p, config, props, model, b) -> int:
    """Run + report for non-Raft (frontend-compiled) specs: the shared
    tail of main() minus the Raft-only paths (liveness, reshard,
    simulate), with trace rendering routed through the model."""
    if args.reshard_to is not None:
        print(f"Error: --reshard-to is not supported for --spec "
              f"{args.spec}", file=sys.stderr)
        return EXIT_ERROR
    t0 = time.monotonic()
    try:
        result = _run(args, config)
    except Exception as e:
        print(f"Error: {e}", file=sys.stderr)
        return EXIT_ERROR
    wall = time.monotonic() - t0
    print(f"{result.n_states} distinct states found, diameter "
          f"{result.diameter}, {result.n_transitions} transitions, "
          f"{wall:.2f}s ({result.n_states / max(wall, 1e-9):,.0f} states/s).")
    if args.coverage:
        for fam, cnt in sorted(result.coverage.items()):
            print(f"  {fam}: {cnt} new states")
    if result.violation is None:
        if not result.complete:
            print("Model checking stopped before completion (state space "
                  "not exhausted); resume from the checkpoint to "
                  "continue.")
            return EXIT_STOPPED
        print("Model checking completed. No error has been found.")
        return EXIT_OK
    from raft_tla_tpu.engine import DEADLOCK
    is_deadlock = result.violation.invariant == DEADLOCK
    if args.no_trace:
        print("Error: Deadlock reached." if is_deadlock else
              f"Error: Invariant {result.violation.invariant} is violated.")
    else:
        print(model.render_trace(result.violation, b))
    return EXIT_DEADLOCK if is_deadlock else EXIT_VIOLATION


def main(argv=None) -> int:
    p = build_argparser()
    args = p.parse_args(argv)
    if args.prescan is not None:
        # Process-wide, BEFORE any step build: the gate is read at step-
        # construction time (ops/kernels._prescan_enabled), and liveness
        # re-runs build engines of their own.
        import os
        os.environ["RAFT_TLA_PRESCAN"] = args.prescan
    if args.sig_prune is not None:
        # Same contract as --prescan: resolved at step-construction time
        # (ops/kernels._sigprune_enabled) by every engine family.
        import os
        os.environ["RAFT_TLA_SIGPRUNE"] = args.sig_prune
    if args.megakernel is not None:
        # Same contract as --sig-prune: resolved at step-construction
        # time (ops/kernels._megakernel_enabled) by every engine family.
        import os
        os.environ["RAFT_TLA_MEGAKERNEL"] = args.megakernel
    if args.host_dedup is not None:
        # Same contract: resolved at engine construction
        # (utils/keyset.host_dedup_enabled) by the ddd engine families.
        import os
        os.environ["RAFT_TLA_HOSTDEDUP"] = args.host_dedup
    if args.prefetch is not None:
        # Same contract: resolved at engine construction
        # (utils/prefetch.prefetch_enabled) by the ddd engine families.
        import os
        os.environ["RAFT_TLA_PREFETCH"] = args.prefetch
    if args.device_dedup is not None:
        # Same contract: resolved at engine construction
        # (ops/devdedup.devdedup_backend) by the ddd engine families.
        import os
        os.environ["RAFT_TLA_DEVDEDUP"] = args.device_dedup
    from raft_tla_tpu.serve.sched import enable_compile_cache
    enable_compile_cache(args.compile_cache)
    _DEVICE_ENGINES = ("device", "paged", "streamed", "ddd", "shard",
                       "pagedshard", "ddd-shard")
    if args.view and args.simulate:
        p.error("--view does not compose with --simulate (random walks "
                "replay concrete states; a view only folds dedup keys)")
    if args.reshard_cap and not (args.reshard_to and
                                 args.engine == "shard"):
        p.error("--reshard-cap only applies to --reshard-to with "
                "--engine shard (the DDD snapshots carry no per-device "
                "store capacity); dropping it silently would ignore "
                "the configured rescue")
    if args.route and args.engine != "ddd":
        p.error(f"--route requires --engine ddd (got {args.engine}); "
                "the routed step is not built for other engines — "
                "dropping it silently would run a different program "
                "than configured")
    if args.route and args.megakernel == "on":
        p.error("--megakernel on does not compose with --route (the "
                "routed step's lane compaction is an XLA scatter between "
                "the megakernel's fused phases); use --route 0 or leave "
                "the megakernel gate auto/off")
    if (args.checkpoint or args.resume) and \
            args.engine not in _DEVICE_ENGINES:
        p.error(f"--checkpoint/--resume require a device-class engine "
                f"(got {args.engine}); other engines would silently "
                "ignore them")
    if args.deadline is not None and args.engine != "ddd":
        p.error(f"--deadline requires --engine ddd (got {args.engine}); "
                "only the ddd engine stops losslessly at a segment "
                "boundary — dropping it silently would run unbounded")
    if args.stats and args.engine not in _DEVICE_ENGINES:
        p.error(f"--stats requires a device-class engine "
                f"(got {args.engine})")
    if (args.events or args.phase_timers or args.trace) and \
            args.engine not in _DEVICE_ENGINES:
        p.error(f"--events/--phase-timers/--trace require a device-class "
                f"engine (got {args.engine}); other engines emit no run "
                "events")
    from raft_tla_tpu.obs.events import events_path
    if args.trace and not events_path(args.events):
        p.error("--trace requires --events PATH (spans are rows in the "
                "run-event log; without a log there is nowhere to put "
                "them)")
    if args.events or args.phase_timers or args.trace:
        # Process-wide, like --sig-prune: every engine an invocation
        # builds (including liveness re-runs) reads the same env gate.
        import os
        from raft_tla_tpu.obs.events import ENV_EVENTS
        from raft_tla_tpu.obs.phases import ENV_PHASE_TIMERS
        from raft_tla_tpu.obs.trace import ENV_TRACE
        if args.events:
            os.environ[ENV_EVENTS] = args.events
        if args.phase_timers:
            os.environ[ENV_PHASE_TIMERS] = "1"
        if args.trace:
            os.environ[ENV_TRACE] = "1"
    try:
        config, props = _resolve_config(args)
    except (OSError, ValueError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return EXIT_ERROR
    from raft_tla_tpu.frontend import resolve_model
    model = resolve_model(args.spec)
    if not model.is_raft and args.engine not in model.engines:
        p.error(f"--engine {args.engine} does not support spec "
                f"{args.spec!r} (supported: {', '.join(model.engines)})")
    if args.simulate is not None and "simulate" not in model.engines:
        p.error(f"--simulate is not supported by spec {args.spec!r} "
                f"(supported engines: {', '.join(model.engines)})")
    if args.fleet and args.simulate is None:
        p.error("--fleet requires --simulate N (fleets are a "
                "simulation-mode engine)")
    if args.steer and not args.fleet:
        p.error("--steer requires --fleet (coverage steering lives in "
                "the sharded fleet engine)")
    if args.fault_weights and not args.fleet:
        p.error("--fault-weights requires --fleet")

    if not args.no_lint:
        # Width-safety (analysis Pass 1) before any step build: for these
        # exact bounds, no transition can write a value the bit-pack would
        # truncate.  Warn-only by default — the proof failing means the
        # analyzer and kernels disagree, which deserves eyes, not a wall —
        # but --lint strict turns any finding into a hard stop.  Non-Raft
        # models route to their schema validity gate.
        from raft_tla_tpu.analysis import report as _report
        try:
            _lint = model.check_widths(config.bounds)
        except Exception as e:      # analyzer bug: report, don't block
            _lint = [_report.Finding(
                _report.WIDTH, _report.ERROR, "lint-internal-error",
                f"width pass crashed: {e!r}")]
        if _lint:
            print(_report.render(
                _lint, header="speclint (width pass):"), file=sys.stderr)
            if args.lint == "strict":
                return EXIT_ERROR

    b = config.bounds
    if not model.is_raft:
        print(f"raft_tla_tpu {__import__('raft_tla_tpu').__version__} — "
              f"exhaustive check of spec {args.spec} (frontend-compiled)")
        print(f"Universe: {b.n_servers} resource managers "
              f"(from {args.cfg})")
        print(f"Invariants: {', '.join(config.invariants) or '(none)'}")
        if args.emit_tlc:
            try:
                tla, cfgp = model.emit_tla(args.emit_tlc, b,
                                           config.invariants)
            except (OSError, ValueError) as e:
                print(f"Error: {e}", file=sys.stderr)
                return EXIT_ERROR
            print(f"TLC parity artifacts: {tla}, {cfgp}")
        if args.simulate is not None:
            if props:
                print(f"Error: PROPERTY {list(props)} cannot be checked "
                      "in --simulate mode (liveness needs exhaustive "
                      "search)", file=sys.stderr)
                return EXIT_ERROR
            _force_cpu(args)
            try:
                return _simulate(args, config)
            except Exception as e:
                print(f"Error: {e}", file=sys.stderr)
                return EXIT_ERROR
        return _finish_run(args, p, config, props, model, b)
    print(f"raft_tla_tpu {__import__('raft_tla_tpu').__version__} — "
          f"exhaustive check of Spec (raft.tla:469), subset: {args.spec}")
    print(f"Universe: {b.n_servers} servers, {b.n_values} values "
          f"(from {args.cfg})")
    print(f"Constraint: MaxTerm={b.max_term} MaxLogLen={b.max_log} "
          f"MaxMsgs={b.max_msgs} MaxDup={b.max_dup}")
    if b.history:
        print("Faithful mode: history variables (elections/allLogs/"
              f"voterLog/mlog) carried; elections capacity {b.max_elections}")
    print(f"Invariants: {', '.join(config.invariants) or '(none)'}")
    if config.symmetry:
        print(f"Symmetry: {' x '.join(config.symmetry)} permutations "
              "(counting orbits)")
    if config.view:
        # registered views are EXACT (bisimulations, models/views.py),
        # so the view quotient is transition-faithful and liveness on
        # it is sound for the view-invariant registered predicates —
        # see the lift argument in liveness.ddd_graph
        print(f"View: {config.view} (counting view-quotient states)")

    if args.emit_tlc:
        from raft_tla_tpu.models import tla_export
        try:
            tla, cfgp = tla_export.export(args.emit_tlc, b,
                                          config.invariants,
                                          parity_view=not b.history,
                                          symmetry=config.symmetry,
                                          view=config.view,
                                          spec=config.spec,
                                          properties=tuple(props),
                                          wf=_parse_wf(args))
        except (OSError, ValueError) as e:
            print(f"Error: {e}", file=sys.stderr)
            return EXIT_ERROR
        print(f"TLC parity artifacts: {tla}, {cfgp}")

    if args.simulate is not None:
        if props:
            # Liveness needs the full behavior graph; sampling cannot check
            # it — reject rather than silently report OK.
            print(f"Error: PROPERTY {list(props)} cannot be checked in "
                  "--simulate mode (liveness needs exhaustive search)",
                  file=sys.stderr)
            return EXIT_ERROR
        _force_cpu(args)
        try:
            return _simulate(args, config)
        except Exception as e:
            print(f"Error: {e}", file=sys.stderr)
            return EXIT_ERROR

    if args.reshard_to is not None:
        if args.engine not in ("shard", "ddd", "ddd-shard"):
            print("Error: --reshard-to requires --engine shard, ddd or "
                  "ddd-shard", file=sys.stderr)
            return EXIT_ERROR
        if not args.resume or not args.checkpoint:
            print("Error: --reshard-to needs --resume SRC and "
                  "--checkpoint DST", file=sys.stderr)
            return EXIT_ERROR
        _force_cpu(args)
        if args.engine == "shard":
            from raft_tla_tpu.parallel.shard_engine import (
                ShardCapacities, reshard_checkpoint)
            caps_src = ShardCapacities(n_states=args.cap,
                                       levels=args.levels)
            caps_dst = ShardCapacities(
                n_states=args.reshard_cap,
                levels=args.levels) if args.reshard_cap else None
            try:
                info = reshard_checkpoint(
                    config, caps_src, args.resume, args.checkpoint,
                    args.reshard_to, caps_dst=caps_dst)
            except Exception as e:
                print(f"Error: {e}", file=sys.stderr)
                return EXIT_ERROR
            print(f"resharded {info['ndev_src']} -> {info['ndev_dst']} "
                  f"devices: {info['n_states']} states, per-device "
                  f"{info['per_device']}, window {info['window']} -> "
                  f"{args.checkpoint}")
            return EXIT_OK
        # DDD family: the streams are mesh-independent history; only
        # window accounting + digest change.  Source geometry is what
        # this CLI itself would run: single-chip ddd uses block 2^20
        # with ndev=1; ddd-shard derives its block from --chunk and its
        # mesh size from --devices.  The destination block preserves the
        # GLOBAL window size, so every snapshot boundary is shared.
        from raft_tla_tpu.parallel.ddd_shard_engine import (
            DDDShardCapacities, reshard_ddd_checkpoint)
        if args.engine == "ddd":
            ndev_src, blk_src = 1, args.block or 1 << 20
        else:
            if not args.devices:
                print("Error: ddd-shard reshard needs --devices "
                      "(the source mesh size)", file=sys.stderr)
                return EXIT_ERROR
            ndev_src = args.devices
            blk_src = args.block or _ddd_shard_block(args.chunk)
        # CP-mode windows are block rows regardless of mesh size (the
        # window replicates), so the window math is ndev-independent
        cp = args.engine == "ddd-shard" and args.cp_lanes
        w_src = blk_src if cp else ndev_src * blk_src
        # destination block: prefer preserving the GLOBAL window size
        # (every snapshot boundary shared), else keep the source block;
        # either way it must be chunk-aligned or the mesh engine would
        # reject the digest-pinned block at resume — refuse loudly here
        # instead of writing an unusable snapshot
        cand = [blk_src] if cp else (
            ([w_src // args.reshard_to]
             if w_src % args.reshard_to == 0 else []) + [blk_src])
        blk_dst = next((b for b in cand
                        if b > 0 and b % args.chunk == 0), None)
        if blk_dst is None:
            print(f"Error: neither {cand} rows is a multiple of "
                  f"--chunk {args.chunk}; no chunk-aligned destination "
                  "block preserves the source window boundaries — use a "
                  "chunk that divides the source window (power-of-two "
                  "chunks always do)", file=sys.stderr)
            return EXIT_ERROR
        try:
            info = reshard_ddd_checkpoint(
                config,
                DDDShardCapacities(block=blk_src, levels=args.levels,
                                   cp=cp),
                args.resume, args.checkpoint, ndev_src, args.reshard_to,
                caps_dst=DDDShardCapacities(block=blk_dst,
                                            levels=args.levels, cp=cp))
        except Exception as e:
            print(f"Error: {e}", file=sys.stderr)
            return EXIT_ERROR
        print(f"resharded DDD {info['ndev_src']} -> {info['ndev_dst']} "
              f"devices: {info['n_states']} states, "
              f"{info['rows_done']} frontier rows done "
              f"({info['blocks_done_dst']} windows) -> "
              f"{args.checkpoint}  [resume with --engine ddd-shard "
              f"--devices {info['ndev_dst']} --block {blk_dst}"
              f"{' --cp-lanes' if cp else ''}]")
        return EXIT_OK

    t0 = time.monotonic()
    try:
        result = _run(args, config)
    except Exception as e:
        print(f"Error: {e}", file=sys.stderr)
        return EXIT_ERROR
    wall = time.monotonic() - t0

    print(f"{result.n_states} distinct states found, diameter "
          f"{result.diameter}, {result.n_transitions} transitions, "
          f"{wall:.2f}s ({result.n_states / max(wall, 1e-9):,.0f} states/s).")
    if args.coverage:
        for fam, cnt in sorted(result.coverage.items()):
            print(f"  {fam}: {cnt} new states")

    if result.violation is None and not result.complete:
        # A lossless stop (SIGINT, --deadline, capacity policy): no
        # verdict was reached, so neither "no error found" nor liveness
        # (which needs the full graph) may be claimed.
        print("Model checking stopped before completion (state space "
              "not exhausted); resume from the checkpoint to continue.")
        return EXIT_STOPPED
    if result.violation is None and props:
        code = _check_liveness(args, config, props)
        if code != EXIT_OK:
            return code
    if result.violation is None:
        print("Model checking completed. No error has been found.")
        return EXIT_OK
    from raft_tla_tpu.engine import DEADLOCK
    is_deadlock = result.violation.invariant == DEADLOCK
    if args.no_trace:
        print("Error: Deadlock reached." if is_deadlock else
              f"Error: Invariant {result.violation.invariant} is violated.")
    else:
        from raft_tla_tpu.utils.render import render_trace
        print(render_trace(result.violation, b))
    return EXIT_DEADLOCK if is_deadlock else EXIT_VIOLATION


def _parse_wf(args) -> tuple:
    """--wf families; 'none' = no fairness (the raw reference Spec).
    One definition for the checker AND the TLC twin emitter, so the
    emitted FairSpec always encodes the same fairness as the verdict."""
    if args.wf.strip().lower() == "none":
        return ()
    return tuple(f.strip() for f in args.wf.split(",") if f.strip())


def _check_liveness(args, config, props) -> int:
    from raft_tla_tpu.models import liveness
    from raft_tla_tpu.utils.render import render_state

    wf = _parse_wf(args)
    # Build the behavior graph once for all properties.  Symmetric runs
    # and the DDD engines use the DDD-store export (orbit-quotient
    # soundness argument in liveness.ddd_graph; no device-table
    # ceiling); other device engines keep the device_engine export; host
    # engines use the interpreter.
    try:
        if args.engine in ("host", "ref") and not config.view:
            graph = liveness.explore_graph(config)
        elif config.view or config.symmetry or args.engine in (
                "ddd", "ddd-shard", "streamed"):
            from raft_tla_tpu.ddd_engine import DDDCapacities
            from raft_tla_tpu.models import spec as S
            if config.symmetry:
                print("Symmetry: liveness runs on the orbit-quotient "
                      "graph (exact for the registered properties — "
                      "models/liveness.ddd_graph); the lasso, if any, "
                      "is a quotient witness")
            A = len(S.action_table(config.bounds, config.spec))
            graph = liveness.ddd_graph(config, DDDCapacities(
                block=args.block or 1 << 20,
                table=1 << max(10, min(26, (2 * args.cap - 1)
                                       .bit_length())),
                seg_rows=max(1 << 19, 2 * args.chunk * A),
                levels=args.levels))
        else:
            from raft_tla_tpu.device_engine import Capacities
            graph = liveness.engine_graph(config, Capacities(
                n_states=args.cap, levels=args.levels))
    except (ValueError, RuntimeError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return EXIT_ERROR
    try:
        return _report_liveness(args, config, props, wf, graph)
    finally:
        if isinstance(graph[0], liveness.StatesView):
            graph[0].close()        # the retained DDD host store


def _report_liveness(args, config, props, wf, graph) -> int:
    from raft_tla_tpu.models import liveness
    from raft_tla_tpu.utils.render import render_state

    for nm in props:
        t0 = time.monotonic()
        try:
            res = liveness.check(config, nm, wf=wf, graph=graph)
        except ValueError as e:
            print(f"Error: {e}", file=sys.stderr)
            return EXIT_ERROR
        wall = time.monotonic() - t0
        pspec = liveness.parse_property(nm)
        shape = f"{pspec.pred_names[0]} ~> {pspec.pred_names[1]}" \
            if pspec.form == liveness.LEADS_TO \
            else f"{pspec.form}{pspec.pred_names[0]}"
        shape_txt = f" ({shape})" if shape != nm else ""
        wf_txt = ", ".join(wf) if wf else "no fairness (raw Spec)"
        print(f"Property {nm}{shape_txt} under WF({wf_txt}): "
              f"{res.n_states} states, {res.n_edges} transitions, "
              f"{wall:.2f}s.")
        if res.holds:
            print(f"Property {nm} is satisfied.")
            continue
        print(f"Error: Property {nm} is violated.")
        if not args.no_trace:
            print("Error: The following behavior, repeated forever, "
                  "refutes it:")
            v = res.violation
            for k, (label, state) in enumerate(v.prefix, start=1):
                head = "<Initial predicate>" if label is None                     else f"<{label}>"
                print(f"State {k}: {head}")
                print(render_state(state, config.bounds))
            n0 = len(v.prefix)
            for k, (label, state) in enumerate(v.cycle, start=n0 + 1):
                print(f"State {k}: <{label}>  (loop)")
                print(render_state(state, config.bounds))
            print(f"(the loop returns to State {n0 + 1})")
        return EXIT_LIVENESS
    return EXIT_OK


def entry() -> None:
    """Console-script entry point (pyproject ``raft-tla-check``)."""
    sys.exit(main())


if __name__ == "__main__":
    entry()
