"""Reference interpreter — a direct Python reading of ``raft.tla:99-465``.

This is oracle #2 of the test strategy (SURVEY §4): a deliberately
straight-line, un-optimized transcription of the spec's guards and effects
over hashable Python states.  The batched JAX kernels (ops/kernels.py) are
differentially tested against it action-instance by action-instance, and the
BFS engine's reachable-set counts must match its exhaustive enumeration.
Stock TLC (once a JVM is available) is oracle #1 via models/tla_export.py.

Parity mode (default): the proof-only history variables — ``elections``
(raft.tla:39), ``allLogs`` (raft.tla:44), ``voterLog`` (raft.tla:77), and the
``mlog`` message fields (raft.tla:220-222, 297-299) — are stripped on both
sides of every comparison (SURVEY §7.0.3).  No guard reads them, so the
transition *behaviour* is unchanged; only state identity coarsens.

Faithful mode (``Bounds.history``): the history variables are carried as
real state, exactly as stock TLC fingerprints them on the unmodified spec —
``allLogs' = allLogs \\cup {log[i] : i \\in Server}`` conjoined (with the
*unprimed* logs) onto every step (raft.tla:464-465), ``voterLog`` rows
cleared by Restart/Timeout (raft.tla:171,186) and extended by granted vote
responses via ``@@`` (keep-existing, raft.tla:316-317), ``elections``
accumulated by BecomeLeader (raft.tla:237-242), and ``mlog`` carried in
RequestVoteResponse/AppendEntriesRequest records as log-universe ranks
(ops/loguniv.py).  History-based invariants (ElectionSafetyHist,
LeaderCompletenessHist, AllLogsPrefixClosed) read them.

Messages use the same packed (hi, lo) content words as the tensor encoding
(ops/msgbits.py) so slot ordering, bag equality, and packing agree with the
kernels by construction; constructors/accessors keep the record semantics
readable.
"""

from __future__ import annotations

import dataclasses
import functools as _functools
from typing import Iterator, Optional

import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import spec as S
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.ops import state as st


@dataclasses.dataclass(frozen=True)
class PyState:
    """One state of the (parity-mode) spec; all fields hashable tuples.

    ``log`` is a tuple per server of (term, value) pairs (``raft.tla:61``);
    ``vResp``/``vGrant`` are bitmask ints over servers (``raft.tla:69,72``);
    ``msgs`` is the bag (``raft.tla:32``) as a tuple of ((hi, lo), count)
    sorted by (hi, lo) — the canonical slot order of the tensor encoding.
    """

    role: tuple
    term: tuple
    votedFor: tuple      # 0 = Nil, else server id + 1
    commitIndex: tuple
    log: tuple           # per server: tuple[(term, value), ...]
    vResp: tuple         # bitmask
    vGrant: tuple        # bitmask
    nextIndex: tuple     # per server: tuple[int, ...]
    matchIndex: tuple
    msgs: tuple          # sorted tuple[((hi, lo), count), ...]
    # Faithful mode only (None in parity mode; SURVEY §7.0.3b):
    allLogs: tuple = None    # sorted tuple of logs ever seen (raft.tla:44)
    vLog: tuple = None       # voterLog[i][j]: log tuple or None (raft.tla:77)
    elections: tuple = None  # sorted (eterm, eleader, elog, evotes, evoterLog)

    def _replace(self, **kw) -> "PyState":
        return dataclasses.replace(self, **kw)


def init_state(bounds: Bounds) -> PyState:
    """``Init`` (raft.tla:155-160): the unique initial state."""
    n = bounds.n_servers
    hist = {}
    if bounds.history:
        # InitHistoryVars (raft.tla:140-142): empty set, empty set, empty maps.
        hist = dict(allLogs=(), vLog=((None,) * n,) * n, elections=())
    return PyState(
        role=(S.FOLLOWER,) * n,
        term=(1,) * n,                      # InitServerVars, raft.tla:143
        votedFor=(S.NIL,) * n,
        commitIndex=(0,) * n,
        log=((),) * n,                      # InitLogVars, raft.tla:153-154
        vResp=(0,) * n,
        vGrant=(0,) * n,                    # InitCandidateVars, raft.tla:146-147
        nextIndex=((1,) * n,) * n,          # InitLeaderVars, raft.tla:151-152
        matchIndex=((0,) * n,) * n,
        msgs=(),                            # raft.tla:155
        **hist,
    )


# -- helpers (raft.tla:99-135) ----------------------------------------------

def last_term(log: tuple) -> int:
    """``LastTerm(xlog)`` (raft.tla:102)."""
    return log[-1][0] if log else 0


def quorum(mask: int, n: int) -> bool:
    """``votesGranted[i] \\in Quorum`` (raft.tla:99) as a popcount test."""
    return 2 * mask.bit_count() > n


def with_message(m: tuple, msgs: tuple) -> tuple:
    """``WithMessage`` (raft.tla:106-110): bag insert, canonical order kept."""
    d = dict(msgs)
    d[m] = d.get(m, 0) + 1
    return tuple(sorted(d.items()))


def without_message(m: tuple, msgs: tuple) -> tuple:
    """``WithoutMessage`` (raft.tla:114-119): bag remove (no-op if absent)."""
    d = dict(msgs)
    if m in d:
        if d[m] <= 1:
            del d[m]
        else:
            d[m] -= 1
    return tuple(sorted(d.items()))


def _upd(t: tuple, i: int, v) -> tuple:
    return t[:i] + (v,) + t[i + 1:]


# -- faithful-mode helpers (history variables, SURVEY §7.0.3b) ---------------

def _log_key(log: tuple) -> tuple:
    """Sort key matching log-universe rank order (ops/loguniv.py): by
    length, then lexicographically by entries — entry codes are
    lex-increasing in (term, value), so plain tuple comparison agrees."""
    return (len(log), log)


def _opt_log_key(log) -> tuple:
    """Key matching rank+1 order (0 = absent sorts first)."""
    return (0,) if log is None else (1,) + _log_key(log)


def _election_key(rec: tuple) -> tuple:
    """Canonical election-slot order: must match ops/state.canonicalize."""
    eterm, eleader, elog, evotes, evlog = rec
    return (eterm, eleader, _log_key(elog), evotes,
            tuple(_opt_log_key(l) for l in evlog))


def _clear_vlog_row(s: "PyState", i: int, n: int) -> dict:
    """``voterLog' = [voterLog EXCEPT ![i] = empty map]`` (raft.tla:171,186)."""
    if s.vLog is None:
        return {}
    return {"vLog": _upd(s.vLog, i, (None,) * n)}


# -- actions (raft.tla:167-276); return None when the guard is disabled ------

def restart(s: PyState, i: int, n: int) -> PyState:
    """``Restart(i)`` (raft.tla:167-175): crash-recover from stable storage.

    Keeps currentTerm/votedFor/log (and messages); resets role to Follower,
    vote sets, nextIndex -> 1, matchIndex -> 0, commitIndex -> 0.
    """
    return s._replace(
        role=_upd(s.role, i, S.FOLLOWER),
        vResp=_upd(s.vResp, i, 0),
        vGrant=_upd(s.vGrant, i, 0),
        nextIndex=_upd(s.nextIndex, i, (1,) * n),
        matchIndex=_upd(s.matchIndex, i, (0,) * n),
        commitIndex=_upd(s.commitIndex, i, 0),
        **_clear_vlog_row(s, i, n),
    )


def timeout(s: PyState, i: int) -> Optional[PyState]:
    """``Timeout(i)`` (raft.tla:178-187): start an election.

    Becomes Candidate with term+1 but does *not* vote for itself —
    self-voting goes through the network (raft.tla:181-183).
    """
    if s.role[i] not in (S.FOLLOWER, S.CANDIDATE):
        return None
    return s._replace(
        role=_upd(s.role, i, S.CANDIDATE),
        term=_upd(s.term, i, s.term[i] + 1),
        votedFor=_upd(s.votedFor, i, S.NIL),
        vResp=_upd(s.vResp, i, 0),
        vGrant=_upd(s.vGrant, i, 0),
        **_clear_vlog_row(s, i, len(s.role)),
    )


def request_vote(s: PyState, i: int, j: int) -> Optional[PyState]:
    """``RequestVote(i, j)`` (raft.tla:190-199); j may equal i (raft.tla:456)."""
    if s.role[i] != S.CANDIDATE or (s.vResp[i] >> j) & 1:
        return None
    m = mb.rv_request(s.term[i], last_term(s.log[i]), len(s.log[i]), i, j)
    return s._replace(msgs=with_message(m, s.msgs))


def append_entries(s: PyState, i: int, j: int, uni=None) -> Optional[PyState]:
    """``AppendEntries(i, j)`` (raft.tla:204-226): <=1 entry from nextIndex.

    Also the heartbeat (empty ``mentries`` when nextIndex is past the log);
    piggybacks ``mcommitIndex = Min(commitIndex[i], lastEntry)`` (raft.tla:223).
    In faithful mode the record carries ``mlog = log[i]`` as a universe rank
    (raft.tla:220-222).
    """
    if i == j or s.role[i] != S.LEADER:
        return None
    log_i = s.log[i]
    ni = s.nextIndex[i][j]
    prev_idx = ni - 1
    prev_term = log_i[prev_idx - 1][0] if prev_idx > 0 else 0
    last_entry = min(len(log_i), ni)
    if ni <= last_entry:
        n_ent, ent_term, ent_val = 1, log_i[ni - 1][0], log_i[ni - 1][1]
    else:
        n_ent, ent_term, ent_val = 0, 0, 0
    mlog = uni.id_of_tuple(log_i) if uni is not None else 0
    m = mb.ae_request(s.term[i], prev_idx, prev_term, n_ent, ent_term, ent_val,
                      min(s.commitIndex[i], last_entry), i, j, mlog)
    return s._replace(msgs=with_message(m, s.msgs))


def become_leader(s: PyState, i: int, n: int) -> Optional[PyState]:
    """``BecomeLeader(i)`` (raft.tla:229-243).

    In faithful mode also records the election into the ``elections``
    history set (raft.tla:237-242): [eterm, eleader, elog, evotes,
    evoterLog], all from the unprimed state.
    """
    if s.role[i] != S.CANDIDATE or not quorum(s.vGrant[i], n):
        return None
    hist = {}
    if s.elections is not None:
        rec = (s.term[i], i, s.log[i], s.vGrant[i], s.vLog[i])
        recs = set(s.elections) | {rec}
        hist = {"elections": tuple(sorted(recs, key=_election_key))}
    return s._replace(
        role=_upd(s.role, i, S.LEADER),
        nextIndex=_upd(s.nextIndex, i, (len(s.log[i]) + 1,) * n),
        matchIndex=_upd(s.matchIndex, i, (0,) * n),
        **hist,
    )


def client_request(s: PyState, i: int, v: int) -> Optional[PyState]:
    """``ClientRequest(i, v)`` (raft.tla:246-253): leader appends locally."""
    if s.role[i] != S.LEADER:
        return None
    return s._replace(log=_upd(s.log, i, s.log[i] + ((s.term[i], v),)))


def advance_commit_index(s: PyState, i: int, n: int) -> Optional[PyState]:
    """``AdvanceCommitIndex(i)`` (raft.tla:259-276).

    Commits ``Max(agreeIndexes)`` only when that entry is from the current
    term — the current-term-commit restriction (raft.tla:268-270).  Note the
    term test applies to the *max* agree index only.
    """
    if s.role[i] != S.LEADER:
        return None
    log_i = s.log[i]
    agree_indexes = [
        idx for idx in range(1, len(log_i) + 1)
        if 2 * len({i} | {k for k in range(n) if s.matchIndex[i][k] >= idx}) > n
    ]
    if agree_indexes and log_i[max(agree_indexes) - 1][0] == s.term[i]:
        new_commit = max(agree_indexes)
    else:
        new_commit = s.commitIndex[i]
    return s._replace(commitIndex=_upd(s.commitIndex, i, new_commit))


# -- message handlers (raft.tla:284-418), dispatched by receive --------------

def _handle_request_vote_request(s, i, j, m_hi, m_lo, uni=None):
    """``HandleRequestVoteRequest`` (raft.tla:284-303), mterm <= currentTerm."""
    mt = mb.mterm(m_hi)
    log_ok = (mb.fa(m_hi) > last_term(s.log[i])
              or (mb.fa(m_hi) == last_term(s.log[i])
                  and mb.fb(m_hi) >= len(s.log[i])))       # raft.tla:285-287
    grant = (mt == s.term[i] and log_ok
             and s.votedFor[i] in (S.NIL, j + 1))           # raft.tla:288-290
    mlog = uni.id_of_tuple(s.log[i]) if uni is not None else 0
    resp = mb.rv_response(s.term[i], int(grant), i, j, mlog)  # mlog :297-299
    msgs = without_message((m_hi, m_lo), with_message(resp, s.msgs))  # Reply :129-130
    out = s._replace(msgs=msgs)
    if grant:
        out = out._replace(votedFor=_upd(s.votedFor, i, j + 1))  # raft.tla:292
    return out


def _handle_request_vote_response(s, i, j, m_hi, m_lo, uni=None):
    """``HandleRequestVoteResponse`` (raft.tla:307-321), mterm = currentTerm.

    Tallies even when i is not a Candidate (harmless, raft.tla:308-309).
    In faithful mode a granted vote extends ``voterLog[i]`` with
    ``j :> m.mlog`` via ``@@`` — the *existing* entry wins on a duplicated
    response (raft.tla:316-317).
    """
    out = s._replace(vResp=_upd(s.vResp, i, s.vResp[i] | (1 << j)))
    if mb.fa(m_hi):                                          # mvoteGranted
        out = out._replace(vGrant=_upd(out.vGrant, i, out.vGrant[i] | (1 << j)))
        if uni is not None and s.vLog[i][j] is None:
            row = _upd(s.vLog[i], j, uni.tuple_of_id(mb.fg(m_lo)))
            out = out._replace(vLog=_upd(s.vLog, i, row))
    return out._replace(msgs=without_message((m_hi, m_lo), s.msgs))


def _handle_append_entries_request(s, i, j, m_hi, m_lo):
    """``HandleAppendEntriesRequest`` (raft.tla:327-389), mterm <= currentTerm.

    Three-way outer branch (reject / candidate-step-down / accept), with the
    accept case split into already-done / conflict-truncate-one / append
    (raft.tla:356-388).  The conflict and append branches *keep* the request
    in the bag, producing the spec's multi-step convergence loop (SURVEY §2.6).
    A Leader receiving a same-term request enables no branch (unreachable
    under Election Safety, but arbitrary differential-test states hit it).
    """
    mt = mb.mterm(m_hi)
    prev_idx, prev_term = mb.fa(m_hi), mb.fb(m_hi)
    n_ent, ent_term, ent_val = mb.fc(m_lo), mb.fd(m_lo), mb.fe(m_lo)
    log_i = s.log[i]
    log_ok = (prev_idx == 0
              or (0 < prev_idx <= len(log_i)
                  and prev_term == log_i[prev_idx - 1][0]))  # raft.tla:328-331
    # reject (raft.tla:333-345)
    if mt < s.term[i] or (mt == s.term[i] and s.role[i] == S.FOLLOWER
                          and not log_ok):
        resp = mb.ae_response(s.term[i], 0, 0, i, j)
        return s._replace(
            msgs=without_message((m_hi, m_lo), with_message(resp, s.msgs)))
    # return to follower state (raft.tla:346-350); message kept
    if mt == s.term[i] and s.role[i] == S.CANDIDATE:
        return s._replace(role=_upd(s.role, i, S.FOLLOWER))
    # accept (raft.tla:351-388)
    if mt == s.term[i] and s.role[i] == S.FOLLOWER and log_ok:
        index = prev_idx + 1
        if n_ent == 0 or (len(log_i) >= index
                          and log_i[index - 1][0] == ent_term):
            # already done with request (raft.tla:356-374); commitIndex may
            # DECREASE on an old duplicated request (raft.tla:361-363).
            resp = mb.ae_response(s.term[i], 1, prev_idx + n_ent, i, j)
            return s._replace(
                commitIndex=_upd(s.commitIndex, i, mb.ff(m_lo)),
                msgs=without_message((m_hi, m_lo),
                                     with_message(resp, s.msgs)))
        if len(log_i) >= index and log_i[index - 1][0] != ent_term:
            # conflict: remove exactly one entry off the TAIL (raft.tla:375-382)
            return s._replace(log=_upd(s.log, i, log_i[:-1]))
        if len(log_i) == prev_idx:
            # no conflict: append entry (raft.tla:383-388)
            return s._replace(
                log=_upd(s.log, i, log_i + ((ent_term, ent_val),)))
    return None


def _handle_append_entries_response(s, i, j, m_hi, m_lo):
    """``HandleAppendEntriesResponse`` (raft.tla:393-403), mterm = currentTerm."""
    if mb.fa(m_hi):  # msuccess
        match = mb.fb(m_hi)
        nexti = _upd(s.nextIndex[i], j, match + 1)
        matchi = _upd(s.matchIndex[i], j, match)
        out = s._replace(nextIndex=_upd(s.nextIndex, i, nexti),
                         matchIndex=_upd(s.matchIndex, i, matchi))
    else:
        nexti = _upd(s.nextIndex[i], j, max(s.nextIndex[i][j] - 1, 1))
        out = s._replace(nextIndex=_upd(s.nextIndex, i, nexti))
    return out._replace(msgs=without_message((m_hi, m_lo), s.msgs))


def receive(s: PyState, slot: int, uni=None) -> Optional[PyState]:
    """``Receive(m)`` (raft.tla:421-436) on the slot-th canonical bag element.

    The guards partition on mterm vs currentTerm[i] (>, =, <), so dispatch is
    deterministic per message; all nondeterminism is in *which* slot is picked
    (SURVEY §2.6).
    """
    if slot >= len(s.msgs):
        return None
    (m_hi, m_lo), _count = s.msgs[slot]
    i, j = mb.dst(m_hi), mb.src(m_hi)
    mt, mty = mb.mterm(m_hi), mb.mtype(m_hi)
    if mt > s.term[i]:
        # UpdateTerm (raft.tla:406-412): adopt term, -> Follower; message is
        # NOT consumed, so it is reprocessed in a later step (raft.tla:411-412).
        return s._replace(term=_upd(s.term, i, mt),
                          role=_upd(s.role, i, S.FOLLOWER),
                          votedFor=_upd(s.votedFor, i, S.NIL))
    if mty == S.M_RVREQ:
        return _handle_request_vote_request(s, i, j, m_hi, m_lo, uni)
    if mty == S.M_RVRESP:
        if mt < s.term[i]:  # DropStaleResponse (raft.tla:415-418)
            return s._replace(msgs=without_message((m_hi, m_lo), s.msgs))
        return _handle_request_vote_response(s, i, j, m_hi, m_lo, uni)
    if mty == S.M_AEREQ:
        return _handle_append_entries_request(s, i, j, m_hi, m_lo)
    if mty == S.M_AERESP:
        if mt < s.term[i]:  # DropStaleResponse (raft.tla:415-418)
            return s._replace(msgs=without_message((m_hi, m_lo), s.msgs))
        return _handle_append_entries_response(s, i, j, m_hi, m_lo)
    return None


def duplicate_message(s: PyState, slot: int) -> Optional[PyState]:
    """``DuplicateMessage(m)`` (raft.tla:443-445): network duplication fault."""
    if slot >= len(s.msgs):
        return None
    return s._replace(msgs=with_message(s.msgs[slot][0], s.msgs))


def drop_message(s: PyState, slot: int) -> Optional[PyState]:
    """``DropMessage(m)`` (raft.tla:448-450): network loss fault."""
    if slot >= len(s.msgs):
        return None
    return s._replace(msgs=without_message(s.msgs[slot][0], s.msgs))


# -- successor enumeration (Next, raft.tla:454-465) --------------------------

@_functools.lru_cache(maxsize=None)
def _uni(bounds: Bounds):
    from raft_tla_tpu.ops.loguniv import LogUniverse
    return LogUniverse.of(bounds)


def apply_action(s: PyState, a: S.ActionInstance, bounds: Bounds
                 ) -> Optional[PyState]:
    n = bounds.n_servers
    uni = _uni(bounds) if bounds.history else None
    if a.family == S.RESTART:
        out = restart(s, a.i, n)
    elif a.family == S.TIMEOUT:
        out = timeout(s, a.i)
    elif a.family == S.REQUESTVOTE:
        out = request_vote(s, a.i, a.j)
    elif a.family == S.BECOMELEADER:
        out = become_leader(s, a.i, n)
    elif a.family == S.CLIENTREQUEST:
        out = client_request(s, a.i, a.v)
    elif a.family == S.ADVANCECOMMIT:
        out = advance_commit_index(s, a.i, n)
    elif a.family == S.APPENDENTRIES:
        out = append_entries(s, a.i, a.j, uni)
    elif a.family == S.RECEIVE:
        out = receive(s, a.slot, uni)
    elif a.family == S.DUPLICATE:
        out = duplicate_message(s, a.slot)
    elif a.family == S.DROP:
        out = drop_message(s, a.slot)
    else:
        raise AssertionError(a.family)
    if out is not None and bounds.history:
        # allLogs' = allLogs \cup {log[i] : i \in Server} — conjoined onto
        # EVERY Next disjunct with the *unprimed* logs (raft.tla:464-465).
        new = set(s.allLogs) | set(s.log)
        out = out._replace(allLogs=tuple(sorted(new, key=_log_key)))
    return out


def successors(s: PyState, bounds: Bounds, table=None, spec: str = "full"
               ) -> Iterator[tuple]:
    """Yield (action_index, successor) for every enabled ``Next`` disjunct."""
    if table is None:
        table = S.action_table(bounds, spec)
    for idx, a in enumerate(table):
        nxt = apply_action(s, a, bounds)
        if nxt is not None:
            yield idx, nxt


# -- struct/vector bridge (for differentials & trace replay) -----------------

def to_struct(s: PyState, bounds: Bounds) -> dict:
    """PyState -> numpy struct (ops/state.py layout), canonical by construction."""
    lay = st.Layout.of(bounds)
    n, L, Sc = lay.n, lay.L, lay.S
    out = st.init_struct(bounds, np)
    out["role"] = np.array(s.role, np.int32)
    out["term"] = np.array(s.term, np.int32)
    out["votedFor"] = np.array(s.votedFor, np.int32)
    out["commitIndex"] = np.array(s.commitIndex, np.int32)
    out["logLen"] = np.array([len(l) for l in s.log], np.int32)
    lt = np.zeros((n, L), np.int32)
    lv = np.zeros((n, L), np.int32)
    for i, l in enumerate(s.log):
        if len(l) > L:
            raise OverflowError(f"log of server {i} exceeds capacity {L}")
        for k, (t, v) in enumerate(l):
            lt[i, k], lv[i, k] = t, v
    out["logTerm"], out["logVal"] = lt, lv
    out["vResp"] = np.array(s.vResp, np.int32)
    out["vGrant"] = np.array(s.vGrant, np.int32)
    out["nextIndex"] = np.array(s.nextIndex, np.int32)
    out["matchIndex"] = np.array(s.matchIndex, np.int32)
    if len(s.msgs) > Sc:
        raise OverflowError(f"message bag exceeds {Sc} slots")
    hi = np.zeros((Sc,), np.int32)
    lo = np.zeros((Sc,), np.int32)
    ct = np.zeros((Sc,), np.int32)
    for k, ((h, l), c) in enumerate(s.msgs):
        hi[k], lo[k], ct[k] = h, l, c
    out["msgHi"], out["msgLo"], out["msgCount"] = hi, lo, ct
    if bounds.history:
        uni = _uni(bounds)
        E = bounds.max_elections
        mask = np.zeros((lay.Wa,), np.int64)
        for l in s.allLogs:
            r = uni.id_of_tuple(l)
            mask[r // 32] |= 1 << (r % 32)
        out["allLogs"] = mask.astype(np.uint32).view(np.int32)
        out["vLog"] = np.asarray(
            [[0 if l is None else uni.id_of_tuple(l) + 1
              for l in row] for row in s.vLog], np.int32)
        if len(s.elections) > E:
            raise OverflowError(f"elections set exceeds {E} slots")
        for k, (eterm, eleader, elog, evotes, evlog) in enumerate(s.elections):
            out["eTerm"][k] = eterm
            out["eLeader"][k] = eleader
            out["eLog"][k] = uni.id_of_tuple(elog)
            out["eVotes"][k] = evotes
            out["eVLog"][k] = [0 if l is None else uni.id_of_tuple(l) + 1
                               for l in evlog]
    return out


def from_struct(struct: dict, bounds: Bounds) -> PyState:
    """numpy struct -> PyState (inverse of :func:`to_struct`)."""
    n = bounds.n_servers
    log = tuple(
        tuple((int(struct["logTerm"][i, k]), int(struct["logVal"][i, k]))
              for k in range(int(struct["logLen"][i])))
        for i in range(n))
    msgs = tuple(
        ((int(struct["msgHi"][k]), int(struct["msgLo"][k])),
         int(struct["msgCount"][k]))
        for k in range(len(struct["msgCount"]))
        if int(struct["msgCount"][k]) > 0)
    hist = {}
    if bounds.history and "allLogs" in struct:
        uni = _uni(bounds)
        logs = []
        for w, word in enumerate(np.asarray(struct["allLogs"],
                                            np.int32).view(np.uint32)):
            word = int(word)
            for b in range(32):
                if word & (1 << b):
                    logs.append(uni.tuple_of_id(32 * w + b))
        vlog = tuple(
            tuple(None if int(x) == 0 else uni.tuple_of_id(int(x) - 1)
                  for x in row) for row in struct["vLog"])
        recs = []
        for k in range(len(struct["eTerm"])):
            if int(struct["eTerm"][k]) == 0:
                continue
            recs.append((
                int(struct["eTerm"][k]), int(struct["eLeader"][k]),
                uni.tuple_of_id(int(struct["eLog"][k])),
                int(struct["eVotes"][k]),
                tuple(None if int(x) == 0 else uni.tuple_of_id(int(x) - 1)
                      for x in struct["eVLog"][k])))
        hist = dict(allLogs=tuple(sorted(logs, key=_log_key)),
                    vLog=vlog,
                    elections=tuple(sorted(recs, key=_election_key)))
    return PyState(
        **hist,
        role=tuple(int(x) for x in struct["role"]),
        term=tuple(int(x) for x in struct["term"]),
        votedFor=tuple(int(x) for x in struct["votedFor"]),
        commitIndex=tuple(int(x) for x in struct["commitIndex"]),
        log=log,
        vResp=tuple(int(x) for x in struct["vResp"]),
        vGrant=tuple(int(x) for x in struct["vGrant"]),
        nextIndex=tuple(tuple(int(x) for x in row)
                        for row in struct["nextIndex"]),
        matchIndex=tuple(tuple(int(x) for x in row)
                         for row in struct["matchIndex"]),
        msgs=tuple(sorted(msgs)),
    )


def to_vec(s: PyState, bounds: Bounds) -> np.ndarray:
    return st.pack(to_struct(s, bounds), np)


def constraint_ok(s: PyState, bounds: Bounds) -> bool:
    """Host-side StateConstraint (must agree with ops/state.constraint_ok)."""
    return (all(t <= bounds.max_term for t in s.term)
            and all(len(l) <= bounds.max_log for l in s.log)
            and len(s.msgs) <= bounds.max_msgs
            and all(c <= bounds.max_dup for _m, c in s.msgs))
