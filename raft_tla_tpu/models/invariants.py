"""Invariant registry — the checker's L5 ``INVARIANT`` stanza target.

The reference cfg declares ``INVARIANT NoTwoLeaders`` (``raft.cfg:3``) but no
such operator exists in ``raft.tla`` (SURVEY §0 defect 1); ``README.md:5``
defers to an external PR.  The registry therefore *defines* it, as Raft's
**Election Safety** — at most one leader per term:

    \\A i, j \\in Server :
        (state[i] = Leader /\\ state[j] = Leader
         /\\ currentTerm[i] = currentTerm[j]) => i = j

A naive "never two simultaneous leaders in any terms" reading is NOT an
invariant of Raft — a deposed leader keeps ``state = Leader`` until it
observes a higher term via ``UpdateTerm`` (``raft.tla:406-412``) — so it is
kept in the registry as ``NaiveNoTwoLeaders``, the canonical smoke test that
the checker finds real violations and reconstructs traces.

Every invariant has two faces sharing one definition site: a Python predicate
over :class:`~raft_tla_tpu.models.interp.PyState` (oracle side) and a jnp
predicate over the tensor struct (vmapped over the frontier, device side).
"""

from __future__ import annotations

import functools

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import spec as S


# -- Python (oracle) predicates: state -> bool (True = invariant holds) ------

def _py_election_safety(s, bounds: Bounds) -> bool:
    n = bounds.n_servers
    return not any(
        s.role[i] == S.LEADER and s.role[j] == S.LEADER
        and s.term[i] == s.term[j]
        for i in range(n) for j in range(i + 1, n))


def _py_naive_no_two_leaders(s, bounds: Bounds) -> bool:
    return sum(1 for r in s.role if r == S.LEADER) <= 1


def _py_log_matching(s, bounds: Bounds) -> bool:
    """If two logs share (index, term), they agree on the whole prefix."""
    n = bounds.n_servers
    for i in range(n):
        for j in range(i + 1, n):
            li, lj = s.log[i], s.log[j]
            for k in range(min(len(li), len(lj))):
                if li[k][0] == lj[k][0] and li[:k + 1] != lj[:k + 1]:
                    return False
    return True


def _py_committed_within_log(s, bounds: Bounds) -> bool:
    """commitIndex never points past the log (sanity, provable from the spec)."""
    return all(s.commitIndex[i] <= len(s.log[i])
               for i in range(bounds.n_servers))


def _py_leader_completeness(s, bounds: Bounds) -> bool:
    """Leader Completeness (Raft Fig. 3): an entry committed in term T is
    present in the log of every leader of a term later than T.

    State-level reading without history variables: the *commit term* of an
    entry counted by ``commitIndex[j]`` is not recorded, but it is always
    <= ``currentTerm[j]`` — j's commitIndex moves only through its own
    AdvanceCommitIndex (commit term = currentTerm[j], ``raft.tla:268-270``)
    or an accepted AppendEntries with ``mterm = currentTerm[j]``
    (``raft.tla:356-365``), and terms only grow.  So the sound check is:
    for every j, k <= commitIndex[j], and every leader i with
    ``currentTerm[i] > currentTerm[j]``, the identical entry sits at k in
    log[i].  Comparing against the *entry* term instead would wrongly flag
    stale leaders of terms between the entry term and the commit term
    (reachable: a deposed-but-unaware leader elected before the commit).
    """
    n = bounds.n_servers
    for j in range(n):
        for k in range(s.commitIndex[j]):
            ent = s.log[j][k]
            for i in range(n):
                if (s.role[i] == S.LEADER and s.term[i] > s.term[j]
                        and (len(s.log[i]) <= k or s.log[i][k] != ent)):
                    return False
    return True


def _py_election_safety_hist(s, bounds: Bounds) -> bool:
    """Election Safety over the ``elections`` history set (faithful mode):
    at most one leader was *ever* elected per term (raft.tla:237-242) —
    strictly stronger than the state-level reading, which only sees leaders
    still in office."""
    if s.elections is None:
        return True
    terms = {}
    for (eterm, eleader, _elog, _evotes, _evlog) in s.elections:
        if terms.setdefault(eterm, eleader) != eleader:
            return False
    return True


def _py_leader_completeness_hist(s, bounds: Bounds) -> bool:
    """Leader Completeness over history (Raft Fig. 3, the proof's reading):
    every entry committed now is present in the ``elog`` of every recorded
    election of a *later* term — including elections whose leader has since
    crashed or been deposed, which the state-level check cannot see."""
    if s.elections is None:
        return True
    for j in range(bounds.n_servers):
        for k in range(s.commitIndex[j]):
            ent = s.log[j][k]
            for (eterm, _el, elog, _ev, _evl) in s.elections:
                if eterm > s.term[j] and (len(elog) <= k or elog[k] != ent):
                    return False
    return True


def _py_all_logs_prefix_closed(s, bounds: Bounds) -> bool:
    """``allLogs`` is prefix-closed: logs grow by single appends
    (``raft.tla:250, 383-388``) and every pre-state log is recorded
    (``raft.tla:465``), so each log's parent prefix must already be in the
    set.  A good self-check of the history machinery itself."""
    if s.allLogs is None:
        return True
    seen = set(s.allLogs)
    return all(l[:-1] in seen for l in s.allLogs if l)


# -- jnp (device) predicates: struct -> scalar bool --------------------------

def _jnp_election_safety(bounds: Bounds):
    import jax.numpy as jnp

    def inv(st):
        is_l = st["role"] == S.LEADER
        same_term = st["term"][:, None] == st["term"][None, :]
        both = is_l[:, None] & is_l[None, :] & same_term
        off_diag = ~jnp.eye(bounds.n_servers, dtype=bool)
        return ~jnp.any(both & off_diag)
    return inv


def _jnp_naive_no_two_leaders(bounds: Bounds):
    import jax.numpy as jnp

    def inv(st):
        return jnp.sum((st["role"] == S.LEADER).astype(jnp.int32)) <= 1
    return inv


def _jnp_log_matching(bounds: Bounds):
    import jax.numpy as jnp

    def inv(st):
        lt, lv, ln = st["logTerm"], st["logVal"], st["logLen"]
        L = lt.shape[1]
        ks = jnp.arange(L)
        # [i, j, k] masks
        valid = (ks[None, None, :]
                 < jnp.minimum(ln[:, None], ln[None, :])[:, :, None])
        term_eq = lt[:, None, :] == lt[None, :, :]
        ent_eq = term_eq & (lv[:, None, :] == lv[None, :, :])
        prefix_eq = jnp.cumprod(ent_eq.astype(jnp.int32), axis=-1) > 0
        bad = valid & term_eq & ~prefix_eq
        return ~jnp.any(bad)
    return inv


def _jnp_committed_within_log(bounds: Bounds):
    import jax.numpy as jnp

    def inv(st):
        return jnp.all(st["commitIndex"] <= st["logLen"])
    return inv


def _jnp_leader_completeness(bounds: Bounds):
    import jax.numpy as jnp

    def inv(st):
        L = st["logTerm"].shape[1]
        ks = jnp.arange(L)
        committed = ks[None, :] < st["commitIndex"][:, None]      # [j, k]
        is_leader = st["role"] == S.LEADER                        # [i]
        later_term = st["term"][:, None] > st["term"][None, :]    # [i, j]
        must_hold = (is_leader[:, None] & later_term)[:, :, None] \
            & committed[None, :, :]
        present = ks[None, :] < st["logLen"][:, None]             # [i, k]
        same = (st["logTerm"][:, None, :] == st["logTerm"][None, :, :]) \
            & (st["logVal"][:, None, :] == st["logVal"][None, :, :])
        ok = present[:, None, :] & same
        return ~jnp.any(must_hold & ~ok)
    return inv


def _jnp_election_safety_hist(bounds: Bounds):
    import jax.numpy as jnp

    def inv(st):
        occ = st["eTerm"] > 0
        both = occ[:, None] & occ[None, :]
        same_term = st["eTerm"][:, None] == st["eTerm"][None, :]
        diff_leader = st["eLeader"][:, None] != st["eLeader"][None, :]
        return ~jnp.any(both & same_term & diff_leader)
    return inv


def _jnp_leader_completeness_hist(bounds: Bounds):
    import jax.numpy as jnp
    from raft_tla_tpu.ops.loguniv import LogUniverse
    uni = LogUniverse.of(bounds)

    def inv(st):
        L = st["logTerm"].shape[1]
        ks = jnp.arange(L)
        committed = ks[None, :] < st["commitIndex"][:, None]      # [j, k]
        et, ev, eln = uni.decode(st["eLog"], jnp)                 # [E, L], [E]
        occ = st["eTerm"] > 0                                     # [E]
        later = occ[:, None] & (st["eTerm"][:, None]
                                > st["term"][None, :])            # [e, j]
        long_enough = ks[None, :] < eln[:, None]                  # [e, k]
        same = (et[:, None, :] == st["logTerm"][None, :, :]) \
            & (ev[:, None, :] == st["logVal"][None, :, :])        # [e, j, k]
        ok = long_enough[:, None, :] & same
        must = later[:, :, None] & committed[None, :, :]
        return ~jnp.any(must & ~ok)
    return inv


def _jnp_all_logs_prefix_closed(bounds: Bounds):
    import jax.numpy as jnp
    import numpy as np
    from raft_tla_tpu.ops.loguniv import LogUniverse
    uni = LogUniverse.of(bounds)
    # Static tables over the whole (small) universe: rank -> parent rank.
    rs = np.arange(uni.size)
    parent = uni.prefix_id(rs, np)
    nonempty = rs >= 1                       # rank 0 is the empty log

    def inv(st):
        mask = st["allLogs"]
        present = (mask[rs // 32] >> (rs % 32)) & 1
        par_present = (mask[parent // 32] >> (parent % 32)) & 1
        bad = (present > 0) & jnp.asarray(nonempty) & (par_present == 0)
        return ~jnp.any(bad)
    return inv


# name -> (python predicate, jnp predicate builder)
REGISTRY = {
    # The reference cfg's undefined operator, defined (see module docstring).
    "NoTwoLeaders": (_py_election_safety, _jnp_election_safety),
    "ElectionSafety": (_py_election_safety, _jnp_election_safety),
    # Deliberately falsifiable — exercises violation reporting + traces.
    "NaiveNoTwoLeaders": (_py_naive_no_two_leaders, _jnp_naive_no_two_leaders),
    "LogMatching": (_py_log_matching, _jnp_log_matching),
    "CommittedWithinLog": (_py_committed_within_log, _jnp_committed_within_log),
    "LeaderCompleteness": (_py_leader_completeness, _jnp_leader_completeness),
}

# History-based invariants: need the faithful-mode encodings (Bounds.history).
HISTORY_REGISTRY = {
    "ElectionSafetyHist": (_py_election_safety_hist,
                           _jnp_election_safety_hist),
    "LeaderCompletenessHist": (_py_leader_completeness_hist,
                               _jnp_leader_completeness_hist),
    "AllLogsPrefixClosed": (_py_all_logs_prefix_closed,
                            _jnp_all_logs_prefix_closed),
}
REGISTRY.update(HISTORY_REGISTRY)

# Which struct fields each invariant's predicate reads — the spec-lint
# (analysis/cfglint) side of the metadata: an invariant whose READS are
# never written by any transition in the active spec subset is vacuous
# (statically constant given Init), and an invariant reading fields a
# VIEW rewrites is checked only up to the view.  Keep in sync with the
# _py_*/_jnp_* bodies above.
READS = {
    "NoTwoLeaders": ("role", "term"),
    "ElectionSafety": ("role", "term"),
    "NaiveNoTwoLeaders": ("role",),
    "LogMatching": ("logTerm", "logVal", "logLen"),
    "CommittedWithinLog": ("commitIndex", "logLen"),
    "LeaderCompleteness": ("role", "term", "logTerm", "logVal", "logLen",
                           "commitIndex"),
    "ElectionSafetyHist": ("eTerm", "eLeader"),
    "LeaderCompletenessHist": ("eTerm", "eLog", "term", "commitIndex",
                               "logTerm", "logVal", "logLen"),
    "AllLogsPrefixClosed": ("allLogs",),
}


@functools.lru_cache(maxsize=None)
def _expression(text: str):
    """Compile a non-registry invariant as a frontend predicate over the
    Raft state schema (cached — cfg text recurs per step build)."""
    from raft_tla_tpu.frontend.predicate import compile_predicate
    from raft_tla_tpu.models import spec as S
    return compile_predicate(text, fields=S.RAFT_SCHEMA.field_names)


def py_invariant(name: str):
    if name in REGISTRY:
        return REGISTRY[name][0]
    pred = _expression(name)

    def check(s, bounds) -> bool:
        import numpy as np
        from raft_tla_tpu.models import interp
        from raft_tla_tpu.ops import state as st
        struct = st.unpack(interp.to_vec(s, bounds), st.Layout.of(bounds),
                           np)
        return bool(pred.ev(struct, np))

    return check


def jnp_invariant(name: str, bounds: Bounds):
    if name in REGISTRY:
        return REGISTRY[name][1](bounds)
    pred = _expression(name)
    import jax.numpy as jnp
    return lambda s: pred.ev(s, jnp)
