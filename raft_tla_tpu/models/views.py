"""Built-in state views — the TLC ``VIEW`` mechanism, engine-native.

TLC's VIEW semantics (cfg ``VIEW <op>``): two states are identified
whenever their view values coincide; the first-reached full state serves
as the orbit representative for successor generation, and invariants are
evaluated on full states.  A view is EXACT (never misses a violation,
never reports a spurious one) when view-equivalence is a bisimulation
with respect to every action and the checked invariants read only
view-preserved fields.  This module registers such views; arbitrary
user expressions (which TLC accepts unsoundly — the manual pushes the
proof obligation onto the user) are intentionally not supported.

``deadvotes`` — zero ``votesResponded[i]``/``votesGranted[i]`` whenever
``state[i] /= Candidate``.  Soundness argument (the bisimulation is
checked mechanically by tests/test_views.py::test_deadvotes_bisimulation
against THIS implementation's action semantics):

- every READ of the vote sets in the spec is guarded by
  ``state[i] = Candidate``: the ``RequestVote`` enabling condition
  (raft.tla:196-203 — ``j # votesResponded[i]`` under Candidate), the
  ``BecomeLeader`` quorum guard (raft.tla:236-238), and the
  ``HandleRequestVoteResponse`` accumulation (raft.tla:341-350, reached
  only for messages at ``currentTerm[i]`` — a Candidate-term exchange);
- every other action either leaves the sets untouched or RESETS them
  (``Timeout``, raft.tla:180-187) independently of their old value;
- therefore two states differing only in a non-Candidate server's vote
  sets enable identical actions and their successors differ only the
  same way: view-equivalence is a bisimulation, and the quotient search
  is exact for every property that does not read dead vote sets — no
  registered invariant (models/invariants.py) reads them at all.

Why it matters: the elect5 campaign's coverage telemetry showed 244.7M
of 311.6M discoveries credited to RequestVote — vote-set combinatorics
of concurrent candidacies dominate the space, and every candidacy that
loses (server overtaken by a higher term) strands its half-accumulated
vote sets as dead freight that multiplies states (VERDICT r2 weak #7).

Views compose with SYMMETRY: the view map is permutation-equivariant
(roles permute together with vote sets), so ``orbit_fp(view(s))`` is
well-defined and the quotient orders commute.
"""

from __future__ import annotations

from raft_tla_tpu.config import Bounds

# view name -> short description (CLI help, cfg validation)
REGISTRY = {
    "deadvotes": "zero votesResponded/votesGranted of non-Candidates "
                 "(exact: dead-variable elimination)",
}

# Spec-lint metadata (analysis/cfglint).  VIEW_WRITES: the fields a view
# rewrites before fingerprinting — invariants reading them are checked
# only up to the view, worth a diagnostic.  EQUIVARIANT_AXES: the
# permutation axes the view commutes with — SYMMETRY on any other axis
# would make view-fingerprints orbit-dependent (unsound dedup).  Keep in
# sync with the py_view/jnp_view bodies below.
VIEW_WRITES = {
    "deadvotes": ("vResp", "vGrant"),
}
EQUIVARIANT_AXES = {
    "deadvotes": ("Server", "Value"),
}


def py_view(name: str):
    """Host-side view map: PyState -> PyState (the oracle twin)."""
    if name == "deadvotes":
        from raft_tla_tpu.models import spec as S

        def view(s, bounds: Bounds):
            vr = tuple(v if r == S.CANDIDATE else 0
                       for v, r in zip(s.vResp, s.role))
            vg = tuple(v if r == S.CANDIDATE else 0
                       for v, r in zip(s.vGrant, s.role))
            if vr == s.vResp and vg == s.vGrant:
                return s
            return s._replace(vResp=vr, vGrant=vg)

        return view
    raise ValueError(f"unknown view {name!r} (known: {sorted(REGISTRY)})")


def jnp_view(name: str, bounds: Bounds):
    """Device-side view map on an unpacked state struct (ops/state.py
    layout) — must be arithmetic-identical to :func:`py_view`."""
    if name == "deadvotes":
        import jax.numpy as jnp

        from raft_tla_tpu.models import spec as S

        def view(struct):
            cand = struct["role"] == S.CANDIDATE
            out = dict(struct)
            out["vResp"] = jnp.where(cand, struct["vResp"], 0)
            out["vGrant"] = jnp.where(cand, struct["vGrant"], 0)
            return out

        return view
    raise ValueError(f"unknown view {name!r} (known: {sorted(REGISTRY)})")
