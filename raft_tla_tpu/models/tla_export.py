"""Emit the TLC-side artifacts for oracle parity runs (SURVEY §0, §5).

The reference's config is not runnable by stock TLC as-is: ``raft.cfg:3``
declares ``INVARIANT NoTwoLeaders`` but no such operator exists in
``raft.tla`` (SURVEY §0 defect 1), and the cfg has no ``CONSTRAINT`` while
the raw spec's state space is infinite (defect 2).  This module generates a
standard "MC" extension module + cfg pair that fixes both *without touching
the read-only reference*:

- ``MCraft.tla`` — ``EXTENDS raft`` and defines (a) every invariant the run
  checks, in TLA+ (one definition site with the registry in
  ``models/invariants.py``: the TLA+ text here and the predicates there are
  differentially tested via the interpreter); (b) ``StateConstraint``, the
  exact bound the tensor encoding enforces (``config.Bounds``); (c)
  ``ParityView``, a TLC ``VIEW`` that strips the history-only state the
  tensor encoding drops (``elections``/``allLogs``/``voterLog``,
  ``raft.tla:39,44,77``, and the ``mlog`` message fields,
  ``raft.tla:220-222,297-299``) so TLC deduplicates states the same way this
  checker does (SURVEY §7.0.3 parity mode).
- ``MCraft.cfg`` — the reference's CONSTANTS block (``raft.cfg:5-15``)
  verbatim-equivalent, plus the INVARIANT/CONSTRAINT/VIEW stanzas.

No JVM exists in this environment, so these artifacts are validated
structurally (tests/test_cli.py::test_tla_export_structure) and by
round-tripping through
``utils/cfgparse``; running them under stock TLC is the documented
parity procedure for a host that has one (README).

Caveat on ``ParityView`` exactness: the view maps the message bag to the set
of ``<<stripped-record, multiplicity>>`` pairs.  If two in-flight messages
differ *only* in ``mlog``, TLC sees two pairs where the tensor encoding sums
one slot; such states would be distinguished by TLC and merged here.  No
reachable pair of messages differs only in ``mlog`` under the spec's guards
(``votedFor`` blocks same-term re-grants, ``raft.tla:290-292``), so counts
agree on reachable spaces; the construction is noted for auditability.
"""

from __future__ import annotations

import os

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.utils.cfgparse import TLCConfig

MODULE_NAME = "MCraft"


def _sym_axes(symmetry) -> tuple:
    """Normalize the ``symmetry`` argument (True or an axis iterable) to a
    canonical ``("Server",)`` / ``("Value",)`` / ``("Server", "Value")``."""
    raw = ("Server",) if symmetry is True else tuple(symmetry)
    bad = [ax for ax in raw if ax not in ("Server", "Value")]
    if bad:
        raise ValueError(f"unknown symmetry axes {bad}: only Server/Value "
                         "permutation symmetry exists in this checker")
    return tuple(ax for ax in ("Server", "Value") if ax in raw)


def _sym_name(symmetry) -> str:
    """Axis-encoded SYMMETRY operator name (``SymServer`` /
    ``SymValue`` / ``SymServerValue``) — one of the names
    ``check.py:_resolve_config`` accepts, so the emitted cfg
    round-trips through this checker as well as TLC.  Canonical
    axis order regardless of the caller's tuple order."""
    return "Sym" + "".join(_sym_axes(symmetry))

# TLA+ text per registry invariant (names match models/invariants.REGISTRY).
_INVARIANT_TLA = {
    "NoTwoLeaders": """\
NoTwoLeaders ==
    \\A i, j \\in Server :
        (/\\ state[i] = Leader
         /\\ state[j] = Leader
         /\\ currentTerm[i] = currentTerm[j]) => i = j""",
    "ElectionSafety": """\
ElectionSafety ==
    \\A i, j \\in Server :
        (/\\ state[i] = Leader
         /\\ state[j] = Leader
         /\\ currentTerm[i] = currentTerm[j]) => i = j""",
    "NaiveNoTwoLeaders": """\
NaiveNoTwoLeaders ==
    \\A i, j \\in Server :
        (state[i] = Leader /\\ state[j] = Leader) => i = j""",
    "LogMatching": """\
LogMatching ==
    \\A i, j \\in Server :
        \\A k \\in 1..Min({Len(log[i]), Len(log[j])}) :
            log[i][k].term = log[j][k].term =>
                SubSeq(log[i], 1, k) = SubSeq(log[j], 1, k)""",
    "CommittedWithinLog": """\
CommittedWithinLog ==
    \\A i \\in Server : commitIndex[i] <= Len(log[i])""",
    "LeaderCompleteness": """\
\\* The commit term of any entry within commitIndex[j] is <= currentTerm[j]
\\* (raft.tla:268-270, 356-365), so leaders of terms beyond currentTerm[j]
\\* must already hold the entry (Raft Fig. 3).
LeaderCompleteness ==
    \\A i, j \\in Server :
        \\A k \\in 1..commitIndex[j] :
            (state[i] = Leader /\\ currentTerm[i] > currentTerm[j]) =>
                (k <= Len(log[i]) /\\ log[i][k] = log[j][k])""",
    # -- history-based (faithful mode: read the raft.tla:39/44 variables) ----
    "ElectionSafetyHist": """\
\\* At most one leader was EVER elected per term (over the elections
\\* history, raft.tla:237-242) — stronger than the state-level reading.
ElectionSafetyHist ==
    \\A e1, e2 \\in elections : e1.eterm = e2.eterm => e1.eleader = e2.eleader""",
    "LeaderCompletenessHist": """\
\\* Every currently-committed entry appears in the elog of every recorded
\\* election of a later term (Raft Fig. 3 over history).
LeaderCompletenessHist ==
    \\A j \\in Server :
        \\A k \\in 1..commitIndex[j] :
            \\A e \\in elections :
                e.eterm > currentTerm[j] =>
                    (k <= Len(e.elog) /\\ e.elog[k] = log[j][k])""",
    "AllLogsPrefixClosed": """\
\\* allLogs (raft.tla:44,465) is prefix-closed: logs grow by single appends.
AllLogsPrefixClosed ==
    \\A l \\in allLogs :
        Len(l) > 0 => SubSeq(l, 1, Len(l) - 1) \\in allLogs""",
}

_PARITY_VIEW = """\
\\* History-free projection of one message record (SURVEY §7.0.3):
\\* mlog (raft.tla:220-222, 297-299) is proof-only and read by no guard.
StripMsg(m) == [f \\in DOMAIN m \\ {"mlog"} |-> m[f]]

\\* The VIEW under which TLC fingerprints states: drops the history
\\* variables elections/allLogs/voterLog (raft.tla:39,44,77) entirely and
\\* the mlog fields inside the message bag.
ParityView ==
    << {<<StripMsg(m), messages[m]>> : m \\in DOMAIN messages},
       currentTerm, state, votedFor, log, commitIndex,
       votesResponded, votesGranted, nextIndex, matchIndex >>"""


_DEAD_VOTES = """\
\\* The deadvotes VIEW (models/views.py): vote sets of non-Candidates are
\\* dead variables — every read in raft.tla (RequestVote raft.tla:196-203,
\\* BecomeLeader raft.tla:236-238, HandleRequestVoteResponse
\\* raft.tla:341-350) is Candidate-guarded, and Timeout (raft.tla:180-187)
\\* resets them — so masking them is an exact quotient.
DeadVotes(v) == [i \\in Server |-> IF state[i] = Candidate THEN v[i]
                                   ELSE {}]"""


# The election sub-spec's Next (models/spec.SUBSETS["election"]), with
# the reference Next's exact structure — the per-step allLogs history
# update is the top-level conjunct (raft.tla:464-465), the disjuncts are
# the subset of raft.tla:455-461 the checker's election action table
# enumerates.  Receive stays unrestricted: with AppendEntries excluded
# the bag only ever holds RequestVote traffic, so the reachable spaces
# coincide.
_ELECTION_NEXT = """\
\\* The election-only sub-spec (BASELINE config #2): Timeout +
\\* RequestVote + BecomeLeader + Receive, the same subset of the
\\* raft.tla:454-463 disjuncts the checker's --spec election explores.
ElectionNext ==
    /\\ \\/ \\E i \\in Server : Timeout(i)
       \\/ \\E i, j \\in Server : RequestVote(i, j)
       \\/ \\E i \\in Server : BecomeLeader(i)
       \\/ \\E m \\in DOMAIN messages : Receive(m)
    /\\ allLogs' = allLogs \\cup {log[i] : i \\in Server}

ElectionSpec == Init /\\ [][ElectionNext]_vars"""


def _spec_parts(spec: str):
    """(module text blocks, SPECIFICATION name) for a sub-spec twin."""
    if spec in (None, "full"):
        return [], "Spec"
    if spec == "election":
        return [_ELECTION_NEXT, ""], "ElectionSpec"
    raise ValueError(
        f"no TLA+ export for spec {spec!r} (replication starts from a "
        "preset-leader Init the exporter does not emit)")


# Existential closure of each action family (raft.tla signatures) for
# WF_vars terms in the fair twin spec.
_FAMILY_ACTION = {
    "Restart": "\\E i \\in Server : Restart(i)",
    "Timeout": "\\E i \\in Server : Timeout(i)",
    "RequestVote": "\\E i, j \\in Server : RequestVote(i, j)",
    "BecomeLeader": "\\E i \\in Server : BecomeLeader(i)",
    "ClientRequest":
        "\\E i \\in Server, v \\in Value : ClientRequest(i, v)",
    "AdvanceCommitIndex": "\\E i \\in Server : AdvanceCommitIndex(i)",
    "AppendEntries": "\\E i, j \\in Server : AppendEntries(i, j)",
    "Receive": "\\E m \\in DOMAIN messages : Receive(m)",
    "DuplicateMessage":
        "\\E m \\in DOMAIN messages : DuplicateMessage(m)",
    "DropMessage": "\\E m \\in DOMAIN messages : DropMessage(m)",
}


def _prop_defs(properties: tuple):
    """[(definition name, TLA temporal formula)] for PROPERTY entries —
    registered names keep their name, formulas get synthesized ones."""
    from raft_tla_tpu.models import liveness

    defs = []
    for k, text in enumerate(properties, start=1):
        ps = liveness.parse_property(text)
        tlas = [liveness.PREDICATES[nm][2] for nm in ps.pred_names]
        if ps.form == liveness.LEADS_TO:
            formula = f"({tlas[0]}) ~> ({tlas[1]})"
        else:
            formula = f"{ps.form}({tlas[0]})"
        name = ps.text if ps.text in liveness.PROPERTIES \
            else f"TemporalProp{k}"
        defs.append((name, formula))
    return defs


def _fair_spec(spec_name: str, spec: str, wf: tuple) -> str:
    """``FairSpec == <base> /\\ WF_vars(...)`` matching the checker's
    ``--wf`` families (the temporal verdicts are fairness-relative)."""
    next_name = "ElectionNext" if spec == "election" else "Next"
    unknown = [f for f in wf if f != "Next" and f not in _FAMILY_ACTION]
    if unknown:
        raise ValueError(f"no TLA+ export for WF families {unknown}")
    terms = [f"WF_vars({next_name})" if fam == "Next"
             else f"WF_vars({_FAMILY_ACTION[fam]})" for fam in wf]
    conj = " /\\ ".join(terms)
    return (f"\\* The checker's --wf fairness, as a twin spec.\n"
            f"FairSpec == {spec_name} /\\ {conj}")


def emit_module(bounds: Bounds, invariants: tuple,
                parity_view: bool = True, symmetry: bool = False,
                view: str | None = None, spec: str = "full",
                properties: tuple = (), wf: tuple = ()) -> str:
    """The ``MCraft.tla`` text: invariants + StateConstraint (+ VIEW +
    temporal PROPERTY definitions and the fairness twin spec)."""
    unknown = [nm for nm in invariants if nm not in _INVARIANT_TLA]
    if unknown:
        raise ValueError(f"no TLA+ export for invariants: {unknown}")
    spec_blocks, _spec_name = _spec_parts(spec)
    parts = [f"---------------------------- MODULE {MODULE_NAME} "
             "----------------------------",
             "\\* Generated by raft_tla_tpu.models.tla_export — the TLC",
             "\\* oracle-side twin of one checker run. Extends the reference",
             "\\* spec unmodified.",
             "EXTENDS raft", ""]
    parts += spec_blocks
    for nm in invariants:
        parts += [_INVARIANT_TLA[nm], ""]
    parts += [f"""\
\\* The state constraint the tensor encoding enforces (config.Bounds).
StateConstraint ==
    /\\ \\A i \\in Server : currentTerm[i] <= {bounds.max_term}
    /\\ \\A i \\in Server : Len(log[i]) <= {bounds.max_log}
    /\\ Cardinality(DOMAIN messages) <= {bounds.max_msgs}
    /\\ \\A m \\in DOMAIN messages : messages[m] <= {bounds.max_dup}""", ""]
    if view not in (None, "deadvotes"):
        raise ValueError(f"no TLA+ export for view {view!r}")
    if view:
        parts += [_DEAD_VOTES, ""]
    if parity_view:
        pv = _PARITY_VIEW
        if view:
            pv = pv.replace(
                "votesResponded, votesGranted",
                "DeadVotes(votesResponded), DeadVotes(votesGranted)")
        parts += [pv, ""]
    elif view:
        # faithful mode: identity keeps the history variables, only the
        # dead vote sets are masked
        parts += ["""\
DeadVotesView ==
    << messages, currentTerm, state, votedFor, log, commitIndex,
       DeadVotes(votesResponded), DeadVotes(votesGranted),
       nextIndex, matchIndex, elections, allLogs, voterLog >>""", ""]
    if symmetry:
        union = " \\cup ".join(f"Permutations({ax})"
                               for ax in _sym_axes(symmetry))
        # Axis-encoded name (SymServer / SymValue / SymServerValue) so
        # check.py:_resolve_config accepts its own --emit-tlc artifact.
        parts += ["\\* TLC symmetry set matching the checker's "
                  "symmetry reduction.",
                  f"{_sym_name(symmetry)} == {union}", ""]
    if properties:
        parts += ["\\* Temporal PROPERTY twins (cfg/CLI formulas)."]
        for name, formula in _prop_defs(properties):
            parts += [f"{name} == {formula}"]
        parts += [""]
        if wf:
            parts += [_fair_spec(_spec_name, spec, wf), ""]
    parts.append("=" * 77)
    return "\n".join(parts)


def emit_cfg(bounds: Bounds, invariants: tuple,
             parity_view: bool = True, symmetry: bool = False,
             view: str | None = None, spec: str = "full",
             properties: tuple = (), wf: tuple = ()) -> str:
    """The ``MCraft.cfg`` text: reference bindings + the new stanzas."""
    servers = ", ".join(f"s{i + 1}" for i in range(bounds.n_servers))
    values = ", ".join(f"v{i + 1}" for i in range(bounds.n_values))
    _blocks, spec_name = _spec_parts(spec)
    lines = [
        f"SPECIFICATION "
        f"{'FairSpec' if properties and wf else spec_name}",
        "",
        *[f"PROPERTY {nm}" for nm, _f in _prop_defs(properties)],
        *[f"INVARIANT {nm}" for nm in invariants],
        "CONSTRAINT StateConstraint",
        # stock TLC rejects VIEW when checking temporal properties
        # (liveness needs real states, not view fingerprints): a
        # temporal twin runs on the faithful space, bounded by the
        # CONSTRAINT — so with properties the VIEW line is omitted
        *([] if properties
          else ["VIEW ParityView"] if parity_view
          else ["VIEW DeadVotesView"] if view else []),
        *([f"SYMMETRY {_sym_name(symmetry)}"] if symmetry else []),
        "",
        "CONSTANTS",
        f"    Server = {{{servers}}}",
        f"    Value = {{{values}}}",
        '    Follower = "Follower"',
        '    Candidate = "Candidate"',
        '    Leader = "Leader"',
        '    Nil = "Nil"',
        '    RequestVoteRequest = "RequestVoteRequest"',
        '    RequestVoteResponse = "RequestVoteResponse"',
        '    AppendEntriesRequest = "AppendEntriesRequest"',
        '    AppendEntriesResponse = "AppendEntriesResponse"',
        "",
    ]
    return "\n".join(lines)


def export(outdir: str, bounds: Bounds, invariants: tuple,
           parity_view: bool = True, symmetry: bool = False,
           view: str | None = None, spec: str = "full",
           properties: tuple = (), wf: tuple = ()) -> tuple:
    """Write ``MCraft.tla``/``MCraft.cfg`` into ``outdir``; return the paths.

    Run on a host with a JVM as::

        java -jar tla2tools.jar -config MCraft.cfg MCraft.tla

    with the reference ``raft.tla`` on the module search path.
    """
    os.makedirs(outdir, exist_ok=True)
    tla = os.path.join(outdir, f"{MODULE_NAME}.tla")
    cfg = os.path.join(outdir, f"{MODULE_NAME}.cfg")
    with open(tla, "w", encoding="utf-8") as f:
        f.write(emit_module(bounds, invariants, parity_view, symmetry,
                            view, spec, properties, wf))
    with open(cfg, "w", encoding="utf-8") as f:
        f.write(emit_cfg(bounds, invariants, parity_view, symmetry, view,
                         spec, properties, wf))
    return tla, cfg
