"""Spec-level metadata — re-exported from the frontend.

The encodings, action families, instance table, and (new) the declared
Raft state schema now live in ``frontend/raft_schema.py`` so the
spec-generic frontend owns them; this module stays the stable import
path (``models.spec`` is imported across kernels, engines, serve, and
tools) and re-exports everything unchanged.  See
:mod:`raft_tla_tpu.frontend.raft_schema` for the documentation.
"""

from __future__ import annotations

from raft_tla_tpu.frontend.raft_schema import (  # noqa: F401
    ADVANCECOMMIT,
    ALL_FAMILIES,
    APPENDENTRIES,
    BECOMELEADER,
    CANDIDATE,
    CLIENTREQUEST,
    DROP,
    DUPLICATE,
    FOLLOWER,
    LEADER,
    M_AEREQ,
    M_AERESP,
    M_NONE,
    M_RVREQ,
    M_RVRESP,
    MTYPE_NAMES,
    NIL,
    RAFT_SCHEMA,
    RECEIVE,
    REQUESTVOTE,
    RESTART,
    ROLE_NAMES,
    SPECS,
    TIMEOUT,
    ActionInstance,
    action_table,
)

__all__ = [
    "ADVANCECOMMIT", "ALL_FAMILIES", "APPENDENTRIES", "BECOMELEADER",
    "CANDIDATE", "CLIENTREQUEST", "DROP", "DUPLICATE", "FOLLOWER", "LEADER",
    "M_AEREQ", "M_AERESP", "M_NONE", "M_RVREQ", "M_RVRESP", "MTYPE_NAMES",
    "NIL", "RAFT_SCHEMA", "RECEIVE", "REQUESTVOTE", "RESTART", "ROLE_NAMES",
    "SPECS", "TIMEOUT", "ActionInstance", "action_table",
]
