"""Exhaustive BFS over the interpreter — the CPU oracle's checker loop.

This is what TLC does (SURVEY §0): breadth-first exploration from ``Init``,
invariants checked on every distinct state, CONSTRAINT gating expansion
(violating states are counted but their successors are not generated), and a
counterexample trace on invariant violation.  The TPU engine (engine.py) must
reproduce its distinct-state count, diameter, and verdicts exactly.
"""

from __future__ import annotations

import dataclasses
import time
from collections import Counter
from typing import Optional

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.models import interp, invariants, spec as S


DEADLOCK = "Deadlock"      # Violation.invariant sentinel (TLC -deadlock)


@dataclasses.dataclass
class Violation:
    invariant: str          # registry name, or DEADLOCK
    state: interp.PyState
    # Trace from Init to the violating state: [(action_label | None, state)].
    trace: list


@dataclasses.dataclass
class RefResult:
    n_states: int          # distinct states found (incl. constraint-violating)
    diameter: int          # number of BFS levels past Init with new states
    n_transitions: int     # enabled (state, action) pairs explored
    coverage: Counter      # action family -> distinct new states produced
    violation: Optional[Violation]
    levels: list           # new-state count per level (levels[0] = 1 = Init)
    wall_s: float
    # The oracle never stops early (no checkpoint/deadline machinery), so
    # a returned result is always a complete exploration — the CLI's
    # lossless-stop gate reads this like every other engine's result.
    complete: bool = True


def check(config: CheckConfig, max_states: int | None = None,
          init_override: interp.PyState | None = None) -> RefResult:
    """Run the oracle checker; stops at the first invariant violation.

    ``init_override`` replaces ``Init`` (testing hook: start exploration from
    a crafted state when the violation region is deep — the pure-Python oracle
    enumerates ~30k states/s, so full-depth demos belong to the TPU engine).
    """
    bounds = config.bounds
    table = S.action_table(bounds, config.spec)
    invs = [(nm, invariants.py_invariant(nm)) for nm in config.invariants]
    viewf = None
    if getattr(config, "view", None):
        from raft_tla_tpu.models import views
        viewf = views.py_view(config.view)
    if config.symmetry:
        from raft_tla_tpu.ops import symmetry as sym_mod
        keyf = lambda s: sym_mod.py_orbit_fingerprint(  # noqa: E731
            viewf(s, bounds) if viewf else s, bounds, config.symmetry)
    elif viewf:
        keyf = lambda s: viewf(s, bounds)                         # noqa: E731
    else:
        keyf = lambda s: s                                        # noqa: E731
    t0 = time.monotonic()

    init = init_override if init_override is not None \
        else interp.init_state(bounds)
    # key(state) -> (parent_state, action_idx) | None; with SYMMETRY the
    # key is the orbit fingerprint, so one orbit keeps one entry (TLC
    # semantics: the first-discovered member is the stored witness).
    seen = {keyf(init): None}
    levels = [1]
    coverage: Counter = Counter()
    n_transitions = 0
    violation = None

    def make_violation(nm, s):
        chain = []
        cur = s
        while cur is not None:
            entry = seen[keyf(cur)]
            chain.append((table[entry[1]].label() if entry else None, cur))
            cur = entry[0] if entry else None
        chain.reverse()
        return Violation(invariant=nm, state=s, trace=chain)

    for nm, fn in invs:
        if not fn(init, bounds):
            violation = make_violation(nm, init)

    frontier = [init] if violation is None else []
    while frontier:
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, bounds):
                continue  # counted, invariant-checked, but not expanded
            n_succ = 0
            for aidx, t in interp.successors(s, bounds, table):
                n_succ += 1
                n_transitions += 1
                k = keyf(t)
                if k in seen:
                    continue
                seen[k] = (s, aidx)
                coverage[table[aidx].family] += 1
                for nm, fn in invs:
                    if not fn(t, bounds):
                        violation = make_violation(nm, t)
                        break
                if violation is not None:
                    break
                nxt.append(t)
            if violation is None and config.check_deadlock and n_succ == 0:
                # TLC's default deadlock check: an expanded state with no
                # successor at all (stuttering excluded).  CONSTRAINT gates
                # exploration, not enabledness, so this is pre-constraint.
                violation = make_violation(DEADLOCK, s)
            if violation is not None:
                break
        if violation is not None:
            break
        if max_states is not None and len(seen) > max_states:
            raise RuntimeError(f"state count exceeded {max_states}")
        if nxt:
            levels.append(len(nxt))
        frontier = nxt

    return RefResult(
        n_states=len(seen),
        diameter=len(levels) - 1,
        n_transitions=n_transitions,
        coverage=coverage,
        violation=violation,
        levels=levels,
        wall_s=time.monotonic() - t0,
    )
