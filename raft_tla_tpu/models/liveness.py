"""Liveness checking under weak fairness — BASELINE config #5.

The reference ``Spec == Init /\\ [][Next]_vars`` has **no fairness
conjuncts** (``raft.tla:469``; SURVEY §2.7), so every liveness property is
vacuously refutable by stuttering.  This module makes the fairness
assumption explicit and checks eventuality properties the way TLC's
liveness checker does at its core: find a reachable *fair lasso* — a
prefix plus a cycle — that refutes the property, via SCC analysis of the
bounded behavior graph.

Semantics implemented (for a state predicate ``P``):

- ``<>P`` (EVENTUALLY): a counterexample is a fair infinite behavior never
  visiting ``P`` — a lasso entirely inside the ``~P`` region.
- ``[]<>P`` (INFINITELY-OFTEN): a counterexample's *cycle* avoids ``P``;
  the prefix may pass through ``P``.

Weak fairness, per action family (the ``\\E i : Timeout(i)``-level
disjuncts of ``Next``, SURVEY §2.5): ``WF(A)`` rules out behaviors where
``A`` is forever enabled but never taken.  A cycle (or a stuttering
self-loop) is **fair** iff for every assumed-fair family ``A``, the cycle
either takes an ``A``-step or contains a state where ``A`` is disabled.
Inside one SCC any finite set of such witness requirements can be realized
by a single closed walk (strong connectivity), so the SCC-level check is
exact.  The name ``Next`` means the whole relation: taking any step (or
total deadlock) satisfies it.

Bound-truncation subtlety (TLC ``CONSTRAINT`` semantics): exploration
stops at states violating the state constraint, but action *enabledness*
for fairness is judged on the spec, not the bound — an action whose only
successors fall outside the bound still counts as enabled, so a stutter at
such a state is unfair under ``WF`` of that action and is correctly
rejected as a counterexample.

The graph comes from either builder — :func:`explore_graph` (reference
interpreter, host) or :func:`engine_graph` (device-engine BFS + one
re-expansion pass, for universes far past the interpreter's reach); the
SCC fair-lasso analysis itself is host-side and exact either way.
"""

from __future__ import annotations

import dataclasses


from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, spec as S

# -- property registry: name -> (temporal form, state predicate) -------------

EVENTUALLY = "<>"
INFINITELY_OFTEN = "[]<>"
LEADS_TO = "~>"


def _some_leader(s, bounds: Bounds) -> bool:
    return any(r == S.LEADER for r in s.role)


def _some_commit(s, bounds: Bounds) -> bool:
    return any(ci > 0 for ci in s.commitIndex)


def _some_candidate(s, bounds: Bounds) -> bool:
    return any(r == S.CANDIDATE for r in s.role)


# State-predicate registry for cfg/CLI temporal FORMULAS (VERDICT r4
# missing #4): name -> (PyState predicate, struct-of-arrays vector twin,
# TLA+ text for the --emit-tlc twin).  Registration carries TWO
# obligations, both machine-checked:
#   1. PERMUTATION-INVARIANT (reads role/commitIndex as sets) — what
#      makes the orbit-quotient check of ddd_graph sound;
#   2. VIEW-INVARIANT under every registered view (reads only
#      view-preserved fields) — what makes the view-quotient check
#      sound (tests/test_views.py::test_predicates_view_invariant
#      asserts pred(s) == pred(view(s)) over a reachable corpus for
#      every predicate x view pair; a predicate reading vote sets
#      would fail it loudly instead of silently mis-evaluating on
#      first-reached representatives).
# The vector twins evaluate over unpacked chunks with a leading batch
# dim (a million PyState materializations just to test
# ``any(role == Leader)`` is the host loop the graph exports exist to
# avoid).
PREDICATES = {
    "SomeLeader": (
        _some_leader,
        lambda st_, b: (st_["role"] == S.LEADER).any(-1),
        "\\E i \\in Server : state[i] = Leader"),
    "SomeCandidate": (
        _some_candidate,
        lambda st_, b: (st_["role"] == S.CANDIDATE).any(-1),
        "\\E i \\in Server : state[i] = Candidate"),
    "SomeCommit": (
        _some_commit,
        lambda st_, b: (st_["commitIndex"] > 0).any(-1),
        "\\E i \\in Server : commitIndex[i] > 0"),
}

PROPERTIES = {
    # Raft's headline liveness claims, both refutable even under full weak
    # fairness (dueling candidates / fault churn) — finding the refuting
    # lasso is the point.
    "EventuallyLeader": (EVENTUALLY, _some_leader),
    "EventuallyCommit": (EVENTUALLY, _some_commit),
    "InfinitelyOftenLeader": (INFINITELY_OFTEN, _some_leader),
}

# the named properties, expressed over the predicate registry (what
# parse_property resolves them to)
_NAMED = {
    "EventuallyLeader": (EVENTUALLY, ("SomeLeader",)),
    "EventuallyCommit": (EVENTUALLY, ("SomeCommit",)),
    "InfinitelyOftenLeader": (INFINITELY_OFTEN, ("SomeLeader",)),
}

# back-compat alias (older call sites key vectorized masks by property
# name; new code keys by predicate name through PREDICATES)
_STRUCT_PREDICATES = {
    nm: PREDICATES[preds[0]][1] for nm, (_f, preds) in _NAMED.items()
}


@dataclasses.dataclass(frozen=True)
class PropSpec:
    """A resolved temporal property: a registered name or a parsed
    formula of one of the three supported shapes."""

    text: str           # display form (the input string)
    form: str           # EVENTUALLY | INFINITELY_OFTEN | LEADS_TO
    pred_names: tuple   # 1 predicate (<>P, []<>P) or 2 (P ~> Q)

    def preds(self):
        return tuple(PREDICATES[nm][0] for nm in self.pred_names)


def parse_property(text: str) -> PropSpec:
    """Resolve a cfg/CLI PROPERTY entry: a registered property name
    (``EventuallyLeader``), or a temporal formula ``<>P`` / ``[]<>P`` /
    ``P ~> Q`` over registered predicate names (TLC's PROPERTY grammar
    restricted to the shapes the lasso checker decides)."""
    t = " ".join(text.split())
    if t in _NAMED:
        form, preds = _NAMED[t]
        return PropSpec(text=t, form=form, pred_names=preds)

    def _pred(nm):
        nm = nm.strip()
        if nm not in PREDICATES:
            raise ValueError(
                f"unknown predicate {nm!r} in PROPERTY {text!r}; "
                f"registry: {sorted(PREDICATES)}")
        return nm

    if "~>" in t:
        lhs, _, rhs = t.partition("~>")
        if not lhs.strip() or not rhs.strip():
            raise ValueError(f"malformed PROPERTY {text!r}: "
                             "expected 'P ~> Q'")
        return PropSpec(text=t, form=LEADS_TO,
                        pred_names=(_pred(lhs), _pred(rhs)))
    if t.startswith("[]<>"):
        return PropSpec(text=t, form=INFINITELY_OFTEN,
                        pred_names=(_pred(t[4:]),))
    if t.startswith("<>"):
        return PropSpec(text=t, form=EVENTUALLY,
                        pred_names=(_pred(t[2:]),))
    raise ValueError(
        f"unknown PROPERTY {text!r}: not a registered property "
        f"({sorted(_NAMED)}) nor a formula of shape '<>P', '[]<>P' or "
        f"'P ~> Q' over registered predicates ({sorted(PREDICATES)})")


@dataclasses.dataclass
class LassoViolation:
    prop: str
    # [(action_label | None, state)] — label None on the first element.
    prefix: list
    # The repeating part; cycle[0] follows prefix[-1], and the step after
    # cycle[-1] returns to cycle[0].
    cycle: list


@dataclasses.dataclass
class LivenessResult:
    prop: str
    holds: bool
    violation: LassoViolation | None
    n_states: int
    n_edges: int
    n_sccs_checked: int


def explore_graph(config: CheckConfig):
    """The bounded behavior graph: states, labeled edges, enabled families.

    Returns ``(states, edges, enabled, expanded)`` where ``states`` is a
    list of PyStates in discovery order, ``edges[u] = [(aidx, v), ...]``
    over in-bound states only, ``enabled[u]`` is the set of action families
    with any enabled instance at u (spec-level, including out-of-bound
    successors — see module docstring), and ``expanded[u]`` says whether u
    satisfied the constraint (was expanded).
    """
    bounds = config.bounds
    table = S.action_table(bounds, config.spec)
    init = interp.init_state(bounds)
    index = {init: 0}
    states = [init]
    edges: list = [[]]
    enabled: list = [set()]
    expanded = [True]
    frontier = [0]
    while frontier:
        nxt = []
        for u in frontier:
            s = states[u]
            if not interp.constraint_ok(s, bounds):
                expanded[u] = False
                continue
            for aidx, t in interp.successors(s, bounds, table):
                enabled[u].add(table[aidx].family)
                v = index.get(t)
                if v is None:
                    v = len(states)
                    index[t] = v
                    states.append(t)
                    edges.append([])
                    enabled.append(set())
                    expanded.append(True)
                    nxt.append(v)
                edges[u].append((aidx, v))
        frontier = nxt
    # Enabledness must be spec-level even for unexpanded states.
    for u, s in enumerate(states):
        if not expanded[u]:
            for aidx, _t in interp.successors(s, bounds, table):
                enabled[u].add(table[aidx].family)
    return states, edges, enabled, expanded


def _csr_export(n, sorted_keys, order, expanded_arr, fams, fam_idx,
                chunks, missing_msg):
    """Shared CSR edge/enabled assembly for the engine graph exports:
    ``chunks`` yields ``(u_offset, valid[nb, A] bool, keys[nb, A]
    u64)``; successor keys resolve by binary search over
    ``sorted_keys`` (no per-state Python objects — ADVICE r3 #2)."""
    import numpy as np

    en_mat = np.zeros((n, len(fams)), bool)
    e_u, e_a, e_v = [], [], []
    for u_off, valid, keys in chunks:
        b_idx, a_idx = np.nonzero(valid)
        u_idx = (u_off + b_idx).astype(np.int64)
        en_mat[u_idx, fam_idx[a_idx]] = True
        m = expanded_arr[u_idx]
        ub, ab = u_idx[m], a_idx[m].astype(np.int32)
        sk = keys[b_idx[m], ab]
        pos = np.searchsorted(sorted_keys, sk)
        if not np.array_equal(sorted_keys[np.minimum(pos, n - 1)], sk):
            raise RuntimeError(missing_msg)
        e_u.append(ub)
        e_a.append(ab)
        e_v.append(order[pos].astype(np.int64))
    u_all = np.concatenate(e_u) if e_u else np.zeros(0, np.int64)
    a_all = np.concatenate(e_a) if e_a else np.zeros(0, np.int32)
    v_all = np.concatenate(e_v) if e_v else np.zeros(0, np.int64)
    # u_all is globally nondecreasing by construction (chunks ascend,
    # np.nonzero is row-major), so CSR needs no sort — just verify
    if u_all.size and (np.diff(u_all) < 0).any():
        raise AssertionError("graph export: edge sources out of order")
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(np.bincount(u_all, minlength=n), out=indptr[1:])
    return _CSREdges(indptr, a_all, v_all), _EnabledSets(en_mat, fams)


def engine_graph(config: CheckConfig, caps=None):
    """:func:`explore_graph` at accelerator speed (VERDICT r1 weak #5).

    The interpreter exploration tops out around toy universes; this builds
    the same ``(states, edges, enabled, expanded)`` tuple from a device-
    engine run: BFS on the engine (device_engine.py), then ONE re-expansion
    pass over the stored rows to emit every labeled edge, resolving
    successor fingerprints by binary search over the sorted key array
    (CSR edges + per-family enabled matrix — check()'s fast path).
    Verdicts are bitwise the same as the interpreter path (asserted in
    tests/test_liveness.py) — the 142,538-state 3-server election graph
    builds in about a minute against the interpreter's tens of minutes.

    The raw (unquotiented) graph only: orbit-level liveness under SYMMETRY
    needs a quotient-soundness argument this module doesn't make.
    """
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tla_tpu.device_engine import Capacities, DeviceEngine
    from raft_tla_tpu.ops import fingerprint as fpr
    from raft_tla_tpu.ops import kernels
    from raft_tla_tpu.ops import state as st

    if config.symmetry:
        raise ValueError(
            "engine_graph builds the raw behavior graph; SYMMETRY "
            "quotients are not sound for liveness here — run without")
    # Safety stops (invariants/deadlock) would truncate the graph; the
    # liveness pass wants the whole bounded space.
    cfg = _dc.replace(config, invariants=(), check_deadlock=False)
    eng = DeviceEngine(cfg, caps)
    res = eng.check(retain_carry=True)
    carry = eng.retained_carry
    n = res.n_states
    bounds = cfg.bounds
    lay = st.Layout.of(bounds)
    table = eng.table
    A, B, W = eng.A, cfg.chunk, lay.width

    rows = np.asarray(jax.device_get(carry.store[:n]))
    expanded_arr = np.asarray(jax.device_get(carry.conflag[:n]), bool)
    # Everything needed is on the host now — release the full carry
    # (store + dedup tables) before the re-expansion pass allocates its
    # own working set.
    eng.retained_carry = None
    del carry

    # successor resolution by binary search over the sorted key array,
    # CSR edge storage — the same flat-array export as ddd_graph, so
    # every engine-built graph takes check()'s CSR fast path
    consts = jnp.asarray(fpr.lane_constants(W))
    rhi, rlo = jax.jit(
        lambda v: fpr.fingerprint(v, consts, jnp))(jnp.asarray(rows))
    rkeys = fpr.to_u64(np.asarray(rhi), np.asarray(rlo))
    order = np.argsort(rkeys)
    sorted_keys = rkeys[order]

    step = jax.jit(kernels.build_step(bounds, cfg.spec, (), ()))
    fams = sorted({inst.family for inst in table})
    fam_idx = np.asarray([fams.index(inst.family) for inst in table],
                         np.int32)

    def chunks():
        for c0 in range(0, n, B):
            nb = min(B, n - c0)
            chunk = rows[c0:c0 + B]
            if nb < B:
                chunk = np.concatenate(
                    [chunk, np.broadcast_to(rows[0], (B - nb, W))])
            out = step(jnp.asarray(chunk))
            valid = np.asarray(out["valid"])[:nb]
            keys = fpr.to_u64(np.asarray(out["fp_hi"])[:nb],
                              np.asarray(out["fp_lo"])[:nb])
            yield c0, valid, keys

    edges, enabled = _csr_export(
        n, sorted_keys, order, expanded_arr, fams, fam_idx, chunks(),
        "engine_graph: successor key missing from the store — BFS "
        "incomplete?")

    # eager PyStates are fine at device-engine scale (bounded by --cap,
    # <= a few 1e6); the 1e8-scale path is ddd_graph's lazy StatesView
    states = [interp.from_struct(st.unpack(rows[i], lay, np), bounds)
              for i in range(n)]
    return states, edges, enabled, expanded_arr


class StatesView:
    """Lazy state access over a retained DDD host store: ``states[u]``
    materializes one PyState on demand (trace rendering), ``mask(prop)``
    evaluates a registered predicate vectorized over packed-row chunks
    (the scale path — no per-state Python objects)."""

    def __init__(self, host, schema, lay, bounds, n: int,
                 batch: int = 1 << 14):
        import numpy as np

        self._host, self._schema, self._lay = host, schema, lay
        self._bounds, self._n, self._batch = bounds, n, batch
        self._np = np

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, u: int):
        from raft_tla_tpu.ops import state as st

        np = self._np
        row = self._schema.unpack(self._host.read(int(u), 1), np)[0]
        return interp.from_struct(st.unpack(row, self._lay, np),
                                  self._bounds)

    def mask(self, prop: str):
        """Vectorized ``[n]`` bool array of a predicate (by PREDICATES
        name, or property name for back-compat); falls back to the
        scalar predicate when no vector twin is registered."""
        from raft_tla_tpu.ops import state as st

        np = self._np
        fn = PREDICATES[prop][1] if prop in PREDICATES \
            else _STRUCT_PREDICATES.get(prop)
        if fn is None:
            _form, pred = PROPERTIES[prop]
            return np.asarray([pred(self[u], self._bounds)
                               for u in range(self._n)], bool)
        out = np.zeros((self._n,), bool)
        for c0 in range(0, self._n, self._batch):
            nb = min(self._batch, self._n - c0)
            vecs = self._schema.unpack(self._host.read(c0, nb), np)
            out[c0:c0 + nb] = fn(st.unpack(vecs, self._lay, np),
                                 self._bounds)
        return out

    def close(self) -> None:
        self._host.close()


def ddd_graph(config: CheckConfig, caps=None):
    """:func:`engine_graph` on the DDD architecture — graph exports past
    every device-table ceiling, SYMMETRY included (VERDICT r2 weak #5).

    Runs the DDD engine (exact dedup in host RAM), keeps its stores, and
    re-expands the stored rows chunkwise to emit labeled edges, resolving
    successor keys through the key log.  Returns
    ``(states, edges, enabled, expanded)`` where ``states`` is a lazy
    :class:`StatesView` (``check`` uses its vectorized predicate mask).

    **Symmetry soundness** (why this builder accepts what engine_graph
    rejects): under SYMMETRY the engine's graph IS the orbit quotient,
    and for this module's fairness semantics the quotient check is
    exact, by the standard argument —

    - every registered predicate is permutation-invariant (set-level
      reads of role/commitIndex), so the ~P region is a union of orbits;
    - WF is per action FAMILY, and families are permutation-closed, so
      family-enabledness is orbit-invariant;
    - a fair lasso in the full graph projects to a fair lasso in the
      quotient (steps project to steps, labels keep their family,
      disabledness is orbit-invariant); conversely a fair quotient cycle
      lifts: replay its actions from any concrete member — each leg
      lands in the next orbit, and after at most |G| traversals the
      concrete walk revisits a state, closing a concrete cycle that
      takes the same family steps (and visits permuted copies of the
      same disabled-witness orbits), hence is fair.

    The rendered counterexample is therefore a QUOTIENT lasso: each
    shown state is an orbit representative, and consecutive steps are
    real transitions modulo a server/value permutation — the same
    witness form TLC prints for symmetric liveness runs.

    **View soundness** (round 5: registered views compose here too):
    every registered view is a machine-checked BISIMULATION
    (models/views.py; tests/test_views.py::test_deadvotes_bisimulation),
    which is strictly stronger than what the symmetry argument needs —
    view-equivalent states enable the same families and their
    successors stay view-equivalent, so fair lassos project to the
    view quotient and lift back step for step, and every registered
    predicate reads only view-preserved fields (role/commitIndex).
    The stored rows are full first-reached representatives (the view
    folds into the dedup key only), so predicate masks and rendered
    witnesses are evaluated on real states.

    **Practical size bound** (ADVICE r3 #2): the export itself is now
    flat-array — sorted-key ``searchsorted`` successor resolution, CSR
    edge storage (12 B/edge via :class:`_CSREdges`), one bool per
    (state, family) for enabledness — so its footprint is ~keys (8 B) +
    rows + edges, the same order as the engine's own stores.  The
    remaining ceiling is :func:`check`, whose subgraph/SCC structures
    are per-node Python lists over the ~P region; graphs are practical
    to a few 10^7 states, beyond which the fair-lasso check (not this
    export) needs its own array representation.
    """
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tla_tpu.ddd_engine import DDDEngine
    from raft_tla_tpu.ops import kernels
    from raft_tla_tpu.ops import state as st
    from raft_tla_tpu.utils import keyset

    cfg = _dc.replace(config, invariants=(), check_deadlock=False)
    eng = DDDEngine(cfg, caps)
    eng.check(retain_store=True)
    host, constore, keystore, n = eng.retained
    bounds = cfg.bounds
    lay, schema = eng.lay, eng.schema
    table = eng.table
    A, B = eng.A, cfg.chunk

    kw = keystore.read(0, n).view(np.uint32)
    keys = keyset.pack_keys(kw[:, 1], kw[:, 0])
    # successor resolution by binary search over the sorted key array —
    # no Python dict over n keys (ADVICE r3 #2: per-object overhead was
    # the real export ceiling, ~hundreds of bytes/state)
    order = np.argsort(keys)
    sorted_keys = keys[order]
    expanded = constore.read(0, n)[:, 0].astype(bool)
    constore.close()
    keystore.close()

    # Export program (VERDICT r4 weak #4: the re-expansion was the
    # liveness wall, ~400x the SCC check).  Two structural changes over
    # the naive per-chunk loop:
    #   1. only (valid, fp) are fetched, so XLA dead-code-eliminates
    #      the step's successor-row packing and constraint lanes
    #      (measured 1.17x per-chunk on CPU, runs/export_anatomy.py);
    #   2. K chunks run in ONE dispatch via lax.map, and segment s+1 is
    #      dispatched before s is harvested — per-dispatch cost (the
    #      tunnel's ~112 ms round-trip floor dominates 1024-row chunks
    #      on the chip) amortizes K-fold and overlaps host assembly.
    raw_step = kernels.build_step(bounds, cfg.spec, (), cfg.symmetry,
                                  view=cfg.view)
    # clamp by n: a sub-SB graph must not pad every dispatch to 64 chunks
    K = max(1, min((1 << 16) // B, -(-n // B)))
    SB = K * B
    seg_step = jax.jit(lambda vs: jax.lax.map(
        lambda v: (lambda o: (o["valid"], o["fp_hi"], o["fp_lo"]))(
            raw_step(v)), vs))
    fams = sorted({inst.family for inst in table})
    fam_idx = np.asarray([fams.index(inst.family) for inst in table],
                         np.int32)

    def dispatch(s0):
        ns = min(SB, n - s0)
        vecs = schema.unpack(host.read(s0, ns), np)
        if ns < SB:
            vecs = np.concatenate(
                [vecs, np.broadcast_to(vecs[:1],
                                       (SB - ns, vecs.shape[1]))])
        return seg_step(jnp.asarray(vecs).reshape(K, B, vecs.shape[1]))

    def chunks():
        pending = dispatch(0)
        for s0 in range(0, n, SB):
            nxt = dispatch(s0 + SB) if s0 + SB < n else None
            va, fh, fl = (np.asarray(x) for x in pending)  # sync here
            pending = nxt
            for k in range(K):
                c0 = s0 + k * B
                if c0 >= n:
                    break
                nb = min(B, n - c0)
                yield c0, va[k][:nb], keyset.pack_keys(
                    fh[k][:nb].reshape(nb, A),
                    fl[k][:nb].reshape(nb, A))

    edges, enabled = _csr_export(
        n, sorted_keys, order, expanded, fams, fam_idx, chunks(),
        "ddd_graph: successor key missing from the key log — store "
        "corrupt")

    states = StatesView(host, schema, lay, bounds, n)
    return states, edges, enabled, expanded


class _CSREdges:
    """``edges[u] -> [(aidx, v), ...]`` materialized on demand from CSR
    arrays — 12 B/edge flat storage instead of per-node Python lists of
    tuple objects.  Supports exactly the access patterns
    :func:`check` uses (indexing, ``len``, iteration)."""

    def __init__(self, indptr, aidx, vidx):
        self._indptr, self._aidx, self._vidx = indptr, aidx, vidx

    @property
    def n_edges(self) -> int:
        return int(self._indptr[-1])

    def __len__(self):
        return len(self._indptr) - 1

    def __getitem__(self, u):
        if u < 0 or u >= len(self):
            raise IndexError(u)
        s, e = self._indptr[u], self._indptr[u + 1]
        return list(zip(self._aidx[s:e].tolist(),
                        self._vidx[s:e].tolist()))


class _EnabledSets:
    """``enabled[u] -> {family, ...}`` view over an ``[n, F]`` bool
    matrix (one byte per (state, family) instead of a Python set per
    state)."""

    def __init__(self, mat, fams):
        self._mat, self._fams = mat, fams

    def __len__(self):
        return self._mat.shape[0]

    def __getitem__(self, u):
        if u < 0 or u >= len(self):
            raise IndexError(u)
        row = self._mat[u]
        return {f for f, b in zip(self._fams, row) if b}


def _sccs(n: int, adj) -> list:
    """Iterative Tarjan; returns SCCs as lists of node ids."""
    UNVISITED = -1
    low = [UNVISITED] * n
    num = [UNVISITED] * n
    on_stack = [False] * n
    stack: list = []
    out = []
    counter = 0
    for root in range(n):
        if num[root] != UNVISITED:
            continue
        work = [(root, 0)]
        while work:
            u, pi = work[-1]
            if pi == 0:
                num[u] = low[u] = counter
                counter += 1
                stack.append(u)
                on_stack[u] = True
            recurse = False
            for i in range(pi, len(adj[u])):
                v = adj[u][i]
                if num[v] == UNVISITED:
                    work[-1] = (u, i + 1)
                    work.append((v, 0))
                    recurse = True
                    break
                if on_stack[v]:
                    low[u] = min(low[u], num[v])
            if recurse:
                continue
            if low[u] == num[u]:
                comp = []
                while True:
                    v = stack.pop()
                    on_stack[v] = False
                    comp.append(v)
                    if v == u:
                        break
                out.append(comp)
            work.pop()
            if work:
                p, _ = work[-1]
                low[p] = min(low[p], low[u])
    return out


def _path(adj_labeled, src: int, dsts: set):
    """BFS path src -> (first reachable of dsts); [(aidx, node), ...]."""
    hit = _path_multi(adj_labeled, [src], dsts)
    return hit[1] if hit is not None else None



def _path_multi(adj_labeled, srcs, dsts):
    """BFS from MANY sources: ``(origin_src, [(aidx, node), ...])`` to
    the first reachable member of ``dsts``, or None."""
    prev = {}
    frontier = []
    for s in srcs:
        if s in prev:
            continue
        prev[s] = None
        if s in dsts:
            return s, []
        frontier.append(s)
    while frontier:
        nxt = []
        for u in frontier:
            for aidx, v in adj_labeled[u]:
                if v in prev:
                    continue
                prev[v] = (u, aidx)
                if v in dsts:
                    path = []
                    cur = v
                    while prev[cur] is not None:
                        pu, pa = prev[cur]
                        path.append((pa, cur))
                        cur = pu
                    path.reverse()
                    return cur, path
                nxt.append(v)
        frontier = nxt
    return None


def _leadsto_prefix(full_adj, sub_adj, seeds, entry):
    """Two-leg prefix for a refuted ``P ~> Q``: Init -> (any states) ->
    a P-and-not-Q seed -> (~Q states only) -> the lasso entry.  The
    second leg runs first (multi-source, so it picks a seed that
    actually reaches the entry inside the restricted region)."""
    hit = _path_multi(sub_adj, seeds, {entry})
    if hit is None:
        raise RuntimeError(         # entry ∈ reach(seeds) by construction
            "leads-to prefix: lasso entry unreachable from seeds")
    origin, leg2 = hit
    leg1 = _path(full_adj, 0, {origin}) or []
    return leg1 + leg2


def _csr_reach(indptr, dst, src0, n):
    """Vectorized BFS reachability over a CSR digraph: bool[n] with
    reach[srcs]=True; ``src0`` is one root or an array of roots
    (multi-source, the ~> seed set); per-round cost proportional to the
    DELTA frontier's edges (ragged-arange gather), total O(E)."""
    import numpy as np

    reach = np.zeros(n, bool)
    srcs = np.atleast_1d(np.asarray(src0, np.int64))
    if srcs.size == 0:
        return reach
    reach[srcs] = True
    delta = srcs
    while delta.size:
        starts = indptr[delta]
        lens = indptr[delta + 1] - starts
        total = int(lens.sum())
        if not total:
            break
        base = np.repeat(starts, lens)
        offs = np.arange(total, dtype=np.int64) \
            - np.repeat(np.cumsum(lens) - lens, lens)
        targets = dst[base + offs]
        new = np.unique(targets[~reach[targets]])
        reach[new] = True
        delta = new
    return reach


class _LazyAdj:
    """``adj[u] -> [(aidx, v), ...]`` computed on demand from CSR arrays
    with an optional destination filter — the adjacency view
    :func:`_path` walks during counterexample rendering (only refuted
    verdicts pay for it)."""

    def __init__(self, indptr, aidx, dst, dst_ok=None):
        self._indptr, self._aidx, self._dst = indptr, aidx, dst
        self._dst_ok = dst_ok

    def __getitem__(self, u):
        s0, e0 = int(self._indptr[u]), int(self._indptr[u + 1])
        a = self._aidx[s0:e0]
        v = self._dst[s0:e0]
        if self._dst_ok is not None:
            m = self._dst_ok(v)
            a, v = a[m], v[m]
        return list(zip(a.tolist(), v.tolist()))


def _fair_witness(nodes, wf, table, enabled, sub_labeled_of):
    """If a fair cycle exists through ``nodes``, a witness per WF family
    (('edge', u, aidx, v) or ('disabled', u)); None otherwise.  The
    shared semantics of check()'s fair_here (one definition for the
    list and CSR paths)."""
    node_set = set(nodes)
    wit = {}
    for fam in wf:
        found = None
        for u in nodes:
            lst = sub_labeled_of(u)
            if fam == "Next":
                hit = next(((a, v) for a, v in lst if v in node_set),
                           None)
                if hit is not None:
                    found = ("edge", u, hit[0], hit[1])
                    break
                if not enabled[u]:
                    found = ("disabled", u)
                    break
            else:
                hit = next(((a, v) for a, v in lst
                            if v in node_set
                            and table[a].family == fam), None)
                if hit is not None:
                    found = ("edge", u, hit[0], hit[1])
                    break
                if fam not in enabled[u]:
                    found = ("disabled", u)
                    break
        if found is None:
            return None
        wit[fam] = found
    return wit


def _render_lasso(states, table, best, reach_adj, scc_adj,
                  prefix_steps=None):
    """Prefix + witness-visiting cycle for a refuted verdict (the
    rendering block shared by both check paths).  ``prefix_steps``
    overrides the default root->entry search (the ~> two-leg prefix)."""
    nodes, wit, entry = best
    if prefix_steps is None:
        prefix_steps = _path(reach_adj, 0, {entry}) or []
    prefix = [(None, states[0])] + [
        (table[a].label(), states[v]) for a, v in prefix_steps]
    cycle = []
    cur = entry
    for fam, w in wit.items():
        if w[0] == "edge":
            _kind, u, a, v = w
            for pa, pv in (_path(scc_adj, cur, {u}) or []):
                cycle.append((table[pa].label(), states[pv]))
            cycle.append((table[a].label(), states[v]))
            cur = v
        else:                               # ("disabled", u): visit u
            _kind, u = w
            for pa, pv in (_path(scc_adj, cur, {u}) or []):
                cycle.append((table[pa].label(), states[pv]))
            cur = u
    for pa, pv in (_path(scc_adj, cur, {entry}) or []):
        cycle.append((table[pa].label(), states[pv]))
    if not cycle:
        cycle = [("<stutter>", states[entry])]
    return cycle, prefix


def _check_csr(config, pspec, wf, states, edges, enabled, n,
               n_edges) -> LivenessResult:
    """The array fast path of :func:`check` for CSR graph exports
    (liveness at 1e7-1e8-state scale — VERDICT r3's 5-server gap): C++
    Tarjan SCC over the target-restricted CSR (utils/native.scc_csr),
    vectorized reachability and stutter/singleton filtering; only
    nontrivial candidate SCCs (size >= 2 or self-loop, intersecting the
    reachable region) enter the per-node Python witness search, whose
    semantics are shared with the list path (_fair_witness)."""
    import numpy as np

    form = pspec.form
    prop = pspec.text
    bounds = config.bounds
    table = S.action_table(bounds, config.spec)
    indptr = edges._indptr
    aidx = edges._aidx
    vidx = edges._vidx.astype(np.int64, copy=False)

    def _mask(pred_name):
        if isinstance(states, StatesView):
            return np.asarray(states.mask(pred_name), bool)
        fn = PREDICATES[pred_name][0]
        return np.asarray([fn(s, bounds) for s in states], bool)

    p_mask = _mask(pspec.pred_names[0])
    tgt_mask = _mask(pspec.pred_names[1]) if form == LEADS_TO else p_mask
    allowed = ~tgt_mask

    src = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
    keep = allowed[src] & allowed[vidx]
    cnt = np.bincount(src[keep], minlength=n)
    indptr2 = np.zeros(n + 1, np.int64)
    np.cumsum(cnt, out=indptr2[1:])
    dst2 = vidx[keep]                      # src-major order preserved
    a2 = aidx[keep]
    src2 = src[keep]

    from raft_tla_tpu.utils import native as native_mod
    comp, ncomp = native_mod.scc_csr(indptr2, dst2)

    def sub_labeled_of(u):
        s0, e0 = int(indptr2[u]), int(indptr2[u + 1])
        return list(zip(a2[s0:e0].tolist(), dst2[s0:e0].tolist()))

    seeds = None
    if form == EVENTUALLY:
        reach_ok = bool(allowed[0])
        reach = _csr_reach(indptr2, dst2, 0, n) if reach_ok \
            else np.zeros(n, bool)
        reach_adj = _LazyAdj(indptr2, a2, dst2)
    elif form == INFINITELY_OFTEN:
        reach = _csr_reach(indptr, vidx, 0, n)
        reach_adj = _LazyAdj(indptr, aidx, vidx)
    else:                                           # LEADS_TO
        full = _csr_reach(indptr, vidx, 0, n)
        seeds = np.nonzero(full & p_mask & allowed)[0]
        reach = _csr_reach(indptr2, dst2, seeds, n)
        reach_adj = _LazyAdj(indptr2, a2, dst2)

    cand_nodes = reach & allowed
    n_checked = 0
    best = None

    # (a) stuttering lassos, vectorized over the enabled matrix when the
    # export provides one (_EnabledSets); per-family disabledness
    if hasattr(enabled, "_mat"):
        mat = enabled._mat
        fams = enabled._fams
        stut = np.ones(n, bool)
        for fam in wf:
            if fam == "Next":
                stut &= ~mat.any(axis=1)
            elif fam in fams:
                stut &= ~mat[:, fams.index(fam)]
            # else: family absent from this spec subset -> disabled
            # everywhere -> no constraint (the list path's
            # `fam not in enabled[u]` reads the same way)
    else:
        stut = np.asarray(
            [all((not enabled[u]) if fam == "Next"
                 else (fam not in enabled[u]) for fam in wf)
             for u in range(n)], bool)
    hits = np.nonzero(cand_nodes & stut)[0]
    if hits.size:
        u = int(hits[0])
        n_checked += int((np.nonzero(cand_nodes)[0] <= u).sum())
        best = ([u], {fam: ("disabled", u) for fam in wf}, u)
    else:
        n_checked += int(cand_nodes.sum())

    # (b) real cycles: nontrivial SCCs of the restricted graph that
    # intersect the reachable region
    if best is None:
        sizes = np.bincount(comp, minlength=ncomp)
        has_self = np.zeros(sizes.shape[0], bool)
        self_e = src2 == dst2
        if self_e.any():
            has_self[np.unique(comp[src2[self_e]])] = True
        reach_comps = np.unique(comp[cand_nodes]) if cand_nodes.any() \
            else np.zeros(0, np.int64)
        cyc = (sizes >= 2) | has_self
        order_nodes = np.argsort(comp, kind="stable")
        bounds_ = np.zeros(sizes.shape[0] + 1, np.int64)
        np.cumsum(sizes, out=bounds_[1:])
        for c in reach_comps.tolist():
            if not cyc[c]:
                continue
            n_checked += 1
            nodes = order_nodes[bounds_[c]:bounds_[c + 1]].tolist()
            wit = _fair_witness(nodes, wf, table, enabled,
                                sub_labeled_of)
            if wit is not None:
                entry = next(u for u in nodes if reach[u])
                best = (nodes, wit, entry)
                break

    if best is None:
        return LivenessResult(prop=prop, holds=True, violation=None,
                              n_states=n, n_edges=n_edges,
                              n_sccs_checked=n_checked)

    in_scc = np.zeros(n, bool)
    in_scc[best[0]] = True
    scc_adj = _LazyAdj(indptr2, a2, dst2, dst_ok=lambda v: in_scc[v])
    prefix_steps = None
    if form == LEADS_TO:
        prefix_steps = _leadsto_prefix(
            _LazyAdj(indptr, aidx, vidx), reach_adj, seeds.tolist(),
            best[2])
    cycle, prefix = _render_lasso(states, table, best, reach_adj,
                                  scc_adj, prefix_steps=prefix_steps)
    violation = LassoViolation(prop=prop, prefix=prefix, cycle=cycle)
    return LivenessResult(prop=prop, holds=False, violation=violation,
                          n_states=n, n_edges=n_edges,
                          n_sccs_checked=n_checked)


def check(config: CheckConfig, prop: str,
          wf: tuple = ("Next",), graph=None) -> LivenessResult:
    """Check ``prop`` under weak fairness of the given action families.

    ``wf`` entries are action family names (``spec.ALL_FAMILIES``) or
    ``"Next"`` for the whole relation; ``wf=()`` assumes no fairness, under
    which any eventuality is refuted by pure stuttering (the reference
    spec's actual situation, ``raft.tla:469``).  ``graph`` accepts a
    prebuilt :func:`explore_graph` result so several properties can share
    one (dominant-cost) exploration.
    """
    pspec = parse_property(prop)
    form = pspec.form
    bounds = config.bounds
    table = S.action_table(bounds, config.spec)
    for fam in wf:
        if fam != "Next" and fam not in S.ALL_FAMILIES:
            raise ValueError(f"unknown WF action family {fam!r}")

    states, edges, enabled, expanded = graph if graph is not None \
        else explore_graph(config)
    n = len(states)
    # O(1) for CSR exports, O(n) list walk otherwise — never O(edges)
    n_edges = edges.n_edges if hasattr(edges, "n_edges") \
        else sum(map(len, edges))
    if hasattr(edges, "_indptr"):
        # CSR graph export (ddd_graph): the array fast path — C++ SCC,
        # vectorized reach/stutter, Python only on nontrivial SCCs
        return _check_csr(config, pspec, wf, states, edges, enabled, n,
                          n_edges)

    def _mask(pred_name):
        if isinstance(states, StatesView):
            return states.mask(pred_name)
        fn = PREDICATES[pred_name][0]
        return [fn(s, bounds) for s in states]

    # The candidate cycle region: ~target states (target = P for <>P /
    # []<>P, Q for P ~> Q); cycle edges must stay inside it.
    p_mask = _mask(pspec.pred_names[0])
    tgt_mask = _mask(pspec.pred_names[1]) if form == LEADS_TO else p_mask
    allowed = [not p for p in tgt_mask]
    # one edges[u] materialization per node (CSR exports rebuild the
    # tuple list per access); sub derives from sub_labeled
    sub_labeled = [[(a, v) for a, v in edges[u] if allowed[v]]
                   if allowed[u] else [] for u in range(n)]
    sub = [[v for _a, v in lst] for lst in sub_labeled]

    def fair_here(nodes: list) -> dict | None:
        """If a fair cycle exists through these nodes, witness per WF
        family: ('edge', u, aidx, v) or ('disabled', u); None otherwise."""
        node_set = set(nodes)
        wit = {}
        for fam in wf:
            found = None
            for u in nodes:
                if fam == "Next":
                    if any(v in node_set for _a, v in sub_labeled[u]):
                        a, v = next((a, v) for a, v in sub_labeled[u]
                                    if v in node_set)
                        found = ("edge", u, a, v)
                        break
                    if not enabled[u]:
                        found = ("disabled", u)
                        break
                else:
                    hit = next((
                        (a, v) for a, v in sub_labeled[u]
                        if v in node_set and table[a].family == fam), None)
                    if hit is not None:
                        found = ("edge", u, hit[0], hit[1])
                        break
                    if fam not in enabled[u]:
                        found = ("disabled", u)
                        break
            if found is None:
                return None
            wit[fam] = found
        return wit

    def _bfs(adj, srcs):
        seen = set(srcs)
        frontier = list(srcs)
        while frontier:
            nxt = []
            for u in frontier:
                for _a, v in adj[u]:
                    if v not in seen:
                        seen.add(v)
                        nxt.append(v)
            frontier = nxt
        return seen

    # Reachability of the lasso's loop node: for <>P the whole prefix
    # must avoid P; for []<>P any path does; for P ~> Q the lasso must
    # be reachable from some (reachable) P-state through ~Q states only
    # — the suffix after that P occurrence never meets Q.
    seeds = None
    if form == EVENTUALLY:
        reach_adj = sub_labeled if allowed[0] else [[]] * n
        reach = _bfs(sub_labeled, [0] if allowed[0] else [])
    elif form == INFINITELY_OFTEN:
        reach_adj = edges
        reach = _bfs(edges, [0])
    else:                                           # LEADS_TO
        full = _bfs(edges, [0])
        seeds = sorted(u for u in full if p_mask[u] and allowed[u])
        reach = _bfs(sub_labeled, seeds)
        reach_adj = sub_labeled     # prefix rendered in two legs below

    def stutter_witness(u: int) -> dict | None:
        """Pure stutter at u: fair iff every wf family is disabled there."""
        wit = {}
        for fam in wf:
            dis = (not enabled[u]) if fam == "Next" \
                else (fam not in enabled[u])
            if not dis:
                return None
            wit[fam] = ("disabled", u)
        return wit

    n_checked = 0
    best = None
    # (a) stuttering lassos: any reachable ~P state where fairness cannot
    # force a step (with wf=() that is every such state — the reference
    # spec's fairness-free reality).
    for u in sorted(reach):
        if not allowed[u]:
            continue
        n_checked += 1
        wit = stutter_witness(u)
        if wit is not None:
            best = ([u], wit, u)
            break
    # (b) real cycles: fair SCCs of the ~P subgraph.
    if best is None:
        for comp in _sccs(n, sub):
            comp_r = [u for u in comp if u in reach]
            if not comp_r:
                continue
            has_cycle = len(comp) > 1 or any(
                v == comp[0] for v in sub[comp[0]])
            if not has_cycle:
                continue
            n_checked += 1
            wit = fair_here(comp)
            if wit is not None:
                best = (comp, wit, comp_r[0])
                break

    if best is None:
        return LivenessResult(prop=prop, holds=True, violation=None,
                              n_states=n, n_edges=n_edges,
                              n_sccs_checked=n_checked)

    nodes, wit, entry = best
    node_set = set(nodes)
    # Cycle: a closed walk from entry visiting EVERY fairness witness —
    # each edge-witness is traversed, and each disabled-witness node is
    # visited (a walk that skipped one could itself be unfair for that
    # family: forever enabled along the walk, never taken).  Routing stays
    # strictly inside the SCC (strong connectivity guarantees the legs).
    scc_adj = [[(a, v) for a, v in sub_labeled[u] if v in node_set]
               if u in node_set else [] for u in range(n)]
    prefix_steps = _leadsto_prefix(edges, sub_labeled, seeds, entry) \
        if form == LEADS_TO else None
    cycle, prefix = _render_lasso(states, table, best, reach_adj,
                                  scc_adj, prefix_steps=prefix_steps)
    violation = LassoViolation(prop=prop, prefix=prefix, cycle=cycle)
    return LivenessResult(prop=prop, holds=False, violation=violation,
                          n_states=n, n_edges=n_edges,
                          n_sccs_checked=n_checked)
