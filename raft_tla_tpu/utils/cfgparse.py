"""Parser for TLC model config files (the L5 layer).

Byte-compatible with the reference's ``raft.cfg:1-15``, whose grammar is:

- ``SPECIFICATION Spec``            (``raft.cfg:1``)
- ``INVARIANT NoTwoLeaders``        (``raft.cfg:3``)
- ``CONSTANTS`` followed by indented ``Name = binding`` lines with optional
  ``\\*`` end-of-line comments (``raft.cfg:5-15``), where a binding is either
  a model value (``Follower = "Follower"`` / ``Nil = Nil``) or a finite set
  (``Server = {s1, s2, s3}``).

Additionally understood (the TLC stanzas the reference does not use but the
checker supports): ``INVARIANTS``, ``CONSTRAINT``, ``PROPERTY``,
``CONSTANT`` (singular), so configs written for stock TLC parse unchanged.

The parsed cfg is mapped onto the built-in compiled Raft model: the cardinality
of ``Server``/``Value`` becomes :class:`raft_tla_tpu.config.Bounds`
``n_servers``/``n_values``; invariant names resolve against the invariant
registry.  Bound parameters (MaxTerm &c.) come from CLI/:class:`Bounds`, and
``models/tla_export.py`` emits the matching ``CONSTRAINT`` module for stock
TLC parity runs.

Diagnostics are load-bearing here: a typo'd stanza keyword or invariant name
must fail *loudly at parse/resolve time* with the offending line number and
the known names (unknown names silently checking nothing is the classic TLC
footgun).  The parser records the source line of every name it reads
(:attr:`TLCConfig.lines`) so both the hard-error path
(:func:`resolve_names`, used by check.py) and the diagnostic path
(analysis/cfglint Pass 2) can point at the exact line.
"""

from __future__ import annotations

import dataclasses
import difflib
import re

_STANZAS = (
    "SPECIFICATION",
    "INVARIANTS",
    "INVARIANT",
    "CONSTANTS",
    "CONSTANT",
    "CONSTRAINTS",
    "CONSTRAINT",
    "PROPERTIES",
    "PROPERTY",
    "INIT",
    "NEXT",
    "SYMMETRY",
    "VIEW",
)


@dataclasses.dataclass
class TLCConfig:
    specification: str | None = None
    init: str | None = None
    next: str | None = None
    invariants: list[str] = dataclasses.field(default_factory=list)
    properties: list[str] = dataclasses.field(default_factory=list)
    constraints: list[str] = dataclasses.field(default_factory=list)
    # Name -> python value: list[str] for set bindings, str for model values.
    constants: dict = dataclasses.field(default_factory=dict)
    symmetry: list[str] = dataclasses.field(default_factory=list)
    view: str | None = None
    # (kind, name) -> 1-based source line, e.g. ("invariant", "NoTwoLeaders")
    # -> 3.  Kinds: invariant, property, constraint, constant, symmetry,
    # view, specification, init, next.  Diagnostics only; equality and the
    # model mapping ignore it.
    lines: dict = dataclasses.field(default_factory=dict, compare=False)

    def server_names(self) -> list[str]:
        v = self.constants.get("Server")
        if not isinstance(v, list):
            raise ValueError("cfg does not bind Server to a finite set")
        return v

    def value_names(self) -> list[str]:
        v = self.constants.get("Value")
        if not isinstance(v, list):
            raise ValueError("cfg does not bind Value to a finite set")
        return v

    def line_of(self, kind: str, name: str) -> int | None:
        return self.lines.get((kind, name))


def _strip_comment(line: str) -> str:
    # TLA+ end-of-line comment: \* ... (also tolerate (* ... *) on one line)
    line = re.sub(r"\(\*.*?\*\)", " ", line)
    idx = line.find("\\*")
    if idx >= 0:
        line = line[:idx]
    return line.strip()


def _parse_set(text: str) -> list[str]:
    inner = text.strip()
    if not (inner.startswith("{") and inner.endswith("}")):
        raise ValueError(f"not a set literal: {text!r}")
    body = inner[1:-1].strip()
    if not body:
        return []
    toks = [tok.strip() for tok in body.split(",")]
    if any(not t for t in toks):
        raise ValueError(f"empty element in set literal: {text!r}")
    return toks


def parse_cfg(text: str) -> TLCConfig:
    cfg = TLCConfig()
    mode: str | None = None
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = _strip_comment(raw)
        if not line:
            continue
        # A stanza keyword may start the line, optionally with an inline value
        # (separated by any whitespace — stock TLC accepts tabs too).
        parts = line.split(None, 1)
        if parts[0] in _STANZAS:
            mode = parts[0]
            line = parts[1].strip() if len(parts) > 1 else ""
            if not line:
                continue
        if mode in ("SPECIFICATION",):
            cfg.specification = line
            cfg.lines[("specification", line)] = lineno
        elif mode == "INIT":
            cfg.init = line
            cfg.lines[("init", line)] = lineno
        elif mode == "NEXT":
            cfg.next = line
            cfg.lines[("next", line)] = lineno
        elif mode in ("INVARIANT", "INVARIANTS"):
            # Bare registry names may share a line like stock TLC; any
            # line that is NOT all bare identifiers is one whole-line
            # predicate EXPRESSION (frontend/predicate.py grammar).
            from raft_tla_tpu.frontend.predicate import is_expression
            names = line.split()
            if any(is_expression(nm) for nm in names):
                text = " ".join(names)
                cfg.invariants.append(text)
                cfg.lines[("invariant", text)] = lineno
            else:
                for name in names:
                    cfg.invariants.append(name)
                    cfg.lines[("invariant", name)] = lineno
        elif mode in ("PROPERTY", "PROPERTIES"):
            # temporal FORMULAS (<>P, []<>P, P ~> Q) are one property
            # per line; bare names may share a line like INVARIANTS
            if "<>" in line or "~>" in line:
                formula = " ".join(line.split())
                cfg.properties.append(formula)
                cfg.lines[("property", formula)] = lineno
            else:
                for name in line.split():
                    cfg.properties.append(name)
                    cfg.lines[("property", name)] = lineno
        elif mode in ("CONSTRAINT", "CONSTRAINTS"):
            for name in line.split():
                cfg.constraints.append(name)
                cfg.lines[("constraint", name)] = lineno
        elif mode == "SYMMETRY":
            for name in line.split():
                cfg.symmetry.append(name)
                cfg.lines[("symmetry", name)] = lineno
        elif mode == "VIEW":
            cfg.view = line
            cfg.lines[("view", line)] = lineno
        elif mode in ("CONSTANT", "CONSTANTS"):
            if "=" not in line:
                raise ValueError(
                    f"line {lineno}: bad CONSTANTS binding: {raw!r}")
            name, _, val = line.partition("=")
            name, val = name.strip(), val.strip()
            # "<-" substitutions are not supported (not used by the reference).
            if val.startswith("{"):
                cfg.constants[name] = _parse_set(val)
            else:
                cfg.constants[name] = val.strip('"')
            cfg.lines[("constant", name)] = lineno
        else:
            raise ValueError(
                f"line {lineno}: line outside any stanza: {raw!r} "
                f"(known stanzas: {', '.join(_STANZAS)})")
    return cfg


def suggest(name: str, known) -> list[str]:
    """Did-you-mean candidates for an unknown cfg name."""
    return difflib.get_close_matches(name, sorted(known), n=3, cutoff=0.5)


def unknown_names(names, known) -> list[tuple[str, list[str]]]:
    """The subset of ``names`` not in ``known``, each with suggestions.
    Non-raising — analysis/cfglint turns these into findings."""
    known = set(known)
    return [(n, suggest(n, known)) for n in names if n not in known]


def resolve_names(names, known, kind: str, *, cfg: TLCConfig | None = None,
                  path: str | None = None) -> list[str]:
    """Validate cfg names against a registry, raising on the first unknown
    with the offending source line, a did-you-mean, and the full registry
    (shared by check.py config resolution and the Pass 2 lint)."""
    bad = unknown_names(names, known)
    if not bad:
        return list(names)
    name, hints = bad[0]
    where = ""
    if cfg is not None:
        lineno = cfg.line_of(kind, name)
        if lineno is not None:
            where = f"{path or 'cfg'} line {lineno}: "
    hint_txt = f" (did you mean: {', '.join(hints)}?)" if hints else ""
    raise ValueError(
        f"{where}unknown {kind} {name!r}{hint_txt}; "
        f"known: {', '.join(sorted(known))}")


def load_cfg(path: str) -> TLCConfig:
    with open(path, "r", encoding="utf-8") as f:
        return parse_cfg(f.read())
