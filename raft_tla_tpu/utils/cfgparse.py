"""Parser for TLC model config files (the L5 layer).

Byte-compatible with the reference's ``raft.cfg:1-15``, whose grammar is:

- ``SPECIFICATION Spec``            (``raft.cfg:1``)
- ``INVARIANT NoTwoLeaders``        (``raft.cfg:3``)
- ``CONSTANTS`` followed by indented ``Name = binding`` lines with optional
  ``\\*`` end-of-line comments (``raft.cfg:5-15``), where a binding is either
  a model value (``Follower = "Follower"`` / ``Nil = Nil``) or a finite set
  (``Server = {s1, s2, s3}``).

Additionally understood (the TLC stanzas the reference does not use but the
checker supports): ``INVARIANTS``, ``CONSTRAINT``, ``PROPERTY``,
``CONSTANT`` (singular), so configs written for stock TLC parse unchanged.

The parsed cfg is mapped onto the built-in compiled Raft model: the cardinality
of ``Server``/``Value`` becomes :class:`raft_tla_tpu.config.Bounds`
``n_servers``/``n_values``; invariant names resolve against the invariant
registry.  Bound parameters (MaxTerm &c.) come from CLI/:class:`Bounds`, and
``models/tla_export.py`` emits the matching ``CONSTRAINT`` module for stock
TLC parity runs.
"""

from __future__ import annotations

import dataclasses
import re

_STANZAS = (
    "SPECIFICATION",
    "INVARIANTS",
    "INVARIANT",
    "CONSTANTS",
    "CONSTANT",
    "CONSTRAINTS",
    "CONSTRAINT",
    "PROPERTIES",
    "PROPERTY",
    "INIT",
    "NEXT",
    "SYMMETRY",
    "VIEW",
)


@dataclasses.dataclass
class TLCConfig:
    specification: str | None = None
    init: str | None = None
    next: str | None = None
    invariants: list[str] = dataclasses.field(default_factory=list)
    properties: list[str] = dataclasses.field(default_factory=list)
    constraints: list[str] = dataclasses.field(default_factory=list)
    # Name -> python value: list[str] for set bindings, str for model values.
    constants: dict = dataclasses.field(default_factory=dict)
    symmetry: list[str] = dataclasses.field(default_factory=list)
    view: str | None = None

    def server_names(self) -> list[str]:
        v = self.constants.get("Server")
        if not isinstance(v, list):
            raise ValueError("cfg does not bind Server to a finite set")
        return v

    def value_names(self) -> list[str]:
        v = self.constants.get("Value")
        if not isinstance(v, list):
            raise ValueError("cfg does not bind Value to a finite set")
        return v


def _strip_comment(line: str) -> str:
    # TLA+ end-of-line comment: \* ... (also tolerate (* ... *) on one line)
    line = re.sub(r"\(\*.*?\*\)", " ", line)
    idx = line.find("\\*")
    if idx >= 0:
        line = line[:idx]
    return line.strip()


def _parse_set(text: str) -> list[str]:
    inner = text.strip()
    if not (inner.startswith("{") and inner.endswith("}")):
        raise ValueError(f"not a set literal: {text!r}")
    body = inner[1:-1].strip()
    if not body:
        return []
    toks = [tok.strip() for tok in body.split(",")]
    if any(not t for t in toks):
        raise ValueError(f"empty element in set literal: {text!r}")
    return toks


def parse_cfg(text: str) -> TLCConfig:
    cfg = TLCConfig()
    mode: str | None = None
    for raw in text.splitlines():
        line = _strip_comment(raw)
        if not line:
            continue
        # A stanza keyword may start the line, optionally with an inline value
        # (separated by any whitespace — stock TLC accepts tabs too).
        parts = line.split(None, 1)
        if parts[0] in _STANZAS:
            mode = parts[0]
            line = parts[1].strip() if len(parts) > 1 else ""
            if not line:
                continue
        if mode in ("SPECIFICATION",):
            cfg.specification = line
        elif mode == "INIT":
            cfg.init = line
        elif mode == "NEXT":
            cfg.next = line
        elif mode in ("INVARIANT", "INVARIANTS"):
            cfg.invariants.extend(line.split())
        elif mode in ("PROPERTY", "PROPERTIES"):
            # temporal FORMULAS (<>P, []<>P, P ~> Q) are one property
            # per line; bare names may share a line like INVARIANTS
            if "<>" in line or "~>" in line:
                cfg.properties.append(" ".join(line.split()))
            else:
                cfg.properties.extend(line.split())
        elif mode in ("CONSTRAINT", "CONSTRAINTS"):
            cfg.constraints.extend(line.split())
        elif mode == "SYMMETRY":
            cfg.symmetry.extend(line.split())
        elif mode == "VIEW":
            cfg.view = line
        elif mode in ("CONSTANT", "CONSTANTS"):
            if "=" not in line:
                raise ValueError(f"bad CONSTANTS binding: {raw!r}")
            name, _, val = line.partition("=")
            name, val = name.strip(), val.strip()
            # "<-" substitutions are not supported (not used by the reference).
            if val.startswith("{"):
                cfg.constants[name] = _parse_set(val)
            else:
                cfg.constants[name] = val.strip('"')
        else:
            raise ValueError(f"line outside any stanza: {raw!r}")
    return cfg


def load_cfg(path: str) -> TLCConfig:
    with open(path, "r", encoding="utf-8") as f:
        return parse_cfg(f.read())
