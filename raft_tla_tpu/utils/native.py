"""ctypes bindings for the C++ host runtime (native/host_store.cc).

Builds the shared library on first use with the baked-in g++ (no pybind11 in
the image — SURVEY §2.8 note; plain C ABI + ctypes instead).  Every entry
point has a NumPy fallback twin so the checker runs — more slowly and
host-RAM-hungry — even where a toolchain is missing; ``HAS_NATIVE`` reports
which implementation is live, and tests assert the two agree.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import tempfile

import numpy as np

from raft_tla_tpu.ops import fingerprint as fpr

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "host_store.cc")
_LIB_DIR = os.path.join(os.path.dirname(_SRC), "build")

_i32p = ctypes.POINTER(ctypes.c_int32)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_i64p = ctypes.POINTER(ctypes.c_int64)


def _build() -> str | None:
    # The library file is named by the source hash: freshness is content-
    # based (mtimes lie after a fresh clone), and concurrent builders race
    # benignly — both produce identical bytes and the os.replace is atomic.
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError as e:
        # wheel installs ship the package without the sibling native/
        # tree — the NumPy fallback serves them (same results, slower)
        print(f"native source unavailable ({e}); using NumPy fallback",
              file=sys.stderr)
        return None
    lib = os.path.join(_LIB_DIR, f"libraft_host-{digest}.so")
    if os.path.exists(lib):
        return lib
    os.makedirs(_LIB_DIR, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_LIB_DIR)
    os.close(fd)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib)
    except (OSError, subprocess.SubprocessError) as e:
        print(f"native build failed ({e}); using NumPy fallback",
              file=sys.stderr)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return lib


def _load():
    path = _build()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.store_create.restype = ctypes.c_void_p
    lib.store_create.argtypes = [ctypes.c_int32]
    lib.store_destroy.argtypes = [ctypes.c_void_p]
    lib.store_size.restype = ctypes.c_int64
    lib.store_size.argtypes = [ctypes.c_void_p]
    lib.store_append.restype = ctypes.c_int64
    lib.store_append.argtypes = [ctypes.c_void_p, _i32p, ctypes.c_int64]
    lib.store_read.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                               ctypes.c_int64, _i32p]
    lib.store_append_links.restype = ctypes.c_int64
    lib.store_append_links.argtypes = [ctypes.c_void_p, _i64p, _i32p,
                                       ctypes.c_int64]
    lib.store_read_links.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                     ctypes.c_int64, _i64p, _i32p]
    lib.store_trace_chain.restype = ctypes.c_int64
    lib.store_trace_chain.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      _i64p, ctypes.c_int64]
    lib.fingerprint_rows.argtypes = [
        _i32p, ctypes.c_int64, ctypes.c_int32, _u32p, _u32p,
        ctypes.c_uint32, ctypes.c_uint32, _u32p, _u32p]
    lib.scc_tarjan.restype = ctypes.c_int64
    lib.scc_tarjan.argtypes = [ctypes.c_int64, _i64p, _i64p, _i64p]
    return lib


_lib = _load()
HAS_NATIVE = _lib is not None


def _as_i32(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int32)


def _as_i64(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int64)


class HostStore:
    """Append-only host store of packed state rows + trace links.

    The TLC ``states/`` analog (SURVEY §2.8): discovery-indexed, append-only,
    host-RAM resident.  C++-backed when the toolchain is available.

    Safe for ONE appender thread plus concurrent readers of disjoint,
    already-published ranges: the C++ side publishes new rows through an
    atomic block directory and a release-stored size, so any read that
    bounds-checks against a previously observed ``len()`` sees fully
    written rows (the upload-prefetch contract, ``utils/prefetch``).
    Reads racing the rows being appended remain undefined.
    """

    def __init__(self, width: int):
        self.width = int(width)
        self._h = _lib.store_create(self.width)
        self._n_links = 0

    def __len__(self) -> int:
        return _lib.store_size(self._h)

    def append(self, rows: np.ndarray) -> int:
        rows = _as_i32(rows).reshape(-1, self.width)
        return _lib.store_append(
            self._h, rows.ctypes.data_as(_i32p), rows.shape[0])

    def read(self, start: int, n: int) -> np.ndarray:
        if not (0 <= start and start + n <= len(self)):
            raise IndexError(f"read [{start}, {start + n}) of {len(self)}")
        out = np.empty((n, self.width), np.int32)
        _lib.store_read(self._h, start, n, out.ctypes.data_as(_i32p))
        return out

    def append_links(self, parent: np.ndarray, lane: np.ndarray) -> int:
        # int64 parents: discovery indices outgrow int32 (VERDICT r3 #2)
        parent, lane = _as_i64(parent).ravel(), _as_i32(lane).ravel()
        assert parent.shape == lane.shape
        self._n_links = _lib.store_append_links(
            self._h, parent.ctypes.data_as(_i64p),
            lane.ctypes.data_as(_i32p), parent.shape[0])
        return self._n_links

    def read_links(self, start: int, n: int):
        if not (0 <= start and start + n <= self._n_links):
            raise IndexError(
                f"read_links [{start}, {start + n}) of {self._n_links}")
        parent = np.empty((n,), np.int64)
        lane = np.empty((n,), np.int32)
        _lib.store_read_links(self._h, start, n,
                              parent.ctypes.data_as(_i64p),
                              lane.ctypes.data_as(_i32p))
        return parent, lane

    def trace_chain(self, from_row: int) -> np.ndarray:
        """Discovery indices from the root to ``from_row`` (inclusive)."""
        if not (0 <= from_row < self._n_links):
            raise IndexError(
                f"trace_chain from {from_row} of {self._n_links}")
        cap = 1 << 10
        while True:
            out = np.empty((cap,), np.int64)
            n = _lib.store_trace_chain(self._h, from_row,
                                       out.ctypes.data_as(_i64p), cap)
            if n >= 0:
                return out[:n]
            cap *= 4

    def close(self) -> None:
        if self._h is not None:
            _lib.store_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _BlockList:
    """Appended ndarray blocks with O(log blocks) range reads (no global
    concatenation — the C++ twin's block structure, in NumPy).

    Concurrency contract (mirrors the C++ store): one appender thread
    plus readers of already-published rows.  ``append`` publishes the
    block before the new cumulative count, and readers snapshot both
    references once (GIL-atomic) before indexing, so a read below a
    previously observed ``len()`` always sees fully-appended blocks.
    """

    def __init__(self):
        self._blocks: list = []
        self._ends = np.zeros((0,), np.int64)   # cumulative row counts

    def __len__(self) -> int:
        ends = self._ends
        return int(ends[-1]) if ends.shape[0] else 0

    def append(self, block: np.ndarray) -> None:
        total = len(self) + block.shape[0]
        # block first, THEN the count that publishes it (the reader's
        # snapshot of _ends never indexes past its snapshot of _blocks)
        self._blocks.append(block)
        self._ends = np.append(self._ends, total)

    def read(self, start: int, n: int) -> np.ndarray:
        blocks, ends = self._blocks, self._ends   # one coherent snapshot
        total = int(ends[-1]) if ends.shape[0] else 0
        if not (0 <= start and start + n <= total):
            raise IndexError(f"read [{start}, {start + n}) of {total}")
        if n <= 0:
            return blocks[0][:0] if blocks else np.empty((0,), np.int32)
        out = []
        b = int(np.searchsorted(ends, start, side="right"))
        pos = start
        while n > 0:
            b_start = int(ends[b - 1]) if b else 0
            take = min(n, int(ends[b]) - pos)
            off = pos - b_start
            out.append(blocks[b][off:off + take])
            pos += take
            n -= take
            b += 1
        return np.concatenate(out) if len(out) != 1 else out[0]


class PyHostStore:
    """NumPy fallback with the identical interface — including the
    one-appender + disjoint-range-readers concurrency contract and the
    ``IndexError`` bounds messages of the C++ store."""

    def __init__(self, width: int):
        self.width = int(width)
        self._rows = _BlockList()
        self._parents = _BlockList()
        self._lanes = _BlockList()

    def __len__(self) -> int:
        return len(self._rows)

    def append(self, rows: np.ndarray) -> int:
        self._rows.append(_as_i32(rows).reshape(-1, self.width).copy())
        return len(self)

    def read(self, start: int, n: int) -> np.ndarray:
        if not (0 <= start and start + n <= len(self)):
            raise IndexError(f"read [{start}, {start + n}) of {len(self)}")
        return self._rows.read(start, n)

    def append_links(self, parent, lane) -> int:
        self._parents.append(_as_i64(parent).ravel().copy())
        self._lanes.append(_as_i32(lane).ravel().copy())
        return len(self._parents)

    def read_links(self, start: int, n: int):
        n_links = len(self._parents)
        if not (0 <= start and start + n <= n_links):
            raise IndexError(
                f"read_links [{start}, {start + n}) of {n_links}")
        return self._parents.read(start, n), self._lanes.read(start, n)

    def trace_chain(self, from_row: int) -> np.ndarray:
        n_links = len(self._parents)
        if not (0 <= from_row < n_links):
            raise IndexError(
                f"trace_chain from {from_row} of {n_links}")
        chain = []
        cur = int(from_row)
        while cur >= 0:
            chain.append(cur)
            cur = int(self._parents.read(cur, 1)[0])
        return np.asarray(chain[::-1], np.int64)

    def close(self) -> None:
        pass


def make_store(width: int):
    """The C++ store when available, the NumPy twin otherwise."""
    return HostStore(width) if HAS_NATIVE else PyHostStore(width)


class FileStore:
    """Append-only row store backed by a ckpt-format stream file — the
    external-memory regime TLC's own ``states/`` directory uses
    (reference ``.gitignore:2``): rows live on DISK, not host RAM, so a
    campaign's state capacity is the filesystem, and the file IS the
    checkpoint stream (``utils/ckpt`` header ``[n_rows, width]`` int64,
    then raw int32 rows) — snapshotting costs an fsync, not a copy.

    ``base``: global discovery index of the file's first row.  Reads
    and appends address GLOBAL indices; rows below ``base`` don't exist
    here (the frontier-retention engine mode drops pre-frontier levels
    entirely).  The header's row count is committed by :meth:`sync` —
    torn appends past the last sync are discarded on reopen, the same
    crash contract as ckpt.stream_rows_append.

    Reads are positionless (``os.preadv``), so one appender thread plus
    concurrent readers of rows below a previously observed ``len()`` is
    safe — the host-store concurrency contract, see :class:`HostStore`.
    """

    def __init__(self, path: str, width: int, base: int = 0,
                 reset: bool = False):
        self.path = path
        self.width = int(width)
        self.base = int(base)
        mode = "w+b" if (reset or not os.path.exists(path)) else "r+b"
        self._f = open(path, mode)
        if mode == "w+b":
            self._n = 0
            self._write_header()
        else:
            hdr = np.fromfile(self._f, np.int64, 2)
            if hdr.shape[0] != 2 or int(hdr[1]) != self.width:
                raise ValueError(
                    f"{path}: not a width-{self.width} row stream")
            self._n = int(hdr[0])
            # drop any torn tail beyond the committed header count —
            # but never extend: truncate() also GROWS a file with a
            # zero hole, and a stream shorter than its header is
            # corruption read() must surface, not silently zero-fill
            end = 16 + self._n * self.width * 4
            self._f.seek(0, os.SEEK_END)
            if self._f.tell() > end:
                self._f.truncate(end)

    def _write_header(self) -> None:
        self._f.seek(0)
        np.array([self._n, self.width], np.int64).tofile(self._f)

    def __len__(self) -> int:
        return self.base + self._n

    def append(self, rows: np.ndarray) -> int:
        rows = np.ascontiguousarray(rows, np.int32) \
            .reshape(-1, self.width)
        self._f.seek(16 + self._n * self.width * 4)
        rows.tofile(self._f)
        self._n += rows.shape[0]
        return len(self)

    def read(self, start: int, n: int) -> np.ndarray:
        if not (self.base <= start and start + n <= len(self)):
            raise IndexError(
                f"read [{start}, {start + n}) of [{self.base}, "
                f"{len(self)})")
        out = np.empty((n, self.width), np.int32)
        if n == 0:
            return out
        # Positionless pread into the preallocated buffer: no shared
        # fd-offset, so a prefetch-thread read never races the appender's
        # seek+tofile or a header rewrite in sync() (appends land via
        # numpy's fd dup, already page-cache-visible here).  One appender
        # plus readers of rows below an observed len() is safe; reads of
        # the appending tail are not.
        nbytes = n * self.width * 4
        mv = memoryview(out).cast("B")
        fd, off, got = self._f.fileno(), 16 + (start - self.base) \
            * self.width * 4, 0
        while got < nbytes:
            k = os.preadv(fd, [mv[got:]], off + got)
            if k <= 0:
                break
            got += k
        if got != nbytes:
            raise ValueError(
                f"{self.path}: truncated row stream — expected {n} rows "
                f"at index {start}, got {got // (self.width * 4)}")
        return out

    def sync(self) -> None:
        """Commit appended rows: data flush, then header, then fsync."""
        self._f.flush()
        os.fsync(self._f.fileno())
        self._write_header()
        self._f.flush()
        os.fsync(self._f.fileno())

    def trim(self, n_global: int) -> None:
        """Drop committed rows past ``n_global`` (resume hygiene: rows
        synced after the surviving metadata npz must be re-discovered,
        not trusted)."""
        n_local = n_global - self.base
        if n_local < 0:
            raise ValueError(
                f"trim to {n_global} below stream base {self.base}")
        if n_local < self._n:
            self._n = n_local
            self._f.truncate(16 + n_local * self.width * 4)
            self._write_header()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class LevelStore:
    """Current + next BFS level of rows, disk-backed (frontier
    retention).  The level-synchronous engines only ever read the level
    being expanded and append the one being discovered, so older
    levels are dead weight in a no-trace campaign — exactly TLC's
    memory regime (fingerprint set in RAM, states on disk,
    ``/root/reference/.gitignore:2``).

    Files are named ``{prefix}L{k}`` by BFS level index; ``rotate()``
    at a level boundary makes the append target the new current level
    and opens the next.  Files for levels older than current are
    deleted only by :meth:`delete_old` (the checkpoint writer calls it
    AFTER the metadata npz commits, so a crash mid-rotation still
    resumes from the previous snapshot's files).
    """

    def __init__(self, prefix: str, width: int, cur_idx: int,
                 cur_base: int, nxt_base: int, reset: bool = False):
        self.prefix = prefix
        self.width = int(width)
        self.cur_idx = int(cur_idx)
        self.cur = FileStore(f"{prefix}L{cur_idx}", width, cur_base,
                             reset=reset)
        self.nxt = FileStore(f"{prefix}L{cur_idx + 1}", width, nxt_base,
                             reset=reset)

    def __len__(self) -> int:
        return len(self.nxt)

    def append(self, rows: np.ndarray) -> int:
        return self.nxt.append(rows)

    def read(self, start: int, n: int) -> np.ndarray:
        """Read ``n`` rows from ONE level (the engines clamp blocks to
        the level end, so a range never spans the cur/nxt boundary)."""
        store = self.nxt if start >= self.nxt.base else self.cur
        if store is self.cur and start + n > len(self.cur):
            raise IndexError(
                f"read [{start}, {start + n}) spans the level boundary "
                f"at {len(self.cur)} — single-level reads only")
        return store.read(start, n)

    def rotate(self, delete_old: bool = False) -> None:
        """Level boundary: next becomes current; open a fresh next.
        ``delete_old`` removes the finished level's file immediately —
        only sound when no snapshot will ever resume from it."""
        old_path = self.cur.path
        if not delete_old:
            # commit the header: close() alone leaves the count stale,
            # and anything reopening the file (backtrace over retained
            # levels) would truncate the data to the stale count
            self.cur.sync()
        self.cur.close()
        if delete_old:
            try:
                os.remove(old_path)
            except OSError:
                pass
        self.cur = self.nxt
        self.cur_idx += 1
        self.nxt = FileStore(f"{self.prefix}L{self.cur_idx + 1}",
                             self.width, len(self.cur), reset=True)

    def trim_next(self, n_global: int) -> None:
        """Drop uncommitted next-level rows past the metadata count."""
        self.nxt.trim(n_global)

    def sync(self) -> None:
        self.cur.sync()
        self.nxt.sync()

    def delete_old(self) -> None:
        """Remove level files below the current index (post-npz-commit
        cleanup; also reclaims files from superseded runs)."""
        import glob
        import re

        for p in glob.glob(f"{self.prefix}L*"):
            m = re.fullmatch(re.escape(self.prefix) + r"L(\d+)", p)
            if m and int(m.group(1)) < self.cur_idx:
                try:
                    os.remove(p)
                except OSError:
                    pass

    def close(self) -> None:
        self.cur.close()
        self.nxt.close()


def scc_csr(indptr: np.ndarray, dst: np.ndarray) -> tuple:
    """Strongly connected components of a CSR digraph: returns
    ``(comp_id[int64 n], n_comps)``.  C++ iterative Tarjan when the
    native library is available; NumPy-assisted iterative Tarjan in
    Python otherwise (same ids-in-completion-order contract)."""
    indptr = _as_i64(indptr)
    dst = _as_i64(dst)
    n = indptr.shape[0] - 1
    comp = np.empty(n, np.int64)
    if HAS_NATIVE:
        ncomp = _lib.scc_tarjan(n, indptr.ctypes.data_as(_i64p),
                                dst.ctypes.data_as(_i64p),
                                comp.ctypes.data_as(_i64p))
        return comp, int(ncomp)
    # Python fallback: iterative Tarjan over the CSR arrays
    num = np.full(n, -1, np.int64)
    low = np.empty(n, np.int64)
    on_stk = np.zeros(n, bool)
    stk: list = []
    counter = 0
    ncomp = 0
    for root in range(n):
        if num[root] != -1:
            continue
        frames = [(root, int(indptr[root]))]
        num[root] = low[root] = counter
        counter += 1
        stk.append(root)
        on_stk[root] = True
        while frames:
            u, e = frames[-1]
            if e < indptr[u + 1]:
                frames[-1] = (u, e + 1)
                v = int(dst[e])
                if num[v] == -1:
                    num[v] = low[v] = counter
                    counter += 1
                    stk.append(v)
                    on_stk[v] = True
                    frames.append((v, int(indptr[v])))
                elif on_stk[v] and num[v] < low[u]:
                    low[u] = num[v]
            else:
                frames.pop()
                if low[u] == num[u]:
                    while True:
                        w = stk.pop()
                        on_stk[w] = False
                        comp[w] = ncomp
                        if w == u:
                            break
                    ncomp += 1
                if frames:
                    p_ = frames[-1][0]
                    if low[u] < low[p_]:
                        low[p_] = low[u]
    return comp, ncomp


def fingerprint_rows(rows: np.ndarray) -> tuple:
    """Bit-identical host fingerprint of packed rows via the C++ path.

    Falls back to the NumPy reference implementation (the definition site,
    ops/fingerprint.py) when no toolchain is available.
    """
    rows = _as_i32(rows)
    rows2d = rows.reshape(-1, rows.shape[-1])
    if not HAS_NATIVE:
        return fpr.fingerprint(rows2d, fpr.lane_constants(rows2d.shape[-1]),
                               np)
    consts = np.ascontiguousarray(fpr.lane_constants(rows2d.shape[-1]))
    hi = np.empty((rows2d.shape[0],), np.uint32)
    lo = np.empty((rows2d.shape[0],), np.uint32)
    _lib.fingerprint_rows(
        rows2d.ctypes.data_as(_i32p), rows2d.shape[0], rows2d.shape[1],
        consts[0].ctypes.data_as(_u32p), consts[1].ctypes.data_as(_u32p),
        int(fpr._LANE_SEEDS[0]), int(fpr._LANE_SEEDS[1]),
        hi.ctypes.data_as(_u32p), lo.ctypes.data_as(_u32p))
    return hi, lo
