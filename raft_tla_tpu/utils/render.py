"""TLA+-style rendering of states and traces for human-facing reports.

The output mimics TLC's violation-trace format (``State 1: <Initial ...>``
followed by a conjunction of variable assignments) so anyone used to reading
TLC ``*.out`` logs (the reference's only run artifact, ``.gitignore:1``) can
read this checker's counterexamples.  Variables render in the declaration
order of ``raft.tla:32-92``; message records render with their
``raft.tla``-schema field names.
"""

from __future__ import annotations

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import spec as S
from raft_tla_tpu.ops import msgbits as mb

_ROLE = {S.FOLLOWER: "Follower", S.CANDIDATE: "Candidate", S.LEADER: "Leader"}

_MTYPE = {1: "RequestVoteRequest", 2: "RequestVoteResponse",
          3: "AppendEntriesRequest", 4: "AppendEntriesResponse"}


def _srv(i: int) -> str:
    return f"s{i + 1}"


def render_msg(hi: int, lo: int) -> str:
    """One message record, with per-type field names (ops/msgbits.py table)."""
    t = mb.mtype(hi)
    base = (f"mtype |-> {_MTYPE.get(t, t)}, mterm |-> {mb.mterm(hi)}, "
            f"msource |-> {_srv(mb.src(hi))}, mdest |-> {_srv(mb.dst(hi))}")
    if t == 1:
        mid = (f"mlastLogTerm |-> {mb.fa(hi)}, "
               f"mlastLogIndex |-> {mb.fb(hi)}")
    elif t == 2:
        mid = f"mvoteGranted |-> {'TRUE' if mb.fa(hi) else 'FALSE'}"
    elif t == 3:
        n = mb.fc(lo)
        ents = (f"<<[term |-> {mb.fd(lo)}, value |-> v{mb.fe(lo)}]>>"
                if n else "<<>>")
        mid = (f"mprevLogIndex |-> {mb.fa(hi)}, "
               f"mprevLogTerm |-> {mb.fb(hi)}, mentries |-> {ents}, "
               f"mcommitIndex |-> {mb.ff(lo)}")
    elif t == 4:
        mid = (f"msuccess |-> {'TRUE' if mb.fa(hi) else 'FALSE'}, "
               f"mmatchIndex |-> {mb.fb(hi)}")
    else:
        mid = f"raw |-> <<{hi}, {lo}>>"
    return f"[{base}, {mid}]"


def _fn(bounds: Bounds, fmt) -> str:
    """A [Server -> ...] function literal in TLC's display style."""
    parts = [f"{_srv(i)} :> {fmt(i)}" for i in range(bounds.n_servers)]
    return "(" + " @@ ".join(parts) + ")"


def _bitmask(mask: int, bounds: Bounds) -> str:
    return "{" + ", ".join(_srv(i) for i in range(bounds.n_servers)
                           if mask >> i & 1) + "}"


def _log(entries) -> str:
    return "<<" + ", ".join(
        f"[term |-> {t}, value |-> v{v}]" for t, v in entries) + ">>"


def render_state(s, bounds: Bounds, indent: str = "    ") -> str:
    """A PyState as a TLC-style conjunction of variable assignments."""
    n = bounds.n_servers
    lines = [
        "/\\ messages = (" + (" @@ ".join(
            f"{render_msg(hi, lo)} :> {cnt}" for (hi, lo), cnt in s.msgs)
            if s.msgs else "<<>> :> 0") + ")",
        "/\\ currentTerm = " + _fn(bounds, lambda i: s.term[i]),
        "/\\ state = " + _fn(bounds, lambda i: _ROLE[s.role[i]]),
        "/\\ votedFor = " + _fn(
            bounds, lambda i: _srv(s.votedFor[i] - 1)
            if s.votedFor[i] else "Nil"),
        "/\\ log = " + _fn(bounds, lambda i: _log(s.log[i])),
        "/\\ commitIndex = " + _fn(bounds, lambda i: s.commitIndex[i]),
        "/\\ votesResponded = " + _fn(
            bounds, lambda i: _bitmask(s.vResp[i], bounds)),
        "/\\ votesGranted = " + _fn(
            bounds, lambda i: _bitmask(s.vGrant[i], bounds)),
        "/\\ nextIndex = " + _fn(bounds, lambda i: "(" + " @@ ".join(
            f"{_srv(j)} :> {s.nextIndex[i][j]}" for j in range(n)) + ")"),
        "/\\ matchIndex = " + _fn(bounds, lambda i: "(" + " @@ ".join(
            f"{_srv(j)} :> {s.matchIndex[i][j]}" for j in range(n)) + ")"),
    ]
    if s.elections is not None:
        # Faithful mode: the history variables, in raft.tla:32-92 render
        # style (elections raft.tla:39, allLogs raft.tla:44, voterLog :77).
        lines.append("/\\ elections = {" + ", ".join(
            f"[eterm |-> {et}, eleader |-> {_srv(el)}, elog |-> {_log(lg)}, "
            f"evotes |-> {_bitmask(ev, bounds)}, "
            "evoterLog |-> (" + " @@ ".join(
                f"{_srv(j)} :> {_log(vl[j])}" for j in range(n)
                if vl[j] is not None) + ")]"
            for et, el, lg, ev, vl in s.elections) + "}")
        lines.append("/\\ allLogs = {" + ", ".join(
            _log(l) for l in s.allLogs) + "}")
        lines.append("/\\ voterLog = " + _fn(
            bounds, lambda i: "(" + " @@ ".join(
                f"{_srv(j)} :> {_log(s.vLog[i][j])}" for j in range(n)
                if s.vLog[i][j] is not None) + ")"))
    return "\n".join(indent + ln for ln in lines)


def render_trace(violation, bounds: Bounds, state_renderer=None) -> str:
    """TLC-style numbered counterexample trace.  ``state_renderer``
    overrides the per-state formatter (non-Raft models supply their
    own); the default is the Raft :func:`render_state`."""
    from raft_tla_tpu.models.refbfs import DEADLOCK
    rs = state_renderer or render_state
    head = "Error: Deadlock reached." if violation.invariant == DEADLOCK \
        else f"Error: Invariant {violation.invariant} is violated."
    out = [head, "Error: The behavior up to this point is:"]
    for k, (label, state) in enumerate(violation.trace, start=1):
        head = "<Initial predicate>" if label is None else f"<{label}>"
        out.append(f"State {k}: {head}")
        out.append(rs(state, bounds))
        out.append("")
    return "\n".join(out)
