"""Shared checkpoint machinery for the engines (TLC ``-recover`` analog).

One definition site for the soundness-critical parts so the engines cannot
drift (a review round caught the device engine's digest missing
``symmetry`` while the paged engine's had it):

- :func:`config_digest` — pins the full model identity (bounds, spec
  subset, invariants, **symmetry**, chunk, capacities) *and the initial
  state's dedup key*, so a checkpoint can be resumed neither under a
  different model nor from a different root (``init_override`` differences
  are caught, not silently discarded).
- :func:`atomic_savez` / :func:`load_npz_checked` — tmp + ``os.replace``
  atomic npz with the digest check.
- :func:`stream_rows_out` / :func:`stream_rows_in` — raw int32 row blocks
  streamed in bounded chunks, so snapshotting a multi-GB host store never
  materializes a second full copy in RAM.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import zipfile

import numpy as np

_STREAM_ROWS = 1 << 20      # rows per streamed block


class CheckpointCorrupt(ValueError):
    """A checkpoint file is truncated, torn, or fails its content digest.

    Subclasses :class:`ValueError` so the engines' existing resume guards
    (and anything matching their messages) keep working unchanged, while
    a campaign supervisor can catch this type specifically and QUARANTINE
    the snapshot instead of retrying it — a corrupt file never
    deserializes into garbage state, and never gets resumed twice.
    """


def _stable(obj):
    """Canonical digest form of a config dataclass: (name, value) pairs in
    field order, OMITTING fields that sit at their declared default.

    Hashing ``repr(obj)`` instead would orphan every existing checkpoint
    each time a dataclass grows a new (defaulted) field — a lesson learned
    when adding ``Bounds.history`` invalidated a 30M-state snapshot mid-run.
    With default-valued fields excluded, old digests stay valid until a
    semantically different value is actually used.
    """
    pairs = []
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if f.default is not dataclasses.MISSING and v == f.default:
            continue
        if dataclasses.is_dataclass(v):
            v = _stable(v)
        pairs.append((f.name, v))
    return (type(obj).__name__, tuple(pairs))


def config_digest(config, caps, init_key: tuple) -> int:
    # check_deadlock / view join the identity only when set (default-
    # omission, like _stable): resuming a non-deadlock checkpoint under
    # --deadlock would silently skip dead states in the already-explored
    # region, and a view changes every dedup key.
    extras = (("check_deadlock", True),) if config.check_deadlock else ()
    if getattr(config, "view", None):
        extras += (("view", config.view),)
    key = repr((_stable(config.bounds), config.spec, config.invariants,
                config.symmetry, config.chunk, _stable(caps),
                init_key, *extras)).encode()
    return int.from_bytes(hashlib.sha256(key).digest()[:8], "big")


def content_digest(arrays) -> str:
    """Order-independent sha256 over every array's name, dtype, shape and
    bytes — the integrity seal :func:`atomic_savez` embeds (under the
    reserved key ``content_sha``) and :func:`load_npz_checked` verifies.
    Distinct from :func:`config_digest`, which pins model *identity*: a
    config mismatch is a caller error, a content mismatch is corruption."""
    h = hashlib.sha256()
    for k in sorted(arrays):
        a = np.asarray(arrays[k])
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def atomic_savez(path: str, **arrays) -> None:
    arrays["content_sha"] = content_digest(arrays)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:      # file handle: savez adds no suffix
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())        # durable before it replaces the old
    os.replace(tmp, path)


def load_npz_verified(path: str):
    """``np.load`` with corruption classified, content digest verified,
    but NO config-digest comparison — for callers that derive the
    expected config digest from the file's own contents (resharders) or
    only need integrity (the campaign supervisor's snapshot verifier).

    Raises :class:`CheckpointCorrupt` (naming the file) when the archive
    is unreadable or fails its embedded content digest.  Snapshots
    predating the embedded digest (no ``content_sha`` key) still load;
    they simply get only the structural zip checks.
    """
    try:
        z = np.load(path)
    except FileNotFoundError:
        raise
    except (OSError, EOFError, ValueError, zipfile.BadZipFile) as e:
        raise CheckpointCorrupt(
            f"checkpoint {path} is not a readable npz archive ({e}) — "
            "truncated or corrupt snapshot") from e
    try:
        names = set(z.files)
        if "content_sha" in names:
            want = str(z["content_sha"])
            got = content_digest(
                {k: z[k] for k in names if k != "content_sha"})
            if got != want:
                z.close()
                raise CheckpointCorrupt(
                    f"checkpoint {path} failed its content digest "
                    f"(embedded {want[:12]}.., computed {got[:12]}..) — "
                    "truncated or corrupt snapshot")
    except CheckpointCorrupt:
        raise
    except (KeyError, OSError, EOFError, ValueError,
            zipfile.BadZipFile) as e:
        z.close()
        raise CheckpointCorrupt(
            f"checkpoint {path} could not be decoded ({e}) — truncated "
            "or corrupt snapshot") from e
    return z


def load_npz_checked(path: str, digest: int):
    """Returns the opened NpzFile.

    Raises :class:`CheckpointCorrupt` (naming the file) when the archive
    is unreadable or fails its embedded content digest, and a plain
    :class:`ValueError` when it is intact but belongs to a different
    model config — the two must stay distinguishable: a supervisor
    quarantines the former and refuses the latter.
    """
    z = load_npz_verified(path)
    try:
        cfg_digest = int(z["config_digest"])
    except (KeyError, OSError, EOFError, ValueError,
            zipfile.BadZipFile) as e:
        z.close()
        raise CheckpointCorrupt(
            f"checkpoint {path} could not be decoded ({e}) — truncated "
            "or corrupt snapshot") from e
    if cfg_digest != digest:
        z.close()
        raise ValueError(
            "checkpoint was written under a different model config or "
            "initial state (digest mismatch); resuming it here would be "
            "unsound")
    return z


def stream_rows_out(path: str, reader, n_rows: int, width: int) -> None:
    """Write ``n_rows`` int32 rows to ``path`` via ``reader(start, n)``,
    never holding more than one block in memory.  Atomic."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.array([n_rows, width], np.int64).tofile(f)
        start = 0
        while start < n_rows:
            n = min(_STREAM_ROWS, n_rows - start)
            np.ascontiguousarray(reader(start, n), np.int32).tofile(f)
            start += n
        # durability before the replace: os.replace of an unsynced file
        # can otherwise destroy the last good snapshot AND lose the new
        # one in a power cut
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def stream_rows_append(path: str, reader, end: int, width: int) -> None:
    """Extend an append-only row stream to ``end`` rows IN PLACE.

    The engines' host stores are append-only with stable prefixes, so a
    snapshot only ever needs to add the suffix since the previous one —
    a full :func:`stream_rows_out` rewrite costs minutes of idle device
    at 10^8-state scale (measured: the elect5 campaign's rewriting
    snapshots took ~10 min each at 50-90M orbits).

    Crash safety, by write order: the file is truncated to the header's
    row count (dropping any garbage from a previously torn append), the
    new rows are appended and fsynced, and the header's count is updated
    LAST — a crash at any point leaves a consistent prefix no shorter
    than the last completed snapshot, which is exactly the contract
    :func:`stream_rows_in` already relies on.  A width change or a
    missing file falls back to the full atomic rewrite.
    """
    if not os.path.exists(path):
        return stream_rows_out(path, reader, end, width)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        hdr = np.fromfile(f, np.int64, 2)
        if (hdr.shape[0] != 2 or int(hdr[1]) != width
                or size < 16 + int(hdr[0]) * width * 4):
            # width change, or a header vouching for more bytes than the
            # file holds (torn full write): nothing here is trustworthy —
            # full rewrite.  (truncate() would silently ZERO-FILL a short
            # file, so the size check must come first.)
            f.close()
            return stream_rows_out(path, reader, end, width)
        # the valid prefix: rows the header vouches for, capped at the
        # target (a longer stream can outlive an older metadata npz —
        # see stream_rows_in — and its prefix is still bit-identical)
        start = min(int(hdr[0]), end)
        f.truncate(16 + start * width * 4)
        f.seek(0, os.SEEK_END)
        while start < end:
            n = min(_STREAM_ROWS, end - start)
            np.ascontiguousarray(reader(start, n), np.int32).tofile(f)
            start += n
        f.flush()
        os.fsync(f.fileno())
        f.seek(0)
        np.array([end, width], np.int64).tofile(f)
        f.flush()
        os.fsync(f.fileno())


def stream_width(path: str) -> int:
    """Row width of an append-only stream (the one place that knows the
    header layout outside the readers/writers in this module)."""
    with open(path, "rb") as f:
        hdr = np.fromfile(f, np.int64, 2)
    if hdr.shape[0] != 2:
        raise CheckpointCorrupt(f"stream {path}: truncated header")
    return int(hdr[1])


def trim_stream(path: str, n_rows: int, width: int) -> None:
    """Cap an append-only stream's trusted prefix at ``n_rows`` (resume
    hygiene: rows beyond the restored metadata's count came from a
    superseded snapshot and must be re-written, not assumed identical)."""
    if not os.path.exists(path):
        return
    with open(path, "r+b") as f:
        hdr = np.fromfile(f, np.int64, 2)
        if hdr.shape[0] != 2 or int(hdr[1]) != width \
                or int(hdr[0]) <= n_rows:
            return
        f.truncate(16 + n_rows * width * 4)
        f.seek(0)
        np.array([n_rows, width], np.int64).tofile(f)
        f.flush()
        os.fsync(f.fileno())


def copy_stream(src: str, dst: str, n_rows: int, width: int) -> None:
    """Copy the first ``n_rows`` of an append-only stream to a new path
    (atomic; blockwise — used by checkpoint resharders, where the stream
    is mesh-independent history and moves verbatim)."""
    with open(src, "rb") as f:
        have, w = (int(x) for x in np.fromfile(f, np.int64, 2))
        if w != width:
            raise ValueError(
                f"stream {src} has row width {w}, expected {width}")
        if have < n_rows:
            raise ValueError(
                f"stream {src} holds {have} rows, need {n_rows}")

        def reader(start, n):
            f.seek(16 + start * width * 4)
            return np.fromfile(f, np.int32, n * width).reshape(n, width)

        stream_rows_out(dst, reader, n_rows, width)


def stream_rows_in(path: str, writer, limit: int,
                   expect_width: int | None = None) -> int:
    """Feed the first ``limit`` rows of ``path`` through ``writer(block)``.

    The stream may legitimately hold MORE rows than ``limit``: snapshots
    write the (append-only, stable-prefix) streams before the metadata
    npz, so a crash between the two leaves longer streams next to an older
    ``paged`` counter — the excess is simply ignored.  Fewer rows than
    ``limit`` means a genuinely torn snapshot and is an error.

    ``expect_width`` pins the caller's current row layout: the config
    digest does not cover the bit-pack schema, so a checkpoint written
    under an older packing must be rejected here, not resumed as silently
    corrupted rows.
    """
    with open(path, "rb") as f:
        hdr = np.fromfile(f, np.int64, 2)
        if hdr.shape[0] != 2:
            raise CheckpointCorrupt(f"stream {path}: truncated header")
        n_rows, width = (int(x) for x in hdr)
        if expect_width is not None and width != expect_width:
            raise ValueError(
                f"checkpoint stream {path} has row width {width}, this "
                f"build expects {expect_width} — the packed-row layout "
                "changed; the snapshot cannot be resumed")
        if n_rows < limit:
            raise CheckpointCorrupt(
                f"checkpoint stream {path} holds {n_rows} rows, "
                f"metadata expects {limit} — torn snapshot")
        start = 0
        while start < limit:
            n = min(_STREAM_ROWS, limit - start)
            raw = np.fromfile(f, np.int32, n * width)
            if raw.shape[0] != n * width:
                raise CheckpointCorrupt(
                    f"truncated checkpoint stream {path}")
            block = raw.reshape(n, width)
            writer(block)
            start += n
    return limit
