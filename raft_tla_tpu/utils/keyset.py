"""Host-side exact fingerprint set for delayed duplicate detection.

The device-resident fingerprint tables cap distinct-state capacity at
~2^28 slots (the 2 GiB single-buffer limit — measured into on the elect5
campaign, RESULTS.md "capacity findings").  The DDD engine
(ddd_engine.py) moves EXACT dedup to the host: candidate keys stream off
the device, and this module maintains the master set of every discovered
state's 64-bit fingerprint, deduplicating pending candidates in
*first-occurrence stream order* so discovery order — and therefore
counts, levels, coverage attribution and traces — stays byte-identical
to the table engines and the pure-Python oracle.

Storage is **tiered sorted runs** (LSM-style), not one monolithic sorted
array.  The round-2 monolith merged every flush with ``np.insert`` —
an O(master) rewrite per flush that measurably decayed the elect5
campaign from 164k to 84k states/s as the master grew 287M → 312M keys
(runs/elect5ddd.stats; VERDICT r2 weak #1).  Here each flush appends its
new keys as one new sorted run — O(new) — and runs compact geometrically
(adjacent runs merge when the older is no more than ``_RATIO``× the
newer), so each key participates in O(log N) merges and total merge
*data movement* over N inserted keys is O(N log N) amortized (plus a
searchsorted log factor on comparisons — memory bandwidth, not
comparisons, is what the flush decay was made of) and per-flush cost no
longer scales with the master size.  Lookups
(`contains`/`dedup` anti-join) searchsort each of the O(log N) runs —
at 10⁹ keys that is ~30 binary searches per candidate instead of 1,
still sub-microsecond, while the flush-time rewrite the campaign was
dying under is gone.

Capacity is host RAM: 8 bytes/state (~15B states in this host's
125 GiB).  All operations are plain NumPy on sorted arrays; the merge
primitive is a vectorized O(a+b) two-way merge of disjoint runs.

Replicates TLC's external-memory fingerprint-set regime (the disk-backed
`states/` dir the reference ignores at `/root/reference/.gitignore:2`),
host-RAM-resident instead of disk-resident.
"""

from __future__ import annotations

import functools

import numpy as np

U64 = np.uint64

# Geometric compaction ratio: after appending a run, adjacent runs merge
# while the older run is <= _RATIO * the newer.  2 gives the classic
# LSM bound (each key participates in <= log2(N/flush) merges) with at
# most ~log2(N/flush) live runs.
_RATIO = 2


def pack_keys(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Fuse the (hi, lo) uint32 fingerprint lanes the device engines use
    (device_engine._dedup_insert keys) into one uint64 key per candidate."""
    return (hi.astype(U64) << U64(32)) | lo.astype(U64)


def _merge_disjoint(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Vectorized merge of two sorted arrays with no common keys (runs
    are mutually disjoint by construction: a new run holds only keys
    absent from every older run).  O(a+b) data movement + O(b log a)
    searchsorted comparisons."""
    if a.size == 0:
        return b
    if b.size == 0:
        return a
    out = np.empty(a.size + b.size, U64)
    posb = np.searchsorted(a, b) + np.arange(b.size, dtype=np.int64)
    amask = np.ones(out.size, bool)
    amask[posb] = False
    out[posb] = b
    out[amask] = a
    return out


def _member(run: np.ndarray, keys: np.ndarray) -> np.ndarray:
    """Boolean membership of ``keys`` in one sorted run."""
    pos = np.searchsorted(run, keys)
    inb = pos < run.size
    hit = np.zeros(keys.shape, bool)
    hit[inb] = run[pos[inb]] == keys[inb]
    return hit


class MasterKeys:
    """Tiered sorted runs of discovered-state fingerprints.

    ``dedup(keys)`` is the only bulk-mutating operation: given one flush
    of candidate keys in stream order, it returns the indices (into that
    flush, ascending) of candidates that are genuinely new — first
    occurrence within the flush AND absent from every run — and admits
    exactly those keys as a new run (compacting tiers as needed).
    Cross-flush first-occurrence order holds because flush i's new keys
    are in the tiers before flush i+1 is examined.
    """

    def __init__(self, keys: np.ndarray | None = None):
        if keys is None or keys.size == 0:
            self._runs: list[np.ndarray] = []
        else:
            base = np.ascontiguousarray(keys, dtype=U64)
            if np.any(base[1:] <= base[:-1]):
                raise ValueError("master keys must be strictly sorted")
            self._runs = [base]

    def __len__(self) -> int:
        return sum(int(r.size) for r in self._runs)

    @property
    def n_runs(self) -> int:
        """Live tier count (diagnostic; O(log N) by construction)."""
        return len(self._runs)

    @property
    def array(self) -> np.ndarray:
        """The full sorted key set as one array (read-only).  Materializes
        a merge of all runs — O(N); for tests and inspection, not the
        hot path."""
        v = self._runs[0] if len(self._runs) == 1 else \
            functools.reduce(_merge_disjoint, self._runs, np.empty(0, U64))
        v = v.view()
        v.flags.writeable = False
        return v

    def seed(self, key: int) -> None:
        """Insert one key (the initial state) if absent."""
        self.dedup(np.asarray([key], U64))

    def contains(self, keys: np.ndarray) -> np.ndarray:
        keys = keys.astype(U64, copy=False)
        hit = np.zeros(keys.shape, bool)
        for run in sorted(self._runs, key=lambda r: -r.size):
            rem = np.flatnonzero(~hit)       # probe only still-unknown
            if rem.size == 0:                # keys; the largest run
                break                        # resolves most duplicates
            hit[rem[_member(run, keys[rem])]] = True
        return hit

    def _append_run(self, run: np.ndarray) -> None:
        self._runs.append(run)
        # geometric compaction: merge newest-first while the older
        # neighbour is small enough that the merge stays amortized
        while (len(self._runs) >= 2
               and self._runs[-2].size <= _RATIO * self._runs[-1].size):
            b = self._runs.pop()
            a = self._runs.pop()
            self._runs.append(_merge_disjoint(a, b))

    def dedup(self, keys: np.ndarray) -> np.ndarray:
        """First-occurrence indices of new keys, in stream order; admits
        the corresponding keys as a new tier."""
        keys = keys.astype(U64, copy=False)
        n = keys.size
        if n == 0:
            return np.empty(0, np.int64)
        order = np.argsort(keys, kind="stable")   # stable: ties keep
        sk = keys[order]                          # stream order
        first = np.ones(n, bool)
        first[1:] = sk[1:] != sk[:-1]
        cand_idx = order[first]                   # first occurrence per key
        cand_keys = sk[first]                     # sorted, unique
        dup = np.zeros(cand_keys.shape, bool)
        for run in sorted(self._runs, key=lambda r: -r.size):
            rem = np.flatnonzero(~dup)
            if rem.size == 0:
                break
            dup[rem[_member(run, cand_keys[rem])]] = True
        new_keys = cand_keys[~dup]                # sorted, disjoint from
        if new_keys.size:                         # every existing run
            self._append_run(np.ascontiguousarray(new_keys))
        return np.sort(cand_idx[~dup])
