"""Host-side exact fingerprint set for delayed duplicate detection.

The device-resident fingerprint tables cap distinct-state capacity at
~2^28 slots (the 2 GiB single-buffer limit — measured into on the elect5
campaign, RESULTS.md "capacity findings").  The DDD engine
(ddd_engine.py) moves EXACT dedup to the host: candidate keys stream off
the device, and this module maintains the master set of every discovered
state's 64-bit fingerprint, deduplicating pending candidates in
*first-occurrence stream order* so discovery order — and therefore
counts, levels, coverage attribution and traces — stays byte-identical
to the table engines and the pure-Python oracle.

Storage is **tiered sorted runs** (LSM-style), not one monolithic sorted
array.  The round-2 monolith merged every flush with ``np.insert`` —
an O(master) rewrite per flush that measurably decayed the elect5
campaign from 164k to 84k states/s as the master grew 287M → 312M keys
(runs/elect5ddd.stats; VERDICT r2 weak #1).  Here each flush appends its
new keys as one new sorted run — O(new) — and runs compact geometrically
(adjacent runs merge when the older is no more than ``_RATIO``× the
newer), so each key participates in O(log N) merges and total merge
*data movement* over N inserted keys is O(N log N) amortized (plus a
searchsorted log factor on comparisons — memory bandwidth, not
comparisons, is what the flush decay was made of) and per-flush cost no
longer scales with the master size.  Lookups
(`contains`/`dedup` anti-join) searchsort each of the O(log N) runs —
at 10⁹ keys that is ~30 binary searches per candidate instead of 1,
still sub-microsecond, while the flush-time rewrite the campaign was
dying under is gone.

Capacity is host RAM: 8 bytes/state (~15B states in this host's
125 GiB).  All operations are plain NumPy on sorted arrays; the merge
primitive is a vectorized O(a+b) two-way merge of disjoint runs.

Two master-set implementations share that storage scheme:

- :class:`MasterKeys` — one set of tiers, single-threaded (the original,
  and the ``RAFT_TLA_HOSTDEDUP=off`` arm).
- :class:`PartitionedMasterKeys` — ``2^k`` partitions keyed by the
  fingerprint's top ``k`` bits, each with its own LSM tiers.  ``dedup``
  radix-splits the flush once, then runs per-partition
  argsort/probe/merge as independent tasks on a process-shared
  :func:`ThreadPoolExecutor <pool>` (NumPy's sort and searchsorted
  release the GIL, so the tasks genuinely overlap), and reconstructs
  first-occurrence stream order exactly from the per-partition index
  vectors.  Geometric compaction splits into per-partition ~N/2^k
  merges and is additionally **budgeted**: a merge bigger than the
  per-flush budget carries a cursor across flushes
  (:class:`_PendingMerge`), so no single flush carries an O(N) data-
  movement spike — the multi-second stall the elect5 campaign hit
  whenever two top tiers merged.

The two are observationally identical (same dedup index vectors, same
``contains``/``len``/``array``) — asserted property-style in
tests/test_keyset.py.  The ``RAFT_TLA_HOSTDEDUP`` gate
(:func:`host_dedup_enabled`) picks which one the DDD engines build and
whether the flush itself moves off-thread (ddd_engine's background
worker, utils/flushq.py).

Replicates TLC's external-memory fingerprint-set regime (the disk-backed
`states/` dir the reference ignores at `/root/reference/.gitignore:2`),
host-RAM-resident instead of disk-resident.
"""

from __future__ import annotations

import functools
import os
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

U64 = np.uint64

# Geometric compaction ratio: after appending a run, adjacent runs merge
# while the older run is <= _RATIO * the newer.  2 gives the classic
# LSM bound (each key participates in <= log2(N/flush) merges) with at
# most ~log2(N/flush) live runs.
_RATIO = 2


def pack_keys(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Fuse the (hi, lo) uint32 fingerprint lanes the device engines use
    (device_engine._dedup_insert keys) into one uint64 key per candidate."""
    return (hi.astype(U64) << U64(32)) | lo.astype(U64)


def _merge_disjoint(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Vectorized merge of two sorted arrays with no common keys (runs
    are mutually disjoint by construction: a new run holds only keys
    absent from every older run).  O(a+b) data movement + O(b log a)
    searchsorted comparisons."""
    if a.size == 0:
        return b
    if b.size == 0:
        return a
    out = np.empty(a.size + b.size, U64)
    posb = np.searchsorted(a, b) + np.arange(b.size, dtype=np.int64)
    amask = np.ones(out.size, bool)
    amask[posb] = False
    out[posb] = b
    out[amask] = a
    return out


def _member(run: np.ndarray, keys: np.ndarray) -> np.ndarray:
    """Boolean membership of ``keys`` in one sorted run."""
    pos = np.searchsorted(run, keys)
    inb = pos < run.size
    hit = np.zeros(keys.shape, bool)
    hit[inb] = run[pos[inb]] == keys[inb]
    return hit


def _probe_runs(runs: list[np.ndarray], keys: np.ndarray) -> np.ndarray:
    """Boolean membership of ``keys`` across a list of sorted runs,
    probed largest-run-first: each pass only probes keys still unknown,
    and the largest run resolves most duplicates, so later (smaller)
    runs see a shrinking candidate set.  Shared by ``contains`` and the
    ``dedup`` anti-join (both flat and partitioned)."""
    hit = np.zeros(keys.shape, bool)
    for run in sorted(runs, key=lambda r: -r.size):
        rem = np.flatnonzero(~hit)
        if rem.size == 0:
            break
        hit[rem[_member(run, keys[rem])]] = True
    return hit


class MasterKeys:
    """Tiered sorted runs of discovered-state fingerprints.

    ``dedup(keys)`` is the only bulk-mutating operation: given one flush
    of candidate keys in stream order, it returns the indices (into that
    flush, ascending) of candidates that are genuinely new — first
    occurrence within the flush AND absent from every run — and admits
    exactly those keys as a new run (compacting tiers as needed).
    Cross-flush first-occurrence order holds because flush i's new keys
    are in the tiers before flush i+1 is examined.
    """

    def __init__(self, keys: np.ndarray | None = None):
        if keys is None or keys.size == 0:
            self._runs: list[np.ndarray] = []
        else:
            base = np.ascontiguousarray(keys, dtype=U64)
            if np.any(base[1:] <= base[:-1]):
                raise ValueError("master keys must be strictly sorted")
            self._runs = [base]

    def __len__(self) -> int:
        return sum(int(r.size) for r in self._runs)

    @property
    def n_runs(self) -> int:
        """Live tier count (diagnostic; O(log N) by construction)."""
        return len(self._runs)

    @property
    def array(self) -> np.ndarray:
        """The full sorted key set as one array (read-only).  Materializes
        a merge of all runs — O(N); for tests and inspection, not the
        hot path."""
        v = self._runs[0] if len(self._runs) == 1 else \
            functools.reduce(_merge_disjoint, self._runs, np.empty(0, U64))
        v = v.view()
        v.flags.writeable = False
        return v

    def seed(self, key: int) -> None:
        """Insert one key (the initial state) if absent."""
        self.dedup(np.asarray([key], U64))

    def contains(self, keys: np.ndarray) -> np.ndarray:
        return _probe_runs(self._runs, keys.astype(U64, copy=False))

    def _append_run(self, run: np.ndarray) -> None:
        self._runs.append(run)
        # geometric compaction: merge newest-first while the older
        # neighbour is small enough that the merge stays amortized
        while (len(self._runs) >= 2
               and self._runs[-2].size <= _RATIO * self._runs[-1].size):
            b = self._runs.pop()
            a = self._runs.pop()
            self._runs.append(_merge_disjoint(a, b))

    def dedup(self, keys: np.ndarray) -> np.ndarray:
        """First-occurrence indices of new keys, in stream order; admits
        the corresponding keys as a new tier."""
        keys = keys.astype(U64, copy=False)
        n = keys.size
        if n == 0:
            return np.empty(0, np.int64)
        order = np.argsort(keys, kind="stable")   # stable: ties keep
        sk = keys[order]                          # stream order
        first = np.ones(n, bool)
        first[1:] = sk[1:] != sk[:-1]
        cand_idx = order[first]                   # first occurrence per key
        cand_keys = sk[first]                     # sorted, unique
        dup = _probe_runs(self._runs, cand_keys)
        new_keys = cand_keys[~dup]                # sorted, disjoint from
        if new_keys.size:                         # every existing run
            self._append_run(np.ascontiguousarray(new_keys))
        return np.sort(cand_idx[~dup])


# ---------------------------------------------------------------------------
# Partitioned master keys (RAFT_TLA_HOSTDEDUP on/auto arm)
# ---------------------------------------------------------------------------

# Default partition count (2^k, k=4).  Partition id = top k bits of the
# fingerprint, so partition order == sorted-key order and the global
# sorted view is just the concatenation of per-partition views.  16
# partitions keeps per-partition tier merges ~N/16 while still giving a
# pool of up to 16 workers independent tasks.
DEFAULT_PARTS = 16

ENV_HOSTDEDUP = "RAFT_TLA_HOSTDEDUP"


def host_dedup_enabled(env: str | None = None) -> bool:
    """Resolve the RAFT_TLA_HOSTDEDUP gate to a bool.

    ``on``/``off`` force; ``auto`` (and unset) applies the measured
    policy (RESULTS.md "Host dedup A/B"): ON iff the host has >= 2
    cores.  Gate (a)'s compaction spike bound holds even
    single-threaded (worst flush 2.0x median where flat spikes 10.9x),
    but it buys that bound by paying the amortized movement every
    flush — 0.72x in-engine warm rate at nproc=1, where neither the
    partition pool nor the background flush worker has a second core
    to run on.  With nproc >= 2 the spike bound rides along and the
    overlap is what the A/B's queued on-chip rerun measures.
    """
    v = (env if env is not None else os.environ.get(ENV_HOSTDEDUP, "auto"))
    v = v.strip().lower()
    if v == "on":
        return True
    if v == "off":
        return False
    return (os.cpu_count() or 1) >= 2


_POOL: ThreadPoolExecutor | None = None
_POOL_LOCK = threading.Lock()


def pool() -> ThreadPoolExecutor | None:
    """Process-shared dedup thread pool, or None when this host cannot
    overlap (ncpu < 2) — callers then run partition tasks inline.
    Shared by every PartitionedMasterKeys in the process (single-chip
    ddd and all per-shard masters of ddd-shard) so total dedup
    parallelism is bounded by the host, not by shard count."""
    global _POOL
    ncpu = os.cpu_count() or 1
    if ncpu < 2:
        return None
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ThreadPoolExecutor(
                max_workers=min(ncpu, DEFAULT_PARTS),
                thread_name_prefix="raft-tla-dedup")
    return _POOL


class _PendingMerge:
    """A budgeted in-progress merge of two adjacent runs.

    The merge target ``out`` is filled left-to-right in budget-sized
    windows; both source runs stay in the partition's run list (probe-
    visible — ``out`` holds garbage past ``opos``) until the merge
    completes, at which point the caller splices ``out`` over them.
    ``posb`` (final position of every b element in ``out``) is computed
    once up front — O(b log a) — so each window is pure data movement.
    """

    __slots__ = ("idx", "a", "b", "posb", "out", "opos", "ja", "jb")

    def __init__(self, idx: int, a: np.ndarray, b: np.ndarray):
        self.idx = idx                       # position of `a` in runs
        self.a = a
        self.b = b
        self.posb = np.searchsorted(a, b) + np.arange(b.size, dtype=np.int64)
        self.out = np.empty(a.size + b.size, U64)
        self.opos = 0                        # filled prefix of out
        self.ja = 0                          # consumed prefix of a
        self.jb = 0                          # consumed prefix of b

    @property
    def done(self) -> bool:
        return self.opos >= self.out.size

    def advance(self, budget: int) -> int:
        """Fill up to ``budget`` more output slots; return slots moved."""
        take = min(int(budget), self.out.size - self.opos)
        if take <= 0:
            return 0
        hi = self.opos + take
        jb2 = self.jb + int(np.searchsorted(self.posb[self.jb:], hi))
        window = self.out[self.opos:hi]
        bmask = np.zeros(take, bool)
        bmask[self.posb[self.jb:jb2] - self.opos] = True
        window[bmask] = self.b[self.jb:jb2]
        na = take - (jb2 - self.jb)
        window[~bmask] = self.a[self.ja:self.ja + na]
        self.opos = hi
        self.ja += na
        self.jb = jb2
        return take


class _Partition:
    """One high-bit partition: its own LSM tiers plus at most one
    pending budgeted merge.  Not thread-safe on its own — the owning
    PartitionedMasterKeys dispatches at most one task per partition."""

    __slots__ = ("runs", "merge", "moved")

    def __init__(self, base: np.ndarray | None = None):
        self.runs: list[np.ndarray] = [] if base is None or base.size == 0 \
            else [base]
        self.merge: _PendingMerge | None = None
        self.moved = 0                       # merge slots moved, last task

    def _live_runs(self) -> list[np.ndarray]:
        return self.runs

    def compact(self, budget: int | None) -> None:
        """Advance compaction by at most ``budget`` moved slots
        (None = unbounded, flat-equivalent).  Invariant on exit when no
        merge is pending: runs[i].size > _RATIO * runs[i+1].size."""
        self.moved = 0
        rem = np.inf if budget is None else int(budget)
        while True:
            if self.merge is not None:
                m = self.merge
                adv = m.advance(m.out.size if rem == np.inf else int(rem))
                self.moved += adv
                if rem != np.inf:
                    rem -= adv
                if not m.done:
                    return                   # carry cursor to next flush
                self.runs[m.idx:m.idx + 2] = [m.out]
                self.merge = None
                if rem <= 0:
                    return
                continue
            # find the innermost adjacent pair violating the geometric
            # invariant (scan from the newest end, like _append_run)
            j = len(self.runs) - 2
            while j >= 0 and self.runs[j].size > _RATIO * self.runs[j + 1].size:
                j -= 1
            if j < 0:
                return
            a, b = self.runs[j], self.runs[j + 1]
            if a.size + b.size <= rem:
                self.runs[j:j + 2] = [_merge_disjoint(a, b)]
                self.moved += a.size + b.size
                if rem != np.inf:
                    rem -= a.size + b.size
                continue
            self.merge = _PendingMerge(j, a, b)
            # loop: the pending branch advances it by the remaining budget

    def append_run(self, run: np.ndarray, budget: int | None) -> None:
        if self.merge is not None and self.merge.idx >= len(self.runs) - 1:
            raise AssertionError("pending merge must precede appended run")
        self.runs.append(run)
        self.compact(budget)


class PartitionedMasterKeys:
    """Partitioned, pool-parallel, budget-compacted master key set.

    Observationally identical to :class:`MasterKeys` (same dedup index
    vectors, ``contains``/``len``/``array``); see the module docstring
    for the ordering argument.  ``merge_budget`` bounds per-partition
    merge data movement per flush (None = unbounded, spikes allowed).
    """

    def __init__(self, keys: np.ndarray | None = None, *,
                 parts: int = DEFAULT_PARTS,
                 merge_budget: int | None = None):
        if parts < 1 or parts & (parts - 1):
            raise ValueError("parts must be a power of two")
        self._parts = parts
        self._k = parts.bit_length() - 1
        self._budget = merge_budget
        if keys is None or keys.size == 0:
            self._p = [_Partition() for _ in range(parts)]
            return
        base = np.ascontiguousarray(keys, dtype=U64)
        if np.any(base[1:] <= base[:-1]):
            raise ValueError("master keys must be strictly sorted")
        self._p = [_Partition(s) for s in self._split_sorted(base)]

    # -- partition addressing ------------------------------------------------

    def _pids(self, keys: np.ndarray) -> np.ndarray:
        if self._k == 0:
            return np.zeros(keys.shape, np.int64)
        return (keys >> U64(64 - self._k)).astype(np.int64)

    def _split_sorted(self, base: np.ndarray) -> list[np.ndarray]:
        """Split one sorted array into per-partition segments (top-k-bit
        order == sorted order, so each segment is contiguous)."""
        if self._k == 0:
            return [base]
        edges = np.arange(1, self._parts, dtype=U64) << U64(64 - self._k)
        bnds = np.searchsorted(base, edges)
        bnds = np.concatenate(([0], bnds, [base.size]))
        return [np.ascontiguousarray(base[bnds[i]:bnds[i + 1]])
                for i in range(self._parts)]

    # -- read side -----------------------------------------------------------

    def __len__(self) -> int:
        return sum(int(r.size) for p in self._p for r in p.runs)

    @property
    def n_runs(self) -> int:
        """Max live tier count over partitions (diagnostic, comparable
        to the flat n_runs bound)."""
        return max((len(p.runs) for p in self._p), default=0)

    @property
    def pending_merges(self) -> int:
        """Partitions currently mid-merge (carry-cursor diagnostic)."""
        return sum(1 for p in self._p if p.merge is not None)

    @property
    def last_flush_moved(self) -> int:
        """Max per-partition merge data movement of the last dedup —
        bounded by ``merge_budget`` (+ one budget-window overshoot from
        an inline pair merge) when a budget is set."""
        return max((p.moved for p in self._p), default=0)

    @property
    def array(self) -> np.ndarray:
        """Full sorted key set (read-only, O(N) materialization)."""
        segs = []
        for p in self._p:
            if p.runs:
                segs.append(p.runs[0] if len(p.runs) == 1 else
                            functools.reduce(_merge_disjoint, p.runs,
                                             np.empty(0, U64)))
        v = np.concatenate(segs) if segs else np.empty(0, U64)
        v = v.view()
        v.flags.writeable = False
        return v

    def seed(self, key: int) -> None:
        self.dedup(np.asarray([key], U64))

    def contains(self, keys: np.ndarray) -> np.ndarray:
        keys = keys.astype(U64, copy=False)
        pids = self._pids(keys)
        hit = np.zeros(keys.shape, bool)
        for pid in np.unique(pids):
            sel = pids == pid
            hit[sel] = _probe_runs(self._p[pid].runs, keys[sel])
        return hit

    # -- write side ----------------------------------------------------------

    @staticmethod
    def _dedup_partition(part: _Partition, keys: np.ndarray,
                         idx: np.ndarray, budget: int | None) -> np.ndarray:
        """Per-partition dedup task: keys/idx are this partition's slice
        of the flush, idx in ascending stream order.  Returns the
        global (flush-relative) indices of genuinely-new keys."""
        if keys.size == 0:
            part.compact(budget)             # keep carrying a cursor
            return np.empty(0, np.int64)
        order = np.argsort(keys, kind="stable")
        sk = keys[order]
        first = np.ones(keys.size, bool)
        first[1:] = sk[1:] != sk[:-1]
        cand_local = order[first]
        cand_keys = sk[first]
        dup = _probe_runs(part.runs, cand_keys)
        new_keys = cand_keys[~dup]
        if new_keys.size:
            part.append_run(np.ascontiguousarray(new_keys), budget)
        else:
            part.compact(budget)
        return idx[cand_local[~dup]]

    def dedup(self, keys: np.ndarray) -> np.ndarray:
        """First-occurrence indices of new keys, in stream order —
        byte-identical to flat MasterKeys.dedup.  Why: partitions are
        disjoint key spaces, so a key's first occurrence within its
        partition slice IS its first occurrence in the flush; per-
        partition results are global flush indices, and their sorted
        concatenation is the flat result."""
        keys = keys.astype(U64, copy=False)
        if keys.size == 0:
            return np.empty(0, np.int64)
        pids = self._pids(keys)
        # stable radix split: within a partition, indices stay ascending
        order = np.argsort(pids, kind="stable")
        bnds = np.searchsorted(pids[order],
                               np.arange(self._parts + 1, dtype=np.int64))
        tasks = []
        for pid in range(self._parts):
            lo, hi = int(bnds[pid]), int(bnds[pid + 1])
            if hi > lo or self._p[pid].merge is not None:
                idx = order[lo:hi]
                tasks.append((self._p[pid], keys[idx], idx))
            else:
                self._p[pid].moved = 0
        ex = pool()
        if ex is not None and len(tasks) > 1:
            futs = [ex.submit(self._dedup_partition, p, k, i, self._budget)
                    for p, k, i in tasks]
            parts_new = [f.result() for f in futs]
        else:
            parts_new = [self._dedup_partition(p, k, i, self._budget)
                         for p, k, i in tasks]
        if not parts_new:
            return np.empty(0, np.int64)
        return np.sort(np.concatenate(parts_new))


# ---------------------------------------------------------------------------
# Factories (gate-aware construction + checkpoint rebuild)
# ---------------------------------------------------------------------------

def new_master(partitioned: bool | None = None, *,
               parts: int = DEFAULT_PARTS,
               merge_budget: int | None = None):
    """Fresh empty master set; ``partitioned=None`` resolves the gate."""
    if partitioned is None:
        partitioned = host_dedup_enabled()
    if partitioned:
        return PartitionedMasterKeys(parts=parts, merge_budget=merge_budget)
    return MasterKeys()


def master_from_keys(keys: np.ndarray, *, source: str = "checkpoint",
                     partitioned: bool | None = None,
                     parts: int = DEFAULT_PARTS,
                     merge_budget: int | None = None):
    """Rebuild a master set from an **unsorted** key log (checkpoint
    resume).  Dedupe-checks before construction so a corrupt log raises
    the stream-corrupt diagnostic naming the snapshot, not MasterKeys's
    generic "must be strictly sorted".  The partitioned path radix-
    splits first and sorts per partition on the shared pool, so
    resume-time sort cost drops from one O(N log N) to parallel
    O(N/2^k log N/2^k) tasks."""
    if partitioned is None:
        partitioned = host_dedup_enabled()
    keys = np.ascontiguousarray(keys, dtype=U64)

    def _checked_sort(seg: np.ndarray) -> np.ndarray:
        s = np.sort(seg)
        if np.any(s[1:] == s[:-1]):
            raise ValueError(
                f"checkpoint key log at {source!r} has duplicate keys "
                "— stream corrupt")
        return s

    if not partitioned:
        return MasterKeys(_checked_sort(keys))
    m = PartitionedMasterKeys(parts=parts, merge_budget=merge_budget)
    pids = m._pids(keys)
    order = np.argsort(pids, kind="stable")
    bnds = np.searchsorted(pids[order], np.arange(parts + 1, dtype=np.int64))
    segs = [keys[order[bnds[i]:bnds[i + 1]]] for i in range(parts)]
    ex = pool()
    if ex is not None:
        sorted_segs = list(ex.map(_checked_sort, segs))
    else:
        sorted_segs = [_checked_sort(s) for s in segs]
    m._p = [_Partition(np.ascontiguousarray(s)) for s in sorted_segs]
    return m
