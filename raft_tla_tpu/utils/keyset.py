"""Host-side exact fingerprint set for delayed duplicate detection.

The device-resident fingerprint tables cap distinct-state capacity at
~2^28 slots (the 2 GiB single-buffer limit — measured into on the elect5
campaign, RESULTS.md "capacity findings").  The DDD engine
(ddd_engine.py) moves EXACT dedup to the host: candidate keys stream off
the device, and this module maintains the master set of every discovered
state's 64-bit fingerprint as a single sorted array, deduplicating
pending candidates in *first-occurrence stream order* so discovery order
— and therefore counts, levels, coverage attribution and traces — stays
byte-identical to the table engines and the pure-Python oracle.

Capacity is host RAM: 8 bytes/state (~15B states in this host's 125 GiB),
three orders of magnitude past the device-table ceiling.  All operations
are plain NumPy on sorted arrays (this host has one core — a threaded C++
twin would buy nothing; `np.sort`/`np.searchsorted`/`np.insert` already
run at memory bandwidth).

Replicates TLC's external-memory fingerprint-set regime (the disk-backed
`states/` dir the reference ignores at `/root/reference/.gitignore:2`),
host-RAM-resident instead of disk-resident.
"""

from __future__ import annotations

import numpy as np

U64 = np.uint64


def pack_keys(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """Fuse the (hi, lo) uint32 fingerprint lanes the device engines use
    (device_engine._dedup_insert keys) into one uint64 key per candidate."""
    return (hi.astype(U64) << U64(32)) | lo.astype(U64)


class MasterKeys:
    """Sorted master array of discovered-state fingerprints.

    ``dedup(keys)`` is the only mutating operation: given one flush of
    candidate keys in stream order, it returns the indices (into that
    flush, ascending) of candidates that are genuinely new — first
    occurrence within the flush AND absent from the master — and merges
    exactly those keys in.  Cross-flush first-occurrence order holds
    because flush i's new keys are in the master before flush i+1 is
    examined.
    """

    def __init__(self, keys: np.ndarray | None = None):
        self._m = np.empty(0, U64) if keys is None \
            else np.ascontiguousarray(keys, dtype=U64)
        if self._m.size and np.any(self._m[1:] <= self._m[:-1]):
            raise ValueError("master keys must be strictly sorted")

    def __len__(self) -> int:
        return int(self._m.size)

    @property
    def array(self) -> np.ndarray:
        """The sorted master array (read-only view; for checkpointing)."""
        v = self._m.view()
        v.flags.writeable = False
        return v

    def seed(self, key: int) -> None:
        """Insert one key (the initial state) into an empty-or-small set."""
        self._m = np.unique(np.append(self._m, U64(key)))

    def contains(self, keys: np.ndarray) -> np.ndarray:
        keys = keys.astype(U64, copy=False)
        pos = np.searchsorted(self._m, keys)
        inb = pos < self._m.size
        hit = np.zeros(keys.shape, bool)
        hit[inb] = self._m[pos[inb]] == keys[inb]
        return hit

    def dedup(self, keys: np.ndarray) -> np.ndarray:
        """First-occurrence indices of new keys, in stream order; merges
        the corresponding keys into the master."""
        keys = keys.astype(U64, copy=False)
        n = keys.size
        if n == 0:
            return np.empty(0, np.int64)
        order = np.argsort(keys, kind="stable")   # stable: ties keep
        sk = keys[order]                          # stream order
        first = np.ones(n, bool)
        first[1:] = sk[1:] != sk[:-1]
        cand_idx = order[first]                   # first occurrence per key
        cand_keys = sk[first]
        pos = np.searchsorted(self._m, cand_keys)
        inb = pos < self._m.size
        dup = np.zeros(cand_keys.shape, bool)
        dup[inb] = self._m[pos[inb]] == cand_keys[inb]
        new_idx = cand_idx[~dup]
        if new_idx.size:
            # np.insert positions refer to the pre-insert array, so one
            # O(master + new) pass merges the whole sorted batch
            self._m = np.insert(self._m, pos[~dup], cand_keys[~dup])
        return np.sort(new_idx)
