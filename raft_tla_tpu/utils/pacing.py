"""Adaptive segment pacing — the shared chunks-per-dispatch controller.

Every segmented engine (device, paged, streamed, ddd, shard, pagedshard)
runs its search as repeated device dispatches of ``budget`` chunks and
retunes the budget after each one.  The controller had been copied
inline into all six loops; any fix (e.g. the executed-count ADVICE fix)
had to be replicated six times.  This is the single implementation.

Policy (unchanged from the engines' inline copies):

- aim each dispatch at ``target_s`` wall seconds (geometric scaling,
  bounded to [0.25x, 2x] per step, clamped into [lo, hi]);
- never *project* a segment past ``clamp_s`` at the worst per-chunk cost
  ever observed — the deployment tunnel kills any single device program
  after ~60 s, so the budget must stay safe even when the run's cheap
  ragged tail is followed by a wide level (the watchdog clamp,
  device_engine.py's original comment);
- the first dispatch carries the XLA compile and is excluded from the
  timing signal;
- dispatches under 50 ms carry no usable signal and are skipped.
"""

from __future__ import annotations


class SegmentPacer:
    """Feed ``update(dt, executed)`` after every dispatch; read
    ``budget`` for the next one."""

    def __init__(self, seg_chunks: int, lo: int, hi: int,
                 target_s: float, clamp_s: float):
        self.budget = max(1, seg_chunks)   # 0/negative would spin forever
        self.lo = lo
        self.hi = hi
        self.target_s = target_s
        self.clamp_s = clamp_s
        self.worst_s_per_chunk = 0.0
        self._first = True

    def update(self, dt: float, executed: int) -> int:
        """``dt``: wall seconds of the completed dispatch (host-side cost
        like pageout may be included — that overestimates chunk cost,
        which is the safe direction for the watchdog).  ``executed``: the
        chunk count the segment actually ran (pass the requested budget
        when the engine has no executed count)."""
        if self._first:
            self._first = False
            return self.budget
        if dt <= 0.05:
            return self.budget
        self.worst_s_per_chunk = max(self.worst_s_per_chunk,
                                     dt / max(1, executed))
        scale = min(2.0, max(0.25, self.target_s / dt))
        b = int(min(self.hi, max(self.lo, self.budget * scale)))
        self.budget = max(self.lo, min(
            b, int(self.clamp_s / self.worst_s_per_chunk)))
        return self.budget
