"""Double-buffered upload prefetch for the DDD harvest loops.

After PR 13 (async cross-bin dispatch) and PR 14 (background dedup
flush), the one synchronous host phase left in the harvest loop was the
per-block frontier **upload**: drain the in-flight flush, read the
block's rows + constraint column from the host store (a DISK read in
frontier retention), pad, and ``device_put`` — all while the device
sits idle at the block boundary.  `BlockPrefetcher` moves that chain
onto one daemon thread: while the device expands block k, the worker
reads block k+1 (its address is known from ``level_ends`` the moment
the level starts) and stages it into one of two preallocated buffer
sets via async ``jax.device_put``; at the boundary the engine swaps to
an already-resident buffer.

Why this is safe (the byte-identity argument):

- **Disjointness.** Within a level, every block read targets rows in
  ``[lvl_lo, lvl_hi)`` — fully published before the level began (the
  level boundary drains the flush worker before ``level_ends`` grows).
  Concurrent flush appends only ever land at ``>= lvl_hi``.  The host
  stores guarantee one-appender + disjoint-range-reader safety
  (``utils/native``: atomic block directory with release-published
  size in C++, snapshot reads in the fallback, positionless ``preadv``
  in `FileStore`), so the prefetch read and the in-flight flush never
  touch the same rows and the upload can drop its unconditional
  ``dedup_wait`` drain.
- **Depth-1, strict protocol.** At most one prefetch is in flight; the
  engine calls ``take(start, rows)`` then ``schedule(next)``, and a
  ``take`` whose range does not match the staged result falls back to
  a synchronous load (a *miss*) — so the values uploaded are the same
  bytes the synchronous path would have read, hit or miss.
- **Invalidation.** Stop events (violation / SIGINT / deadline) and
  level boundaries call ``invalidate()``, which discards staged and
  in-flight work and returns only once the worker is quiescent — no
  in-flight store read survives into a frontier rotation or teardown,
  and the refbfs-exact stop point is untouched.

Worker exceptions are captured and re-raised on the main thread at the
next ``schedule``/``take`` (the `flushq.DedupWorker` pattern);
``invalidate``/``close`` never raise, so stop paths cannot be masked.

Gated by ``RAFT_TLA_PREFETCH`` / ``check.py --prefetch``; the ``off``
arm never constructs a prefetcher and is byte-for-byte the old loop.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

ENV_PREFETCH = "RAFT_TLA_PREFETCH"


def prefetch_enabled(env: str | None = None) -> bool:
    """Resolve the upload-prefetch gate (``RAFT_TLA_PREFETCH``).

    ``on`` / ``off`` force; anything else is ``auto``: enabled iff the
    host has a second core to run the prefetch thread on.  Measured
    (runs/prefetch_ab.py, this container at nproc=1): the *median*
    block boundary drops 6-8x even single-core (the read+h2d chain
    overlaps GIL-releasing device work), but the *worst* boundary
    degrades — a time-sliced worker that has not finished by the
    boundary costs more than the inline chain — and the frontier/disk
    regime, the feature's headline, nets 0.91x in-engine.  The tail
    and the headline regime need a real second core, so auto mirrors
    ``keyset.host_dedup_enabled``.
    """
    v = (env if env is not None else os.environ.get(ENV_PREFETCH, "auto"))
    v = v.strip().lower()
    if v == "on":
        return True
    if v == "off":
        return False
    return (os.cpu_count() or 1) >= 2


class BlockPrefetcher:
    """Stage block reads on a background thread, depth-1, double-buffered.

    ``loader(start, rows, slot) -> Any`` is engine-supplied: it reads
    the stores, stages into the slot-indexed preallocated buffers, and
    returns device-resident arrays (calling ``block_until_ready`` so
    the slot's host buffers are reusable once the result is taken).
    The loader runs on the worker thread on hits and on the caller's
    thread on misses — it must be safe for either, which the store
    concurrency contract (module docstring) provides.
    """

    def __init__(self, loader: Callable[[int, int, int], Any], *,
                 slots: int = 2, name: str = "raft-tla-prefetch",
                 phases=None, tracer=None):
        self._loader = loader
        self._phases = phases               # PhaseTimers | None: the
        # worker-side stage accrues a prefetch@<thread> bucket (and a
        # span) so background reads are attributed, not invisible
        self._tracer = tracer               # SpanTracer | None: take()
        # emits a hit/miss-tagged span nested under the engine's upload
        self._slots = int(slots)
        self._next_slot = 0
        self._gen = 0                       # bumped by invalidate()
        self._cv = threading.Condition()
        self._req: tuple | None = None      # (gen, start, rows, slot)
        self._ready: tuple | None = None    # (gen, start, rows, result)
        self._busy = False
        self._exc: BaseException | None = None
        self._closed = False
        self.hits = 0
        self.misses = 0
        self.wait_s = 0.0                   # main-thread wall in take()
        self._t = threading.Thread(target=self._run, name=name,
                                   daemon=True)
        self._t.start()

    # -- worker thread ----------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._req is None and not self._closed:
                    self._cv.wait()
                if self._req is None:       # closed and idle
                    return
                gen, start, rows, slot = self._req
                self._req = None
                self._busy = True
            try:
                if self._phases is not None:
                    with self._phases.phase("prefetch"):
                        res, err = self._loader(start, rows, slot), None
                else:
                    res, err = self._loader(start, rows, slot), None
            except BaseException as e:      # noqa: BLE001 — re-raised on main
                res, err = None, e
            with self._cv:
                self._busy = False
                if err is not None:
                    self._exc = self._exc or err
                elif gen == self._gen:      # stale results are dropped
                    self._ready = (gen, start, rows, res)
                self._cv.notify_all()

    def _reraise_locked(self) -> None:
        exc, self._exc = self._exc, None
        if exc is not None:
            raise RuntimeError("background upload prefetch failed") from exc

    # -- main thread ------------------------------------------------------

    def schedule(self, start: int, rows: int) -> None:
        """Non-blocking: stage ``[start, start + rows)`` in the
        background into the next slot."""
        with self._cv:
            self._reraise_locked()
            if self._closed:
                raise RuntimeError("BlockPrefetcher is closed")
            slot = self._next_slot
            self._next_slot = (slot + 1) % self._slots
            self._ready = None              # depth-1: one staged result
            self._req = (self._gen, start, rows, slot)
            self._cv.notify_all()

    def take(self, start: int, rows: int) -> Any:
        """Return staged data for ``[start, start + rows)``; waits for a
        matching in-flight stage (hit), else loads synchronously on the
        calling thread (miss).  Either way the worker is quiescent when
        this returns."""
        tr = self._tracer
        if tr is not None and tr.enabled:
            with tr.span("take", start=int(start), rows=int(rows)) as sp:
                res, hit = self._take(start, rows)
                sp.set(hit=hit)
                return res
        return self._take(start, rows)[0]

    def _take(self, start: int, rows: int) -> tuple:
        t0 = time.perf_counter()
        with self._cv:
            self._reraise_locked()
            while self._busy or self._req is not None:
                self._cv.wait()
            self._reraise_locked()
            r = self._ready
            self._ready = None
            if r is not None and r[0] == self._gen \
                    and (r[1], r[2]) == (start, rows):
                self.hits += 1
                self.wait_s += time.perf_counter() - t0
                return r[3], True
            slot = self._next_slot
            self._next_slot = (slot + 1) % self._slots
        self.misses += 1
        res = self._loader(start, rows, slot)
        self.wait_s += time.perf_counter() - t0
        return res, False

    def invalidate(self) -> None:
        """Discard staged and pending work; block until the worker is
        quiescent.  No in-flight store read survives this call.  Never
        raises (stop paths call it); worker errors surface at the next
        ``schedule``/``take``."""
        with self._cv:
            self._gen += 1
            self._req = None
            self._ready = None
            while self._busy:
                self._cv.wait()

    def close(self) -> None:
        """Invalidate, stop and join the worker thread (idempotent)."""
        if self._closed:
            return
        with self._cv:
            self._gen += 1
            self._req = None
            self._ready = None
            self._closed = True
            self._cv.notify_all()
        self._t.join(timeout=60.0)
