"""Depth-1 ordered background flush worker for the DDD engines.

The exact-dedup flush (`DDDEngine._flush`) was the last fully serial
host phase in the harvest loop: while `MasterKeys.dedup` argsorts and
merges on the main thread, the two-deep segment pipeline drains and the
device sits idle.  `DedupWorker` moves the flush onto one daemon thread
with **depth-1 ordered** submission — the same ticket discipline as
`serve/sched.py`: `submit(batch_i)` blocks until flush i-1 has fully
completed, so flushes execute strictly in submission order and at most
one sealed batch is ever in flight.  Cross-flush first-occurrence order
(the whole exactness argument of ddd_engine.py) is therefore untouched:
flush i's new keys are in the master tiers before flush i+1's dedup
begins, exactly as in the synchronous engine.

The engine's drain discipline (ddd_engine.py): every reader of state the
flush mutates — checkpoint save, level boundaries, `_IDX_CEIL` checks,
violation identity, lossless SIGINT/deadline stops — calls `drain()`
first, so all byte-identity and lossless-stop arguments reduce to the
synchronous case.  The block upload drains too when the prefetch gate is
off; with ``RAFT_TLA_PREFETCH`` on it instead relies on the stores'
one-appender + disjoint-range-reader contract (utils/native,
utils/prefetch) — uploads read only rows published before the level
began, while an in-flight flush appends strictly past them.

Worker exceptions are captured and re-raised on the main thread at the
next `submit`/`collect`/`drain`, so a flush failure cannot be silently
swallowed.  Gated by ``RAFT_TLA_HOSTDEDUP`` (utils/keyset.py); the
``off`` arm never constructs a worker.

Attribution: the flush itself runs off the main thread, so without help
it is invisible to both ``--phase-timers`` (whose buckets used to be
main-thread-only) and traces.  Pass ``phases=`` (a
``PhaseTimers``; duck-typed, may be None) and each flush accrues a
``dedup@raft-tla-flush`` bucket and — when tracing is on — emits a v8
``dedup`` span on its own thread track, making the overlap (or lack of
it) visible in the merged timeline next to the main thread's
``dedup_submit``/``dedup_wait``.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable


class DedupWorker:
    """Run ``fn(batch) -> n_new`` on a background thread, one batch at a
    time, in submission order."""

    def __init__(self, fn: Callable[[Any], int], *,
                 name: str = "raft-tla-flush", phases=None):
        self._fn = fn
        self._phases = phases                 # PhaseTimers | None
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._slot = threading.Semaphore(1)   # depth-1 backpressure
        self._lock = threading.Lock()
        self._done_new = 0                    # flushed, not yet collected
        self._inflight_keys = 0               # raw keys of pending batch
        self._exc: BaseException | None = None
        self._closed = False
        self._t = threading.Thread(target=self._run, name=name, daemon=True)
        self._t.start()

    # -- worker thread -------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            batch, _n_keys = item
            try:
                if self._phases is not None:
                    with self._phases.phase("dedup"):
                        n_new = int(self._fn(batch))
                else:
                    n_new = int(self._fn(batch))
                with self._lock:
                    self._done_new += n_new
            except BaseException as e:        # noqa: BLE001 — re-raised on main
                with self._lock:
                    self._exc = e
            finally:
                with self._lock:
                    self._inflight_keys = 0
                self._slot.release()

    def _reraise(self) -> None:
        with self._lock:
            exc, self._exc = self._exc, None
        if exc is not None:
            raise RuntimeError("background dedup flush failed") from exc

    # -- main thread ---------------------------------------------------------

    def submit(self, batch: Any, n_keys: int) -> None:
        """Enqueue a sealed batch.  Blocks until the previous flush has
        completed (ordered, depth-1), so the harvest loop overlaps at
        most one flush with device compute."""
        if self._closed:
            raise RuntimeError("DedupWorker is closed")
        self._slot.acquire()
        try:
            self._reraise()
        except BaseException:
            self._slot.release()              # keep drain() unblocked
            raise
        with self._lock:
            self._inflight_keys = int(n_keys)
        self._q.put((batch, n_keys))

    def collect(self) -> int:
        """Non-blocking: take (and reset) the new-state count of every
        flush completed since the last collect/drain."""
        self._reraise()
        with self._lock:
            n, self._done_new = self._done_new, 0
        return n

    def drain(self) -> int:
        """Block until the in-flight flush (if any) completes; return
        the uncollected new-state count.  After this returns, the master
        set, stores and coverage reflect every submitted batch."""
        self._slot.acquire()
        self._slot.release()
        return self.collect()

    def backlog(self) -> int:
        """1 if a flush is pending/in flight, else 0 (obs flush_backlog)."""
        with self._lock:
            return 1 if self._inflight_keys else 0

    def inclusive_extra(self) -> int:
        """Completed-but-uncollected new states plus raw in-flight keys,
        for the progress n_incl upper bound (telemetry only)."""
        with self._lock:
            return self._done_new + self._inflight_keys

    def close(self) -> None:
        """Drain, stop and join the worker thread (idempotent).  Any
        uncollected count is discarded — callers drain first."""
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._t.join(timeout=60.0)
