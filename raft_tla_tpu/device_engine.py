"""Device-resident BFS engine — the flagship L4 checker (SURVEY §7.1 step 5-6).

``engine.py`` proved the semantics with a host-side dedup loop; this module is
the TPU-first redesign the hardware demands.  Measured on the deployment
tunnel, every host↔device round trip costs ~0.7 s and every eager-op compile
~10 s, so the architecture keeps **all search state resident in HBM**: the
state store, the fingerprint table, the frontier, parent links, coverage
counters and violation flags never leave the device.  The host sees nothing
but a ``done`` scalar until the search ends, then makes at most two more
gathers to reconstruct a counterexample trace.

Execution is **segmented**: one jitted *segment* advances the search by up to
``seg_chunks`` chunk expansions (crossing BFS-level boundaries freely) and
returns the carry, whose buffers are **donated** back into the next segment
call — zero copies, zero reallocation.  Segmenting exists because single XLA
program executions are killed by the deployment tunnel's watchdog at roughly
a minute of device time (measured empirically: ~25 s fine, ~2 min kills the
TPU worker process); it also gives the host a natural place to snapshot the
carry for checkpoint/resume and to report per-level progress (SURVEY §5).
The search is resumable mid-level: the chunk cursor is part of the carry.

Architecture (all shapes static — XLA's compilation model, SURVEY §7.2.4):

- **Store** ``[Ncap, W] int32``: every discovered state, in discovery order.
  Because BFS is level-synchronous, each level is a *contiguous segment*
  ``[level_start, level_end)`` — the frontier is a slice of the store, never
  a separate buffer.
- **Fingerprint table** ``2·[Tcap/8, 8] uint32``: a bucketized open-
  addressing hash set of (hi, lo) fingerprint pairs (TLC's FP64 set, SURVEY
  §2.8), probed bucket-rows-at-a-time with batched inserts resolved by a
  scatter-min claim protocol (full design notes on ``_dedup_insert``).
  ``scatter-min`` by flat index makes the *first* candidate in discovery
  order the winner — exactly the oracle's first-discoverer-is-parent rule,
  so parent links and traces match refbfs.
- **Per-chunk fused step** (``ops/kernels.build_step``): unpack → all action
  guards/effects → canonicalize → pack → fingerprint → invariants →
  constraint, for ``chunk`` states × A action lanes at a time.
- **TLC CONSTRAINT semantics**: states violating the bound are stored,
  counted and invariant-checked, but their expansion lanes are masked off
  (``conflag`` gates ``valid``).
- **Failure is loud** (SURVEY §4.5): store overflow, level overflow, probe
  overflow and transition-capacity overflow each set a flag that aborts the
  search; the host raises.  Nothing is silently clamped.

Fingerprint collisions merge states, as in TLC (probability ~2^-64 per pair;
the parity tests run on spaces where a collision would surface as a count
mismatch).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import os
import time
from collections import Counter
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.engine import DEADLOCK, EngineResult, Violation
from raft_tla_tpu.obs import RunTelemetry
from raft_tla_tpu.models import interp, invariants as inv_mod, spec as S
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym_mod
from raft_tla_tpu.utils import ckpt
from raft_tla_tpu.utils import pacing

I32 = jnp.int32
U32 = jnp.uint32
_EMPTY = np.uint32(0xFFFFFFFF)   # table sentinel: both words all-ones
_MAX_PROBE = 64                  # probe-iteration safety cap -> fail flag
BUCKET = 8                       # fingerprint-table slots per bucket row


@dataclasses.dataclass(frozen=True)
class Capacities:
    """Static shapes of one compiled search. Doubling any field recompiles."""

    n_states: int = 1 << 20      # store rows (Ncap)
    levels: int = 256            # max BFS depth (Lcap)

    @property
    def table(self) -> int:      # hash slots, load factor <= 0.5
        return 1 << (2 * self.n_states - 1).bit_length()

    def grown(self) -> "Capacities":
        return dataclasses.replace(self, n_states=self.n_states * 2)


# Chip-measured note (round 4, runs/filter_inengine.out): inside a
# while_loop body that both GATHERS from and SCATTERS to the same carry
# table, XLA materializes a full defensive copy of the table every
# iteration (~45 ns per byte of table) — in-place donation does not
# apply.  For this EXACT table the size is a correctness requirement
# (unlike the DDD engines' shrinkable lossy filter), so large --cap
# runs pay ~45 ms/chunk per GiB of table; that copy, not the probe
# gathers, is most of what the round-2 "paged engine at 2^28 slots
# measured ~8k orbits/s" observation was.  The DDD engines are the
# designed escape (host-exact dedup, small filter).
def _dedup_insert(tbl_hi, tbl_lo, key_hi, key_lo, active):
    """Batched insert-if-absent of fingerprint pairs into the hash set.

    Returns ``(tbl_hi, tbl_lo, is_new, unres)``.  ``is_new[c]`` is True
    iff candidate c's key was absent and c is the *first* active candidate
    (smallest flat index) carrying that key in this batch.  ``unres[c]``
    is True iff lane c's probe was still unresolved at ``_MAX_PROBE`` —
    its key was neither matched nor inserted.  The table engines treat
    any unresolved lane as fatal (``jnp.any(unres) * FAIL_PROBE``); the
    devdedup filter instead streams such lanes to the exact host tier.

    Two-stage design (dedup is the chunk pipeline's hottest stage —
    measured 30 ms of a 53 ms chunk before these changes):

    1. **In-batch dedup by sort**: one ``lexsort`` finds each key's first
       active occurrence; only those lanes probe the table at all.  BFS
       batches carry heavy duplication (every state is typically produced
       by several (parent, action) lanes), so this removes most table
       traffic outright.
    2. **Probe with a hashed claim domain**: contenders for an empty slot
       scatter-min their flat index into a small claim array indexed by
       ``slot mod CA`` rather than a table-sized one (which materialized
       the full table width every probe iteration).  Distinct slots
       sharing a claim cell are false contention: the cell's loser simply
       re-contends next iteration — correctness is unaffected, and at
       CA = 4·BA the collision rate is a few percent.

    ``scatter-min`` by flat index makes the *first* candidate in discovery
    order the winner — the oracle's first-discoverer-is-parent rule.
    """
    BA = key_hi.shape[0]
    TB, S = tbl_hi.shape            # buckets x slots
    bmask = jnp.uint32(TB - 1)
    ids = jnp.arange(BA, dtype=I32)
    h0 = key_lo & bmask             # lo lane is already avalanche-mixed

    # -- stage 1: batch-first occurrences (smallest id per distinct key) --
    # Two stable sorts (lexsort cost scales with key count); inactive lanes
    # sort to the back under all-ones keys.  An active lane whose real key
    # is all-ones may interleave with them and get conservatively marked
    # first-of-key — it then probes redundantly and resolves as a duplicate
    # through the claim protocol, so correctness is unaffected.
    skh = jnp.where(active, key_hi, _EMPTY)
    skl = jnp.where(active, key_lo, _EMPTY)
    perm = jnp.lexsort((skl, skh))      # stable: ties keep id order
    ph, pl = key_hi[perm], key_lo[perm]
    pa = active[perm]
    same_as_prev = jnp.concatenate([
        jnp.zeros((1,), bool),
        (ph[1:] == ph[:-1]) & (pl[1:] == pl[:-1]) & pa[1:] & pa[:-1]])
    first_of_key = jnp.zeros((BA,), bool).at[perm].set(~same_as_prev)
    probe = active & first_of_key

    CA = max(1024, 1 << (4 * BA - 1).bit_length())
    cmask = jnp.int32(CA - 1)

    def cond(c):
        _, _, unres, _, d, _ = c
        return jnp.any(unres) & (d < _MAX_PROBE)

    def body(c):
        tbl_hi, tbl_lo, unres, is_new, d, dist = c
        bidx = ((h0 + dist.astype(U32)) & bmask).astype(I32)
        # One ROW gather per lane (the TPU embedding-lookup fast path)
        # examines S slots at once — the whole batch advances in lockstep,
        # so iteration count is set by the worst lane, and S-wide buckets
        # divide the worst probe chain by S.
        row_hi, row_lo = tbl_hi[bidx], tbl_lo[bidx]          # [L, S]
        slot_empty = (row_hi == _EMPTY) & (row_lo == _EMPTY)
        slot_match = (row_hi == key_hi[:, None]) & (row_lo == key_lo[:, None])
        dup_old = unres & jnp.any(slot_match, axis=1)
        has_empty = jnp.any(slot_empty, axis=1)
        contend = unres & ~dup_old & has_empty
        # Claim a bucket via scatter-min into a small hashed claim domain;
        # smallest flat index wins — the oracle's first-discoverer rule.
        cidx = bidx & cmask
        claim = jnp.full((CA,), BA, dtype=I32).at[
            jnp.where(contend, cidx, CA)].min(
                jnp.where(contend, ids, BA), mode="drop")
        won = contend & (claim[cidx] == ids)
        wslot = jnp.argmax(slot_empty, axis=1)               # first empty
        wb = jnp.where(won, bidx, TB)
        tbl_hi = tbl_hi.at[wb, wslot].set(key_hi, mode="drop")
        tbl_lo = tbl_lo.at[wb, wslot].set(key_lo, mode="drop")
        # Losers consult the winner through the (VMEM-sized) claim/key
        # arrays instead of re-gathering the table: if the winner put MY
        # key in MY bucket, I'm a duplicate; otherwise my bucket merely
        # gained an entry (same bucket) or nothing changed (false claim
        # collision) — either way retry the same bucket, which is only
        # left behind when it has no empty slot at all.
        wid = jnp.clip(claim[cidx], 0, BA - 1)
        dup_batch = contend & ~won & (bidx[wid] == bidx) & \
            (key_hi[wid] == key_hi) & (key_lo[wid] == key_lo)
        resolved = dup_old | won | dup_batch
        unres = unres & ~resolved
        dist = dist + (unres & ~has_empty).astype(I32)       # bucket full
        return tbl_hi, tbl_lo, unres, is_new | won, d + 1, dist

    init = (tbl_hi, tbl_lo, probe, jnp.zeros((BA,), bool), jnp.int32(0),
            jnp.zeros((BA,), I32))
    tbl_hi, tbl_lo, unres, is_new, _, _ = jax.lax.while_loop(cond, body, init)
    return tbl_hi, tbl_lo, is_new, unres


# Failure bitmask (the "fail loudly" contract, SURVEY §4.5).
FAIL_WIDTH = 1      # a successor exceeded a tensor-encoding capacity
FAIL_PROBE = 2      # linear probe exceeded _MAX_PROBE (table too full)
FAIL_STORE = 4      # more distinct states than Capacities.n_states
FAIL_LEVEL = 8      # BFS deeper than Capacities.levels
FAIL_RING = 16      # paged engine: live BFS window outgrew the HBM ring
FAIL_ROUTE = 32     # a routing budget overflowed: shard engine's
                    # all_to_all exchange halo, or the EP-routed step's
                    # route_rows compaction slots (ddd_engine)
FAIL_INDEX = 64     # paged engine: discovery index near the int32 ceiling

_FAIL_TEXT = {
    FAIL_WIDTH: "state-width overflow (encoding capacity exceeded)",
    FAIL_PROBE: "fingerprint-table probe overflow (table too full)",
    FAIL_STORE: "state-store capacity exceeded",
    FAIL_LEVEL: "BFS level capacity exceeded",
    FAIL_RING: "live BFS window exceeded the HBM ring",
    FAIL_ROUTE: "routing budget exceeded (all_to_all halo or EP "
                "route_rows too small)",
    FAIL_INDEX: "global state index reached the int32 ceiling "
                "(2^31-1 rows/device is the per-run limit)",
}


def decode_fail(fail_bits: int) -> str:
    return "; ".join(txt for bit, txt in _FAIL_TEXT.items()
                     if fail_bits & bit) or "unknown"


# -- 64-bit run counters without jax_enable_x64 ----------------------------
# JAX's default x64-disabled mode silently narrows jnp.int64 to int32, and
# the round-1 flagship already logged 258M transitions — a 5-server/2-value
# run exceeds 2^31, where an int32 accumulator wraps silently.  Counters
# that can pass 2^31 are therefore carried as TWO uint32 limbs with
# branchless carry propagation (regression: tests/test_device_engine.py::
# test_transition_counter_64bit).  State *indices* stay int32: the device
# and shard engines bound rows by Capacities.n_states (far below 2^31 at
# any allocatable HBM size; the shard engine additionally asserts
# ndev * n_states fits the int32 global-id space at construction), and the
# paged engine fails loudly via FAIL_INDEX before its global discovery
# index could wrap.

def _acc64_zero():
    return jnp.zeros((2,), U32)


def _acc64_add(acc, delta):
    """``acc (+)= delta`` for a traced int32 ``0 <= delta < 2^31``."""
    lo = acc[..., 0] + delta.astype(U32)
    hi = acc[..., 1] + (lo < acc[..., 0]).astype(U32)
    return jnp.stack([lo, hi], axis=-1)


def acc64_int(arr) -> int:
    """Host side: combine two-limb counters (summing any leading axes)."""
    a = np.asarray(arr, dtype=np.uint64).reshape(-1, 2)
    return int(((a[:, 1] << np.uint64(32)) | a[:, 0]).sum())


def widen_legacy_n_trans(arrs: list, fields: tuple) -> list:
    """Checkpoint migration: round-1 checkpoints carried ``n_trans`` as a
    scalar (or per-device vector of) int32; widen to the two-limb uint32
    layout so long runs resume across the upgrade."""
    i = fields.index("n_trans")
    a = np.asarray(arrs[i])
    if a.dtype != np.uint32:
        lo = a.astype(np.int64).reshape(-1).astype(np.uint32)
        limbs = np.stack([lo, np.zeros_like(lo)], axis=-1)
        arrs[i] = limbs[0] if a.ndim == 0 else limbs.reshape(-1)
    return arrs


class Carry(NamedTuple):
    """The segment carry: the entire search state, resident in HBM.

    A NamedTuple is a pytree, so it threads through ``lax.while_loop`` and
    ``donate_argnums`` unchanged while keeping every access self-describing.
    """

    store: jax.Array      # [Ncap, W] every discovered state, discovery order
    parent: jax.Array     # [Ncap] parent row (trace links)
    lane: jax.Array       # [Ncap] action lane that produced the row
    conflag: jax.Array    # [Ncap] state satisfies the CONSTRAINT -> expand
    tbl_hi: jax.Array     # [Tcap] fingerprint table, hi words
    tbl_lo: jax.Array     # [Tcap] fingerprint table, lo words
    n_states: jax.Array   # rows used
    lvl_start: jax.Array  # current BFS level window [lvl_start, lvl_end)
    lvl_end: jax.Array
    viol_g: jax.Array     # first violating row, -1 if none
    viol_i: jax.Array     # index into config.invariants
    n_trans: jax.Array    # [2] uint32 limbs: enabled (state, action) pairs
    cov: jax.Array        # [A] per-lane new-state counts
    fail: jax.Array       # FAIL_* bitmask
    levels: jax.Array     # [Lcap] per-level new-state counts
    lvl: jax.Array        # current level number
    c: jax.Array          # chunk cursor within the current level


def _carry_done(carry: Carry):
    """Search-complete predicate over the segment carry."""
    return ((carry.lvl_end <= carry.lvl_start) | (carry.viol_g >= 0)
            | (carry.fail != 0))


def _build_segment(config: CheckConfig, caps: Capacities, A: int, W: int):
    """One watchdog-safe slice of the search: ≤ ``budget`` chunk steps.

    ``budget`` is a traced scalar, so the host can retune the segment length
    every dispatch (targeting a fixed seconds-per-segment) without
    recompiling.
    """
    B = config.chunk
    n_inv = len(config.invariants)
    # Orbit-scan variants (prescan ladder, sig-prune) are resolved inside
    # build_step at CONSTRUCTION time from their env gates — set
    # RAFT_TLA_SIGPRUNE/RAFT_TLA_PRESCAN before building the engine.
    step = kernels.build_step(config.bounds, config.spec,
                              tuple(config.invariants), config.symmetry,
                              view=config.view)
    Ncap, Lcap, Tcap = caps.n_states, caps.levels, caps.table
    BIG = jnp.int32(np.iinfo(np.int32).max)

    def chunk_body(carry: Carry) -> Carry:
        (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
         lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail,
         levels, lvl, c) = carry
        start = lvl_start + c * B
        gstart = jnp.minimum(start, Ncap - B)      # clamped window (see below)
        rows_g = gstart + jnp.arange(B, dtype=I32)
        row_act = (rows_g >= start) & (rows_g < lvl_end)
        vecs = jax.lax.dynamic_slice(store, (gstart, 0), (B, W))
        out = step(vecs)
        con_par = jax.lax.dynamic_slice(conflag, (gstart,), (B,))
        valid = out["valid"] & row_act[:, None] & con_par[:, None]
        n_trans = _acc64_add(n_trans, jnp.sum(valid.astype(I32)))
        fail = fail | jnp.any(valid & out["overflow"]) * FAIL_WIDTH

        fhi = out["fp_hi"].reshape(-1)
        flo = out["fp_lo"].reshape(-1)
        fvalid = valid.reshape(-1)
        tbl_hi, tbl_lo, is_new, pfail = _dedup_insert(
            tbl_hi, tbl_lo, fhi, flo, fvalid)
        fail = fail | jnp.any(pfail) * FAIL_PROBE

        # Append new states to the store in discovery order.
        pos = n_states + jnp.cumsum(is_new.astype(I32)) - 1
        sl = jnp.where(is_new & (pos < Ncap), pos, Ncap)
        svecs = out["svecs"].reshape(B * A, W)
        store = store.at[sl].set(svecs, mode="drop")
        flat_b = jnp.arange(B * A, dtype=I32) // A
        flat_a = jnp.arange(B * A, dtype=I32) % A
        parent = parent.at[sl].set(gstart + flat_b, mode="drop")
        lane = lane.at[sl].set(flat_a, mode="drop")
        conflag = conflag.at[sl].set(out["con_ok"].reshape(-1), mode="drop")
        cov = cov.at[jnp.where(is_new, flat_a, A)].add(1, mode="drop")

        n_new = jnp.sum(is_new.astype(I32))
        fail = fail | (n_states + n_new > Ncap) * FAIL_STORE
        n_states = jnp.minimum(n_states + n_new, Ncap)

        # First invariant violation among new states, in discovery order.
        inv_bad = is_new & jnp.any(
            ~out["inv_ok"].reshape(B * A, n_inv), axis=-1) if n_inv \
            else jnp.zeros((B * A,), bool)
        first = jnp.min(jnp.where(inv_bad, jnp.arange(B * A, dtype=I32), BIG))
        bad_inv = jnp.argmax(
            ~out["inv_ok"].reshape(B * A, n_inv)
            [jnp.minimum(first, B * A - 1)]) if n_inv else jnp.int32(0)
        g_target = pos[jnp.minimum(first, B * A - 1)]
        if config.check_deadlock:
            # TLC's default deadlock check: an expanded row with no enabled
            # action (pre-constraint — CONSTRAINT gates exploration, not
            # enabledness).  Flat priority b*A orders it before any
            # successor of the same row, after earlier rows' successors.
            dead = row_act & con_par & ~jnp.any(out["valid"], axis=1)
            drow = jnp.min(jnp.where(dead, jnp.arange(B, dtype=I32), BIG))
            dpos = jnp.where(drow < BIG // A, drow * A, BIG)
            use_dead = dpos < first
            first = jnp.minimum(first, dpos)
            g_target = jnp.where(use_dead,
                                 gstart + jnp.minimum(drow, B - 1), g_target)
            bad_inv = jnp.where(use_dead, jnp.int32(n_inv), bad_inv)
        has_viol = first < BIG
        new_viol = has_viol & (viol_g < 0)
        viol_g = jnp.where(new_viol, g_target, viol_g)
        viol_i = jnp.where(new_viol, bad_inv, viol_i)
        return Carry(store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
                     lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail,
                     levels, lvl, c + 1)

    def outer_body(sc):
        """Run chunks until the level is exhausted or the budget runs out,
        then (maybe) advance the level window — scalar selects only, so the
        big buffers are never threaded through a conditional."""
        steps, carry = sc
        n_chunks = (carry.lvl_end - carry.lvl_start + B - 1) // B

        def ccond(cc):
            s, inner = cc
            return ((inner.c < n_chunks) & (inner.viol_g < 0) &
                    (inner.fail == 0) & (s < budget))

        def cbody(cc):
            s, inner = cc
            return s + 1, chunk_body(inner)

        steps, carry = jax.lax.while_loop(ccond, cbody, (steps, carry))
        (store, parent, lane, conflag, tbl_hi, tbl_lo, n_states,
         lvl_start, lvl_end, viol_g, viol_i, n_trans, cov, fail,
         levels, lvl, c) = carry
        adv = (c >= n_chunks) & (viol_g < 0) & (fail == 0)
        n_new = n_states - lvl_end
        levels = levels.at[jnp.where(adv, jnp.minimum(lvl, Lcap - 1),
                                     Lcap)].set(n_new, mode="drop")
        fail = fail | (adv & (lvl >= Lcap - 1) & (n_new > 0)) * FAIL_LEVEL
        lvl_start = jnp.where(adv, lvl_end, lvl_start)
        lvl_end = jnp.where(adv, n_states, lvl_end)
        lvl = jnp.where(adv, lvl + 1, lvl)
        c = jnp.where(adv, 0, c)
        return steps, Carry(store, parent, lane, conflag, tbl_hi, tbl_lo,
                            n_states, lvl_start, lvl_end, viol_g, viol_i,
                            n_trans, cov, fail, levels, lvl, c)

    def outer_cond(sc):
        steps, carry = sc
        return (steps < budget) & ~_carry_done(carry)

    def segment(carry, budget_):
        nonlocal budget
        budget = budget_
        _, carry = jax.lax.while_loop(outer_cond, outer_body,
                                      (jnp.int32(0), carry))
        return carry, _carry_done(carry)

    budget = None
    return segment


def _build_init(caps: Capacities, A: int, W: int):
    """The initial segment carry: Init in the store, its FP in the table."""
    Ncap, Lcap, Tcap = caps.n_states, caps.levels, caps.table
    TB = Tcap // BUCKET

    def init(init_vec, init_key_hi, init_key_lo, init_con):
        store = jnp.zeros((Ncap, W), I32).at[0].set(init_vec)
        parent = jnp.full((Ncap,), -1, I32)
        lane = jnp.full((Ncap,), -1, I32)
        conflag = jnp.zeros((Ncap,), bool).at[0].set(init_con)
        b0 = (init_key_lo & jnp.uint32(TB - 1)).astype(I32)
        tbl_hi = jnp.full((TB, BUCKET), _EMPTY, U32).at[b0, 0].set(
            init_key_hi)
        tbl_lo = jnp.full((TB, BUCKET), _EMPTY, U32).at[b0, 0].set(
            init_key_lo)
        levels = jnp.zeros((Lcap,), I32)
        return Carry(store, parent, lane, conflag, tbl_hi, tbl_lo,
                     jnp.int32(1), jnp.int32(0), jnp.int32(1),
                     jnp.int32(-1), jnp.int32(0), _acc64_zero(),
                     jnp.zeros((A,), I32), jnp.int32(0),
                     levels, jnp.int32(1), jnp.int32(0))

    return init


def aggregate_coverage(table, cov) -> Counter:
    """Per-action-family coverage from the device counters ([.., A]) —
    ONE definition for every engine's result assembly and stats stream."""
    cov = np.asarray(cov).reshape(-1, len(table)).sum(axis=0)
    out: Counter = Counter()
    for a, inst in enumerate(table):
        if cov[a]:
            out[inst.family] += int(cov[a])
    return out


class DeviceEngine:
    """One compiled exhaustive checker; reusable across runs."""

    # Adaptive segment sizing: target seconds of device time per dispatch,
    # far enough under the ~60 s watchdog to absorb a 2-3x misprediction.
    SEG_TARGET_S = 8.0
    SEG_CLAMP_S = 25.0       # hard ceiling on projected segment seconds
    SEG_MIN, SEG_MAX = 16, 1 << 16

    def __init__(self, config: CheckConfig, caps: Capacities | None = None,
                 device=None, seg_chunks: int = 64):
        self.config = config
        self.bounds = config.bounds
        self.lay = st.Layout.of(self.bounds)
        self.table = S.action_table(self.bounds, config.spec)
        self.A = len(self.table)
        self.caps = caps or Capacities()
        if self.caps.n_states < config.chunk:
            raise ValueError("Capacities.n_states must be >= config.chunk")
        # jit follows input placement; ``device`` (None = default backend)
        # is applied to the four small inputs in check().
        self.device = device
        self.seg_chunks = seg_chunks    # initial budget; adapted per segment
        self._init = jax.jit(_build_init(self.caps, self.A, self.lay.width))
        # The carry's buffers are donated: each segment updates the search
        # state in place in HBM; the host only syncs on the `done` scalar.
        self._segment = jax.jit(
            _build_segment(config, self.caps, self.A, self.lay.width),
            donate_argnums=(0,))

    # -- checkpoint / resume (SURVEY §5: TLC's states/ + -recover analog) ---
    # A checkpoint is the full carry — the search is a pure function of it,
    # so resume is exact: same discovery order, counts, traces.

    def save_checkpoint(self, path: str, carry: Carry,
                        init_key: tuple) -> None:
        """Snapshot the carry to ``path`` (.npz), atomically.  The digest
        pins the full model identity (bounds/spec/invariants/symmetry/
        chunk/capacities) AND the initial state's dedup key, so a resume
        under a different config or a different ``init_override`` fails
        loudly (utils/ckpt.py)."""
        host = jax.device_get(carry)
        ckpt.atomic_savez(
            path,
            **{f"c{i}": np.asarray(x) for i, x in enumerate(host)},
            config_digest=np.uint64(
                ckpt.config_digest(self.config, self.caps, init_key)),
            width=np.int64(self.lay.width))

    def load_checkpoint(self, path: str, init_key: tuple) -> Carry:
        """Load a carry saved by :meth:`save_checkpoint` (digest-checked)."""
        with ckpt.load_npz_checked(
                path, ckpt.config_digest(self.config, self.caps,
                                         init_key)) as z:
            arrs = [z[f"c{i}"] for i in range(len(Carry._fields))]
        arrs = widen_legacy_n_trans(arrs, Carry._fields)
        carry = Carry(*(jnp.asarray(a) for a in arrs))
        if self.device is not None:
            carry = jax.device_put(carry, self.device)
        return carry

    def check(self, init_override: interp.PyState | None = None,
              checkpoint: str | None = None,
              checkpoint_every_s: float = 600.0,
              resume: str | None = None,
              on_progress=None, retain_carry: bool = False,
              events: str | None = None) -> EngineResult:
        """``on_progress``, if given, is called after every segment with the
        shared :class:`~raft_tla_tpu.obs.ProgressRecord` dict (SURVEY §5
        observability): wall seconds, states found, BFS level, transitions,
        dedup hit rate, cumulative + incremental throughput, live
        per-action-family coverage — TLC's ``-coverage 1`` minute-ticker
        analog, here per segment.  ``events`` (or ``RAFT_TLA_EVENTS``)
        additionally streams the versioned run-event log (obs/events.py).
        Either costs one extra batched transfer per segment.

        ``retain_carry=True`` keeps the final carry on ``self.retained_carry``
        (store/conflag for post-hoc passes, e.g. liveness graph export —
        models/liveness.engine_graph).  The retained buffers stay resident
        in HBM until the caller sets ``retained_carry = None``; a second
        ``check`` on the same engine allocates a fresh carry alongside."""
        t0 = time.monotonic()
        tel = RunTelemetry(
            "device", config=self.config, caps=self.caps,
            on_progress=on_progress, events=events,
            resumed=resume is not None,
            n0=1 if resume is None else None, t0=t0)
        try:
            return self._check_impl(tel, t0, init_override, checkpoint,
                                    checkpoint_every_s, resume, retain_carry)
        finally:
            tel.close()

    def _check_impl(self, tel, t0, init_override, checkpoint,
                    checkpoint_every_s, resume, retain_carry) -> EngineResult:
        bounds = self.bounds
        init_py = init_override if init_override is not None \
            else interp.init_state(bounds)
        init_vec = interp.to_vec(init_py, bounds)
        hi0, lo0 = sym_mod.init_fingerprint(self.config, init_py,
                                            init_vec)
        tel.run_start()

        for nm in self.config.invariants:
            if not inv_mod.py_invariant(nm)(init_py, bounds):
                res = EngineResult(
                    n_states=1, diameter=0, n_transitions=0,
                    coverage=Counter(),
                    violation=Violation(nm, init_py, [(None, init_py)]),
                    levels=[1], wall_s=time.monotonic() - t0)
                tel.run_end(res)
                return res

        args = (jnp.asarray(init_vec, I32), jnp.uint32(hi0), jnp.uint32(lo0),
                jnp.bool_(interp.constraint_ok(init_py, bounds)))
        if self.device is not None:
            args = jax.device_put(args, self.device)
        carry = self.load_checkpoint(resume, (hi0, lo0)) if resume \
            else self._init(*args)
        # Segment loop: each dispatch runs <= budget chunk expansions on
        # device, then the host syncs on one scalar.  Buffers are donated, so
        # the search state never moves.  The budget is retuned each dispatch
        # toward SEG_TARGET_S seconds (the first, compile-carrying dispatch
        # is excluded from the timing signal).
        pacer = pacing.SegmentPacer(self.seg_chunks, self.SEG_MIN,
                                    self.SEG_MAX, self.SEG_TARGET_S,
                                    self.SEG_CLAMP_S)
        budget = pacer.budget
        last_ckpt = time.monotonic()
        while True:
            t_seg = time.monotonic()
            with tel.phases.phase("expand") as ph:
                carry, done = self._segment(carry, jnp.int32(budget))
                ph.sync(done)
            if tel.active:
                with tel.phases.phase("export") as ph:
                    n_states, lvl, n_trans, cov = jax.device_get(
                        (carry.n_states, carry.lvl, carry.n_trans,
                         carry.cov))
                tel.segment(
                    n_states=int(n_states), level=int(lvl),
                    n_transitions=acc64_int(n_trans),
                    coverage=dict(aggregate_coverage(self.table, cov)))
            if bool(done):
                break
            dt = time.monotonic() - t_seg
            if checkpoint and (time.monotonic() - last_ckpt
                               >= checkpoint_every_s):
                with tel.phases.phase("snapshot"):
                    self.save_checkpoint(checkpoint, carry, (hi0, lo0))
                tel.checkpoint(checkpoint)
                last_ckpt = time.monotonic()
            # this segment loop has no executed-chunk count; the requested
            # budget only underestimates chunk cost on early-exiting final
            # segments, which break above — harmless (pacing.py policy)
            budget = pacer.update(dt, budget)
            self.seg_chunks = budget        # warm check() calls start tuned
        if retain_carry:
            self.retained_carry = carry
        # One batched transfer for all the small outputs; the wide arrays
        # (store, parent, lane) stay on device unless a trace is needed.
        (n_states, viol_g, viol_i, n_trans, fail, n_levels, levels_dev,
         cov_arr) = jax.device_get((
             carry.n_states, carry.viol_g, carry.viol_i, carry.n_trans,
             carry.fail, carry.lvl, carry.levels, carry.cov))
        n_states, viol_g, fail = int(n_states), int(viol_g), int(fail)
        if fail:
            raise RuntimeError(
                f"device search aborted: {decode_fail(fail)} "
                f"(caps={self.caps}) — grow Capacities and rerun")
        out = {"store": carry.store, "parent": carry.parent,
               "lane": carry.lane, "viol_i": viol_i,
               "n_transitions": acc64_int(n_trans)}
        # The partially-explored violating level is never recorded (the
        # level window only advances on completed levels), matching refbfs.
        levels_arr = [1] + [int(x) for x in levels_dev[:int(n_levels)]
                            if int(x) > 0]
        coverage: Counter = Counter()
        for a, inst in enumerate(self.table):
            if cov_arr[a]:
                coverage[inst.family] += int(cov_arr[a])

        violation = None
        if viol_g >= 0:
            violation = self._extract_trace(out, viol_g)

        result = EngineResult(
            n_states=n_states,
            diameter=len(levels_arr) - 1,
            n_transitions=int(out["n_transitions"]),
            coverage=coverage,
            violation=violation,
            levels=levels_arr,
            wall_s=time.monotonic() - t0)
        tel.run_end(result)
        return result

    def _extract_trace(self, out, viol_g: int) -> Violation:
        """Two extra transfers: parent/lane links, then the chain's rows."""
        n = viol_g + 1
        parent = np.asarray(out["parent"][:n])
        lane = np.asarray(out["lane"][:n])
        chain_idx = []
        cur = viol_g
        while cur >= 0:
            chain_idx.append(cur)
            cur = int(parent[cur])
        chain_idx.reverse()
        rows = np.asarray(out["store"][jnp.asarray(chain_idx)])
        chain = []
        for k, g in enumerate(chain_idx):
            py = interp.from_struct(
                st.unpack(rows[k], self.lay, np), self.bounds)
            label = self.table[int(lane[g])].label() if g > 0 else None
            chain.append((label, py))
        vi = int(out["viol_i"])   # lint: jit-ok — host path, out is fetched
        inv_name = DEADLOCK if vi == len(self.config.invariants) \
            else self.config.invariants[vi]
        return Violation(invariant=inv_name, state=chain[-1][1], trace=chain)


@functools.lru_cache(maxsize=None)
def _cached_engine(config: CheckConfig, caps: Capacities) -> DeviceEngine:
    return DeviceEngine(config, caps)


def check(config: CheckConfig, caps: Capacities | None = None,
          **kw) -> EngineResult:
    """One-shot convenience mirroring ``engine.check`` / ``refbfs.check``."""
    return _cached_engine(config, caps or Capacities()).check(**kw)
