"""Fault-isolated serving: the supervised worker pool.

:func:`run_service` executes every admitted lane in one process — one
poison cfg that segfaults the step compiler, one lane that OOMs the
device, and every tenant in the batch dies with it.  ``run_pool`` is
the same contract (same admission gate, same per-tenant event logs,
same results.jsonl records) with a blast radius of one worker:

- Admitted jobs are partitioned by step-signature bin
  (:func:`~raft_tla_tpu.serve.batch.bin_key`) into up to ``workers``
  groups, each dispatched to a child process running the ordinary
  serve CLI (``python -m raft_tla_tpu.serve MANIFEST --out OUT
  --drain-on-sigint``) over a self-contained manifest of
  :meth:`CheckJob.to_dict` lines.  Workers write the per-tenant
  ``<id>.events`` logs and crash-safe ``results.jsonl`` records
  themselves — artifacts are byte-compatible with the in-process path.
- A supervision loop tails every worker's tenant logs
  (:class:`~raft_tla_tpu.serve.supervise.WorkerHealth`, built on the
  campaign supervisor's ``_LogTail`` + ``HealthMonitor``) and reaps
  exits.  A lost worker's death is classified
  (:func:`~raft_tla_tpu.serve.supervise.classify_death`) and its
  *unfinished* jobs — terminal results.jsonl records are the ground
  truth — are requeued with decorrelated-jitter backoff.
- Poison bisection: every unfinished job of a dead worker takes one
  blame point; a blamed group is split in half, a job one death short
  of the threshold runs solo, and a job whose K-th death was solo is
  QUARANTINED — an attributed ``quarantined`` results record plus
  tenant-log attribution, and (being terminal) it is never re-run,
  not even across daemon restarts.  Innocent cellmates are re-run
  losslessly (BFS is deterministic: the re-run reproduces the same
  counts, so completed artifacts stay byte-identical to a solo run).
- Graceful degradation: an OOM-classified death takes no blame —
  the group respawns with its dispatch width halved (down to
  ``PoolPolicy.min_chunk``; an OOM at the floor is treated as poison).
  A global respawn budget bounds the whole recovery effort.

Supervision telemetry lands in ``OUT/pool.events`` (obs schema v7:
``worker_spawn`` / ``worker_lost`` / ``job_retry`` / ``quarantine``,
plus campaign-style ``preempt``) so ``raft-tla-monitor`` renders pool
attribution rows with no new tooling.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

from raft_tla_tpu.obs import append_event
from raft_tla_tpu.obs.metrics import ENV_METRICS
from raft_tla_tpu.campaign.supervisor import DecorrelatedBackoff
from raft_tla_tpu.serve import supervise
from raft_tla_tpu.serve.service import (_append_records, _events_path,
                                        _reject_events, read_results,
                                        record_is_terminal)
from raft_tla_tpu.serve.supervise import PoolPolicy, WorkerHealth


class _PoolJob:
    """One admitted job's pool-side state: blame count + base record."""

    def __init__(self, job, rec: dict):
        self.job = job
        self.rec = rec                   # admission-time base record
        self.deaths = 0                  # worker deaths blamed on it
        self.attempts = 0                # times handed to a worker
        self.done = False                # has a terminal results record

    @property
    def job_id(self) -> str:
        return self.job.job_id


class _Group:
    """A unit of dispatch: jobs that ride one worker process."""

    def __init__(self, jobs: list, chunk: int, retry: bool = False,
                 not_before: float = 0.0):
        self.jobs = jobs
        self.chunk = chunk
        self.retry = retry
        self.not_before = not_before

    def pending_jobs(self) -> list:
        return [pj for pj in self.jobs if not pj.done]


class _Worker:
    """One live child process + its health view."""

    def __init__(self, wid: str, group: _Group, proc, out_path: str,
                 health: WorkerHealth):
        self.wid = wid
        self.group = group
        self.proc = proc
        self.out_path = out_path
        self.health = health
        self.preempt: tuple | None = None   # (reason, detail) once signaled
        self.signaled_at: float | None = None
        self.killed = False
        self.draining = False
        self.t0_mono = time.monotonic()     # lifetime span start (tracing)
        self.signal_mono: float | None = None  # SIGINT sent (drain span)

    def out_tail(self, n: int = 4096) -> str:
        try:
            with open(self.out_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - n))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""


def _ensure_newline(path: str) -> None:
    """Guard an append onto a possibly torn tail (a SIGKILLed worker's
    half-written line): the attribution events must start on their own
    line so the reader drops only the torn fragment, never our record."""
    try:
        with open(path, "rb+") as f:
            f.seek(0, os.SEEK_END)
            if f.tell() == 0:
                return
            f.seek(-1, os.SEEK_END)
            if f.read(1) != b"\n":
                f.write(b"\n")
    except OSError:
        pass


def _attribute_stop(path: str, reason: str, outcome: str) -> None:
    """End-state attribution in a tenant's event log — a log is never
    silent about why its run has no verdict.  Appends onto an existing
    (possibly torn) log, or writes a fresh three-event log when the
    job never reached a worker at all."""
    if os.path.exists(path):
        _ensure_newline(path)
    else:
        append_event(path, "run_start", engine="serve", universe={},
                     spec="", invariants=[], resumed=False,
                     pid=os.getpid())
    append_event(path, "stop_requested", reason=reason, source="pool",
                 pid=os.getpid())
    append_event(path, "run_end", n_states=0, n_transitions=0,
                 complete=False, outcome=outcome)


def _partition(admitted: list, workers: int) -> list:
    """Group (job, adm, rec) triples into up to ``workers`` worker
    assignments: same-bin jobs stay together (one compiled step serves
    the whole lane pack), bins round-robin across workers, and when
    there are fewer bins than workers the largest groups split so the
    pool is actually a pool (fault isolation beats compile sharing
    once jobs < workers would otherwise share one blast radius)."""
    from raft_tla_tpu.serve.batch import bin_key

    by_bin: dict = {}
    order: list = []
    for job, adm, rec in admitted:
        key = bin_key(adm.config)
        if key not in by_bin:
            by_bin[key] = []
            order.append(key)
        by_bin[key].append(_PoolJob(job, rec))
    lists = [by_bin[k] for k in order]
    total = sum(len(l) for l in lists)
    while len(lists) < min(workers, total):
        biggest = max(lists, key=len)
        if len(biggest) < 2:
            break
        lists.remove(biggest)
        mid = (len(biggest) + 1) // 2
        lists += [biggest[:mid], biggest[mid:]]
    slots = min(workers, len(lists)) or 1
    assigned: list = [[] for _ in range(slots)]
    for i, l in enumerate(lists):
        assigned[i % slots].extend(l)
    return [a for a in assigned if a]


def run_pool(jobs, out_dir: str, *, workers: int = 2, chunk: int = 1024,
             max_states: int | None = None, quiet: bool = False,
             depth: int = 2, cpu: bool = False,
             policy: PoolPolicy | None = None, spawn_hook=None,
             stop=None, clock=time.time, sleep=time.sleep) -> list:
    """Serve ``jobs`` through the supervised worker pool; returns the
    final results.jsonl record per job (last record wins — a requeued
    job's drained ``stopped`` record is superseded by its re-run).

    ``spawn_hook(worker)`` is the chaos seam, called after every child
    spawn with the live :class:`_Worker` (serve/chaos.py kills through
    it); ``stop`` is the daemon's drain hook — when truthy, active
    workers are SIGINTed (they drain losslessly) and undispatched jobs
    get attributed ``stopped`` records.  ``clock``/``sleep`` are
    injectable for tests.
    """
    from raft_tla_tpu.serve.jobs import admit

    policy = policy or PoolPolicy()
    os.makedirs(out_dir, exist_ok=True)
    pool_dir = os.path.join(out_dir, "pool")
    os.makedirs(pool_dir, exist_ok=True)
    pool_events = os.path.join(out_dir, "pool.events")
    # v8 tracing: worker lifetimes and SIGINT->exit drains become spans
    # in pool.events, and the anchored run_start lets the collector put
    # the supervisor on the same wall axis as its children.  Gated so an
    # untraced pool log is byte-compatible with v7 consumers.
    from raft_tla_tpu.obs.trace import NULL_TRACER, anchored_run_start, \
        trace_enabled, tracer_for
    tracer = NULL_TRACER
    if trace_enabled():
        anchored_run_start(pool_events, "pool")
        tracer = tracer_for(pool_events)

    def say(msg: str) -> None:
        if not quiet:
            print(msg, flush=True)

    # Admission in the parent — host-only, and rejects must not burn a
    # worker spawn.  Workers re-admit their (admitted) manifests; that
    # repeat is cheap and keeps the worker the ordinary serve CLI.
    records: list = []
    admitted: list = []
    for job in jobs:
        t_adm = time.monotonic()
        adm = admit(job)
        try:
            digest = job.digest()
        except (OSError, ValueError):
            digest = None
        rec = {"job_id": job.job_id, "digest": digest,
               "admission_s": round(time.monotonic() - t_adm, 3),
               "events": _events_path(out_dir, job.job_id)}
        if not adm.admitted or adm.properties:
            reason = adm.reason if not adm.admitted \
                else "property-unsupported"
            findings = adm.findings_text() if adm.findings else \
                [f"PROPERTY {list(adm.properties)}: liveness needs a "
                 "dedicated exhaustive run (raft-tla-check --property); "
                 "the batched service checks invariants only"]
            rec.update(status="rejected", reason=reason,
                       findings=findings)
            _reject_events(rec["events"], job, reason)
            say(f"[{job.job_id}] rejected at admission ({reason})")
            records.append(rec)
            continue
        admitted.append((job, adm, rec))
    if records:
        _append_records(out_dir, records)

    pool_jobs: list = []
    pending: list = []
    if admitted:
        groups = _partition(admitted, workers)
        for g in groups:
            pool_jobs.extend(g)
            pending.append(_Group(g, chunk))
        say(f"pool: {len(pool_jobs)} admitted job(s) across "
            f"{len(groups)} worker group(s) "
            f"({len(jobs) - len(pool_jobs)} rejected) — chunk {chunk}, "
            f"up to {workers} worker(s)")

    backoff = DecorrelatedBackoff(policy.backoff_base_s,
                                  policy.backoff_cap_s,
                                  seed=policy.backoff_jitter_seed)
    active: list = []
    wseq = 0
    respawns = 0
    draining = False

    def refresh_done() -> dict:
        """results.jsonl is the ground truth for completion: map every
        job id to its LAST record and mark terminal ones done."""
        last = {}
        for r in read_results(out_dir):
            last[r.get("job_id")] = r
        for pj in pool_jobs:
            r = last.get(pj.job_id)
            if r is not None and record_is_terminal(r):
                pj.done = True
        return last

    def spawn(group: _Group) -> None:
        nonlocal wseq
        wid = f"w{wseq}"
        wseq += 1
        todo = group.pending_jobs()
        # Requeue rotation: a prior attempt's partial event log moves
        # aside so the re-run's log reads exactly like a solo run (and
        # the health tail starts from byte 0 of fresh content).
        for pj in todo:
            pj.attempts += 1
            path = _events_path(out_dir, pj.job_id)
            if pj.attempts > 1 and os.path.exists(path):
                try:
                    os.replace(path, f"{path}.retry{pj.attempts - 1}")
                except OSError:
                    pass
        manifest = os.path.join(pool_dir, f"{wid}.jobs.jsonl")
        with open(manifest, "w", encoding="utf-8") as f:
            for pj in todo:
                f.write(json.dumps(pj.job.to_dict(), sort_keys=True)
                        + "\n")
        argv = [sys.executable, "-m", "raft_tla_tpu.serve", manifest,
                "--out", out_dir, "--chunk", str(group.chunk),
                "--depth", str(depth), "--quiet", "--drain-on-sigint"]
        if max_states is not None:
            argv += ["--max-states", str(max_states)]
        if cpu:
            argv += ["--cpu"]
        out_path = os.path.join(pool_dir, f"{wid}.out")
        out_f = open(out_path, "wb")
        # Workers inherit the environment EXCEPT the metrics gate: the
        # pool's supervising process owns the one endpoint over out_dir
        # (it already sees every tenant log the workers write), and a
        # child re-binding the same port would die at startup.
        child_env = dict(os.environ)
        child_env.pop(ENV_METRICS, None)
        try:
            proc = subprocess.Popen(argv, stdout=out_f,
                                    stderr=subprocess.STDOUT,
                                    stdin=subprocess.DEVNULL,
                                    env=child_env)
        finally:
            out_f.close()
        health = WorkerHealth(
            policy, [_events_path(out_dir, pj.job_id) for pj in todo],
            clock=clock)
        health.start(clock())
        w = _Worker(wid, group, proc, out_path, health)
        active.append(w)
        append_event(pool_events, "worker_spawn", worker=wid,
                     pid=proc.pid, jobs=[pj.job_id for pj in todo],
                     chunk=group.chunk, respawn=group.retry,
                     attempt=max(pj.attempts for pj in todo))
        say(f"pool: spawned {wid} (pid {proc.pid}) for "
            f"{len(todo)} job(s)"
            + (f" [retry, chunk {group.chunk}]" if group.retry else ""))
        if spawn_hook is not None:
            spawn_hook(w)

    def give_up(reason: str) -> None:
        """Respawn budget exhausted: every unfinished job gets an
        attributed (non-terminal — a restart may retry) record."""
        recs = []
        for pj in pool_jobs:
            if pj.done:
                continue
            pj.done = True
            _attribute_stop(_events_path(out_dir, pj.job_id),
                            f"pool gave up: {reason}", "stopped")
            recs.append(dict(pj.rec, status="stopped",
                             error=f"pool gave up: {reason}"))
        pending.clear()
        if recs:
            _append_records(out_dir, recs)
            say(f"pool: gave up on {len(recs)} job(s) ({reason})")

    def quarantine(pj: _PoolJob, w: _Worker, detail: str) -> None:
        pj.done = True
        append_event(pool_events, "quarantine", job_id=pj.job_id,
                     reason="poison-job", deaths=pj.deaths, worker=w.wid,
                     detail=detail)
        path = _events_path(out_dir, pj.job_id)
        _attribute_stop(
            path,
            f"quarantined after {pj.deaths} worker death(s): {detail}",
            "quarantined")
        rec = dict(pj.rec, status="quarantined", reason="poison-job",
                   deaths=pj.deaths,
                   error=f"poison-job: blamed for {pj.deaths} worker "
                         f"death(s); last: {detail}")
        _append_records(out_dir, [rec])
        say(f"[{pj.job_id}] QUARANTINED after {pj.deaths} worker "
            f"death(s) ({detail})")

    def requeue(suspects: list, w: _Worker, kind: str,
                detail: str) -> None:
        """Blame-and-bisect: each suspect takes a death; a lone suspect
        at K deaths is quarantined; survivors one short of K go solo
        (so their K-th death, if it comes, is unambiguous); the rest
        bisect.  OOM and session-wall arrive here via their own
        no-blame paths."""
        nonlocal respawns
        K = policy.max_job_deaths
        blame = kind not in ("session-wall", "oom", "drain")
        if blame:
            for pj in suspects:
                pj.deaths += 1
        survivors = []
        for pj in suspects:
            if blame and len(suspects) == 1 and pj.deaths >= K:
                quarantine(pj, w, detail)
            else:
                survivors.append(pj)
        if not survivors:
            return
        solos = [pj for pj in survivors if blame and pj.deaths >= K - 1]
        rest = [pj for pj in survivors if pj not in solos]
        new_lists = [[pj] for pj in solos]
        if len(rest) > 1 and blame:
            mid = (len(rest) + 1) // 2
            new_lists += [rest[:mid], rest[mid:]]
        elif rest:
            new_lists += [rest]
        new_chunk = w.group.chunk
        if kind == "oom":
            new_chunk = max(policy.min_chunk, new_chunk // 2)
        if respawns + len(new_lists) > policy.max_respawns:
            give_up(f"respawn budget ({policy.max_respawns}) "
                    f"exhausted; last death: {kind}: {detail}")
            return
        respawns += len(new_lists)
        delay = backoff.next()
        nb = clock() + delay
        for lst in new_lists:
            pending.append(_Group(lst, new_chunk, retry=True,
                                  not_before=nb))
            for pj in lst:
                append_event(pool_events, "job_retry", job_id=pj.job_id,
                             attempt=pj.attempts, worker=w.wid,
                             backoff_s=round(delay, 3), reason=kind)
        say(f"pool: requeued {sum(len(l) for l in new_lists)} job(s) "
            f"from {w.wid} in {len(new_lists)} group(s) "
            f"(death: {kind}; backoff {delay:.2f}s"
            + (f"; chunk -> {new_chunk}" if kind == "oom" else "") + ")")

    def reap(w: _Worker, rc: int) -> None:
        active.remove(w)
        if tracer.enabled:
            now_mono = time.monotonic()
            tracer.emit_span("worker", w.t0_mono, now_mono - w.t0_mono,
                             thread="workers", worker=w.wid,
                             pid=w.proc.pid, exit_code=rc)
            if w.signal_mono is not None:
                # SIGINT->exit: how much of the grace window the drain
                # actually used (nests inside the worker lifetime).
                tracer.emit_span("drain", w.signal_mono,
                                 now_mono - w.signal_mono,
                                 thread="workers", worker=w.wid)
        last = refresh_done()
        unfinished = w.group.pending_jobs()
        if w.draining:
            kind, detail = "drain", "pool drain (stop requested)"
        elif w.preempt is not None:
            kind, detail = w.preempt
        elif rc in (0, 1):
            # Clean exit: a job whose record is non-terminal "stopped"
            # was attributed by the worker itself (a runtime lane
            # failure — exactly what in-process run_service reports
            # without retrying), so it is settled, not requeued; only
            # jobs with NO record at all count as lost with the worker.
            for pj in unfinished:
                if pj.job_id in last:
                    pj.done = True
            unfinished = [pj for pj in unfinished
                          if pj.job_id not in last]
            if not unfinished:
                backoff.reset()
                say(f"pool: {w.wid} finished cleanly "
                    f"({len(w.group.jobs)} job(s) settled)")
                return
            kind, detail = supervise.classify_death(rc, w.out_tail())
        else:
            kind, detail = supervise.classify_death(rc, w.out_tail())
        append_event(pool_events, "worker_lost", worker=w.wid,
                     kind=kind, pid=w.proc.pid, exit_code=rc,
                     jobs=[pj.job_id for pj in unfinished],
                     detail=detail)
        say(f"pool: lost {w.wid} ({kind}: {detail}; exit {rc}; "
            f"{len(unfinished)} job(s) unfinished)")
        if kind == "drain" or not unfinished:
            return
        if kind == "oom" and w.group.chunk <= policy.min_chunk:
            # Degradation floor reached: this is not memory pressure we
            # can shrink away — treat as a poison death.
            kind = "crashed"
            detail += f" (chunk already at floor {policy.min_chunk})"
        requeue(unfinished, w, kind, detail)

    while active or pending:
        now = clock()
        if stop is not None and stop() and not draining:
            draining = True
            # Undispatched jobs never reached a worker — attribute now;
            # active workers drain losslessly via their own SIGINT path.
            recs = []
            for g in pending:
                for pj in g.pending_jobs():
                    pj.done = True
                    _attribute_stop(
                        _events_path(out_dir, pj.job_id),
                        "stop requested (drain; job never reached a "
                        "worker)", "stopped")
                    recs.append(dict(
                        pj.rec, status="stopped",
                        error="stop requested (drain; job never "
                              "reached a worker)"))
            pending.clear()
            if recs:
                _append_records(out_dir, recs)
            for w in active:
                w.draining = True
                w.signaled_at = now
                w.signal_mono = time.monotonic()
                try:
                    w.proc.send_signal(signal.SIGINT)
                except OSError:
                    pass
            say(f"pool: draining — {len(active)} active worker(s) "
                f"signaled, {len(recs)} undispatched job(s) attributed")
        if not draining:
            ready = [g for g in pending if g.not_before <= now]
            while ready and len(active) < workers:
                g = ready.pop(0)
                pending.remove(g)
                if not g.pending_jobs():
                    continue
                spawn(g)
        for w in list(active):
            w.health.poll()
            rc = w.proc.poll()
            if rc is None:
                if w.signaled_at is None:
                    bad = w.health.verdict()
                    if bad is not None:
                        reason, detail = bad
                        w.preempt = bad
                        w.signaled_at = now
                        w.signal_mono = time.monotonic()
                        append_event(pool_events, "preempt",
                                     reason=reason, detail=detail,
                                     pid=w.proc.pid)
                        say(f"pool: preempting {w.wid} "
                            f"({reason}: {detail})")
                        try:
                            w.proc.send_signal(signal.SIGINT)
                        except OSError:
                            pass
                elif not w.killed and now - w.signaled_at > policy.grace_s:
                    w.killed = True
                    try:
                        w.proc.kill()
                    except OSError:
                        pass
                continue
            reap(w, rc)
        if active or pending:
            sleep(policy.poll_s)

    # Final sweep: anything still unfinished (shouldn't happen — every
    # path above settles or requeues) gets an attributed record so the
    # pool never returns silence for an accepted job.
    last = refresh_done()
    tail_recs = []
    for pj in pool_jobs:
        if pj.job_id not in last and not pj.done:
            _attribute_stop(_events_path(out_dir, pj.job_id),
                            "pool exit with no worker verdict", "stopped")
            tail_recs.append(dict(pj.rec, status="stopped",
                                  error="pool exit with no worker "
                                        "verdict"))
    if tail_recs:
        _append_records(out_dir, tail_recs)
        last = refresh_done()

    out = []
    for job in jobs:
        rec = last.get(job.job_id)
        if rec is None:                  # parent-side reject (appended
            for r in records:            # before any worker ran)
                if r["job_id"] == job.job_id:
                    rec = r
                    break
        if rec is not None:
            out.append(rec)
    n_by: dict = {}
    for rec in out:
        n_by[rec["status"]] = n_by.get(rec["status"], 0) + 1
    say("pool: " + ", ".join(f"{v} {k}"
                             for k, v in sorted(n_by.items()))
        + f" ({respawns} respawn(s))")
    return out
