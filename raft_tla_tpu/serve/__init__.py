"""Multi-tenant check service — checking as serving (ROADMAP §5).

One process, many bounded check jobs.  The paper's capability is one
exhaustive check of one cfg per process; every ingredient of a *service*
already shipped job-shaped — the byte-compatible cfg parser (L5), the
speclint per-cfg admission verdicts (analysis/), and the versioned obs/
event stream as a per-job progress API — and this package is the
subsystem that accepts N jobs and amortizes device dispatch across them:

- :mod:`raft_tla_tpu.serve.jobs` — the :class:`CheckJob` spec (cfg text +
  bounds + invariants + engine options), the shared cfg→CheckConfig
  builder ``resolve_check_config`` (one code path for check.py and the
  server), and speclint-gated :func:`admit` (width-unsafe or vacuous
  configs are rejected with the lint findings as the error payload,
  before any device time is spent).
- :mod:`raft_tla_tpu.serve.batch` — the lane-packed batch executor:
  admitted jobs are binned by step signature (packed state width +
  compiled step identity) and lane-tagged into shared fused-step
  dispatches, so one vmapped dispatch advances N independent BFS
  frontiers per chunk, with per-lane completion, per-lane invariant
  verdicts and lane backfill as jobs finish (continuous batching).
  Correctness anchor: each lane's reachable-state/orbit counts are
  byte-identical to a solo ``engine.Engine`` run of the same cfg.
- :mod:`raft_tla_tpu.serve.service` — the front: ``raft-tla-serve`` /
  ``python -m raft_tla_tpu.serve`` consumes a JSONL job manifest or a
  job-queue directory, emits one obs/ SCHEMA_VERSION=1 event log per
  job (``raft-tla-monitor`` works unchanged per tenant), and isolates
  tenants by per-job config digests in every result record.
- :mod:`raft_tla_tpu.serve.pool` + :mod:`raft_tla_tpu.serve.supervise`
  — fault-isolated serving (``--workers N``): admitted jobs dispatch
  to supervised worker child processes (health via the campaign
  supervisor's ``_LogTail``/``HealthMonitor``), with death
  classification, poison-job bisection + quarantine, per-job wall
  budgets, OOM chunk-halving degradation and bounded jittered
  respawns.  :mod:`raft_tla_tpu.serve.chaos` is the fault-injection
  harness asserting pool artifacts stay canonically identical to an
  unsupervised solo pass.
"""

from raft_tla_tpu.serve.jobs import (Admission, CheckJob, JobOptions,
                                     admit, resolve_check_config)

__all__ = ["Admission", "CheckJob", "JobOptions", "admit",
           "resolve_check_config"]
