"""Async dispatch scheduler — keep the device busy through every host
phase of the serving loop.

PR 6's :class:`~raft_tla_tpu.serve.batch.BatchExecutor` dispatched bins
round-robin but *synchronously*: pack bin A's chunk, run its fused step,
then immediately fetch the outputs and walk every lane's host phases
(d2h fetch -> dedup -> lane scan -> backfill) while the device sat idle
— and every new step signature paid its jit compile on that same
critical path.  This module lifts the ddd engines' two-deep segment
pipeline (``ddd_engine.py`` harvest loop, ``parallel/ddd_shard_engine``)
into the serving layer:

- **Pipelined dispatch** — up to ``depth`` fused dispatches are kept in
  flight at once (JAX async dispatch: enqueue returns immediately; the
  d2h fetch is the only blocking point).  While bin A's harvest runs on
  the host, bin B's step — or bin A's *next* chunk of the same frontier
  level — is already executing.  Tickets are harvested strictly FIFO, so
  per-lane slice order equals dispatch order equals the order a solo
  ``engine.Engine`` would process the same frontier: per-lane chunk
  semantics stay Engine-verbatim and completing lanes remain
  byte-identical to their solo runs (the PR 6 invariant).
- **Double-buffered staging** (the ddd bufset discipline): each bin owns
  ``depth`` host staging buffers; a dispatch claims one, the harvest
  frees it, so an in-flight dispatch's input is never overwritten — and
  the packer writes rows in place instead of reallocating per dispatch.
- **Speculative same-bin dispatch**: within a BFS level, chunk k+1 of a
  lane's frontier does not depend on chunk k's harvest (new states only
  extend the *next* level), so it may be dispatched before k's results
  land.  If k stops the lane (violation, deadlock, failure), k+1's
  slice for that lane is dropped whole at harvest — exactly the ddd
  rule that post-stop segments are dropped — which leaves every counter
  identical to a run that never speculated.
- **Compile off the critical path**: each bin's fused step is
  lowered+compiled AOT on a background thread, so already-compiled bins
  keep the device fed while a new signature compiles.  The scheduler
  only blocks on a compile when nothing else has work (the device would
  idle anyway).  ``enable_compile_cache`` wires JAX's persistent
  compilation cache (``--compile-cache DIR`` / ``RAFT_TLA_COMPILE_CACHE``)
  so daemon restarts are warm.
- **Fair-share packing** (deficit round robin): when a bin's live lanes
  oversubscribe the chunk, each dispatch grants every pending lane a
  quantum of ``max(1, B // n_live)`` rows plus any deficit carried from
  dispatches where the chunk ran out; the ring head advances past the
  lanes served, so consecutive dispatches sweep the ring.  Starvation
  bound (asserted in tests): a live lane with pending rows rides at
  least once in any window of ``ceil(n_live / lanes-served-per-dispatch)``
  consecutive dispatches — at most ``n_live``.  Leftover chunk space
  backfills greedily in ring order (work-conserving), so the chunk stays
  full whenever any lane has work.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

import numpy as np

from raft_tla_tpu.obs.trace import NULL_TRACER
from raft_tla_tpu.ops import fingerprint as fpr

ENV_COMPILE_CACHE = "RAFT_TLA_COMPILE_CACHE"


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``path`` (or the
    ``RAFT_TLA_COMPILE_CACHE`` env var), so a daemon restart re-serving
    the same step signatures skips recompilation.  Returns the resolved
    directory, or None when neither source names one.  Best-effort: the
    knobs exist on the baked-in jax, but each update is guarded so an
    older/newer jax degrades to cold compiles instead of failing."""
    path = path or os.environ.get(ENV_COMPILE_CACHE) or None
    if not path:
        return None
    import jax
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    for knob, val in (("jax_compilation_cache_dir", path),
                      ("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):
            pass
    return path


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()


class _Ticket:
    """One in-flight fused dispatch: the device outputs plus the host
    metadata needed to demux them per lane at harvest time."""

    __slots__ = ("bn", "slices", "out", "buf_idx", "t_disp")

    def __init__(self, bn, slices, out, buf_idx, t_disp=0.0):
        self.bn = bn
        self.slices = slices            # [(lane, row0, nrows, gidx)]
        self.out = out                  # device dict (async results)
        self.buf_idx = buf_idx
        self.t_disp = t_disp            # monotonic issue time (tracing)


class _BinState:
    """Scheduler-side state for one bin: staging buffers, the DRR ring,
    and the background compile."""

    __slots__ = ("bn", "bufs", "free", "rr", "deficit", "compiled",
                 "thread", "compile_wall_s", "compiled_async")

    def __init__(self, bn, depth: int, chunk: int):
        self.bn = bn
        self.bufs = [np.zeros((chunk, bn.lay.width), np.int32)
                     for _ in range(depth)]
        self.free = list(range(depth))
        self.rr = 0
        self.deficit: dict[str, int] = {}
        self.compiled = None
        self.thread: threading.Thread | None = None
        self.compile_wall_s: float | None = None
        self.compiled_async = False


class DispatchScheduler:
    """Route every bin dispatch through one pipelined issue/harvest loop.

    ``depth`` is the global in-flight dispatch cap (2 = the ddd two-deep
    precedent; 1 = fully synchronous, byte-for-byte the PR 6 executor's
    issue order — the A/B baseline).  ``compile_async=False`` also moves
    compiles back onto the dispatch path (lazy jit), completing the
    sequential baseline.  ``stop`` is an optional zero-arg callable; when
    it turns truthy the scheduler stops submitting, harvests what is in
    flight (their rows were already claimed from the frontiers, so the
    accounting stays exact) and returns — the daemon's drain hook.
    """

    def __init__(self, chunk: int, max_states: int | None = None,
                 depth: int = 2, compile_async: bool = True,
                 stop=None, tracer=None):
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.chunk = chunk
        self.max_states = max_states
        self.depth = depth
        self.compile_async = compile_async
        self.stop = stop
        # v8 tracing (``--trace``): dispatch/harvest/compile spans plus
        # per-ticket issue->harvest lifetimes on a synthetic "tickets"
        # track (they overlap the main thread's nested spans).  The
        # NULL tracer's span() returns one shared no-op handle, so the
        # untraced path stays allocation-free.
        self.tracer = tracer or NULL_TRACER
        self.inflight: deque[_Ticket] = deque()
        self.stats = {"dispatches": 0, "peak_inflight": 0,
                      "async_compiles": 0, "compile_wall_s": {}}

    # -- compile ------------------------------------------------------------

    def _compile(self, st: _BinState) -> None:
        """Lower+compile a bin's fused step AOT (worker thread).  On any
        lowering/AOT failure, fall back to lazy jit — the compile lands
        back on the dispatch path but correctness is unchanged."""
        import jax
        import jax.numpy as jnp
        with self.tracer.span("compile",
                              bin=getattr(st.bn, "tag", "bin")):
            t0 = time.monotonic()
            fn = jax.jit(st.bn.step_fn)
            try:
                spec = jax.ShapeDtypeStruct((self.chunk, st.bn.lay.width),
                                            jnp.int32)
                st.compiled = fn.lower(spec).compile()
            except Exception:
                st.compiled = fn
            st.compile_wall_s = time.monotonic() - t0

    def _start_compile(self, st: _BinState) -> None:
        if not self.compile_async:
            # sequential baseline: lazy jit, compiled at first dispatch
            import jax
            st.compiled = jax.jit(st.bn.step_fn)
            return
        st.compiled_async = True
        st.thread = threading.Thread(
            target=self._compile, args=(st,),
            name=f"serve-compile-{getattr(st.bn, 'tag', 'bin')}",
            daemon=True)
        st.thread.start()

    def _ready(self, st: _BinState) -> bool:
        if st.compiled is not None:
            return True
        if st.thread is not None and not st.thread.is_alive():
            st.thread.join()
            return st.compiled is not None
        return False

    # -- fair-share packing (deficit round robin) ---------------------------

    def _plan_takes(self, st: _BinState, live: list) -> list:
        """Decide how many rows each live lane rides this dispatch.
        Returns ``[(lane, take)]`` in ring order (takes > 0 only)."""
        B = self.chunk
        n = len(live)
        quantum = max(1, B // n)
        start = st.rr % n
        order = live[start:] + live[:start]
        budget = B
        takes: dict[str, int] = {}
        cut = n                          # ring index where the chunk ran out
        for i, lane in enumerate(order):
            if budget == 0:
                cut = i
                break
            d = min(st.deficit.get(lane.job_id, 0) + quantum, B)
            t = min(d, lane.pending_rows(), budget)
            if t > 0:
                takes[lane.job_id] = t
                budget -= t
            # deficit carries only while the lane still has unserved work
            st.deficit[lane.job_id] = \
                d - t if lane.pending_rows() - t > 0 else 0
        # ring head past the lanes served: consecutive dispatches sweep
        # the ring (the starvation bound); on a full sweep rotate by one
        # so pass-2 leftover priority also rotates
        st.rr = (start + (cut if cut < n else 1)) % n
        if budget:
            # work-conserving backfill: leftover space goes to deeper
            # frontiers in ring order, no deficit charge (it's idle space)
            for lane in order:
                if budget == 0:
                    break
                extra = min(lane.pending_rows() - takes.get(lane.job_id, 0),
                            budget)
                if extra > 0:
                    takes[lane.job_id] = takes.get(lane.job_id, 0) + extra
                    budget -= extra
        return [(lane, takes[lane.job_id]) for lane in order
                if takes.get(lane.job_id, 0) > 0]

    # -- issue --------------------------------------------------------------

    def _try_submit(self, st: _BinState) -> bool:
        """Pack and dispatch one chunk from this bin.  False when the bin
        has nothing packable right now (no live pending lanes, step not
        compiled yet, or no free staging buffer)."""
        import jax.numpy as jnp
        bn = st.bn
        if not st.free or not self._ready(st):
            return False
        live = [ln for ln in bn.live_lanes() if ln.pending_rows() > 0]
        if not live:
            return False
        plan = self._plan_takes(st, live)
        if not plan:
            return False
        tr = self.tracer
        with tr.span("dispatch", bin=getattr(bn, "tag", "bin")) as sp:
            buf_idx = st.free.pop(0)
            buf = st.bufs[buf_idx]
            B = self.chunk
            slices, pos = [], 0
            for lane, take in plan:
                gidx, vecs = lane.take(take)
                lane.inflight_slices += 1
                buf[pos:pos + take] = vecs
                slices.append((lane, pos, take, gidx))
                pos += take
            if pos < B:                  # pad to the static chunk shape
                buf[pos:B] = buf[0]
            out = st.compiled(jnp.asarray(buf))  # async: enqueue, no wait
            sp.set(rows=pos, lanes=len(slices))
        t_disp = time.monotonic() if tr.enabled else 0.0
        self.inflight.append(_Ticket(bn, slices, out, buf_idx, t_disp))
        self.stats["dispatches"] += 1
        self.stats["peak_inflight"] = max(self.stats["peak_inflight"],
                                          len(self.inflight))
        return True

    # -- harvest ------------------------------------------------------------

    def _harvest_one(self, states: dict, outcomes: dict) -> None:
        """Pop the oldest ticket, block on its d2h fetch, and run every
        host phase (dedup, lane scan, gather, backfill) — verbatim the
        PR 6 ``_dispatch`` tail, minus the lanes stopped since issue
        (their speculative slices drop whole)."""
        tk = self.inflight.popleft()
        tr = self.tracer
        tag = getattr(tk.bn, "tag", "bin")
        with tr.span("harvest", bin=tag):
            self._harvest_ticket(tk, states, outcomes)
        if tr.enabled:
            # The ticket's issue->harvest lifetime overlaps the main
            # thread's nested spans, so it rides a synthetic track.
            tr.emit_span("ticket", tk.t_disp,
                         time.monotonic() - tk.t_disp,
                         thread="tickets", bin=tag)

    def _harvest_ticket(self, tk: _Ticket, states: dict,
                        outcomes: dict) -> None:
        from raft_tla_tpu.serve.batch import _LaneFailure
        import jax.numpy as jnp
        bn, out = tk.bn, tk.out
        B, W, A = self.chunk, bn.lay.width, bn.A

        valid = np.asarray(out["valid"])
        ovf = np.asarray(out["overflow"])
        keys = fpr.to_u64(np.asarray(out["fp_hi"]),
                          np.asarray(out["fp_lo"]))
        inv_ok = np.asarray(out["inv_ok"])
        con_ok = np.asarray(out["con_ok"])

        # Phase 1 per lane slice; collect the chunk-global flat indices
        # of every accepted new state for one shared device gather.
        sel_flat: list[int] = []
        committing = []
        for lane, r0, nb, gidx in tk.slices:
            lane.inflight_slices -= 1
            if not lane.active:          # stopped since issue: drop whole
                continue
            sl = slice(r0, r0 + nb)
            try:
                new_flat = lane.scan_slice(valid[sl], ovf[sl], keys[sl],
                                           inv_ok[sl], con_ok[sl], gidx)
            except _LaneFailure as e:
                lane.fail(str(e))
                outcomes[lane.job_id] = lane.outcome
                continue
            committing.append((lane, len(new_flat)))
            sel_flat.extend(r0 * A + fi for fi in new_flat)

        # One gather for the whole dispatch (padded to a pow2 bucket so
        # the eager gather compiles O(log) distinct shapes), then split
        # back per lane in chunk order.
        n_new = len(sel_flat)
        if n_new:
            cap = _next_pow2(n_new)
            sel = np.asarray(sel_flat + [0] * (cap - n_new), dtype=np.int64)
            rows_all = np.asarray(
                out["svecs"].reshape(B * A, W)[jnp.asarray(sel)])[:n_new]
        else:
            rows_all = np.empty((0, W), dtype=np.int32)
        off = 0
        inflight_now = len(self.inflight)
        for lane, n_lane in committing:
            lane.commit_slice(rows_all[off:off + n_lane])
            off += n_lane
            try:
                lane.advance(self.max_states, inflight=inflight_now)
            except _LaneFailure as e:
                lane.fail(str(e))
            if not lane.active:
                outcomes[lane.job_id] = lane.outcome
        states[bn.key].free.append(tk.buf_idx)

    # -- main loop ----------------------------------------------------------

    def _stopping(self) -> bool:
        return bool(self.stop and self.stop())

    def run(self, bins: dict, outcomes: dict) -> dict:
        """Drive every bin to quiescence (or to the stop signal).
        Returns the per-bin compile stats (also kept on ``self.stats``)."""
        states = {key: _BinState(bn, self.depth, self.chunk)
                  for key, bn in bins.items()}
        # Kick off every compile up-front: the first signatures to finish
        # start dispatching while the rest still compile in background.
        for st in states.values():
            if st.bn.live_lanes():
                self._start_compile(st)
        order = list(states.values())
        rr = 0
        while True:
            stopping = self._stopping()
            if not stopping:
                # fill the pipeline, round-robin across bins
                while len(self.inflight) < self.depth:
                    submitted = False
                    for k in range(len(order)):
                        st = order[(rr + k) % len(order)]
                        if self._try_submit(st):
                            rr = (rr + k + 1) % len(order)
                            submitted = True
                            break
                    if not submitted:
                        break
            if self.inflight:
                self._harvest_one(states, outcomes)
                continue
            if stopping:
                break
            # Nothing in flight and nothing packable: done, unless a bin
            # with live work is still compiling — then wait for it (the
            # device would idle regardless; this is the only block).
            waiting = [st for st in order
                       if st.thread is not None and st.thread.is_alive()
                       and any(ln.pending_rows() > 0
                               for ln in st.bn.live_lanes())]
            if not waiting:
                break
            waiting[0].thread.join()
        for st in order:
            if st.compile_wall_s is not None:
                tag = getattr(st.bn, "tag", str(st.bn.key))
                self.stats["compile_wall_s"][tag] = \
                    round(st.compile_wall_s, 3)
                if st.compiled_async:
                    self.stats["async_compiles"] += 1
        return self.stats
