"""The service front — ``raft-tla-serve`` / ``python -m raft_tla_tpu.serve``.

One-pass multi-tenant driver: read a job source (JSONL manifest or a
queue directory of per-job JSON files), admit every job through the
speclint gate (``jobs.admit``), run all admitted jobs through the
lane-packed :class:`~raft_tla_tpu.serve.batch.BatchExecutor`, and leave
behind per-tenant artifacts:

- ``OUT/<job_id>.events`` — one obs/ versioned event log per job,
  so ``raft-tla-monitor OUT/<job_id>.events`` renders any tenant's run
  unchanged.  Rejected jobs get a three-event log (``run_start``,
  ``stop_requested`` with the admission reason, ``run_end`` outcome
  ``rejected``) so end-state attribution is uniform: a tenant's log
  always says completed / rejected-at-admission / stopped.
- ``OUT/results.jsonl`` — one record per job with the job's content
  digest (:meth:`CheckJob.digest` — cfg text + options), verdict, counts
  and findings.  The digest is the tenant-isolation tag: two jobs'
  outputs can never be conflated, and a client can verify the result it
  reads answers the exact model it submitted.

Exit code: 0 when every admitted job reached a verdict (including
violation/deadlock verdicts — finding a counterexample is the service
working); 1 when any lane was stopped by a runtime failure or the job
source itself was unreadable.  Admission rejects do not fail the
service — they are per-tenant client errors, reported in the results.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time


_JOB_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


def load_jobs(source: str, skipped: list | None = None,
              only: list | None = None) -> list:
    """Read :class:`CheckJob` entries from a JSONL manifest file or a
    queue directory of ``*.json`` job files (sorted name order — the
    queue convention: producers write ``NNN-name.json``).  ``only``
    restricts a queue-dir scan to the named files (the daemon's
    incremental intake; an empty restricted scan is then not an error).

    Queue-dir intake is race-tolerant: a producer writing a job file the
    moment the service scans the directory must not poison the whole
    pass, so a file that fails to read or parse gets one short-delay
    retry and is then SKIPPED (recorded as ``(name, error)`` in the
    optional ``skipped`` list) while the rest of the queue proceeds.
    Manifest files stay strict — a manifest is one artifact written by
    one producer, so a bad line is a bad manifest.

    Job ids must be path-safe (``[A-Za-z0-9._-]``, no leading dot) since
    they name the per-tenant event logs; duplicates are a hard error —
    two tenants sharing a log would be the conflation the digests exist
    to prevent.
    """
    from raft_tla_tpu.serve.jobs import CheckJob

    entries: list[tuple[str | None, dict]] = []
    if os.path.isdir(source):
        names = sorted(n for n in os.listdir(source) if n.endswith(".json"))
        if only is not None:
            names = [n for n in names if n in set(only)]
            if not names:
                return []
        elif not names:
            raise ValueError(f"queue directory {source!r} has no *.json jobs")
        for n in names:
            path = os.path.join(source, n)
            d = None
            for attempt in (0, 1):
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        d = json.load(f)
                    break
                except (OSError, ValueError) as e:
                    if attempt:             # second failure: skip, not fail
                        if skipped is not None:
                            skipped.append((n, str(e)))
                    else:
                        time.sleep(0.05)    # writer may be mid-write
            if d is not None:
                entries.append((n[:-len(".json")], d))
        if not entries:
            raise ValueError(
                f"queue directory {source!r}: all {len(names)} job "
                "file(s) unreadable")
    else:
        with open(source, "r", encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    d = json.loads(line)
                except ValueError as e:
                    raise ValueError(
                        f"{source}:{lineno}: not JSON: {e}") from e
                entries.append((None, d))

    # Relative cfg paths resolve against the job source's own directory —
    # a manifest is self-contained wherever the service runs from.
    base = source if os.path.isdir(source) else os.path.dirname(source)
    jobs, seen = [], set()
    for default_id, d in entries:
        if d.get("cfg") and not os.path.isabs(d["cfg"]):
            d = dict(d, cfg=os.path.join(base, d["cfg"]))
        job = CheckJob.from_dict(d, job_id=default_id)
        if not _JOB_ID_RE.match(job.job_id):
            raise ValueError(
                f"job id {job.job_id!r} is not path-safe "
                "([A-Za-z0-9._-], no leading punctuation, <= 64 chars)")
        if job.job_id in seen:
            raise ValueError(f"duplicate job id {job.job_id!r}")
        seen.add(job.job_id)
        jobs.append(job)
    return jobs


def _events_path(out_dir: str, job_id: str) -> str:
    return os.path.join(out_dir, f"{job_id}.events")


def _reject_events(path: str, job, reason: str) -> None:
    """The rejected-tenant event log: same schema, same monitor, explicit
    attribution — a log is never silent about why a run has no states."""
    from raft_tla_tpu.obs import append_event

    append_event(path, "run_start", engine="serve", universe={}, spec="",
                 invariants=[], resumed=False, pid=os.getpid())
    append_event(path, "stop_requested",
                 reason=f"rejected-at-admission: {reason}",
                 source="admission", pid=os.getpid())
    # One zero segment so the monitor's heartbeat (which needs a segment
    # timeline) renders the rejection attribution instead of "no data".
    append_event(path, "segment", wall_s=0.0, n_states=0, level=0,
                 n_transitions=0, dedup_hit_rate=0.0, states_per_sec=0.0,
                 inc_states_per_sec=0.0, since_resume=True)
    append_event(path, "run_end", n_states=0, n_transitions=0,
                 complete=False, outcome="rejected")


def run_service(jobs, out_dir: str, chunk: int = 1024,
                max_states: int | None = None, quiet: bool = False,
                depth: int = 2, compile_async: bool = True,
                stop=None) -> list:
    """Admit + execute + record: returns the results.jsonl records.

    Split from the CLI so tests (and later fronts — a socket server, an
    elastic-fleet supervisor) drive the same path with in-memory jobs.
    ``depth``/``compile_async`` configure the async dispatch scheduler
    (serve/sched.py; depth 1 + sync compile = the PR 6 synchronous
    executor); ``stop`` is a zero-arg callable the executor polls at
    dispatch boundaries — the daemon's SIGINT drain hook: when it turns
    truthy, in-flight dispatches are harvested and every unfinished lane
    gets an attributed "stop requested (drain)" record.
    """
    from raft_tla_tpu.obs import RunTelemetry
    from raft_tla_tpu.serve.batch import BatchExecutor
    from raft_tla_tpu.serve.jobs import admit

    os.makedirs(out_dir, exist_ok=True)

    def say(msg: str) -> None:
        if not quiet:
            print(msg, flush=True)

    # Admission first, for the whole intake — host-only, so a manifest
    # full of junk costs zero device time and the rejects are reported
    # before the first compile.
    records: list[dict] = []
    admitted = []
    for job in jobs:
        t_adm = time.monotonic()
        adm = admit(job)
        try:
            digest = job.digest()
        except (OSError, ValueError):
            digest = None               # unreadable cfg: admission rejects
        rec = {"job_id": job.job_id, "digest": digest,
               "admission_s": round(time.monotonic() - t_adm, 3),
               "events": _events_path(out_dir, job.job_id)}
        if not adm.admitted:
            rec.update(status="rejected", reason=adm.reason,
                       findings=adm.findings_text())
            _reject_events(rec["events"], job, adm.reason)
            say(f"[{job.job_id}] rejected at admission ({adm.reason}); "
                f"{len(adm.findings)} finding(s)")
            records.append(rec)
            continue
        if adm.properties:
            rec.update(status="rejected", reason="property-unsupported",
                       findings=[f"PROPERTY {list(adm.properties)}: "
                                 "liveness needs a dedicated exhaustive "
                                 "run (raft-tla-check --property); the "
                                 "batched service checks invariants only"])
            _reject_events(rec["events"], job, "property-unsupported")
            say(f"[{job.job_id}] rejected at admission "
                "(property-unsupported)")
            records.append(rec)
            continue
        admitted.append((job, adm, rec))
        records.append(rec)

    # One telemetry facade per tenant, each with its own explicit events
    # path (never the RAFT_TLA_EVENTS fallback — that one env var would
    # merge every lane into a single log).
    telemetry = {}
    for job, adm, rec in admitted:
        telemetry[job.job_id] = RunTelemetry(
            "serve", config=adm.config, events=rec["events"])

    outcomes = {}
    if admitted:
        say(f"serving {len(admitted)} admitted job(s) "
            f"({len(jobs) - len(admitted)} rejected) — chunk {chunk}, "
            f"pipeline depth {depth}")
        # Scheduler-level spans (dispatch/harvest/compile/ticket) are
        # cross-lane, so they get their own per-process log — pid-keyed
        # because pool workers share one out_dir.  The collector merges
        # it with the tenant logs of the same pid into one track set.
        from raft_tla_tpu.obs import EventLog
        from raft_tla_tpu.obs.trace import (SpanTracer, clock_anchor,
                                            host_context, trace_enabled)
        tracer = None
        sched_log = None
        if trace_enabled():
            sched_log = EventLog(os.path.join(
                out_dir, f"sched-{os.getpid()}.events"))
            sched_log.emit("run_start", engine="sched", universe={},
                           spec="", invariants=[], resumed=False,
                           pid=os.getpid(), anchor=clock_anchor(),
                           host=host_context())
            tracer = SpanTracer(sched_log.emit)
        ex = BatchExecutor(chunk=chunk, max_states=max_states,
                           depth=depth, compile_async=compile_async,
                           stop=stop, tracer=tracer)
        budgets = {job.job_id: job.options.wall_s
                   for job, adm, rec in admitted
                   if job.options.wall_s is not None}
        try:
            outcomes = ex.run([(job.job_id, adm.config)
                               for job, adm, rec in admitted],
                              telemetry=telemetry, budgets=budgets)
        finally:
            if sched_log is not None:
                sched_log.close()

    for job, adm, rec in admitted:
        oc = outcomes[job.job_id]
        rec["status"] = oc.status
        if oc.error:
            rec["error"] = oc.error
        if adm.findings:                 # admitted-with-warnings
            rec["findings"] = adm.findings_text()
        if oc.result is not None:
            r = oc.result
            rec.update(n_states=r.n_states, diameter=r.diameter,
                       n_transitions=r.n_transitions,
                       levels=list(r.levels),
                       complete=bool(r.complete),
                       wall_s=round(r.wall_s, 3),
                       states_per_sec=round(r.states_per_sec, 1),
                       # the run's final duplicate rate — same formula
                       # as the segment stream's dedup_hit_rate
                       # (obs ProgressTracker.record), so result records
                       # stop under-reporting it as absent/0.0
                       dedup_hit_rate=round(
                           1.0 - r.n_states / max(1, r.n_transitions),
                           4))
            if r.violation is not None:
                rec["violation"] = r.violation.invariant
        say(f"[{job.job_id}] {rec['status']}: "
            f"{rec.get('n_states', 0):,} states, "
            f"diameter {rec.get('diameter', 0)}, "
            f"{rec.get('wall_s', 0.0):.2f}s")

    _append_records(out_dir, records)
    return records


def _append_records(out_dir: str, records: list) -> None:
    """Crash-safe results append: every record is ONE whole-line write,
    flushed (and fsynced) before the next — a worker SIGKILLed between
    records can tear at most the final line, never interleave two
    records, and O_APPEND keeps concurrent pool workers' lines whole.
    The torn-tail case is the reader's to forgive (:func:`read_results`),
    exactly the queue-dir intake contract."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "results.jsonl"), "a",
              encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())


def read_results(out_dir: str) -> list:
    """Read ``OUT/results.jsonl`` tolerating a torn tail: a crash (or
    SIGKILLed pool worker) mid-append leaves at most one partial final
    line, which is dropped — same forgiveness the queue-dir intake
    extends to producers caught mid-write.  A non-JSON line anywhere
    else is skipped too (the stream is append-only; one bad line must
    not hide the records around it).  Missing file = no records."""
    path = os.path.join(out_dir, "results.jsonl")
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().split("\n")
    except OSError:
        return []
    records = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            d = json.loads(line)
        except ValueError:
            continue                     # torn/garbled line
        if isinstance(d, dict) and "job_id" in d:
            records.append(d)
    return records


# Statuses that settle a job for good: re-running the same digest can
# only reproduce them (BFS is deterministic), so a daemon restart or a
# pool requeue never re-runs these — the seed of the digest-keyed result
# cache (ROADMAP item 7).  A plain drained "stopped" is NOT terminal
# (the stop was the service's, not the job's); a budget/cap stop IS (the
# same budget would stop the re-run at the same place).
def record_is_terminal(rec: dict) -> bool:
    status = rec.get("status")
    if status in ("completed", "violation", "deadlock", "rejected",
                  "quarantined"):
        return True
    if status == "stopped":
        err = rec.get("error") or ""
        return err.startswith("budget-exceeded") \
            or err.startswith("state count exceeded")
    return False


def run_daemon(source: str, out_dir: str, chunk: int = 1024,
               max_states: int | None = None, quiet: bool = False,
               depth: int = 2, poll_s: float = 2.0,
               max_idle_polls: int | None = None, workers: int = 0,
               cpu: bool = False) -> int:
    """The long-running front: ``raft-tla-serve QUEUE_DIR --watch``.

    Continuous intake atop the one-pass queue-dir code path: every poll
    picks up job files not yet processed and runs them as one executor
    batch (so cross-bin interleaving spans the whole arrival burst).
    Each job file is parsed in isolation — a malformed file is retried
    across a few polls (a producer may be mid-write) and then recorded
    as a rejected result instead of poisoning the loop; a job id already
    served this daemon's lifetime is rejected as ``duplicate-id``
    *without* touching the original tenant's event log (conflation is
    the thing the digests exist to prevent).

    Restart dedup (the result cache's seed): at startup the daemon reads
    the existing ``results.jsonl`` (torn-tail tolerant) and any intake
    whose content digest already has a *terminal* record is skipped, not
    re-run — a restarted daemon never re-bills device time for work it
    already finished.  ``workers > 0`` routes every batch through the
    fault-isolated worker pool (:func:`raft_tla_tpu.serve.pool.run_pool`)
    instead of executing in-process.

    Stop contract (the campaign supervisor's, reused): the FIRST SIGINT
    stops intake and drains — the executor finishes in-flight dispatches
    and every unfinished lane gets an attributed "stop requested (drain)"
    results.jsonl record, so nothing the daemon accepted ever exits
    silently.  A SECOND SIGINT aborts raw.  ``max_idle_polls`` bounds
    the idle loop for smoke tests (None = run until signalled).
    """
    import signal
    import threading

    if not os.path.isdir(source):
        print(f"Error: --watch needs a queue directory, got {source!r}",
              file=sys.stderr)
        return 1

    def say(msg: str) -> None:
        if not quiet:
            print(msg, flush=True)

    stop = threading.Event()
    prev = signal.getsignal(signal.SIGINT)

    def handler(_signum, _frame):
        if stop.is_set():
            signal.signal(signal.SIGINT, prev)
            raise KeyboardInterrupt
        stop.set()
        print("SIGINT: draining — in-flight lanes get attributed "
              "records (SIGINT again aborts raw)", file=sys.stderr,
              flush=True)

    main_thread = threading.current_thread() is threading.main_thread()
    if main_thread:
        signal.signal(signal.SIGINT, handler)
    try:
        done: set[str] = set()          # file names fully handled
        attempts: dict[str, int] = {}   # unreadable-file retry counts
        served_ids: set[str] = set()
        # restart dedup: digest-keyed terminal records survive restarts
        prior = [r for r in read_results(out_dir)
                 if record_is_terminal(r)]
        done_digests = {r["digest"] for r in prior if r.get("digest")}
        if prior:
            say(f"restart: {len(done_digests)} terminal digest(s) in "
                f"{out_dir}/results.jsonl will not be re-run")
        idle = 0
        say(f"watching {source} (poll {poll_s:g}s) -> "
            f"{out_dir}/results.jsonl")
        while not stop.is_set():
            try:
                fresh = sorted(n for n in os.listdir(source)
                               if n.endswith(".json") and n not in done)
            except OSError as e:
                print(f"Error: queue directory unreadable: {e}",
                      file=sys.stderr)
                return 1
            batch, extra_records = [], []
            for name in fresh:
                if stop.is_set():
                    break               # drain: no new intake
                skipped: list = []
                try:
                    jobs = load_jobs(source, skipped=skipped, only=[name])
                except (OSError, ValueError) as e:
                    # structurally bad (unsafe id, ...): reject for good
                    done.add(name)
                    extra_records.append(
                        {"job_id": name[:-len(".json")],
                         "status": "rejected", "reason": "bad-job-file",
                         "error": str(e)})
                    continue
                if skipped:             # torn read: retry a few polls
                    attempts[name] = attempts.get(name, 0) + 1
                    if attempts[name] >= 3:
                        done.add(name)
                        extra_records.append(
                            {"job_id": name[:-len(".json")],
                             "status": "rejected",
                             "reason": "unreadable-job-file",
                             "error": skipped[0][1]})
                    continue
                done.add(name)
                for job in jobs:
                    if job.job_id in served_ids:
                        extra_records.append(
                            {"job_id": job.job_id, "status": "rejected",
                             "reason": "duplicate-id",
                             "error": "job id already served by this "
                                      "daemon; events log belongs to "
                                      "the first submission"})
                        continue
                    served_ids.add(job.job_id)
                    try:
                        dg = job.digest()
                    except (OSError, ValueError):
                        dg = None       # unreadable cfg: admission rejects
                    if dg is not None and dg in done_digests:
                        say(f"[{job.job_id}] cached: digest {dg} already "
                            "has a terminal record (not re-run)")
                        continue
                    batch.append(job)
            if extra_records:
                for rec in extra_records:
                    say(f"[{rec['job_id']}] rejected ({rec['reason']})")
                _append_records(out_dir, extra_records)
            if batch:
                idle = 0
                if workers:
                    from raft_tla_tpu.serve.pool import run_pool
                    recs = run_pool(batch, out_dir, workers=workers,
                                    chunk=chunk, max_states=max_states,
                                    quiet=quiet, depth=depth, cpu=cpu,
                                    stop=stop.is_set)
                else:
                    recs = run_service(batch, out_dir, chunk=chunk,
                                       max_states=max_states, quiet=quiet,
                                       depth=depth, stop=stop.is_set)
                done_digests |= {r["digest"] for r in recs
                                 if record_is_terminal(r)
                                 and r.get("digest")}
                continue                # re-scan immediately after a batch
            if stop.is_set():
                break
            idle += 1
            if max_idle_polls is not None and idle >= max_idle_polls:
                say(f"idle for {idle} poll(s) — exiting (--max-idle-polls)")
                break
            # sleep in small increments so SIGINT turns around fast
            deadline = time.monotonic() + poll_s
            while time.monotonic() < deadline and not stop.is_set():
                time.sleep(min(0.05, poll_s))
        say(f"daemon exit: {len(served_ids)} job(s) served"
            + (" (drained on SIGINT)" if stop.is_set() else ""))
        return 0
    finally:
        if main_thread:
            signal.signal(signal.SIGINT, prev)


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="raft-tla-serve",
        description="Multi-tenant bounded-check service: admit N jobs "
                    "through the speclint gate and pack them into shared "
                    "batched device dispatches (lane-packed continuous "
                    "batching), one event log per tenant.")
    p.add_argument("source",
                   help="job source: a JSONL manifest (one job object "
                        "per line) or a queue directory of *.json job "
                        "files; each job: {'id', 'cfg' | 'cfg_text', "
                        "+ JobOptions fields (spec, max_term, ...)}")
    p.add_argument("--out", default="serve-out", metavar="DIR",
                   help="output directory: <id>.events per job + "
                        "results.jsonl (default: serve-out)")
    p.add_argument("--chunk", type=int, default=1024,
                   help="shared dispatch width B — every bin compiles "
                        "one [B, W] fused step and all of its lanes "
                        "pack into it (default 1024)")
    p.add_argument("--max-states", type=int, default=None,
                   help="per-lane distinct-state cap; an exceeding lane "
                        "is stopped (attributed in its event log), the "
                        "other tenants keep running")
    p.add_argument("--depth", type=int, default=2,
                   help="dispatch pipeline depth: how many fused steps "
                        "may be in flight while earlier harvests run on "
                        "the host (1 = sequential; default 2)")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent JAX compilation-cache directory "
                        "(also via RAFT_TLA_COMPILE_CACHE); warm-starts "
                        "bin compiles across service restarts")
    p.add_argument("--watch", action="store_true",
                   help="daemon mode: SOURCE must be a queue directory; "
                        "keep polling it for new *.json job files and "
                        "serve each arrival burst as one interleaved "
                        "batch; first SIGINT drains losslessly, second "
                        "aborts")
    p.add_argument("--poll", type=float, default=2.0, metavar="SECS",
                   help="--watch poll interval (default 2.0)")
    p.add_argument("--max-idle-polls", type=int, default=None,
                   metavar="N",
                   help="--watch: exit 0 after N consecutive empty "
                        "polls (smoke-test bound; default: run until "
                        "SIGINT)")
    p.add_argument("--workers", type=int, default=0, metavar="N",
                   help="fault-isolated mode: dispatch admitted jobs to "
                        "up to N supervised worker child processes "
                        "(serve/pool.py) — a poison job, OOM or segfault "
                        "kills one worker, not the service; 0 (default) "
                        "executes in-process")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend")
    p.add_argument("--trace", action="store_true",
                   help="emit schema-v8 trace spans (RAFT_TLA_TRACE): "
                        "per-tenant engine phases into each tenant log "
                        "plus scheduler dispatch/harvest/compile/ticket "
                        "spans into OUT/sched-<pid>.events; merge with "
                        "raft-tla-trace")
    p.add_argument("--metrics-port", type=int, default=None, metavar="P",
                   help="expose a live OpenMetrics endpoint on "
                        "127.0.0.1:P (0 = ephemeral port; also via "
                        "RAFT_TLA_METRICS): per-tenant p50/p95/p99 "
                        "admission-to-result latency, queue depth, "
                        "per-bin inflight and pool-worker gauges, "
                        "snapshotted into OUT/metrics.events")
    p.add_argument("--quiet", action="store_true",
                   help="suppress per-job progress lines")
    p.add_argument("--drain-on-sigint", action="store_true",
                   help="one-pass mode: first SIGINT drains losslessly "
                        "(in-flight dispatches harvested, unfinished "
                        "lanes get attributed 'stopped' records) instead "
                        "of aborting — how pool workers are spawned, so "
                        "a supervisor preempt never loses finished work")
    return p


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    if args.trace:
        # Process-wide so pool worker children (plain serve CLIs spawned
        # with the inherited environment) trace too — the gate pattern
        # every RAFT_TLA_* knob follows.
        from raft_tla_tpu.obs.trace import ENV_TRACE
        os.environ[ENV_TRACE] = "1"
    if args.cpu:
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            if jax.default_backend() != "cpu":
                print("Warning: --cpu requested but JAX backends are "
                      f"already initialized on {jax.default_backend()!r}; "
                      "proceeding there", file=sys.stderr)
    from raft_tla_tpu.serve.sched import enable_compile_cache
    cache_dir = enable_compile_cache(args.compile_cache)
    if cache_dir and not args.quiet:
        print(f"compile cache: {cache_dir}")
    from raft_tla_tpu.obs.metrics import metrics_port
    mport = metrics_port(args.metrics_port)
    mserver = None
    if mport is not None:
        # The endpoint lives in THIS supervising process and only READS
        # the out dir's event logs (each scrape tails the new bytes) —
        # the engines' off-path cost is untouched (tel.active
        # discipline; A/B'd by runs/obs_overhead_ab.py events+metrics).
        from raft_tla_tpu.obs.openmetrics import MetricsServer
        os.makedirs(args.out, exist_ok=True)
        mserver = MetricsServer(
            args.out, port=mport,
            snapshot_path=os.path.join(args.out, "metrics.events"))
        print(f"metrics endpoint: {mserver.url}", flush=True)
    try:
        return _run_front(args)
    finally:
        if mserver is not None:
            mserver.close()


def _run_front(args) -> int:
    if args.watch:
        return run_daemon(args.source, args.out, chunk=args.chunk,
                          max_states=args.max_states, quiet=args.quiet,
                          depth=args.depth, poll_s=args.poll,
                          max_idle_polls=args.max_idle_polls,
                          workers=args.workers, cpu=args.cpu)
    skipped: list = []
    try:
        jobs = load_jobs(args.source, skipped=skipped)
    except (OSError, ValueError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    for name, err in skipped:
        print(f"Warning: skipped unreadable job file {name}: {err}",
              file=sys.stderr)
    stop = None
    prev_sigint = None
    if args.drain_on_sigint:
        import signal
        import threading
        drain = threading.Event()

        def _handler(_signum, _frame):
            if drain.is_set():
                signal.signal(signal.SIGINT, prev_sigint)
                raise KeyboardInterrupt
            drain.set()
            print("SIGINT: draining — unfinished lanes get attributed "
                  "records (SIGINT again aborts raw)", file=sys.stderr,
                  flush=True)

        if threading.current_thread() is threading.main_thread():
            prev_sigint = signal.getsignal(signal.SIGINT)
            signal.signal(signal.SIGINT, _handler)
        stop = drain.is_set
    if args.workers:
        from raft_tla_tpu.serve.pool import run_pool
        records = run_pool(jobs, args.out, workers=args.workers,
                           chunk=args.chunk, max_states=args.max_states,
                           quiet=args.quiet, depth=args.depth,
                           cpu=args.cpu, stop=stop)
    else:
        records = run_service(jobs, args.out, chunk=args.chunk,
                              max_states=args.max_states, quiet=args.quiet,
                              depth=args.depth, stop=stop)
    n_by = {}
    for rec in records:
        n_by[rec["status"]] = n_by.get(rec["status"], 0) + 1
    if not args.quiet:
        print("serve: " + ", ".join(f"{v} {k}"
                                    for k, v in sorted(n_by.items()))
              + f" -> {args.out}/results.jsonl")
    return 1 if n_by.get("stopped") else 0


def entry() -> None:
    """Console-script entry point (pyproject ``raft-tla-serve``)."""
    sys.exit(main())


if __name__ == "__main__":
    entry()
