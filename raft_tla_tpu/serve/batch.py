"""Lane-packed batch executor — N independent checks per fused dispatch.

The continuous-batching shape that makes inference stacks fast, applied
to model checking: admitted small-universe jobs are binned by **step
signature** (bounds + spec subset + invariants + symmetry + view — the
exact tuple ``ops/kernels.build_step`` compiles, which pins the packed
state width), and each bin's lanes share ONE compiled fused step.  Every
dispatch packs rows from all of the bin's live frontiers into one
``[B, W]`` chunk — lane-tagged on the host, anonymous on the device —
so a single vmapped step advances N independent BFS frontiers at once.
As a lane completes, its chunk share backfills with the remaining
lanes' rows on the very next dispatch (continuous batching, not static
batching): the chunk stays full while any lane has work.

Why this is fast for serving: a solo toy-universe run wastes most of
its fixed-shape chunk on padding (BFS levels are narrower than B) and
pays one jit compile per process; the batch pays one compile per *bin*
and fills chunks across tenants.  Why it is sound: lanes never share
dedup state — each lane owns its fingerprint set, store, parent links,
coverage and level accounting, exactly the per-run state of
``engine.Engine.check`` — so a lane's slice of a dispatch is processed
with byte-for-byte the same logic as a solo chunk.  For runs that
complete (no violation), counts are chunk-boundary-independent, hence
**byte-identical to a solo run of the same cfg**; a violating lane's
transition tally depends on its slice boundaries, the same way a solo
Engine's depends on ``--chunk`` (the verdict and trace do not).
"""

from __future__ import annotations

import dataclasses
import time
from collections import Counter
from typing import Optional

import numpy as np

from raft_tla_tpu.config import CheckConfig
from raft_tla_tpu.engine import DEADLOCK, EngineResult, Violation, _VecStore
from raft_tla_tpu.obs import RunTelemetry
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.serve.sched import DispatchScheduler


def bin_key(config: CheckConfig) -> tuple:
    """The step-signature bin: everything ``build_step`` compiles over.

    Delegates to ``ops/kernels.step_signature`` — THE definition of
    step-compile identity, including the construction-time gate
    resolutions (megakernel / prescan / sig-prune) — so a gate flipping
    between admissions can never mix step variants inside one bin.
    (Previously this tuple was hand-maintained here, so a new
    step-compile toggle had to be remembered in two places.)

    ``chunk`` is deliberately excluded — the executor imposes its own
    shared chunk shape, so jobs differing only in requested chunk share
    a bin (and a compile).  ``check_deadlock`` is appended even though
    the step does not compile over it: the executor's per-lane scan
    logic branches on it, and bins share that scan path.
    """
    return kernels.step_signature(
        config.bounds, config.spec, tuple(config.invariants),
        tuple(config.symmetry), config.view) + (config.check_deadlock,)


class _LaneFailure(Exception):
    """A per-lane abort (capacity overflow, cap exceeded) — poisons the
    lane, never the dispatch: the other tenants keep running."""


@dataclasses.dataclass
class LaneOutcome:
    """One job's terminal state, service-attribution-ready."""

    job_id: str
    status: str                       # completed | violation | deadlock
    #                                 # | stopped (lane failure)
    result: Optional[EngineResult] = None
    error: str | None = None


class _Lane:
    """One job's BFS state — the per-run state of ``engine.Engine.check``
    factored out so N of them can interleave on one compiled step."""

    def __init__(self, job_id: str, config: CheckConfig, table, lay,
                 tel: RunTelemetry | None = None, init_override=None,
                 model=None, wall_s: float | None = None):
        if model is None:
            from raft_tla_tpu.frontend import resolve_model
            model = resolve_model(config.spec)
        self.job_id = job_id
        self.config = config
        self.model = model
        self.table = table
        self.A = len(table)
        self.lay = lay
        self.tel = tel
        self.wall_s = wall_s            # per-job wall budget (JobOptions)
        self.t0 = time.monotonic()

        bounds = config.bounds
        init_py = init_override if init_override is not None \
            else model.init_py(bounds)
        init_vec = model.to_vec(init_py, bounds)
        hi0, lo0 = model.init_fingerprint(config, init_py, init_vec)
        self.seen: set[int] = {int(fpr.to_u64(hi0, lo0))}
        self.store = _VecStore(lay.width)
        self.store.append(init_vec[None, :])
        self.parents: list = [None]
        self.coverage: Counter = Counter()
        self.levels = [1]
        self.n_transitions = 0
        self.violation: Optional[Violation] = None
        self.new_this_level = 0
        self.next_frontier: list[int] = []
        self.outcome: Optional[LaneOutcome] = None
        self._pending = None

        if tel is not None:
            tel.run_start()
        for nm in config.invariants:
            if not model.py_invariant(nm)(init_py, bounds):
                self.violation = self._make_violation(nm, 0)
                break
        self.frontier = [0] if self.violation is None and \
            model.constraint_ok(init_py, bounds) else []
        self.cursor = 0
        # Slices taken by a dispatch but not yet harvested.  The cursor
        # advances at take() time (so a speculative same-level dispatch
        # can claim the NEXT rows before the previous harvest lands), so
        # "cursor at end of frontier" alone no longer means the level is
        # done — promotion must also wait for the in-flight count to
        # drain back to zero.
        self.inflight_slices = 0
        if self.violation is not None or not self.frontier:
            self._finish()

    # -- executor interface ---------------------------------------------------

    @property
    def active(self) -> bool:
        return self.outcome is None

    def pending_rows(self) -> int:
        return len(self.frontier) - self.cursor

    def take(self, n: int):
        """Claim the next ``n`` frontier rows: (gidx list, stacked vecs)."""
        gidx = self.frontier[self.cursor:self.cursor + n]
        self.cursor += len(gidx)
        vecs = np.stack([self.store.get(g) for g in gidx])
        return gidx, vecs

    def scan_slice(self, valid, ovf, keys, inv_ok, con_ok, gidx) -> list:
        """Phase 1 on this lane's slice of a dispatch (its 'chunk'):
        dedup in discovery order, transition/deadlock accounting, and
        the violation cut — ``engine.Engine.check`` semantics verbatim.
        Returns slice-relative flat indices of accepted new states."""
        A = self.A
        if ovf.any():
            _, a = np.argwhere(ovf)[0]
            raise _LaneFailure(
                "state-capacity overflow at "
                f"{self.table[int(a)].label()} — bounds reasoning "
                "violated (config.py capacity scheme)")
        dead_limit = None
        if self.config.check_deadlock:
            dead = ~valid.any(axis=1)
            if dead.any():
                dead_limit = int(np.argmax(dead)) * A
        flat_keys = keys.reshape(-1)
        flat_valid = valid.reshape(-1)
        if dead_limit is not None:
            flat_valid = flat_valid.copy()
            flat_valid[dead_limit:] = False
        self.n_transitions += int(flat_valid.sum())
        new_flat: list[int] = []
        for fi in np.nonzero(flat_valid)[0]:
            kk = int(flat_keys[fi])
            if kk in self.seen:
                continue
            self.seen.add(kk)
            new_flat.append(int(fi))
        for t, fi in enumerate(new_flat):
            b, a = divmod(fi, A)
            if not inv_ok[b, a].all():
                new_flat = new_flat[:t + 1]
                break
        self._pending = (new_flat, inv_ok, con_ok, gidx, dead_limit)
        return new_flat

    def commit_slice(self, rows: np.ndarray) -> None:
        """Phase 2: append the gathered new-state rows and record
        parents/coverage/verdicts in discovery order."""
        new_flat, inv_ok, con_ok, gidx, dead_limit = self._pending
        self._pending = None
        inv_names = list(self.config.invariants)
        if not new_flat:
            if dead_limit is not None:
                self.violation = self._make_violation(
                    DEADLOCK, gidx[dead_limit // self.A])
            return
        base = len(self.store)
        self.store.append(rows)
        for t, fi in enumerate(new_flat):
            b, a = divmod(fi, self.A)
            g = base + t
            self.parents.append((gidx[b], int(a)))
            self.coverage[self.table[int(a)].family] += 1
            self.new_this_level += 1
            bad = np.nonzero(~inv_ok[b, a])[0]
            if bad.size:
                self.violation = self._make_violation(
                    inv_names[int(bad[0])], g)
                break
            if bool(con_ok[b, a]):
                self.next_frontier.append(g)
        if self.violation is None and dead_limit is not None:
            self.violation = self._make_violation(
                DEADLOCK, gidx[dead_limit // self.A])

    def advance(self, max_states: int | None,
                inflight: int | None = None) -> None:
        """Post-slice lane control: violation stop, level promotion,
        completion — with a per-lane segment event at each boundary.
        ``inflight`` is the scheduler's dispatch-pipeline depth at the
        boundary (schema-v4 attribution, with the lane's bin tag)."""
        if self.violation is not None:
            self._finish()
            return
        if self.cursor < len(self.frontier) or self.inflight_slices > 0:
            return                      # level still in flight
        if self.new_this_level:
            self.levels.append(self.new_this_level)
        if self.tel is not None:
            self.tel.segment(len(self.store), len(self.levels) - 1,
                             self.n_transitions,
                             coverage=dict(self.coverage),
                             bin=getattr(self, "bin_tag", None),
                             inflight=inflight)
        if max_states is not None and len(self.store) > max_states:
            raise _LaneFailure(f"state count exceeded {max_states}")
        if self.wall_s is not None:
            spent = time.monotonic() - self.t0
            if spent > self.wall_s:
                # lossless deadline stop, the engines' --deadline analog:
                # the level boundary is a consistent cut, so every count
                # this lane reported stands and the record attributes the
                # stop to the tenant's own budget, not a service fault
                raise _LaneFailure(
                    f"budget-exceeded: wall {spent:.3f}s over the "
                    f"{self.wall_s:g}s wall_s budget (lossless "
                    "level-boundary stop)")
        self.frontier = self.next_frontier
        self.next_frontier = []
        self.cursor = 0
        self.new_this_level = 0
        if not self.frontier:
            self._finish()

    def fail(self, message: str) -> None:
        """Poison this lane (its tenants' verdict is 'stopped', with the
        failure as the reason); the dispatch and the other lanes live."""
        res = self._result(complete=False)
        if self.tel is not None:
            self.tel.stop_requested(message, source="serve")
            self.tel.run_end(res)
        self.outcome = LaneOutcome(self.job_id, "stopped", result=res,
                                   error=message)

    # -- internals ------------------------------------------------------------

    def _result(self, complete: bool = True) -> EngineResult:
        return EngineResult(
            n_states=len(self.store), diameter=len(self.levels) - 1,
            n_transitions=self.n_transitions, coverage=self.coverage,
            violation=self.violation, levels=self.levels,
            wall_s=time.monotonic() - self.t0, complete=complete)

    def _finish(self) -> None:
        res = self._result()
        if self.violation is None:
            status = "completed"
        else:
            status = "deadlock" if self.violation.invariant == DEADLOCK \
                else "violation"
        if self.tel is not None:
            self.tel.run_end(res)
        self.outcome = LaneOutcome(self.job_id, status, result=res)

    def _make_violation(self, inv_name: str, gidx: int) -> Violation:
        chain = []
        cur: Optional[int] = gidx
        while cur is not None:
            py = self.model.from_vec(self.store.get(cur),
                                     self.config.bounds)
            entry = self.parents[cur]
            label = self.table[entry[1]].label() if entry else None
            chain.append((label, py))
            cur = entry[0] if entry else None
        chain.reverse()
        return Violation(invariant=inv_name, state=chain[-1][1], trace=chain)


class _Bin:
    """One step signature: a fused step + the lanes sharing it.  The
    step is *built* here (host-side closure, cheap) but *compiled* by
    the scheduler — AOT on a background thread when async compiles are
    on, lazily at first dispatch otherwise — so a new signature never
    stalls bins that are already serving."""

    def __init__(self, key: tuple, config: CheckConfig, tag: str = "bin"):
        from raft_tla_tpu.frontend import resolve_model
        self.key = key
        self.tag = tag                  # stable per-run label (obs v4)
        self.bounds = config.bounds
        self.model = resolve_model(config.spec)
        self.lay = self.model.layout(config.bounds)
        self.table = self.model.action_table(config.bounds)
        self.A = len(self.table)
        self.step_fn = self.model.build_step(config)
        self.lanes: list[_Lane] = []

    def live_lanes(self) -> list:
        return [ln for ln in self.lanes if ln.active]


class BatchExecutor:
    """Run N admitted jobs with shared, lane-packed fused dispatches.

    ``chunk`` is the shared dispatch width ``B`` (every bin compiles one
    ``[B, W]`` step); ``max_states`` is a per-lane cap mirroring
    ``engine.Engine.check(max_states=)``.  ``run`` returns
    ``{job_id: LaneOutcome}`` — one terminal record per job, always.

    Every dispatch is routed through :class:`~raft_tla_tpu.serve.sched.
    DispatchScheduler`: ``depth`` fused dispatches ride the device at
    once (issue bin B's step while bin A's harvest runs on the host) and
    new-bin compiles run on a background thread.  ``depth=1`` with
    ``compile_async=False`` is the synchronous PR 6 baseline (the A/B
    sequential arm).  ``stop`` is an optional zero-arg callable polled at
    dispatch boundaries: when truthy, in-flight work is harvested and
    every still-active lane is stopped with drain attribution — the
    daemon's lossless-SIGINT contract.
    """

    def __init__(self, chunk: int = 1024, max_states: int | None = None,
                 depth: int = 2, compile_async: bool = True, stop=None,
                 tracer=None):
        self.chunk = chunk
        self.max_states = max_states
        self.depth = depth
        self.compile_async = compile_async
        self.stop = stop
        self.tracer = tracer            # SpanTracer | None (v8 tracing)
        self.last_stats: dict | None = None   # scheduler stats of last run

    def run(self, jobs, telemetry: dict | None = None,
            init_overrides: dict | None = None,
            budgets: dict | None = None) -> dict:
        """``jobs``: iterable of ``(job_id, CheckConfig)``; ``telemetry``
        optionally maps job_id -> RunTelemetry (the service wires one
        per-job event log each; callers owning none pass nothing).
        ``init_overrides`` maps job_id -> PyState, mirroring the solo
        engines' ``init_override`` hook (parity tests seed from it).
        ``budgets`` maps job_id -> wall seconds (``JobOptions.wall_s``):
        an over-budget lane is stopped losslessly at its next level
        boundary with a ``budget-exceeded`` record."""
        telemetry = telemetry or {}
        init_overrides = init_overrides or {}
        budgets = budgets or {}
        bins: dict[tuple, _Bin] = {}
        outcomes: dict[str, LaneOutcome] = {}
        lanes: list[_Lane] = []
        for job_id, config in jobs:
            if job_id in outcomes or any(ln.job_id == job_id
                                         for ln in lanes):
                raise ValueError(f"duplicate job id {job_id!r}")
            key = bin_key(config)
            bn = bins.get(key)
            if bn is None:
                bn = bins[key] = _Bin(key, config, tag=f"bin{len(bins)}")
            lane = _Lane(job_id, config, bn.table, bn.lay,
                         tel=telemetry.get(job_id),
                         init_override=init_overrides.get(job_id),
                         model=bn.model, wall_s=budgets.get(job_id))
            lane.bin_tag = bn.tag
            bn.lanes.append(lane)
            lanes.append(lane)
            if not lane.active:         # init-state verdict, no dispatch
                outcomes[job_id] = lane.outcome

        sched = DispatchScheduler(
            chunk=self.chunk, max_states=self.max_states,
            depth=self.depth, compile_async=self.compile_async,
            stop=self.stop, tracer=self.tracer)
        try:
            self.last_stats = sched.run(bins, outcomes)
            # The scheduler returns with live lanes only when stopped
            # (daemon drain) or when a bin's step never became runnable:
            # both get an attributed terminal record, never silence.
            stopped = bool(self.stop and self.stop())
            for lane in lanes:
                if lane.active:
                    lane.fail("stop requested (drain)" if stopped
                              else "scheduler quiescent with live lanes "
                                   "(step unrunnable)")
                    outcomes[lane.job_id] = lane.outcome
        finally:
            for lane in lanes:
                if lane.tel is not None:
                    lane.tel.close()
        return {ln.job_id: outcomes[ln.job_id] for ln in lanes}
