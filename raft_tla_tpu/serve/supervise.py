"""Worker supervision primitives for the fault-isolated serve pool.

serve/pool.py dispatches admitted jobs to child worker processes; this
module holds the *decisions* the pool makes about those children, kept
free of process trees so every rule is unit-testable:

- :class:`PoolPolicy` — the knobs: poison-quarantine threshold K
  (``max_job_deaths``), the global respawn budget, SIGINT->SIGKILL
  grace, retry backoff, and the degradation floor for OOM chunk
  halving.
- :func:`classify_death` — map a worker's exit (returncode + captured
  stderr/stdout tail) to a death kind: ``oom`` / ``killed`` /
  ``segfault`` / ``signal`` / ``crashed``.  The kind picks the recovery
  path: OOM degrades (respawn at half dispatch width), everything else
  blames the worker's unfinished jobs and bisects toward the poison.
- :class:`WorkerHealth` — one worker's liveness view, built from the
  campaign supervisor's pieces verbatim: a
  :class:`~raft_tla_tpu.campaign.supervisor._LogTail` per assigned
  tenant event log feeding one
  :class:`~raft_tla_tpu.campaign.supervisor.HealthMonitor` (heartbeat
  staleness from segment cadence, session wall budget).  The campaign
  proved these rules against checkpointed solo children; the pool
  reuses them unchanged against lane-packed workers — same verdict
  tuple, same injectable clock.
"""

from __future__ import annotations

import dataclasses
import signal
import time

from raft_tla_tpu.campaign.supervisor import (CampaignPolicy,
                                              HealthMonitor, _LogTail)


@dataclasses.dataclass(frozen=True)
class PoolPolicy:
    """When to quarantine, how long to wait, how far to degrade."""

    max_job_deaths: int = 3              # K: a job blamed for K worker
    #                                      deaths (the last one solo) is
    #                                      quarantined, never re-run
    max_respawns: int = 16               # global respawn budget — the
    #                                      give-up backstop against a
    #                                      systematically failing fleet
    grace_s: float = 10.0                # preempt SIGINT -> SIGKILL
    poll_s: float = 0.05                 # supervision loop period
    stale_after_s: float | None = None   # heartbeat threshold; None =
    #                                      HealthMonitor's cadence rule
    session_wall_s: float | None = None  # per-worker-attempt wall budget
    backoff_base_s: float = 0.25         # requeue delay (decorrelated
    backoff_cap_s: float = 10.0          #   jitter, campaign/'s class)
    backoff_jitter_seed: int | None = None
    min_chunk: int = 32                  # OOM degradation floor: chunk
    #                                      halves per OOM down to this;
    #                                      an OOM *at* the floor is
    #                                      treated as a poison death

    def health_policy(self) -> CampaignPolicy:
        """The CampaignPolicy slice HealthMonitor reads (stale + wall);
        campaign-only fields stay at their defaults, unused here."""
        return CampaignPolicy(stale_after_s=self.stale_after_s,
                              session_wall_s=self.session_wall_s)


# Allocator failures surface differently per layer: Python raises
# MemoryError, XLA/TPU raise RESOURCE_EXHAUSTED, the C++ runtime throws
# bad_alloc, and a host OOM-kill leaves only SIGKILL (classified by
# returncode below, with the marker scan catching the logged cases).
_OOM_MARKERS = ("MemoryError", "RESOURCE_EXHAUSTED", "Out of memory",
                "out of memory", "std::bad_alloc")


def classify_death(returncode: int, out_text: str = "") -> tuple:
    """``(kind, detail)`` for a worker that exited abnormally.

    ``kind`` is one of ``oom`` (degrade: respawn at half width),
    ``killed`` (SIGKILL — external killer or the host OOM reaper),
    ``segfault``, ``signal`` (any other fatal signal), or ``crashed``
    (nonzero exit with no better evidence).  The output scan wins over
    the returncode: an uncaught MemoryError exits 1, a TPU
    RESOURCE_EXHAUSTED aborts on a signal — both are OOM for recovery
    purposes (blaming a job for the pool's own memory pressure would
    quarantine innocents).
    """
    text = out_text or ""
    if any(m in text for m in _OOM_MARKERS):
        return ("oom", "worker output shows an out-of-memory failure")
    if returncode < 0:
        sig = -returncode
        try:
            name = signal.Signals(sig).name
        except ValueError:
            name = f"signal {sig}"
        if sig == signal.SIGKILL:
            return ("killed", f"{name}: external kill or host OOM reaper")
        if sig == signal.SIGSEGV:
            return ("segfault", name)
        return ("signal", name)
    return ("crashed", f"exit code {returncode}")


class WorkerHealth:
    """Health view over one worker attempt's assigned tenant logs.

    Tails each ``OUT/<job_id>.events`` (byte-offset, torn-line-safe,
    truncation-aware — requeue rotation shrinks files under us) and
    feeds every parsed event into one HealthMonitor, so a worker is
    "alive" as long as *any* of its lanes heartbeats.  ``verdict()``
    is the campaign tuple: ``None`` or ``(reason, detail)``.
    """

    def __init__(self, policy: PoolPolicy, event_paths: list,
                 clock=time.time):
        self.monitor = HealthMonitor(policy.health_policy(), clock=clock)
        self.tails = [_LogTail(p) for p in event_paths]

    def start(self, now: float) -> None:
        self.monitor.spawned_at = now

    def poll(self) -> list:
        """Drain all tails into the monitor; returns the new events."""
        events: list = []
        for tail in self.tails:
            events.extend(tail.poll())
        if events:
            self.monitor.observe(events)
        return events

    def verdict(self):
        return self.monitor.verdict()
