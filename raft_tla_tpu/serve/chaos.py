"""Chaos harness for the serve worker pool (serve/pool.py).

The pool's acceptance bar is byte-equivalence under faults: SIGKILL a
worker mid-dispatch, poison one job so it kills every host it rides,
tear the results tail — and every *non-poison* job's final results
record and tenant event log must still be identical (modulo wall-clock
fields) to an unsupervised in-process :func:`run_service` pass over the
same jobs.  This module provides:

- :class:`PoolChaos` — a ``spawn_hook`` fault injector.  Two fault
  schedules, composable: ``kill_after_events=N`` SIGKILLs the first
  spawned worker once its tenants' event logs have shown N ``segment``
  events (a mid-dispatch hard loss); ``poison=JOB_ID`` stalks every
  worker assigned that job and SIGKILLs it as soon as the poison job's
  event log first shows life (a job that reliably kills its host —
  the pool must bisect to it and quarantine it in <= K deaths).
- :func:`canon_record` / :func:`canon_events` — canonical forms for
  the byte-equivalence comparison: volatile fields (timings, rates,
  pids, paths, timestamps) are stripped; everything that describes the
  *model-checking result* (counts, levels, verdicts, outcomes) is
  kept verbatim.
- a CLI (``python -m raft_tla_tpu.serve.chaos CFG --workdir DIR``)
  that runs the solo reference, then the pool under a scheduled
  worker kill, and verifies the equivalence end to end — the
  tools/lint.sh serve-chaos smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from raft_tla_tpu.campaign.supervisor import _LogTail


class PoolChaos:
    """Fault injector riding :func:`run_pool`'s ``spawn_hook``.

    Each scheduled fault runs as a stalker thread that tails the
    victim worker's tenant event logs (the same ``_LogTail`` the
    supervisor uses) and delivers SIGKILL when its trigger condition
    is met — so kills land *mid-run*, anchored to observed progress,
    not at a wall-clock guess.  ``kills`` records ``(worker_id,
    trigger)`` pairs for assertions.
    """

    def __init__(self, kill_after_events: int | None = None,
                 poison: str | None = None,
                 max_kills: int | None = None, poll_s: float = 0.02):
        self.kill_after_events = kill_after_events
        self.poison = poison
        self.max_kills = max_kills
        self.poll_s = poll_s
        self.kills: list = []
        self._first_armed = False
        self._lock = threading.Lock()

    def spawn_hook(self, worker) -> None:
        jobs = [pj.job_id for pj in worker.group.pending_jobs()]
        if self.poison is not None and self.poison in jobs:
            with self._lock:
                if self.max_kills is not None \
                        and len(self.kills) >= self.max_kills:
                    return
            path = [t.path for t in worker.health.tails
                    if t.path.endswith(f"{os.sep}{self.poison}.events")]
            self._stalk(worker, path or
                        [t.path for t in worker.health.tails],
                        need=1, events=None, trigger="poison")
            return
        if self.kill_after_events is not None and not self._first_armed:
            self._first_armed = True
            self._stalk(worker, [t.path for t in worker.health.tails],
                        need=self.kill_after_events,
                        events=("segment",), trigger="kill-after-events")

    def _stalk(self, worker, paths: list, need: int, events,
               trigger: str) -> None:
        def run() -> None:
            tails = [_LogTail(p) for p in paths]
            seen = 0
            while worker.proc.poll() is None:
                for t in tails:
                    for e in t.poll():
                        if events is None or e.get("event") in events:
                            seen += 1
                if seen >= need:
                    with self._lock:
                        self.kills.append((worker.wid, trigger))
                    try:
                        worker.proc.kill()
                    except OSError:
                        pass
                    return
                time.sleep(self.poll_s)

        threading.Thread(target=run, daemon=True,
                         name=f"chaos-{trigger}-{worker.wid}").start()


# --------------------------------------------------------------------------
# canonical forms for byte-equivalence

# Result-record fields that legitimately differ between two runs of the
# same job: timings, rates, and the artifact path.
_VOLATILE_RECORD = frozenset({"admission_s", "wall_s", "states_per_sec",
                              "events"})

# Per event type, the fields that describe the checking RESULT — kept
# for comparison; everything else (ts, v, pid, wall_s, rates, phase
# timings, scheduler attribution like bin/inflight/chunk) is volatile.
_EVENT_KEEP = {
    "run_start": ("event", "engine", "universe", "spec", "invariants",
                  "resumed", "bounds", "symmetry", "view"),
    "segment": ("event", "n_states", "level", "n_transitions",
                "dedup_hit_rate", "since_resume"),
    "level_end": ("event", "level", "n_states"),
    "violation": ("event", "invariant", "kind"),
    "stop_requested": ("event", "reason", "source"),
    "run_end": ("event", "n_states", "n_transitions", "complete",
                "outcome", "diameter", "levels"),
}


def canon_record(rec: dict) -> dict:
    return {k: v for k, v in rec.items() if k not in _VOLATILE_RECORD}


def canon_events(path: str) -> list:
    """The stable projection of a tenant event log: same BFS, same
    chunk => identical list, whether the run was solo or survived a
    pool worker kill and a lossless re-run."""
    out = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().split("\n")
    except OSError:
        return out
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            e = json.loads(line)
        except ValueError:
            continue                     # torn tail
        keep = _EVENT_KEEP.get(e.get("event"))
        if keep:
            out.append({k: e[k] for k in keep if k in e})
    return out


def last_records(out_dir: str) -> dict:
    """Last results.jsonl record per job id (a requeued job's drained
    record is superseded by its re-run's)."""
    from raft_tla_tpu.serve.service import read_results

    last: dict = {}
    for r in read_results(out_dir):
        last[r.get("job_id")] = r
    return last


# --------------------------------------------------------------------------
# CLI smoke: solo reference vs pool-under-fire


def _toy_jobs(cfg_path: str, n: int, max_msgs: int) -> list:
    """n election-subset jobs over one cfg, alternating symmetry so the
    batch spans two step-signature bins (two worker groups)."""
    from raft_tla_tpu.serve.jobs import CheckJob, JobOptions

    return [CheckJob(f"j{i}",
                     JobOptions(spec="election", max_term=2, max_log=0,
                                max_msgs=max_msgs, symmetry=bool(i % 2)),
                     cfg_path=cfg_path)
            for i in range(n)]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="raft-tla-serve-chaos",
        description="Serve-pool chaos smoke: run N toy jobs solo "
                    "(reference), then through the supervised worker "
                    "pool with a scheduled mid-dispatch worker SIGKILL, "
                    "and verify every job's final results record and "
                    "event log are identical to the reference.")
    p.add_argument("cfg", help="toy cfg path (election subset)")
    p.add_argument("--workdir", required=True)
    p.add_argument("--jobs", type=int, default=4)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--chunk", type=int, default=256)
    p.add_argument("--max-msgs", type=int, default=1)
    p.add_argument("--kill-after-segments", type=int, default=2,
                   metavar="N",
                   help="SIGKILL the first worker after N segment "
                        "events across its lanes (default 2)")
    p.add_argument("--cpu", action="store_true")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)

    if args.cpu:
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    from raft_tla_tpu.serve.pool import run_pool
    from raft_tla_tpu.serve.service import run_service
    from raft_tla_tpu.serve.supervise import PoolPolicy

    ref_dir = os.path.join(args.workdir, "ref")
    pool_dir = os.path.join(args.workdir, "pool-out")
    jobs = _toy_jobs(args.cfg, args.jobs, args.max_msgs)

    ref_recs = run_service(jobs, ref_dir, chunk=args.chunk,
                           quiet=args.quiet)
    chaos = PoolChaos(kill_after_events=args.kill_after_segments)
    run_pool(jobs, pool_dir, workers=args.workers, chunk=args.chunk,
             quiet=args.quiet, cpu=args.cpu,
             policy=PoolPolicy(backoff_base_s=0.05, backoff_cap_s=0.2,
                               backoff_jitter_seed=1),
             spawn_hook=chaos.spawn_hook)

    if not chaos.kills:
        print("serve-chaos: FAIL — scheduled worker kill never fired",
              file=sys.stderr)
        return 1
    ref_by = {r["job_id"]: r for r in ref_recs}
    pool_by = last_records(pool_dir)
    bad = []
    for job in jobs:
        jid = job.job_id
        a, b = ref_by.get(jid), pool_by.get(jid)
        if a is None or b is None or b.get("status") != "completed":
            bad.append(f"{jid}: missing/uncompleted pool record "
                       f"({None if b is None else b.get('status')})")
            continue
        if canon_record(a) != canon_record(b):
            bad.append(f"{jid}: results record diverged")
        ev_a = canon_events(os.path.join(ref_dir, f"{jid}.events"))
        ev_b = canon_events(os.path.join(pool_dir, f"{jid}.events"))
        if ev_a != ev_b:
            bad.append(f"{jid}: event log diverged "
                       f"({len(ev_a)} vs {len(ev_b)} canonical events)")
    if bad:
        print("serve-chaos: FAIL\n  " + "\n  ".join(bad),
              file=sys.stderr)
        return 1
    print(f"serve-chaos: OK — {len(jobs)} job(s) byte-identical to the "
          f"solo reference through {len(chaos.kills)} worker "
          f"SIGKILL(s) ({', '.join(w for w, _ in chaos.kills)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
