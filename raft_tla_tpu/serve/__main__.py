"""``python -m raft_tla_tpu.serve`` — same front as ``raft-tla-serve``."""

from raft_tla_tpu.serve.service import entry

entry()
