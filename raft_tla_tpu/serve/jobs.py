"""Job model + admission — the service's L5/L6 seam.

A :class:`CheckJob` is one tenant's bounded check: a TLC model config
(text or path) plus the engine options the check.py CLI would take as
flags, normalised into :class:`JobOptions`.  Two functions do all the
work:

- :func:`resolve_check_config` — the cfg→``CheckConfig`` builder that
  used to be inlined in ``check.py._resolve_config``.  check.py and the
  server now share this one code path (check.py is a thin single-job
  client); every validation it performs is host-only.
- :func:`admit` — the speclint gate: parse, build :class:`Bounds`, run
  the Pass 1 width proof and the Pass 2 cfg lint, and reject
  width-unsafe or vacuous configs *with the lint findings as the error
  payload* — all before any step build, so a rejected job costs zero
  device time.

Tenant isolation: :meth:`CheckJob.digest` is a stable content hash of
(cfg text, options).  The service stamps it into every result record
and artifact name, the same role the checkpoint config digest plays for
resumes — two tenants' outputs can never be silently conflated.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

from raft_tla_tpu.analysis import report as _report
from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.utils import cfgparse
from raft_tla_tpu.utils.cfgparse import TLCConfig


@dataclasses.dataclass(frozen=True)
class JobOptions:
    """The engine-facing options of one check — the check.py flags that
    shape the compiled model, minus anything about *where* it runs.
    Field names match the CLI flags (``--max-term`` → ``max_term``)."""

    spec: str = "full"
    max_term: int = 3
    max_log: int = 2
    max_msgs: int = 4
    max_dup: int = 1
    faithful: bool = False
    max_elections: int = 6
    chunk: int = 1024
    symmetry: bool = False          # --symmetry: force the Server axis
    view: str | None = None         # --view: registered exact view
    deadlock: bool = False
    properties: tuple = ()          # --property additions (cfg's also read)
    wall_s: float | None = None     # per-job wall budget: the lane is
    #                                 stopped losslessly at the first
    #                                 level boundary past this many
    #                                 seconds (budget-exceeded record)


def resolve_check_config(cfg: TLCConfig, opts: JobOptions,
                         path: str | None = None):
    """cfg + options -> ``(CheckConfig, properties)``; raises ValueError.

    The single code path behind ``check.py`` and the server: stanza
    support checks (SPECIFICATION/INIT/NEXT must name the compiled
    spec), invariant/property resolution with did-you-mean, SYMMETRY
    axis mapping, CONSTRAINT/VIEW compatibility, and the Bounds build.
    """
    from raft_tla_tpu.frontend import resolve_model
    from raft_tla_tpu.frontend.predicate import is_expression
    from raft_tla_tpu.models import invariants as inv_mod
    from raft_tla_tpu.models import liveness as live_mod

    model = resolve_model(opts.spec)     # ValueError on unknown spec name
    if not model.is_raft:
        # Non-Raft models own their cfg mapping (constants, invariant
        # language, bounds) — one method, same (config, props) contract.
        return model.resolve_check_config(cfg, opts, path)

    if cfg.specification not in (None, "Spec"):
        raise ValueError(
            f"unsupported SPECIFICATION {cfg.specification!r}: the compiled "
            "model implements Spec == Init /\\ [][Next]_vars (raft.tla:469)")
    # INIT/NEXT-style configs: only the spec's own operators are compiled;
    # any other name would silently run a different model.
    if cfg.init not in (None, "Init") or cfg.next not in (None, "Next"):
        raise ValueError(
            f"unsupported INIT/NEXT ({cfg.init!r}/{cfg.next!r}): only the "
            "spec's Init (raft.tla:155-160) and Next (raft.tla:454-465) "
            "are compiled")
    # Unknown names fail at resolve time with the offending cfg line and
    # a did-you-mean (one resolver, shared with the Pass 2 lint).
    # Whole-line predicate EXPRESSIONS bypass the registry and must
    # parse against the Raft state schema instead.
    named = [nm for nm in cfg.invariants if not is_expression(nm)]
    cfgparse.resolve_names(named, inv_mod.REGISTRY, "invariant",
                           cfg=cfg, path=path)
    for nm in cfg.invariants:
        if not is_expression(nm):
            continue
        try:
            inv_mod._expression(nm)
        except ValueError as e:
            lineno = cfg.line_of("invariant", nm)
            where = f"{path or 'cfg'} line {lineno}: " if lineno else ""
            raise ValueError(
                f"{where}invariant expression {nm!r} does not parse: {e}")
    for nm in cfg.properties:
        live_mod.parse_property(nm)     # raises with both registries
    sym_names = set(cfg.symmetry) | ({"Server"} if opts.symmetry else set())
    bad_sym = sym_names - {"Server", "SymServer", "Value", "SymValue",
                           "SymServerValue"}
    if bad_sym:
        raise ValueError(
            f"SYMMETRY {sorted(bad_sym)} not supported: Server and/or "
            "Value permutation symmetry (name them Server/SymServer, "
            "Value/SymValue, or the combined SymServerValue)")
    symmetry = tuple(ax for ax in ("Server", "Value")
                     if {ax, f"Sym{ax}"} & sym_names
                     or "SymServerValue" in sym_names)
    # Our own --emit-tlc artifacts declare the constraint/view this checker
    # builds in; anything else would be silently unchecked.
    if [c for c in cfg.constraints if c != "StateConstraint"]:
        raise ValueError(
            f"CONSTRAINT {cfg.constraints} not supported: the state "
            "constraint is the built-in bound, set via --max-* flags "
            "(emitted to TLC as 'StateConstraint')")
    if opts.faithful:
        # Faithful mode fingerprints FULL states; accepting a cfg that
        # declares the history-stripping view would silently contradict
        # what stock TLC does with that very cfg.
        if cfg.view is not None:
            raise ValueError(
                f"VIEW {cfg.view} contradicts --faithful: faithful mode "
                "fingerprints full states (no view); re-emit the TLC twin "
                "with --faithful --emit-tlc")
    elif cfg.view not in (None, "ParityView"):
        raise ValueError(
            f"VIEW {cfg.view} not supported: parity mode fingerprints "
            "under the built-in history-free ParityView")
    bounds = Bounds(
        n_servers=len(cfg.server_names()),
        n_values=len(cfg.value_names()),
        max_term=opts.max_term, max_log=opts.max_log,
        max_msgs=opts.max_msgs, max_dup=opts.max_dup,
        history=opts.faithful, max_elections=opts.max_elections)
    props = list(cfg.properties) + [nm for nm in opts.properties
                                    if nm not in cfg.properties]
    for nm in props:
        live_mod.parse_property(nm)     # raises with both registries
    return CheckConfig(bounds=bounds, spec=opts.spec,
                       invariants=tuple(cfg.invariants), symmetry=symmetry,
                       chunk=opts.chunk,
                       check_deadlock=opts.deadlock,
                       view=opts.view), tuple(props)


# --------------------------------------------------------------------------
# jobs


# JobOptions fields a manifest/queue entry may set (everything except the
# tuple-typed properties, which JSON lists map onto).
_OPTION_KEYS = tuple(f.name for f in dataclasses.fields(JobOptions))


@dataclasses.dataclass(frozen=True)
class CheckJob:
    """One tenant's bounded check: identity + cfg + options.

    ``cfg_text`` wins over ``cfg_path`` when both are set (a queue entry
    may inline the config so the job file is self-contained); the digest
    always covers the *text*, so the same model submitted by path or
    inline hashes identically.
    """

    job_id: str
    options: JobOptions = JobOptions()
    cfg_path: str | None = None
    cfg_text: str | None = None

    def read_cfg_text(self) -> str:
        if self.cfg_text is not None:
            return self.cfg_text
        if self.cfg_path is None:
            raise ValueError(f"job {self.job_id!r} has neither cfg_text "
                             "nor cfg_path")
        with open(self.cfg_path, "r", encoding="utf-8") as f:
            return f.read()

    def digest(self) -> str:
        """Stable content hash of (cfg text, options) — the tenant
        isolation tag stamped into every result record."""
        payload = json.dumps(
            {"cfg": self.read_cfg_text(),
             "options": dataclasses.asdict(self.options)},
            sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    @classmethod
    def from_dict(cls, d: dict, job_id: str | None = None) -> "CheckJob":
        """Build from one manifest/queue JSON object.  Unknown keys are
        a hard error — a typo'd option silently running default bounds
        is the cfg-footgun all over again."""
        d = dict(d)
        jid = d.pop("id", None) or job_id
        if not jid:
            raise ValueError(f"job entry has no 'id': {sorted(d)}")
        cfg_path = d.pop("cfg", None)
        cfg_text = d.pop("cfg_text", None)
        props = d.pop("properties", ())
        unknown = set(d) - set(_OPTION_KEYS)
        if unknown:
            raise ValueError(
                f"job {jid!r}: unknown option(s) {sorted(unknown)} "
                f"(known: id, cfg, cfg_text, {', '.join(_OPTION_KEYS)})")
        opts = JobOptions(properties=tuple(props), **d)
        return cls(job_id=str(jid), options=opts,
                   cfg_path=cfg_path, cfg_text=cfg_text)

    def to_dict(self) -> dict:
        """The inverse of :meth:`from_dict`, with the cfg TEXT inlined —
        the self-contained job form the worker pool writes into per-child
        manifests (a child must not depend on the parent's cwd or on a
        cfg file still existing).  ``from_dict(to_dict(j))`` digests
        identically to ``j`` (the digest covers text, never path)."""
        d = {"id": self.job_id, "cfg_text": self.read_cfg_text()}
        defaults = JobOptions()
        for f in dataclasses.fields(JobOptions):
            v = getattr(self.options, f.name)
            if v != getattr(defaults, f.name):
                d[f.name] = list(v) if isinstance(v, tuple) else v
        return d


# --------------------------------------------------------------------------
# admission


@dataclasses.dataclass
class Admission:
    """The speclint verdict for one job, findings attached either way.

    ``admitted`` jobs carry the resolved ``config``/``properties`` the
    executor runs; rejected jobs carry ``reason`` (stable kebab-case)
    and the findings that justify it — the error payload the service
    returns to the tenant.
    """

    job: CheckJob
    admitted: bool
    findings: list                       # analysis/report.Finding
    config: CheckConfig | None = None
    properties: tuple = ()
    reason: str | None = None

    def findings_text(self) -> list:
        return [f.format() for f in self.findings]


def admit(job: CheckJob) -> Admission:
    """Gate one job through speclint — host-only, zero device time.

    Reject paths, in order: unreadable/unparseable cfg; Bounds the
    packed encodings cannot represent (width-unsafe by construction);
    Pass 1 width-proof failures; Pass 2 cfg-lint errors (unknown names,
    mode mismatches, constant/bounds conflicts); *vacuous invariants*
    (a warning for the CLI, but a service must not bill device time for
    a check that statically checks nothing); and any residual
    resolve-time error.  The returned findings are the error payload.
    """
    from raft_tla_tpu.analysis import cfglint, widthcheck
    from raft_tla_tpu.frontend import resolve_model

    opts = job.options
    # Budget first: a zero/negative/non-numeric wall_s is a client error
    # the lint gate must name, never a traceback out of the queue worker
    # (and never a job the executor starts only to stop at once).
    w = opts.wall_s
    if w is not None and (type(w) not in (int, float) or w <= 0):
        f = _report.Finding(
            _report.CFG, _report.ERROR, "budget-invalid",
            f"wall_s must be a positive number of seconds, got {w!r}",
            field="wall_s")
        return Admission(job, False, [f], reason="budget-invalid")
    # Spec name next: an unknown spec must be a lint-style finding, not
    # a traceback out of the queue worker.
    try:
        model = resolve_model(opts.spec)
    except ValueError as e:
        f = _report.Finding(_report.CFG, _report.ERROR, "spec-unknown",
                            str(e), field=opts.spec)
        return Admission(job, False, [f], reason="spec-unknown")

    try:
        cfg = cfgparse.parse_cfg(job.read_cfg_text())
    except (OSError, ValueError) as e:
        f = _report.Finding(_report.CFG, _report.ERROR, "cfg-unreadable",
                            str(e), file=job.cfg_path)
        return Admission(job, False, [f], reason="cfg-unreadable")

    if not model.is_raft:
        # Non-Raft admission: the model maps the cfg itself, then its
        # schema validity gate plays the width-proof role.
        try:
            config, props = model.resolve_check_config(
                cfg, opts, path=job.cfg_path)
        except ValueError as e:
            f = _report.Finding(_report.CFG, _report.ERROR,
                                "resolve-failed", str(e), file=job.cfg_path)
            return Admission(job, False, [f], reason="cfg-invalid")
        findings = list(model.check_widths(config.bounds))
        if _report.has_errors(findings):
            return Admission(job, False, findings, reason="width-unsafe")
        return Admission(job, True, findings, config=config,
                         properties=props)

    try:
        bounds = Bounds(
            n_servers=len(cfg.server_names()),
            n_values=len(cfg.value_names()),
            max_term=opts.max_term, max_log=opts.max_log,
            max_msgs=opts.max_msgs, max_dup=opts.max_dup,
            history=opts.faithful, max_elections=opts.max_elections)
    except ValueError as e:
        # The encodings cannot even represent these bounds: width-unsafe
        # by construction (same lift analysis/__main__ applies).
        f = _report.Finding(_report.WIDTH, _report.ERROR, "bounds-invalid",
                            str(e), file=job.cfg_path)
        findings = [f] + cfglint.lint_cfg(
            cfg, Bounds(), spec=model.sub, view=opts.view,
            path=job.cfg_path)
        return Admission(job, False, findings, reason="width-unsafe")

    findings = list(widthcheck.check_widths(bounds, model.sub))
    if _report.has_errors(findings):
        return Admission(job, False, findings, reason="width-unsafe")

    findings += cfglint.lint_cfg(cfg, bounds, spec=model.sub,
                                 view=opts.view, path=job.cfg_path)
    if _report.has_errors(findings):
        return Admission(job, False, findings, reason="cfg-invalid")
    vacuous = [f for f in findings if f.code == "invariant-vacuous"]
    if vacuous:
        return Admission(job, False, findings, reason="vacuous")

    try:
        config, props = resolve_check_config(cfg, opts, path=job.cfg_path)
    except ValueError as e:
        findings.append(_report.Finding(
            _report.CFG, _report.ERROR, "resolve-failed", str(e),
            file=job.cfg_path))
        return Admission(job, False, findings, reason="cfg-invalid")
    return Admission(job, True, findings, config=config, properties=props)
