"""Pass 2 — spec/config lint: TLCConfig diagnostics against the model.

The classic TLC footgun this pass exists for: a typo'd ``INVARIANT``
name, or an invariant that nothing in the chosen spec subset can ever
falsify, silently checks *nothing* while the run prints OK.  Every
diagnostic here is a claim about the cfg/model pairing:

- **Unknown names** (error): INVARIANT / PROPERTY / SYMMETRY / VIEW
  entries that resolve against no registry, each with a did-you-mean
  (``utils.cfgparse.suggest``) and the offending cfg line.
- **Mode mismatches** (error): history invariants under parity bounds
  (their READS fields do not exist in the parity layout), VIEW vs
  faithful fingerprints.
- **Constant bindings inconsistent with Bounds** (error/warning):
  Server/Value sets out of the supported ranges, bound-constants
  (MaxTerm &c.) that contradict the Bounds in force.
- **Vacuous invariants** (warning): the invariant holds on Init and
  reads only fields no transition in the active spec subset writes
  (``ops/kernels.TRANSFER_WRITES``) — statically true, checking nothing.
- **Symmetry/view compatibility** (error/warning): SYMMETRY on an axis
  the view is not equivariant to (orbit-dependent fingerprints: unsound
  dedup), and invariants reading fields the view rewrites (checked only
  up to the view).
"""

from __future__ import annotations

from raft_tla_tpu.analysis.report import CFG, ERROR, WARNING, Finding
from raft_tla_tpu.config import Bounds, _MAX_SERVERS, _MAX_VALUES
from raft_tla_tpu.utils import cfgparse

_SYM_NAMES = ("Server", "SymServer", "Value", "SymValue", "SymServerValue")
# The built-in parity view: history-stripping only; equivariant to every
# permutation axis and rewrites no parity-layout field.
_BUILTIN_VIEWS = ("ParityView",)

# cfg constant name -> Bounds attribute, for binding-consistency checks.
_BOUND_CONSTANTS = {
    "MaxTerm": "max_term",
    "MaxLog": "max_log",
    "MaxMsgs": "max_msgs",
    "MaxDup": "max_dup",
    "MaxElections": "max_elections",
}


def _unknown(kind, names, known, cfg, path) -> list:
    findings = []
    for name, hints in cfgparse.unknown_names(names, known):
        hint = f"; did you mean: {', '.join(hints)}?" if hints else ""
        findings.append(Finding(
            CFG, ERROR, f"unknown-{kind}",
            f"unknown {kind} {name!r} (known: {', '.join(sorted(known))})"
            f"{hint}", field=name, file=path,
            line=cfg.line_of(kind, name)))
    return findings


def lint_cfg(cfg: cfgparse.TLCConfig, bounds: Bounds, *,
             spec: str = "full", view: str | None = None,
             path: str | None = None) -> list:
    """Run every Pass 2 diagnostic for one parsed cfg + Bounds pairing.

    ``view`` is the CLI-selected state view (views.REGISTRY name), if
    any; the cfg's own VIEW stanza is validated separately (it can only
    name the built-in ParityView).
    """
    from raft_tla_tpu.models import invariants as inv_mod
    from raft_tla_tpu.models import liveness as live_mod
    from raft_tla_tpu.models import spec as SP
    from raft_tla_tpu.models import views as views_mod

    findings = []

    # -- unknown names --------------------------------------------------------
    # Whole-line predicate EXPRESSIONS (frontend grammar) are not registry
    # names: parse-check them instead of spell-checking them.
    from raft_tla_tpu.frontend.predicate import is_expression
    inv_names = [nm for nm in cfg.invariants if not is_expression(nm)]
    inv_exprs = [nm for nm in cfg.invariants if is_expression(nm)]
    findings += _unknown("invariant", inv_names, inv_mod.REGISTRY,
                         cfg, path)
    for text in inv_exprs:
        try:
            inv_mod._expression(text)
        except ValueError as e:
            findings.append(Finding(
                CFG, ERROR, "invariant-parse-error",
                f"invariant expression {text!r} does not parse: {e}",
                field=text, file=path,
                line=cfg.line_of("invariant", text)))
    for text in cfg.properties:
        try:
            live_mod.parse_property(text)
        except ValueError as e:
            findings.append(Finding(
                CFG, ERROR, "unknown-property", str(e), field=text,
                file=path, line=cfg.line_of("property", text)))
    findings += _unknown("symmetry", cfg.symmetry, _SYM_NAMES, cfg, path)
    if cfg.view is not None:
        findings += _unknown(
            "view", [cfg.view],
            set(_BUILTIN_VIEWS) | set(views_mod.REGISTRY), cfg, path)

    # -- constant bindings vs Bounds ------------------------------------------
    for axis, cap, n in (("Server", _MAX_SERVERS, bounds.n_servers),
                         ("Value", _MAX_VALUES, bounds.n_values)):
        names = cfg.constants.get(axis)
        if names is None:
            findings.append(Finding(
                CFG, ERROR, "constant-missing",
                f"cfg does not bind {axis} to a finite set (the model "
                "takes its cardinality from this binding)", field=axis,
                file=path))
            continue
        if not isinstance(names, list):
            findings.append(Finding(
                CFG, ERROR, "constant-not-set",
                f"{axis} must be bound to a finite set, got {names!r}",
                field=axis, file=path,
                line=cfg.line_of("constant", axis)))
            continue
        if not 1 <= len(names) <= cap:
            findings.append(Finding(
                CFG, ERROR, "constant-out-of-range",
                f"{axis} has {len(names)} elements; the packed encodings "
                f"support 1..{cap}", field=axis, file=path,
                line=cfg.line_of("constant", axis)))
        if len(names) != n:
            findings.append(Finding(
                CFG, ERROR, "constant-bounds-mismatch",
                f"cfg binds {len(names)} {axis} elements but Bounds has "
                f"{n} — the cfg and the bounds in force disagree",
                field=axis, file=path, line=cfg.line_of("constant", axis)))
    for cname, attr in _BOUND_CONSTANTS.items():
        bound_val = cfg.constants.get(cname)
        if bound_val is None or isinstance(bound_val, list):
            continue
        try:
            bound_int = int(bound_val)
        except ValueError:
            continue                      # model value, not a bound
        have = getattr(bounds, attr)
        if bound_int != have:
            findings.append(Finding(
                CFG, WARNING, "constant-bounds-mismatch",
                f"cfg binds {cname} = {bound_int} but the bounds in force "
                f"use {attr} = {have} (cfg bound constants are "
                "informational; --max-* flags win)", field=cname,
                file=path, line=cfg.line_of("constant", cname)))

    # -- mode mismatches ------------------------------------------------------
    for name in cfg.invariants:
        if name in inv_mod.HISTORY_REGISTRY and not bounds.history:
            findings.append(Finding(
                CFG, ERROR, "invariant-needs-history",
                f"invariant {name} reads history variables "
                f"({', '.join(inv_mod.READS[name])}) that the parity "
                "layout does not carry; run with --faithful", field=name,
                file=path, line=cfg.line_of("invariant", name)))
    if bounds.history and cfg.view is not None:
        findings.append(Finding(
            CFG, ERROR, "view-vs-faithful",
            f"VIEW {cfg.view} contradicts faithful mode: faithful "
            "fingerprints full states (no view)", field=cfg.view,
            file=path, line=cfg.line_of("view", cfg.view)))

    # -- vacuous invariants ---------------------------------------------------
    findings += _vacuity(cfg, bounds, spec, path)

    # -- symmetry / view compatibility ----------------------------------------
    axes = set()
    for s in cfg.symmetry:
        if s == "SymServerValue":
            axes |= {"Server", "Value"}
        elif s in _SYM_NAMES:
            axes.add(s.removeprefix("Sym"))
    if view is not None and view in views_mod.REGISTRY:
        equivariant = set(views_mod.EQUIVARIANT_AXES.get(view, ()))
        for ax in sorted(axes - equivariant):
            findings.append(Finding(
                CFG, ERROR, "view-symmetry-incompatible",
                f"SYMMETRY {ax} with view {view!r}: the view is not "
                f"declared equivariant to {ax} permutations, so "
                "view-fingerprints would be orbit-dependent (unsound "
                "dedup)", field=view, file=path))
        written = set(views_mod.VIEW_WRITES.get(view, ()))
        for name in cfg.invariants:
            reads = set(inv_mod.READS.get(name, ()))
            hit = sorted(reads & written)
            if hit:
                findings.append(Finding(
                    CFG, WARNING, "invariant-under-view",
                    f"invariant {name} reads {', '.join(hit)} which view "
                    f"{view!r} rewrites before fingerprinting: it is "
                    "checked only up to the view", field=name, file=path,
                    line=cfg.line_of("invariant", name)))
    return findings


def _vacuity(cfg, bounds, spec, path) -> list:
    """An invariant is vacuous when (a) its predicate holds on Init and
    (b) it reads only fields no transition in the active spec subset
    writes — then no reachable state can falsify it and the run checks
    nothing.  The write-sets are the *reachability-refined* ones from the
    Pass 1 transfer twins (with the spec-restricted message envelope):
    in the election subset, Receive never carries an AppendEntries
    record, so it never writes the log — the static
    ``kernels.TRANSFER_WRITES`` superset would miss that vacuity.  Plus
    one host evaluation on the unique Init state."""
    from raft_tla_tpu.analysis import intervals as iv
    from raft_tla_tpu.analysis import widthcheck as wc
    from raft_tla_tpu.models import interp
    from raft_tla_tpu.models import invariants as inv_mod
    from raft_tla_tpu.models import spec as SP
    from raft_tla_tpu.ops import kernels

    findings = []
    try:
        fams = {a.family for a in SP.action_table(bounds, spec)}
    except (KeyError, ValueError):
        return findings                   # bad spec name, reported upstream
    written = set(kernels.POSTLUDE_WRITES) if bounds.history else set()
    env = iv.expansion_envelope(bounds)
    active = {f: wc.TRANSFERS[f] for f in fams if f in wc.TRANSFERS}
    menv = wc.message_envelope(bounds, env, active)
    for t in active.values():
        written |= set(t(bounds, env, menv).writes)
    from raft_tla_tpu.frontend.predicate import is_expression
    init = interp.init_state(bounds)
    for name in cfg.invariants:
        if name in inv_mod.REGISTRY:
            if name not in inv_mod.READS:
                continue
            if name in inv_mod.HISTORY_REGISTRY and not bounds.history:
                continue                  # already an error above
            reads = set(inv_mod.READS[name])
        elif is_expression(name):
            # compiled expressions carry their own read-set
            try:
                reads = set(inv_mod._expression(name).reads)
            except ValueError:
                continue                  # parse error, reported upstream
        else:
            continue                      # unknown name, reported upstream
        if reads & written:
            continue
        try:
            holds = inv_mod.py_invariant(name)(init, bounds)
        except Exception:
            continue
        if holds:
            findings.append(Finding(
                CFG, WARNING, "invariant-vacuous",
                f"invariant {name} reads only "
                f"{', '.join(sorted(reads))}, which no transition of "
                f"spec {spec!r} writes, and it holds on Init — it is "
                "statically true and checks nothing", field=name,
                file=path, line=cfg.line_of("invariant", name)))
    return findings
