"""Pass 1 — width-safety: prove no transition can overflow a packed field.

The theorem being checked, per mode (parity / faithful):

1. **Base**: the Init state lies inside the claimed per-field envelope
   (:func:`.intervals.envelope`), and the envelope fits the bit widths
   ``ops/bitpack.field_bits`` allots.
2. **Induction**: for every action family in the spec subset, the abstract
   transfer function — mirroring the guard/update structure of the kernel
   in ``ops/kernels`` — maps the *expansion envelope* (envelope met with
   the StateConstraint: only constraint-satisfying states are ever
   expanded, TLC semantics) back inside the envelope.
3. **Messages**: every packed-record creation site writes subfields that
   fit the ``ops/msgbits`` shift/width tables, where the subfield ranges
   of *received* messages come from a monotone fixpoint over all creation
   sites (the bag starts empty at Init, so the fixpoint is the inductive
   message invariant).
4. **Tables**: the shift/width tables themselves have no overlap and no
   spill past bit 31 (int32 sign bit clear), and every flat-vector field
   width is <= 31 except the declared raw-mask fields.

Any hole is reported with the transition name, field, derived interval,
and allotted width — the acceptance contract of the analyzer.

The transfer functions are *hand-written twins* of the kernels, the same
way ``models/interp.py`` twins them for value semantics; the cross-check
against ``ops/kernels.transfer_metadata()`` (same families, same
written-field sets) makes silent drift between kernel and transfer a
loud lint error.  Every input (field widths, shift tables, envelopes,
transfers) is injectable so the seeded-mutation harness
(``tests/test_lint_mutations.py``) can prove the analyzer has no false
negatives on known overflow bugs.
"""

from __future__ import annotations

import dataclasses

from raft_tla_tpu.analysis import intervals as iv
from raft_tla_tpu.analysis.report import ERROR, WIDTH, Finding
from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import spec as SP
from raft_tla_tpu.ops import state as st

BIG = 1 << 40       # "unbounded" guard limit for meet() refinements


@dataclasses.dataclass(frozen=True)
class MsgRecord:
    """Abstract packed record: one creation site's subfield intervals.

    Keys are the ``ops/msgbits`` field names; keys containing ``+`` are
    *derived* relational facts (e.g. ``a+c`` of AppendEntriesRequest:
    prevLogIndex + Len(mentries), which the done-reply echoes as
    mmatchIndex) — they join into the message envelope but are not
    width-checked against the shift tables.
    """

    mtype: int
    fields: dict


@dataclasses.dataclass(frozen=True)
class TransferResult:
    writes: dict          # struct field -> Interval of newly written values
    sends: tuple = ()     # MsgRecords added to the bag


def _rank_iv(bounds: Bounds) -> iv.Interval:
    """Log-universe ranks (faithful mode); parity passes 0 (stripped)."""
    if not bounds.history:
        return iv.const(0)
    from raft_tla_tpu.ops.loguniv import LogUniverse
    return iv.Interval(0, LogUniverse.of(bounds).size - 1)


def _last_term(env) -> iv.Interval:
    """LastTerm(log[i]) (raft.tla:102): 0 when empty, else a stored term."""
    return env["logTerm"].join(0)


def _server_iv(bounds: Bounds) -> iv.Interval:
    return iv.Interval(0, max(bounds.n_servers - 1, 0))


def _bag_count(env) -> iv.Interval:
    """msgCount after a bag_add: one multiplicity bumped by 1."""
    return env["msgCount"] + iv.Interval(0, 1)


# -- per-family transfers (the kernel twins) ---------------------------------

def t_restart(bounds, env, menv):
    """Restart(i) (raft.tla:167-175)."""
    writes = {"role": iv.const(SP.FOLLOWER), "vResp": iv.const(0),
              "vGrant": iv.const(0), "nextIndex": iv.const(1),
              "matchIndex": iv.const(0), "commitIndex": iv.const(0)}
    if bounds.history:
        writes["vLog"] = iv.const(0)
    return TransferResult(writes)


def t_timeout(bounds, env, menv):
    """Timeout(i) (raft.tla:178-187): the term increment.  Sound only
    because env is the EXPANSION envelope (term <= max_term): the +1
    capacity scheme of config.py, proved rather than assumed."""
    writes = {"role": iv.const(SP.CANDIDATE), "term": env["term"] + 1,
              "votedFor": iv.const(SP.NIL), "vResp": iv.const(0),
              "vGrant": iv.const(0)}
    if bounds.history:
        writes["vLog"] = iv.const(0)
    return TransferResult(writes)


def t_request_vote(bounds, env, menv):
    """RequestVote(i, j) (raft.tla:190-199)."""
    rec = MsgRecord(SP.M_RVREQ, {
        "mtype": iv.const(SP.M_RVREQ),
        "mterm": env["term"],
        "a": _last_term(env),            # mlastLogTerm (raft.tla:195)
        "b": env["logLen"],              # mlastLogIndex (raft.tla:196)
        "src": _server_iv(bounds), "dst": _server_iv(bounds),
        "c": iv.const(0), "d": iv.const(0), "e": iv.const(0),
        "f": iv.const(0), "g": iv.const(0),
    })
    return TransferResult(_send_writes(env, (rec,)), (rec,))


def t_append_entries(bounds, env, menv):
    """AppendEntries(i, j) (raft.tla:204-226)."""
    prev_idx = env["nextIndex"] - 1
    last_entry = env["logLen"].min_(env["nextIndex"])     # raft.tla:213
    rec = MsgRecord(SP.M_AEREQ, {
        "mtype": iv.const(SP.M_AEREQ),
        "mterm": env["term"],
        "a": prev_idx,                                    # mprevLogIndex
        "b": _last_term(env),                             # mprevLogTerm
        "c": iv.BOOL,                                     # Len(mentries)
        "d": env["logTerm"].join(0),                      # mentries[1].term
        "e": env["logVal"].join(0),                       # mentries[1].value
        "f": env["commitIndex"].min_(last_entry),         # mcommitIndex
        "g": _rank_iv(bounds),                            # mlog rank
        "src": _server_iv(bounds), "dst": _server_iv(bounds),
        # Relational fact the done-reply echoes as mmatchIndex: when an
        # entry is carried (c = 1) the guard ni <= Len(log[i]) makes
        # prevIdx + 1 <= logLen; with c = 0 it is prevIdx itself.  The
        # c = 1 case is infeasible when logs cannot hold an entry.
        "a+c": (prev_idx.join(iv.Interval(1, env["logLen"].hi))
                if env["logLen"].hi >= 1 else prev_idx),
    })
    return TransferResult(_send_writes(env, (rec,)), (rec,))


def t_become_leader(bounds, env, menv):
    """BecomeLeader(i) (raft.tla:229-243)."""
    writes = {"role": iv.const(SP.LEADER),
              "nextIndex": env["logLen"] + 1,
              "matchIndex": iv.const(0)}
    if bounds.history:
        writes.update({
            "eTerm": env["term"], "eLeader": _server_iv(bounds),
            "eLog": _rank_iv(bounds), "eVotes": env["vGrant"],
            "eVLog": env["vLog"],
        })
    return TransferResult(writes)


def t_client_request(bounds, env, menv):
    """ClientRequest(i, v) (raft.tla:246-253): the log append.  logLen + 1
    fits log_cap only under the expansion envelope (logLen <= max_log)."""
    return TransferResult({
        "logTerm": env["term"],
        "logVal": iv.Interval(1, bounds.n_values),
        "logLen": env["logLen"] + 1,
    })


def t_advance_commit(bounds, env, menv):
    """AdvanceCommitIndex(i) (raft.tla:259-276): commits at most logLen."""
    max_agree = iv.Interval(0, env["logLen"].hi)
    return TransferResult({
        "commitIndex": max_agree.join(env["commitIndex"]),
    })


def t_receive(bounds, env, menv):
    """Receive(m) (raft.tla:421-436): the 11-branch dispatch.  Reads come
    from the message envelope ``menv`` (the bag's inductive invariant),
    not the raw subfield widths — the whole point of the fixpoint."""
    writes: dict = {}
    sends: list = []

    def join_write(field, interval):
        writes[field] = interval if field not in writes \
            else writes[field].join(interval)

    ct = env["term"]
    resp_srcdst = _server_iv(bounds)

    # UpdateTerm (raft.tla:406-412): term' = mterm of any carried type.
    mterms = [rec["mterm"] for rec in menv.values() if "mterm" in rec]
    if mterms:
        t = mterms[0]
        for m in mterms[1:]:
            t = t.join(m)
        join_write("term", t)
        join_write("role", iv.const(SP.FOLLOWER))
        join_write("votedFor", iv.const(SP.NIL))

    rv = menv.get(SP.M_RVREQ)
    if rv is not None:
        # HandleRequestVoteRequest (raft.tla:284-303)
        join_write("votedFor", rv["src"] + 1)          # raft.tla:292
        rec = MsgRecord(SP.M_RVRESP, {
            "mtype": iv.const(SP.M_RVRESP), "mterm": ct,
            "a": iv.BOOL,                              # mvoteGranted
            "b": iv.const(0),
            "src": resp_srcdst, "dst": resp_srcdst,
            "c": iv.const(0), "d": iv.const(0), "e": iv.const(0),
            "f": iv.const(0),
            "g": _rank_iv(bounds),                     # voter mlog (:297-299)
        })
        sends.append(rec)

    rvr = menv.get(SP.M_RVRESP)
    if rvr is not None:
        # HandleRequestVoteResponse (raft.tla:307-321)
        one_hot = iv.Interval(1, 1 << _server_iv(bounds).hi)   # 1 << j
        join_write("vResp", env["vResp"].or_(one_hot))
        join_write("vGrant", env["vGrant"].or_(one_hot))
        if bounds.history:
            # voterLog[i] @@ (j :> m.mlog): rank+1, existing entry wins
            join_write("vLog", env["vLog"].join(rvr["g"] + 1))

    ae = menv.get(SP.M_AEREQ)
    if ae is not None:
        # HandleAppendEntriesRequest (raft.tla:327-389)
        rej = MsgRecord(SP.M_AERESP, {
            "mtype": iv.const(SP.M_AERESP), "mterm": ct,
            "a": iv.const(0), "b": iv.const(0),
            "src": resp_srcdst, "dst": resp_srcdst,
            "c": iv.const(0), "d": iv.const(0), "e": iv.const(0),
            "f": iv.const(0), "g": iv.const(0),
        })
        sends.append(rej)
        # done (raft.tla:356-374): commitIndex' = mcommitIndex, success
        # reply echoes mprevLogIndex + Len(mentries) as mmatchIndex.
        join_write("commitIndex", ae["f"])
        done = MsgRecord(SP.M_AERESP, {
            "mtype": iv.const(SP.M_AERESP), "mterm": ct,
            "a": iv.const(1), "b": ae["a+c"],
            "src": resp_srcdst, "dst": resp_srcdst,
            "c": iv.const(0), "d": iv.const(0), "e": iv.const(0),
            "f": iv.const(0), "g": iv.const(0),
        })
        sends.append(done)
        # candidate step-down (raft.tla:346-350)
        join_write("role", iv.const(SP.FOLLOWER))
        # conflict (raft.tla:375-382): drop one tail entry; the guard
        # Len(log[i]) >= index >= 1 bounds logLen away from 0 (and makes
        # the branch infeasible when logs are always empty).
        join_write("logTerm", iv.const(0))
        join_write("logVal", iv.const(0))
        if env["logLen"].hi >= 1:
            join_write("logLen",
                       env["logLen"].meet(iv.Interval(1, BIG)) - 1)
        # append (raft.tla:383-388)
        join_write("logTerm", ae["d"])
        join_write("logVal", ae["e"])
        join_write("logLen", env["logLen"] + 1)

    aer = menv.get(SP.M_AERESP)
    if aer is not None:
        # HandleAppendEntriesResponse (raft.tla:393-403)
        join_write("matchIndex", aer["b"])
        join_write("nextIndex",
                   (aer["b"] + 1).join((env["nextIndex"] - 1).max_(1)))

    # Every reply is Reply = remove + add; removes zero emptied slots.
    for field, interval in _send_writes(env, sends).items():
        join_write(field, interval)
    join_write("msgHi", iv.const(0))
    join_write("msgLo", iv.const(0))
    join_write("msgCount", iv.Interval(0, env["msgCount"].hi))
    return TransferResult(writes, tuple(sends))


def t_duplicate(bounds, env, menv):
    """DuplicateMessage(m) (raft.tla:443-445): one multiplicity + 1; fits
    dup_cap only under the expansion envelope (msgCount <= max_dup)."""
    return TransferResult({"msgCount": env["msgCount"] + 1})


def t_drop(bounds, env, menv):
    """DropMessage(m) (raft.tla:448-450): decrement, zero emptied slots."""
    return TransferResult({
        "msgHi": iv.const(0), "msgLo": iv.const(0),
        "msgCount": iv.Interval(0, env["msgCount"].hi),
    })


def _send_writes(env, sends) -> dict:
    """bag_add's writes for a set of creation sites: the packed words
    (exact shift/or arithmetic over the subfield intervals — unmasked,
    so an overflowing subfield surfaces as a word-level overflow too)
    plus the bumped multiplicity."""
    if not sends:
        return {}
    from raft_tla_tpu.ops.msgbits import HI_FIELDS, LO_FIELDS
    hi = lo = iv.const(0)
    for rec in sends:
        h = l = iv.const(0)
        for name, (sh, _w) in HI_FIELDS.items():
            f = rec.fields.get(name, iv.const(0))
            h = h + iv.Interval(f.lo << sh, f.hi << sh)
        for name, (sh, _w) in LO_FIELDS.items():
            f = rec.fields.get(name, iv.const(0))
            l = l + iv.Interval(f.lo << sh, f.hi << sh)
        hi, lo = hi.join(h), lo.join(l)
    return {"msgHi": hi, "msgLo": lo, "msgCount": _bag_count(env)}


TRANSFERS = {
    SP.RESTART: t_restart,
    SP.TIMEOUT: t_timeout,
    SP.REQUESTVOTE: t_request_vote,
    SP.APPENDENTRIES: t_append_entries,
    SP.BECOMELEADER: t_become_leader,
    SP.CLIENTREQUEST: t_client_request,
    SP.ADVANCECOMMIT: t_advance_commit,
    SP.RECEIVE: t_receive,
    SP.DUPLICATE: t_duplicate,
    SP.DROP: t_drop,
}


def message_envelope(bounds: Bounds, env: dict, transfers: dict) -> dict:
    """Least fixpoint of per-(mtype, subfield) intervals over all record
    creation sites.  The bag is empty at Init, so iteration from bottom
    is the inductive invariant of message content; monotone over a
    finite lattice (every interval is capped by a field range), so it
    converges — the bound is a hard error, not a widening."""
    menv: dict = {}
    for _ in range(32):
        changed = False
        for t in transfers.values():
            for rec in t(bounds, env, menv).sends:
                cur = menv.setdefault(rec.mtype, {})
                for name, interval in rec.fields.items():
                    new = interval if name not in cur \
                        else cur[name].join(interval)
                    if cur.get(name) != new:
                        cur[name] = new
                        changed = True
        if not changed:
            return menv
    raise RuntimeError("message-envelope fixpoint did not converge")


def check_tables(hi_fields=None, lo_fields=None) -> list:
    """Validate the msgHi/msgLo composite encodings: no overlapping
    subfields, no spill past bit 31 (the int32 sign bit stays clear)."""
    from raft_tla_tpu.ops import msgbits as mb
    findings = []
    for word, table in (("msgHi", hi_fields or mb.HI_FIELDS),
                        ("msgLo", lo_fields or mb.LO_FIELDS)):
        spans = sorted((sh, sh + w, name) for name, (sh, w) in table.items())
        for (s0, e0, n0), (s1, e1, n1) in zip(spans, spans[1:]):
            if s1 < e0:
                findings.append(Finding(
                    WIDTH, ERROR, "msg-table-overlap",
                    f"{word} subfields {n0} [{s0},{e0}) and {n1} "
                    f"[{s1},{e1}) overlap", field=f"{word}.{n1}",
                    interval=(s1, e1 - 1), width=e0 - s1))
        top = max(e for _s, e, _n in spans)
        if top > 31:
            name = next(n for _s, e, n in spans if e == top)
            findings.append(Finding(
                WIDTH, ERROR, "msg-table-spill",
                f"{word} subfield {name} ends at bit {top} > 31: the "
                "packed word would touch the int32 sign bit",
                field=f"{word}.{name}", width=top - 31))
    return findings


def check_flat_widths(bounds: Bounds, field_bits_table=None) -> list:
    """Validate the int32 flat-vector encoding: every field width <= 31
    (values stay non-negative in int32) except the declared raw-mask
    fields, and the claimed envelope fits every width."""
    from raft_tla_tpu.ops import bitpack
    fb = field_bits_table or bitpack.field_bits(bounds)
    findings = []
    for field, bits in fb.items():
        if bits > (32 if field in bitpack.RAW_FIELDS else 31):
            findings.append(Finding(
                WIDTH, ERROR, "flat-width",
                f"field {field} is allotted {bits} bits; int32 elements "
                "hold at most 31 value bits (sign clear) unless declared "
                "raw", field=field, width=bits))
    env = iv.envelope(bounds)
    for field, interval in env.items():
        if field not in fb:
            findings.append(Finding(
                WIDTH, ERROR, "schema-drift",
                f"envelope field {field} missing from field_bits",
                field=field))
            continue
        if field in bitpack.RAW_FIELDS:
            continue
        if not interval.fits_bits(fb[field]):
            findings.append(Finding(
                WIDTH, ERROR, "envelope-width",
                f"claimed envelope of {field} does not fit its packed "
                "width", field=field, interval=interval.as_tuple(),
                width=fb[field]))
    missing = [f for f in fb if f not in env]
    for field in missing:
        findings.append(Finding(
            WIDTH, ERROR, "schema-drift",
            f"packed field {field} has no envelope entry", field=field))
    return findings


def _mode_fields(bounds: Bounds) -> tuple:
    return st.STATE_FIELDS + (st.HISTORY_FIELDS if bounds.history else ())


def _top_menv(bounds: Bounds) -> dict:
    """Top of the message-envelope lattice: every mtype present, every
    subfield spanning its full table width.  Used by the coverage
    cross-check so a kernel/twin write-set comparison is structural —
    independent of which messages a spec subset can actually reach."""
    from raft_tla_tpu.ops.msgbits import HI_FIELDS, LO_FIELDS
    full = {name: iv.bitmask(w) for name, (_sh, w) in HI_FIELDS.items()}
    full.update({name: iv.bitmask(w) for name, (_sh, w) in LO_FIELDS.items()})
    full["a+c"] = iv.Interval(0, bounds.log_cap)
    return {mt: dict(full)
            for mt in (SP.M_RVREQ, SP.M_RVRESP, SP.M_AEREQ, SP.M_AERESP)}


def check_transfer_coverage(bounds: Bounds, spec: str,
                            transfers: dict) -> list:
    """Cross-check the transfer twins against the kernel-side declaration
    (``ops/kernels.transfer_metadata``): same families, same written-field
    sets.  A kernel writing a field its transfer does not model — or vice
    versa — is silent-drift territory and fails the lint loudly."""
    from raft_tla_tpu.ops import kernels
    findings = []
    meta = kernels.transfer_metadata()
    fams = {a.family for a in SP.action_table(bounds, spec)}
    mode = set(_mode_fields(bounds))
    env = iv.expansion_envelope(bounds)
    menv = _top_menv(bounds)
    for fam in sorted(fams):
        if fam not in transfers:
            findings.append(Finding(
                WIDTH, ERROR, "transfer-missing",
                f"kernel family {fam} has no width-transfer twin",
                transition=fam))
            continue
        if fam not in meta:
            findings.append(Finding(
                WIDTH, ERROR, "transfer-drift",
                f"family {fam} missing from kernels.transfer_metadata",
                transition=fam))
            continue
        declared = set(meta[fam]["writes"]) & mode
        modeled = set(transfers[fam](bounds, env, menv).writes) & mode
        for f in sorted(declared - modeled):
            findings.append(Finding(
                WIDTH, ERROR, "transfer-drift",
                f"kernel {fam} declares a write of {f} the transfer twin "
                "does not model", transition=fam, field=f))
        for f in sorted(modeled - declared):
            findings.append(Finding(
                WIDTH, ERROR, "transfer-drift",
                f"transfer twin of {fam} models a write of {f} the kernel "
                "does not declare", transition=fam, field=f))
    return findings


def check_fused_coverage(bounds: Bounds, spec: str) -> list:
    """Cross-check the megakernel's whole-step write surface
    (``ops/pallas_step.FUSED_WRITES`` — hand-maintained, like the
    per-family twins) against the union of the spec subset's per-family
    kernel declarations plus the expansion postlude.  The fused kernel
    evaluates the same staged program as the XLA step, so its write
    surface must be EXACTLY that union: a family growing a new write, a
    subset gaining a family, or the fused table going stale all surface
    here as loud drift — the width-safety proof keeps covering the hot
    path whichever step build the gate selects."""
    from raft_tla_tpu.ops import kernels, pallas_step
    findings = []
    if spec not in pallas_step.FUSED_WRITES:
        findings.append(Finding(
            WIDTH, ERROR, "fused-missing",
            f"spec subset {spec!r} has no megakernel write-surface entry "
            "(ops/pallas_step.FUSED_WRITES)", transition=spec))
        return findings
    mode = set(_mode_fields(bounds))
    fams = {a.family for a in SP.action_table(bounds, spec)}
    union = set(kernels.POSTLUDE_WRITES)
    for fam in fams:
        union |= set(kernels.TRANSFER_WRITES.get(fam, ()))
    declared = set(pallas_step.FUSED_WRITES[spec]) & mode
    modeled = union & mode
    for f in sorted(declared - modeled):
        findings.append(Finding(
            WIDTH, ERROR, "fused-drift",
            f"megakernel write surface for {spec!r} declares {f}, which "
            "no per-family transfer twin proves", transition=spec,
            field=f))
    for f in sorted(modeled - declared):
        findings.append(Finding(
            WIDTH, ERROR, "fused-drift",
            f"family kernels of {spec!r} can write {f} but the megakernel "
            "write surface (ops/pallas_step.FUSED_WRITES) omits it",
            transition=spec, field=f))
    return findings


def check_widths(bounds: Bounds, spec: str = "full", *,
                 field_bits_table=None, hi_fields=None, lo_fields=None,
                 transfers=None, expansion_env=None,
                 coverage_check: bool = True) -> list:
    """Run the full width-safety proof for one Bounds instance/mode.

    Every input is injectable (the seeded-mutation harness depends on
    it); defaults are the shipped tables and transfers.  Returns the
    list of findings — empty means *proved*: no reachable transition can
    write a value the pack would truncate.
    """
    from raft_tla_tpu.ops import bitpack, msgbits as mb
    fb = field_bits_table or bitpack.field_bits(bounds)
    hi_t = hi_fields or mb.HI_FIELDS
    lo_t = lo_fields or mb.LO_FIELDS
    transfers = transfers or TRANSFERS
    findings = check_tables(hi_t, lo_t)
    findings += check_flat_widths(bounds, field_bits_table=fb)

    env = iv.envelope(bounds)
    exp_env = expansion_env or iv.expansion_envelope(bounds)

    # Base case: Init inside the envelope.
    for field, interval in iv.init_env(bounds).items():
        if field in env and not interval.subset(env[field]):
            findings.append(Finding(
                WIDTH, ERROR, "init-escape",
                f"Init writes {field} outside the claimed envelope",
                transition="Init", field=field,
                interval=interval.as_tuple()))

    fams = {a.family for a in SP.action_table(bounds, spec)}
    active = {f: transfers[f] for f in fams if f in transfers}
    menv = message_envelope(bounds, exp_env, active)
    mode = set(_mode_fields(bounds))

    for fam in sorted(fams):
        if fam not in transfers:
            continue        # reported by the coverage cross-check
        res = transfers[fam](bounds, exp_env, menv)
        for field, interval in res.writes.items():
            if field not in mode:
                continue
            if field not in fb:
                findings.append(Finding(
                    WIDTH, ERROR, "schema-drift",
                    f"{fam} writes unknown field {field}",
                    transition=fam, field=field))
                continue
            if field not in bitpack.RAW_FIELDS and \
                    not interval.fits_bits(fb[field]):
                findings.append(Finding(
                    WIDTH, ERROR, "width-overflow",
                    f"{fam} can write {field} outside its packed width — "
                    "the pack would silently truncate and collide "
                    "fingerprints", transition=fam, field=field,
                    interval=interval.as_tuple(), width=fb[field]))
            if field in env and not interval.subset(env[field]):
                findings.append(Finding(
                    WIDTH, ERROR, "envelope-escape",
                    f"{fam} writes {field} outside the inductive "
                    "envelope: the width proof is not closed under this "
                    "transition", transition=fam, field=field,
                    interval=interval.as_tuple(), width=fb.get(field)))
        for rec in res.sends:
            findings += _check_record(bounds, fam, rec, hi_t, lo_t)

    # Faithful-mode postlude: the shared allLogs union (raw 32-bit or).
    if bounds.history and "allLogs" not in bitpack.RAW_FIELDS:
        findings.append(Finding(
            WIDTH, ERROR, "schema-drift",
            "allLogs must be declared raw (32-bit mask words)",
            field="allLogs"))

    if coverage_check:
        findings += check_transfer_coverage(bounds, spec, transfers)
        findings += check_fused_coverage(bounds, spec)
    return findings


def _check_record(bounds, fam, rec, hi_fields, lo_fields) -> list:
    """One creation site vs the shift/width tables (mode-aware: parity
    must strip mlog — a nonzero g would widen parity rows)."""
    findings = []
    mtype_name = SP.MTYPE_NAMES[rec.mtype]
    tables = dict(hi_fields)
    tables.update(lo_fields)
    for name, interval in rec.fields.items():
        if "+" in name:
            continue                       # derived relational fact
        if name not in tables:
            findings.append(Finding(
                WIDTH, ERROR, "msg-subfield-unknown",
                f"{fam} packs unknown subfield {name} into a "
                f"{mtype_name}", transition=fam,
                field=f"{mtype_name}.{name}"))
            continue
        _sh, w = tables[name]
        if name == "g" and not bounds.history:
            if interval.as_tuple() != (0, 0):
                findings.append(Finding(
                    WIDTH, ERROR, "parity-mlog-nonzero",
                    f"{fam} packs a nonzero mlog into a {mtype_name} in "
                    "parity mode (history must be stripped)",
                    transition=fam, field=f"{mtype_name}.g",
                    interval=interval.as_tuple(), width=w))
            continue
        if not interval.fits_bits(w):
            findings.append(Finding(
                WIDTH, ERROR, "msg-subfield-overflow",
                f"{fam} packs {mtype_name}.{name} outside its "
                f"{w}-bit slot — neighbouring subfields would be "
                "corrupted", transition=fam,
                field=f"{mtype_name}.{name}",
                interval=interval.as_tuple(), width=w))
    return findings
